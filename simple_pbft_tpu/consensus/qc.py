"""Quorum-certificate helpers: share signing, aggregation, cached verify,
and the off-loop batched verify lane.

The QC path (config.qc_mode, BASELINE config 4) moves vote traffic from
O(n^2) all-to-all broadcast to O(n): replicas BLS-sign the phase payload
and send the share to the primary only; the primary aggregates 2f+1
shares into one ``QuorumCert`` whose pairing check certifies the whole
phase. This module owns the share/aggregate/verify mechanics so the
replica runtime stays protocol-shaped.

Verification results are memoized process-wide, keyed by the full
(payload, signer set, aggregate) triple — deterministic, so sharing the
memo across in-process replicas is sound, and a 256-node simulated
committee pays each pairing once instead of once per replica.

``QcVerifyLane`` (ISSUE 3 tentpole) is the runtime's verify path: a
dedicated worker thread with a bounded admission queue that coalesces
every replica's pending certificate checks into ONE random-linear-
combination multi-pairing (bls.verify_aggregates_batch — 2 Miller loops
per batch instead of 2 per cert). Before the lane, each check rode
``asyncio.to_thread`` into the default executor: at n=256 a 25-60 ms
pairing per cert serialized against the Ed25519 dispatcher's worker
threads and the drain sweep — the r5 qc256 wedge shape (15 s verify RTT,
zero commits). The lane keeps certificate crypto off both the event loop
and the shared executor, and its counters (queue depth, batch size,
pairing latency) feed the telemetry plane.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from .. import devledger, sanitize, spans
from ..crypto import bls
from ..messages import QuorumCert, qc_payload

# Vote QCs drive instance transitions; "checkpoint" certs attest state
# digests (view pinned to 0 in the payload — checkpoints are
# view-independent) and travel ONLY inside view-change certificates.
# Routing guards use VOTE_PHASES so the two sets cannot drift.
VOTE_PHASES = ("prepare", "commit")
PHASES = VOTE_PHASES + ("checkpoint",)

_CACHE_MAX = 4096
_cache: "OrderedDict[tuple, bool]" = OrderedDict()
_cache_lock = sanitize.wrap_lock(threading.Lock(), "qc.cache")
# key -> Event for a pairing currently being computed: concurrent callers
# of the same certificate (every backup receives the primary's broadcast
# at once) wait for the first computation instead of redundantly burning
# ~0.8 s of CPU each — the memo's once-per-process promise, made true
# under concurrency as well.
_inflight: Dict[tuple, threading.Event] = {}


def sign_share(bls_sk: int, phase: str, view: int, seq: int, digest: str) -> str:
    """One replica's BLS share over the QC payload, hex for the wire."""
    return bls.sign(bls_sk, qc_payload(phase, view, seq, digest)).hex()


def share_valid_shape(share_hex: str) -> bool:
    """Cheap structural check (hex, curve point) — NOT a signature check;
    the aggregate pairing (or failure bisection) is the authority."""
    try:
        raw = bytes.fromhex(share_hex)
    except ValueError:
        return False
    return bls._g1_from_bytes(raw) is not None


def build_qc(
    phase: str,
    view: int,
    seq: int,
    digest: str,
    shares: Dict[str, str],
    quorum: int,
) -> Optional[QuorumCert]:
    """Aggregate `quorum` shares (signer -> hex share) into a QuorumCert.
    Callers verify the result before broadcasting (a Byzantine share
    corrupts the aggregate; see bisect_bad_shares)."""
    signers = sorted(shares)[:quorum] if len(shares) >= quorum else None
    if signers is None:
        return None
    try:
        raws = [bytes.fromhex(shares[s]) for s in signers]
    except ValueError:
        return None
    agg = bls.aggregate_signatures(raws)
    if agg is None:
        return None
    return QuorumCert(
        phase=phase,
        view=view,
        seq=seq,
        digest=digest,
        signers=list(signers),
        agg_sig=agg.hex(),
    )


def _qc_entry(cfg, qc: QuorumCert) -> Optional[Tuple[List[bytes], bytes, bytes]]:
    """Structural admission shared by every verify path (sync, lane,
    certificate batch): phase, signer set, pubkey resolution, aggregate
    decode. Returns (pubkeys, payload, aggregate bytes) or None —
    keeping this single-sourced means the lane and the sync path can
    never drift in what they reject."""
    if qc.phase not in PHASES:
        return None
    if len(qc.signers) < cfg.quorum or len(set(qc.signers)) != len(qc.signers):
        return None
    pks: List[bytes] = []
    for s in qc.signers:
        pk = cfg.bls_pubkey(s)
        if pk is None:
            return None
        pks.append(pk)
    try:
        agg = bytes.fromhex(qc.agg_sig)
    except ValueError:
        return None
    return pks, qc.payload(), agg


def _cache_key(qc: QuorumCert) -> tuple:
    return (qc.payload(), tuple(qc.signers), qc.agg_sig)


def cached_verdict(qc: QuorumCert) -> Optional[bool]:
    """Memoized verdict for a certificate, or None when never computed."""
    key = _cache_key(qc)
    with _cache_lock:
        hit = _cache.get(key)
        if hit is not None:
            _cache.move_to_end(key)
        return hit


def _cache_store(key: tuple, verdict: bool) -> None:
    with _cache_lock:
        _cache[key] = verdict
        while len(_cache) > _CACHE_MAX:
            _cache.popitem(last=False)


def verify_qc(cfg, qc: QuorumCert) -> bool:
    """Full certificate check: structure, signer set, one pairing.
    Pairing-expensive (25-60 ms native, ~0.8 s pure Python) — run
    off-loop (the runtime path is QcVerifyLane, which also batches);
    results are memoized process-wide."""
    ent = _qc_entry(cfg, qc)
    if ent is None:
        return False
    pks, payload, agg = ent
    key = (payload, tuple(qc.signers), qc.agg_sig)
    while True:
        with _cache_lock:
            hit = _cache.get(key)
            if hit is not None:
                _cache.move_to_end(key)
                return hit
            waiter = _inflight.get(key)
            if waiter is None:
                _inflight[key] = threading.Event()
                break
        waiter.wait()  # another thread is computing this exact pairing
    ok: Optional[bool] = None
    try:
        # pbftlint: disable=PBL001 -- loop residency only via verify_qc_async's clock.simulated() branch (sim-only by contract); every production caller runs in the lane worker or an executor thread
        ok = bls.verify_aggregate(pks, payload, agg)
    finally:
        with _cache_lock:
            ev = _inflight.pop(key, None)
            if ok is not None:  # None = exception: waiters recompute
                _cache[key] = ok
                while len(_cache) > _CACHE_MAX:
                    _cache.popitem(last=False)
        if ev is not None:
            ev.set()
    return ok


def bisect_bad_shares(
    cfg, phase: str, view: int, seq: int, digest: str, shares: Dict[str, str]
) -> Dict[str, str]:
    """Aggregate failed its pairing: verify each share individually and
    return only the good ones. Costs one pairing per share — only runs
    when a Byzantine replica actually sent a corrupt share, and each bad
    signer is then excluded by the caller, bounding the total damage to f
    bisections."""
    payload = qc_payload(phase, view, seq, digest)
    good: Dict[str, str] = {}
    for signer, share_hex in shares.items():
        pk = cfg.bls_pubkey(signer)
        if pk is None:
            continue
        try:
            raw = bytes.fromhex(share_hex)
        except ValueError:
            continue
        if bls.verify(pk, payload, raw):
            good[signer] = share_hex
    return good


def verify_qcs_all(cfg, qcs: List[QuorumCert]) -> bool:
    """All-or-nothing batched check for the quorum certs embedded in ONE
    view-change-class certificate: memoized certs answer from the cache,
    the rest ride one RLC batch (bls.verify_aggregates_all). On batch
    failure nothing is memoized (a combined check cannot attribute
    blame) and the certificate is rejected — a Byzantine certificate
    stuffed with fabricated aggregates costs one batch check, preserving
    the old sequential path's early-exit DoS bound. Pairing-expensive:
    run off-loop."""
    fresh: List[QuorumCert] = []
    entries: List[tuple] = []
    for cert in qcs:
        hit = cached_verdict(cert)
        if hit is False:
            return False
        if hit is True:
            continue
        ent = _qc_entry(cfg, cert)
        if ent is None:
            return False
        fresh.append(cert)
        entries.append(ent)
    if not entries:
        return True
    if not bls.verify_aggregates_all(entries):
        return False
    for cert in fresh:
        _cache_store(_cache_key(cert), True)
    return True


# ---------------------------------------------------------------------------
# Off-loop QC verify lane (ISSUE 3 tentpole)
# ---------------------------------------------------------------------------


class QcLaneOverloaded(RuntimeError):
    """Admission-rejected QC submit: the lane's pending pile is at cap.

    Raised (as the future's exception) instead of queueing when the
    pending certificate count is at ``max_pending`` — under sustained
    submit-rate > pairing-rate an unbounded lane queue reproduces the r5
    qc256 wedge one layer up. Callers shed the certificate; QCs are
    self-certifying and re-arrive via the primary's broadcast, relays,
    or the slot-probe chain."""


class _LaneEntry:
    __slots__ = ("key", "pks", "payload", "agg", "futs", "t_enq")

    def __init__(self, key, pks, payload, agg, fut):
        self.key = key
        self.pks = pks
        self.payload = payload
        self.agg = agg
        self.futs = [fut]
        self.t_enq = time.perf_counter()  # lane queue-wait span anchor


class QcVerifyLane:
    """Dedicated certificate-verify executor: bounded queue, batch-close
    coalescing, RLC multi-pairing, process-wide memo integration.

    One daemon worker owns all pairing work, so a 60 ms aggregate check
    can never starve the Ed25519 dispatcher's threads or the event loop
    (the r5 qc256 failure shape). Concurrent submissions of the same
    certificate (every backup receives the primary's broadcast at once)
    join the same entry — one pairing, many futures. ``close_window``
    is the batch-close policy: after the first pending cert the worker
    waits that long for the rest of the burst before cutting a batch,
    trading ~2 ms of latency for 2-Miller-loop batches under load.
    """

    def __init__(
        self,
        max_pending: int = 512,
        max_batch: int = 32,
        close_window: float = 0.002,
    ):
        self._max_pending = max_pending
        self._max_batch = max_batch
        self._close_window = close_window
        self._cond = threading.Condition(
            sanitize.wrap_lock(threading.Lock(), "qc.lane.cond")
        )
        self._pending: "OrderedDict[tuple, _LaneEntry]" = OrderedDict()
        self._inflight_entries: Dict[tuple, _LaneEntry] = {}
        self._closed = False
        self._started = False
        # observability (telemetry.py / pbft_top / bench_consensus)
        self.submitted = 0
        self.cache_hits = 0
        self.dedup_joins = 0
        self.structural_rejects = 0
        self.overload_rejections = 0
        self.batches = 0
        self.batch_items = 0
        self.max_batch_seen = 0
        self.rlc_batches = 0
        self.batch_fallbacks = 0
        self.verified_true = 0
        self.verified_false = 0
        self.max_pending_seen = 0
        self._pairing_ms_ema = 0.0
        self.last_batch_ms = 0.0
        self.last_batch_items = 0

    # -- submission -----------------------------------------------------

    def submit(self, cfg, qc: QuorumCert) -> "Future[bool]":
        """Enqueue one certificate check; the future resolves to its
        verdict. Never blocks; never runs a pairing on the caller's
        thread (memo hits and structural rejects resolve inline)."""
        fut: Future = Future()
        self.submitted += 1
        hit = cached_verdict(qc)
        if hit is not None:
            self.cache_hits += 1
            fut.set_result(hit)
            return fut
        ent = _qc_entry(cfg, qc)
        if ent is None:
            self.structural_rejects += 1
            fut.set_result(False)
            return fut
        pks, payload, agg = ent
        key = (payload, tuple(qc.signers), qc.agg_sig)
        closed = False
        with self._cond:
            closed = self._closed
            if not closed:
                joined = self._pending.get(key) or self._inflight_entries.get(key)
                if joined is not None:
                    joined.futs.append(fut)
                    self.dedup_joins += 1
                    return fut
                if len(self._pending) >= self._max_pending:
                    self.overload_rejections += 1
                    fut.set_exception(
                        QcLaneOverloaded(
                            f"qc verify lane overloaded: {len(self._pending)} "
                            f"certs pending (cap {self._max_pending})"
                        )
                    )
                    return fut
                self._pending[key] = _LaneEntry(key, pks, payload, agg, fut)
                if len(self._pending) > self.max_pending_seen:
                    self.max_pending_seen = len(self._pending)
                if not self._started:
                    self._started = True
                    threading.Thread(
                        target=self._worker, name="qc-verify-lane", daemon=True
                    ).start()
                self._cond.notify_all()
        if closed:
            # teardown race: answer via a one-off worker rather than
            # erroring a certificate already in the pipeline — and never
            # pair on the CALLER's thread (verify_qc_async submits from
            # the event loop, which must not eat a 25-60 ms pairing even
            # during teardown). Memo hits make this near-free in practice.
            def _late() -> None:
                try:
                    fut.set_result(verify_qc(cfg, qc))
                except BaseException as exc:  # noqa: BLE001
                    if not fut.cancelled():
                        fut.set_exception(exc)

            threading.Thread(
                target=_late, name="qc-verify-late", daemon=True
            ).start()
        return fut

    # -- worker ---------------------------------------------------------

    def _take_locked(self) -> List[_LaneEntry]:
        take: List[_LaneEntry] = []
        while self._pending and len(take) < self._max_batch:
            _, ent = self._pending.popitem(last=False)
            take.append(ent)
            self._inflight_entries[ent.key] = ent
        return take

    def _worker(self) -> None:
        sanitize.bind_owner(("qc.lane.worker", id(self)), "QcVerifyLane._worker")
        try:
            while True:
                with self._cond:
                    while not self._pending and not self._closed:
                        self._cond.wait()
                    if self._closed and not self._pending:
                        return
                    if (
                        self._close_window > 0
                        and not self._closed
                        and len(self._pending) < self._max_batch
                    ):
                        # batch-close: let the rest of a broadcast burst land
                        self._cond.wait(self._close_window)
                    take = self._take_locked()
                if take:
                    self._run_batch(take)
        finally:
            # a later lane at this recycled id() must bind fresh
            sanitize.release_owner(("qc.lane.worker", id(self)))

    def _run_batch(self, take: List[_LaneEntry]) -> None:
        # pairing work is confined to the lane worker: a pairing on any
        # other thread (the loop!) is exactly the r5 wedge shape
        sanitize.check_owner(("qc.lane.worker", id(self)), "QcVerifyLane._run_batch")
        t0 = time.perf_counter()
        for e in take:
            # lane wait per certificate: submit -> batch start (includes
            # the deliberate ~2 ms close window — that policy cost must
            # be visible in the decomposition, not folded into "pairing")
            spans.record(spans.QC_QUEUE, t0 - e.t_enq, n=len(e.futs))
        try:
            verdicts = bls.verify_aggregates_batch(
                [(e.pks, e.payload, e.agg) for e in take]
            )
        except BaseException as exc:  # noqa: BLE001 — futures must resolve
            with self._cond:
                futs = []
                for e in take:
                    self._inflight_entries.pop(e.key, None)
                    futs.extend(e.futs)
            for fut in futs:
                if not fut.cancelled():
                    fut.set_exception(exc)
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        spans.record(spans.QC_PAIRING, dt_ms / 1e3, n=len(take))
        # device-ledger event for the BLS pairing lane (ISSUE 14): same
        # schema as the Ed25519 jit dispatches — one row per RLC batch,
        # queue wait = mean lane wait, bytes_up = the certificate
        # material the pairing consumed (payloads + aggregates + 96 B
        # per signer pubkey). No jit here, so compile is always cached.
        devledger.record(
            devledger.LANE_BLS, "pairing", 0, len(take), len(take),
            rtt_s=dt_ms / 1e3,
            queue_wait_s=(
                sum(t0 - e.t_enq for e in take) / len(take) if take else 0.0
            ),
            submissions=len(take),
            bytes_up=sum(
                len(e.payload) + len(e.agg) + 96 * len(e.pks) for e in take
            ),
            bytes_down=len(take),
        )
        self.batches += 1
        self.batch_items += len(take)
        self.max_batch_seen = max(self.max_batch_seen, len(take))
        self.last_batch_ms = dt_ms
        self.last_batch_items = len(take)
        self._pairing_ms_ema = (
            dt_ms if self._pairing_ms_ema == 0.0
            else 0.8 * self._pairing_ms_ema + 0.2 * dt_ms
        )
        if len(take) > 1:
            self.rlc_batches += 1
            if not all(verdicts):
                self.batch_fallbacks += 1  # halving/per-cert path ran
        for e, ok in zip(take, verdicts):
            _cache_store(e.key, ok)
            if ok:
                self.verified_true += 1
            else:
                self.verified_false += 1
            with self._cond:
                self._inflight_entries.pop(e.key, None)
                futs = list(e.futs)
            for fut in futs:
                if not fut.cancelled():
                    fut.set_result(ok)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def snapshot(self) -> dict:
        """QC-lane counters for the telemetry plane."""
        with self._cond:
            pending = len(self._pending)
            inflight = len(self._inflight_entries)
        return {
            "pending": pending,
            "inflight": inflight,
            "max_pending": self._max_pending,
            "max_pending_seen": self.max_pending_seen,
            "submitted": self.submitted,
            "cache_hits": self.cache_hits,
            "dedup_joins": self.dedup_joins,
            "structural_rejects": self.structural_rejects,
            "overload_rejections": self.overload_rejections,
            "batches": self.batches,
            "batch_items": self.batch_items,
            "batch_mean": (
                round(self.batch_items / self.batches, 2) if self.batches else 0.0
            ),
            "max_batch_seen": self.max_batch_seen,
            "rlc_batches": self.rlc_batches,
            "batch_fallbacks": self.batch_fallbacks,
            "verified_true": self.verified_true,
            "verified_false": self.verified_false,
            "pairing_ms_ema": round(self._pairing_ms_ema, 3),
            "last_batch_ms": round(self.last_batch_ms, 3),
            "last_batch_items": self.last_batch_items,
        }


_lane_lock = sanitize.wrap_lock(threading.Lock(), "qc.lane_registry")
_lane: Optional[QcVerifyLane] = None


def qc_lane() -> QcVerifyLane:
    """The process-wide lane (lazily created): every in-process replica
    shares it, so concurrent replicas' certificate checks coalesce into
    the same RLC batches — the same sharing shape as the coalescing
    Ed25519 VerifyService."""
    global _lane
    with _lane_lock:
        if _lane is None:
            _lane = QcVerifyLane()
        return _lane


def lane_snapshot() -> Optional[dict]:
    """Snapshot of the process lane, or None when no QC was ever
    submitted (non-QC committees pay nothing for the lane existing)."""
    with _lane_lock:
        return _lane.snapshot() if _lane is not None else None


async def verify_qc_async(cfg, qc: QuorumCert) -> bool:
    """The runtime's certificate check: submit to the lane and await the
    batched verdict off-loop. Raises QcLaneOverloaded when the lane's
    admission queue is at cap (callers shed; the cert re-arrives).

    Under simulation (simple_pbft_tpu/sim.py) the pairing runs INLINE:
    the lane's worker thread completes in wall time, which a virtual
    clock outruns arbitrarily — every downstream interleaving would
    race it. Loop-blocking is harmless there (nothing real-time shares
    a simulated loop), and the verdict memo keeps the cost one pairing
    per distinct certificate either way."""
    import asyncio

    from .. import clock

    if clock.simulated():
        # pbftlint: disable=PBL001 -- sim-only branch: clock.simulated() gates it off every production loop; blocking a simulated loop is the determinism contract, not a stall
        return verify_qc(cfg, qc)
    return await asyncio.wrap_future(qc_lane().submit(cfg, qc))
