"""Quorum-certificate helpers: share signing, aggregation, cached verify.

The QC path (config.qc_mode, BASELINE config 4) moves vote traffic from
O(n^2) all-to-all broadcast to O(n): replicas BLS-sign the phase payload
and send the share to the primary only; the primary aggregates 2f+1
shares into one ``QuorumCert`` whose pairing check certifies the whole
phase. This module owns the share/aggregate/verify mechanics so the
replica runtime stays protocol-shaped.

Verification results are memoized process-wide, keyed by the full
(payload, signer set, aggregate) triple — deterministic, so sharing the
memo across in-process replicas is sound, and a 256-node simulated
committee pays each ~0.8 s pairing once instead of once per replica.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from ..crypto import bls
from ..messages import QuorumCert, qc_payload

# Vote QCs drive instance transitions; "checkpoint" certs attest state
# digests (view pinned to 0 in the payload — checkpoints are
# view-independent) and travel ONLY inside view-change certificates.
# Routing guards use VOTE_PHASES so the two sets cannot drift.
VOTE_PHASES = ("prepare", "commit")
PHASES = VOTE_PHASES + ("checkpoint",)

_CACHE_MAX = 4096
_cache: "OrderedDict[tuple, bool]" = OrderedDict()
_cache_lock = threading.Lock()
# key -> Event for a pairing currently being computed: concurrent callers
# of the same certificate (every backup receives the primary's broadcast
# at once) wait for the first computation instead of redundantly burning
# ~0.8 s of CPU each — the memo's once-per-process promise, made true
# under concurrency as well.
_inflight: Dict[tuple, threading.Event] = {}


def sign_share(bls_sk: int, phase: str, view: int, seq: int, digest: str) -> str:
    """One replica's BLS share over the QC payload, hex for the wire."""
    return bls.sign(bls_sk, qc_payload(phase, view, seq, digest)).hex()


def share_valid_shape(share_hex: str) -> bool:
    """Cheap structural check (hex, curve point) — NOT a signature check;
    the aggregate pairing (or failure bisection) is the authority."""
    try:
        raw = bytes.fromhex(share_hex)
    except ValueError:
        return False
    return bls._g1_from_bytes(raw) is not None


def build_qc(
    phase: str,
    view: int,
    seq: int,
    digest: str,
    shares: Dict[str, str],
    quorum: int,
) -> Optional[QuorumCert]:
    """Aggregate `quorum` shares (signer -> hex share) into a QuorumCert.
    Callers verify the result before broadcasting (a Byzantine share
    corrupts the aggregate; see bisect_bad_shares)."""
    signers = sorted(shares)[:quorum] if len(shares) >= quorum else None
    if signers is None:
        return None
    try:
        raws = [bytes.fromhex(shares[s]) for s in signers]
    except ValueError:
        return None
    agg = bls.aggregate_signatures(raws)
    if agg is None:
        return None
    return QuorumCert(
        phase=phase,
        view=view,
        seq=seq,
        digest=digest,
        signers=list(signers),
        agg_sig=agg.hex(),
    )


def verify_qc(cfg, qc: QuorumCert) -> bool:
    """Full certificate check: structure, signer set, one pairing.
    Pairing-expensive (~0.8 s pure Python) — run off-loop; results are
    memoized process-wide."""
    if qc.phase not in PHASES:
        return False
    if len(qc.signers) < cfg.quorum or len(set(qc.signers)) != len(qc.signers):
        return False
    pks: List[bytes] = []
    for s in qc.signers:
        pk = cfg.bls_pubkey(s)
        if pk is None:
            return False
        pks.append(pk)
    try:
        agg = bytes.fromhex(qc.agg_sig)
    except ValueError:
        return False
    payload = qc.payload()
    key = (payload, tuple(qc.signers), qc.agg_sig)
    while True:
        with _cache_lock:
            hit = _cache.get(key)
            if hit is not None:
                _cache.move_to_end(key)
                return hit
            waiter = _inflight.get(key)
            if waiter is None:
                _inflight[key] = threading.Event()
                break
        waiter.wait()  # another thread is computing this exact pairing
    ok: Optional[bool] = None
    try:
        ok = bls.verify_aggregate(pks, payload, agg)
    finally:
        with _cache_lock:
            ev = _inflight.pop(key, None)
            if ok is not None:  # None = exception: waiters recompute
                _cache[key] = ok
                while len(_cache) > _CACHE_MAX:
                    _cache.popitem(last=False)
        if ev is not None:
            ev.set()
    return ok


def bisect_bad_shares(
    cfg, phase: str, view: int, seq: int, digest: str, shares: Dict[str, str]
) -> Dict[str, str]:
    """Aggregate failed its pairing: verify each share individually and
    return only the good ones. Costs one pairing per share — only runs
    when a Byzantine replica actually sent a corrupt share, and each bad
    signer is then excluded by the caller, bounding the total damage to f
    bisections."""
    payload = qc_payload(phase, view, seq, digest)
    good: Dict[str, str] = {}
    for signer, share_hex in shares.items():
        pk = cfg.bls_pubkey(signer)
        if pk is None:
            continue
        try:
            raw = bytes.fromhex(share_hex)
        except ValueError:
            continue
        if bls.verify(pk, payload, raw):
            good[signer] = share_hex
    return good
