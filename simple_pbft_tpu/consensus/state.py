"""Per-(view, seq) PBFT instance state machine — pure logic, no I/O.

Parity target: the reference's ``State`` in pbft/consensus/pbft_impl.go
(Stage enum :27-32, phase methods :55-173, quorum predicates :207-232).
Redesigned:

- One ``Instance`` per (view, seq) so many consensus rounds run
  concurrently (the reference's single scalar ``CurrentState``, node.go:21,
  serializes rounds — its author's gap #2, 需要改进的地方.md:14-15).
- Castro-Liskov quorums: prepared = pre-prepare + 2f+1 distinct prepare
  senders (own vote counts); committed-local = prepared + 2f+1 distinct
  commit senders. (The reference counts 2f votes excluding its own,
  pbft_impl.go:212,227 — same tolerance, different bookkeeping.)
- Inputs are assumed *signature-verified already* (the replica runtime
  batch-verifies via the crypto plane before feeding instances); this
  module still enforces view/seq/digest consistency, mirroring
  ``verifyMsg`` (pbft_impl.go:176-202).

Methods return ``Action`` values describing what the runtime should do
(broadcast a vote, execute a block) — the state machine itself never sends.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from ..messages import Commit, PrePrepare, Prepare, QuorumCert


class Stage(enum.Enum):
    """Reference: Stage enum pbft_impl.go:27-32 (Idle/PrePrepared/
    Prepared/Committed)."""

    IDLE = 0
    PRE_PREPARED = 1
    PREPARED = 2
    COMMITTED = 3


@dataclass
class SendPrepare:
    view: int
    seq: int
    digest: str


@dataclass
class SendCommit:
    view: int
    seq: int
    digest: str


@dataclass
class ExecuteBlock:
    view: int
    seq: int
    digest: str
    block: List[Dict[str, Any]]


Action = Union[SendPrepare, SendCommit, ExecuteBlock]


@dataclass
class Instance:
    """State of one consensus slot (view, seq) at one replica."""

    view: int
    seq: int
    quorum: int  # 2f+1
    primary: str  # the view's primary — the only allowed pre-prepare sender
    stage: Stage = Stage.IDLE
    digest: Optional[str] = None
    block: Optional[List[Dict[str, Any]]] = None
    pre_prepare: Optional[PrePrepare] = None
    prepares: Dict[str, Prepare] = field(default_factory=dict)
    commits: Dict[str, Commit] = field(default_factory=dict)
    executed: bool = False
    # QC mode (config.qc_mode): transitions are driven by verified
    # QuorumCerts, not by counting votes locally — votes flow to the
    # primary only, so a backup's vote logs never reach quorum.
    qc_mode: bool = False
    prepare_qc: Optional[QuorumCert] = None  # verified, phase=prepare
    commit_qc: Optional[QuorumCert] = None
    t_started: float = 0.0  # perf_counter at pre-prepare admission (stats)
    # phase-transition clocks (ISSUE 4 spans): set by the runtime when
    # the slot prepares / its commit certificate forms, so the three
    # phase.* spans tile t_started -> execution exactly and their sum
    # reconciles against the commit_ms histogram (tools/critical_path)
    t_prepared: float = 0.0
    t_committed: float = 0.0
    # conflicting-digest rejections retained for forensics (ISSUE 5):
    # (sender, digest) of messages this slot turned away because they
    # disagreed with the fixed digest — the wedge-autopsy instance table
    # can then tell "slot starved by loss" from "slot contested by a
    # fork" at a glance. Compact tuples, not the messages: a byzantine
    # pre-prepare carries an attacker-sized block, and pinning four of
    # those per contested in-flight slot until watermark GC would be a
    # memory lever. The audit plane (audit.SafetyAuditor) independently
    # records the full signed evidence; this is only the state
    # machine's own breadcrumb.
    conflicts: List[Tuple[str, str]] = field(default_factory=list)
    # incremental counts of votes matching the fixed digest — counting
    # the logs on every arrival was O(n) per vote = O(n^2) per slot per
    # replica (measured ~7% of an n=100 committee's CPU)
    _prep_matching: int = 0
    _com_matching: int = 0

    MAX_CONFLICTS = 4  # forensic breadcrumbs, not a log

    def _note_conflict(self, msg: Union[PrePrepare, Prepare, Commit]) -> None:
        if len(self.conflicts) < self.MAX_CONFLICTS:
            self.conflicts.append((msg.sender, msg.digest))

    def _recount_matching(self) -> None:
        """Digest just became fixed: count the buffered early votes."""
        self._prep_matching = sum(
            1 for v in self.prepares.values() if v.digest == self.digest
        )
        self._com_matching = sum(
            1 for v in self.commits.values() if v.digest == self.digest
        )

    # -- phase inputs -------------------------------------------------------

    def on_pre_prepare(self, msg: PrePrepare) -> List[Action]:
        """Reference: State.PrePrepare (pbft_impl.go:91-109).

        Accept the primary's proposal once; check digest covers the block;
        move to PRE_PREPARED and vote prepare.
        """
        if msg.view != self.view or msg.seq != self.seq:
            return []
        if msg.sender != self.primary:
            return []  # only the view's primary may propose (verifyMsg's
            # primary-identity check; a Byzantine backup must not steal slots)
        if self.pre_prepare is not None:
            if msg.digest != self.digest:
                self._note_conflict(msg)  # contested slot: keep the proof
            return []  # already have one for this slot (first wins)
        if self.digest is not None and msg.digest != self.digest:
            # the slot's digest was already fixed by a verified quorum
            # certificate (QC mode, QC-before-pre-prepare arrival order);
            # an equivocating primary must not swap in a different block
            # and ride the stored commit QC into executing it
            self._note_conflict(msg)
            return []
        if PrePrepare.block_digest(msg.block) != msg.digest:
            return []  # digest mismatch — mirrors verifyMsg digest check
        self.pre_prepare = msg
        if self.digest is None:
            self.digest = msg.digest
            self._recount_matching()
        self.block = msg.block
        if self.stage == Stage.IDLE:
            self.stage = Stage.PRE_PREPARED
        out: List[Action] = [SendPrepare(self.view, self.seq, msg.digest)]
        # Votes that arrived before the pre-prepare (buffered by pools) may
        # already form a quorum — re-evaluate.
        out.extend(self._maybe_advance())
        return out

    def on_prepare(self, msg: Prepare) -> List[Action]:
        """Reference: State.Prepare (pbft_impl.go:115-139)."""
        if msg.view != self.view or msg.seq != self.seq:
            return []
        if self.digest is not None and msg.digest != self.digest:
            self._note_conflict(msg)
            return []  # vote for a different proposal
        if msg.sender in self.prepares:
            return []  # duplicate sender
        self.prepares[msg.sender] = msg
        if self.digest is not None and msg.digest == self.digest:
            self._prep_matching += 1
        return self._maybe_advance()

    def on_commit(self, msg: Commit) -> List[Action]:
        """Reference: State.Commit (pbft_impl.go:145-173)."""
        if msg.view != self.view or msg.seq != self.seq:
            return []
        if self.digest is not None and msg.digest != self.digest:
            self._note_conflict(msg)
            return []
        if msg.sender in self.commits:
            return []
        self.commits[msg.sender] = msg
        if self.digest is not None and msg.digest == self.digest:
            self._com_matching += 1
        return self._maybe_advance()

    # -- quorum predicates --------------------------------------------------

    def prepared(self) -> bool:
        """Reference: prepared() pbft_impl.go:207-217."""
        return (
            self.pre_prepare is not None
            and self._prep_matching >= self.quorum
        )

    def committed(self) -> bool:
        """Reference: committed() pbft_impl.go:222-232."""
        return self.prepared() and self._com_matching >= self.quorum

    # -- transitions --------------------------------------------------------

    def _maybe_advance(self) -> List[Action]:
        if self.qc_mode:
            # quorum formation happens at the primary via QC aggregation;
            # local vote counts must not drive transitions
            return self._maybe_advance_qc()
        out: List[Action] = []
        # the is-not-None re-checks are implied by prepared()/committed()
        # (a quorum fixes the digest and admits the block) but let mypy
        # prove the Action fields are never None
        if (
            self.stage == Stage.PRE_PREPARED
            and self.prepared()
            and self.digest is not None
        ):
            self.stage = Stage.PREPARED
            out.append(SendCommit(self.view, self.seq, self.digest))
        if (
            self.stage == Stage.PREPARED
            and self.committed()
            and self.digest is not None
            and self.block is not None
        ):
            self.stage = Stage.COMMITTED
            if not self.executed:
                self.executed = True
                out.append(
                    ExecuteBlock(self.view, self.seq, self.digest, self.block)
                )
        return out

    # -- QC-mode transitions -------------------------------------------------

    def on_prepare_qc(self, qc: QuorumCert) -> List[Action]:
        """A VERIFIED prepare QC for this slot. The commit share is only
        emitted once our own pre-prepare is also held (_maybe_advance_qc):
        a replica in the commit quorum must be able to produce a P-set
        entry ({pre_prepare, prepare_qc}) in a view change, or the
        quorum-intersection argument that protects committed blocks
        across views breaks."""
        if (qc.view, qc.seq) != (self.view, self.seq):
            return []
        if self.digest is not None and qc.digest != self.digest:
            return []  # conflicts with the pre-prepare we admitted
        if self.prepare_qc is not None:
            return []
        self.prepare_qc = qc
        if self.digest is None:
            self.digest = qc.digest
            self._recount_matching()
        return self._maybe_advance_qc()

    def on_commit_qc(self, qc: QuorumCert) -> List[Action]:
        if (qc.view, qc.seq) != (self.view, self.seq):
            return []
        if self.digest is not None and qc.digest != self.digest:
            return []
        if self.commit_qc is not None:
            return []
        self.commit_qc = qc
        if self.digest is None:
            self.digest = qc.digest
            self._recount_matching()
        return self._maybe_advance_qc()

    def _maybe_advance_qc(self) -> List[Action]:
        out: List[Action] = []
        if (
            self.prepare_qc is not None
            and self.pre_prepare is not None  # must be able to prove the
            # slot in a view change (prepared_proof needs the block)
            and self.stage in (Stage.IDLE, Stage.PRE_PREPARED)
            and self.digest is not None  # fixed by the QC admission
        ):
            self.stage = Stage.PREPARED
            out.append(SendCommit(self.view, self.seq, self.digest))
        if (
            self.commit_qc is not None
            and self.stage is not Stage.COMMITTED
            # a commit QC subsumes the prepare QC (2f+1 replicas held one);
            # execution still needs the block content from the pre-prepare
            and self.block is not None
            and self.digest is not None
            and not self.executed
        ):
            self.stage = Stage.COMMITTED
            self.executed = True
            out.append(
                ExecuteBlock(self.view, self.seq, self.digest, self.block)
            )
        return out

    # -- hole repair ---------------------------------------------------------

    def adopt_block(self, block: List[Dict[str, Any]]) -> List[Action]:
        """Catch-up refill: a self-authenticating block (its digest must
        match the digest a verified quorum certificate fixed for this
        slot) for a slot whose pre-prepare was never delivered — the
        steady-state hole SlotFetch repairs (replica._on_block_reply).
        Never overrides an admitted block; emits at most the execution
        transition (no votes)."""
        if self.block is not None or self.digest is None:
            return []
        if PrePrepare.block_digest(block) != self.digest:
            return []
        self.block = block
        return self._maybe_advance_qc() if self.qc_mode else []

    # -- view-change support -------------------------------------------------

    def _detached_pre_prepare(self) -> Dict[str, Any]:
        """The pre-prepare with its block stripped: the digest (which the
        signature covers — PrePrepare.signing_payload detaches the block)
        binds the content, so certificates ship digests and receivers
        refill blocks locally or via BlockFetch. This is what keeps
        VIEW-CHANGE/NEW-VIEW wires small under load."""
        if self.pre_prepare is None:  # callers guard; keep mypy honest
            raise RuntimeError("no pre-prepare admitted for this slot")
        d = self.pre_prepare.to_dict()
        d["block"] = []
        return d

    def prepared_proof(self) -> Optional[Dict[str, Any]]:
        """If prepared, the certificate a VIEW-CHANGE message carries for
        this slot (Castro-Liskov P-set): {pre-prepare, 2f+1 prepares} —
        or, in QC mode, {pre-prepare, prepare_qc}: the aggregate IS the
        2f+1-signer certificate, one pairing check instead of 2f+1
        signature checks and a fraction of the wire bytes. Pre-prepares
        ship digest-only (blocks detached)."""
        if self.qc_mode:
            if self.prepare_qc is None or self.pre_prepare is None:
                return None
            if self.prepare_qc.digest != self.pre_prepare.digest:
                return None
            return {
                "pre_prepare": self._detached_pre_prepare(),
                "prepare_qc": self.prepare_qc.to_dict(),
            }
        if not self.prepared():
            return None
        votes = [
            p.to_dict()
            for p in self.prepares.values()
            if p.digest == self.digest
        ]
        return {
            "pre_prepare": self._detached_pre_prepare(),
            "prepares": votes[: self.quorum],
        }
