"""Injectable clock seam (ISSUE 13 tentpole).

Every TIMER DECISION in the clock-injectable modules — replica view/
retransmit/cooldown deadlines, client backoff and request timestamps,
the statesync retry tick, telemetry watchdogs, the fault injector's
event offsets — goes through this module instead of reading the OS
clock directly:

- ``clock.now()``     instead of ``time.monotonic()``/``perf_counter()``
- ``clock.sleep(d)``  instead of ``asyncio.sleep(d)``
- ``clock.timestamp_us()`` instead of ``int(time.time() * 1e6)``
- ``clock.off_thread(fn, *a)`` instead of ``asyncio.to_thread(fn, *a)``

In wall mode (the default, and the only mode real deployments run) the
four are thin aliases with identical behavior. Under simulation
(simple_pbft_tpu/sim.py installs a :class:`SimClock`) ``now()`` reads
the SimLoop's VIRTUAL time — which jumps to the next scheduled event
instead of sleeping — ``timestamp_us()`` derives request timestamps
from virtual time against a fixed epoch (bit-identical traces run to
run), and ``off_thread`` runs the work inline on the loop, because a
real worker thread completes in wall time and would race virtual time
nondeterministically.

Timers scheduled directly on the event loop (``loop.call_later``,
``loop.call_at``, ``asyncio.wait_for``) need no seam: they already key
on ``loop.time()``, which the SimLoop virtualizes wholesale. The seam
exists for the OTHER clock reads — deadline/cooldown comparisons held
in plain floats — which would silently freeze (cooldowns never expire)
or starve (deadlines never arrive) if they stayed on the wall clock
while the loop's time compressed.

pbftlint PBL007 enforces the contract: raw ``time.monotonic()`` /
``time.perf_counter()`` / ``time.time()`` / ``asyncio.sleep()`` /
``loop.time()`` in a clock-injectable module is a finding unless a
justified suppression names why that site is exempt.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable


class WallClock:
    """The default: real monotonic time, real sleeps, real threads."""

    simulated = False

    def now(self) -> float:
        return time.monotonic()

    def timestamp_us(self) -> int:
        # wall-derived (Castro-Liskov §2.4): client request timestamps
        # must be monotonic ACROSS process restarts — see client.py
        return int(time.time() * 1_000_000)

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)

    async def off_thread(self, fn: Callable, *args: Any) -> Any:
        return await asyncio.to_thread(fn, *args)


class SimClock:
    """Virtual clock bound to a SimLoop (simple_pbft_tpu/sim.py).

    ``now()`` is the loop's virtual time, so deadline math in product
    code and the loop's own timers share one timebase. Request
    timestamps derive from virtual time against a FIXED epoch: the same
    scenario seed replays byte-identical wire traffic, and a "restart"
    within one simulation stays monotonic because virtual time does.
    """

    simulated = True

    # deterministic wall anchor for timestamp_us (an arbitrary constant;
    # only monotonicity and reproducibility matter inside a simulation)
    SIM_WALL_EPOCH_US = 1_700_000_000_000_000

    def __init__(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def now(self) -> float:
        return self._loop.time()

    def timestamp_us(self) -> int:
        return self.SIM_WALL_EPOCH_US + int(self._loop.time() * 1_000_000)

    async def sleep(self, delay: float) -> None:
        # plain asyncio.sleep: the SimLoop virtualizes loop timers, so
        # this parks on a virtual deadline, not a wall one
        await asyncio.sleep(delay)

    async def off_thread(self, fn: Callable, *args: Any) -> Any:
        # inline: a worker thread finishes in WALL time, which under a
        # compressed virtual clock is "arbitrarily late" — every
        # interleaving downstream of it would be a race against however
        # far virtual time happened to jump meanwhile. Simulation trades
        # loop-blocking (harmless: nothing real-time shares the loop)
        # for determinism.
        return fn(*args)


_WALL = WallClock()
_active: Any = _WALL


def get() -> Any:
    return _active


def simulated() -> bool:
    return bool(_active.simulated)


def install(c: Any) -> Any:
    """Install a clock; returns the previous one (callers restore it in
    a finally — sim_run does)."""
    global _active
    prev = _active
    _active = c
    return prev


def reset() -> None:
    global _active
    _active = _WALL


def now() -> float:
    return _active.now()


def timestamp_us() -> int:
    return _active.timestamp_us()


async def sleep(delay: float) -> None:
    await _active.sleep(delay)


async def off_thread(fn: Callable, *args: Any) -> Any:
    return await _active.off_thread(fn, *args)
