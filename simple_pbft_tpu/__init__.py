"""simple_pbft_tpu — a TPU-native PBFT consensus framework.

A from-scratch rebuild of the capabilities of the reference `simple_pbft`
(an educational pure-Go PBFT: three-phase pre-prepare/prepare/commit
consensus for an f=1 committee; see /root/reference, surveyed in SURVEY.md),
redesigned TPU-first:

- **Consensus plane** (pure Python, event-driven): per-sequence-number PBFT
  state machines (replacing the reference's single scalar ``CurrentState``,
  node.go:21), message pools keyed by (view, seq) (replacing the
  per-NodeID/per-ClientID pools in pool/*.go), an asyncio replica runtime
  with event-driven wakeups (replacing the 1 s polling tick, node.go:44,513),
  a client library with f+1 matching replies, checkpointing with h/H
  watermarks, and a full view-change protocol (the reference's view.go is
  dead code).

- **Crypto plane** (JAX/XLA/Pallas, the TPU-native part): every consensus
  message is Ed25519-signed (the reference has *no* signatures —
  see SURVEY.md §2.9), and signature verification — the hot path of any
  production PBFT — is batched and executed on TPU: pools drain pending
  (message, signature, pubkey) tuples into one vmapped Ed25519 verification
  pass, with GF(2^255-19) field arithmetic in limb-decomposed int32
  vector ops / Pallas kernels, returning a validity bitmap so
  quorum-certificate formation is one TPU call per round.
"""

__version__ = "0.1.0"


def force_cpu() -> None:
    """Force the JAX CPU backend IN-PROCESS, before any backend
    initializes. On chip-tunnel hosts the ambient sitecustomize
    force-registers the axon TPU backend and OVERRIDES the JAX_PLATFORMS
    env var, so code that must not touch the (possibly wedged) tunnel —
    CPU test suites, bench smoke runs, plumbing shakeouts — calls this
    first instead of trusting the environment."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _cache_fingerprint() -> str:
    """Hash of the local CPU's feature flags. Cache entries include
    XLA:CPU AOT machine code; an entry compiled against a different
    CPU's features — e.g. by the remote side of a device tunnel, whose
    host advertises AMX/prefer-no-scatter this machine lacks — loads
    with a warning and then wedges or SIGILLs at execution (observed:
    every consensus --verifier tpu run deadlocking inside a cached
    executable while holding the device lock). Namespacing the cache
    directory by (backend, CPU flags) makes such entries unreachable."""
    import hashlib
    import platform

    fp = platform.processor() or platform.machine() or "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith(("flags", "features")):
                    fp = line
                    break
    except OSError:
        pass
    return hashlib.sha256(fp.encode()).hexdigest()[:10]


def enable_jit_cache(path: str | None = None) -> None:
    """Point JAX's persistent compilation cache at a shared directory so
    the crypto kernels (40-60 s compiles on small CPU hosts) compile once
    per machine, not once per process. Call before the first jit
    execution. Used by tests/conftest.py and the benchmarks; override the
    location with SIMPLE_PBFT_JIT_CACHE or the `path` argument.

    The directory is partitioned by CPU fingerprint — see
    _cache_fingerprint for the cross-machine poisoning this prevents.
    (Platform/backend is already part of JAX's own cache key, and
    consulting jax.default_backend() here would INITIALIZE the ambient
    backend — breaking callers like bench.py --smoke that select the
    CPU platform after pointing the cache.)"""
    import os

    import jax

    uid = os.getuid() if hasattr(os, "getuid") else 0
    base = path or os.environ.get(
        "SIMPLE_PBFT_JIT_CACHE", f"/tmp/jax_cache_simple_pbft_{uid}"
    )
    cache = os.path.join(base, f"host-{_cache_fingerprint()}")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
