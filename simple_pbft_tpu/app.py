"""Pluggable application (execution) layer.

The reference never executes anything: commit sets ``result = "Executed"``
(a literal string, pbft_impl.go:158) and drops the operation. Here
execution is a real seam: committed blocks are applied in sequence order to
an ``Application``, whose state digest feeds checkpoint messages, and whose
snapshot/restore pair supports state transfer to lagging replicas.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Protocol


class Application(Protocol):
    def apply(self, op: str) -> str:
        """Execute one operation, return its result string."""
        ...

    def state_digest(self) -> str:
        """Digest of current state (checkpoint identity). Must equal
        sha256(snapshot()) so snapshots are verifiable against checkpoint
        certificates."""
        ...

    def snapshot(self) -> str:
        """Serialize full state (state-transfer payload)."""
        ...

    def restore(self, snap: str) -> None:
        """Replace state with a snapshot."""
        ...


def snapshot_digest(snap: str) -> str:
    return hashlib.sha256(snap.encode()).hexdigest()


class EchoApp:
    """Reference-parity app: every operation 'executes' to a fixed string
    (mirrors pbft_impl.go:158)."""

    def apply(self, op: str) -> str:
        return "Executed"

    def snapshot(self) -> str:
        return ""

    def restore(self, snap: str) -> None:
        pass

    def state_digest(self) -> str:
        return snapshot_digest("")


class KVStore:
    """Tiny ordered key-value store: ``put k v`` / ``get k`` / ``noop``.

    Deterministic across replicas (a requirement the reference never faced,
    having no execution). The state digest is the hash of the canonical
    snapshot, so a lagging replica can verify a transferred snapshot
    against a 2f+1 checkpoint certificate.
    """

    def __init__(self) -> None:
        self.data: Dict[str, str] = {}

    def apply(self, op: str) -> str:
        parts = op.split(" ")
        if parts[0] == "put" and len(parts) >= 3:
            key, value = parts[1], " ".join(parts[2:])
            self.data[key] = value
            return "ok"
        if parts[0] == "get" and len(parts) == 2:
            return self.data.get(parts[1], "")
        if parts[0] == "noop":
            return "ok"
        return "err:bad-op"

    def snapshot(self) -> str:
        return json.dumps(self.data, sort_keys=True, separators=(",", ":"))

    def restore(self, snap: str) -> None:
        data = json.loads(snap)
        if not isinstance(data, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in data.items()
        ):
            raise ValueError("bad snapshot")
        self.data = data

    def state_digest(self) -> str:
        return snapshot_digest(self.snapshot())
