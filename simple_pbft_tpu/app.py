"""Pluggable application (execution) layer.

The reference never executes anything: commit sets ``result = "Executed"``
(a literal string, pbft_impl.go:158) and drops the operation. Here
execution is a real seam: committed blocks are applied in sequence order to
an ``Application``, whose state digest feeds checkpoint messages, and whose
snapshot/restore pair supports state transfer to lagging replicas.

ISSUE 15 adds the speculative seam: :class:`ForkableApp` holds a
disposable FORK of the committed state that prepared-but-uncommitted
blocks execute against (Proof-of-Execution-style speculation,
consensus/speculation.py). The committed surface — ``apply`` /
``snapshot`` / ``state_digest`` / ``restore`` — always reflects ONLY
finally-committed execution, so checkpoint digests can never absorb
speculative writes; the fork is a separate object built from (and
discarded back to) the committed snapshot.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, FrozenSet, Optional, Protocol, Tuple


class Application(Protocol):
    def apply(self, op: str) -> str:
        """Execute one operation, return its result string."""
        ...

    def state_digest(self) -> str:
        """Digest of current state (checkpoint identity). Must equal
        sha256(snapshot()) so snapshots are verifiable against checkpoint
        certificates."""
        ...

    def snapshot(self) -> str:
        """Serialize full state (state-transfer payload)."""
        ...

    def restore(self, snap: str) -> None:
        """Replace state with a snapshot."""
        ...


def snapshot_digest(snap: str) -> str:
    return hashlib.sha256(snap.encode()).hexdigest()


class EchoApp:
    """Reference-parity app: every operation 'executes' to a fixed string
    (mirrors pbft_impl.go:158)."""

    def apply(self, op: str) -> str:
        return "Executed"

    def snapshot(self) -> str:
        return ""

    def restore(self, snap: str) -> None:
        pass

    def state_digest(self) -> str:
        return snapshot_digest("")


class KVStore:
    """Tiny ordered key-value store: ``put k v`` / ``get k`` / ``noop``.

    Deterministic across replicas (a requirement the reference never faced,
    having no execution). The state digest is the hash of the canonical
    snapshot, so a lagging replica can verify a transferred snapshot
    against a 2f+1 checkpoint certificate.
    """

    def __init__(self) -> None:
        self.data: Dict[str, str] = {}

    def apply(self, op: str) -> str:
        parts = op.split(" ")
        if parts[0] == "put" and len(parts) >= 3:
            key, value = parts[1], " ".join(parts[2:])
            self.data[key] = value
            return "ok"
        if parts[0] == "get" and len(parts) == 2:
            return self.data.get(parts[1], "")
        if parts[0] == "noop":
            return "ok"
        return "err:bad-op"

    def snapshot(self) -> str:
        return json.dumps(self.data, sort_keys=True, separators=(",", ":"))

    def restore(self, snap: str) -> None:
        data = json.loads(snap)
        if not isinstance(data, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in data.items()
        ):
            raise ValueError("bad snapshot")
        self.data = data

    def state_digest(self) -> str:
        return snapshot_digest(self.snapshot())

    def rw_sets(
        self, op: str
    ) -> Optional[Tuple[FrozenSet[str], FrozenSet[str]]]:
        """(reads, writes) key sets of one operation, or None when the
        op is unparsable. Out-of-order speculation (consensus/
        speculation.py) uses this to prove a later slot commutes with a
        committed-but-unapplied gap; None disables that fast path for
        the op — never a wrong answer."""
        parts = op.split(" ")
        if parts[0] == "put" and len(parts) >= 3:
            return frozenset(), frozenset([parts[1]])
        if parts[0] == "get" and len(parts) == 2:
            return frozenset([parts[1]]), frozenset()
        if parts[0] == "noop":
            return frozenset(), frozenset()
        return None


class ForkableApp:
    """Committed application + a disposable speculative fork.

    The Application protocol surface (``apply``/``snapshot``/``restore``/
    ``state_digest``) delegates to the COMMITTED inner app only — by
    construction a checkpoint snapshot cut through this wrapper can never
    contain speculative writes (the ISSUE 15 safety invariant). The fork
    is a second instance of the same Application class, (re)built from
    the committed snapshot on first speculative apply after a rollback,
    and kept in lockstep thereafter: confirmed slots apply to BOTH
    states (the fork via ``apply_spec`` at prepare time, the committed
    app via ``apply`` at commit time), so in honest runs the two digests
    converge whenever speculation drains.

    Unknown attributes delegate to the inner app (``r.app.data`` etc.
    keep working for tests and tools)."""

    def __init__(self, inner: Application) -> None:
        self.inner = inner
        self._fork: Optional[Application] = None
        self.forks_built = 0

    # -- Application protocol: committed state only ---------------------

    def apply(self, op: str) -> str:
        return self.inner.apply(op)

    def snapshot(self) -> str:
        return self.inner.snapshot()

    def restore(self, snap: str) -> None:
        self.inner.restore(snap)
        # the committed anchor moved under the fork (state transfer):
        # every speculative write built on the old anchor is void
        self._fork = None

    def state_digest(self) -> str:
        return self.inner.state_digest()

    # -- speculative fork ----------------------------------------------

    def forkable(self) -> bool:
        """Can a fork be built? Needs a zero-arg-constructible app class
        with snapshot/restore — checked once, cheaply, not assumed."""
        try:
            probe = type(self.inner)()
            probe.restore(self.inner.snapshot())
            return True
        except Exception:  # noqa: BLE001 — any failure: speculation off
            return False

    def _ensure_fork(self) -> Application:
        if self._fork is None:
            fork = type(self.inner)()
            fork.restore(self.inner.snapshot())
            self._fork = fork
            self.forks_built += 1
        return self._fork

    def apply_spec(self, op: str) -> str:
        """Execute one operation on the speculative fork (building it
        from the committed snapshot if none is open)."""
        return self._ensure_fork().apply(op)

    def spec_digest(self) -> Optional[str]:
        return self._fork.state_digest() if self._fork is not None else None

    def spec_open(self) -> bool:
        return self._fork is not None

    def rollback(self) -> None:
        """Discard the fork: speculative state walks back to the
        committed anchor. O(1) — the next apply_spec re-clones."""
        self._fork = None

    def rw_sets(self, op: str):
        fn = getattr(self.inner, "rw_sets", None)
        return fn(op) if callable(fn) else None

    def __getattr__(self, name):
        return getattr(self.inner, name)
