"""ctypes loader for the native host-prep library (pbft_native.cpp).

The shared object is built on demand with g++ (cached next to the source,
rebuilt when the source is newer) and loaded via ctypes — no pybind11
dependency. Every entry point has a pure-Python fallback so the framework
works on machines without a toolchain; `available()` reports which path is
active and the bench records it.

API (numpy in, numpy out, zero per-item Python work):
- challenge_batch(r, a, msgs) -> (n, 32) uint8 little-endian scalars
  k_i = SHA-512(R_i || A_i || M_i) mod L   (the Ed25519 challenge)
- sha512_batch(msgs) -> (n, 64) uint8 digests
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "pbft_native.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_pbft_native.so")
_SRC_BLS = os.path.join(os.path.dirname(__file__), "bls381.cpp")
_SO_BLS = os.path.join(os.path.dirname(__file__), "_bls381.so")
_SRC_ED = os.path.join(os.path.dirname(__file__), "ed25519.cpp")
_SO_ED = os.path.join(os.path.dirname(__file__), "_ed25519.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
# own lock: a first-use BLS build (g++, up to ~2 min) must not stall
# Ed25519 host-prep calls on the unrelated library
_bls_lock = threading.Lock()
_bls_lib: Optional[ctypes.CDLL] = None
_bls_tried = False
_ed_lock = threading.Lock()
_ed_lib: Optional[ctypes.CDLL] = None
_ed_tried = False

_u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _build_so(src: str, so: str, extra=()) -> bool:
    # per-process temp name: concurrent builders (multi-process launch,
    # parallel test workers) must never interleave linker output in a
    # shared file; os.replace keeps the final install atomic
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", *extra, "-shared", "-fPIC", "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        log.warning("native build failed (%s) %s — using Python fallback",
                    e, detail.decode(errors="replace")[:500])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load_library(src: str, so: str, configure, extra=()) -> Optional[ctypes.CDLL]:
    """Shared build-on-demand loader: rebuild when the source is newer
    (tolerating a missing source by using the cached .so), CDLL-load,
    then run ``configure(lib)`` (argtypes + optional selftest; raise
    AttributeError for stale exports, return None to reject). Any
    failure degrades to the caller's Python fallback."""
    name = os.path.basename(so)
    if not _ensure_built(src, so, extra=extra):
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError as e:
        log.warning("%s load failed: %s — using Python fallback", name, e)
        return None
    try:
        return configure(lib)
    except AttributeError as e:
        # a stale cached .so missing newer exports (e.g. source file
        # absent so no rebuild happened): degrade to the Python path
        log.warning("%s stale/incomplete: %s — Python fallback", name, e)
        return None


def _configure_hostprep(lib):
    lib.challenge_batch.argtypes = [
        _u8p, _u8p, _u8p, _i64p, ctypes.c_int64, _u8p,
    ]
    lib.challenge_batch.restype = None
    lib.sha512_batch.argtypes = [_u8p, _i64p, ctypes.c_int64, _u8p]
    lib.sha512_batch.restype = None
    lib.sc_reduce_batch.argtypes = [_u8p, ctypes.c_int64, _u8p]
    lib.sc_reduce_batch.restype = None
    lib.native_num_threads.argtypes = []
    lib.native_num_threads.restype = ctypes.c_int
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if not _tried:
            _tried = True
            _lib = _load_library(
                _SRC, _SO, _configure_hostprep, extra=("-fopenmp",)
            )
        return _lib


def _configure_bls(lib):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64 = ctypes.c_int64
    lib.bls_verify_one.argtypes = [
        u8p, u8p, i64, u8p, u8p, i64, ctypes.c_int,
    ]
    lib.bls_verify_one.restype = ctypes.c_int
    lib.bls_verify_aggregate.argtypes = [
        u8p, i64, u8p, i64, u8p, u8p, i64,
    ]
    lib.bls_verify_aggregate.restype = ctypes.c_int
    lib.bls_verify_batch_rlc.argtypes = [
        u8p, i64, u8p, _i64p, i64, u8p, u8p, u8p, i64,
    ]
    lib.bls_verify_batch_rlc.restype = ctypes.c_int
    lib.bls_sign.argtypes = [u8p, u8p, i64, u8p, i64, u8p]
    lib.bls_sign.restype = ctypes.c_int
    lib.bls_pubkey.argtypes = [u8p, u8p]
    lib.bls_pubkey.restype = ctypes.c_int
    lib.bls_selftest.argtypes = []
    lib.bls_selftest.restype = ctypes.c_int
    if lib.bls_selftest() != 1:
        log.warning("bls381 selftest FAILED — using Python fallback")
        return None
    return lib


def _load_bls() -> Optional[ctypes.CDLL]:
    global _bls_lib, _bls_tried
    with _bls_lock:
        if not _bls_tried:
            _bls_tried = True
            _bls_lib = _load_library(_SRC_BLS, _SO_BLS, _configure_bls)
        return _bls_lib


def _configure_ed(lib):
    _i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    lib.ed25519_batch_verify.argtypes = [
        _u8p, ctypes.c_int, _i32p, _u8p, _u8p, _u8p, _u8p, _u8p,
        ctypes.c_int,
    ]
    lib.ed25519_batch_verify.restype = ctypes.c_int
    lib.ed25519_fused_table.argtypes = [_u8p, ctypes.c_int, _u8p]
    lib.ed25519_fused_table.restype = ctypes.c_int
    return lib


def _load_ed() -> Optional[ctypes.CDLL]:
    global _ed_lib, _ed_tried
    with _ed_lock:
        if not _ed_tried:
            _ed_tried = True
            _ed_lib = _load_library(_SRC_ED, _SO_ED, _configure_ed)
        return _ed_lib


def ed25519_available() -> bool:
    return _load_ed() is not None


def ed25519_fused_table(
    a_xy: np.ndarray, wbits: int
) -> Optional[np.ndarray]:
    """Affine pubkey (64,) uint8 (x||y LE) -> (npos * 4^wbits, 96) uint8
    affine-Niels field-element bytes for the fused dual-scalar comb
    (KeyBank cold-start fast path); None = library unavailable."""
    lib = _load_ed()
    if lib is None:
        return None
    npos = -(-256 // wbits)
    n = npos * (1 << wbits) ** 2
    out = np.empty((n, 96), dtype=np.uint8)
    rc = lib.ed25519_fused_table(
        np.ascontiguousarray(a_xy, dtype=np.uint8), wbits, out
    )
    return out if rc == 0 else None


def ed25519_batch_verify(
    a_xy: np.ndarray,       # (n_keys, 64) uint8: affine x||y, 32B LE each
    key_idx: np.ndarray,    # (B,) int32 into a_xy (-1 = invalid key)
    s_scalars: np.ndarray,  # (B, 32) uint8, already range-checked < L
    k_scalars: np.ndarray,  # (B, 32) uint8, SHA-512(R||A||M) mod L
    r_wire: np.ndarray,     # (B, 32) uint8, signature R wire bytes
    precheck: np.ndarray,   # (B,) uint8 validity mask
) -> Optional[np.ndarray]:
    """Batched [S]B + [k](-A) == R verification; None = unavailable."""
    lib = _load_ed()
    if lib is None:
        return None
    batch = len(key_idx)
    out = np.zeros(batch, dtype=np.uint8)
    rc = lib.ed25519_batch_verify(
        np.ascontiguousarray(a_xy, dtype=np.uint8),
        len(a_xy),
        np.ascontiguousarray(key_idx, dtype=np.int32),
        np.ascontiguousarray(s_scalars, dtype=np.uint8),
        np.ascontiguousarray(k_scalars, dtype=np.uint8),
        np.ascontiguousarray(r_wire, dtype=np.uint8),
        np.ascontiguousarray(precheck, dtype=np.uint8),
        out,
        batch,
    )
    if rc != 0:
        return None
    return out


def _cbuf(b: bytes):
    return (ctypes.c_uint8 * max(1, len(b))).from_buffer_copy(b or b"\0")


def bls_available() -> bool:
    return _load_bls() is not None


def bls_verify_one(
    pubkey: bytes, msg: bytes, sig: bytes, dst: bytes, check_pk: bool
) -> Optional[bool]:
    """Native single-signature BLS verify; None = library unavailable
    (caller falls back to the Python path)."""
    if len(pubkey) != 192 or len(sig) != 96:
        return False
    lib = _load_bls()
    if lib is None:
        return None
    r = lib.bls_verify_one(
        _cbuf(pubkey), _cbuf(msg), len(msg), _cbuf(sig), _cbuf(dst),
        len(dst), 1 if check_pk else 0,
    )
    return bool(r)


def bls_sign(sk: int, msg: bytes, dst: bytes) -> Optional[bytes]:
    """Native BLS sign (bit-identical to the Python path — deterministic
    hash-and-multiply); None = unavailable (caller falls back, including
    out-of-range scalars the bigint path accepts)."""
    if not 0 <= sk < (1 << 256):
        return None
    lib = _load_bls()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 96)()
    r = lib.bls_sign(
        _cbuf(sk.to_bytes(32, "big")), _cbuf(msg), len(msg), _cbuf(dst),
        len(dst), out,
    )
    return bytes(out) if r else None


def bls_pubkey(sk: int) -> Optional[bytes]:
    """Native G2 pubkey derivation; None = unavailable (caller falls
    back, including out-of-range scalars)."""
    if not 0 <= sk < (1 << 256):
        return None
    lib = _load_bls()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 192)()
    r = lib.bls_pubkey(_cbuf(sk.to_bytes(32, "big")), out)
    return bytes(out) if r else None


def bls_verify_aggregate(
    pubkeys: Sequence[bytes], msg: bytes, sig: bytes, dst: bytes
) -> Optional[bool]:
    """Native aggregate BLS verify; None = library unavailable."""
    if not pubkeys or len(sig) != 96 or any(len(p) != 192 for p in pubkeys):
        return False
    lib = _load_bls()
    if lib is None:
        return None
    cat = b"".join(pubkeys)
    r = lib.bls_verify_aggregate(
        _cbuf(cat), len(pubkeys), _cbuf(msg), len(msg), _cbuf(sig),
        _cbuf(dst), len(dst),
    )
    return bool(r)


def bls_verify_batch_rlc(
    pubkeys: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    rands: Sequence[int],
    dst: bytes,
) -> Optional[bool]:
    """Native random-linear-combination batch verify of k aggregate
    signatures sharing ONE signer set (the QC-plane fast path): checks
    e(sum r_i*sig_i, G2) == e(sum r_i*H(m_i), agg_pk) with two Miller
    loops total. True = every cert in the batch is valid; False = the
    batch fails (the caller bisects); None = library unavailable."""
    k = len(msgs)
    if (
        k == 0
        or len(sigs) != k
        or len(rands) != k
        or not pubkeys
        or any(len(p) != 192 for p in pubkeys)
        or any(len(s) != 96 for s in sigs)
        or any(not 0 < r < (1 << 256) for r in rands)
    ):
        return False
    lib = _load_bls()
    if lib is None:
        return None
    cat_msgs, offs = b"".join(msgs), np.zeros(k + 1, dtype=np.int64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    r = lib.bls_verify_batch_rlc(
        _cbuf(b"".join(pubkeys)), len(pubkeys),
        _cbuf(cat_msgs), np.ascontiguousarray(offs), k,
        _cbuf(b"".join(sigs)),
        _cbuf(b"".join(ri.to_bytes(32, "big") for ri in rands)),
        _cbuf(dst), len(dst),
    )
    return bool(r)


def available() -> bool:
    return _load() is not None


def num_threads() -> int:
    lib = _load()
    return lib.native_num_threads() if lib is not None else 1


def _concat_offsets(msgs: Sequence[bytes]):
    offs = np.zeros(len(msgs) + 1, dtype=np.int64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    cat = b"".join(msgs)
    buf = np.frombuffer(cat, dtype=np.uint8) if cat else np.zeros(1, np.uint8)
    return np.ascontiguousarray(buf), offs


def challenge_batch(
    r: np.ndarray, a: np.ndarray, msgs: Sequence[bytes]
) -> np.ndarray:
    """(n, 32) R encodings, (n, 32) A encodings, n message byte strings ->
    (n, 32) uint8 little-endian challenge scalars (mod L, canonical)."""
    n = len(msgs)
    assert r.shape == (n, 32) and a.shape == (n, 32), (r.shape, a.shape)
    out = np.empty((n, 32), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        cat, offs = _concat_offsets(msgs)
        lib.challenge_batch(
            np.ascontiguousarray(r), np.ascontiguousarray(a),
            cat, offs, n, out,
        )
        return out
    from ..crypto import ed25519_cpu as ref  # fallback: per-item Python

    for i, m in enumerate(msgs):
        k = ref.challenge_scalar(r[i].tobytes(), a[i].tobytes(), m)
        out[i] = np.frombuffer(k.to_bytes(32, "little"), np.uint8)
    return out


def sc_reduce_batch(digests: np.ndarray) -> np.ndarray:
    """(n, 64) uint8 little-endian 512-bit values -> (n, 32) uint8
    canonical scalars mod L (the Ed25519 group order)."""
    n = len(digests)
    assert digests.shape == (n, 64), digests.shape
    out = np.empty((n, 32), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.sc_reduce_batch(np.ascontiguousarray(digests), n, out)
        return out
    from ..crypto import ed25519_cpu as ref  # fallback: per-item Python

    for i in range(n):
        v = int.from_bytes(digests[i].tobytes(), "little") % ref.L
        out[i] = np.frombuffer(v.to_bytes(32, "little"), np.uint8)
    return out


def sha512_batch(msgs: Sequence[bytes]) -> np.ndarray:
    """n message byte strings -> (n, 64) uint8 SHA-512 digests."""
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        cat, offs = _concat_offsets(msgs)
        lib.sha512_batch(cat, offs, n, out)
        return out
    import hashlib

    for i, m in enumerate(msgs):
        out[i] = np.frombuffer(hashlib.sha512(m).digest(), np.uint8)
    return out


# ---------------------------------------------------------------------------
# canonical-JSON encoder (CPython extension module, canonjson.cpp)
# ---------------------------------------------------------------------------

_SRC_CANON = os.path.join(os.path.dirname(__file__), "canonjson.cpp")
_SO_CANON = os.path.join(os.path.dirname(__file__), "_canonjson.so")
_canon_lock = threading.Lock()
_canon_mod = None
_canon_tried = False


def _python_includes():
    import sysconfig

    return [f"-I{sysconfig.get_path('include')}"]


def _ensure_built(src: str, so: str, extra=()) -> bool:
    """Shared freshness check + build-on-demand (used by the ctypes
    loader below and the extension loader): rebuild when the source is
    newer, tolerate a missing source by trusting the cached .so."""
    try:
        fresh = os.path.exists(so) and (
            os.path.getmtime(so) >= os.path.getmtime(src)
        )
    except OSError:  # source missing: use the existing .so as-is
        fresh = os.path.exists(so)
    return fresh or _build_so(src, so, extra=extra)


def _load_canonjson():
    """Build (on demand) and import the _canonjson extension; None on any
    failure — callers keep the pure-json path. Unlike the ctypes
    libraries this is a real CPython extension (it walks Python objects),
    so it is imported via ExtensionFileLoader, not CDLL."""
    global _canon_mod, _canon_tried
    if _canon_tried:  # lock-free fast path: _canon_mod is write-once
        return _canon_mod
    with _canon_lock:
        if _canon_tried:
            return _canon_mod
        _canon_tried = True  # every exit below is final (no per-call retry)
        if not _ensure_built(_SRC_CANON, _SO_CANON, extra=_python_includes()):
            return None
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader(
                "_canonjson", _SO_CANON
            )
            spec = importlib.util.spec_from_loader("_canonjson", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
        except (ImportError, OSError) as e:
            log.warning("canonjson load failed: %s — json fallback", e)
            return None
        # self-test: byte-exact equivalence on a representative sample; a
        # silently divergent encoder would FORK the committee (digests),
        # so any mismatch rejects the library outright
        import json as _json

        samples = [
            {"kind": "commit", "seq": 1, "view": 0, "digest": "ab" * 32,
             "sig": "", "b": [1, 2, [3]], "n": None, "t": True},
            {"z": "\x00\x1f\"\\\né€\U0001f600", "a": -(2**80)},
            {"": {"nested": ["\ud800", 2**63 - 1, -(2**63)]}},
        ]
        for s in samples:
            want = _json.dumps(s, sort_keys=True, separators=(",", ":")).encode(
                "utf-8", "surrogatepass"
            )
            if mod.encode(s) != want:
                log.warning("canonjson self-test mismatch — json fallback")
                return None
        _canon_mod = mod
        return mod


def canonjson_encode(obj):
    """Native canonical encode, or None when the library is unavailable
    or the object leaves the wire subset (caller falls back to json)."""
    mod = _load_canonjson()
    if mod is None:
        return None
    try:
        return mod.encode(obj)
    except (TypeError, RecursionError):
        return None


def canonjson_available() -> bool:
    return _load_canonjson() is not None
