"""ctypes loader for the native host-prep library (pbft_native.cpp).

The shared object is built on demand with g++ (cached next to the source,
rebuilt when the source is newer) and loaded via ctypes — no pybind11
dependency. Every entry point has a pure-Python fallback so the framework
works on machines without a toolchain; `available()` reports which path is
active and the bench records it.

API (numpy in, numpy out, zero per-item Python work):
- challenge_batch(r, a, msgs) -> (n, 32) uint8 little-endian scalars
  k_i = SHA-512(R_i || A_i || M_i) mod L   (the Ed25519 challenge)
- sha512_batch(msgs) -> (n, 64) uint8 digests
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "pbft_native.cpp")
_SO = os.path.join(os.path.dirname(__file__), "_pbft_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    # per-process temp name: concurrent builders (multi-process launch,
    # parallel test workers) must never interleave linker output in a
    # shared file; os.replace keeps the final install atomic
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        log.warning("native build failed (%s) %s — using Python fallback",
                    e, detail.decode(errors="replace")[:500])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        fresh = os.path.exists(_SO) and (
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        )
        if not fresh and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native load failed: %s — using Python fallback", e)
            return None
        lib.challenge_batch.argtypes = [
            _u8p, _u8p, _u8p, _i64p, ctypes.c_int64, _u8p,
        ]
        lib.challenge_batch.restype = None
        lib.sha512_batch.argtypes = [_u8p, _i64p, ctypes.c_int64, _u8p]
        lib.sha512_batch.restype = None
        lib.sc_reduce_batch.argtypes = [_u8p, ctypes.c_int64, _u8p]
        lib.sc_reduce_batch.restype = None
        lib.native_num_threads.argtypes = []
        lib.native_num_threads.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def num_threads() -> int:
    lib = _load()
    return lib.native_num_threads() if lib is not None else 1


def _concat_offsets(msgs: Sequence[bytes]):
    offs = np.zeros(len(msgs) + 1, dtype=np.int64)
    np.cumsum([len(m) for m in msgs], out=offs[1:])
    cat = b"".join(msgs)
    buf = np.frombuffer(cat, dtype=np.uint8) if cat else np.zeros(1, np.uint8)
    return np.ascontiguousarray(buf), offs


def challenge_batch(
    r: np.ndarray, a: np.ndarray, msgs: Sequence[bytes]
) -> np.ndarray:
    """(n, 32) R encodings, (n, 32) A encodings, n message byte strings ->
    (n, 32) uint8 little-endian challenge scalars (mod L, canonical)."""
    n = len(msgs)
    assert r.shape == (n, 32) and a.shape == (n, 32), (r.shape, a.shape)
    out = np.empty((n, 32), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        cat, offs = _concat_offsets(msgs)
        lib.challenge_batch(
            np.ascontiguousarray(r), np.ascontiguousarray(a),
            cat, offs, n, out,
        )
        return out
    from ..crypto import ed25519_cpu as ref  # fallback: per-item Python

    for i, m in enumerate(msgs):
        k = ref.challenge_scalar(r[i].tobytes(), a[i].tobytes(), m)
        out[i] = np.frombuffer(k.to_bytes(32, "little"), np.uint8)
    return out


def sc_reduce_batch(digests: np.ndarray) -> np.ndarray:
    """(n, 64) uint8 little-endian 512-bit values -> (n, 32) uint8
    canonical scalars mod L (the Ed25519 group order)."""
    n = len(digests)
    assert digests.shape == (n, 64), digests.shape
    out = np.empty((n, 32), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        lib.sc_reduce_batch(np.ascontiguousarray(digests), n, out)
        return out
    from ..crypto import ed25519_cpu as ref  # fallback: per-item Python

    for i in range(n):
        v = int.from_bytes(digests[i].tobytes(), "little") % ref.L
        out[i] = np.frombuffer(v.to_bytes(32, "little"), np.uint8)
    return out


def sha512_batch(msgs: Sequence[bytes]) -> np.ndarray:
    """n message byte strings -> (n, 64) uint8 SHA-512 digests."""
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    if n == 0:
        return out
    lib = _load()
    if lib is not None:
        cat, offs = _concat_offsets(msgs)
        lib.sha512_batch(cat, offs, n, out)
        return out
    import hashlib

    for i, m in enumerate(msgs):
        out[i] = np.frombuffer(hashlib.sha512(m).digest(), np.uint8)
    return out
