// Native canonical-JSON encoder (CPython extension).
//
// Canonical encoding (sorted keys, no whitespace, ensure_ascii) is the
// wire format AND the digest/signing preimage of every consensus message
// (simple_pbft_tpu/messages.py:canonical_json), so the committee-wide
// CPU profile is dominated by message volume x codec cost — measured
// ~20% of committee CPU in json.dumps/json.loads at n=100
// (bench_results/cpu_budget_r04.md). This module encodes the exact wire
// subset {dict[str->*], list, str, int, bool, None} byte-identically to
//
//     json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()
//
// and raises TypeError for anything outside the subset (floats, exotic
// key types), which the Python wrapper treats as "fall back to json" —
// a digest divergence between the two encoders would fork the
// committee, so equivalence is enforced by differential fuzz tests
// (tests/test_native_canonjson.py) covering control characters, astral
// planes, lone surrogates, and big ints.
//
// Key ordering uses PyList_Sort on the key list — exactly sorted()'s
// comparison — rather than a reimplementation of str ordering.
//
// The reference has no codec layer at all (its wire format is Go's
// encoding/json over HTTP, /root/reference/pbft/network/
// consensusInterface.go:47-107); this is new framework infrastructure.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string>

namespace {

constexpr int kMaxDepth = 64;  // mirrors messages.MAX_NESTING with margin

const char kHex[] = "0123456789abcdef";

void append_escaped(std::string &out, PyObject *str) {
  // str is guaranteed PyUnicode by the caller (may be unready only for
  // exotic subclasses; PyUnicode_READY is a no-op post-3.12 but cheap)
  Py_ssize_t n = PyUnicode_GET_LENGTH(str);
  int kind = PyUnicode_KIND(str);
  const void *data = PyUnicode_DATA(str);
  out.push_back('"');
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_UCS4 c = PyUnicode_READ(kind, data, i);
    switch (c) {
      case '"':
        out += "\\\"";
        continue;
      case '\\':
        out += "\\\\";
        continue;
      case '\b':
        out += "\\b";
        continue;
      case '\f':
        out += "\\f";
        continue;
      case '\n':
        out += "\\n";
        continue;
      case '\r':
        out += "\\r";
        continue;
      case '\t':
        out += "\\t";
        continue;
      default:
        break;
    }
    if (c >= 0x20 && c < 0x7f) {
      out.push_back(static_cast<char>(c));
    } else if (c <= 0xffff) {
      // includes lone surrogates, exactly as the json module emits them
      out += "\\u";
      out.push_back(kHex[(c >> 12) & 0xf]);
      out.push_back(kHex[(c >> 8) & 0xf]);
      out.push_back(kHex[(c >> 4) & 0xf]);
      out.push_back(kHex[c & 0xf]);
    } else {
      Py_UCS4 v = c - 0x10000;
      Py_UCS4 hi = 0xd800 + (v >> 10);
      Py_UCS4 lo = 0xdc00 + (v & 0x3ff);
      out += "\\u";
      out.push_back(kHex[(hi >> 12) & 0xf]);
      out.push_back(kHex[(hi >> 8) & 0xf]);
      out.push_back(kHex[(hi >> 4) & 0xf]);
      out.push_back(kHex[hi & 0xf]);
      out += "\\u";
      out.push_back(kHex[(lo >> 12) & 0xf]);
      out.push_back(kHex[(lo >> 8) & 0xf]);
      out.push_back(kHex[(lo >> 4) & 0xf]);
      out.push_back(kHex[lo & 0xf]);
    }
  }
  out.push_back('"');
}

// returns false with a Python exception set (TypeError for out-of-subset
// input -> wrapper falls back; RecursionError/MemoryError otherwise)
bool encode(std::string &out, PyObject *obj, int depth) {
  if (depth > kMaxDepth) {
    PyErr_SetString(PyExc_RecursionError, "canonical json too deep");
    return false;
  }
  if (obj == Py_None) {
    out += "null";
    return true;
  }
  if (obj == Py_True) {
    out += "true";
    return true;
  }
  if (obj == Py_False) {
    out += "false";
    return true;
  }
  if (PyUnicode_Check(obj)) {
    append_escaped(out, obj);
    return true;
  }
  if (PyLong_Check(obj)) {
    // exact-int fast path; big ints go through Python's own str()
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (!overflow && !(v == -1 && PyErr_Occurred())) {
      out += std::to_string(v);
      return true;
    }
    PyErr_Clear();
    // json.dumps formats ints via int.__repr__ REGARDLESS of subclass
    // overrides — going through PyObject_Str here would let an int
    // subclass with a custom __str__ change the encoding (a digest fork
    // and possibly invalid JSON); call the base type's repr slot
    PyObject *s = PyLong_Type.tp_repr(obj);
    if (s == nullptr) return false;
    Py_ssize_t sz = 0;
    const char *buf = PyUnicode_AsUTF8AndSize(s, &sz);
    if (buf == nullptr) {
      Py_DECREF(s);
      return false;
    }
    out.append(buf, static_cast<size_t>(sz));
    Py_DECREF(s);
    return true;
  }
  if (PyList_Check(obj)) {
    out.push_back('[');
    Py_ssize_t n = PyList_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (i) out.push_back(',');
      // borrow is safe: no Python code runs between READ and use
      if (!encode(out, PyList_GET_ITEM(obj, i), depth + 1)) return false;
    }
    out.push_back(']');
    return true;
  }
  if (PyTuple_Check(obj)) {
    // json encodes tuples as arrays; our wire never produces them but a
    // caller-side structure might
    out.push_back('[');
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (i) out.push_back(',');
      if (!encode(out, PyTuple_GET_ITEM(obj, i), depth + 1)) return false;
    }
    out.push_back(']');
    return true;
  }
  if (PyDict_Check(obj)) {
    PyObject *keys = PyDict_Keys(obj);
    if (keys == nullptr) return false;
    // exact sorted() semantics — mixed/non-str keys fail the sort or the
    // per-key check below and fall back
    if (PyList_Sort(keys) < 0) {
      Py_DECREF(keys);
      PyErr_Clear();
      PyErr_SetString(PyExc_TypeError, "unsortable dict keys");
      return false;
    }
    out.push_back('{');
    Py_ssize_t n = PyList_GET_SIZE(keys);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *k = PyList_GET_ITEM(keys, i);
      if (!PyUnicode_Check(k)) {
        Py_DECREF(keys);
        PyErr_SetString(PyExc_TypeError, "non-str dict key");
        return false;
      }
      if (i) out.push_back(',');
      append_escaped(out, k);
      out.push_back(':');
      PyObject *v = PyDict_GetItemWithError(obj, k);  // borrowed
      if (v == nullptr) {
        Py_DECREF(keys);
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_RuntimeError, "dict mutated during encode");
        return false;
      }
      if (!encode(out, v, depth + 1)) {
        Py_DECREF(keys);
        return false;
      }
    }
    Py_DECREF(keys);
    out.push_back('}');
    return true;
  }
  PyErr_Format(PyExc_TypeError, "unsupported type for canonical json: %s",
               Py_TYPE(obj)->tp_name);
  return false;
}

PyObject *py_encode(PyObject *, PyObject *obj) {
  std::string out;
  out.reserve(256);
  if (!encode(out, obj, 0)) return nullptr;
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

PyMethodDef kMethods[] = {
    {"encode", py_encode, METH_O,
     "encode(obj) -> bytes identical to json.dumps(obj, sort_keys=True, "
     "separators=(',', ':')).encode() for the wire subset; raises "
     "TypeError outside it."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_canonjson",
    "Native canonical-JSON encoder for consensus wire messages.", -1,
    kMethods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__canonjson(void) { return PyModule_Create(&kModule); }
