// BLS12-381 pairing verification — native sibling of crypto/bls.py.
//
// Same construction as the Python module (which remains the differential
// oracle and fallback): Fp -> Fp2 -> Fp6 -> Fp12 tower (u^2 = -1,
// v^3 = 1+u, w^2 = v), M-twist G2, textbook optimal-ate Miller loop over
// the untwisted Fp12 curve.  The final exponentiation is decomposed:
// easy part f^((p^6-1)(p^2+1)) via conjugation + p^2-Frobenius, hard
// part via the x-based chain on 3*(p^4-p^2+1)/r with Granger-Scott
// cyclotomic squarings (see final_exp_is_one).  Min-sig layout:
// signatures in G1 (96 B uncompressed), pubkeys in G2 (192 B),
// try-and-increment SHA-256 hash-to-G1 with cofactor clearing.
//
// Arithmetic: 6x64-bit Montgomery representation with __int128 CIOS
// multiplication — ~30x faster end-to-end than the bigint Python path
// (one aggregate-QC check drops from ~750 ms to ~25 ms on one core),
// which is what makes qc_mode failover usable on CPU-only hosts.
//
// The reference project has no signature code at all (SURVEY.md §2.1);
// this file is new framework infrastructure, written from the curve
// equations up to mirror crypto/bls.py exactly so the two paths can be
// differentially tested against each other (tests/test_bls.py).

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;

// ---------------------------------------------------------------------------
// Fp: integers mod P in Montgomery form (R = 2^384)
// ---------------------------------------------------------------------------

static const u64 P_LIMB[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const u64 R_MONT[6] = {  // 2^384 mod P == montgomery form of 1
    0x760900000002fffdULL, 0xebf4000bc40c0002ULL, 0x5f48985753c758baULL,
    0x77ce585370525745ULL, 0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const u64 R2_MONT[6] = {  // 2^768 mod P (to-Montgomery multiplier)
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};
static const u64 N0INV = 0x89f3fffcfffcfffdULL;  // -P^{-1} mod 2^64

struct Fp {
  u64 v[6];
};

static inline bool fp_eq(const Fp& a, const Fp& b) {
  for (int i = 0; i < 6; i++)
    if (a.v[i] != b.v[i]) return false;
  return true;
}

static inline bool fp_is_zero(const Fp& a) {
  for (int i = 0; i < 6; i++)
    if (a.v[i]) return false;
  return true;
}

// a >= b on raw limbs
static inline bool geq(const u64* a, const u64* b) {
  for (int i = 5; i >= 0; i--) {
    if (a[i] > b[i]) return true;
    if (a[i] < b[i]) return false;
  }
  return true;  // equal
}

static inline void sub_limbs(u64* r, const u64* a, const u64* b) {
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a[i] - b[i] - borrow;
    r[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
}

static inline void fp_add(Fp& r, const Fp& a, const Fp& b) {
  u128 carry = 0;
  u64 t[6];
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  if (carry || geq(t, P_LIMB)) sub_limbs(r.v, t, P_LIMB);
  else memcpy(r.v, t, sizeof t);
}

static inline void fp_sub(Fp& r, const Fp& a, const Fp& b) {
  u128 borrow = 0;
  u64 t[6];
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) {
    u128 carry = 0;
    for (int i = 0; i < 6; i++) {
      u128 s = (u128)t[i] + P_LIMB[i] + carry;
      t[i] = (u64)s;
      carry = s >> 64;
    }
  }
  memcpy(r.v, t, sizeof t);
}

static inline void fp_neg(Fp& r, const Fp& a) {
  if (fp_is_zero(a)) { r = a; return; }
  sub_limbs(r.v, P_LIMB, a.v);
}

// Montgomery CIOS multiply: r = a*b*R^{-1} mod P
static void fp_mul(Fp& r, const Fp& a, const Fp& b) {
  u64 t[8] = {0};
  for (int i = 0; i < 6; i++) {
    u128 carry = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[6] + carry;
    t[6] = (u64)s;
    t[7] = (u64)(s >> 64);

    u64 m = t[0] * N0INV;
    carry = 0;
    u128 s0 = (u128)t[0] + (u128)m * P_LIMB[0];
    carry = s0 >> 64;
    for (int j = 1; j < 6; j++) {
      u128 s2 = (u128)t[j] + (u128)m * P_LIMB[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    u128 s3 = (u128)t[6] + carry;
    t[5] = (u64)s3;
    t[6] = t[7] + (u64)(s3 >> 64);
    t[7] = 0;
  }
  if (t[6] || geq(t, P_LIMB)) sub_limbs(r.v, t, P_LIMB);
  else memcpy(r.v, t, 6 * sizeof(u64));
}

static inline void fp_sq(Fp& r, const Fp& a) { fp_mul(r, a, a); }

static const Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static Fp FP_ONE;  // R mod P, set in init

static void fp_from_limbs(Fp& r, const u64* raw) {
  // raw (standard form) -> Montgomery: montmul(raw, R^2)
  Fp t;
  memcpy(t.v, raw, sizeof t.v);
  Fp r2;
  memcpy(r2.v, R2_MONT, sizeof r2.v);
  fp_mul(r, t, r2);
}

static void fp_to_limbs(u64* raw, const Fp& a) {
  // Montgomery -> standard: montmul(a, 1)
  Fp one = {{1, 0, 0, 0, 0, 0}}, t;
  fp_mul(t, a, one);
  memcpy(raw, t.v, sizeof t.v);
}

// big-endian 48 bytes -> Fp (returns false if >= P)
static bool fp_from_be(Fp& r, const uint8_t* be) {
  u64 raw[6];
  for (int i = 0; i < 6; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | be[(5 - i) * 8 + j];
    raw[i] = w;
  }
  if (geq(raw, P_LIMB)) return false;  // non-canonical (geq covers == P)
  fp_from_limbs(r, raw);
  return true;
}

// pow by a standard-form limb exponent (MSB-first), base in Montgomery
static void fp_pow_limbs(Fp& r, const Fp& base, const u64* e, int nlimbs) {
  Fp acc = FP_ONE;
  bool started = false;
  for (int i = nlimbs - 1; i >= 0; i--) {
    for (int b = 63; b >= 0; b--) {
      if (started) fp_sq(acc, acc);
      if ((e[i] >> b) & 1) {
        if (started) fp_mul(acc, acc, base);
        else { acc = base; started = true; }
      }
    }
  }
  r = started ? acc : FP_ONE;
}

static const u64 P_MINUS2[6] = {
    0xb9feffffffffaaa9ULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const u64 P_PLUS1_DIV4[6] = {
    0xee7fbfffffffeaabULL, 0x07aaffffac54ffffULL, 0xd9cc34a83dac3d89ULL,
    0xd91dd2e13ce144afULL, 0x92c6e9ed90d2eb35ULL, 0x0680447a8e5ff9a6ULL};

static void fp_inv(Fp& r, const Fp& a) { fp_pow_limbs(r, a, P_MINUS2, 6); }

// standard-form compare (for the min(y, P-y) canonical choice)
static bool fp_std_less(const Fp& a, const Fp& b) {
  u64 ra[6], rb[6];
  fp_to_limbs(ra, a);
  fp_to_limbs(rb, b);
  for (int i = 5; i >= 0; i--) {
    if (ra[i] < rb[i]) return true;
    if (ra[i] > rb[i]) return false;
  }
  return false;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)
// ---------------------------------------------------------------------------

struct F2 {
  Fp a, b;  // a + b*u
};

static F2 F2_ZERO_, F2_ONE_;

static inline bool f2_eq(const F2& x, const F2& y) {
  return fp_eq(x.a, y.a) && fp_eq(x.b, y.b);
}
static inline bool f2_is_zero(const F2& x) {
  return fp_is_zero(x.a) && fp_is_zero(x.b);
}
static inline void f2_add(F2& r, const F2& x, const F2& y) {
  fp_add(r.a, x.a, y.a);
  fp_add(r.b, x.b, y.b);
}
static inline void f2_sub(F2& r, const F2& x, const F2& y) {
  fp_sub(r.a, x.a, y.a);
  fp_sub(r.b, x.b, y.b);
}
static inline void f2_neg(F2& r, const F2& x) {
  fp_neg(r.a, x.a);
  fp_neg(r.b, x.b);
}
static void f2_mul(F2& r, const F2& x, const F2& y) {
  Fp t0, t1, t2, t3;
  fp_mul(t0, x.a, y.a);
  fp_mul(t1, x.b, y.b);
  fp_mul(t2, x.a, y.b);
  fp_mul(t3, x.b, y.a);
  fp_sub(r.a, t0, t1);
  fp_add(r.b, t2, t3);
}
static void f2_sq(F2& r, const F2& x) {
  Fp s, d, t;
  fp_add(s, x.a, x.b);
  fp_sub(d, x.a, x.b);
  fp_mul(t, x.a, x.b);
  fp_mul(r.a, s, d);
  fp_add(r.b, t, t);
}
static void f2_inv(F2& r, const F2& x) {
  Fp a2, b2, d, di;
  fp_sq(a2, x.a);
  fp_sq(b2, x.b);
  fp_add(d, a2, b2);
  fp_inv(di, d);
  fp_mul(r.a, x.a, di);
  Fp nb;
  fp_neg(nb, x.b);
  fp_mul(r.b, nb, di);
}
// x * (1+u)
static inline void f2_mul_xi(F2& r, const F2& x) {
  Fp na, nb;
  fp_sub(na, x.a, x.b);
  fp_add(nb, x.a, x.b);
  r.a = na;
  r.b = nb;
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - (1+u)),  Fp12 = Fp6[w]/(w^2 - v)
// ---------------------------------------------------------------------------

struct F6 {
  F2 c0, c1, c2;
};
struct F12 {
  F6 a, b;
};

static F6 F6_ZERO_, F6_ONE_;
static F12 F12_ONE_;

static inline bool f6_eq(const F6& x, const F6& y) {
  return f2_eq(x.c0, y.c0) && f2_eq(x.c1, y.c1) && f2_eq(x.c2, y.c2);
}
static inline void f6_add(F6& r, const F6& x, const F6& y) {
  f2_add(r.c0, x.c0, y.c0);
  f2_add(r.c1, x.c1, y.c1);
  f2_add(r.c2, x.c2, y.c2);
}
static inline void f6_sub(F6& r, const F6& x, const F6& y) {
  f2_sub(r.c0, x.c0, y.c0);
  f2_sub(r.c1, x.c1, y.c1);
  f2_sub(r.c2, x.c2, y.c2);
}
static inline void f6_neg(F6& r, const F6& x) {
  f2_neg(r.c0, x.c0);
  f2_neg(r.c1, x.c1);
  f2_neg(r.c2, x.c2);
}
static void f6_mul(F6& r, const F6& x, const F6& y) {
  F2 t00, t11, t22, s, u1, u2;
  f2_mul(t00, x.c0, y.c0);
  f2_mul(t11, x.c1, y.c1);
  f2_mul(t22, x.c2, y.c2);
  // c0 = t00 + xi*(a1*b2 + a2*b1)
  f2_mul(u1, x.c1, y.c2);
  f2_mul(u2, x.c2, y.c1);
  f2_add(s, u1, u2);
  f2_mul_xi(s, s);
  F2 c0, c1, c2;
  f2_add(c0, t00, s);
  // c1 = a0*b1 + a1*b0 + xi*t22
  f2_mul(u1, x.c0, y.c1);
  f2_mul(u2, x.c1, y.c0);
  f2_add(s, u1, u2);
  F2 x22;
  f2_mul_xi(x22, t22);
  f2_add(c1, s, x22);
  // c2 = a0*b2 + a2*b0 + t11
  f2_mul(u1, x.c0, y.c2);
  f2_mul(u2, x.c2, y.c0);
  f2_add(s, u1, u2);
  f2_add(c2, s, t11);
  r.c0 = c0;
  r.c1 = c1;
  r.c2 = c2;
}
// x * v: (c0, c1, c2) -> (xi*c2, c0, c1)
static void f6_mul_v(F6& r, const F6& x) {
  F2 t;
  f2_mul_xi(t, x.c2);
  F2 c1 = x.c0, c2 = x.c1;
  r.c0 = t;
  r.c1 = c1;
  r.c2 = c2;
}
static void f6_inv(F6& r, const F6& x) {
  F2 t0, t1, t2, s, u1, u2, delta, dinv;
  f2_sq(t0, x.c0);
  f2_mul(u1, x.c1, x.c2);
  f2_mul_xi(u1, u1);
  f2_sub(t0, t0, u1);  // a0^2 - xi*a1*a2
  f2_sq(t1, x.c2);
  f2_mul_xi(t1, t1);
  f2_mul(u1, x.c0, x.c1);
  f2_sub(t1, t1, u1);  // xi*a2^2 - a0*a1
  f2_sq(t2, x.c1);
  f2_mul(u1, x.c0, x.c2);
  f2_sub(t2, t2, u1);  // a1^2 - a0*a2
  f2_mul(u1, x.c1, t2);
  f2_mul(u2, x.c2, t1);
  f2_add(s, u1, u2);
  f2_mul_xi(s, s);
  f2_mul(u1, x.c0, t0);
  f2_add(delta, u1, s);
  f2_inv(dinv, delta);
  f2_mul(r.c0, t0, dinv);
  f2_mul(r.c1, t1, dinv);
  f2_mul(r.c2, t2, dinv);
}

static inline bool f12_eq(const F12& x, const F12& y) {
  return f6_eq(x.a, y.a) && f6_eq(x.b, y.b);
}
static void f12_mul(F12& r, const F12& x, const F12& y) {
  F6 t0, t1, u1, u2, c0, c1;
  f6_mul(t0, x.a, y.a);
  f6_mul(t1, x.b, y.b);
  f6_mul_v(u1, t1);
  f6_add(c0, t0, u1);
  f6_mul(u1, x.a, y.b);
  f6_mul(u2, x.b, y.a);
  f6_add(c1, u1, u2);
  r.a = c0;
  r.b = c1;
}
static inline void f12_sq(F12& r, const F12& x) { f12_mul(r, x, x); }
static inline void f12_conj(F12& r, const F12& x) {
  r.a = x.a;
  f6_neg(r.b, x.b);
}
static void f12_inv(F12& r, const F12& x) {
  F6 t0, t1, d, di;
  f6_mul(t0, x.a, x.a);
  f6_mul(t1, x.b, x.b);
  f6_mul_v(t1, t1);
  f6_sub(d, t0, t1);
  f6_inv(di, d);
  f6_mul(r.a, x.a, di);
  F6 nb;
  f6_neg(nb, x.b);
  f6_mul(r.b, nb, di);
}
static inline void f12_add(F12& r, const F12& x, const F12& y) {
  f6_add(r.a, x.a, y.a);
  f6_add(r.b, x.b, y.b);
}
static inline void f12_sub(F12& r, const F12& x, const F12& y) {
  f6_sub(r.a, x.a, y.a);
  f6_sub(r.b, x.b, y.b);
}
static inline void f12_neg(F12& r, const F12& x) {
  f6_neg(r.a, x.a);
  f6_neg(r.b, x.b);
}

// ---------------------------------------------------------------------------
// Curve points.  G1 over Fp, G2 over Fp2, E12 over Fp12 (for the Miller
// loop, mirroring crypto/bls.py's untwisted formulation).  Affine with an
// infinity flag; Jacobian ladders for scalar multiplication.
// ---------------------------------------------------------------------------

template <class F>
struct Pt {
  F x, y;
  bool inf;
};

// field op table via overloads
static inline void el_add(Fp& r, const Fp& a, const Fp& b) { fp_add(r, a, b); }
static inline void el_sub(Fp& r, const Fp& a, const Fp& b) { fp_sub(r, a, b); }
static inline void el_neg(Fp& r, const Fp& a) { fp_neg(r, a); }
static inline void el_mul(Fp& r, const Fp& a, const Fp& b) { fp_mul(r, a, b); }
static inline void el_sq(Fp& r, const Fp& a) { fp_sq(r, a); }
static inline void el_inv(Fp& r, const Fp& a) { fp_inv(r, a); }
static inline bool el_eq(const Fp& a, const Fp& b) { return fp_eq(a, b); }
static inline bool el_is_zero(const Fp& a) { return fp_is_zero(a); }
static inline void el_one(Fp& r) { r = FP_ONE; }

static inline void el_add(F2& r, const F2& a, const F2& b) { f2_add(r, a, b); }
static inline void el_sub(F2& r, const F2& a, const F2& b) { f2_sub(r, a, b); }
static inline void el_neg(F2& r, const F2& a) { f2_neg(r, a); }
static inline void el_mul(F2& r, const F2& a, const F2& b) { f2_mul(r, a, b); }
static inline void el_sq(F2& r, const F2& a) { f2_sq(r, a); }
static inline void el_inv(F2& r, const F2& a) { f2_inv(r, a); }
static inline bool el_eq(const F2& a, const F2& b) { return f2_eq(a, b); }
static inline bool el_is_zero(const F2& a) { return f2_is_zero(a); }
static inline void el_one(F2& r) { r = F2_ONE_; }

static inline void el_add(F12& r, const F12& a, const F12& b) { f12_add(r, a, b); }
static inline void el_sub(F12& r, const F12& a, const F12& b) { f12_sub(r, a, b); }
static inline void el_neg(F12& r, const F12& a) { f12_neg(r, a); }
static inline void el_mul(F12& r, const F12& a, const F12& b) { f12_mul(r, a, b); }
static inline void el_sq(F12& r, const F12& a) { f12_sq(r, a); }
static inline void el_inv(F12& r, const F12& a) { f12_inv(r, a); }
static inline bool el_eq(const F12& a, const F12& b) { return f12_eq(a, b); }
static inline bool el_is_zero(const F12& a) {
  return f6_eq(a.a, F6_ZERO_) && f6_eq(a.b, F6_ZERO_);
}
static inline void el_one(F12& r) { r = F12_ONE_; }

template <class F>
static inline void el_muls(F& r, const F& a, int s) {
  // multiply by a small positive int via repeated addition (s <= 8 here)
  F acc = a;
  for (int i = 1; i < s; i++) el_add(acc, acc, a);
  r = acc;
}

// affine add (mirrors bls.py _Curve.add_pts)
template <class F>
static Pt<F> pt_add(const Pt<F>& p1, const Pt<F>& p2) {
  if (p1.inf) return p2;
  if (p2.inf) return p1;
  F lam;
  if (el_eq(p1.x, p2.x)) {
    if (!el_eq(p1.y, p2.y)) return {p1.x, p1.y, true};
    if (el_is_zero(p1.y)) return {p1.x, p1.y, true};
    F x2, n, d, di;
    el_sq(x2, p1.x);
    el_muls(n, x2, 3);
    el_add(d, p1.y, p1.y);
    el_inv(di, d);
    el_mul(lam, n, di);
  } else {
    F n, d, di;
    el_sub(n, p2.y, p1.y);
    el_sub(d, p2.x, p1.x);
    el_inv(di, d);
    el_mul(lam, n, di);
  }
  F x3, y3, t;
  el_sq(x3, lam);
  el_sub(x3, x3, p1.x);
  el_sub(x3, x3, p2.x);
  el_sub(t, p1.x, x3);
  el_mul(y3, lam, t);
  el_sub(y3, y3, p1.y);
  return {x3, y3, false};
}

template <class F>
static inline Pt<F> pt_neg(const Pt<F>& p) {
  if (p.inf) return p;
  F ny;
  el_neg(ny, p.y);
  return {p.x, ny, false};
}

// Jacobian double (dbl-2009-l, as in bls.py _jdbl).  R may alias Pj, so
// every output is computed into a local before the writeback.
template <class F>
static void jdbl(F* R, const F* Pj) {
  F A, B, C, D, E, Ff, t, t2, X3, Y3, Z3;
  el_sq(A, Pj[0]);
  el_sq(B, Pj[1]);
  el_sq(C, B);
  el_add(t, Pj[0], B);
  el_sq(t, t);
  el_sub(t, t, A);
  el_sub(t, t, C);
  el_muls(D, t, 2);
  el_muls(E, A, 3);
  el_sq(Ff, E);
  el_muls(t, D, 2);
  el_sub(X3, Ff, t);
  el_sub(t, D, X3);
  el_mul(t, E, t);
  el_muls(t2, C, 8);
  el_sub(Y3, t, t2);
  el_mul(t, Pj[1], Pj[2]);
  el_muls(Z3, t, 2);
  R[0] = X3;
  R[1] = Y3;
  R[2] = Z3;
}

// Jacobian mixed/general add (add-2007-bl, as in bls.py _jadd).
// Returns false if the add hit p + (-p) (infinity mid-ladder).
template <class F>
static bool jadd(F* R, const F* Pj, const F* Q) {
  F Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  el_sq(Z1Z1, Pj[2]);
  el_sq(Z2Z2, Q[2]);
  el_mul(U1, Pj[0], Z2Z2);
  el_mul(U2, Q[0], Z1Z1);
  el_mul(t, Pj[1], Q[2]);
  el_mul(S1, t, Z2Z2);
  el_mul(t, Q[1], Pj[2]);
  el_mul(S2, t, Z1Z1);
  if (el_eq(U1, U2)) {
    if (!el_eq(S1, S2)) return false;
    jdbl(R, Pj);
    return true;
  }
  F H, I, J, rr, V, t2, X3, Y3, Z3;
  el_sub(H, U2, U1);
  el_muls(t, H, 2);
  el_sq(I, t);
  el_mul(J, H, I);
  el_sub(t, S2, S1);
  el_muls(rr, t, 2);
  el_mul(V, U1, I);
  el_sq(t, rr);
  el_sub(t, t, J);
  el_muls(t2, V, 2);
  el_sub(X3, t, t2);
  el_sub(t, V, X3);
  el_mul(t, rr, t);
  el_mul(t2, S1, J);
  el_muls(t2, t2, 2);
  el_sub(Y3, t, t2);
  el_mul(t, H, Pj[2]);
  el_mul(Z3, t, Q[2]);
  el_muls(Z3, Z3, 2);
  R[0] = X3;
  R[1] = Y3;
  R[2] = Z3;
  return true;
}

// MSB-first double-and-add over big-endian bit source.  `fail` reports a
// mid-ladder infinity (the subgroup-check probe relies on it).
template <class F>
static Pt<F> pt_mul(const Pt<F>& p, const uint8_t* ebytes, int elen,
                    bool* fail) {
  *fail = false;
  if (p.inf) return p;
  F base[3];
  base[0] = p.x;
  base[1] = p.y;
  el_one(base[2]);
  F acc[3];
  bool started = false;
  for (int i = 0; i < elen; i++) {
    for (int b = 7; b >= 0; b--) {
      if (started) jdbl(acc, acc);
      if ((ebytes[i] >> b) & 1) {
        if (!started) {
          memcpy(acc, base, sizeof acc);
          started = true;
        } else if (!jadd(acc, acc, base)) {
          *fail = true;
          return {p.x, p.y, true};
        }
      }
    }
  }
  if (!started) return {p.x, p.y, true};
  if (el_is_zero(acc[2])) return {p.x, p.y, true};
  F zi, zi2, zi3, xr, yr;
  el_inv(zi, acc[2]);
  el_sq(zi2, zi);
  el_mul(zi3, zi2, zi);
  el_mul(xr, acc[0], zi2);
  el_mul(yr, acc[1], zi3);
  return {xr, yr, false};
}

// y^2 == x^3 + b
template <class F>
static bool on_curve(const Pt<F>& p, const F& b) {
  if (p.inf) return true;
  F y2, x3, t;
  el_sq(y2, p.y);
  el_sq(t, p.x);
  el_mul(x3, t, p.x);
  el_add(x3, x3, b);
  return el_eq(y2, x3);
}

// ---------------------------------------------------------------------------
// Constants (set in init): curve b's, generators, scalar byte strings
// ---------------------------------------------------------------------------

static Fp G1_B;              // 4
static F2 G2_B;              // 4*(1+u)
static Pt<F2> G2_GEN_;       // pubkey-side generator
static uint8_t R_MINUS1_BE[32];   // r-1 big-endian (subgroup probes)
static uint8_t H_EFF_BE[16];      // G1 cofactor big-endian
static int initialized = 0;

static const char* G2_GEN_HEX[4] = {
    // x0, x1, y0, y1 big-endian hex (96 chars each)
    "024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d177"
    "0bac0326a805bbefd48056c8c121bdb8",
    "13e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
    "334cf11213945d57e5ac7d055d042b7e",
    "0ce5d527727d6e118cc9cdc6da2e351aadfd9baa8cbdd3a76d429a695160d12c"
    "923ac9cc3baca289e193548608b82801",
    "0606c4a02ea734cc32acd2b02bc28b99cb3e287e85a763af267492ab572e99ab"
    "3f370d275cec1da1aaa9075ff05f79be"};

static int hexval(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

static void hex_to_bytes(uint8_t* out, const char* hex, int nbytes) {
  int n = (int)strlen(hex);
  // right-align: leading zero bytes if hex shorter than nbytes*2
  memset(out, 0, nbytes);
  int bi = nbytes - 1;
  for (int i = n - 1; i >= 0; i -= 2) {
    int lo = hexval(hex[i]);
    int hi = (i - 1 >= 0) ? hexval(hex[i - 1]) : 0;
    out[bi--] = (uint8_t)((hi << 4) | lo);
  }
}

static bool fp_from_hex(Fp& r, const char* hex) {
  uint8_t be[48];
  hex_to_bytes(be, hex, 48);
  return fp_from_be(r, be);
}

static void bls_init() {
  if (initialized) return;
  memcpy(FP_ONE.v, R_MONT, sizeof FP_ONE.v);
  F2_ZERO_ = {FP_ZERO, FP_ZERO};
  F2_ONE_ = {FP_ONE, FP_ZERO};
  F6_ZERO_ = {F2_ZERO_, F2_ZERO_, F2_ZERO_};
  F6_ONE_ = {F2_ONE_, F2_ZERO_, F2_ZERO_};
  F12_ONE_ = {F6_ONE_, F6_ZERO_};

  u64 four[6] = {4, 0, 0, 0, 0, 0};
  fp_from_limbs(G1_B, four);
  // 4*(1+u) = 4 + 4u
  G2_B.a = G1_B;
  G2_B.b = G1_B;

  fp_from_hex(G2_GEN_.x.a, G2_GEN_HEX[0]);
  fp_from_hex(G2_GEN_.x.b, G2_GEN_HEX[1]);
  fp_from_hex(G2_GEN_.y.a, G2_GEN_HEX[2]);
  fp_from_hex(G2_GEN_.y.b, G2_GEN_HEX[3]);
  G2_GEN_.inf = false;

  // r - 1 big-endian
  static const u64 RM1[4] = {0xffffffff00000000ULL, 0x53bda402fffe5bfeULL,
                             0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL};
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++)
      R_MINUS1_BE[i * 8 + j] = (uint8_t)(RM1[3 - i] >> (8 * (7 - j)));
  static const u64 HE[2] = {0x8c00aaab0000aaabULL, 0x396c8c005555e156ULL};
  for (int i = 0; i < 2; i++)
    for (int j = 0; j < 8; j++)
      H_EFF_BE[i * 8 + j] = (uint8_t)(HE[1 - i] >> (8 * (7 - j)));

  initialized = 1;
}

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4) — for hash_to_g1's try-and-increment
// ---------------------------------------------------------------------------

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t total = (uint64_t)len * 8;
  uint8_t block[64];
  size_t off = 0;
  bool final_done = false;
  bool len_done = false;
  while (!final_done) {
    size_t take = len > off ? (len - off > 64 ? 64 : len - off) : 0;
    memcpy(block, data + off, take);
    off += take;
    if (take < 64) {
      size_t pos = take;
      if (!len_done) {
        block[pos++] = 0x80;
        len_done = true;
      }
      if (pos <= 56) {
        memset(block + pos, 0, 56 - pos);
        for (int i = 0; i < 8; i++)
          block[56 + i] = (uint8_t)(total >> (8 * (7 - i)));
        final_done = true;
      } else {
        memset(block + pos, 0, 64 - pos);
      }
    }
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)block[i * 4] << 24) | ((uint32_t)block[i * 4 + 1] << 16) |
             ((uint32_t)block[i * 4 + 2] << 8) | block[i * 4 + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
      uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
      uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + mj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  for (int i = 0; i < 8; i++) {
    out[i * 4] = (uint8_t)(h[i] >> 24);
    out[i * 4 + 1] = (uint8_t)(h[i] >> 16);
    out[i * 4 + 2] = (uint8_t)(h[i] >> 8);
    out[i * 4 + 3] = (uint8_t)h[i];
  }
}

// ---------------------------------------------------------------------------
// hash to G1 (try-and-increment, mirroring bls.py exactly)
// ---------------------------------------------------------------------------

// reduce a 64-byte big-endian value mod P into Fp (Montgomery)
static void fp_from_be64_mod(Fp& r, const uint8_t* be64) {
  // split as hi*2^256 + lo; compute in Montgomery arithmetic:
  // take 48-byte chunks: v = b[0..15]*2^384 + b[16..63] (48 bytes)
  // simpler: iterate 8-byte words MSB-first, acc = acc*2^64 + word
  Fp acc = FP_ZERO;
  u64 two64_raw[6] = {0, 1, 0, 0, 0, 0};  // 2^64
  Fp two64;
  fp_from_limbs(two64, two64_raw);
  for (int i = 0; i < 8; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | be64[i * 8 + j];
    u64 wr[6] = {w, 0, 0, 0, 0, 0};
    Fp wf;
    fp_from_limbs(wf, wr);
    fp_mul(acc, acc, two64);
    fp_add(acc, acc, wf);
  }
  r = acc;
}

static Pt<Fp> hash_to_g1(const uint8_t* msg, size_t msg_len,
                         const uint8_t* dst, size_t dst_len) {
  // buffer: dst || ctr(4, BE) || msg [|| 0x01]
  size_t blen = dst_len + 4 + msg_len + 1;
  uint8_t* buf = new uint8_t[blen];
  memcpy(buf, dst, dst_len);
  memcpy(buf + dst_len + 4, msg, msg_len);
  Pt<Fp> out = {FP_ZERO, FP_ZERO, true};
  for (uint32_t ctr = 0;; ctr++) {
    buf[dst_len] = (uint8_t)(ctr >> 24);
    buf[dst_len + 1] = (uint8_t)(ctr >> 16);
    buf[dst_len + 2] = (uint8_t)(ctr >> 8);
    buf[dst_len + 3] = (uint8_t)ctr;
    uint8_t h[64];
    sha256(buf, dst_len + 4 + msg_len, h);
    buf[dst_len + 4 + msg_len] = 0x01;
    sha256(buf, dst_len + 4 + msg_len + 1, h + 32);
    Fp x, y2, y, chk;
    fp_from_be64_mod(x, h);
    Fp x2, x3;
    fp_sq(x2, x);
    fp_mul(x3, x2, x);
    fp_add(y2, x3, G1_B);
    fp_pow_limbs(y, y2, P_PLUS1_DIV4, 6);
    fp_sq(chk, y);
    if (!fp_eq(chk, y2)) continue;
    Fp ny;
    fp_neg(ny, y);
    Fp ymin = fp_std_less(y, ny) ? y : ny;  // min(y, P-y)
    Pt<Fp> pt = {x, ymin, false};
    bool fail = false;
    Pt<Fp> cleared = pt_mul(pt, H_EFF_BE, 16, &fail);
    if (!fail && !cleared.inf) {
      out = cleared;
      break;
    }
  }
  delete[] buf;
  return out;
}

// ---------------------------------------------------------------------------
// Miller loop + final exponentiation (mirrors bls.py's untwisted form)
// ---------------------------------------------------------------------------

static const u64 BLS_X_ABS = 0xD201000000010000ULL;

// Projective, inversion-free Miller loop on the twist.
//
// Every point in the loop is the untwist psi(x', y') = (x' v^2/xi,
// y' v w/xi) of a twist point, so the walker stays on E'(Fp2) in
// homogeneous projective coordinates and the line through two untwisted
// points, evaluated at the embedded P = (xp, yp), is SPARSE:
//
//   l = c0 * 1 + c1 * (v w) + c2 * (v^2 w),   c_i in Fp2
//
// with (after clearing denominators by an Fp2 scale factor, which is
// harmless: any c in Fp2* satisfies c^(p^2-1) = 1, so it dies in the
// (p^6-1)(p^2+1) easy part of the final exponentiation)
//
//   doubling  (W = 3X^2, S = Y Z):  c0 = -yp * 2 S Z xi,
//             c1 = 2 S Y - W X,     c2 = W Z xp
//   addition  (D = x2 Z - X, E = y2 Z - Y):  c0 = -yp * D Z xi,
//             c1 = D Y - E X,       c2 = E Z xp
//
// Point updates are the standard a=0 projective formulas (EFD dbl-2007-bl
// / madd-1998-cmo); no field inversion anywhere in the loop.

struct TwistPt {
  F2 X, Y, Z;
  bool inf;
};

// multiply an Fp2 element by an embedded Fp scalar
static inline void f2_mul_fp(F2& r, const F2& x, const Fp& s) {
  fp_mul(r.a, x.a, s);
  fp_mul(r.b, x.b, s);
}

// f *= (c0 + c1 (v w) + c2 (v^2 w)):  with L = (0, c1, c2) in Fp6,
//   out.a = f.a * c0 + v * (f.b * L)
//   out.b = f.b * c0 + f.a * L
// where * L exploits L's zero c0 slot (6 Fp2 muls per product).
static void f6_mul_sparse12(F6& r, const F6& x, const F2& b1, const F2& b2) {
  F2 t11, t22, s, u1, u2, c0, c1, c2;
  f2_mul(t11, x.c1, b1);
  f2_mul(t22, x.c2, b2);
  f2_mul(u1, x.c1, b2);
  f2_mul(u2, x.c2, b1);
  f2_add(s, u1, u2);
  f2_mul_xi(c0, s);
  f2_mul(u1, x.c0, b1);
  f2_mul_xi(s, t22);
  f2_add(c1, u1, s);
  f2_mul(u2, x.c0, b2);
  f2_add(c2, u2, t11);
  r.c0 = c0;
  r.c1 = c1;
  r.c2 = c2;
}

static void f6_scale(F6& r, const F6& x, const F2& s) {
  f2_mul(r.c0, x.c0, s);
  f2_mul(r.c1, x.c1, s);
  f2_mul(r.c2, x.c2, s);
}

static void mul_by_line(F12& f, const F2& c0, const F2& c1, const F2& c2) {
  F6 aL, bL, ac, bc;
  f6_mul_sparse12(bL, f.b, c1, c2);
  f6_mul_v(bL, bL);
  f6_mul_sparse12(aL, f.a, c1, c2);
  f6_scale(ac, f.a, c0);
  f6_scale(bc, f.b, c0);
  f6_add(f.a, ac, bL);
  f6_add(f.b, bc, aL);
}

// doubling step: T <- 2T, line coefficients out
static void dbl_step(TwistPt& T, F2& c0, F2& c1, F2& c2, const Fp& xp,
                     const Fp& nyp) {
  F2 W, S, B, H, t, Y2, S2;
  f2_sq(t, T.X);
  f2_add(W, t, t);
  f2_add(W, W, t);          // W = 3 X^2
  f2_mul(S, T.Y, T.Z);      // S = Y Z
  f2_mul(t, T.X, T.Y);
  f2_mul(B, t, S);          // B = X Y S
  f2_sq(t, W);
  F2 eightB;
  f2_add(eightB, B, B);
  f2_add(eightB, eightB, eightB);
  f2_add(eightB, eightB, eightB);
  f2_sub(H, t, eightB);     // H = W^2 - 8B
  // line first (it reads X, Y, Z before the update)
  F2 twoS;
  f2_add(twoS, S, S);
  f2_mul(t, twoS, T.Z);
  f2_mul_xi(t, t);
  f2_mul_fp(c0, t, nyp);    // c0 = -yp * 2 S Z xi
  f2_mul(t, twoS, T.Y);
  F2 WX;
  f2_mul(WX, W, T.X);
  f2_sub(c1, t, WX);        // c1 = 2 S Y - W X
  f2_mul(t, W, T.Z);
  f2_mul_fp(c2, t, xp);     // c2 = W Z xp
  // point update
  F2 X3, Y3, Z3, fourB;
  f2_mul(t, H, S);
  f2_add(X3, t, t);         // X3 = 2 H S
  f2_add(fourB, B, B);
  f2_add(fourB, fourB, fourB);
  f2_sub(t, fourB, H);
  f2_mul(t, W, t);
  f2_sq(Y2, T.Y);
  F2 SS;
  f2_sq(SS, S);
  f2_mul(S2, Y2, SS);       // Y^2 S^2
  F2 eightY2S2;
  f2_add(eightY2S2, S2, S2);
  f2_add(eightY2S2, eightY2S2, eightY2S2);
  f2_add(eightY2S2, eightY2S2, eightY2S2);
  f2_sub(Y3, t, eightY2S2); // Y3 = W(4B - H) - 8 Y^2 S^2
  f2_mul(Z3, SS, S);
  f2_add(Z3, Z3, Z3);
  f2_add(Z3, Z3, Z3);
  f2_add(Z3, Z3, Z3);       // Z3 = 8 S^3
  T.X = X3;
  T.Y = Y3;
  T.Z = Z3;
  T.inf = f2_is_zero(Z3);
}

// mixed addition step: T <- T + Q (Q affine on the twist), line out.
// Returns false for the degenerate T == +/-Q cases (caller handles).
static bool add_step(TwistPt& T, const Pt<F2>& Q, F2& c0, F2& c1, F2& c2,
                     const Fp& xp, const Fp& nyp) {
  F2 D, E, t;
  f2_mul(t, Q.x, T.Z);
  f2_sub(D, t, T.X);        // D = x2 Z - X
  f2_mul(t, Q.y, T.Z);
  f2_sub(E, t, T.Y);        // E = y2 Z - Y
  if (f2_is_zero(D)) return false;
  // line
  F2 DZ;
  f2_mul(DZ, D, T.Z);
  f2_mul_xi(t, DZ);
  f2_mul_fp(c0, t, nyp);    // c0 = -yp * D Z xi
  F2 DY, EX;
  f2_mul(DY, D, T.Y);
  f2_mul(EX, E, T.X);
  f2_sub(c1, DY, EX);       // c1 = D Y - E X
  f2_mul(t, E, T.Z);
  f2_mul_fp(c2, t, xp);     // c2 = E Z xp
  // point update (madd-1998-cmo): A = E^2 Z - D^3 - 2 D^2 X
  F2 D2, D3, E2, A, D2X;
  f2_sq(D2, D);
  f2_mul(D3, D2, D);
  f2_sq(E2, E);
  f2_mul(t, E2, T.Z);
  f2_mul(D2X, D2, T.X);
  f2_sub(A, t, D3);
  f2_sub(A, A, D2X);
  f2_sub(A, A, D2X);
  F2 X3, Y3, Z3;
  f2_mul(X3, D, A);
  f2_sub(t, D2X, A);
  f2_mul(t, E, t);
  F2 D3Y;
  f2_mul(D3Y, D3, T.Y);
  f2_sub(Y3, t, D3Y);
  f2_mul(Z3, D3, T.Z);
  T.X = X3;
  T.Y = Y3;
  T.Z = Z3;
  T.inf = f2_is_zero(Z3);
  return true;
}

static void miller(F12& f, const Pt<Fp>& p1, const Pt<F2>& q2) {
  f = F12_ONE_;
  if (p1.inf || q2.inf) return;
  Fp nyp;
  fp_neg(nyp, p1.y);
  TwistPt T = {q2.x, q2.y, F2_ONE_, false};
  F2 c0, c1, c2;
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  for (int b = top - 1; b >= 0; b--) {
    f12_sq(f, f);
    if (!T.inf) {
      dbl_step(T, c0, c1, c2, p1.x, nyp);
      mul_by_line(f, c0, c1, c2);
    }
    if ((BLS_X_ABS >> b) & 1) {
      if (T.inf) {
        T = {q2.x, q2.y, F2_ONE_, false};  // inf + Q
      } else if (add_step(T, q2, c0, c1, c2, p1.x, nyp)) {
        mul_by_line(f, c0, c1, c2);
      } else {
        // x-coords match: T == +/-Q.  Only reachable via hostile
        // non-subgroup inputs; handle both soundly.
        F2 E, t;
        f2_mul(t, q2.y, T.Z);
        f2_sub(E, t, T.Y);
        if (f2_is_zero(E)) {
          // T == Q: the addition is a doubling
          dbl_step(T, c0, c1, c2, p1.x, nyp);
          mul_by_line(f, c0, c1, c2);
        } else {
          // T == -Q: vertical line l = xp - x_T (scaled by Z xi),
          // sparse in the w^0 part: xp Z xi - X v^2; sum is infinity
          F12 l;
          F2 nx;
          l.b = F6_ZERO_;
          f2_mul_xi(t, T.Z);
          f2_mul_fp(l.a.c0, t, p1.x);
          l.a.c1 = F2_ZERO_;
          f2_neg(nx, T.X);
          l.a.c2 = nx;
          f12_mul(f, f, l);
          T.inf = true;
        }
      }
    }
  }
  // BLS parameter is negative: conjugate
  F12 c;
  f12_conj(c, f);
  f = c;
}

// -- cyclotomic final exponentiation ---------------------------------------
//
// f^((p^12-1)/r) decomposed as (p^6-1)(p^2+1) * (p^4-p^2+1)/r:
//   g = f^(p^6-1) = conj(f) * f^-1      (p^6-Frobenius is conjugation)
//   h = g^(p^2) * g                      (p^2-Frobenius via gamma constants)
//   out = h^E3, E3 = (p^4-p^2+1)/r       (binary pow, Granger-Scott
//                                         cyclotomic squarings: h is in
//                                         the cyclotomic subgroup, where
//                                         squaring is ~3x cheaper)

static void f2_pow_be(F2& r, const F2& base, const uint8_t* e, int elen) {
  F2 acc = F2_ONE_;
  bool started = false;
  for (int i = 0; i < elen; i++) {
    for (int b = 7; b >= 0; b--) {
      if (started) f2_sq(acc, acc);
      if ((e[i] >> b) & 1) {
        if (started) f2_mul(acc, acc, base);
        else { acc = base; started = true; }
      }
    }
  }
  r = started ? acc : F2_ONE_;
}

// gamma_k^m for m = 0..5, gamma_k = xi^((p^k-1)/6): the p^k-Frobenius
// multiplier of basis monomial v^j w^i with m = i + 2j
static F2 GAMMA_P1[6];
static F2 GAMMA_P2[6];

// (p^2-1)/6, big-endian hex (759 bits)
static const char* K_P2_HEX =
    "70b3f0c975e54be1f8697c705d30fc507a18262d12b673667b9a6188c5174d62"
    "c65cd4d924f7127e32e188819427d584e6baef6baeba1486dd1646bd6d9ab6e6"
    "7542fcdfbd9e8b2e5cb340905834d4ea2791da3e5eb271dbc7000004bd97b4";

// (p-1)/6, big-endian hex (379 bits)
static const char* K_P1_HEX =
    "45582fc5eeaa66f0c849bf3b5e1f223e613e1eb7deb831fe688231ad3c829060"
    "51caaaa72e3555549aa7ffffffff1c7";

static inline void f2_conj(F2& r, const F2& x) {
  r.a = x.a;
  fp_neg(r.b, x.b);
}

// p-Frobenius: conjugate every Fp2 coefficient (u^p = -u since
// p == 3 mod 4), then scale slot v^j w^i by gamma1^(i+2j)
static void frob_p1(F12& r, const F12& x) {
  F2 t;
  f2_conj(r.a.c0, x.a.c0);               // m = 0
  f2_conj(t, x.a.c1);
  f2_mul(r.a.c1, t, GAMMA_P1[2]);        // v
  f2_conj(t, x.a.c2);
  f2_mul(r.a.c2, t, GAMMA_P1[4]);        // v^2
  f2_conj(t, x.b.c0);
  f2_mul(r.b.c0, t, GAMMA_P1[1]);        // w
  f2_conj(t, x.b.c1);
  f2_mul(r.b.c1, t, GAMMA_P1[3]);        // w v
  f2_conj(t, x.b.c2);
  f2_mul(r.b.c2, t, GAMMA_P1[5]);        // w v^2
}

// p^2-Frobenius: coefficients are fixed by Frob (it is the identity on
// Fp2 here since p^2 == 1 mod 8 makes u^(p^2) = u); each basis slot
// v^j w^i picks up gamma2^(i+2j)
static void frob_p2(F12& r, const F12& x) {
  r.a.c0 = x.a.c0;                       // m = 0
  f2_mul(r.a.c1, x.a.c1, GAMMA_P2[2]);   // v
  f2_mul(r.a.c2, x.a.c2, GAMMA_P2[4]);   // v^2
  f2_mul(r.b.c0, x.b.c0, GAMMA_P2[1]);   // w
  f2_mul(r.b.c1, x.b.c1, GAMMA_P2[3]);   // w v
  f2_mul(r.b.c2, x.b.c2, GAMMA_P2[5]);   // w v^2
}

// Fp4 square: (a + b*t)^2 with t^2 = xi -> (a^2 + xi*b^2, 2ab)
static inline void fp4_sq(F2& c, F2& d, const F2& a, const F2& b) {
  F2 a2, b2, t;
  f2_sq(a2, a);
  f2_sq(b2, b);
  f2_mul_xi(t, b2);
  f2_add(c, a2, t);
  f2_mul(t, a, b);
  f2_add(d, t, t);
}

// Granger-Scott cyclotomic square (same Fp2[v]/(v^3-xi), Fp6[w]/(w^2-v)
// tower as the published formulas; valid only for elements of the
// cyclotomic subgroup — which final_exp_is_one guarantees)
static void cyc_sq(F12& r, const F12& x) {
  const F2 &z0 = x.a.c0, &z4 = x.a.c1, &z3 = x.a.c2;
  const F2 &z2 = x.b.c0, &z1 = x.b.c1, &z5 = x.b.c2;
  F2 t0, t1, t2, t3, s;

  F2 n0, n1, n2, n3, n4, n5;
  fp4_sq(t0, t1, z0, z1);
  // n0 = 3t0 - 2z0 ; n1 = 3t1 + 2z1
  f2_sub(s, t0, z0);
  f2_add(s, s, s);
  f2_add(n0, s, t0);
  f2_add(s, t1, z1);
  f2_add(s, s, s);
  f2_add(n1, s, t1);

  fp4_sq(t0, t1, z2, z3);
  fp4_sq(t2, t3, z4, z5);
  // n4 = 3t0 - 2z4 ; n5 = 3t1 + 2z5
  f2_sub(s, t0, z4);
  f2_add(s, s, s);
  f2_add(n4, s, t0);
  f2_add(s, t1, z5);
  f2_add(s, s, s);
  f2_add(n5, s, t1);
  // n2 = 3*xi*t3 + 2z2 ; n3 = 3t2 - 2z3
  F2 xt3;
  f2_mul_xi(xt3, t3);
  f2_add(s, xt3, z2);
  f2_add(s, s, s);
  f2_add(n2, s, xt3);
  f2_sub(s, t2, z3);
  f2_add(s, s, s);
  f2_add(n3, s, t2);

  r.a.c0 = n0;
  r.a.c1 = n4;
  r.a.c2 = n3;
  r.b.c0 = n2;
  r.b.c1 = n1;
  r.b.c2 = n5;
}

// f^|x| for the BLS parameter magnitude (64 bits, Hamming weight 6) —
// cyclotomic squarings, valid only inside the cyclotomic subgroup
static void cyc_pow_absx(F12& r, const F12& base) {
  F12 acc = base;  // leading bit
  for (int b = 62; b >= 0; b--) {
    cyc_sq(acc, acc);
    if ((BLS_X_ABS >> b) & 1) f12_mul(acc, acc, base);
  }
  r = acc;
}

// h^(x-1) for the NEGATIVE parameter x = -|x|: h^(-(|x|+1)) =
// conj(h^|x| * h)  (conjugation is inversion in the cyclotomic subgroup)
static void cyc_pow_xm1(F12& r, const F12& h) {
  F12 hx;
  cyc_pow_absx(hx, h);
  f12_mul(hx, hx, h);
  f12_conj(r, hx);
}

static int fe_initialized = 0;

static void final_exp_init() {
  // runs once, under the loader's lock via bls_selftest, before any
  // concurrent verify can reach here
  if (fe_initialized) return;
  uint8_t kbytes[95];
  F2 xi = {FP_ONE, FP_ONE};
  F2 gamma;
  int klen = ((int)strlen(K_P2_HEX) + 1) / 2;
  hex_to_bytes(kbytes, K_P2_HEX, klen);
  f2_pow_be(gamma, xi, kbytes, klen);
  GAMMA_P2[0] = F2_ONE_;
  for (int m = 1; m < 6; m++) f2_mul(GAMMA_P2[m], GAMMA_P2[m - 1], gamma);
  klen = ((int)strlen(K_P1_HEX) + 1) / 2;
  hex_to_bytes(kbytes, K_P1_HEX, klen);
  f2_pow_be(gamma, xi, kbytes, klen);
  GAMMA_P1[0] = F2_ONE_;
  for (int m = 1; m < 6; m++) f2_mul(GAMMA_P1[m], GAMMA_P1[m - 1], gamma);
  fe_initialized = 1;
}

// Test f^((p^12-1)/r) == 1.  Easy part g = f^((p^6-1)(p^2+1)) lands in
// the cyclotomic subgroup; for the hard part we use the x-based chain on
// the exponent multiple 3*(p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3
// (verified exactly; the factor 3 is coprime to r, and the tested value
// lies in mu_r, so "raised to 3e equals one" iff "raised to e equals
// one").  Cost: ~4 pow-by-|x| = ~256 cyclotomic squarings + ~30 muls,
// vs ~1300 squarings for a generic binary pow of the 1268-bit exponent.
static bool final_exp_is_one(const F12& f) {
  final_exp_init();
  F12 fi, c, g, gp, h;
  f12_inv(fi, f);
  f12_conj(c, f);
  f12_mul(g, c, fi);   // f^(p^6-1): unitary
  frob_p2(gp, g);
  f12_mul(h, gp, g);   // ^(p^2+1): cyclotomic
  // m2 = h^((x-1)^2)
  F12 m1, m2, m3, m4, t;
  cyc_pow_xm1(m1, h);
  cyc_pow_xm1(m2, m1);
  // m3 = m2^(x+p) = conj(m2^|x|) * frob_p1(m2)
  cyc_pow_absx(t, m2);
  f12_conj(t, t);
  frob_p1(m3, m2);
  f12_mul(m3, m3, t);
  // m4 = m3^(x^2+p^2-1) = m3^(|x|^2) * frob_p2(m3) * conj(m3)
  cyc_pow_absx(t, m3);
  cyc_pow_absx(t, t);
  frob_p2(m4, m3);
  f12_mul(m4, m4, t);
  f12_conj(t, m3);
  f12_mul(m4, m4, t);
  // out = m4 * h^3  must be ONE
  cyc_sq(t, h);
  f12_mul(t, t, h);
  f12_mul(m4, m4, t);
  return f12_eq(m4, F12_ONE_);
}

// e(a1, a2) == e(b1, b2) via e(a1, a2) * e(-b1, b2) == 1
static bool pairings_equal(const Pt<Fp>& a1, const Pt<F2>& a2,
                           const Pt<Fp>& b1, const Pt<F2>& b2) {
  if (a1.inf || a2.inf) return b1.inf || b2.inf;
  if (b1.inf || b2.inf) return false;
  F12 fa, fb, prod;
  miller(fa, a1, a2);
  miller(fb, pt_neg(b1), b2);
  f12_mul(prod, fa, fb);
  return final_exp_is_one(prod);
}

// ---------------------------------------------------------------------------
// (De)serialization + subgroup checks (mirroring bls.py)
// ---------------------------------------------------------------------------

static bool g1_from_bytes(Pt<Fp>& r, const uint8_t* raw) {
  bool all_zero = true;
  for (int i = 0; i < 96; i++)
    if (raw[i]) { all_zero = false; break; }
  if (all_zero) return false;  // infinity encoding rejected
  if (!fp_from_be(r.x, raw) || !fp_from_be(r.y, raw + 48)) return false;
  r.inf = false;
  return on_curve(r, G1_B);
}

static bool g2_from_bytes(Pt<F2>& r, const uint8_t* raw) {
  bool all_zero = true;
  for (int i = 0; i < 192; i++)
    if (raw[i]) { all_zero = false; break; }
  if (all_zero) return false;
  if (!fp_from_be(r.x.a, raw) || !fp_from_be(r.x.b, raw + 48) ||
      !fp_from_be(r.y.a, raw + 96) || !fp_from_be(r.y.b, raw + 144))
    return false;
  r.inf = false;
  return on_curve(r, G2_B);
}

template <class F>
static bool subgroup_check(const Pt<F>& p) {
  // p * (r-1) == -p, with a mid-ladder infinity meaning NOT in subgroup
  bool fail = false;
  Pt<F> m = pt_mul(p, R_MINUS1_BE, 32, &fail);
  if (fail) return false;
  Pt<F> np = pt_neg(p);
  if (m.inf || np.inf) return m.inf == np.inf;
  return el_eq(m.x, np.x) && el_eq(m.y, np.y);
}

// ---------------------------------------------------------------------------
// Exported API (ctypes)
// ---------------------------------------------------------------------------

extern "C" {

// 1 = valid, 0 = invalid
int bls_verify_one(const uint8_t* pk192, const uint8_t* msg, int64_t msg_len,
                   const uint8_t* sig96, const uint8_t* dst, int64_t dst_len,
                   int check_pk_subgroup) {
  bls_init();
  Pt<F2> pk;
  Pt<Fp> s;
  if (!g2_from_bytes(pk, pk192)) return 0;
  if (!g1_from_bytes(s, sig96)) return 0;
  if (!subgroup_check(s)) return 0;
  if (check_pk_subgroup && !subgroup_check(pk)) return 0;
  Pt<Fp> h = hash_to_g1(msg, (size_t)msg_len, dst, (size_t)dst_len);
  return pairings_equal(s, G2_GEN_, h, pk) ? 1 : 0;
}

// pks: n concatenated 192-byte pubkeys.  1 = valid, 0 = invalid.
int bls_verify_aggregate(const uint8_t* pks, int64_t n, const uint8_t* msg,
                         int64_t msg_len, const uint8_t* sig96,
                         const uint8_t* dst, int64_t dst_len) {
  bls_init();
  if (n <= 0) return 0;
  Pt<Fp> s;
  if (!g1_from_bytes(s, sig96)) return 0;
  if (!subgroup_check(s)) return 0;
  Pt<F2> agg = {F2_ZERO_, F2_ZERO_, true};
  for (int64_t i = 0; i < n; i++) {
    Pt<F2> pk;
    if (!g2_from_bytes(pk, pks + i * 192)) return 0;
    agg = pt_add(agg, pk);
  }
  Pt<Fp> h = hash_to_g1(msg, (size_t)msg_len, dst, (size_t)dst_len);
  return pairings_equal(s, G2_GEN_, h, agg) ? 1 : 0;
}

// QC-plane fast path: random-linear-combination batch verify of k
// aggregate signatures sharing ONE signer set.  pks: npk concatenated
// 192-byte pubkeys (the shared signer set).  msgs/offs: k concatenated
// payloads with k+1 offsets.  sigs96: k x 96-byte aggregate signatures.
// rands32: k x 32-byte big-endian RLC coefficients (secret, nonzero —
// soundness is 2^-bits per check).  Verifies
//     e(sum r_i*sig_i, G2) == e(sum r_i*H(m_i), agg_pk)
// with TWO Miller loops total instead of 2k.  1 = batch holds, 0 = batch
// fails (caller bisects; structural rejects also report 0, so a bad
// input degrades to the per-cert path, never to a false accept).
int bls_verify_batch_rlc(const uint8_t* pks, int64_t npk,
                         const uint8_t* msgs, const int64_t* offs, int64_t k,
                         const uint8_t* sigs96, const uint8_t* rands32,
                         const uint8_t* dst, int64_t dst_len) {
  bls_init();
  if (npk <= 0 || k <= 0) return 0;
  Pt<F2> agg = {F2_ZERO_, F2_ZERO_, true};
  for (int64_t i = 0; i < npk; i++) {
    Pt<F2> pk;
    if (!g2_from_bytes(pk, pks + i * 192)) return 0;
    agg = pt_add(agg, pk);
  }
  Pt<Fp> s_acc = {FP_ZERO, FP_ZERO, true};
  Pt<Fp> m_acc = {FP_ZERO, FP_ZERO, true};
  for (int64_t i = 0; i < k; i++) {
    Pt<Fp> s;
    if (!g1_from_bytes(s, sigs96 + i * 96)) return 0;
    if (!subgroup_check(s)) return 0;
    bool fail = false;
    Pt<Fp> rs = pt_mul(s, rands32 + i * 32, 32, &fail);
    if (fail) return 0;
    s_acc = pt_add(s_acc, rs);
    Pt<Fp> h = hash_to_g1(msgs + offs[i], (size_t)(offs[i + 1] - offs[i]),
                          dst, (size_t)dst_len);
    Pt<Fp> rh = pt_mul(h, rands32 + i * 32, 32, &fail);
    if (fail) return 0;
    m_acc = pt_add(m_acc, rh);
  }
  if (s_acc.inf || m_acc.inf || agg.inf) return 0;  // degenerate: go per-cert
  return pairings_equal(s_acc, G2_GEN_, m_acc, agg) ? 1 : 0;
}

// -- debug hooks (differential testing vs crypto/bls.py) -------------------

static void fp_to_be(uint8_t* be, const Fp& a) {
  u64 raw[6];
  fp_to_limbs(raw, a);
  for (int i = 0; i < 6; i++)
    for (int j = 0; j < 8; j++)
      be[(5 - i) * 8 + j] = (uint8_t)(raw[i] >> (8 * (7 - j)));
}

int dbg_fp_mul(const uint8_t* a, const uint8_t* b, uint8_t* out) {
  bls_init();
  Fp fa, fb, r;
  if (!fp_from_be(fa, a) || !fp_from_be(fb, b)) return 0;
  fp_mul(r, fa, fb);
  fp_to_be(out, r);
  return 1;
}

int dbg_fp_inv(const uint8_t* a, uint8_t* out) {
  bls_init();
  Fp fa, r;
  if (!fp_from_be(fa, a)) return 0;
  fp_inv(r, fa);
  fp_to_be(out, r);
  return 1;
}

int dbg_hash_g1(const uint8_t* msg, int64_t msg_len, const uint8_t* dst,
                int64_t dst_len, uint8_t* out96) {
  bls_init();
  Pt<Fp> h = hash_to_g1(msg, (size_t)msg_len, dst, (size_t)dst_len);
  if (h.inf) return 0;
  fp_to_be(out96, h.x);
  fp_to_be(out96 + 48, h.y);
  return 1;
}

int dbg_g1_mul(const uint8_t* pt96, const uint8_t* scalar_be, int64_t slen,
               uint8_t* out96) {
  bls_init();
  Pt<Fp> p;
  if (!g1_from_bytes(p, pt96)) return 0;
  bool fail = false;
  Pt<Fp> r = pt_mul(p, scalar_be, (int)slen, &fail);
  if (fail || r.inf) return 0;
  fp_to_be(out96, r.x);
  fp_to_be(out96 + 48, r.y);
  return 1;
}

int dbg_checks(const uint8_t* pk192) {
  bls_init();
  Pt<F2> pk;
  int r = 0;
  if (g2_from_bytes(pk, pk192)) r |= 1;
  else return 0;
  if (subgroup_check(pk)) r |= 2;
  if (subgroup_check(G2_GEN_)) r |= 4;
  if (on_curve(G2_GEN_, G2_B)) r |= 8;
  return r;
}

int dbg_miller_one(const uint8_t* p96, const uint8_t* q192) {
  // returns 1 if final_exp(miller(p,q) * miller(-p,q)) == 1 (must hold)
  bls_init();
  Pt<Fp> p;
  Pt<F2> q;
  if (!g1_from_bytes(p, p96) || !g2_from_bytes(q, q192)) return -1;
  return pairings_equal(p, q, p, q) ? 1 : 0;
}

// sk (32-byte big-endian scalar, already reduced mod r) signs msg under
// dst: sig = hash_to_g1(msg, dst)^sk, 96-byte uncompressed out.
// 1 = ok, 0 = degenerate (zero scalar / infinity result).
int bls_sign(const uint8_t* sk_be, const uint8_t* msg, int64_t msg_len,
             const uint8_t* dst, int64_t dst_len, uint8_t* out96) {
  bls_init();
  Pt<Fp> h = hash_to_g1(msg, (size_t)msg_len, dst, (size_t)dst_len);
  bool fail = false;
  Pt<Fp> s = pt_mul(h, sk_be, 32, &fail);
  if (fail || s.inf) return 0;
  fp_to_be(out96, s.x);
  fp_to_be(out96 + 48, s.y);
  return 1;
}

// pubkey = G2_gen^sk, 192-byte uncompressed out. 1 = ok, 0 = degenerate.
int bls_pubkey(const uint8_t* sk_be, uint8_t* out192) {
  bls_init();
  bool fail = false;
  Pt<F2> pk = pt_mul(G2_GEN_, sk_be, 32, &fail);
  if (fail || pk.inf) return 0;
  fp_to_be(out192, pk.x.a);
  fp_to_be(out192 + 48, pk.x.b);
  fp_to_be(out192 + 96, pk.y.a);
  fp_to_be(out192 + 144, pk.y.b);
  return 1;
}

// self-test hook: e(G1gen, G2gen)^r == 1 and bilinearity smoke
int bls_selftest(void) {
  bls_init();
  // hash two messages, verify e(H, G2)*e(-H, G2) == 1
  const uint8_t m1[] = "native selftest";
  const uint8_t d1[] = "DSTSELFTEST";
  Pt<Fp> h = hash_to_g1(m1, sizeof m1 - 1, d1, sizeof d1 - 1);
  if (h.inf) return 0;
  if (!on_curve(h, G1_B)) return 0;
  if (!subgroup_check(h)) return 0;
  if (!subgroup_check(G2_GEN_)) return 0;
  // the Granger-Scott square must agree with the generic square on a
  // real cyclotomic-subgroup element (guards the slot mapping: a wrong
  // permutation fails HERE and the loader falls back to Python)
  final_exp_init();
  F12 f, fi, cj, g, gp, cy, sq;
  miller(f, h, G2_GEN_);
  f12_inv(fi, f);
  f12_conj(cj, f);
  f12_mul(g, cj, fi);
  frob_p2(gp, g);
  f12_mul(g, gp, g);
  cyc_sq(cy, g);
  f12_sq(sq, g);
  if (!f12_eq(cy, sq)) return 0;
  return pairings_equal(h, G2_GEN_, h, G2_GEN_) ? 1 : 0;
}
}
