// Native host-prep kernels for the TPU verifier's batch pipeline.
//
// The consensus plane drains thousands of pending votes per sweep; before
// the device can verify them, each item needs its challenge scalar
// k = SHA-512(R || A || M) mod L. In Python that is ~3 us/item of
// GIL-bound work (hashlib releases the GIL only for large buffers), which
// caps end-to-end throughput far below the device's verify rate
// (BASELINE.md: >= 1M verifies/s = 1 us/item total). This library computes
// the whole challenge batch in C++ with OpenMP — one call per batch, no
// Python loop, all cores.
//
// Contents:
//   - SHA-512 (FIPS 180-4; constants generated from integer cube/square
//     roots of the first 80 primes, validated against hashlib in
//     tests/test_native.py)
//   - sc_reduce: 512-bit little-endian digest -> canonical scalar mod
//     L = 2^252 + 27742317777372353535851937790883648493 (signed fold at
//     the 2^252 boundary: n = hi*2^252 + lo == lo - hi*C (mod L), C 125
//     bits, so magnitudes shrink ~127 bits per fold)
//   - challenge_batch / sha512_batch: OpenMP-parallel batch drivers over
//     flat numpy buffers (no per-item allocation).
//
// The reference implements none of this (it has no signatures at all —
// /root/reference/utils/utils.go:13-17 is its entire crypto surface); this
// is new TPU-framework infrastructure, not a port.

#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// SHA-512
// ---------------------------------------------------------------------------

constexpr uint64_t kInitH[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

constexpr uint64_t kK[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

inline uint64_t rotr(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

inline uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return v;
}

inline void store_be64(uint8_t* p, uint64_t v) {
  for (int i = 7; i >= 0; --i) {
    p[i] = static_cast<uint8_t>(v & 0xff);
    v >>= 8;
  }
}

struct Sha512Ctx {
  uint64_t h[8];
  uint8_t buf[128];
  uint64_t total;  // bytes fed so far (messages here are << 2^61)
  unsigned fill;
};

void sha512_compress(uint64_t h[8], const uint8_t* block) {
  uint64_t w[80];
  for (int i = 0; i < 16; ++i) w[i] = load_be64(block + 8 * i);
  for (int i = 16; i < 80; ++i) {
    uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 80; ++i) {
    uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + kK[i] + w[i];
    uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  h[0] += a;
  h[1] += b;
  h[2] += c;
  h[3] += d;
  h[4] += e;
  h[5] += f;
  h[6] += g;
  h[7] += hh;
}

void sha512_init(Sha512Ctx* c) {
  std::memcpy(c->h, kInitH, sizeof(kInitH));
  c->total = 0;
  c->fill = 0;
}

void sha512_update(Sha512Ctx* c, const uint8_t* data, uint64_t len) {
  c->total += len;
  if (c->fill) {
    unsigned take = 128 - c->fill;
    if (take > len) take = static_cast<unsigned>(len);
    std::memcpy(c->buf + c->fill, data, take);
    c->fill += take;
    data += take;
    len -= take;
    if (c->fill == 128) {
      sha512_compress(c->h, c->buf);
      c->fill = 0;
    }
  }
  while (len >= 128) {
    sha512_compress(c->h, data);
    data += 128;
    len -= 128;
  }
  if (len) {
    std::memcpy(c->buf, data, len);
    c->fill = static_cast<unsigned>(len);
  }
}

void sha512_final(Sha512Ctx* c, uint8_t out[64]) {
  uint64_t bits = c->total * 8;
  uint8_t pad = 0x80;
  sha512_update(c, &pad, 1);
  uint8_t zero = 0;
  while (c->fill != 112) sha512_update(c, &zero, 1);
  uint8_t lenbuf[16] = {0};
  store_be64(lenbuf + 8, bits);  // bits was captured before padding
  sha512_update(c, lenbuf, 16);
  for (int i = 0; i < 8; ++i) store_be64(out + 8 * i, c->h[i]);
}

// ---------------------------------------------------------------------------
// Scalar reduction mod L (Ed25519 group order)
// ---------------------------------------------------------------------------

// L = 2^252 + C, C = 0x14def9dea2f79cd6'5812631a5cf5d3ed (125 bits)
constexpr uint64_t kC0 = 0x5812631a5cf5d3edULL;
constexpr uint64_t kC1 = 0x14def9dea2f79cd6ULL;
constexpr uint64_t kL[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0ULL,
                            0x1000000000000000ULL};

// Fixed-width little-endian bignum, 9 x 64-bit limbs (enough for 512-bit
// inputs and every intermediate below).
struct Big {
  uint64_t v[9];
};

int big_cmp(const Big& a, const Big& b) {
  for (int i = 8; i >= 0; --i) {
    if (a.v[i] != b.v[i]) return a.v[i] > b.v[i] ? 1 : -1;
  }
  return 0;
}

// a -= b, requires a >= b
void big_sub(Big& a, const Big& b) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 9; ++i) {
    unsigned __int128 d =
        (unsigned __int128)a.v[i] - b.v[i] - (uint64_t)borrow;
    a.v[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

bool big_is_zero(const Big& a) {
  for (int i = 0; i < 9; ++i)
    if (a.v[i]) return false;
  return true;
}

// out = hi * C where hi has up to 5 limbs; out fits 7 limbs.
void mul_by_c(const uint64_t hi[5], Big& out) {
  std::memset(out.v, 0, sizeof(out.v));
  unsigned __int128 carry = 0;
  for (int i = 0; i < 6; ++i) {
    unsigned __int128 acc = carry;
    carry = 0;
    if (i < 5) acc += (unsigned __int128)hi[i] * kC0;
    if (i >= 1 && i - 1 < 5) acc += (unsigned __int128)hi[i - 1] * kC1;
    // acc can overflow 128 bits only if both products near max — they
    // can't: kC1 < 2^61 and kC0 < 2^63, so acc < 2^127 + carry.
    out.v[i] = (uint64_t)acc;
    carry = acc >> 64;
  }
  out.v[6] = (uint64_t)carry;
}

// digest (64 bytes little-endian) -> canonical scalar mod L (32 bytes LE)
void sc_reduce(const uint8_t in[64], uint8_t out[32]) {
  Big m;
  std::memset(m.v, 0, sizeof(m.v));
  for (int i = 0; i < 8; ++i) {
    uint64_t w = 0;
    for (int j = 7; j >= 0; --j) w = (w << 8) | in[8 * i + j];
    m.v[i] = w;
  }
  int sign = 1;  // value == sign * m (mod L)
  for (;;) {
    // split at 2^252: hi = m >> 252 (<= 260 bits), lo = m mod 2^252
    uint64_t hi[5];
    for (int i = 0; i < 5; ++i) {
      uint64_t lo_part = (i + 3 < 9) ? (m.v[i + 3] >> 60) : 0;
      uint64_t hi_part = (i + 4 < 9) ? (m.v[i + 4] << 4) : 0;
      hi[i] = lo_part | hi_part;
    }
    bool hi_zero = !(hi[0] | hi[1] | hi[2] | hi[3] | hi[4]);
    if (hi_zero) break;
    Big lo;
    std::memset(lo.v, 0, sizeof(lo.v));
    for (int i = 0; i < 3; ++i) lo.v[i] = m.v[i];
    lo.v[3] = m.v[3] & 0x0fffffffffffffffULL;
    Big prod;
    mul_by_c(hi, prod);  // m == sign*(lo - prod) (mod L)
    if (big_cmp(lo, prod) >= 0) {
      m = lo;
      big_sub(m, prod);
    } else {
      m = prod;
      big_sub(m, lo);
      sign = -sign;
    }
  }
  // m < 2^252 < L
  if (sign < 0 && !big_is_zero(m)) {
    Big l;
    std::memset(l.v, 0, sizeof(l.v));
    for (int i = 0; i < 4; ++i) l.v[i] = kL[i];
    big_sub(l, m);
    m = l;
  }
  for (int i = 0; i < 4; ++i) {
    uint64_t w = m.v[i];
    for (int j = 0; j < 8; ++j) {
      out[8 * i + j] = (uint8_t)(w & 0xff);
      w >>= 8;
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// Exported batch entry points (ctypes ABI: flat buffers + offsets)
// ---------------------------------------------------------------------------

extern "C" {

// k[i] = SHA-512(r[i] || a[i] || msg[i]) mod L, little-endian 32 bytes.
// r, a: n*32 bytes. msgs: concatenated message bytes; offs: n+1 int64
// prefix offsets into msgs. out: n*32 bytes.
void challenge_batch(const uint8_t* r, const uint8_t* a, const uint8_t* msgs,
                     const int64_t* offs, int64_t n, uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    Sha512Ctx c;
    sha512_init(&c);
    sha512_update(&c, r + 32 * i, 32);
    sha512_update(&c, a + 32 * i, 32);
    sha512_update(&c, msgs + offs[i], (uint64_t)(offs[i + 1] - offs[i]));
    uint8_t digest[64];
    sha512_final(&c, digest);
    sc_reduce(digest, out + 32 * i);
  }
}

// digests[i] = SHA-512(msgs[offs[i]:offs[i+1]]) — generic batch hasher.
void sha512_batch(const uint8_t* msgs, const int64_t* offs, int64_t n,
                  uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    Sha512Ctx c;
    sha512_init(&c);
    sha512_update(&c, msgs + offs[i], (uint64_t)(offs[i + 1] - offs[i]));
    sha512_final(&c, out + 64 * i);
  }
}

// out[i] = in[i] mod L for 64-byte little-endian digests — exported so the
// reduction's boundary behavior (sign flips, m == 0, values straddling L
// and 2^252) is directly testable, not only through SHA-512 outputs.
void sc_reduce_batch(const uint8_t* in, int64_t n, uint8_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) sc_reduce(in + 64 * i, out + 32 * i);
}

int native_num_threads(void) {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}
}
