// Batch Ed25519 verification for the consensus plane's CPU verifier.
//
// Design mirrors the TPU fused-comb kernel (ops/comb.py) rather than a
// textbook verify: the host (Python) decompresses each committee pubkey
// ONCE with exact bigint math and passes affine (x, y); the challenge
// scalars k = SHA-512(R||A||M) mod L arrive precomputed (pbft_native.cpp
// challenge_batch). This library evaluates P = [S]B + [k](-A) per item,
// normalizes the whole batch with ONE field inversion (Montgomery batch
// trick), and byte-compares P's canonical encoding against the wire R.
// A non-canonical or off-curve R simply never matches — the same
// (strictest) semantics as the TPU kernel, so the two accelerated
// backends agree bit-for-bit.
//
// NOT constant-time, deliberately: verification consumes public data
// (wire messages, public keys, signatures). Field arithmetic: 5x51-bit
// limbs with unsigned __int128 products — portable g++, no asm.
//
// Reference for parity: crypto/ed25519_cpu.py (RFC 8032 oracle);
// SURVEY.md §7 (crypto plane), BASELINE configs 1-3 (CPU verifier).

#include <cstdint>
#include <cstring>
#include <mutex>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef int64_t i64;

static const u64 MASK51 = ((u64)1 << 51) - 1;

// ---------------------------------------------------------------------------
// fe51: GF(2^255 - 19) as 5 x 51-bit limbs
// ---------------------------------------------------------------------------

struct fe {
    u64 v[5];
};

static inline fe fe_zero() { fe r{}; return r; }
static inline fe fe_one() { fe r{}; r.v[0] = 1; return r; }

static inline fe fe_add(const fe &a, const fe &b) {
    fe r;
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    return r;
}

// a - b + 4p: the bias must dominate b's limbs, which after an fe_add of
// two carried elements reach 2^52 + eps (> 2p's limb0 of 2^52 - 38, the
// classic underflow trap) — 4p's limbs are ~2^53 and keep every term
// positive while products still fit u128 comfortably
static inline fe fe_sub(const fe &a, const fe &b) {
    fe r;
    r.v[0] = a.v[0] + 0x1FFFFFFFFFFFB4ull - b.v[0];  // 4*(2^51-19)
    r.v[1] = a.v[1] + 0x1FFFFFFFFFFFFCull - b.v[1];  // 4*(2^51-1)
    r.v[2] = a.v[2] + 0x1FFFFFFFFFFFFCull - b.v[2];
    r.v[3] = a.v[3] + 0x1FFFFFFFFFFFFCull - b.v[3];
    r.v[4] = a.v[4] + 0x1FFFFFFFFFFFFCull - b.v[4];
    return r;
}

// weak carry: brings limbs under ~2^52 (enough headroom for adds/subs
// before the next multiply)
static inline fe fe_carry(const fe &a) {
    fe r = a;
    u64 c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
    c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
    c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
    c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += c * 19;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    return r;
}

static fe fe_mul(const fe &a, const fe &b) {
    u128 t0, t1, t2, t3, t4;
    u64 a0 = a.v[0], a1 = a.v[1], a2 = a.v[2], a3 = a.v[3], a4 = a.v[4];
    u64 b0 = b.v[0], b1 = b.v[1], b2 = b.v[2], b3 = b.v[3], b4 = b.v[4];
    u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19, b4_19 = b4 * 19;

    t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
         (u128)a3 * b2_19 + (u128)a4 * b1_19;
    t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
         (u128)a3 * b3_19 + (u128)a4 * b2_19;
    t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
         (u128)a3 * b4_19 + (u128)a4 * b3_19;
    t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
         (u128)a3 * b0 + (u128)a4 * b4_19;
    t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
         (u128)a3 * b1 + (u128)a4 * b0;

    fe r;
    u64 c;
    r.v[0] = (u64)t0 & MASK51; c = (u64)(t0 >> 51);
    t1 += c;
    r.v[1] = (u64)t1 & MASK51; c = (u64)(t1 >> 51);
    t2 += c;
    r.v[2] = (u64)t2 & MASK51; c = (u64)(t2 >> 51);
    t3 += c;
    r.v[3] = (u64)t3 & MASK51; c = (u64)(t3 >> 51);
    t4 += c;
    r.v[4] = (u64)t4 & MASK51; c = (u64)(t4 >> 51);
    r.v[0] += c * 19;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    return r;
}

static inline fe fe_sq(const fe &a) { return fe_mul(a, a); }

static fe fe_invert(const fe &z) {
    // z^(p-2) via the standard 254-squaring addition chain
    fe z2 = fe_sq(z);                       // 2
    fe z8 = fe_sq(fe_sq(z2));               // 8
    fe z9 = fe_mul(z8, z);                  // 9
    fe z11 = fe_mul(z9, z2);                // 11
    fe z22 = fe_sq(z11);                    // 22
    fe z_5_0 = fe_mul(z22, z9);             // 2^5 - 2^0
    fe t = z_5_0;
    for (int i = 0; i < 5; i++) t = fe_sq(t);
    fe z_10_0 = fe_mul(t, z_5_0);           // 2^10 - 2^0
    t = z_10_0;
    for (int i = 0; i < 10; i++) t = fe_sq(t);
    fe z_20_0 = fe_mul(t, z_10_0);
    t = z_20_0;
    for (int i = 0; i < 20; i++) t = fe_sq(t);
    fe z_40_0 = fe_mul(t, z_20_0);
    t = z_40_0;
    for (int i = 0; i < 10; i++) t = fe_sq(t);
    fe z_50_0 = fe_mul(t, z_10_0);
    t = z_50_0;
    for (int i = 0; i < 50; i++) t = fe_sq(t);
    fe z_100_0 = fe_mul(t, z_50_0);
    t = z_100_0;
    for (int i = 0; i < 100; i++) t = fe_sq(t);
    fe z_200_0 = fe_mul(t, z_100_0);
    t = z_200_0;
    for (int i = 0; i < 50; i++) t = fe_sq(t);
    fe z_250_0 = fe_mul(t, z_50_0);
    t = z_250_0;
    for (int i = 0; i < 5; i++) t = fe_sq(t);
    return fe_mul(t, z11);                  // 2^255 - 21
}

static fe fe_frombytes(const uint8_t s[32]) {
    u64 lo0, lo1, lo2, lo3;
    memcpy(&lo0, s, 8);
    memcpy(&lo1, s + 8, 8);
    memcpy(&lo2, s + 16, 8);
    memcpy(&lo3, s + 24, 8);
    fe r;
    r.v[0] = lo0 & MASK51;
    r.v[1] = ((lo0 >> 51) | (lo1 << 13)) & MASK51;
    r.v[2] = ((lo1 >> 38) | (lo2 << 26)) & MASK51;
    r.v[3] = ((lo2 >> 25) | (lo3 << 39)) & MASK51;
    r.v[4] = (lo3 >> 12) & MASK51;  // drops the sign bit (bit 255)
    return r;
}

// full reduction to [0, p) then serialize little-endian
static void fe_tobytes(uint8_t out[32], const fe &a) {
    fe t = fe_carry(fe_carry(a));
    // add 19 and see if it overflows 2^255 => t >= p
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51;
    u64 lo0 = t.v[0] | (t.v[1] << 51);
    u64 lo1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 lo2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 lo3 = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(out, &lo0, 8);
    memcpy(out + 8, &lo1, 8);
    memcpy(out + 16, &lo2, 8);
    memcpy(out + 24, &lo3, 8);
}

static inline bool fe_isodd(const fe &a) {
    uint8_t b[32];
    fe_tobytes(b, a);
    return b[0] & 1;
}

// ---------------------------------------------------------------------------
// Group: extended coordinates (X, Y, Z, T), a = -1 twisted Edwards
// ---------------------------------------------------------------------------

struct ge {
    fe X, Y, Z, T;
};

// precomputed point in affine Niels form: (y+x, y-x, 2dxy)
struct ge_aff {
    fe ypx, ymx, xy2d;
};

// precomputed point in projective Niels form: (Y+X, Y-X, Z, 2dT)
struct ge_proj {
    fe YpX, YmX, Z, T2d;
};

// 2d mod p
static fe fe_d2() {
    static const uint8_t D2[32] = {
        0x59, 0xf1, 0xb2, 0x26, 0x94, 0x9b, 0xd6, 0xeb, 0x56, 0xb1, 0x83,
        0x82, 0x9a, 0x14, 0xe0, 0x00, 0x30, 0xd1, 0xf3, 0xee, 0xf2, 0x80,
        0x8e, 0x19, 0xe7, 0xfc, 0xdf, 0x56, 0xdc, 0xd9, 0x06, 0x24};
    return fe_frombytes(D2);
}

static ge ge_identity() {
    ge r;
    r.X = fe_zero();
    r.Y = fe_one();
    r.Z = fe_one();
    r.T = fe_zero();
    return r;
}

// dbl-2008-hwcd with a = -1 (so D = -A):
//   E = (X+Y)^2 - (A+B); G = D + B = B - A; F = G - C; H = D - B = -(A+B)
static ge ge_dbl(const ge &p) {
    fe A = fe_sq(p.X);
    fe B = fe_sq(p.Y);
    fe C = fe_mul(fe_sq(p.Z), fe_add(fe_one(), fe_one()));
    fe AB = fe_add(A, B);
    fe H = fe_sub(fe_zero(), AB);
    fe E = fe_sub(fe_sq(fe_add(p.X, p.Y)), AB);
    fe G = fe_sub(B, A);
    fe F = fe_sub(G, C);
    ge r;
    r.X = fe_mul(E, F);
    r.Y = fe_mul(G, H);
    r.Z = fe_mul(F, G);
    r.T = fe_mul(E, H);
    return r;
}

// mixed add with affine Niels: 7M
static ge ge_madd(const ge &p, const ge_aff &q) {
    fe A = fe_mul(fe_add(p.Y, p.X), q.ypx);
    fe B = fe_mul(fe_sub(p.Y, p.X), q.ymx);
    fe C = fe_mul(q.xy2d, p.T);
    fe D = fe_add(p.Z, p.Z);
    fe E = fe_sub(A, B);
    fe F = fe_sub(D, C);
    fe G = fe_add(D, C);
    fe H = fe_add(A, B);
    ge r;
    r.X = fe_mul(E, F);
    r.Y = fe_mul(G, H);
    r.Z = fe_mul(F, G);
    r.T = fe_mul(E, H);
    return r;
}

// full add with projective Niels: 8M
static ge ge_padd(const ge &p, const ge_proj &q) {
    fe A = fe_mul(fe_add(p.Y, p.X), q.YpX);
    fe B = fe_mul(fe_sub(p.Y, p.X), q.YmX);
    fe C = fe_mul(q.T2d, p.T);
    fe D = fe_mul(p.Z, q.Z);
    fe D2 = fe_add(D, D);
    fe E = fe_sub(A, B);
    fe F = fe_sub(D2, C);
    fe G = fe_add(D2, C);
    fe H = fe_add(A, B);
    ge r;
    r.X = fe_mul(E, F);
    r.Y = fe_mul(G, H);
    r.Z = fe_mul(F, G);
    r.T = fe_mul(E, H);
    return r;
}

static ge ge_psub(const ge &p, const ge_proj &q) {
    fe A = fe_mul(fe_add(p.Y, p.X), q.YmX);
    fe B = fe_mul(fe_sub(p.Y, p.X), q.YpX);
    fe C = fe_mul(q.T2d, p.T);
    fe D = fe_mul(p.Z, q.Z);
    fe D2 = fe_add(D, D);
    fe E = fe_sub(A, B);
    fe F = fe_add(D2, C);
    fe G = fe_sub(D2, C);
    fe H = fe_add(A, B);
    ge r;
    r.X = fe_mul(E, F);
    r.Y = fe_mul(G, H);
    r.Z = fe_mul(F, G);
    r.T = fe_mul(E, H);
    return r;
}

static ge_proj ge_to_proj(const ge &p) {
    ge_proj r;
    r.YpX = fe_carry(fe_add(p.Y, p.X));
    r.YmX = fe_carry(fe_sub(p.Y, p.X));
    r.Z = p.Z;
    r.T2d = fe_mul(p.T, fe_d2());
    return r;
}

// ---------------------------------------------------------------------------
// Fixed-base comb table for B: 64 positions x 16 nibble entries, affine
// Niels — built once at first use (exactly the ops/comb.py layout).
// ---------------------------------------------------------------------------

static ge_aff BASE_TABLE[64][16];
static std::once_flag base_once;

// B's standard affine coordinates (single definition — both the comb
// table and the fused-table builder start from these)
static const uint8_t BX[32] = {
    0x1a, 0xd5, 0x25, 0x8f, 0x60, 0x2d, 0x56, 0xc9, 0xb2, 0xa7, 0x25,
    0x95, 0x60, 0xc7, 0x2c, 0x69, 0x5c, 0xdc, 0xd6, 0xfd, 0x31, 0xe2,
    0xa4, 0xc0, 0xfe, 0x53, 0x6e, 0xcd, 0xd3, 0x36, 0x69, 0x21};
static const uint8_t BY[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66};

static ge ge_basepoint() {
    ge base;
    base.X = fe_frombytes(BX);
    base.Y = fe_frombytes(BY);
    base.Z = fe_one();
    base.T = fe_mul(base.X, base.Y);
    return base;
}

static void build_base_table() {
    ge base = ge_basepoint();

    // entries in extended coords first, batch-normalize at the end
    static ge ext[64][16];
    ge cur = base;  // 16^pos * B
    for (int pos = 0; pos < 64; pos++) {
        ge_proj curp = ge_to_proj(cur);
        ge acc = ge_identity();
        for (int w = 0; w < 16; w++) {
            ext[pos][w] = acc;
            acc = ge_padd(acc, curp);
        }
        cur = acc;  // 16 * (16^pos * B)
    }
    // batch inversion of all 1024 Z's
    static fe zs[1024], pre[1025];
    pre[0] = fe_one();
    for (int i = 0; i < 1024; i++) {
        zs[i] = ext[i / 16][i % 16].Z;
        pre[i + 1] = fe_mul(pre[i], zs[i]);
    }
    fe inv = fe_invert(pre[1024]);
    for (int i = 1023; i >= 0; i--) {
        fe zinv = fe_mul(pre[i], inv);
        inv = fe_mul(inv, zs[i]);
        ge &e = ext[i / 16][i % 16];
        fe x = fe_mul(e.X, zinv);
        fe y = fe_mul(e.Y, zinv);
        ge_aff &a = BASE_TABLE[i / 16][i % 16];
        a.ypx = fe_carry(fe_add(y, x));
        a.ymx = fe_carry(fe_sub(y, x));
        a.xy2d = fe_mul(fe_mul(x, y), fe_d2());
    }
}

// ---------------------------------------------------------------------------
// w-NAF (w=5) recoding: scalar (little-endian 32B, < L so < 2^253)
// -> digits[256], each 0 or odd in [-15, 15]
// ---------------------------------------------------------------------------

static int scalar_wnaf(const uint8_t s[32], int8_t naf[257]) {
    int bits[257];
    for (int i = 0; i < 256; i++) bits[i] = (s[i >> 3] >> (i & 7)) & 1;
    bits[256] = 0;
    memset(naf, 0, 257);
    int top = -1;
    int i = 0;
    while (i < 257) {
        if (!bits[i]) { i++; continue; }
        // gather 5 bits
        int val = 0;
        for (int j = 0; j < 5 && i + j < 257; j++) val |= bits[i + j] << j;
        if (val > 16) {
            val -= 32;
            // propagate carry
            int j = i + 5;
            while (j < 257) {
                if (!bits[j]) { bits[j] = 1; break; }
                bits[j] = 0;
                j++;
            }
        }
        naf[i] = (int8_t)val;
        top = i;
        for (int j = 1; j < 5 && i + j < 257; j++) bits[i + j] = 0;
        i += 5;
    }
    return top;
}

// ---------------------------------------------------------------------------
// Entry point
// ---------------------------------------------------------------------------

// ---------------------------------------------------------------------------
// Fused dual-scalar comb table construction (KeyBank cold-start path).
//
// Mirrors ops/comb.py fused_table_np: row[i*4^w + ws*2^w + wk] =
// (ws * 2^(w*i)) B + (wk * 2^(w*i)) (-A), emitted as affine Niels
// (y+x, y-x, 2dxy) 32-byte LE field elements (96 B/entry) — Python
// converts to the TPU limb packing with its existing vectorized path.
// The Python bigint build costs ~0.2 s/key at w=4 (~2 s at w=6); this
// native build is ~milliseconds, making a cold n=64 committee bank a
// sub-second affair instead of tens of seconds.
// ---------------------------------------------------------------------------

static ge ge_neg(const ge &p) {
    ge r = p;
    r.X = fe_carry(fe_sub(fe_zero(), p.X));
    r.T = fe_carry(fe_sub(fe_zero(), p.T));
    return r;
}

extern "C" int ed25519_fused_table(
    const uint8_t a_xy[64],  // pubkey affine x||y (32B LE each)
    int wbits,               // window bits (4..6)
    uint8_t *out)            // npos * 4^wbits * 96 bytes
{
    if (wbits < 1 || wbits > 8) return -1;
    const int window = 1 << wbits;
    const int fw = window * window;
    const int npos = (256 + wbits - 1) / wbits;
    const int n = npos * fw;

    ge base_b = ge_basepoint();
    ge A;
    A.X = fe_frombytes(a_xy);
    A.Y = fe_frombytes(a_xy + 32);
    A.Z = fe_one();
    A.T = fe_mul(A.X, A.Y);
    ge base_a = ge_neg(A);

    ge *ext = new ge[n];
    int idx = 0;
    for (int pos = 0; pos < npos; pos++) {
        ge_proj bp = ge_to_proj(base_b);
        ge_proj ap = ge_to_proj(base_a);
        ge row_b = ge_identity();
        for (int ws = 0; ws < window; ws++) {
            ge acc = row_b;
            for (int wk = 0; wk < window; wk++) {
                ext[idx++] = acc;
                acc = ge_padd(acc, ap);
            }
            row_b = ge_padd(row_b, bp);
        }
        for (int d = 0; d < wbits; d++) {
            base_b = ge_dbl(base_b);
            base_a = ge_dbl(base_a);
        }
    }

    // batch-invert all Z's, emit affine Niels bytes (ext stays live
    // through the backward pass, so Z is read in place)
    fe *prefix = new fe[n + 1];
    prefix[0] = fe_one();
    for (int i = 0; i < n; i++) {
        prefix[i + 1] = fe_mul(prefix[i], ext[i].Z);
    }
    fe inv = fe_invert(prefix[n]);
    fe d2 = fe_d2();
    for (int i = n - 1; i >= 0; i--) {
        fe zinv = fe_mul(prefix[i], inv);
        inv = fe_mul(inv, ext[i].Z);
        fe x = fe_mul(ext[i].X, zinv);
        fe y = fe_mul(ext[i].Y, zinv);
        uint8_t *o = out + (size_t)i * 96;
        fe_tobytes(o, fe_add(y, x));
        fe_tobytes(o + 32, fe_sub(y, x));
        fe_tobytes(o + 64, fe_mul(fe_mul(x, y), d2));
    }
    delete[] prefix;
    delete[] ext;
    return 0;
}

extern "C" int ed25519_batch_verify(
    const uint8_t *a_xy,       // n_keys * 64: affine x||y (32B LE each)
    int n_keys,
    const int32_t *key_idx,    // batch
    const uint8_t *s_scalars,  // batch * 32 (already checked < L)
    const uint8_t *k_scalars,  // batch * 32 (SHA-512(R||A||M) mod L)
    const uint8_t *r_wire,     // batch * 32 (signature R, raw wire bytes)
    const uint8_t *precheck,   // batch (0 = already invalid)
    uint8_t *out,              // batch (written 0/1)
    int batch)
{
    std::call_once(base_once, build_base_table);
    if (n_keys < 0 || batch < 0) return -1;

    // per-key projective-Niels tables of odd multiples 1A,3A,...,15A
    ge_proj (*ktab)[8] = new ge_proj[n_keys > 0 ? n_keys : 1][8];
    for (int kk = 0; kk < n_keys; kk++) {
        ge A;
        A.X = fe_frombytes(a_xy + kk * 64);
        A.Y = fe_frombytes(a_xy + kk * 64 + 32);
        A.Z = fe_one();
        A.T = fe_mul(A.X, A.Y);
        ge A2 = ge_dbl(A);
        ge_proj A2p = ge_to_proj(A2);
        ge cur = A;
        for (int m = 0; m < 8; m++) {
            ktab[kk][m] = ge_to_proj(cur);      // (2m+1) A
            cur = ge_padd(cur, A2p);
        }
    }

    fe *zs = new fe[batch];
    fe *xs = new fe[batch];
    fe *ys = new fe[batch];
    uint8_t *alive = new uint8_t[batch];

    for (int b = 0; b < batch; b++) {
        alive[b] = 0;
        out[b] = 0;
        if (!precheck[b]) continue;
        int kk = key_idx[b];
        if (kk < 0 || kk >= n_keys) continue;

        // acc = [S]B via the base comb (64 madds, no doublings)
        const uint8_t *s = s_scalars + b * 32;
        ge acc = ge_identity();
        for (int pos = 0; pos < 64; pos++) {
            int nib = (s[pos >> 1] >> ((pos & 1) * 4)) & 0xF;
            if (nib) acc = ge_madd(acc, BASE_TABLE[pos][nib]);
        }

        // acc += [k](-A): w-NAF ladder over k, SUBTRACTING multiples of A
        int8_t naf[257];
        int top = scalar_wnaf(k_scalars + b * 32, naf);
        if (top >= 0) {
            ge t = ge_identity();
            bool started = false;
            for (int i = top; i >= 0; i--) {
                if (started) t = ge_dbl(t);
                int8_t d = naf[i];
                if (d > 0) {
                    t = ge_psub(t, ktab[kk][(d - 1) >> 1]);   // -= dA
                    started = true;
                } else if (d < 0) {
                    t = ge_padd(t, ktab[kk][(-d - 1) >> 1]);  // += |d|A
                    started = true;
                }
            }
            // acc += t  (t = [k](-A), extended + extended via proj Niels)
            acc = ge_padd(acc, ge_to_proj(t));
        }
        xs[b] = acc.X;
        ys[b] = acc.Y;
        zs[b] = acc.Z;
        alive[b] = 1;
    }

    // Montgomery batch inversion over the live Z's
    fe run = fe_one();
    fe *prefix = new fe[batch + 1];
    prefix[0] = run;
    for (int b = 0; b < batch; b++) {
        if (alive[b]) run = fe_mul(run, zs[b]);
        prefix[b + 1] = run;
    }
    fe inv = fe_invert(run);
    for (int b = batch - 1; b >= 0; b--) {
        if (!alive[b]) continue;
        fe zinv = fe_mul(prefix[b], inv);
        inv = fe_mul(inv, zs[b]);
        fe x = fe_mul(xs[b], zinv);
        fe y = fe_mul(ys[b], zinv);
        uint8_t enc[32];
        fe_tobytes(enc, y);
        enc[31] |= (uint8_t)(fe_isodd(x) << 7);
        out[b] = memcmp(enc, r_wire + b * 32, 32) == 0;
    }

    delete[] prefix;
    delete[] alive;
    delete[] ys;
    delete[] xs;
    delete[] zs;
    delete[] ktab;
    return 0;
}
