"""Critical-path span layer (ISSUE 4 tentpole).

Round 5's verdict: steady-state consensus drives the device at 6-31k
verifies/s against the same chip's 715k microbench, and nothing in the
telemetry plane could say where the other ~96% goes — the counters count
but cannot ATTRIBUTE. This module is the attribution layer: monotonic-
clock, allocation-light span records for every stage of the verify
critical path and the consensus pipeline, so a commit's latency
decomposes into named waits instead of one opaque number.

A span is (stage, start, duration) plus whatever ids the stage has
(node, view/seq slot, request id, item count). Recording is one tuple
append into a bounded ring plus one O(1) histogram update under a lock —
no dict is built unless the span is exported or persisted. Stages:

  verify.queue       VerifyService admission-queue wait (submit -> take)
  verify.host_prep   TpuVerifier host-side batch prep before dispatch
  verify.device      device dispatch -> result RTT (one coalesced pass)
  verify.cpu         CPU small-batch pass
  verify.cpu_reroute CPU reroute chunk (quarantine / depth-full big pile)
  qc.queue           QcVerifyLane wait (cert submit -> batch start)
  qc.pairing         one RLC multi-pairing batch
  replica.verify_wait  a sweep's verify from the replica's seat (queue +
                       device + resolution, the full service round trip)
  phase.prepare      pre-prepare admission -> slot prepared
  phase.commit       prepared -> commit certificate formed
  phase.execute      commit certificate -> applied in order
  execute.spec       admission -> speculative reply sent (ISSUE 15)
  execute.final      admission -> applied in order (the same slot's
                     full commit latency, comparable against spec)
  transport.queue    local-transport residency (enqueue -> recv), fault
                     delay included — the wire's contribution
  client.e2e         client submit -> f+1 accepted

The three phase.* spans of a slot tile its end-to-end commit latency
exactly (same clock, adjacent endpoints), which is what lets
``tools/critical_path.py`` check its decomposition against the measured
``commit_ms`` histogram — the acceptance reconciliation.

One recorder per process (like consensus/qc.py's verify lane): the
coalescing service and the QC lane are process-wide anyway, and
per-node spans carry their node id in the record. ``configure()``
attaches the JSONL sink (``<log-dir>/<id>.spans.jsonl`` in node.py;
``<flight-dir>/<config>.spans.jsonl`` in bench_consensus). High-volume
stages (per-message transport residency) record with ``persist=False``:
histogram only — never a file line per message, and never a slot in the
recent ring the autopsy exports.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

from . import clock, sanitize
from .logutil import Histogram

# canonical stage names (keep tools/critical_path.py's grouping in sync)
VERIFY_QUEUE = "verify.queue"
VERIFY_HOST_PREP = "verify.host_prep"
VERIFY_DEVICE = "verify.device"
VERIFY_CPU = "verify.cpu"
VERIFY_REROUTE = "verify.cpu_reroute"
QC_QUEUE = "qc.queue"
QC_PAIRING = "qc.pairing"
REPLICA_VERIFY_WAIT = "replica.verify_wait"
PHASE_PREPARE = "phase.prepare"
PHASE_COMMIT = "phase.commit"
PHASE_EXECUTE = "phase.execute"
# the phase.execute split (ISSUE 15): both measured from pre-prepare
# ADMISSION so their percentiles are directly comparable — the gap
# between p50(execute.spec) and p50(execute.final) IS the speculative
# win. phase.execute keeps its commit-cert→applied meaning (the tiling/
# reconciliation contract below depends on it); these two are the
# attribution overlay, not a rename.
EXECUTE_SPEC = "execute.spec"      # admission -> speculative reply sent
EXECUTE_FINAL = "execute.final"    # admission -> applied in order
TRANSPORT_QUEUE = "transport.queue"
CLIENT_E2E = "client.e2e"

# the slot-level stages that tile a commit's end-to-end latency, in
# pipeline order (critical_path.py reconciles their sum against commit_ms)
PHASE_STAGES = (PHASE_PREPARE, PHASE_COMMIT, PHASE_EXECUTE)


class SpanRecorder:
    """Bounded-memory span sink: per-stage histograms + a recent ring +
    an optional line-flushed JSONL file.

    Thread-safe (`record` is called from the event loop, the verify
    dispatcher/completion threads, the QC lane worker, and reroute
    threads). The main lock covers one deque append and one histogram
    update — nanoseconds — so an event-loop recorder (per-message
    transport spans) can never block behind disk. Sink writes happen
    OUTSIDE it under their own lock, and on failure the sink degrades
    to the in-memory surfaces exactly like the flight recorder
    (telemetry must never take down the node it observes)."""

    def __init__(self, ring: int = 4096) -> None:
        self._lock = sanitize.wrap_lock(threading.Lock(), "spans.recorder")
        # serializes file I/O only; same sanitizer group as _lock: the
        # two must never be held together (sink I/O off the ring lock)
        self._sink_lock = sanitize.wrap_lock(threading.Lock(), "spans.sink")
        self._ring: deque = deque(maxlen=ring)
        self._hists: Dict[str, Histogram] = {}
        self._sink = None
        self.node_id = ""
        self.recorded = 0
        self.persisted = 0

    def configure(self, node_id: str, path: Optional[str] = None) -> None:
        """Name the process (multi-process deployments: the node id),
        attach the JSONL sink, and START A FRESH SURFACE — histograms,
        ring, and counters reset, so a process running several
        measurement cells (bench_consensus config ladder) never bleeds
        one cell's spans into the next cell's record."""
        from .telemetry import _JsonlSink  # no cycle: telemetry never

        # imports spans at module level
        with self._sink_lock:
            old = self._sink
            if old is not None:
                old.close()
            new_sink = _JsonlSink(path) if path else None
        with self._lock:
            self.node_id = node_id
            self._sink = new_sink
            self._ring.clear()
            self._hists = {}
            self.recorded = 0
            self.persisted = 0

    def record(
        self,
        stage: str,
        dur: float,
        *,
        node: Optional[str] = None,
        view: Optional[int] = None,
        seq: Optional[int] = None,
        rid: Optional[str] = None,
        n: Optional[int] = None,
        persist: bool = True,
    ) -> None:
        """One span: ``dur`` seconds of ``stage``, ending now. The record
        is stamped with its END time on the clock seam (monotonic on real
        runs, virtual under the sim loop — so sim span ledgers are
        byte-deterministic and joinable with trace-plane edge docs) —
        start is end - dur, same clock. ``persist=False`` marks
        per-message-volume stages: histogram only — no file line, and no
        slot in the recent ring (an autopsy's last-N window must hold the
        pipeline spans that diagnose a wedge, not thousands of transport
        residencies)."""
        end = clock.now()
        rec = (stage, end, dur, node, view, seq, rid, n)
        with self._lock:
            h = self._hists.get(stage)
            if h is None:
                h = self._hists[stage] = Histogram()
            h.record(dur * 1e3)
            self.recorded += 1
            sink = None
            if persist:
                self._ring.append(rec)
                sink = self._sink
        if sink is not None:
            doc = self._to_doc(rec)
            with self._sink_lock:
                sink.write(doc)
                if sink._fh is not None:
                    # counted only when the line actually landed: a sink
                    # degraded by ENOSPC must not keep inflating the
                    # on-disk count post-mortem tooling trusts
                    self.persisted += 1

    def emit(self, doc: Dict[str, Any]) -> None:
        """Write one non-span ledger doc straight to the JSONL sink.

        The trace plane's cross-node edge events and per-certificate
        quorum docs (trace.py) share the span ledger file — one
        ``<id>.spans.jsonl`` per node is the unit slot_trace joins —
        but they are not spans: no histogram, no ring slot, and no-op
        when no sink is attached. Never raises (a ledger write must not
        be able to take down the transport or consensus path calling
        it)."""
        try:
            with self._lock:
                sink = self._sink
            if sink is None:
                return
            with self._sink_lock:
                sink.write(doc)
                if sink._fh is not None:
                    self.persisted += 1
        except Exception:
            pass

    def _to_doc(self, rec) -> Dict[str, Any]:
        stage, end, dur, node, view, seq, rid, n = rec
        doc: Dict[str, Any] = {
            "evt": "span",
            "stage": stage,
            "node": node if node is not None else self.node_id,
            "t_mono": round(end, 6),
            "dur_ms": round(dur * 1e3, 4),
        }
        if view is not None:
            doc["view"] = view
        if seq is not None:
            doc["seq"] = seq
        if rid is not None:
            doc["rid"] = rid
        if n is not None:
            doc["n"] = n
        return doc

    def recent(self, limit: int = 256) -> List[Dict[str, Any]]:
        """The last ``limit`` spans as dicts (autopsy dumps, tests)."""
        with self._lock:
            tail = list(self._ring)[-limit:]
        return [self._to_doc(rec) for rec in tail]

    def stage_summaries(self) -> Dict[str, Dict[str, float]]:
        """Per-stage histogram summaries, ms (telemetry snapshots)."""
        with self._lock:
            return {s: h.summary() for s, h in sorted(self._hists.items())}

    def snapshot(self) -> Dict[str, Any]:
        sink = self._sink
        return {
            "recorded": self.recorded,
            "persisted": self.persisted,
            # nonzero = the JSONL surface is truncated (sink degraded to
            # in-memory on a write failure); critical_path consumers
            # should distrust file completeness past that point
            "sink_write_errors": sink.write_errors if sink is not None else 0,
            "stages": self.stage_summaries(),
        }

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


# the process-wide recorder (every in-process node shares the verify
# service and QC lane, so they share the span surface too; per-node
# stages carry node= in each record)
_recorder = SpanRecorder()


def recorder() -> SpanRecorder:
    return _recorder


def configure(node_id: str, path: Optional[str] = None) -> None:
    _recorder.configure(node_id, path)


def record(stage: str, dur: float, **kw) -> None:
    _recorder.record(stage, dur, **kw)


def emit(doc: Dict[str, Any]) -> None:
    _recorder.emit(doc)


def recent(limit: int = 256) -> List[Dict[str, Any]]:
    return _recorder.recent(limit)


def snapshot() -> Dict[str, Any]:
    return _recorder.snapshot()
