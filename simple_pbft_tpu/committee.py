"""One-call harness: an N-replica committee + clients on a local network.

The reference's only "deployment" is run.bat launching 4 Windows processes;
this harness is its in-process equivalent and the substrate for every test
and benchmark config in BASELINE.md (4 → 256 replicas).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .app import Application, KVStore
from .client import Client
from .config import CommitteeConfig, KeyPair, make_test_committee
from .consensus.replica import Replica
from .crypto.verifier import Verifier
from .transport.local import FaultPlan, LocalNetwork


@dataclass
class LocalCommittee:
    cfg: CommitteeConfig
    keys: Dict[str, KeyPair]
    net: LocalNetwork
    replicas: List[Replica] = field(default_factory=list)
    clients: List[Client] = field(default_factory=list)
    lag_gauge: Optional[object] = None  # LoopLagGauge (attach_loop_lag)
    traffic_stats: Optional[object] = None  # workload.TrafficStats (ISSUE 17)
    knob_registry: Optional[object] = None  # controller.KnobRegistry (ISSUE 19)

    @staticmethod
    def build(
        n: int = 4,
        clients: int = 1,
        fault_plan: Optional[FaultPlan] = None,
        verifier_factory=None,
        app_factory=KVStore,
        shed_watermark: int = 0,
        max_drain: int = 0,
        **cfg_overrides,
    ) -> "LocalCommittee":
        cfg, keys = make_test_committee(n=n, clients=clients, **cfg_overrides)
        net = LocalNetwork(fault_plan)
        committee = LocalCommittee(cfg=cfg, keys=keys, net=net)
        # shed-plane knobs forward only when set: Replica's defaults are
        # production-sized, and sim scenarios shrink them to make the
        # overload seams reachable at sim scale (ISSUE 17)
        shed_kw = {}
        if shed_watermark:
            shed_kw["shed_watermark"] = shed_watermark
        if max_drain:
            shed_kw["max_drain"] = max_drain
        for rid in cfg.replica_ids:
            committee.replicas.append(
                Replica(
                    node_id=rid,
                    cfg=cfg,
                    seed=keys[rid].seed,
                    transport=net.endpoint(rid),
                    app=app_factory(),
                    verifier=verifier_factory() if verifier_factory else None,
                    **shed_kw,
                )
            )
        for i in range(clients):
            cid = f"c{i}"
            committee.clients.append(
                Client(
                    client_id=cid,
                    cfg=cfg,
                    seed=keys[cid].seed,
                    transport=net.endpoint(cid),
                )
            )
        return committee

    def start(self) -> None:
        for r in self.replicas:
            r.start()
        for c in self.clients:
            c.start()

    async def stop(self) -> None:
        import asyncio

        if self.lag_gauge is not None:
            await self.lag_gauge.stop()
            self.lag_gauge = None
        # concurrent: graceful stop drains each replica's pipeline (up to
        # ~10 s when certificate-heavy sweeps are mid-flight); serially a
        # 64-node teardown could take minutes. return_exceptions so one
        # failing stop can't abandon the rest mid-teardown
        results = await asyncio.gather(
            *(r.stop() for r in self.replicas), return_exceptions=True
        )
        results += await asyncio.gather(
            *(c.stop() for c in self.clients), return_exceptions=True
        )
        for exc in results:
            if isinstance(exc, BaseException):
                raise exc

    def replica(self, rid: str) -> Replica:
        return next(r for r in self.replicas if r.id == rid)

    # -- telemetry plane (simple_pbft_tpu/telemetry.py) -----------------

    def node_telemetry(self, node_id: str):
        """Unified-telemetry registry for one node of this committee
        (replica or client) — the object StatusServer / FlightRecorder
        serve from."""
        from .telemetry import NodeTelemetry

        for r in self.replicas:
            if r.id == node_id:
                return NodeTelemetry(
                    node_id, replica=r, transport=r.transport,
                    tracer=r.tracer, loop_lag=self.lag_gauge,
                    traffic=self.traffic_stats,
                    knobs=self.knob_registry,
                )
        for c in self.clients:
            if c.id == node_id:
                return NodeTelemetry(
                    node_id, client=c, transport=c.transport,
                    tracer=c.tracer, loop_lag=self.lag_gauge,
                    traffic=self.traffic_stats,
                    knobs=self.knob_registry,
                )
        raise KeyError(node_id)

    def attach_knobs(self):
        """Build the standard knob registry over this committee (ISSUE
        19 perf plane) and surface it in every node's telemetry. Returns
        the registry; a KnobController is attached separately (sim.py
        does both when a scenario asks for the controller)."""
        from .controller import registry_for_committee

        self.knob_registry = registry_for_committee(self)
        return self.knob_registry

    def attach_loop_lag(self, interval: float = 0.1):
        """Start the committee's event-loop lag gauge (ISSUE 4: one loop
        runs every in-process node, so one gauge serves them all — a
        starved dispatcher core shows in every node's snapshot). Call
        from inside the running loop; stop via ``await
        committee.lag_gauge.stop()`` (committee.stop() does it too)."""
        from .telemetry import LoopLagGauge

        self.lag_gauge = LoopLagGauge(interval=interval)
        self.lag_gauge.start()
        return self.lag_gauge

    def attach_auditors(self, log_dir: Optional[str] = None,
                        watchdog=None) -> Dict[str, object]:
        """Give every replica a SafetyAuditor (the ISSUE 5 audit plane):
        online safety-invariant checks over the verified message stream,
        with evidence + observation ledgers under ``log_dir`` (None =
        in-memory surfaces only). ``watchdog`` (a ProgressWatchdog)
        makes a safety violation trigger the same forensic dump path as
        a stall. Returns {replica_id: auditor}; close each auditor after
        ``stop()`` to flush the ledgers."""
        from .audit import SafetyAuditor

        auditors: Dict[str, object] = {}
        for r in self.replicas:
            auditors[r.id] = r.auditor = SafetyAuditor(
                r.id, self.cfg, log_dir=log_dir, watchdog=watchdog
            )
        return auditors

    def attach_tracers(self, sample_mod: int = 64, trace_dir: Optional[str] = None):
        """Give every replica AND client a RequestTracer with the same
        deterministic sampling, so a sampled request's lifecycle exists
        at every hop and joins by request id. Returns {node_id: tracer}.
        trace_dir=None keeps events in the in-memory rings only."""
        import os

        from .telemetry import RequestTracer

        tracers = {}
        for node in [*self.replicas, *self.clients]:
            path = (
                os.path.join(trace_dir, f"{node.id}.trace.jsonl")
                if trace_dir
                else None
            )
            tracers[node.id] = node.tracer = RequestTracer(
                node.id, sample_mod=sample_mod, path=path
            )
        return tracers
