"""Device-plane event ledger (ISSUE 14 tentpole).

The verify plane's cost claim — bandwidth-bound at 777k verifies/s/chip
with a measured route to ~1.05M (`bench_results/
verify_1m_decomposition_r05.md`) — was produced by hand, once. Every
other plane got continuous instrumentation (spans in PR 4, wire
accounting in PR 9); the device plane, where per-role crypto cost
dominates, stayed a markdown memo. This module is the continuously-
measured replacement: every jit dispatch on the verify path records one
event — (lane, mode, window, bucket, batch size, pad waste, queue wait,
host prep, device RTT, compile-vs-cache, host<->device bytes) — into a
bounded lock-free ring, and the aggregates ride
``VerifyService.snapshot()["device"]`` -> telemetry -> every flight
frame and bench record. ``tools/verify_observatory.py`` joins the
ledger with the span layer and the static cost model
(``crypto/costmodel.py``) into a measured roofline verdict per run.

Lanes share one schema so the 8-mesh shard-out inherits it day one:

  ``ed25519``  TpuVerifier jit dispatches (the coalesced verify path)
  ``bls``      QcVerifyLane RLC multi-pairing batches
  ``shard``    parallel/sharded_verify per-device SPMD step events

Discipline (PBL004): every public entry point here is audited
never-raise — recording wraps its body in a broad except because a
telemetry bug must not take down the verify pipeline it observes — and
the ledger is ZERO-overhead when disabled: ``record()`` returns after
one attribute read (A/B-asserted in tests/test_devledger.py). Like
``spans.py`` the recorder is process-wide (the verify service and QC
lane are process-wide too); events are tuples appended to a deque
(GIL-atomic, no lock on the hot path) and the aggregate counters are
plain int/float adds — observability, not control flow. Works under
``JAX_PLATFORMS=cpu`` unchanged, so tier-1 exercises the full path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

LANE_ED25519 = "ed25519"
LANE_BLS = "bls"
LANE_SHARD = "shard"


# the raw (summable) lane counters; consumers that merge blocks across
# processes (tools/verify_observatory.py) sum exactly these keys
LANE_SUM_KEYS = (
    "dispatches", "items", "pad_items", "submissions", "busy_s",
    "host_prep_s", "queue_wait_s", "bytes_up", "bytes_down", "compiles",
)


def _zero_agg() -> Dict[str, float]:
    return {k: 0 for k in LANE_SUM_KEYS}


def lane_view(agg: Dict[str, float], elapsed: float,
              n_devices: int) -> Dict[str, Any]:
    """Derived per-lane metrics from the raw summable counters — THE
    single definition of pad-waste %, items/dispatch, effective rate,
    and occupancy, shared by the live ledger snapshot and the
    cross-process merge in tools/verify_observatory.py (a second
    hand-maintained copy of these formulas would drift silently)."""
    disp = agg["dispatches"]
    items = agg["items"]
    total = items + agg["pad_items"]
    return {
        "dispatches": int(disp),
        "items": int(items),
        "pad_items": int(agg["pad_items"]),
        "pad_waste_pct": round(100.0 * agg["pad_items"] / total, 2)
        if total else 0.0,
        "submissions": int(agg["submissions"]),
        "coalesced_subs_per_dispatch": round(
            agg["submissions"] / disp, 2) if disp else 0.0,
        "items_per_dispatch": round(items / disp, 1) if disp else 0.0,
        "dispatches_per_s": round(disp / elapsed, 2),
        "verifies_per_s_effective": round(items / elapsed, 1),
        "busy_s": round(agg["busy_s"], 4),
        # busy fraction of the window; a latency integral, so
        # overlapped (double-buffered) passes clamp at 1.0 — the
        # occupancy a roofline wants is "was the device the
        # bottleneck", and >= 1 means unambiguously yes
        "occupancy": round(
            min(1.0, agg["busy_s"] / (elapsed * max(1, n_devices))), 4),
        "host_prep_s": round(agg["host_prep_s"], 4),
        "queue_wait_s": round(agg["queue_wait_s"], 4),
        "bytes_up": int(agg["bytes_up"]),
        "bytes_down": int(agg["bytes_down"]),
        "bytes_up_per_s": round(agg["bytes_up"] / elapsed, 1),
        "compiles": int(agg["compiles"]),
        "devices": n_devices if n_devices > 1 else 1,
    }


class DeviceLedger:
    """Bounded per-dispatch event ring + per-lane / per-shape aggregates.

    Thread-safe by construction rather than by locking: the ring is a
    ``deque`` (append is GIL-atomic), counters are plain adds on a dict
    owned by one lane's recording threads in practice, and every reader
    (``snapshot``) tolerates a torn mid-update view — these numbers are
    observability, never control flow. ``configure()`` takes the only
    lock, to swap surfaces atomically against concurrent recorders.
    """

    def __init__(self, ring: int = 2048) -> None:
        self._enabled = True
        self._lock = threading.Lock()
        self._ring_size = ring
        self._tls = threading.local()
        self.node_id = ""
        self.profile_captures = 0
        self.profile_last_dir: Optional[str] = None
        self._profile_armed = False
        self._reset_locked()

    # -- lifecycle -------------------------------------------------------

    def _reset_locked(self) -> None:
        self._ring: deque = deque(maxlen=self._ring_size)
        self._lanes: Dict[str, Dict[str, float]] = {}
        self._shapes: Dict[Tuple[str, str, int, int], Dict[str, int]] = {}
        self._devices: Dict[str, set] = {}
        self._t0 = time.monotonic()
        self.recorded = 0
        self.dropped = 0

    def configure(self, node_id: str = "", enabled: bool = True) -> None:
        """Name the process and START A FRESH WINDOW — ring, aggregates
        and the rate clock reset, so warmup compiles never pollute the
        measurement window (bench cells / node serve loops call this
        right next to ``spans.configure``). ``enabled=False`` turns the
        ledger into a no-op whose only cost is one attribute read per
        would-be event."""
        with self._lock:
            self.node_id = node_id
            self._enabled = bool(enabled)
            self._reset_locked()

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- queue-wait handoff ---------------------------------------------

    def annotate(self, queue_wait_s: float, submissions: int) -> None:
        """Stash the coalesced take's admission-queue wait for the NEXT
        dispatch recorded on THIS thread (the VerifyService dispatch
        loop calls ``dispatch_batch`` synchronously, so the thread-local
        slot bridges the service layer — which knows the waits — and
        the verifier layer — which knows the dispatch). Never raises."""
        if not self._enabled:
            return
        try:
            self._tls.pending = (float(queue_wait_s), int(submissions))
        except Exception:  # noqa: BLE001 — telemetry never raises inward
            pass

    def _take_annotation(self) -> Tuple[float, int]:
        pend = getattr(self._tls, "pending", None)
        if pend is None:
            return 0.0, 1
        self._tls.pending = None
        return pend

    # -- recording -------------------------------------------------------

    def record(
        self,
        lane: str,
        mode: str,
        window: int,
        bucket: int,
        n: int,
        *,
        host_prep_s: float = 0.0,
        rtt_s: float = 0.0,
        compile_fresh: bool = False,
        bytes_up: int = 0,
        bytes_down: int = 0,
        queue_wait_s: Optional[float] = None,
        submissions: Optional[int] = None,
        device: str = "",
    ) -> None:
        """One dispatch event. ``bucket`` is the padded device batch,
        ``n`` the real item count (pad waste = bucket - n). Queue wait
        defaults to the thread-local annotation (see ``annotate``).
        Audited never-raise (PBL004): the body is broad-guarded because
        a malformed field from a new seam must drop the event, not the
        verify pass recording it."""
        if not self._enabled:
            return
        try:
            if queue_wait_s is None or submissions is None:
                q, s = self._take_annotation()
                queue_wait_s = q if queue_wait_s is None else queue_wait_s
                submissions = s if submissions is None else submissions
            end = time.monotonic()
            pad = max(0, int(bucket) - int(n))
            self._ring.append((
                lane, mode, int(window), int(bucket), int(n), pad,
                round(float(queue_wait_s), 6), round(float(host_prep_s), 6),
                round(float(rtt_s), 6), bool(compile_fresh),
                int(bytes_up), int(bytes_down), device, round(end, 6),
            ))
            agg = self._lanes.get(lane)
            if agg is None:
                agg = self._lanes.setdefault(lane, _zero_agg())
            agg["dispatches"] += 1
            agg["items"] += int(n)
            agg["pad_items"] += pad
            agg["submissions"] += int(submissions)
            agg["busy_s"] += float(rtt_s)
            agg["host_prep_s"] += float(host_prep_s)
            agg["queue_wait_s"] += float(queue_wait_s)
            agg["bytes_up"] += int(bytes_up)
            agg["bytes_down"] += int(bytes_down)
            if compile_fresh:
                agg["compiles"] += 1
            if device:
                self._devices.setdefault(lane, set()).add(device)
            skey = (lane, mode, int(window), int(bucket))
            srow = self._shapes.get(skey)
            if srow is None:
                srow = self._shapes.setdefault(
                    skey, {"dispatches": 0, "items": 0, "pad_items": 0}
                )
            srow["dispatches"] += 1
            srow["items"] += int(n)
            srow["pad_items"] += pad
            self.recorded += 1
        except Exception:  # noqa: BLE001 — telemetry never raises inward
            self.dropped += 1

    # -- export ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The aggregate ``device`` block (never raises; returns a
        minimal stub on any internal error). Top level mirrors the
        ed25519 lane when present (the consensus verify path — what
        pbft_top's DEV column and the bench gate floors read), with
        every lane broken out under ``lanes`` and per-(mode, window,
        bucket) dispatch counts under ``shapes``."""
        try:
            elapsed = max(1e-9, time.monotonic() - self._t0)
            lanes = {}
            # iterate KEY snapshots throughout (list(dict) is one
            # C-level pass): a recorder thread inserting a new lane or
            # shape mid-read must not raise dictionary-changed-size
            # out of the exporter — the rows themselves only ever
            # mutate fixed keys, so dict(row) copies are safe
            for lane in sorted(list(self._lanes)):
                agg = self._lanes.get(lane)
                if agg is None:
                    continue
                nd = len(self._devices.get(lane, ())) or 1
                lanes[lane] = lane_view(dict(agg), elapsed, nd)
            shapes: Dict[str, Any] = {}
            for skey in sorted(list(self._shapes)):
                row = self._shapes.get(skey)
                if row is None:
                    continue
                ln, m, w, b = skey
                # lane-qualified keys: "ed25519:fused/w4/b8192" — the
                # lane prefix keeps e.g. an ed25519 ladder shape and
                # the shard wrapper's identical (mode, window, bucket)
                # from overwriting each other in the export
                shapes[f"{ln}:{m}/w{w}/b{b}"] = dict(row)
            top_src = lanes.get(LANE_ED25519)
            if top_src is None and lanes:
                top_src = next(iter(lanes.values()))
            out: Dict[str, Any] = {
                "enabled": self._enabled,
                # the ledger is ONE PER PROCESS: the id lets consumers
                # that see the same block through several per-replica
                # flight files (an in-process committee writes n files
                # embedding one ledger) dedup instead of n-fold-count
                "node": self.node_id,
                "window_s": round(elapsed, 3),
                "recorded": self.recorded,
                "dropped": self.dropped,
                "lanes": lanes,
                "shapes": shapes,
                "profile_captures": self.profile_captures,
            }
            for k in TOP_MIRROR_KEYS:
                out[k] = top_src[k] if top_src else _EMPTY_TOP[k]
            return out
        except Exception:  # noqa: BLE001 — telemetry never raises inward
            return {"enabled": self._enabled, "error": "snapshot failed"}

    def recent(self, limit: int = 256) -> List[Dict[str, Any]]:
        """The last ``limit`` events as dicts (observatory deep view,
        autopsy dumps, tests)."""
        tail = list(self._ring)[-limit:]
        out = []
        for (lane, mode, window, bucket, n, pad, qw, hp, rtt, comp,
             b_up, b_down, device, end) in tail:
            out.append({
                "evt": "dispatch",
                "lane": lane,
                "mode": mode,
                "window": window,
                "bucket": bucket,
                "n": n,
                "pad": pad,
                "queue_wait_s": qw,
                "host_prep_s": hp,
                "rtt_s": rtt,
                "compile": comp,
                "bytes_up": b_up,
                "bytes_down": b_down,
                "device": device,
                "t_mono": end,
            })
        return out

    # -- optional deep capture (--device-profile) ------------------------

    def arm_profile(self, out_dir: str, seconds: float) -> bool:
        """Arm ONE bounded ``jax.profiler`` trace capture on a sidecar
        daemon thread — off-loop, never in a consensus path, never
        raises, and a second arm while one is running is a no-op.
        Artifacts land under ``out_dir`` (the flight dir in node.py /
        bench_consensus). Returns whether a capture was armed."""
        if not self._enabled or self._profile_armed or seconds <= 0:
            return False
        self._profile_armed = True

        def run() -> None:
            try:
                import os

                import jax.profiler  # noqa: PLC0415 — optional dep path

                os.makedirs(out_dir, exist_ok=True)
                jax.profiler.start_trace(out_dir)
                try:
                    time.sleep(min(float(seconds), 120.0))
                finally:
                    jax.profiler.stop_trace()
                self.profile_captures += 1
                self.profile_last_dir = out_dir
            except Exception:  # noqa: BLE001 — capture is best-effort
                pass
            finally:
                self._profile_armed = False

        threading.Thread(
            target=run, name="device-profile", daemon=True
        ).start()
        return True


# the lane metrics mirrored at the block's top level (the consensus
# verify lane's view — what pbft_top's DEV cell and the bench-gate
# floors read without digging into lanes). THE single definition:
# DeviceLedger.snapshot and tools/verify_observatory's merger both
# iterate this, so a new lane_view metric propagates everywhere or
# nowhere — never to one surface only.
_EMPTY_TOP: Dict[str, Any] = {
    "dispatches": 0, "items": 0, "pad_waste_pct": 0.0, "occupancy": 0.0,
    "items_per_dispatch": 0.0, "dispatches_per_s": 0.0,
    "verifies_per_s_effective": 0.0, "busy_s": 0.0, "host_prep_s": 0.0,
    "queue_wait_s": 0.0, "bytes_up": 0, "bytes_down": 0, "compiles": 0,
    "coalesced_subs_per_dispatch": 0.0,
}
TOP_MIRROR_KEYS = tuple(_EMPTY_TOP)

# the process-wide ledger (the verify service, QC lane and shard mesh
# are process-wide; per-node deployments get one ledger per process)
_ledger = DeviceLedger()


def ledger() -> DeviceLedger:
    return _ledger


def configure(node_id: str = "", enabled: bool = True) -> None:
    _ledger.configure(node_id, enabled=enabled)


def record(lane: str, mode: str, window: int, bucket: int, n: int,
           **kw: Any) -> None:
    _ledger.record(lane, mode, window, bucket, n, **kw)


def annotate(queue_wait_s: float, submissions: int) -> None:
    _ledger.annotate(queue_wait_s, submissions)


def take_annotation() -> Tuple[float, int]:
    """Consume the current thread's pending queue-wait annotation
    (0.0, 1 when none). Never raises."""
    try:
        return _ledger._take_annotation()
    except Exception:  # noqa: BLE001 — telemetry never raises inward
        return 0.0, 1


def snapshot() -> Dict[str, Any]:
    return _ledger.snapshot()


def recent(limit: int = 256) -> List[Dict[str, Any]]:
    return _ledger.recent(limit)


def arm_profile(out_dir: str, seconds: float) -> bool:
    return _ledger.arm_profile(out_dir, seconds)
