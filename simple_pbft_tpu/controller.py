"""Self-driving perf plane (ISSUE 19): knob registry + online controller
+ hash-chained decision ledger.

The observatory can attribute every dispatch (devledger/costmodel), gate
every regression (bench_gate), and generate adversarial load at virtual
scale (workload plane) — but the knobs those instruments implicate
(coalesce batch bucket, CPU/device cutoff, QC-lane close window, shed
watermark, speculation depth) were hand-set constructor constants tuned
for one load shape. This module closes the loop:

- :class:`Knob` / :class:`KnobRegistry` lift the scattered constants
  into named, bounded, live-settable knobs. Every knob carries a
  DISCRETE ascending ``choices`` ladder — the controller's whole action
  space. For device-shaped knobs (verify.max_batch, qc.max_batch) the
  ladder is capped at the constructor value, i.e. the ceiling the warmup
  ladder already compiled: moving inside it can never trigger a
  post-warm jit compile (PBL006's zero-recompile contract holds by
  construction, and the campaign gate pins ``post_warm_compiles == 0``).

- :class:`KnobController` runs off the consensus hot path as a clock-
  seam task, reads one telemetry snapshot per tick, distills it into a
  flat signal view, and fires at most ONE rule per tick from the
  priority-ordered :data:`RULES` catalogue (one rule per verdict
  family: traffic admission gap, devledger pad-waste/queue-wait,
  costmodel limiter verdicts, QC-lane pressure, speculation churn).
  Per-knob cooldowns, calm-tick hysteresis (enter fast, exit slow) and
  an oscillation guard (alternating directions inside a short window
  freeze the knob instead of flapping it) keep it from chasing noise.

- :class:`DecisionLedger` appends every decision to a hash-chained
  JSONL file (``<id>.knobs.jsonl``) with the audit plane's chain idiom:
  open → action/guard/effect → close, each record carrying ``prev`` and
  ``h``. An action records the rule fired, the knob's old → new value,
  and the exact trigger signals the rule read — so
  :func:`replay_ledger` can re-derive every action from the ledger
  alone (the ISSUE 19 replay acceptance test).

Determinism: ticks advance on ``clock.sleep`` and every recorded
timestamp is virtual ``clock.now()`` — under SimClock the same seed
produces a byte-identical ledger (no wall reads anywhere in this
module, enforced by PBL007 via the marker below).
"""
# pbftlint: deterministic-module

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import clock
from .messages import canonical_json, sha256_hex

log = logging.getLogger("pbft.controller")

#: decision-ledger line schema (schema-stamped like telemetry/bench
#: ledgers; parsers hard-fail on a mismatch rather than misread)
LEDGER_SCHEMA_VERSION = 1

GENESIS = "0" * 64


def chain_hash(rec: Dict[str, Any]) -> str:
    """Hash of a ledger record EXCLUDING its own ``h`` (the audit-plane
    idiom): ``prev`` is inside, so each line commits to the whole
    prefix."""
    body = {k: v for k, v in rec.items() if k != "h"}
    return sha256_hex(canonical_json(body))


# ---------------------------------------------------------------------------
# knob registry
# ---------------------------------------------------------------------------


@dataclass
class Knob:
    """One live-settable performance knob.

    ``choices`` is the FULL action space, ascending: the controller only
    ever steps one rung along it, and ``KnobRegistry.set`` refuses any
    value off the ladder — bounds are enforced at the registry, not by
    each caller's discipline."""

    name: str
    doc: str
    choices: Tuple[Any, ...]
    get: Callable[[], Any]
    set: Callable[[Any], None]
    unit: str = ""


class KnobRegistry:
    """Named, bounded knobs over live subsystems.

    The registry is the single write path for tuning: ``set`` validates
    against the knob's ladder, ``step`` moves one rung and clamps at the
    ends. ``snapshot_block`` is the additive ``knobs`` telemetry block
    (values + bounds + controller posture) that rides NodeTelemetry
    snapshots and flight frames."""

    def __init__(self) -> None:
        self._knobs: Dict[str, Knob] = {}
        #: optional posture source (KnobController.posture) — surfaces
        #: the active profile / last action / guard state in telemetry
        self.posture_source: Optional[Callable[[], Dict[str, Any]]] = None

    def register(self, knob: Knob) -> Knob:
        if not knob.choices:
            raise ValueError(f"knob {knob.name}: empty choices ladder")
        if knob.name in self._knobs:
            raise ValueError(f"knob {knob.name} already registered")
        self._knobs[knob.name] = knob
        return knob

    def names(self) -> List[str]:
        return sorted(self._knobs)

    def __contains__(self, name: str) -> bool:
        return name in self._knobs

    def knob(self, name: str) -> Knob:
        return self._knobs[name]

    def value(self, name: str) -> Any:
        return self._knobs[name].get()

    def values(self) -> Dict[str, Any]:
        return {n: self._knobs[n].get() for n in self.names()}

    def set(self, name: str, value: Any) -> None:
        k = self._knobs[name]
        if value not in k.choices:
            raise ValueError(
                f"knob {name}: {value!r} is off the ladder {list(k.choices)}"
            )
        k.set(value)

    def _index(self, k: Knob) -> int:
        cur = k.get()
        if cur in k.choices:
            return k.choices.index(cur)
        # drifted off-ladder (some other writer): snap to the nearest
        # rung rather than raising from the controller's tick
        diffs = [
            (abs(float(c) - float(cur)), i) for i, c in enumerate(k.choices)
        ]
        return min(diffs)[1]

    def peek_step(self, name: str, direction: int) -> Tuple[Any, Any]:
        """(old, new) a one-rung step WOULD produce, without applying.
        Clamped at the ladder ends (old == new there)."""
        k = self._knobs[name]
        i = self._index(k)
        j = min(len(k.choices) - 1, max(0, i + (1 if direction > 0 else -1)))
        return k.get(), k.choices[j]

    def step(self, name: str, direction: int) -> Tuple[Any, Any]:
        """Move one rung along the ladder; returns (old, new)."""
        old, new = self.peek_step(name, direction)
        if new != old:
            self._knobs[name].set(new)
        return old, new

    def snapshot_block(self) -> Dict[str, Any]:
        """The ``knobs`` telemetry block. Additive to the snapshot
        schema — SCHEMA_VERSION unchanged, per the stability contract in
        telemetry.py."""
        block: Dict[str, Any] = {"schema": 1, "knobs": {}}
        for n in self.names():
            k = self._knobs[n]
            block["knobs"][n] = {
                "value": k.get(),
                "choices": list(k.choices),
                "lo": k.choices[0],
                "hi": k.choices[-1],
                "unit": k.unit,
            }
        if self.posture_source is not None:
            try:
                block["controller"] = self.posture_source()
            except Exception:  # degrade, don't take telemetry down
                log.exception("controller posture source failed")
        return block


def _ladder(*vals: Any) -> Tuple[Any, ...]:
    """Dedup + ascending sort — ladders built around a live initial
    value must stay canonical regardless of how the parts overlap."""
    return tuple(sorted(set(vals)))


def _fanout(objs: Sequence[Any], attr: str, cast=int) -> Callable[[Any], None]:
    def setter(v: Any) -> None:
        for o in objs:
            setattr(o, attr, cast(v))

    return setter


def registry_for_committee(com) -> KnobRegistry:
    """The standard knob set over a LocalCommittee.

    Every knob degrades to absent when its subsystem is (hasattr-guarded
    — an unsigned committee has no VerifyService, a non-speculative one
    no SpeculationEngine). Setters fan out to EVERY replica so the
    committee moves as one; getters read the first replica (they are
    built identically and only this registry writes them)."""
    reg = KnobRegistry()
    reps = list(getattr(com, "replicas", []) or [])
    if not reps:
        return reg
    r0 = reps[0]

    wm = int(r0.shed_watermark)
    reg.register(Knob(
        name="replica.shed_watermark",
        doc="inbox sweep size above which deferrable traffic is shed",
        # mid rungs (1.5x steps) above the configured watermark give
        # the knee-seeking traffic rules resolution where it matters:
        # the capacity knee usually sits between "configured" and
        # "configured x4", and a pure power-of-two ladder straddles it
        choices=_ladder(
            max(8, wm // 8), max(8, wm // 4), max(8, wm // 2),
            wm, wm * 3 // 2, wm * 2, wm * 3, wm * 4,
        ),
        get=lambda: reps[0].shed_watermark,
        set=_fanout(reps, "shed_watermark"),
        unit="msgs",
    ))
    md = int(r0.max_drain)
    reg.register(Knob(
        name="replica.max_drain",
        doc="max messages decoded per inbox sweep",
        choices=_ladder(max(64, md // 2), md, md * 2),
        get=lambda: reps[0].max_drain,
        set=_fanout(reps, "max_drain"),
        unit="msgs",
    ))

    engines = [r.spec for r in reps if getattr(r, "spec", None) is not None]
    if engines:
        sd = int(engines[0].max_depth)
        reg.register(Knob(
            name="spec.max_depth",
            doc="max concurrently open speculative slots",
            choices=_ladder(
                max(2, sd // 16), max(2, sd // 8), max(2, sd // 4),
                max(2, sd // 2), sd,
            ),
            get=lambda: engines[0].max_depth,
            set=_fanout(engines, "max_depth"),
            unit="slots",
        ))

    svcs = []
    seen = set()
    for r in reps:
        svc = getattr(r, "verifier", None)
        if svc is not None and hasattr(svc, "_max_batch") and id(svc) not in seen:
            seen.add(id(svc))
            svcs.append(svc)
    if svcs:
        mb = int(svcs[0]._max_batch)
        reg.register(Knob(
            name="verify.max_batch",
            # ladder CEILING == the constructor value: that is the top
            # bucket the warmup ladder compiled, so every rung is a
            # warmed shape — zero post-warm compiles by construction
            # (PBL006; the campaign gate pins the counter at 0)
            doc="coalesced verify batch cap (warmed-bucket ladder only)",
            choices=_ladder(
                max(64, mb // 8), max(64, mb // 4), max(64, mb // 2), mb,
            ),
            get=lambda: svcs[0]._max_batch,
            set=_fanout(svcs, "_max_batch"),
            unit="items",
        ))
        cut = svcs[0]._fixed_cutoff

        def _set_cutoff(v: Any) -> None:
            for s in svcs:
                # -1 is the ladder's "adaptive" rung: restore the
                # measured-throughput crossover (coalesce.py)
                s._fixed_cutoff = None if int(v) < 0 else int(v)

        reg.register(Knob(
            name="verify.cpu_cutoff",
            doc="max items taking the CPU path (-1 = adaptive crossover)",
            choices=_ladder(16, 64, 256, 1024) + (-1,),
            get=lambda: (
                -1 if svcs[0]._fixed_cutoff is None else svcs[0]._fixed_cutoff
            ),
            set=_set_cutoff,
            unit="items",
        ))
        mp = int(svcs[0]._max_pending)
        reg.register(Knob(
            name="verify.max_pending",
            doc="verify admission backlog cap before overload rejection",
            choices=_ladder(max(256, mp // 2), mp, mp * 2),
            get=lambda: svcs[0]._max_pending,
            set=_fanout(svcs, "_max_pending"),
            unit="items",
        ))

    try:
        from .consensus.qc import qc_lane

        lane = qc_lane()
    except Exception:  # qc stack unavailable: knobs absent, not fatal
        lane = None
    if lane is not None:
        cw_ms = round(lane._close_window * 1000.0, 3)

        def _set_cw(v: Any) -> None:
            lane._close_window = float(v) / 1000.0

        reg.register(Knob(
            name="qc.close_window_ms",
            doc="QC-lane batch close window (collect longer vs reply sooner)",
            choices=_ladder(0.5, 1.0, cw_ms, 4.0, 8.0),
            get=lambda: round(lane._close_window * 1000.0, 3),
            set=_set_cw,
            unit="ms",
        ))
        qb = int(lane._max_batch)
        reg.register(Knob(
            name="qc.max_batch",
            # same warmed-ceiling argument as verify.max_batch: the RLC
            # pairing batches never grow past what the lane already ran
            doc="QC-lane pairing batch cap (warmed ladder only)",
            choices=_ladder(max(16, qb // 4), max(16, qb // 2), qb),
            get=lambda: lane._max_batch,
            set=_fanout([lane], "_max_batch"),
            unit="certs",
        ))
    return reg


# ---------------------------------------------------------------------------
# rule catalogue
# ---------------------------------------------------------------------------

#: hysteresis / thresholds (module constants so tests can pin TP/TN
#: cases against the exact boundaries)
WIN_P99_STORM_MS = 300.0   # last-window p99 that reads as queue buildup
WIN_P99_FAST_MS = 150.0    # ...and the committee-is-fast band below it
STORM_SHED_FLOOR = 128.0   # shed/tick above max(2*wm, floor) = storm
RELAX_SERVED_RATIO = 0.8   # fresh inflow served fraction gating relax
CALM_TICKS = 3             # quiet ticks before the idle-trim rules act
PAD_WASTE_PCT = 40.0       # devledger pad-waste verdict threshold
PAD_OCCUPANCY = 0.5        # ...only while the device is underfilled
QUEUE_PRESSURE = 0.75      # verify pending / max_pending
CPU_SHARE = 0.5            # cpu-path item share that reads host-bound
GAP_OCCUPANCY = 0.2        # dispatch-gap verdict: starved device
GAP_DISPATCHES = 4         # ...fed by many small dispatches per tick
QC_PRESSURE = 0.5          # qc pending / max_pending


@dataclass(frozen=True)
class Rule:
    """One decision rule: a pure predicate over the flat signal view.

    ``needs`` lists exactly the view keys the predicate reads — the
    controller records that subset as the action's ``trigger``, which is
    what makes :func:`replay_ledger` possible: feeding the trigger back
    through ``fires`` must re-derive the decision."""

    name: str
    family: str
    knob: str
    direction: int
    needs: Tuple[str, ...]
    fires: Callable[[Dict[str, Any]], bool]

    def trigger(self, view: Dict[str, Any]) -> Dict[str, Any]:
        return {k: view.get(k, 0) for k in self.needs}


def _g(view: Dict[str, Any], key: str) -> float:
    try:
        return float(view.get(key, 0) or 0)
    except (TypeError, ValueError):
        return 0.0


#: priority-ordered catalogue: the FIRST firing rule whose step is not a
#: no-op acts this tick. Shrink-under-pressure rules outrank relax
#: rules — the controller must react to a storm before it optimizes an
#: idle committee.
RULES: Tuple[Rule, ...] = (
    # -- traffic family: storm cut vs drain relax ----------------------
    # The pair below splits on shed MAGNITUDE, so cut and relax are
    # mutually exclusive over any single view.  A storm sheds hundreds
    # of requests per tick (offered far above the watermark); benign
    # over-trim sheds a trickle.  Cutting hard during a storm converts
    # slow-drip retry chains into fast client timeouts and keeps every
    # ADMITTED request fast — fail-fast brownout, the point of the
    # shed plane.  Queue buildup (window p99 inflating, since the
    # primary's pending_requests drains into in-flight blocks
    # instantly and the real backlog lives in the WAN links'
    # serialization queues) also reads as storm.  Admission-gap
    # ratios are deliberately NOT used: a gap cannot distinguish
    # queue collapse (admit less) from over-shedding (admit more).
    Rule(
        name="storm_backlog", family="traffic",
        knob="replica.shed_watermark", direction=-1,
        needs=("shed_delta", "win_p99_ms", "backlog", "shed_watermark"),
        fires=lambda v: (
            _g(v, "shed_delta")
            > max(2.0 * _g(v, "shed_watermark"), STORM_SHED_FLOOR)
            or _g(v, "win_p99_ms") > WIN_P99_STORM_MS
            or _g(v, "backlog") > _g(v, "shed_watermark")
        ),
    ),
    Rule(
        # relax ONLY when fresh inflow is essentially fully served and
        # the committee is fast while sheds still happen: the watermark
        # sits below the benign sweep size and is trimming traffic the
        # committee could absorb.  The served-ratio term is the safety
        # interlock: a strangled post-storm backlog (fresh inflow NOT
        # served) must never trigger relaxation, because admitting a
        # patience-aged retry backlog converts invisible timeouts into
        # a guaranteed multi-second p99 tail.  Expired backlog washes
        # out within client patience; until then the debt stands.  A
        # calm committee never fires this — no shed, no reason to move.
        name="drain_relax", family="traffic",
        knob="replica.shed_watermark", direction=+1,
        needs=("shed_delta", "win_p99_ms", "offered_req_s",
               "accepted_req_s"),
        fires=lambda v: (
            _g(v, "shed_delta") > 0
            and _g(v, "win_p99_ms") < WIN_P99_FAST_MS
            and _g(v, "offered_req_s") > 0
            and _g(v, "accepted_req_s")
            >= RELAX_SERVED_RATIO * _g(v, "offered_req_s")
        ),
    ),
    # -- devledger family: pad waste / queue wait vs batch bucket ------
    Rule(
        name="pad_waste", family="devledger",
        knob="verify.max_batch", direction=-1,
        needs=("pad_waste_pct", "occupancy"),
        fires=lambda v: (
            _g(v, "pad_waste_pct") >= PAD_WASTE_PCT
            and _g(v, "occupancy") < PAD_OCCUPANCY
        ),
    ),
    Rule(
        name="queue_wait", family="devledger",
        knob="verify.max_batch", direction=+1,
        needs=("verify_queue_ratio", "queue_wait_delta_s"),
        fires=lambda v: (
            _g(v, "verify_queue_ratio") >= QUEUE_PRESSURE
            or _g(v, "queue_wait_delta_s") > 0.1
        ),
    ),
    # -- costmodel family: limiter verdicts (host-bound / dispatch gap)
    Rule(
        name="host_cpu_path", family="costmodel",
        knob="verify.cpu_cutoff", direction=-1,
        needs=("cpu_share", "verify_pending"),
        fires=lambda v: (
            _g(v, "cpu_share") >= CPU_SHARE and _g(v, "verify_pending") > 0
        ),
    ),
    Rule(
        name="dispatch_gap", family="costmodel",
        knob="verify.max_batch", direction=+1,
        needs=("occupancy", "dispatch_delta"),
        fires=lambda v: (
            0 < _g(v, "occupancy") < GAP_OCCUPANCY
            and _g(v, "dispatch_delta") >= GAP_DISPATCHES
        ),
    ),
    # -- qc family: pairing-lane pressure vs close window --------------
    Rule(
        name="qc_pressure", family="qc",
        knob="qc.close_window_ms", direction=+1,
        needs=("qc_pending_ratio", "qc_batch_headroom"),
        fires=lambda v: (
            _g(v, "qc_pending_ratio") >= QC_PRESSURE
            or (0 < _g(v, "qc_batch_headroom") <= 0.1)
        ),
    ),
    Rule(
        name="qc_idle", family="qc",
        knob="qc.close_window_ms", direction=-1,
        needs=("qc_pending", "calm_ticks"),
        fires=lambda v: (
            _g(v, "qc_pending") == 0 and _g(v, "calm_ticks") >= CALM_TICKS
        ),
    ),
    # -- spec family: rollback churn vs speculation depth --------------
    Rule(
        name="spec_churn", family="spec",
        knob="spec.max_depth", direction=-1,
        needs=("spec_rollback_delta",),
        fires=lambda v: _g(v, "spec_rollback_delta") > 0,
    ),
    Rule(
        name="spec_stable", family="spec",
        knob="spec.max_depth", direction=+1,
        needs=("spec_rollback_delta", "calm_ticks"),
        fires=lambda v: (
            _g(v, "spec_rollback_delta") == 0
            and _g(v, "calm_ticks") >= CALM_TICKS
        ),
    ),
)

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}


# ---------------------------------------------------------------------------
# decision ledger
# ---------------------------------------------------------------------------


class DecisionLedger:
    """Hash-chained JSONL decision ledger (``<id>.knobs.jsonl``).

    Same chain discipline as the audit plane's evidence ledger: every
    record carries ``prev`` (previous record's hash, GENESIS first) and
    ``h`` = sha256 of its own canonical body. Writes go through the
    telemetry ``_JsonlSink`` (line-flushed, degrade-don't-raise) —
    ``json.dumps(sort_keys=True)`` makes the bytes deterministic, so a
    seeded sim run reproduces the ledger byte for byte."""

    def __init__(self, path: str):
        import os

        from .telemetry import _JsonlSink

        self.path = path
        # a decision ledger is one run's chain: truncate any stale file
        # so the genesis record is always line 1 (the sink appends)
        try:
            if os.path.exists(path):
                os.remove(path)
        except OSError:
            pass
        self._sink = _JsonlSink(path)
        self._prev = GENESIS
        self.records = 0

    def append(self, kind: str, **fields: Any) -> str:
        rec: Dict[str, Any] = {
            "schema": LEDGER_SCHEMA_VERSION, "kind": kind,
            "t": round(clock.now(), 3),
        }
        rec.update(fields)
        rec["prev"] = self._prev
        rec["h"] = chain_hash(rec)
        self._sink.write(rec)
        self._prev = rec["h"]
        self.records += 1
        return rec["h"]

    def close(self) -> None:
        self._sink.close()


def parse_decision_ledger(path: str) -> Tuple[List[Dict[str, Any]], str]:
    """Parse + verify a decision ledger. Returns (records, error) —
    error is "" when every line parses, hashes, and chains."""
    records: List[Dict[str, Any]] = []
    prev = GENESIS
    try:
        with open(path) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    return records, f"line {i}: unparseable"
                if rec.get("schema") != LEDGER_SCHEMA_VERSION:
                    return records, f"line {i}: schema mismatch"
                if rec.get("prev") != prev:
                    return records, f"line {i}: chain break"
                if chain_hash(rec) != rec.get("h"):
                    return records, f"line {i}: hash mismatch"
                prev = rec["h"]
                records.append(rec)
    except OSError as e:
        return records, f"unreadable: {e}"
    return records, ""


def replay_ledger(
    records: Sequence[Dict[str, Any]],
    rules: Dict[str, Rule] = RULES_BY_NAME,
) -> Tuple[bool, str]:
    """Re-derive every action from the ledger alone (ISSUE 19 replay
    acceptance): each action's recorded trigger must re-fire its rule,
    the step direction must match the rule, per-knob old → new values
    must chain from the open record to the close record."""
    if not records or records[0].get("kind") != "open":
        return False, "no open record"
    values: Dict[str, Any] = dict(records[0].get("knobs", {}))
    for i, rec in enumerate(records):
        if rec.get("kind") != "action":
            continue
        rule = rules.get(rec.get("rule", ""))
        if rule is None:
            return False, f"record {i}: unknown rule {rec.get('rule')!r}"
        if rule.knob != rec.get("knob"):
            return False, f"record {i}: rule/knob mismatch"
        if rule.direction != rec.get("direction"):
            return False, f"record {i}: rule/direction mismatch"
        if not rule.fires(dict(rec.get("trigger", {}))):
            return False, f"record {i}: trigger does not re-fire {rule.name}"
        knob = rec["knob"]
        if knob in values and values[knob] != rec.get("old"):
            return False, (
                f"record {i}: {knob} old={rec.get('old')!r} breaks "
                f"continuity (expected {values[knob]!r})"
            )
        values[knob] = rec.get("new")
    last = records[-1]
    if last.get("kind") == "close":
        for knob, v in (last.get("knobs") or {}).items():
            if knob in values and values[knob] != v:
                return False, f"close: {knob} final {v!r} != replayed {values[knob]!r}"
    return True, ""


# ---------------------------------------------------------------------------
# the online controller
# ---------------------------------------------------------------------------


class KnobController:
    """Off-loop online tuner: one telemetry snapshot → one flat signal
    view → at most one knob step per tick, everything ledgered.

    ``snapshot_fn`` is any zero-arg callable returning a NodeTelemetry-
    shaped snapshot dict (sim passes the primary's registry; a live node
    could pass its StatusServer source). ``tick(snap)`` is synchronous
    and accepts an explicit snapshot, so unit tests drive rules without
    a running loop."""

    def __init__(
        self,
        registry: KnobRegistry,
        snapshot_fn: Callable[[], Dict[str, Any]],
        ledger_path: Optional[str] = None,
        *,
        interval: float = 0.5,
        profile: str = "default",
        cooldown_ticks: int = 2,
        effect_ticks: int = 2,
        osc_window_ticks: int = 6,
        freeze_ticks: int = 8,
        rules: Sequence[Rule] = RULES,
    ) -> None:
        self.registry = registry
        self.snapshot_fn = snapshot_fn
        self.interval = interval
        self.profile = profile
        self.cooldown_ticks = cooldown_ticks
        self.effect_ticks = effect_ticks
        self.osc_window_ticks = osc_window_ticks
        self.freeze_ticks = freeze_ticks
        self.rules = tuple(rules)
        self.ledger = DecisionLedger(ledger_path) if ledger_path else None
        self.actions = 0
        self.oscillations = 0
        self.ticks = 0
        self._task: Optional[asyncio.Task] = None
        self._prev_counters: Dict[str, float] = {}
        self._calm = 0
        # knob -> (tick, direction) of the last APPLIED action
        self._last_action: Dict[str, Tuple[int, int]] = {}
        self._frozen: Dict[str, int] = {}  # knob -> unfreeze tick
        # (due_tick, action_h, rule, knob, before-signals)
        self._effects: List[Tuple[int, str, str, str, Dict[str, Any]]] = []
        self._last_info: Optional[Dict[str, Any]] = None
        registry.posture_source = self.posture

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.ledger is not None:
            self.ledger.append(
                "open", profile=self.profile,
                interval=self.interval, knobs=self.registry.values(),
            )
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            await clock.sleep(self.interval)
            try:
                self.tick()
            except Exception:
                # the controller must never take down the run it tunes
                log.exception("controller tick failed")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._flush_effects(final=True)
        if self.ledger is not None:
            self.ledger.append(
                "close", tick=self.ticks, knobs=self.registry.values(),
                actions=self.actions, oscillations=self.oscillations,
            )
            self.ledger.close()

    # -- signal view -------------------------------------------------------

    def _view(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """Distill a node snapshot into the flat signal dict the rules
        read. Every source block is optional — absent surfaces read as
        0 and their rules simply never fire (degrade, don't raise)."""
        v: Dict[str, Any] = {}
        tr = snap.get("traffic") or {}
        v["offered_req_s"] = tr.get("offered_req_s", 0)
        v["accepted_req_s"] = tr.get("accepted_req_s", 0)
        v["worst_p99_ms"] = tr.get("worst_p99_ms", 0)
        # last CLOSED window's worst honest p99: the queue-buildup
        # signal (cumulative p99 above is too sticky to steer by)
        byz = {
            n for n, c in (tr.get("classes") or {}).items()
            if c.get("byzantine")
        }
        wt = tr.get("windows_tail") or []
        wc = (wt[-1].get("classes") or {}) if wt else {}
        v["win_p99_ms"] = max(
            (c.get("p99_ms", 0) for n, c in sorted(wc.items())
             if n not in byz),
            default=0,
        )
        rep = snap.get("replica") or {}
        v["backlog"] = (
            rep.get("pending_requests", 0) + rep.get("relay_buffer", 0)
        )
        met = rep.get("metrics") or {}
        ver = snap.get("verify") or {}
        dev = ver.get("device") or {}
        v["occupancy"] = dev.get("occupancy", 0)
        v["pad_waste_pct"] = dev.get("pad_waste_pct", 0)
        v["verify_pending"] = ver.get("pending_items", 0)
        vmp = ver.get("max_pending", 0) or 0
        v["verify_queue_ratio"] = (
            v["verify_pending"] / vmp if vmp else 0.0
        )
        qc = snap.get("qc_lane") or {}
        v["qc_pending"] = qc.get("pending", 0)
        qmp = qc.get("max_pending", 0) or 0
        v["qc_pending_ratio"] = v["qc_pending"] / qmp if qmp else 0.0
        if "qc.max_batch" in self.registry:
            qmb = float(self.registry.value("qc.max_batch"))
            bm = float(qc.get("batch_mean", 0) or 0)
            v["qc_batch_headroom"] = (
                max(0.0, (qmb - bm) / qmb) if qmb and bm else 0.0
            )
        else:
            v["qc_batch_headroom"] = 0.0
        # cumulative counters -> per-tick deltas
        cum = {
            "shed": float(met.get("messages_shed", 0) or 0),
            "rollbacks": float(met.get("spec_rollbacks", 0) or 0),
            "queue_wait_s": float(dev.get("queue_wait_s", 0) or 0),
            "dispatches": float(dev.get("dispatches", 0) or 0),
            "cpu_items": float(ver.get("cpu_pass_items", 0) or 0),
            "dev_items": float(ver.get("device_pass_items", 0) or 0),
        }
        prev = self._prev_counters
        d = {k: max(0.0, cum[k] - prev.get(k, 0.0)) for k in cum}
        self._prev_counters = cum
        v["shed_delta"] = d["shed"]
        v["spec_rollback_delta"] = d["rollbacks"]
        v["queue_wait_delta_s"] = round(d["queue_wait_s"], 4)
        v["dispatch_delta"] = d["dispatches"]
        items = d["cpu_items"] + d["dev_items"]
        v["cpu_share"] = round(d["cpu_items"] / items, 3) if items else 0.0
        # live knob values the rules compare signals against
        for name in ("replica.shed_watermark",):
            if name in self.registry:
                v["shed_watermark"] = self.registry.value(name)
        v["calm_ticks"] = self._calm
        return v

    def _effect_signals(self, view: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: view.get(k, 0)
            for k in ("worst_p99_ms", "accepted_req_s", "occupancy",
                      "qc_pending", "backlog")
        }

    # -- the tick ----------------------------------------------------------

    def tick(self, snap: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """One decision round; returns the fired rule's name (or None).
        Synchronous and snapshot-injectable for tests."""
        self.ticks += 1
        if snap is None:
            snap = self.snapshot_fn() or {}
        view = self._view(snap)
        self._flush_effects(view=view)
        fired: Optional[str] = None
        for rule in self.rules:
            if rule.knob not in self.registry:
                continue
            if not rule.fires(view):
                continue
            if self._frozen.get(rule.knob, 0) > self.ticks:
                continue
            last = self._last_action.get(rule.knob)
            if last is not None and self.ticks - last[0] < self.cooldown_ticks:
                continue
            old, new = self.registry.peek_step(rule.knob, rule.direction)
            if new == old:
                continue  # clamped at the ladder end: not a decision
            if last is not None and last[1] != rule.direction and (
                self.ticks - last[0] <= self.osc_window_ticks
            ):
                # oscillation guard: a reversal hot on the heels of the
                # opposite step means the two rules are fighting over
                # this knob — freeze it instead of flapping it
                self.oscillations += 1
                until = self.ticks + self.freeze_ticks
                self._frozen[rule.knob] = until
                if self.ledger is not None:
                    self.ledger.append(
                        "guard", tick=self.ticks, knob=rule.knob,
                        rule=rule.name, until_tick=until,
                        trigger=rule.trigger(view),
                    )
                fired = None
                break
            self.registry.step(rule.knob, rule.direction)
            self.actions += 1
            self._last_action[rule.knob] = (self.ticks, rule.direction)
            self._last_info = {
                "rule": rule.name, "knob": rule.knob, "old": old,
                "new": new, "tick": self.ticks, "t": round(clock.now(), 3),
            }
            if self.ledger is not None:
                h = self.ledger.append(
                    "action", tick=self.ticks, rule=rule.name,
                    family=rule.family, knob=rule.knob,
                    direction=rule.direction, old=old, new=new,
                    trigger=rule.trigger(view),
                )
                self._effects.append((
                    self.ticks + self.effect_ticks, h, rule.name,
                    rule.knob, self._effect_signals(view),
                ))
            fired = rule.name
            break
        # hysteresis state for the relax rules: a tick is calm when
        # admission is healthy and nothing was shed
        if (
            _g(view, "shed_delta") == 0
            and _g(view, "offered_req_s")
            <= 1.05 * max(_g(view, "accepted_req_s"), 1.0)
        ):
            self._calm += 1
        else:
            self._calm = 0
        return fired

    def _flush_effects(
        self, view: Optional[Dict[str, Any]] = None, final: bool = False
    ) -> None:
        if self.ledger is None:
            return
        due: List[Tuple[int, str, str, str, Dict[str, Any]]] = []
        keep: List[Tuple[int, str, str, str, Dict[str, Any]]] = []
        for e in self._effects:
            (due if final or e[0] <= self.ticks else keep).append(e)
        self._effects = keep
        after = self._effect_signals(view) if view is not None else {}
        for due_tick, h, rule, knob, before in due:
            self.ledger.append(
                "effect", tick=self.ticks, action_h=h, rule=rule,
                knob=knob, before=before, after=after,
            )

    # -- posture (pbft_top CTL column / knobs telemetry block) -------------

    def posture(self) -> Dict[str, Any]:
        frozen = {
            k: t for k, t in sorted(self._frozen.items()) if t > self.ticks
        }
        p: Dict[str, Any] = {
            "profile": self.profile,
            "tick": self.ticks,
            "actions": self.actions,
            "oscillations": self.oscillations,
            "guard": {"frozen": frozen},
        }
        if self._last_info is not None:
            p["last"] = dict(self._last_info)
            p["last_age_s"] = round(
                max(0.0, clock.now() - self._last_info["t"]), 3
            )
        return p

    def coverage(self) -> Dict[str, Any]:
        """Flat summary sim.py folds into scenario coverage/details."""
        return {
            "ticks": self.ticks,
            "actions": self.actions,
            "oscillations": self.oscillations,
            "ledger_records": self.ledger.records if self.ledger else 0,
        }
