"""Client binary: ``python -m simple_pbft_tpu.client_cli``.

Parity target: the reference's client.go — which fire-and-forgets ONE
hard-coded request at the primary and exits without reading any reply
(client.go:27-34; its author's top gap, 需要改进的地方.md:3-9). This
client submits operations, waits for f+1 matching replies via the client
library, retries/rebroadcasts on timeout, and reports latency stats.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import time

from . import deploy
from .client import Client
from .node import make_transport


async def run_client(args) -> None:
    dep = deploy.load(os.path.join(args.deploy_dir, "committee.json"))
    seed = deploy.read_seed(args.deploy_dir, args.id)
    transport = make_transport(args.transport, args.id, dep)
    await transport.start()
    client = Client(
        client_id=args.id,
        cfg=dep.cfg,
        seed=seed,
        transport=transport,
        request_timeout=args.timeout,
    )
    client.start()

    ops = args.op or []
    if args.load:
        ops = [f"put k{i} v{i}" for i in range(args.load)]
    latencies = []
    results = []
    t_start = time.perf_counter()
    inflight = args.concurrency

    async def submit_one(op):
        t0 = time.perf_counter()
        res = await client.submit(op, retries=args.retries)
        latencies.append(time.perf_counter() - t0)
        results.append((op, res))

    for start in range(0, len(ops), inflight):
        await asyncio.gather(*(submit_one(op) for op in ops[start : start + inflight]))
    elapsed = time.perf_counter() - t_start

    for op, res in results[: args.print_results]:
        print(f"{op!r} -> {res!r}")
    if latencies:
        lat_sorted = sorted(latencies)
        print(
            json.dumps(
                {
                    "ops": len(latencies),
                    "elapsed_s": round(elapsed, 4),
                    "throughput_ops_per_s": round(len(latencies) / elapsed, 2),
                    "latency_p50_ms": round(lat_sorted[len(lat_sorted) // 2] * 1e3, 2),
                    "latency_p99_ms": round(
                        lat_sorted[int(len(lat_sorted) * 0.99)] * 1e3, 2
                    ),
                }
            )
        )
    await client.stop()
    await transport.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description="simple_pbft_tpu client")
    ap.add_argument("--id", default="c0", help="client id (must be in the deployment)")
    ap.add_argument("--deploy-dir", required=True)
    ap.add_argument(
        "--op", action="append", help="operation to submit (repeatable)"
    )
    ap.add_argument("--load", type=int, default=0, help="submit N generated puts")
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--timeout", type=float, default=1.0)
    ap.add_argument("--retries", type=int, default=5)
    ap.add_argument("--print-results", type=int, default=10)
    ap.add_argument("--transport", default="tcp", choices=["tcp", "grpc"])
    ap.add_argument("--log-level", default="WARNING")
    args = ap.parse_args()
    logging.basicConfig(level=args.log_level)
    if not args.op and not args.load:
        ap.error("need --op or --load")
    asyncio.run(run_client(args))


if __name__ == "__main__":
    main()
