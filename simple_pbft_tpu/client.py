"""Client library: submit requests, collect f+1 matching replies.

The reference client (client.go:12-34) fire-and-forgets one request at the
primary and exits — no reply collection, no retry, no f+1 matching; all
called out in its author's gap list (需要改进的地方.md:3-9). This client:

- signs requests (client identities have keys like replicas);
- sends to the current primary, rebroadcasts to ALL replicas on timeout
  (the PBFT liveness path that eventually triggers a view change);
- waits for f+1 replies with matching (timestamp, result) before
  accepting — f+1 guarantees at least one honest replica's word.
"""

from __future__ import annotations

import asyncio
import itertools
import random
from collections import OrderedDict, defaultdict, deque
from typing import Dict, Optional, Tuple

from . import clock, spans
from .config import CommitteeConfig, config_from_doc
from .crypto.signer import Signer
from .crypto.verifier import BatchItem, Verifier, best_cpu_verifier
from .messages import ConfigFetch, ConfigReply, Message, Reply, Request
from .transport.base import Transport


class SupersededError(Exception):
    """f+1 replicas answered with Reply.superseded=1: the request's
    timestamp fell under a folded checkpoint watermark and the operation
    was NOT applied by this submission. Whether to resubmit is the
    application's call — the same answer is given for a request that DID
    execute long ago but whose cached reply was folded away, so a blind
    automatic retry could apply a non-idempotent operation twice."""


class Client:
    def __init__(
        self,
        client_id: str,
        cfg: CommitteeConfig,
        seed: bytes,
        transport: Transport,
        verifier: Optional[Verifier] = None,
        request_timeout: float = 1.0,
        hedge: int = 0,
        backoff_factor: float = 1.6,
        backoff_cap: float = 0.0,
        jitter: float = 0.1,
    ) -> None:
        self.id = client_id
        self.cfg = cfg
        self.signer = Signer(client_id, seed)
        self.transport = transport
        self.verifier = verifier if verifier is not None else best_cpu_verifier()
        self.request_timeout = request_timeout
        # Retry policy (ISSUE 1): attempt k waits request_timeout *
        # backoff_factor**k (capped), +/- jitter fraction. Exponential
        # backoff keeps a shedding committee from being re-flooded at a
        # fixed cadence by every starving client at once (the r5 chaos
        # cell's retry waves); jitter decorrelates the waves themselves.
        # backoff_cap <= 0 means 8x the CURRENT request_timeout (benches
        # mutate request_timeout after construction). factor 1.0 restores
        # the old fixed-interval behavior exactly.
        self.backoff_factor = backoff_factor
        self.backoff_cap = backoff_cap
        self.jitter = jitter
        # deterministic per-client jitter stream: fault-injection runs
        # replay identically for a given (client set, seed) pair
        self._rng = random.Random(int.from_bytes(seed[:8], "big") ^ 0x5BD1)
        # observability: retransmissions sent, requests that only
        # completed after at least one retry (the "shed then recovered"
        # signature — distinguishes overload shedding from real loss)
        self.metrics: Dict[str, int] = defaultdict(int)
        # Hedged first send: also deliver each request to `hedge` backups
        # (rotating), who relay it to the primary and arm their failover
        # timers on first receipt. Kills the worst-case failover tail
        # where a crashing primary was the ONLY replica that knew about
        # the in-flight batch — recovery then waits a full client
        # request_timeout before anyone even suspects. Costs hedge+1
        # sends per request instead of 1 (still O(1), not a broadcast).
        self.hedge = hedge
        # per-replica MAC keys: replies carry an HMAC tag instead of a
        # signature when both ends publish kx keys (crypto/mac.py)
        from .crypto import mac as mac_mod

        self._mac = mac_mod.MacBank(seed, cfg.kx_pubkeys)
        # microsecond wall-clock start via the clock seam (virtual and
        # deterministic under simulation) (Castro-Liskov §2.4: client
        # timestamps are monotonic ACROSS restarts — a counter from 1
        # would leave a restarted client below the replicas' per-client
        # dedup watermark, every request silently dropped as a replay;
        # found by the real-process failover test). Known limitation,
        # shared with every clock-derived request-id scheme: a host clock
        # stepped BACKWARDS across a restart re-enters the replay window
        # until wall-clock passes the old watermark; deploy clients with
        # slewing (not stepping) time sync, or persist the last timestamp.
        self._ts = itertools.count(clock.timestamp_us())
        self._waiters: Dict[int, asyncio.Future] = {}
        # per-ts replies: sender -> (result, superseded, spec). One slot
        # per replica (ISSUE 15 reply accounting): a replica upgrading
        # its speculative reply to final overwrites its own slot — never
        # a second count toward either quorum — and the stricter (final)
        # mark wins: a late speculative reply never downgrades a
        # recorded final one.
        self._replies: Dict[int, Dict[str, tuple]] = defaultdict(dict)
        # how each accepted ts resolved ("spec" fast path or "final"),
        # consumed by submit() for the latency split benches record
        self._accept_kind: Dict[int, str] = {}
        self._submit_t0: Dict[int, float] = {}
        # speculative answers awaiting final-commit confirmation:
        # ts -> {result, t0, senders}. The fast answer already resolved
        # the submit; f+1 matching FINAL replies upgrade it to confirmed
        # (metrics final_confirms + the confirm-latency sample). Bounded.
        self._confirming: "OrderedDict[int, dict]" = OrderedDict()
        self.CONFIRMING_MAX = 8192
        # (latency_s, "spec"|"final") per accepted request, and the
        # submit->f+1-final confirmation latencies — the bench ledger's
        # p50_spec_latency_ms / p50_final_latency_ms sources
        self.accept_latencies: deque = deque(maxlen=1 << 16)
        self.confirm_latencies: deque = deque(maxlen=1 << 16)
        # wire bytes of in-flight requests, for the mixed-split early
        # rebroadcast below (submit() owns the normal retransmission)
        self._inflight_raw: Dict[int, bytes] = {}
        self._mixed_retry_done: set = set()
        self._bg_tasks: set = set()
        self._task: Optional[asyncio.Task] = None
        self.view_hint = 0  # latest view seen in replies
        # committee-epoch tracking (ISSUE 7): after a live
        # reconfiguration this client's address book (cfg.replica_ids)
        # is stale — any reply carrying a higher epoch triggers a
        # ConfigFetch round, and f+1 matching signed ConfigReplies from
        # replicas we ALREADY know rebuild the book (one lying replica
        # cannot steer us into a fake committee)
        self._seed = seed
        self.epoch = cfg.epoch
        # sender -> its latest (epoch, config-bytes) claim. Keyed by
        # SENDER, not by claim: each known replica controls exactly one
        # slot, so a hostile replica signing arbitrarily many distinct
        # configs only ever overwrites itself — no eviction policy to
        # game, bounded by the committee size by construction
        self._config_votes: Dict[str, tuple] = {}
        self._config_fetch_at = 0.0
        # sampled request tracing (telemetry.RequestTracer), attached
        # after construction; the client stamps submit/retransmit/
        # accepted so a trace joins the replica-side phases end to end
        self.tracer = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._recv_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _recv_loop(self) -> None:
        while True:
            raw = await self.transport.recv()
            try:
                msg = Message.from_wire(raw)
            except ValueError:
                continue
            if isinstance(msg, ConfigReply):
                self._on_config_reply(msg)
                continue
            if not isinstance(msg, Reply) or msg.client_id != self.id:
                continue
            if msg.sender not in self.cfg.replica_ids:
                continue  # only replicas may answer; f+1 matching assumes it
            fut = self._waiters.get(msg.timestamp)
            confirming = msg.timestamp in self._confirming
            if (fut is None or fut.done()) and not confirming:
                # nobody is waiting on this timestamp (late replies after
                # f+1 matched, or stale retransmissions): skip the
                # signature check — at committee size n the client
                # otherwise pays n-(f+1) wasted verifies per request.
                # (A speculatively-accepted ts awaiting final-commit
                # confirmation still verifies: the f+1 final quorum the
                # confirmation trusts must be signature-checked.)
                continue
            if self.cfg.verify_signatures:
                if msg.mac:
                    # point-to-point fast path: HMAC under the shared key
                    # with the claimed sender (crypto/mac.py)
                    from .crypto import mac as mac_mod

                    key = self._mac.key_for(msg.sender)
                    if key is None or not mac_mod.tag_valid(
                        key, msg.signing_payload(), msg.mac
                    ):
                        continue
                else:
                    pub = self.cfg.pubkey(msg.sender)
                    if pub is None or not msg.sig:
                        continue
                    try:
                        sig = bytes.fromhex(msg.sig)
                    except ValueError:
                        continue
                    ok = self.verifier.verify_batch(
                        [
                            BatchItem(
                                pubkey=pub, msg=msg.signing_payload(), sig=sig
                            )
                        ]
                    )
                    if not ok[0]:
                        continue
            if fut is None or fut.done():
                self._on_confirm(msg)
            else:
                self._on_reply(msg)

    def _on_reply(self, msg: Reply) -> None:
        ts = msg.timestamp
        fut = self._waiters.get(ts)
        if fut is None or fut.done():
            return
        self.view_hint = max(self.view_hint, msg.view)
        if msg.epoch > self.epoch:
            # authenticated reply from a later committee epoch: our
            # address book is stale — re-resolve instead of timing out
            # against removed replicas (the reply itself still counts
            # toward f+1 below; epoch is a hint, not part of matching)
            self._maybe_refresh_config(msg.epoch)
        # f+1 matching is on the RESULT only (Castro-Liskov §2.4): honest
        # replicas may execute the same request in different views when a
        # failover re-proposes it, and their replies still agree on the
        # outcome — matching on (result, view) would deadlock exactly
        # when a view change lands mid-request. The view rides along
        # purely as the primary hint above.
        spec = bool(getattr(msg, "spec", 0))
        prev = self._replies[ts].get(msg.sender)
        if prev is not None and not prev[2] and spec:
            # reply accounting (ISSUE 15): this replica already answered
            # FINAL — a late speculative copy must neither double-count
            # nor downgrade the recorded mark
            return
        self._replies[ts][msg.sender] = (
            msg.result, bool(msg.superseded), spec, msg.seq, msg.view,
        )
        counts_final: Dict[tuple, int] = defaultdict(int)
        counts_slot: Dict[tuple, int] = defaultdict(int)
        for result, superseded, sp, seq, view in self._replies[ts].values():
            counts_slot[(result, superseded, seq, view)] += 1
            if not sp:
                counts_final[(result, superseded)] += 1
        # final answer: f+1 matching non-speculative replies (classic —
        # matching ignores seq/view: honest replicas execute the same
        # request at the same agreed slot, and the result alone is what
        # f+1 vouches for)
        for key, cnt in counts_final.items():
            if cnt >= self.cfg.weak_quorum:
                self._resolve(ts, fut, key, "final")
                return
        # speculative fast answer: 2f+1 matching marks of ANY strength
        # (a final reply subsumes a speculative one from the same
        # replica) — matched on (result, superseded, SEQ, VIEW). The
        # full slot identity is part of the key because the safety
        # argument is per prepare-certificate: 2f+1 speculators of one
        # (view, seq) are 2f+1 preparers of ONE digest there (two
        # conflicting 2f+1 prepare quorums at the same (view, seq) need
        # > f double-voters), and by quorum intersection no later view
        # can install a different block at that seq. Marks for the same
        # request speculated at different seqs — or at the same seq
        # under different views' re-proposals, each with <= f honest
        # preparers — must never pool into a fake quorum.
        for (result, superseded, _seq, _view), cnt in counts_slot.items():
            if cnt >= self.cfg.quorum:
                self._resolve(ts, fut, (result, superseded), "spec")
                return
        # Mixed superseded/real split with no quorum: a checkpoint fold
        # raced our retransmission — replicas that folded answer
        # superseded=1 while laggards re-send the cached real reply, and
        # with designated repliers neither pair may reach f+1 until the
        # fold stabilizes committee-wide (replica._send_superseded has
        # the server-side account). Stabilization needs no help from us,
        # but the answer does: nudge with one early rebroadcast (folded
        # replicas re-answer superseded from durable state) instead of
        # sitting out the full request_timeout.
        flags = {s for _, s, _sp, _seq, _v in self._replies[ts].values()}
        if len(flags) == 2 and ts not in self._mixed_retry_done:
            self._mixed_retry_done.add(ts)
            raw = self._inflight_raw.get(ts)
            if raw is not None:
                loop = asyncio.get_running_loop()
                backoff = min(0.25, self.request_timeout / 4)
                loop.call_later(backoff, self._fire_mixed_retry, ts, raw)

    def _resolve(self, ts: int, fut: asyncio.Future, key: Tuple[str, bool],
                 kind: str) -> None:
        """A quorum formed for ``key`` = (result, superseded): answer the
        waiter. A speculative acceptance additionally keeps collecting
        FINAL replies for the same ts (the final-commit confirmation the
        fast path must retain — satellite/PoE contract)."""
        result, superseded = key
        self._accept_kind[ts] = kind
        if kind == "spec" and not superseded:
            self.metrics["spec_accepted"] += 1
            senders = {
                s
                for s, (res, sup, sp, _seq, _v) in self._replies[ts].items()
                if not sp and (res, sup) == key
            }
            while len(self._confirming) >= self.CONFIRMING_MAX:
                self._confirming.popitem(last=False)
            self._confirming[ts] = {
                "result": result,
                "t0": self._submit_t0.get(ts, clock.now()),
                "senders": senders,
                "contradicting": set(),
            }
        if superseded:
            fut.set_exception(SupersededError())
        else:
            fut.set_result(result)

    def _on_confirm(self, msg: Reply) -> None:
        """A signature-verified reply for a speculatively-accepted ts:
        count FINAL copies toward the f+1 confirmation quorum."""
        ent = self._confirming.get(msg.timestamp)
        if ent is None or getattr(msg, "spec", 0) or msg.superseded:
            return
        if msg.result != ent["result"]:
            # A single contradicting final can be one byzantine replica
            # (well within f) — it must neither fire the alarm nor
            # destroy confirmation tracking. Only f+1 DISTINCT
            # contradictors prove the COMMITTEE contradicted the 2f+1
            # speculative quorum — impossible under quorum intersection
            # unless > f replicas are faulty; surface THAT loudly.
            ent["contradicting"].add(msg.sender)
            if len(ent["contradicting"]) >= self.cfg.weak_quorum:
                self.metrics["spec_final_mismatch"] += 1
                del self._confirming[msg.timestamp]
            return
        ent["senders"].add(msg.sender)
        if len(ent["senders"]) >= self.cfg.weak_quorum:
            self.metrics["final_confirms"] += 1
            self.confirm_latencies.append(clock.now() - ent["t0"])
            del self._confirming[msg.timestamp]

    def _bg(self, coro) -> None:
        """Launch a fire-and-forget send: hold the task reference (GC can
        cancel unreferenced tasks) and consume its exception (a transport
        closed during a backoff must not surface as 'exception was never
        retrieved')."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)

        def _consume(t: asyncio.Task) -> None:
            self._bg_tasks.discard(t)
            if not t.cancelled():
                t.exception()

        task.add_done_callback(_consume)

    def _fire_mixed_retry(self, ts: int, raw: bytes) -> None:
        if ts not in self._waiters:
            return
        self._bg(self.transport.broadcast(raw, self.cfg.replica_ids))

    # -- committee re-resolution (ISSUE 7: live reconfiguration) ---------

    def _maybe_refresh_config(self, epoch_hint: int) -> None:
        """Fire one ConfigFetch round at the replicas we still know
        (survivors answer — membership changes are bounded per epoch, so
        f+1 of our current book are members of the new committee).
        Rate-limited: every reply from the new epoch would otherwise
        re-fire the round."""
        now = clock.now()
        if now - self._config_fetch_at < 0.5:
            return
        self._config_fetch_at = now
        self.metrics["config_fetches"] += 1
        cf = ConfigFetch(epoch=epoch_hint)
        self.signer.sign_msg(cf)
        self._bg(self.transport.broadcast(cf.to_wire(), self.cfg.replica_ids))

    def _on_config_reply(self, msg: ConfigReply) -> None:
        """Count signed configuration copies; adopt on f+1 matching
        (epoch, config bytes) from DISTINCT known replicas. Verification
        uses keys we already hold — a reply from an unknown sender (or a
        forged config under a known key) never counts."""
        if msg.sender not in self.cfg.replica_ids or msg.epoch <= self.epoch:
            return
        if self.cfg.verify_signatures:
            pub = self.cfg.pubkey(msg.sender)
            if pub is None or not msg.sig:
                return
            try:
                sig = bytes.fromhex(msg.sig)
            except ValueError:
                return
            ok = self.verifier.verify_batch(
                [BatchItem(pubkey=pub, msg=msg.signing_payload(), sig=sig)]
            )
            if not ok[0]:
                return
        key = (msg.epoch, msg.config)
        self._config_votes[msg.sender] = key
        if (
            sum(1 for v in self._config_votes.values() if v == key)
            < self.cfg.weak_quorum
        ):
            return
        import json

        try:
            new_cfg = config_from_doc(self.cfg, json.loads(msg.config))
        except ValueError:
            return
        if new_cfg.epoch != msg.epoch:
            return
        self._adopt_config(new_cfg)

    def _adopt_config(self, new_cfg: CommitteeConfig) -> None:
        from .crypto import mac as mac_mod

        self.cfg = new_cfg
        self.epoch = new_cfg.epoch
        self._config_votes.clear()
        # reply MACs key on the replica set: rebuild for the new members
        self._mac = mac_mod.MacBank(self._seed, new_cfg.kx_pubkeys)
        if new_cfg.addrs:
            # socket transports route by peer book — learn the added
            # members' addresses or retransmits to a new primary that
            # joined after our boot book was built silently vanish
            from .transport.base import update_peer_book

            update_peer_book(self.transport, new_cfg.addrs)
        self.metrics["config_refreshes"] += 1
        # chase the new committee NOW: in-flight requests head straight
        # for the new primary instead of waiting out a timeout against a
        # replica that may no longer exist
        primary = self.cfg.primary(self.view_hint)
        resent = 0
        for ts, raw in list(self._inflight_raw.items()):
            if ts in self._waiters:
                self._bg(self.transport.send(primary, raw))
                resent += 1
        if resent:
            self.metrics["config_retransmits"] += resent

    def retries_for_patience(self, patience: float) -> int:
        """Smallest retry count whose CUMULATIVE wait (backoff included,
        jitter ignored) covers ``patience`` seconds. Benches size client
        patience in wall-clock terms ("must outlast a 75 s failover
        stall"); under exponential backoff a fixed retry COUNT would
        silently mean minutes, not the intended budget."""
        total, k = 0.0, 0
        cap = self.backoff_cap if self.backoff_cap > 0 else (
            8.0 * self.request_timeout
        )
        while total < patience and k < 1000:
            total += min(cap, self.request_timeout * (self.backoff_factor ** k))
            k += 1
        return max(1, k - 1)  # k attempts = k-1 retries

    def _attempt_timeout(self, attempt: int) -> float:
        """Wait budget for retry ``attempt`` (0-based): exponential
        backoff from request_timeout, capped, jittered. Monotone in
        expectation — a request never waits LESS than the base timeout
        minus jitter, so the f+1 collection window is never starved."""
        cap = self.backoff_cap if self.backoff_cap > 0 else (
            8.0 * self.request_timeout
        )
        t = min(cap, self.request_timeout * (self.backoff_factor ** attempt))
        if self.jitter > 0:
            t *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return t

    async def submit(self, operation: str, retries: int = 3) -> str:
        """Submit one operation; return the f+1-matched result.

        Retransmissions are IDEMPOTENT by construction: every retry
        re-sends the same signed (client_id, timestamp) request bytes, so
        replicas dedup it server-side (cached-reply resend, never a
        second execution) — a request shed under overload recovers on a
        later attempt instead of becoming a timeout. Retries back off
        exponentially with jitter (see __init__).

        Raises SupersededError if the committee reports the request's
        slot was folded under a checkpoint watermark (the op was not
        applied by this call — see the exception's docstring before
        resubmitting non-idempotent operations)."""
        ts = next(self._ts)
        # completion floor: everything below the oldest still-outstanding
        # submit is answered and will never be retransmitted (see
        # messages.Request.ack — this is what lets replicas fold replay
        # state without NACKing a pipelined sibling still in flight)
        floor = min(self._waiters, default=ts) - 1
        req = Request(
            client_id=self.id, timestamp=ts, operation=operation, ack=floor
        )
        self.signer.sign_msg(req)
        raw = req.to_wire()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters[ts] = fut
        self._inflight_raw[ts] = raw
        tracer = self.tracer
        rid = tracer.rid_if_sampled(self.id, ts) if tracer is not None else None
        traced = rid is not None
        if traced:
            tracer.emit("submit", rid, op_bytes=len(operation))
        t_sub = clock.now()
        self._submit_t0[ts] = t_sub  # confirmation latency anchors here
        try:
            # first attempt: primary (+ hedged backups); afterwards:
            # broadcast (classic PBFT retransmission — backups forward to
            # the primary and arm view-change timers)
            primary = self.cfg.primary(self.view_hint)
            await self.transport.send(primary, raw)
            ids = self.cfg.replica_ids
            if self.hedge and len(ids) > 1:
                start = ids.index(primary) if primary in ids else 0
                for k in range(self.hedge):
                    # rotate targets per request so hedged load spreads
                    rid = ids[(start + 1 + (ts + k) % (len(ids) - 1)) % len(ids)]
                    if rid != primary:
                        await self.transport.send(rid, raw)
            for attempt in range(retries + 1):
                try:
                    # a SupersededError set on the future raises here
                    result = await asyncio.wait_for(
                        asyncio.shield(fut), self._attempt_timeout(attempt)
                    )
                    if attempt:
                        self.metrics["recovered_after_retry"] += 1
                    kind = self._accept_kind.pop(ts, "final")
                    self.accept_latencies.append(
                        (clock.now() - t_sub, kind)
                    )
                    if traced:
                        tracer.emit("accepted", rid, attempts=attempt + 1)
                    # submit -> f+1 accepted: the client's view of the
                    # whole pipeline — the number every replica-side
                    # span decomposition must add up toward. File lines
                    # only for SAMPLED requests (volume bound).
                    spans.record(
                        spans.CLIENT_E2E,
                        clock.now() - t_sub,
                        node=self.id, rid=rid, persist=traced,
                    )
                    return result
                except asyncio.TimeoutError:
                    if attempt == retries:
                        self.metrics["request_timeouts"] += 1
                        if traced:
                            tracer.emit("timeout", rid, attempts=attempt + 1)
                        raise
                    self.metrics["retransmissions"] += 1
                    if traced:
                        tracer.emit("retransmit", rid, attempts=attempt + 1)
                    await self.transport.broadcast(raw, self.cfg.replica_ids)
            raise asyncio.TimeoutError  # pragma: no cover
        finally:
            self._waiters.pop(ts, None)
            self._replies.pop(ts, None)
            self._inflight_raw.pop(ts, None)
            self._mixed_retry_done.discard(ts)
            self._accept_kind.pop(ts, None)
            self._submit_t0.pop(ts, None)
