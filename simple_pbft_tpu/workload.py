# pbftlint: deterministic-module
"""Million-user traffic observatory: the open-loop workload plane (ISSUE 17).

The north star talks about "heavy traffic from millions of users"; every
instrument before this PR only ever watched a handful of closed-loop
test clients. This module is the missing traffic plane: a seeded,
deterministic, OPEN-LOOP arrival process driving 10^5-10^6 *virtual*
clients over the deterministic simulation runtime's virtual clock
(simple_pbft_tpu/sim.py), multiplexed over a BOUNDED pool of real
transport endpoints — never one coroutine (or one object) per client,
so a million-client day fits in one CI job with bounded memory.

Design, in one breath:

- A :class:`WorkloadSpec` names client CLASSES (interactive / bulk /
  byzantine by convention; any names work) with per-class base rates,
  virtual-client populations, read/write mix, payload sizes and hotspot
  skew.
- :class:`ArrivalGen` turns (spec, workload events, seed) into per-
  window aggregate offered counts plus a BOUNDED materialized arrival
  batch — open-loop semantics with a finite load-generator fleet:
  offered demand is accounted exactly (fractional-rate carry
  accumulators, diurnal modulation, burst/remix/flood/storm events),
  while only up to the wire budget is materialized onto the transport
  pool; the overflow is *ingress shed*, counted per class. Virtual-
  client identity is O(1): a hotspot prefix plus a round-robin cold
  pointer give exact distinct-clients-touched accounting with two
  integers per class.
- :class:`TrafficPlane` fires the materialized arrivals in CLUSTERED
  batches at discrete virtual instants (a flash crowd is simultaneous
  arrivals, and under a virtual clock only same-instant traffic can
  queue — smeared arrivals are infinitely-fast-served), drives them
  through the pool clients' ordinary ``submit()`` path, re-enqueues
  timed-out arrivals into the next cluster (synchronized retry waves —
  the correlated-retry-storm shape), and sends byzantine flood frames
  (well-formed requests with garbage signatures in signed committees:
  they reach the verify-admission seam and die as ``bad_sig``;
  undecodable frames in unsigned committees: they die at decode).
- :class:`TrafficStats` keeps per-class cumulative and per-window
  counters plus bounded latency reservoirs, and exposes the ``traffic``
  telemetry block that rides NodeTelemetry snapshots and flight frames
  (pbft_top's LOAD column, tools/traffic_report.py).
- :func:`judge_slo` turns a finished run's stats into machine-checkable
  SLO verdicts beyond safety: bounded p99 per honest class, no starved
  honest class (a FAIRNESS oracle — load-shape invariant, judged
  relative to the best-served class, so honest graceful degradation
  under any offered load passes), and shed-before-collapse (overload
  must surface as shed counters, never as silently queued traffic).

Everything is a pure function of (spec, events, seed): same inputs,
byte-identical arrival stream (:func:`arrival_digest`), byte-identical
sim trace fingerprint. Workload events (burst / remix / retry_storm /
byz_flood) ride FaultSchedule (schema fault-schedule-v3) so one replay
tuple carries faults AND load shape, and sim_explore mutates both.
"""

from __future__ import annotations

import hashlib
import math
import random
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import clock
from .messages import Request

# The authoritative workload-event registry: kind -> one-line
# description. Mirrors faults.KIND_REGISTRY (same drift rule: everything
# naming the kind set derives from this dict). Events target a CLASS
# name (``target``), not a replica id.
WORKLOAD_KIND_REGISTRY: Dict[str, str] = {
    "burst": (
        "flash crowd: multiply the target class's offered rate by "
        "`magnitude` for `duration` seconds ('' targets every honest "
        "class)"
    ),
    "remix": (
        "class remix: move `magnitude` fraction of the source class's "
        "base rate to the destination class for `duration` seconds "
        "(`spec` is 'SRC>DST')"
    ),
    "retry_storm": (
        "correlated retry storm: for `duration` seconds timed-out "
        "arrivals re-enqueue with `magnitude`x the normal attempt "
        "budget, re-fired in synchronized clusters"
    ),
    "byz_flood": (
        "byzantine client flood: the byzantine class offers an EXTRA "
        "`magnitude` x (sum of honest base rates) of bad-signature "
        "requests for `duration` seconds (verify-admission pressure)"
    ),
}

WORKLOAD_KINDS = tuple(WORKLOAD_KIND_REGISTRY)


def workload_kind_table() -> str:
    width = max(len(k) for k in WORKLOAD_KIND_REGISTRY)
    return "\n".join(
        f"- {k.ljust(width)} : {d}" for k, d in WORKLOAD_KIND_REGISTRY.items()
    )


@dataclass(frozen=True)
class WorkloadEvent:
    """One scheduled load-shape change. Field-compatible with
    faults.FaultEvent so schedule mutation/minimization treat fault and
    workload events uniformly; ``target`` names a client CLASS."""

    t: float
    kind: str
    target: str = ""
    duration: float = 0.0
    magnitude: float = 0.0
    spec: str = ""  # remix routing ("bulk>interactive")

    def to_dict(self) -> dict:
        d = {
            "t": round(self.t, 3),
            "kind": self.kind,
            "target": self.target,
            "duration": round(self.duration, 3),
            "magnitude": round(self.magnitude, 4),
        }
        if self.spec:
            d["spec"] = self.spec
        return d


def workload_event_from_dict(e: dict) -> WorkloadEvent:
    kind = e.get("kind", "")
    if kind not in WORKLOAD_KIND_REGISTRY:
        raise ValueError(
            f"cannot replay: unknown workload kind {kind!r} "
            f"(known: {sorted(WORKLOAD_KIND_REGISTRY)}); the schedule was "
            "recorded under a different workload-kind registry"
        )
    return WorkloadEvent(
        t=float(e["t"]),
        kind=kind,
        target=str(e.get("target", "")),
        duration=float(e.get("duration", 0.0)),
        magnitude=float(e.get("magnitude", 0.0)),
        spec=str(e.get("spec", "")),
    )


# ---------------------------------------------------------------------------
# workload specification
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ClientClass:
    """One traffic class: a virtual-client population with a base
    offered rate. ``hot_clients``/``hot_fraction`` give hotspot skew
    (that many low-id clients soak that fraction of arrivals — the
    zipf-head shape without per-client state); ``op_bytes`` pads write
    payloads (bulk traffic is BIG, which is what the planted shed-bias
    defect discriminates on)."""

    name: str
    rate: float            # base offered req/s, plane-wide
    clients: int           # virtual-client population
    read_fraction: float = 0.0
    op_bytes: int = 0
    byzantine: bool = False
    hot_clients: int = 0
    hot_fraction: float = 0.0

    def to_doc(self) -> Dict[str, Any]:
        return {
            "name": self.name, "rate": self.rate, "clients": self.clients,
            "read_fraction": self.read_fraction, "op_bytes": self.op_bytes,
            "byzantine": self.byzantine, "hot_clients": self.hot_clients,
            "hot_fraction": self.hot_fraction,
        }


@dataclass(frozen=True)
class WorkloadSpec:
    """The whole plane's shape. ``pool`` real clients multiplex every
    virtual arrival; ``max_inflight`` bounds concurrently-awaited
    submissions (the plane's memory bound); ``wire_per_window`` bounds
    how many arrivals per accounting window are materialized onto the
    wire (the rest is exact ingress-shed accounting); ``clusters``
    arrivals-per-window instants model simultaneity (see module doc).
    ``shed_watermark`` scales the REPLICA-side shed plane to sim scale
    (0 = the replica default, which a sim-sized committee never
    reaches)."""

    classes: Tuple[ClientClass, ...]
    window: float = 0.5
    pool: int = 4
    max_inflight: int = 512
    wire_per_window: int = 96
    flood_per_window: int = 192
    clusters: int = 2
    diurnal_amplitude: float = 0.0
    diurnal_period: float = 0.0  # 0 = no diurnal modulation
    patience: float = 4.0        # per-arrival end-to-end retry budget (s)
    shed_watermark: int = 0

    def honest(self) -> Tuple[ClientClass, ...]:
        return tuple(c for c in self.classes if not c.byzantine)

    def honest_base_rate(self) -> float:
        return sum(c.rate for c in self.honest())

    def population(self) -> int:
        return sum(c.clients for c in self.classes)

    def to_doc(self) -> Dict[str, Any]:
        return {
            "classes": [c.to_doc() for c in self.classes],
            "window": self.window, "pool": self.pool,
            "max_inflight": self.max_inflight,
            "wire_per_window": self.wire_per_window,
            "flood_per_window": self.flood_per_window,
            "clusters": self.clusters,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period": self.diurnal_period,
            "patience": self.patience,
            "shed_watermark": self.shed_watermark,
        }


def spec_from_doc(doc: Dict[str, Any]) -> WorkloadSpec:
    """Rebuild a spec from its JSON form. ``{"preset": name, ...}``
    resolves the named preset first and applies the remaining keys as
    overrides — the compact form Scenario docs and CLI flags use."""
    doc = dict(doc)
    name = doc.pop("preset", None)
    if name is not None:
        base = preset(str(name))
        if not doc:
            return base
        merged = base.to_doc()
        merged.update(doc)
        doc = merged
    classes = tuple(
        ClientClass(
            name=str(c["name"]), rate=float(c["rate"]),
            clients=int(c["clients"]),
            read_fraction=float(c.get("read_fraction", 0.0)),
            op_bytes=int(c.get("op_bytes", 0)),
            byzantine=bool(c.get("byzantine", False)),
            hot_clients=int(c.get("hot_clients", 0)),
            hot_fraction=float(c.get("hot_fraction", 0.0)),
        )
        for c in doc["classes"]
    )
    return WorkloadSpec(
        classes=classes,
        window=float(doc.get("window", 0.5)),
        pool=int(doc.get("pool", 4)),
        max_inflight=int(doc.get("max_inflight", 512)),
        wire_per_window=int(doc.get("wire_per_window", 96)),
        flood_per_window=int(doc.get("flood_per_window", 192)),
        clusters=int(doc.get("clusters", 2)),
        diurnal_amplitude=float(doc.get("diurnal_amplitude", 0.0)),
        diurnal_period=float(doc.get("diurnal_period", 0.0)),
        patience=float(doc.get("patience", 4.0)),
        shed_watermark=int(doc.get("shed_watermark", 0)),
    )


#: Named workload presets (spec_from_doc's {"preset": ...} form, the
#: sim_explore --workload flag, CI jobs). Rates are offered DEMAND —
#: open-loop, independent of what the committee can absorb.
PRESETS: Dict[str, Callable[[], WorkloadSpec]] = {}


def _preset(name: str):
    def reg(fn: Callable[[], WorkloadSpec]):
        PRESETS[name] = fn
        return fn

    return reg


def preset(name: str) -> WorkloadSpec:
    if name not in PRESETS:
        raise ValueError(
            f"unknown workload preset {name!r} (known: {sorted(PRESETS)})"
        )
    return PRESETS[name]()


@_preset("steady")
def _steady() -> WorkloadSpec:
    """Mixed interactive/bulk load a 4-replica sim committee absorbs
    comfortably; the byzantine class idles until a byz_flood event."""
    return WorkloadSpec(
        classes=(
            ClientClass("interactive", rate=60.0, clients=3000,
                        read_fraction=0.5, hot_clients=32,
                        hot_fraction=0.2),
            ClientClass("bulk", rate=20.0, clients=400, op_bytes=96),
            ClientClass("byzantine", rate=0.0, clients=400,
                        byzantine=True),
        ),
        wire_per_window=48, max_inflight=256, shed_watermark=24,
        diurnal_amplitude=0.3, diurnal_period=20.0, patience=4.0,
    )


@_preset("overload")
def _overload() -> WorkloadSpec:
    """Offered demand well past the wire budget: ingress shed is the
    steady state and the replica shed plane engages on every cluster —
    the adversarial exam for the shedding fairness the planted
    shed_bulk_bias defect breaks."""
    return WorkloadSpec(
        classes=(
            ClientClass("interactive", rate=360.0, clients=20000,
                        read_fraction=0.3, hot_clients=64,
                        hot_fraction=0.25),
            ClientClass("bulk", rate=120.0, clients=2500, op_bytes=96),
            ClientClass("byzantine", rate=0.0, clients=2500,
                        byzantine=True),
        ),
        wire_per_window=160, max_inflight=512, shed_watermark=24,
        patience=3.0,
    )


@_preset("smoke1e5")
def _smoke1e5() -> WorkloadSpec:
    """10^5 distinct virtual clients inside a tier-1-sized horizon
    (30 virtual seconds): offered demand covers every population.

    flood_per_window stays BELOW shed_watermark: signed flood frames
    are well-formed, so they compete for overload-shed admission slots
    (the shed plane is deliberately cheaper than verify and runs first)
    and only die later as ``bad_sig``. A cap at/above the watermark
    lets the baseline flood monopolize admission and the "healthy"
    cell measures an attacked committee — byz_flood EVENTS exist to
    push toward the cap on purpose; the baseline must not."""
    return WorkloadSpec(
        classes=(
            ClientClass("interactive", rate=2600.0, clients=70_000,
                        read_fraction=0.4, hot_clients=128,
                        hot_fraction=0.2),
            ClientClass("bulk", rate=950.0, clients=25_000, op_bytes=96),
            ClientClass("byzantine", rate=600.0, clients=15_000,
                        byzantine=True),
        ),
        wire_per_window=64, max_inflight=384, flood_per_window=8,
        shed_watermark=24, patience=3.0,
        diurnal_amplitude=0.25, diurnal_period=15.0,
    )


@_preset("million")
def _million() -> WorkloadSpec:
    """>= 10^6 distinct virtual clients over a ~360 virtual-second day
    (the tier-2 acceptance cell): aggregate offered demand > 10^6 while
    the wire stays bounded — ingress shed carries the difference, the
    honest open-loop-with-finite-fleet semantics."""
    return WorkloadSpec(
        classes=(
            ClientClass("interactive", rate=2400.0, clients=800_000,
                        read_fraction=0.5, hot_clients=512,
                        hot_fraction=0.25),
            ClientClass("bulk", rate=500.0, clients=150_000, op_bytes=128),
            ClientClass("byzantine", rate=250.0, clients=80_000,
                        byzantine=True),
        ),
        wire_per_window=64, max_inflight=384, flood_per_window=8,
        shed_watermark=24, patience=3.0,
        diurnal_amplitude=0.4, diurnal_period=120.0,
    )


@_preset("swing")
def _swing() -> WorkloadSpec:
    """Idle→storm→drain swing (the ISSUE 19 controller acceptance
    driver). The idle baseline is comfortable for the mid shed
    watermark; the storm (a ``swing_events`` burst over the middle
    third) outruns a thin-WAN committee's commit throughput so an
    over-admitting watermark queues past client patience, while an
    over-shedding one pays the synchronized-retry quantum at idle.
    ``op_bytes`` is deliberately heavy: block bytes are what the WAN
    serializes, so admission control has real teeth. Pair with
    ``swing_events(horizon)`` and a ``shape`` fault event (see
    tools/knob_campaign.py)."""
    return WorkloadSpec(
        classes=(
            ClientClass("interactive", rate=60.0, clients=4000,
                        read_fraction=0.4, op_bytes=192,
                        hot_clients=32, hot_fraction=0.2),
            ClientClass("bulk", rate=20.0, clients=600, op_bytes=256),
            ClientClass("byzantine", rate=0.0, clients=400,
                        byzantine=True),
        ),
        wire_per_window=768, max_inflight=2048, clusters=2,
        shed_watermark=64, patience=4.0,
    )


def swing_events(
    horizon: float, magnitude: float = 10.0
) -> Tuple[WorkloadEvent, ...]:
    """The canonical idle→storm→drain event shape over ``horizon``: one
    interactive burst spanning the middle third. The knob campaign and
    the controller-smoke CI job share this single definition so the
    acceptance cell cannot drift between them."""
    return (
        WorkloadEvent(
            t=round(horizon / 3.0, 3), kind="burst", target="interactive",
            duration=round(horizon / 3.0, 3), magnitude=magnitude,
        ),
    )


# ---------------------------------------------------------------------------
# deterministic arrival generation
# ---------------------------------------------------------------------------


@dataclass
class WindowPlan:
    """One accounting window's plan: exact per-class offered/ingress-shed
    counts plus the bounded materialized batch. ``arrivals`` is a list of
    (t_rel, class_name, op) with t_rel relative to plane start."""

    index: int
    t0: float
    offered: Dict[str, int]
    shed_ingress: Dict[str, int]
    arrivals: List[Tuple[float, str, str]]
    floods: int = 0            # materialized bad-auth frames this window
    storm_mult: float = 1.0    # retry-attempt multiplier (retry_storm)


class ArrivalGen:
    """Seeded per-window arrival planner. ``plan(w)`` must be called for
    consecutive windows (internal carry/pointer state); memory is O(
    classes + wire budget), never O(clients)."""

    def __init__(self, spec: WorkloadSpec,
                 events: Sequence[WorkloadEvent], seed: int) -> None:
        self.spec = spec
        self.events = tuple(events)
        self.rng = random.Random((seed << 1) ^ 0x17AFF1C)
        self._carry: Dict[str, float] = {c.name: 0.0 for c in spec.classes}
        self._cold_ptr: Dict[str, int] = {c.name: 0 for c in spec.classes}
        self._cum_hot: Dict[str, int] = {c.name: 0 for c in spec.classes}
        self._cum_cold: Dict[str, int] = {c.name: 0 for c in spec.classes}
        self._flood_carry = 0.0

    # -- demand model ------------------------------------------------------

    def _active(self, t0: float, kind: str) -> List[WorkloadEvent]:
        w = self.spec.window
        return [
            e for e in self.events
            if e.kind == kind and e.t < t0 + w and t0 < e.t + max(e.duration, w)
        ]

    def _rate(self, cls: ClientClass, t0: float) -> float:
        """Offered rate for one class at window start: base rate x
        diurnal x bursts + remix flow. Byzantine classes additionally
        gain byz_flood demand (handled in plan(): flood demand is
        frames, not submissions)."""
        sp = self.spec
        diurnal = 1.0
        if sp.diurnal_period > 0 and sp.diurnal_amplitude:
            diurnal += sp.diurnal_amplitude * math.sin(
                2.0 * math.pi * t0 / sp.diurnal_period
            )
        r = cls.rate * max(0.0, diurnal)
        add = 0.0
        for e in self._active(t0, "burst"):
            if cls.byzantine:
                continue
            if e.target in ("", cls.name):
                r *= max(1.0, e.magnitude)
        for e in self._active(t0, "remix"):
            if ">" not in e.spec:
                continue
            src, dst = e.spec.split(">", 1)
            frac = min(1.0, max(0.0, e.magnitude))
            if cls.name == src:
                r *= (1.0 - frac)
            if cls.name == dst:
                src_cls = next(
                    (c for c in sp.classes if c.name == src), None
                )
                if src_cls is not None:
                    add += frac * src_cls.rate
        return r + add

    def storm_mult(self, t0: float) -> float:
        mults = [max(1.0, e.magnitude)
                 for e in self._active(t0, "retry_storm")]
        return max(mults) if mults else 1.0

    def _flood_rate(self, t0: float) -> float:
        """Extra bad-auth demand (req/s) during byz_flood windows —
        scaled off the honest base rate so a flood means something even
        when the byzantine class's own base rate is zero."""
        base = self.spec.honest_base_rate()
        return sum(
            max(0.0, e.magnitude) * base
            for e in self._active(t0, "byz_flood")
        )

    # -- identity model (O(1) per class) -----------------------------------

    def _client_id(self, cls: ClientClass) -> int:
        """Draw one virtual-client id: hotspot head with probability
        hot_fraction, else the round-robin cold pointer."""
        hot_n = min(cls.hot_clients, cls.clients)
        cold_n = max(1, cls.clients - hot_n)
        if hot_n and self.rng.random() < cls.hot_fraction:
            self._cum_hot[cls.name] += 1
            return self.rng.randrange(hot_n)
        i = self._cold_ptr[cls.name] % cold_n
        self._cold_ptr[cls.name] += 1
        self._cum_cold[cls.name] += 1
        return hot_n + i

    def _account_unmaterialized(self, cls: ClientClass, count: int) -> None:
        """Ingress-shed arrivals still came from clients: advance the
        identity accounting by aggregate (no per-arrival work)."""
        hot_n = min(cls.hot_clients, cls.clients)
        hot = int(round(count * cls.hot_fraction)) if hot_n else 0
        self._cum_hot[cls.name] += hot
        self._cum_cold[cls.name] += count - hot
        self._cold_ptr[cls.name] += count - hot

    def clients_touched(self) -> Dict[str, int]:
        """Exact distinct-clients-touched per class: the hotspot head
        saturates at hot_clients, the cold round-robin saturates at the
        rest of the population."""
        out: Dict[str, int] = {}
        for c in self.spec.classes:
            hot_n = min(c.hot_clients, c.clients)
            cold_n = c.clients - hot_n
            out[c.name] = (
                min(hot_n, self._cum_hot[c.name])
                + min(cold_n, self._cum_cold[c.name])
            )
        return out

    # -- materialization ---------------------------------------------------

    def _op(self, cls: ClientClass, cid: int, w: int) -> str:
        key = f"k_{cls.name[:1]}{cid}"
        if cls.read_fraction and self.rng.random() < cls.read_fraction:
            return f"get {key}"
        pad = "x" * cls.op_bytes
        return f"put {key} v{w}{pad}"

    def plan(self, w: int) -> WindowPlan:
        sp = self.spec
        t0 = w * sp.window
        offered: Dict[str, int] = {}
        shed: Dict[str, int] = {}
        takes: Dict[str, int] = {}
        honest = [c for c in sp.classes if not c.byzantine]
        for c in sp.classes:
            want = self._rate(c, t0) * sp.window + self._carry[c.name]
            n = int(want)
            self._carry[c.name] = want - n
            offered[c.name] = n
        # byz_flood demand rides the byzantine class's offered count
        flood_want = self._flood_rate(t0) * sp.window + self._flood_carry
        flood_extra = int(flood_want)
        self._flood_carry = flood_want - flood_extra
        byz = [c for c in sp.classes if c.byzantine]
        if byz and flood_extra:
            offered[byz[0].name] += flood_extra
        # honest materialization: proportional shares of the wire budget
        total_honest = sum(offered[c.name] for c in honest)
        budget = sp.wire_per_window
        for c in honest:
            n = offered[c.name]
            if total_honest <= budget:
                take = n
            else:
                take = min(n, max(0, int(round(budget * n / total_honest))))
            takes[c.name] = take
            shed[c.name] = n - take
            self._account_unmaterialized(c, n - take)
        # byzantine materialization: flood frames, separately capped
        floods = 0
        for c in byz:
            n = offered[c.name]
            floods = min(n, sp.flood_per_window)
            shed[c.name] = n - floods
            self._account_unmaterialized(c, n - floods)
            break  # one byzantine class per spec by convention
        # proportional weave across classes, clustered into
        # `sp.clusters` simultaneous instants per window (simultaneity
        # is what makes load queue under a virtual clock). The weave
        # order — classes interleaved by fractional position — IS the
        # launch/arrival order within an instant: clean arrival-order
        # shedding at the replica then degrades every class
        # proportionally, which is exactly the fairness property the
        # SLO oracle checks (and the planted shed-bias defect breaks).
        weave: List[Tuple[float, ClientClass]] = []
        for c in honest:
            m = takes.get(c.name, 0)
            weave.extend(((j + 0.5) / m, c) for j in range(m))
        weave.sort(key=lambda x: (x[0], x[1].name))
        k = max(1, sp.clusters)
        buckets: List[List[Tuple[str, str]]] = [[] for _ in range(k)]
        for i, (_, c) in enumerate(weave):
            cid = self._client_id(c)
            buckets[i % k].append((c.name, self._op(c, cid, w)))
        arrivals: List[Tuple[float, str, str]] = []
        for j, batch in enumerate(buckets):
            t = t0 + sp.window * (j + 0.5) / k
            arrivals.extend((t, cls, op) for cls, op in batch)
        return WindowPlan(
            index=w, t0=t0, offered=offered, shed_ingress=shed,
            arrivals=arrivals, floods=floods,
            storm_mult=self.storm_mult(t0),
        )


def arrival_digest(spec: WorkloadSpec, events: Sequence[WorkloadEvent],
                   seed: int, horizon: float) -> str:
    """sha256 over the whole planned arrival stream — the byte-identity
    check the determinism tests assert (same seed => same stream)."""
    gen = ArrivalGen(spec, events, seed)
    h = hashlib.sha256()
    for w in range(int(horizon / spec.window)):
        p = gen.plan(w)
        h.update(repr((
            p.index,
            sorted(p.offered.items()),
            sorted(p.shed_ingress.items()),
            [(round(t, 6), c, op) for t, c, op in p.arrivals],
            p.floods,
            round(p.storm_mult, 4),
        )).encode())
    h.update(repr(sorted(gen.clients_touched().items())).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# traffic accounting
# ---------------------------------------------------------------------------

#: bounded per-class latency reservoir size (deterministic replacement)
LATENCY_RESERVOIR = 4096
#: per-window latency sample cap (windows are short; keep them light)
WINDOW_SAMPLES = 512
#: how many recent windows ride each telemetry snapshot (flight frames
#: at 1 s interval overlap heavily at 0.5 s windows, so the union across
#: frames reconstructs the full timeline — tools/traffic_report.py)
WINDOWS_TAIL = 8


def _percentile(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    i = min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))
    return s[i]


class TrafficStats:
    """Per-class cumulative + per-window traffic counters, bounded
    memory. The plane writes; telemetry snapshots and judge_slo read."""

    def __init__(self, spec: WorkloadSpec) -> None:
        self.spec = spec
        self.class_names = [c.name for c in spec.classes]
        self.byz_names = {c.name for c in spec.classes if c.byzantine}
        z = lambda: {n: 0 for n in self.class_names}  # noqa: E731
        self.offered = z()
        self.shed_ingress = z()
        self.wire = z()            # submissions actually fired
        self.accepted = z()
        self.timeouts = z()        # attempts budget exhausted
        self.errors = z()
        self.superseded = z()
        self.requeued = z()        # re-enqueued after a timed-out attempt
        self.abandoned = z()       # in flight past drain, cancelled
        self.floods_sent = 0
        self.clients_touched: Dict[str, int] = z()
        self.peak_inflight = 0
        self.windows: List[Dict[str, Any]] = []
        self._lat: Dict[str, List[float]] = {n: [] for n in self.class_names}
        self._lat_n: Dict[str, int] = {n: 0 for n in self.class_names}
        # end-to-end reservoirs (ISSUE 19): latency anchored at the
        # request's FIRST launch, carried across plane-owned retries.
        # Per-attempt latency above resets per retry, which makes
        # shedding invisible to p99 — a controller tuned on it would
        # learn to shed everything. E2E is what the knob campaign gates.
        self._e2e: Dict[str, List[float]] = {n: [] for n in self.class_names}
        self._e2e_n: Dict[str, int] = {n: 0 for n in self.class_names}
        self._win_acc: Dict[str, int] = z()
        self._win_lat: Dict[str, List[float]] = {
            n: [] for n in self.class_names
        }

    # -- plane write path --------------------------------------------------

    def note_latency(self, cls: str, latency: float) -> None:
        n = self._lat_n[cls]
        self._lat_n[cls] = n + 1
        res = self._lat[cls]
        if len(res) < LATENCY_RESERVOIR:
            res.append(latency)
        else:
            res[(n * 2654435761) % LATENCY_RESERVOIR] = latency
        win = self._win_lat[cls]
        if len(win) < WINDOW_SAMPLES:
            win.append(latency)

    def note_e2e(self, cls: str, latency: float) -> None:
        n = self._e2e_n[cls]
        self._e2e_n[cls] = n + 1
        res = self._e2e[cls]
        if len(res) < LATENCY_RESERVOIR:
            res.append(latency)
        else:
            res[(n * 2654435761) % LATENCY_RESERVOIR] = latency

    def complete(self, cls: str, outcome: str,
                 latency: float = 0.0) -> None:
        if outcome == "accepted":
            self.accepted[cls] += 1
            self._win_acc[cls] += 1
            self.note_latency(cls, latency)
        else:
            getattr(self, outcome)[cls] += 1  # timeouts/errors/superseded

    def close_window(self, plan: WindowPlan,
                     wire_sent: Dict[str, int]) -> Dict[str, Any]:
        """Seal one window: fold the plan's exact offered/shed counts
        plus the in-window completion accumulators into a window record.
        Completions are attributed to the window they LAND in (the
        timeline a report wants: accepted/s per wall of virtual time)."""
        rec: Dict[str, Any] = {"w": plan.index, "t": round(plan.t0, 3),
                               "classes": {}}
        for n in self.class_names:
            off = plan.offered.get(n, 0)
            sh = plan.shed_ingress.get(n, 0)
            wr = wire_sent.get(n, 0)
            self.offered[n] += off
            self.shed_ingress[n] += sh
            self.wire[n] += wr
            lat = self._win_lat[n]
            rec["classes"][n] = {
                "off": off, "shed": sh, "wire": wr,
                "acc": self._win_acc[n],
                "p50_ms": round(_percentile(lat, 0.50) * 1000, 1),
                "p99_ms": round(_percentile(lat, 0.99) * 1000, 1),
            }
            self._win_acc[n] = 0
            self._win_lat[n] = []
        self.windows.append(rec)
        return rec

    # -- read path ---------------------------------------------------------

    def p99_ms(self, cls: str) -> float:
        return round(_percentile(self._lat[cls], 0.99) * 1000, 1)

    def p50_ms(self, cls: str) -> float:
        return round(_percentile(self._lat[cls], 0.50) * 1000, 1)

    def e2e_p99_ms(self, cls: str) -> float:
        return round(_percentile(self._e2e[cls], 0.99) * 1000, 1)

    def worst_honest_e2e_p99_ms(self) -> float:
        vals = [self.e2e_p99_ms(n) for n in self.class_names
                if n not in self.byz_names and self._e2e[n]]
        return max(vals) if vals else 0.0

    def accept_ratio(self, cls: str) -> float:
        off = self.offered[cls]
        return (self.accepted[cls] / off) if off else 0.0

    def totals(self) -> Dict[str, int]:
        return {
            "offered": sum(self.offered.values()),
            "shed": sum(self.shed_ingress.values()),
            "wire": sum(self.wire.values()),
            "accepted": sum(self.accepted.values()),
            "timeouts": sum(self.timeouts.values()),
            "requeued": sum(self.requeued.values()),
            "clients": sum(self.clients_touched.values()),
            "floods_sent": self.floods_sent,
        }

    def worst_honest_p99_ms(self) -> float:
        vals = [self.p99_ms(n) for n in self.class_names
                if n not in self.byz_names and self._lat[n]]
        return max(vals) if vals else 0.0

    def snapshot_block(self) -> Dict[str, Any]:
        """The ``traffic`` telemetry block (NodeTelemetry snapshots,
        flight frames): cumulative totals, last-closed-window rates, and
        the recent-windows tail traffic_report stitches timelines from.
        Additive to the snapshot schema — SCHEMA_VERSION unchanged, per
        the stability contract in telemetry.py."""
        t = self.totals()
        block: Dict[str, Any] = {
            "schema": 1,
            **t,
            "windows_total": len(self.windows),
            "worst_p99_ms": self.worst_honest_p99_ms(),
            "worst_e2e_p99_ms": self.worst_honest_e2e_p99_ms(),
            "peak_inflight": self.peak_inflight,
            "classes": {},
            "windows_tail": self.windows[-WINDOWS_TAIL:],
        }
        w = self.spec.window
        if self.windows:
            last = self.windows[-1]["classes"]
            block["offered_req_s"] = round(
                sum(c["off"] for c in last.values()) / w, 1
            )
            block["accepted_req_s"] = round(
                sum(c["acc"] for c in last.values()) / w, 1
            )
        for n in self.class_names:
            block["classes"][n] = {
                "offered": self.offered[n],
                "shed": self.shed_ingress[n],
                "wire": self.wire[n],
                "accepted": self.accepted[n],
                "timeouts": self.timeouts[n],
                "requeued": self.requeued[n],
                "clients": self.clients_touched[n],
                "byzantine": n in self.byz_names,
                "p50_ms": self.p50_ms(n),
                "p99_ms": self.p99_ms(n),
                "e2e_p99_ms": self.e2e_p99_ms(n),
                "accept_ratio": round(self.accept_ratio(n), 4),
            }
        return block

    def bench_traffic_block(self, horizon: float) -> Dict[str, Any]:
        """Flat metric block for bench ledger lines (tools/bench_gate.py
        rows under ``traffic.``)."""
        t = self.totals()
        flat: Dict[str, Any] = {
            "offered": t["offered"],
            "accepted": t["accepted"],
            "clients": t["clients"],
            "accepted_req_s": round(t["accepted"] / max(1e-9, horizon), 2),
            "shed_fraction": round(t["shed"] / max(1, t["offered"]), 4),
            "worst_p99_ms": self.worst_honest_p99_ms(),
            "worst_e2e_p99_ms": self.worst_honest_e2e_p99_ms(),
        }
        for n in self.class_names:
            if n in self.byz_names:
                continue
            flat[f"{n}_p99_ms"] = self.p99_ms(n)
            flat[f"{n}_e2e_p99_ms"] = self.e2e_p99_ms(n)
            flat[f"{n}_accept_ratio"] = round(self.accept_ratio(n), 4)
        return flat


# ---------------------------------------------------------------------------
# the traffic plane
# ---------------------------------------------------------------------------


class TrafficPlane:
    """Drives an ArrivalGen's plan over a LocalCommittee's bounded client
    pool on the virtual clock. One task per IN-FLIGHT submission (capped
    at spec.max_inflight), never per client."""

    def __init__(
        self,
        committee,
        spec: WorkloadSpec,
        events: Sequence[WorkloadEvent],
        seed: int,
        horizon: float,
        note: Optional[Callable[..., None]] = None,
    ) -> None:
        import asyncio  # local: keep module import-light for tools

        self._asyncio = asyncio
        self.com = committee
        self.spec = spec
        self.horizon = horizon
        self.gen = ArrivalGen(spec, events, seed)
        self.stats = TrafficStats(spec)
        self.note = note
        self.pool = list(committee.clients)[: spec.pool]
        self._rr = 0
        self._flood_ts = 0
        self._tasks: set = set()
        # (cls, op, attempts_left, born) re-fired at the next cluster
        # instant; ``born`` anchors e2e latency at the FIRST launch so
        # retry waves stay visible in the e2e reservoirs (ISSUE 19)
        self._requeue: List[Tuple[str, str, int, float]] = []
        self._attempts = max(1, int(spec.patience / max(
            0.25, getattr(self.pool[0], "request_timeout", 1.0)
        ))) if self.pool else 1

    # -- submission path ---------------------------------------------------

    def _launch(self, cls: str, op: str, attempts: int,
                win: Dict[str, int], born: float = -1.0) -> None:
        if len(self._tasks) >= self.spec.max_inflight:
            # pool saturated: exact ingress-shed accounting, no wire
            self.stats.shed_ingress[cls] += 1
            return
        win[cls] = win.get(cls, 0) + 1
        c = self.pool[self._rr % len(self.pool)]
        self._rr += 1
        task = self._asyncio.get_running_loop().create_task(
            self._one(c, cls, op, attempts, born)
        )
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        self.stats.peak_inflight = max(
            self.stats.peak_inflight, len(self._tasks)
        )

    async def _one(self, client, cls: str, op: str, attempts: int,
                   born: float = -1.0) -> None:
        from .client import SupersededError

        t0 = clock.now()
        if born < 0:
            born = t0  # first attempt: this launch IS the arrival
        try:
            # single-attempt submits: the PLANE owns retries, re-firing
            # them in synchronized clusters (correlated retry waves) —
            # smeared per-client backoff retries would never queue under
            # a virtual clock (see module doc)
            await client.submit(op, retries=0)
            self.stats.complete(cls, "accepted", clock.now() - t0)
            self.stats.note_e2e(cls, clock.now() - born)
        except self._asyncio.TimeoutError:
            if attempts > 1:
                self.stats.requeued[cls] += 1
                self._requeue.append((cls, op, attempts - 1, born))
            else:
                self.stats.complete(cls, "timeouts")
        except SupersededError:
            self.stats.complete(cls, "superseded")
        except self._asyncio.CancelledError:
            self.stats.abandoned[cls] += 1
            raise
        except Exception:
            self.stats.complete(cls, "errors")

    def _flood_frame(self) -> bytes:
        """One bad-auth frame. Signed committees: a well-formed Request
        from a KNOWN client with a garbage signature — it reaches the
        verify-admission seam and dies as ``bad_sig`` (the per-frame
        verify cost IS the attack). Unsigned committees would EXECUTE a
        well-formed request, so the flood degrades to undecodable bytes
        (killed at decode as ``malformed`` — the only admission seam an
        unsigned deployment has)."""
        self._flood_ts += 1
        c = self.pool[self._flood_ts % len(self.pool)]
        if not self.com.cfg.verify_signatures:
            return b"\xff\xfe" + self._flood_ts.to_bytes(4, "big")
        req = Request(
            client_id=c.id,
            # far-future timestamps: never collide with the pool
            # clients' real submissions (they would be rejected before
            # dedup anyway — bad sig — but collisions would still skew
            # the accounting)
            timestamp=(1 << 60) + self._flood_ts,
            operation="byz", ack=0,
        )
        req.sender = c.id
        req.sig = "00" * 64
        return req.to_wire()

    async def _send_floods(self, count: int) -> None:
        if not count or not self.pool:
            return
        c = self.pool[0]
        primary = c.cfg.primary(c.view_hint)
        for _ in range(count):
            raw = self._flood_frame()
            try:
                await c.transport.send(primary, raw)
            except Exception:
                return
            self.stats.floods_sent += 1

    # -- the run loop ------------------------------------------------------

    async def run(self) -> None:
        sp = self.spec
        t_start = clock.now()
        n_windows = max(1, int(self.horizon / sp.window))
        k = max(1, sp.clusters)
        for w in range(n_windows):
            plan = self.gen.plan(w)
            storm = plan.storm_mult
            wire_sent: Dict[str, int] = {}
            # group arrivals by the plan's cluster instants (PRESERVING
            # the plan's interleaved within-instant order — the replica
            # sheds in arrival order, so launch order is load-bearing
            # for fairness); the requeue list folds into the first
            # cluster (synchronized retry wave)
            att = max(1, int(round(self._attempts * storm)))
            clusters: List[List[Tuple[str, str, int, float]]] = [
                [] for _ in range(k)
            ]
            for t, cls, op in plan.arrivals:
                j = min(k - 1, int((t - plan.t0) / sp.window * k))
                clusters[j].append((cls, op, att, -1.0))
            if self._requeue:
                clusters[0].extend(self._requeue)
                self._requeue = []
            floods_per = plan.floods // k if plan.floods else 0
            for j, batch in enumerate(clusters):
                t_fire = (
                    t_start + plan.t0 + sp.window * (j + 0.5) / k
                )
                dt = t_fire - clock.now()
                if dt > 0:
                    await clock.sleep(dt)
                for cls, op, att, born in batch:
                    self._launch(cls, op, att, wire_sent, born)
                flood_n = (
                    plan.floods - floods_per * (k - 1)
                    if j == k - 1 else floods_per
                )
                await self._send_floods(flood_n)
            # seal the window at its end
            t_end = t_start + (w + 1) * sp.window
            dt = t_end - clock.now()
            if dt > 0:
                await clock.sleep(dt)
            self.stats.clients_touched = self.gen.clients_touched()
            rec = self.stats.close_window(plan, wire_sent)
            if self.note is not None:
                cls_rec = rec["classes"]
                self.note(
                    w=w,
                    off=sum(c["off"] for c in cls_rec.values()),
                    acc=sum(c["acc"] for c in cls_rec.values()),
                    shed=sum(c["shed"] for c in cls_rec.values()),
                    wire=sum(c["wire"] for c in cls_rec.values()),
                )
        # leftover synchronized retries get one final wave
        if self._requeue:
            wire_sent = {}
            for cls, op, att, born in self._requeue:
                self._launch(cls, op, 1, wire_sent, born)
            self._requeue = []
            for n, v in wire_sent.items():
                self.stats.wire[n] += v

    async def drain(self, timeout: float) -> None:
        """Bounded settle for in-flight submissions after the horizon;
        whatever outlives the budget is cancelled and counted
        ``abandoned`` (never silently dropped)."""
        tasks = [t for t in self._tasks if not t.done()]
        if tasks:
            await self._asyncio.wait(tasks, timeout=timeout)
        for t in list(self._tasks):
            if not t.done():
                t.cancel()
        if self._tasks:
            await self._asyncio.gather(
                *list(self._tasks), return_exceptions=True
            )
        self.stats.clients_touched = self.gen.clients_touched()


# ---------------------------------------------------------------------------
# SLO oracles (judged by sim._drive when a scenario carries a workload)
# ---------------------------------------------------------------------------

#: default oracle knobs (Scenario.slo overrides individual keys).
#: Calibrated to be LOAD-SHAPE INVARIANT: a healthy committee shedding
#: gracefully under any offered load passes; only genuine unfairness /
#: unbounded latency / silent queuing fails. See docs/OBSERVABILITY.md.
DEFAULT_SLO: Dict[str, float] = {
    # p99 bound for ACCEPTED requests per honest class; 0 derives
    # (2*patience + 10)s — a structural bound given the plane's bounded
    # attempt budget, so only a latency-accounting or admission bug
    # trips it. Scenarios testing tight SLOs set it explicitly.
    "p99_ms": 0.0,
    # judge a class only past this offered mass (tiny samples lie)
    "min_offered": 50.0,
    # starvation is judged RELATIVELY and PER WINDOW: in one window a
    # class is starved when its accept ratio falls below starve_gap x
    # the best-served honest class's ratio, while that best class is
    # >= fair_floor. Fair arrival-order shedding hands each class
    # budget proportional to its presence in every instant, which
    # EQUALIZES accept ratios within any window — so a healthy
    # committee passes at any overload depth and any load shape, and
    # only genuine class-preferential admission (the shed_bulk_bias
    # shape) fails. Judging per window (not on run totals) matters
    # under fault schedules: the class mix varies across windows while
    # partitions/crashes vary the windows' accept rates, so run-total
    # ratios split apart for healthy committees (Simpson's paradox).
    # Persistence (starve_windows) turns isolated attribution noise —
    # retried requests land in later windows than they were offered —
    # into a non-signal while a real bias starves EVERY loaded window.
    "starve_gap": 0.34,
    "fair_floor": 0.12,
    "starve_windows": 6.0,
    # judge a window's class only past this offered count
    "min_offered_window": 12.0,
    # shed-before-collapse: this many windows that pushed wire traffic,
    # accepted nothing and shed nothing (silent queuing) fail the run.
    # Sized above max_inflight/wire_per_window so a partition window
    # (where the pool legitimately goes blind until the in-flight cap
    # engages) cannot trip it.
    "collapse_windows": 12.0,
}


def judge_slo(
    stats: TrafficStats,
    spec: WorkloadSpec,
    overrides: Optional[Dict[str, float]] = None,
) -> Tuple[Dict[str, Any], Optional[str]]:
    """(verdicts, failure) for one finished run. ``failure`` is a
    ``slo:<detail>`` string for SimResult.failure, or None."""
    cfg = dict(DEFAULT_SLO)
    cfg.update(overrides or {})
    p99_bound = cfg["p99_ms"] or (2.0 * spec.patience + 10.0) * 1000.0
    verdicts: Dict[str, Any] = {"p99": {}, "starvation": {},
                                "shed_before_collapse": {}}
    failure: Optional[str] = None
    honest = [c.name for c in spec.classes if not c.byzantine]

    # bounded p99 per honest class (accepted-request latency)
    for n in honest:
        p99 = stats.p99_ms(n)
        judged = stats.accepted[n] >= 20
        ok = (not judged) or p99 <= p99_bound
        verdicts["p99"][n] = {"p99_ms": p99, "bound_ms": round(p99_bound, 1),
                              "judged": judged, "ok": ok}
        if not ok and failure is None:
            failure = f"slo:p99:{n}"

    # no starved honest class (relative fairness, judged per window
    # with persistence — see the DEFAULT_SLO rationale)
    starved_w: Dict[str, int] = {}
    judged_w = 0
    for rec in stats.windows:
        wr = {}
        for n in honest:
            c = rec["classes"].get(n)
            if c and c["off"] >= cfg["min_offered_window"]:
                wr[n] = c["acc"] / c["off"]
        if len(wr) < 2:
            continue
        best = max(wr.values())
        if best < cfg["fair_floor"]:
            continue
        judged_w += 1
        for n, r in wr.items():
            if r < cfg["starve_gap"] * best:
                starved_w[n] = starved_w.get(n, 0) + 1
    starved = sorted(
        n for n, k in starved_w.items() if k >= cfg["starve_windows"]
    )
    ratios = {
        n: stats.accept_ratio(n) for n in honest
        if stats.offered[n] >= cfg["min_offered"]
    }
    verdicts["starvation"] = {
        "ok": not starved, "starved": starved,
        "judged_windows": judged_w,
        "starved_windows": dict(sorted(starved_w.items())),
        "ratios": {n: round(r, 4) for n, r in ratios.items()},
    }
    if starved and failure is None:
        failure = f"slo:starved-class:{','.join(starved)}"

    # shed-before-collapse: overload must surface as shed counters,
    # never as wire traffic that neither completes nor sheds
    blind = best_run = run = 0
    for rec in stats.windows:
        cls = {n: rec["classes"][n] for n in honest if n in rec["classes"]}
        off = sum(c["off"] for c in cls.values())
        acc = sum(c["acc"] for c in cls.values())
        sh = sum(c["shed"] for c in cls.values())
        wire = sum(c["wire"] for c in cls.values())
        if off >= cfg["min_offered"] and wire > 0 and acc == 0 and sh == 0:
            blind += 1
            run += 1
            best_run = max(best_run, run)
        else:
            run = 0
    ok = best_run < cfg["collapse_windows"]
    verdicts["shed_before_collapse"] = {
        "ok": ok, "blind_windows": blind,
        "longest_blind_run": best_run,
        "limit": int(cfg["collapse_windows"]),
    }
    if not ok and failure is None:
        failure = "slo:collapse"
    return verdicts, failure


# ---------------------------------------------------------------------------
# bench-ledger record (tools/bench_gate.py traffic rows)
# ---------------------------------------------------------------------------


def bench_record(
    stats: TrafficStats,
    horizon: float,
    cell: str = "traffic_smoke",
    gate: Optional[Dict[str, Dict[str, float]]] = None,
    gate_mode: str = "",
) -> Dict[str, Any]:
    """One bench ledger line carrying the flat ``traffic`` block
    (schema-pinned like every other ledger line; bench_gate's
    ``traffic.*`` METRICS rows and floors-mode gates read it)."""
    from .telemetry import BENCH_SCHEMA_VERSION

    rec: Dict[str, Any] = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "cell": cell,
        "traffic": stats.bench_traffic_block(horizon),
    }
    if gate:
        rec["gate"] = gate
    if gate_mode:
        rec["gate_mode"] = gate_mode
    return rec


# Regenerate kind documentation from the registry (same no-drift rule as
# faults.KIND_REGISTRY).
__doc__ = (__doc__ or "") + (
    "\n\nWorkload-event kinds (generated from WORKLOAD_KIND_REGISTRY):\n\n"
    + workload_kind_table() + "\n"
)
