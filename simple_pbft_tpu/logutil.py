"""Structured per-node logging + lightweight perf instrumentation.

Parity target: the reference's only real auxiliary subsystem — its zap +
lumberjack setup (/root/reference/zapConfig/loggerConfig.go:15-69): one
log file per node chosen by a flag, 1 MiB rotation with 5 backups, ISO
timestamps, caller annotation, console + file sinks. This module matches
that surface with the stdlib (RotatingFileHandler) and adds what perf
work actually needs and the reference lacked (VERDICT round-1 weak #8):
histograms for sweep size / verify latency / commit latency, and a
machine-readable metrics dump on shutdown.
"""

from __future__ import annotations

import bisect
import json
import logging
import logging.handlers
import os
import time
from typing import Dict, List, Optional

# zapConfig parity: 1 MiB per file, 5 backups (loggerConfig.go:53-59)
ROTATE_BYTES = 1 * 1024 * 1024
ROTATE_BACKUPS = 5

_FORMAT = (
    "%(asctime)s\t%(levelname)s\t%(name)s\t%(filename)s:%(lineno)d\t%(message)s"
)


def setup_node_logging(
    node_id: str,
    log_dir: Optional[str] = None,
    level: str = "INFO",
    console: bool = True,
) -> logging.Logger:
    """Configure the root logger the way the reference's NewLogger does:
    per-node rotating file (log_dir/<node_id>.log) + console, ISO
    timestamps, caller annotation. Returns the root logger."""
    root = logging.getLogger()
    root.setLevel(level.upper())
    for h in list(root.handlers):  # idempotent across restarts in-process
        root.removeHandler(h)
    fmt = logging.Formatter(_FORMAT, datefmt="%Y-%m-%dT%H:%M:%S%z")
    if console:
        sh = logging.StreamHandler()
        sh.setFormatter(fmt)
        root.addHandler(sh)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fh = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, f"{node_id}.log"),
            maxBytes=ROTATE_BYTES,
            backupCount=ROTATE_BACKUPS,
        )
        fh.setFormatter(fmt)
        root.addHandler(fh)
    return root


class Histogram:
    """Fixed-boundary histogram: O(1) record, stable export shape.

    Boundaries are powers of two in the unit the caller picks (ms, items);
    export gives count/sum/min/max plus approximate p50/p90/p99 from the
    bucket midpoints — enough to steer perf work without a dependency.
    """

    __slots__ = ("bounds", "counts", "count", "total", "vmin", "vmax")

    def __init__(self, bounds: Optional[List[float]] = None) -> None:
        self.bounds = bounds or [2.0**i for i in range(-4, 16)]
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def record(self, v: float) -> None:
        self.counts[bisect.bisect_right(self.bounds, v)] += 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def _quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.vmax
                return (lo + hi) / 2
        return self.vmax

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            # full zeroed schema, not a bare {"count": 0}: snapshot
            # consumers (telemetry plane, pbft_top, bench joins) index
            # p50/p99 unconditionally and must never key-error on an
            # idle node (ISSUE 2 satellite)
            return {
                "count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                "p50": 0.0, "p90": 0.0, "p99": 0.0,
            }
        return {
            "count": self.count,
            "mean": round(self.total / self.count, 3),
            "min": round(self.vmin, 3),
            "max": round(self.vmax, 3),
            "p50": round(self._quantile(0.50), 3),
            "p90": round(self._quantile(0.90), 3),
            "p99": round(self._quantile(0.99), 3),
        }


class ReplicaStats:
    """The perf counters a replica keeps beyond its integer metrics dict:
    sweep occupancy, verify-batch latency/throughput, commit latency."""

    def __init__(self) -> None:
        self.sweep_size = Histogram([1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                     1024, 2048, 4096])
        self.sweep_ms = Histogram()
        self.verify_ms = Histogram()
        self.commit_ms = Histogram()
        # speculative-reply latency (ISSUE 15): pre-prepare admission ->
        # speculative reply sent — the client-visible half of commit
        # latency under speculation; compare p50 against commit_ms
        self.spec_reply_ms = Histogram()
        self.verify_items = 0
        self.verify_seconds = 0.0
        self._started = time.perf_counter()

    def verifies_per_sec(self) -> float:
        return (
            self.verify_items / self.verify_seconds
            if self.verify_seconds > 0
            else 0.0
        )

    def snapshot(self, metrics: Optional[Dict[str, int]] = None) -> Dict:
        """The histogram/rate surface as one dict — the shape the
        telemetry plane embeds in every /metrics.json and flight-recorder
        frame (metrics included only when the caller passes them)."""
        doc = {
            "uptime_s": round(time.perf_counter() - self._started, 1),
            "sweep_size": self.sweep_size.summary(),
            "sweep_ms": self.sweep_ms.summary(),
            "verify_ms": self.verify_ms.summary(),
            "verify_per_s": round(self.verifies_per_sec(), 1),
            "commit_ms": self.commit_ms.summary(),
            "spec_reply_ms": self.spec_reply_ms.summary(),
        }
        if metrics is not None:
            doc["metrics"] = dict(sorted(metrics.items()))
        return doc

    def dump(self, metrics: Dict[str, int]) -> str:
        """One JSON line a human (or the driver) can steer perf work with."""
        return json.dumps(self.snapshot(metrics), sort_keys=True)
