"""Cross-replica causal trace plane: wire envelopes + quorum-arrival stats.

Every other instrument in the repo is per-process — ``spans.py`` tiles a
slot's latency at one node, wire accounting counts bytes per link.  This
module adds the committee-global view:

- ``stamp(raw, ...)`` splices an **unsigned** trace envelope into the
  canonical wire frame of a hot consensus message (pre-prepare, votes,
  QC certs, view-change traffic).  The envelope is a top-level ``"tr"``
  key inserted at its sorted position, so the frame stays canonical
  JSON; signatures cover the message *fields* (``Message._build`` drops
  unknown keys before payload reconstruction), so stamped and unstamped
  frames verify identically — no wire-compat or signature break.
- ``recv_stamp(node_id, raw)`` runs at each transport's delivery seam.
  The envelope carries the sender's send timestamp, so one recv-side
  ``{"evt":"edge"}`` ledger doc is a complete send/recv pair keyed on
  (view, seq, phase, src, dst).  ``tools/slot_trace.py`` joins these
  across all nodes' span ledgers into one causal DAG per slot.
- ``QuorumStats`` records per-certificate vote *arrival order* at the
  collecting replica: the arrival rank of each voter, and the margin
  between the (2f+1)-th vote and the slowest — the headroom before a
  straggler enters the quorum path.  In QC mode votes flow to the
  primary only, so arrival order is observable there alone (documented
  in docs/OBSERVABILITY.md).

Timestamps are ``int(clock.now() * 1e6)`` — virtual microseconds under
the sim clock (byte-deterministic across identical seeds), per-process
monotonic microseconds on real runs (independent epochs per node; the
skew solver in slot_trace recovers pairwise offsets from symmetric
message pairs, NTP-style).

The plane is OFF by default (``configure(True)`` to enable): production
hot paths and existing sim wire fingerprints are unchanged unless a run
opts in.  Every public entry point is never-raise — tracing must not be
able to take down consensus (pbftlint PBL004 audits the call sites).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import clock
from .logutil import Histogram

# Envelope phases stamped on the wire.  slot_trace classifies message
# edges by these names; keep in sync with docs/OBSERVABILITY.md.
PREPREPARE = "preprepare"
PREPARE = "prepare"
COMMIT = "commit"
QC_PREPARE = "qc-prepare"
QC_COMMIT = "qc-commit"
VIEWCHANGE = "viewchange"
NEWVIEW = "newview"

# Fast substring gate: a stamped frame always contains this byte run
# (canonical JSON — no whitespace), an unstamped one never does because
# "tr" is not a field name of any message type (checked in tests).
_GATE = b'"tr":{'

_enabled = False
_lock = threading.Lock()
# sender -> next span id.  Reset by configure() so two identical seeded
# runs in one process emit byte-identical ledgers.
_span_seq: Dict[str, int] = {}


def enabled() -> bool:
    """True when wire stamping is on for this process."""
    return _enabled


def configure(on: bool) -> None:
    """Enable/disable wire stamping and reset per-sender span counters."""
    global _enabled
    with _lock:
        _enabled = bool(on)
        _span_seq.clear()


def _next_span(sender: str) -> int:
    with _lock:
        i = _span_seq.get(sender, 0)
        _span_seq[sender] = i + 1
    return i


# ---------------------------------------------------------------------------
# Canonical-frame scanners.  These mirror transport.base._skip_string /
# _skip_value byte-for-byte; kept local so the import graph stays one
# direction (transports import trace, never the reverse).

def _skip_string(raw: bytes, i: int) -> int:
    # raw[i] == '"'; returns index just past the closing quote.
    i += 1
    n = len(raw)
    while i < n:
        c = raw[i]
        if c == 0x5C:  # backslash
            i += 2
            continue
        if c == 0x22:  # quote
            return i + 1
        i += 1
    raise ValueError("unterminated string")


def _skip_value(raw: bytes, i: int) -> int:
    # Returns index just past the JSON value starting at i.
    n = len(raw)
    c = raw[i]
    if c == 0x22:  # string
        return _skip_string(raw, i)
    if c in (0x7B, 0x5B):  # { or [
        depth = 0
        while i < n:
            c = raw[i]
            if c == 0x22:
                i = _skip_string(raw, i)
                continue
            if c in (0x7B, 0x5B):
                depth += 1
            elif c in (0x7D, 0x5D):
                depth -= 1
                if depth == 0:
                    return i + 1
            i += 1
        raise ValueError("unterminated container")
    # number / literal: scan to the next delimiter
    while i < n and raw[i] not in (0x2C, 0x7D, 0x5D):
        i += 1
    return i


def stamp(raw: bytes, phase: str, view: int, seq: int, sender: str) -> bytes:
    """Return ``raw`` with an unsigned trace envelope spliced in.

    No-op (returns ``raw`` unchanged) when the plane is disabled, the
    frame is already stamped, or anything at all goes wrong — a stamp
    failure must never cost a consensus message.
    """
    if not _enabled:
        return raw
    try:
        if _GATE in raw or not raw.startswith(b'{"'):
            return raw
        env = (
            b'"tr":{"i":%d,"p":"%s","q":%d,"s":"%s","t":%d,"v":%d}'
            % (
                _next_span(sender),
                phase.encode("ascii"),
                seq,
                sender.encode("ascii"),
                int(clock.now() * 1e6),
                view,
            )
        )
        return _splice(raw, env)
    except Exception:
        return raw


def _splice(raw: bytes, env: bytes) -> bytes:
    # Insert env at its sorted top-level key position so the frame stays
    # canonical (sorted keys, no whitespace).
    i = 1
    n = len(raw)
    while i < n and raw[i] == 0x22:
        j = _skip_string(raw, i)
        key = raw[i + 1 : j - 1]
        if key > b"tr":
            return raw[:i] + env + b"," + raw[i:]
        if raw[j : j + 1] != b":":
            return raw
        i = _skip_value(raw, j + 1)
        if raw[i : i + 1] != b",":
            # end of object: append before the closing brace
            return raw[:i] + b"," + env + raw[i:]
        i += 1
    return raw


def extract(raw: bytes) -> Optional[Dict[str, Any]]:
    """Parse the trace envelope out of a stamped frame, or None."""
    try:
        if _GATE not in raw or not raw.startswith(b'{"'):
            return None
        i = 1
        n = len(raw)
        seg: Optional[Tuple[int, int]] = None
        while i < n and raw[i] == 0x22:
            j = _skip_string(raw, i)
            key = raw[i + 1 : j - 1]
            if raw[j : j + 1] != b":":
                return None
            k = _skip_value(raw, j + 1)
            if key == b"tr":
                seg = (j + 1, k)
                break
            if key > b"tr":
                return None
            if raw[k : k + 1] != b",":
                return None
            i = k + 1
        if seg is None:
            return None
        env = json.loads(raw[seg[0] : seg[1]])
        if (
            isinstance(env, dict)
            and isinstance(env.get("p"), str)
            and isinstance(env.get("s"), str)
            and isinstance(env.get("t"), int)
            and isinstance(env.get("v"), int)
            and isinstance(env.get("q"), int)
        ):
            return env
        return None
    except Exception:
        return None


def recv_stamp(node_id: str, raw: bytes) -> None:
    """Record one cross-node edge doc for a stamped inbound frame.

    Called at every transport's delivery seam, after queue residency
    (so the recv timestamp includes injected fault delay and queue
    wait).  Self-delivered frames and unstamped frames are free: the
    substring gate rejects them before any parsing.  Never raises.
    """
    try:
        if _GATE not in raw:
            return
        env = extract(raw)
        if env is None or env["s"] == node_id:
            return
        from . import spans

        spans.emit(
            {
                "evt": "edge",
                "phase": env["p"],
                "view": env["v"],
                "seq": env["q"],
                "src": env["s"],
                "node": node_id,
                "span": env.get("i", 0),
                "t_send_us": env["t"],
                "t_recv_us": int(clock.now() * 1e6),
            }
        )
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Quorum-arrival order statistics


class QuorumStats:
    """Per-certificate vote-arrival order at the collecting replica.

    ``note_vote`` is called at decode time in the ingest sweep —
    *before* verification and before the redundant-vote precheck, which
    is the whole point: post-quorum stragglers are dropped there and
    never reach the state machine, but their arrival time is exactly
    the headroom number we want.  First arrival per (cert, sender)
    wins; sender ids are unverified at that point, so the table is
    bounded (``MAX_OPEN`` certs, committee-sized voter maps).

    A certificate finalizes when the quorum has been marked
    (``note_quorum`` from the SendCommit / ExecuteBlock transitions)
    and either every committee member's vote has arrived or the slot is
    garbage-collected past the stable watermark (``flush_upto``).
    Finalizing emits one ``{"evt":"quorum"}`` ledger doc with the full
    arrival order, the (2f+1)-th-vs-slowest margin, and the straggler
    id, and feeds the live margin histogram surfaced via telemetry.

    All methods are never-raise (pbftlint PBL004 audited).
    """

    MAX_OPEN = 4096

    def __init__(self, node_id: str) -> None:
        self.node_id = node_id
        self.margin_ms = Histogram()
        self.straggler_counts: Dict[str, int] = {}
        self.last_margin_ms = 0.0
        self.last_straggler = ""
        self.certs_finalized = 0
        self.certs_partial = 0
        # (view, seq, phase) -> {"arr": {sender: t}, "q": quorum, "n": committee, "tq": t_quorum}
        self._open: Dict[Tuple[int, int, str], Dict[str, Any]] = {}

    def _rec(self, view: int, seq: int, phase: str) -> Optional[Dict[str, Any]]:
        key = (view, seq, phase)
        rec = self._open.get(key)
        if rec is None:
            if len(self._open) >= self.MAX_OPEN:
                return None
            rec = self._open[key] = {"arr": {}, "q": 0, "n": 0, "tq": None}
        return rec

    def note_vote(self, phase: str, view: int, seq: int, sender: str) -> None:
        """Record a vote arrival (first arrival per sender wins)."""
        try:
            rec = self._rec(view, seq, phase)
            if rec is None or sender in rec["arr"]:
                return
            rec["arr"][sender] = clock.now()
            if rec["tq"] is not None and rec["n"] and len(rec["arr"]) >= rec["n"]:
                self._finalize((view, seq, phase), rec)
        except Exception:
            pass

    def note_quorum(self, phase: str, view: int, seq: int, quorum: int, n: int) -> None:
        """Mark that the certificate reached quorum (2f+1 valid votes)."""
        try:
            rec = self._rec(view, seq, phase)
            if rec is None or rec["tq"] is not None:
                return
            rec["q"] = quorum
            rec["n"] = n
            rec["tq"] = clock.now()
            if len(rec["arr"]) >= n:
                self._finalize((view, seq, phase), rec)
        except Exception:
            pass

    def flush_upto(self, stable_seq: int) -> None:
        """Finalize and drop every open certificate at or below the watermark."""
        try:
            for key in sorted(k for k in self._open if k[1] <= stable_seq):
                self._finalize(key, self._open[key])
        except Exception:
            pass

    def flush_all(self) -> None:
        """Finalize everything still open (end of run)."""
        try:
            for key in sorted(self._open):
                self._finalize(key, self._open[key])
        except Exception:
            pass

    def _finalize(self, key: Tuple[int, int, str], rec: Dict[str, Any]) -> None:
        self._open.pop(key, None)
        quorum = rec["q"]
        arr = rec["arr"]
        if rec["tq"] is None or quorum <= 0 or len(arr) < quorum:
            # Never reached quorum locally (e.g. QC-mode backup: shares
            # flow to the primary only) — nothing to attribute.
            self.certs_partial += 1
            return
        order = sorted(arr, key=lambda s: (arr[s], s))
        t_q = arr[order[quorum - 1]]
        t_slow = arr[order[-1]]
        margin_ms = round((t_slow - t_q) * 1e3, 4)
        straggler = order[-1]
        self.certs_finalized += 1
        self.margin_ms.record(margin_ms)
        self.straggler_counts[straggler] = self.straggler_counts.get(straggler, 0) + 1
        self.last_margin_ms = margin_ms
        self.last_straggler = straggler
        from . import spans

        spans.emit(
            {
                "evt": "quorum",
                "node": self.node_id,
                "phase": key[2],
                "view": key[0],
                "seq": key[1],
                "quorum": quorum,
                "votes": len(arr),
                "t_quorum_us": int(rec["tq"] * 1e6),
                "margin_ms": margin_ms,
                "straggler": straggler,
                "order": order,
            }
        )

    def snapshot(self) -> Dict[str, Any]:
        """Live quorum block for the telemetry snapshot / pbft_top."""
        try:
            top: List[Tuple[str, int]] = sorted(
                self.straggler_counts.items(), key=lambda kv: (-kv[1], kv[0])
            )[:3]
            return {
                "certs": self.certs_finalized,
                "partial": self.certs_partial,
                "open": len(self._open),
                "margin_ms": self.margin_ms.summary(),
                "last_margin_ms": self.last_margin_ms,
                "last_straggler": self.last_straggler,
                "stragglers": {k: v for k, v in top},
            }
        except Exception:
            return {"certs": 0, "partial": 0, "open": 0}
