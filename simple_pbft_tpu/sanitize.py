"""Opt-in runtime sanitizers (ISSUE 8): the dynamic half of pbftlint.

Two sanitizers, enabled via ``PBFT_SANITIZE`` (comma list, or ``all``):

- ``loop`` — **event-loop blocking sanitizer.** A daemon watcher posts a
  heartbeat callback onto every watched loop; when the echo stalls past
  the threshold (``PBFT_SANITIZE_LOOP_MS``, default 150) it samples the
  loop thread's live stack via ``sys._current_frames()`` and records a
  violation attributed to the innermost product frame. This is the
  dynamic backstop for pbftlint's PBL001: the static call graph cannot
  see through dynamic dispatch, ctypes, or C extensions — a stalled
  heartbeat can't be fooled by any of them. (``sys.monitoring`` would
  give exact per-callback attribution but is 3.12+; this runtime is
  3.10, and the sampling design additionally catches stalls *between*
  callbacks — e.g. a GIL-hogging native call — that callback timing
  misses. See docs/STATIC_ANALYSIS.md.)

- ``locks`` — **lock-discipline sanitizer.** The cross-thread surfaces
  (VerifyService, QcVerifyLane, SpanRecorder, FlightRecorder) wrap
  their locks in :func:`wrap_lock`, which enforces the documented
  ranked acquisition order (:data:`LOCK_RANKS` is the single source;
  the docs table is asserted against it in tests), leaf annotations
  (nothing may be acquired while a leaf lock is held), and group
  exclusion (the SpanRecorder's ring lock and sink lock must NEVER be
  held together — the PR 4 "sink I/O off the recorder lock" contract).
  :func:`bind_owner`/:func:`check_owner` assert owning-thread
  annotations on worker-confined and loop-confined methods.

Both sanitizers RECORD violations instead of raising: a sanitizer that
raises into consensus would itself violate the telemetry contract. The
pytest hook in tests/conftest.py drains :func:`take_violations` after
each test and fails the test that caused them. Zero overhead when
disabled: :func:`wrap_lock` returns the raw lock object and the owner
checks are no-ops.
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "enabled",
    "install",
    "take_violations",
    "violations",
    "format_violations",
    "wrap_lock",
    "bind_owner",
    "check_owner",
    "watch_loop",
    "LOCK_RANKS",
]


def enabled(kind: str) -> bool:
    """Is sanitizer ``kind`` ("loop"/"locks") requested via env? Read
    per call so tests can monkeypatch PBFT_SANITIZE."""
    raw = os.environ.get("PBFT_SANITIZE", "")
    modes = {m.strip() for m in raw.split(",") if m.strip()}
    return "all" in modes or kind in modes


# ---------------------------------------------------------------------------
# violation store (process-wide, bounded; never raises into the caller)
# ---------------------------------------------------------------------------

_MAX_VIOLATIONS = 256
_viol_lock = threading.Lock()
_violations: List[Dict[str, Any]] = []


def _record(kind: str, **doc: Any) -> None:
    doc = {"kind": kind, "t_mono": round(time.monotonic(), 4), **doc}
    with _viol_lock:
        if len(_violations) < _MAX_VIOLATIONS:
            _violations.append(doc)


def violations(kind: Optional[str] = None) -> List[Dict[str, Any]]:
    with _viol_lock:
        out = list(_violations)
    if kind is not None:
        out = [v for v in out if v["kind"] == kind]
    return out


def take_violations() -> List[Dict[str, Any]]:
    """Drain the store (per-test reset + check)."""
    with _viol_lock:
        out = list(_violations)
        _violations.clear()
    return out


def format_violations(viols: List[Dict[str, Any]]) -> str:
    lines = [f"{len(viols)} sanitizer violation(s):"]
    for v in viols:
        head = f"  [{v['kind']}] " + (v.get("message") or "")
        lines.append(head)
        for fr in v.get("stack", [])[-8:]:
            lines.append(f"      {fr}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# loop-blocking sanitizer
# ---------------------------------------------------------------------------

DEFAULT_LOOP_MS = 150.0

# stdlib frames that mean "the loop thread is idle/parked, not blocked
# in product code" — a sampled stack whose innermost frame lives here is
# not attributable and is dropped rather than guessed at
_IDLE_FUNCS = {
    "select", "poll", "epoll", "kqueue", "_run_once", "run_forever",
    "_read_from_self", "_write_to_self", "_process_events",
}


class _LoopWatch:
    """One watcher thread per watched loop. The loop echoes heartbeats;
    a stalled echo past ``threshold_s`` samples the loop thread's stack
    and records ONE violation per stall episode (debounced until the
    heartbeat recovers)."""

    def __init__(self, loop: asyncio.AbstractEventLoop, threshold_s: float):
        self.loop = loop
        self.threshold_s = threshold_s
        self._last_beat = time.monotonic()
        self._loop_tid: Optional[int] = None
        self._in_stall = False
        self._thread = threading.Thread(
            target=self._run, name="pbft-sanitize-loop", daemon=True
        )
        self._thread.start()

    def _beat(self) -> None:
        self._loop_tid = threading.get_ident()
        self._last_beat = time.monotonic()

    def _run(self) -> None:
        try:
            self._watch()
        finally:
            # the loop is closed: release its id so a LATER loop object
            # reusing the freed address gets its own watcher instead of
            # being silently unwatched (id() reuse after gc)
            with _watch_lock:
                _watched.discard(id(self.loop))

    def _watch(self) -> None:
        period = max(0.005, self.threshold_s / 4.0)
        while True:
            if self.loop.is_closed():
                return
            if not self.loop.is_running():
                # between run_until_complete calls (tests) the loop is
                # parked: a missing echo is not a block
                self._last_beat = time.monotonic()
                self._in_stall = False
                time.sleep(period)
                continue
            try:
                self.loop.call_soon_threadsafe(self._beat)
            except RuntimeError:  # loop closed between check and call
                return
            time.sleep(period)
            gap = time.monotonic() - self._last_beat
            if gap <= self.threshold_s or not self.loop.is_running():
                self._in_stall = False
                continue
            if self._in_stall:
                continue  # one violation per episode
            stack = self._sample()
            if stack is None:
                continue  # idle/unattributable — not a block
            self._in_stall = True
            _record(
                "loop",
                message=(
                    f"event loop stalled {gap * 1e3:.0f} ms "
                    f"(threshold {self.threshold_s * 1e3:.0f} ms) — "
                    f"blocked in: {stack[-1].strip()}"
                ),
                stall_ms=round(gap * 1e3, 1),
                stack=stack,
            )

    def _sample(self) -> Optional[List[str]]:
        tid = self._loop_tid
        if tid is None:
            # no beat ever echoed (the loop blocked on its very first
            # callback): fall back to asyncio's own record of the thread
            # running the loop (CPython BaseEventLoop._thread_id)
            tid = getattr(self.loop, "_thread_id", None)
        if tid is None:
            return None
        frame = sys._current_frames().get(tid)
        if frame is None:
            return None
        summary = traceback.extract_stack(frame)
        if not summary:
            return None
        if summary[-1].name in _IDLE_FUNCS:
            return None  # parked in the selector / loop machinery
        here = os.path.dirname(os.path.abspath(__file__))
        out = []
        for fr in summary:
            if fr.filename == os.path.join(here, "sanitize.py"):
                continue
            out.append(
                f"{fr.filename}:{fr.lineno} in {fr.name}: "
                f"{(fr.line or '').strip()}"
            )
        return out or None


_watched: "set[int]" = set()
_watch_lock = threading.Lock()


def watch_loop(
    loop: asyncio.AbstractEventLoop, threshold_s: Optional[float] = None
) -> Optional[_LoopWatch]:
    """Attach the blocking watcher to ``loop`` (idempotent). Explicit
    call = explicit opt-in: works regardless of PBFT_SANITIZE (tests)."""
    with _watch_lock:
        if id(loop) in _watched:
            return None
        _watched.add(id(loop))
    if threshold_s is None:
        threshold_s = (
            float(os.environ.get("PBFT_SANITIZE_LOOP_MS", DEFAULT_LOOP_MS))
            / 1e3
        )
    return _LoopWatch(loop, threshold_s)


_installed = False


def install() -> None:
    """Auto-instrument every event loop created from now on (the
    ``PBFT_SANITIZE=loop`` entry point; tests/conftest.py calls this
    when the env asks). Wraps the current policy's ``new_event_loop``
    so ``asyncio.run()`` in any test or tool gets a watched loop."""
    global _installed
    if _installed or not enabled("loop"):
        return
    _installed = True
    pol = asyncio.get_event_loop_policy()
    orig = pol.new_event_loop

    def _watched_new_event_loop():
        loop = orig()
        watch_loop(loop)
        return loop

    pol.new_event_loop = _watched_new_event_loop  # type: ignore[method-assign]


# ---------------------------------------------------------------------------
# lock-discipline sanitizer
# ---------------------------------------------------------------------------

# THE documented lock order (docs/STATIC_ANALYSIS.md renders this table;
# a test asserts the docs and this dict agree). Rules enforced on every
# blocking acquire:
#   * rank:  a thread may only acquire a lock whose rank is STRICTLY
#            greater than every rank it already holds;
#   * leaf:  while a leaf lock is held, acquiring ANYTHING is a
#            violation (leaf locks guard pure in-memory state and must
#            never nest outward);
#   * group: two locks sharing a group must never be held together even
#            in rank order (SpanRecorder: sink file I/O must not happen
#            under the ring lock — the PR 4 review contract).
# Non-blocking acquires (trylocks, Condition's ownership probe) are
# exempt: they cannot deadlock and Condition._is_owned probes the lock
# the thread already holds.
LOCK_RANKS: Dict[str, Dict[str, Any]] = {
    # NOT leaf: lane_snapshot() legally acquires qc.lane.cond inside it
    "qc.lane_registry": {"rank": 10},
    "verify_service.cond": {"rank": 20},
    "verify_service.done_cond": {"rank": 25},  # nests inside .cond
    "qc.lane.cond": {"rank": 30},
    "spans.recorder": {"rank": 40, "group": "spans"},
    "spans.sink": {"rank": 45, "group": "spans"},
    "qc.cache": {"rank": 90, "leaf": True},
}

_tls = threading.local()


def _held() -> List[Tuple[str, int, Optional[str], bool, int]]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _RankedLock:
    """Discipline-checking proxy over a ``threading.Lock``. Supports the
    full lock protocol (``acquire``/``release``/context manager) so it
    drops into ``threading.Condition(lock=...)`` unchanged."""

    __slots__ = ("_lock", "name", "rank", "leaf", "group")

    def __init__(self, lock: Any, name: str):
        spec = LOCK_RANKS[name]
        self._lock = lock
        self.name = name
        self.rank = spec["rank"]
        self.leaf = bool(spec.get("leaf"))
        self.group = spec.get("group")

    def _check(self) -> None:
        held = _held()
        if any(h[4] == id(self) for h in held):
            return  # re-entrant acquire of the same lock object
        for name, rank, group, leaf, _lid in held:
            msg = None
            if leaf:
                msg = (
                    f"acquired {self.name!r} while holding LEAF lock "
                    f"{name!r} — leaf locks must never nest outward"
                )
            elif self.group is not None and group == self.group:
                msg = (
                    f"{self.name!r} and {name!r} (group {group!r}) held "
                    "together — the group contract forbids nesting them "
                    "in either order"
                )
            elif rank >= self.rank:
                msg = (
                    f"lock order violation: acquired {self.name!r} "
                    f"(rank {self.rank}) while holding {name!r} "
                    f"(rank {rank}) — documented order is by "
                    "ascending rank"
                )
            if msg:
                _record(
                    "locks",
                    message=msg,
                    thread=threading.current_thread().name,
                    stack=traceback.format_stack(limit=8),
                )
                return  # one violation per acquire is enough signal

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._check()
        got = self._lock.acquire(blocking, timeout)
        if got:
            _held().append(
                (self.name, self.rank, self.group, self.leaf, id(self))
            )
        return got

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][4] == id(self):
                del held[i]
                break
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def wrap_lock(lock: Any, name: str, *, force: bool = False) -> Any:
    """Instrument ``lock`` under the documented name, or return it
    untouched when the locks sanitizer is off (zero overhead on the
    default path). ``name`` must be in :data:`LOCK_RANKS` — an unknown
    name is a programming error and raises immediately (at construction
    time, never mid-consensus). ``force`` opts in regardless of env
    (tests)."""
    if name not in LOCK_RANKS:
        raise KeyError(f"undocumented lock {name!r}: add it to LOCK_RANKS")
    if not (force or enabled("locks")):
        return lock
    return _RankedLock(lock, name)


# -- owning-thread annotations ----------------------------------------------

_owner_lock = threading.Lock()
_owners: Dict[Any, Tuple[int, str]] = {}
# owner keys embed id(obj): without release on teardown a recycled
# address would inherit a DEAD object's binding and record a spurious
# rebind (the same id()-reuse hazard the loop watch set discards on
# close). Owning objects call release_owner() when their confined
# lifetime ends; the cap bounds a long-lived armed process where some
# surface lacks a teardown hook (eviction only ever causes a fresh
# re-bind — a missed violation, never a false one).
_MAX_OWNERS = 4096


def bind_owner(key: Any, label: str) -> None:
    """Declare the CURRENT thread the owner of ``key`` (a worker binding
    its confined surface). Rebinding from a different thread is itself a
    violation — a surface must not silently migrate owners."""
    if not enabled("locks"):
        return
    me = threading.get_ident()
    with _owner_lock:
        prev = _owners.get(key)
        if prev is not None and prev[0] != me:
            _record(
                "locks",
                message=(
                    f"owner rebind: {label} bound to thread "
                    f"{threading.current_thread().name!r} but was owned "
                    f"by {prev[1]!r}"
                ),
                stack=traceback.format_stack(limit=8),
            )
        if key not in _owners and len(_owners) >= _MAX_OWNERS:
            _owners.pop(next(iter(_owners)))
        _owners[key] = (me, threading.current_thread().name)


def release_owner(key: Any) -> None:
    """Forget ``key``'s binding — called by the owning object's teardown
    so a later object at a recycled id() binds fresh. Safe from any
    thread and when the key was never bound (armed or not)."""
    with _owner_lock:
        _owners.pop(key, None)


def check_owner(key: Any, label: str) -> None:
    """Assert the current thread owns ``key``; first call binds (the
    loop-confined FlightRecorder pattern: whoever touches it first is
    the owner, anyone else after that is a cross-thread bug)."""
    if not enabled("locks"):
        return
    me = threading.get_ident()
    with _owner_lock:
        prev = _owners.get(key)
        if prev is None:
            if len(_owners) >= _MAX_OWNERS:
                _owners.pop(next(iter(_owners)))
            _owners[key] = (me, threading.current_thread().name)
            return
    if prev[0] != me:
        _record(
            "locks",
            message=(
                f"owning-thread violation: {label} touched from thread "
                f"{threading.current_thread().name!r} but is owned by "
                f"{prev[1]!r}"
            ),
            stack=traceback.format_stack(limit=8),
        )


def reset_owners() -> None:
    """Tests: forget all owner bindings (fresh objects, fresh owners)."""
    with _owner_lock:
        _owners.clear()
