# pbftlint: deterministic-module
"""FoundationDB-style deterministic simulation runtime (ISSUE 13).

One process, one thread, one committee — and a VIRTUAL clock. The
:class:`SimLoop` is a stock selector event loop whose ``time()`` is a
plain float: when the loop would otherwise sleep until the next
scheduled timer, virtual time JUMPS there instead. A wan3dc scenario
whose shaped links, view-change ladders, statesync retry ticks and
client backoffs burn minutes of wall clock runs in milliseconds, and —
because every product timer either lives on the loop (``call_later`` /
``call_at`` / ``wait_for``) or reads the injectable clock seam
(simple_pbft_tpu/clock.py) — the entire interleaving is a pure function
of the scenario seed. Same seed, same trace, byte for byte.

What runs under simulation is the REAL system: the same Replica /
Client / StateSync / ViewChanger / ShapedTransport / FaultInjector
objects every test and bench uses, over the in-process LocalNetwork.
The only behavioral difference is the clock seam's ``off_thread``,
which runs worker-thread work inline (a real thread completes in wall
time and would race virtual time), and ``qc.verify_qc_async``, which
pairs inline for the same reason.

On top of the runtime, :func:`run_scenario` drives one seeded scenario
end to end — committee up, fault schedule injected at virtual offsets,
paced client load, heal, bounded drain, a liveness probe — and judges
it with machine-checkable oracles:

- **safety**: honest replicas' committed digests must agree per slot,
  and honest auditors must have recorded zero violations unless the
  schedule armed a byzantine injector (docs/AUDIT.md);
- **liveness**: after every fault heals, a fresh request must commit
  within the probe patience (all in virtual time).

``tools/sim_explore.py`` loops this at thousands of runs per
invocation with coverage-guided schedule mutation; :func:`minimize`
delta-debugs a failing schedule's event list down to a minimal
replayable repro (docs/SCENARIOS.md has the workflow).
"""

from __future__ import annotations

import asyncio
import hashlib
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import clock as clock_mod
from .faults import FaultInjector, FaultSchedule, find_shaped

#: virtual-time origin. NOT 0.0: product code uses 0.0 floats as a
#: "never happened" sentinel (last_commit_mono, cooldown maps), and a
#: clock starting at 0 would put the first seconds of a run inside
#: every such sentinel's cooldown window.
SIM_START = 1000.0


class SimStall(RuntimeError):
    """The virtual run wedged: no runnable callbacks, no scheduled
    timers, no I/O — every task is awaiting an event that can never
    arrive. (The discrete-event analogue of a deadlock.)"""


class SimLoop(asyncio.SelectorEventLoop):
    """Selector event loop on virtual time.

    ``BaseEventLoop._run_once`` computes how long it may sleep in
    ``selector.select(timeout)`` from the earliest scheduled timer
    relative to ``self.time()``. We patch both ends of that contract:
    ``time()`` returns the virtual clock, and the selector's ``select``
    never sleeps — it polls real FDs (timeout 0) and, when nothing is
    ready, ADVANCES the virtual clock by the requested timeout. Timers
    become due instantly; runnable callbacks still run in exactly the
    order the real loop would run them.

    A ``select(None)`` request (no ready callbacks, no timers, no I/O)
    gets a bounded number of short REAL waits — a stray worker thread
    may still wake the loop via ``call_soon_threadsafe`` — and then
    raises :class:`SimStall`, because in a deterministic run it means
    the simulation can never progress again.
    """

    #: bounded real waits (MAX_IDLE_SPINS * IDLE_SPIN_S wall seconds)
    #: before an idle loop with nothing scheduled is declared wedged
    MAX_IDLE_SPINS = 200
    IDLE_SPIN_S = 0.02

    def __init__(self, start: float = SIM_START) -> None:
        super().__init__()
        self._sim_now = float(start)
        self._idle_spins = 0
        inner_select = self._selector.select

        def _sim_select(timeout: Optional[float] = None):
            events = inner_select(0)
            if events:
                self._idle_spins = 0
                return events
            if timeout:
                # the loop wanted to sleep until its next timer: jump
                self._sim_now += timeout
                self._idle_spins = 0
                return events
            if timeout == 0:
                return events
            self._idle_spins += 1
            if self._idle_spins > self.MAX_IDLE_SPINS:
                raise SimStall(
                    "no runnable callbacks, no scheduled timers, no "
                    "I/O: the virtual run can never progress (a task "
                    "awaits an event nothing will deliver)"
                )
            return inner_select(self.IDLE_SPIN_S)

        self._selector.select = _sim_select  # type: ignore[method-assign]

    def time(self) -> float:
        return self._sim_now


def _cancel_all_tasks(loop: asyncio.AbstractEventLoop) -> None:
    tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for t in tasks:
        t.cancel()
    if tasks:
        loop.run_until_complete(
            asyncio.gather(*tasks, return_exceptions=True)
        )
    loop.run_until_complete(loop.shutdown_asyncgens())
    try:
        loop.run_until_complete(loop.shutdown_default_executor())
    except Exception:
        pass  # no executor ever started (the common sim case)


def sim_run(
    main,
    *,
    start: float = SIM_START,
    wall_timeout: float = 300.0,
):
    """Run a coroutine to completion on a fresh :class:`SimLoop` with
    the sim clock installed (and the previous clock restored after —
    nestable under pytest, safe across failures).

    ``wall_timeout`` bounds REAL time: a runaway simulation (infinite
    virtual events) never trips virtual timeouts, so a daemon timer
    cancels the main task from outside and the run fails as
    :class:`SimStall` instead of hanging CI.
    """
    loop = SimLoop(start=start)
    prev_clock = clock_mod.install(clock_mod.SimClock(loop))
    asyncio.set_event_loop(loop)
    fired: List[bool] = []
    timer: Optional[threading.Timer] = None
    try:
        task = loop.create_task(main)
        if wall_timeout:
            def _expire() -> None:
                fired.append(True)
                loop.call_soon_threadsafe(task.cancel)

            timer = threading.Timer(wall_timeout, _expire)
            timer.daemon = True
            timer.start()
        try:
            return loop.run_until_complete(task)
        except asyncio.CancelledError:
            if fired:
                raise SimStall(
                    f"wall timeout {wall_timeout}s exceeded — the "
                    "simulation was cancelled from outside virtual time"
                ) from None
            raise
    finally:
        if timer is not None:
            timer.cancel()
        try:
            _cancel_all_tasks(loop)
        finally:
            clock_mod.install(prev_clock)
            asyncio.set_event_loop(None)
            loop.close()


# ---------------------------------------------------------------------------
# deterministic event trace
# ---------------------------------------------------------------------------


class SimTrace:
    """Append-only deterministic event log. Every line is a pure
    function of the scenario seed (virtual timestamps, protocol
    content); the sha256 fingerprint is the replay-identity check the
    acceptance criteria require (same seed => byte-identical trace)."""

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 base: float = SIM_START) -> None:
        self._loop = loop
        self._base = base
        self.lines: List[str] = []

    def note(self, tag: str, **kv: Any) -> None:
        t = self._loop.time() - self._base
        fields = " ".join(f"{k}={kv[k]}" for k in sorted(kv))
        self.lines.append(f"{t:.6f} {tag} {fields}")

    def fingerprint(self) -> str:
        h = hashlib.sha256()
        for line in self.lines:
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


@dataclass
class Scenario:
    """One seeded simulation scenario: committee shape, load, fault
    schedule, oracles' knobs. Everything is deterministic given the
    fields — a Scenario (plus its resolved schedule) IS the repro."""

    seed: int = 1
    n: int = 4
    clients: int = 1
    requests: int = 8          # per client, paced across the horizon
    horizon: float = 10.0      # virtual seconds of scheduled faulting
    drain: float = 60.0        # virtual ceiling for post-heal settling
    # Virtual budget per liveness probe. Calibrated ABOVE the view-change
    # backoff ladder's worst post-storm convergence: the ladder caps at
    # 60 s/replica, and a crashed TARGET-view primary costs two
    # backed-off expiries to walk past — measured recoveries at ~+70 s
    # (seed-10012: lossy storm + crash) and ~+115 s (search repro:
    # crash + late outbound cut; the committee sat at target 4 whose
    # primary was the crashed r0) — see the docs/SCENARIOS.md triage.
    # The oracle hunts WEDGES, not slow-but-converging failover tails;
    # convergence SPEED is a coverage signal (probe_s) instead. The
    # tail DEPTH scales with storm depth (deeper targets + 60 s-capped
    # desynchronized backoffs: measured +369 s on the checked-in
    # crash+cut repro, +750 s on a deeper double-symmetric-cut one), so
    # no fixed patience separates "slow" from "never" in every family —
    # 600 s covers the sweep/smoke families, deeper-storm search
    # families may legitimately surface beyond-patience tails as
    # findings for triage (docs/SCENARIOS.md), and a true wedge fails
    # at ANY patience.
    probe_patience: float = 600.0
    # schedule sources, in precedence order:
    schedule: Optional[FaultSchedule] = None  # explicit (replay/minimize)
    spec: str = ""             # --fault-schedule grammar
    gen: Dict[str, Any] = field(default_factory=dict)  # generate() kwargs
    qc_mode: bool = False
    verify_signatures: bool = True
    # speculative execution (ISSUE 15). Repro artifacts recorded BEFORE
    # the feature carry {"speculative": false} so they replay the exact
    # interleaving that was minimized (speculative reply traffic shifts
    # every downstream virtual timestamp); new scenarios default on.
    speculative: bool = True
    view_timeout: float = 1.0
    checkpoint_interval: int = 16
    watermark_window: int = 256
    request_timeout: float = 1.0
    probes: int = 2  # sequential post-heal liveness probes (ALL must land)
    defects: Tuple[str, ...] = ()  # planted-defect knobs (statesync.DEFECTS)
    audit_dir: Optional[str] = None  # write auditor ledgers here
    # open-loop traffic plane (ISSUE 17): a WorkloadSpec doc — usually
    # the compact {"preset": name, ...overrides} form. When set, the
    # plane REPLACES the closed-loop pumps (sc.clients/requests are
    # ignored; the committee gets spec.pool clients), workload events
    # ride the resolved FaultSchedule (schema v3), and the SLO oracles
    # in judge_slo() run after the safety/liveness oracles.
    workload: Optional[Dict[str, Any]] = None
    slo: Dict[str, Any] = field(default_factory=dict)  # judge_slo overrides
    flight_dir: Optional[str] = None  # write per-replica flight frames here
    # cross-replica trace plane (ISSUE 20): when set, wire stamping is
    # enabled for the run and the process-wide span recorder writes its
    # ledger (spans + cross-node edge docs + quorum docs) to
    # <trace_dir>/sim.spans.jsonl. Virtual-clock timestamps make the
    # joined ledger byte-deterministic across identical seeds. None =
    # off: pre-ISSUE-20 scenarios replay with identical fingerprints
    # (the envelope changes wire byte counts the SimTrace hashes).
    trace_dir: Optional[str] = None
    # self-driving perf plane (ISSUE 19). ``knobs``: fixed settings
    # {knob name -> ladder value} applied through the KnobRegistry after
    # build (the campaign's fixed-knob cells). ``controller``: online
    # KnobController config ({interval, profile, cooldown_ticks,
    # effect_ticks, osc_window_ticks, freeze_ticks, ledger}); None = off
    # — pre-ISSUE-19 scenarios replay with identical fingerprints.
    knobs: Dict[str, Any] = field(default_factory=dict)
    controller: Optional[Dict[str, Any]] = None
    name: str = ""

    def replica_ids(self) -> Tuple[str, ...]:
        return tuple(f"r{i}" for i in range(self.n))

    def workload_spec(self):
        """Resolved WorkloadSpec, or None for closed-loop scenarios."""
        if not self.workload:
            return None
        from .workload import spec_from_doc

        return spec_from_doc(self.workload)

    def resolved_schedule(self) -> FaultSchedule:
        if self.schedule is not None:
            return self.schedule
        ids = self.replica_ids()
        if self.spec:
            return FaultSchedule.parse(self.spec, self.horizon, ids)
        gen = dict(self.gen)
        wspec = self.workload_spec()
        if wspec is not None and "class_names" not in gen:
            # give generated burst/remix events real classes to target
            gen["class_names"] = tuple(c.name for c in wspec.honest())
        return FaultSchedule.generate(
            seed=self.seed, horizon=self.horizon, replica_ids=ids,
            **gen,
        )

    def to_doc(self) -> Dict[str, Any]:
        """JSON form for repro artifacts. The schedule rides RESOLVED
        (explicit event list), so the artifact replays the exact events
        even if generate()'s dealing ever changes."""
        return {
            "seed": self.seed,
            "n": self.n,
            "clients": self.clients,
            "requests": self.requests,
            "horizon": self.horizon,
            "drain": self.drain,
            "probe_patience": self.probe_patience,
            "schedule": self.resolved_schedule().summary(),
            "qc_mode": self.qc_mode,
            "verify_signatures": self.verify_signatures,
            "speculative": self.speculative,
            "view_timeout": self.view_timeout,
            "checkpoint_interval": self.checkpoint_interval,
            "watermark_window": self.watermark_window,
            "request_timeout": self.request_timeout,
            "probes": self.probes,
            "defects": list(self.defects),
            "workload": self.workload,
            "slo": dict(self.slo),
            "knobs": dict(self.knobs),
            "controller": self.controller,
            "trace_dir": self.trace_dir,
            "name": self.name,
        }

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "Scenario":
        return cls(
            seed=int(doc.get("seed", 1)),
            n=int(doc.get("n", 4)),
            clients=int(doc.get("clients", 1)),
            requests=int(doc.get("requests", 8)),
            horizon=float(doc.get("horizon", 10.0)),
            drain=float(doc.get("drain", 60.0)),
            probe_patience=float(doc.get("probe_patience", 600.0)),
            schedule=FaultSchedule.from_summary(doc["schedule"]),
            qc_mode=bool(doc.get("qc_mode", False)),
            verify_signatures=bool(doc.get("verify_signatures", True)),
            speculative=bool(doc.get("speculative", True)),
            view_timeout=float(doc.get("view_timeout", 1.0)),
            checkpoint_interval=int(doc.get("checkpoint_interval", 16)),
            watermark_window=int(doc.get("watermark_window", 256)),
            request_timeout=float(doc.get("request_timeout", 1.0)),
            probes=int(doc.get("probes", 2)),
            defects=tuple(doc.get("defects", ())),
            workload=doc.get("workload") or None,
            slo=dict(doc.get("slo", {})),
            knobs=dict(doc.get("knobs", {})),
            controller=doc.get("controller") or None,
            trace_dir=doc.get("trace_dir") or None,
            name=str(doc.get("name", "")),
        )


@dataclass
class SimResult:
    ok: bool
    failure: Optional[str]  # "<class>:<detail>" or None
    coverage: Dict[str, int]
    fingerprint: str
    committed: int
    wall_s: float
    vtime_s: float
    schedule: Dict[str, Any]  # FaultSchedule.summary() — replayable
    byzantine: List[str]
    app_digests: Dict[str, str]
    details: Dict[str, Any] = field(default_factory=dict)

    @property
    def failure_class(self) -> Optional[str]:
        return self.failure.split(":", 1)[0] if self.failure else None


def coverage_key(cov: Dict[str, int]) -> Tuple[int, ...]:
    """Bucketed coverage signature for novelty search. Coarse on
    purpose: the corpus should grow on qualitatively new interleavings
    (a view change happened at all; statesync aborted at all), not on
    every commit-count wiggle."""

    def bucket(x: int) -> int:
        for i, edge in enumerate((0, 2, 8, 32)):
            if x <= edge:
                return i
        return 4

    return (
        min(int(cov.get("max_view", 0)), 4),
        bucket(int(cov.get("commits", 0))),
        bucket(int(cov.get("vc_started", 0))),
        int(cov.get("statesync", 0) > 0),
        int(cov.get("statesync_restarts", 0) > 0),
        int(cov.get("statesync_abandoned", 0) > 0),
        # starvation ramp: 0 none, 1 <=3 ticks, 2 <=15, 3 <=63, 4 = at
        # the abandon cliff
        next((i for i, edge in enumerate((0, 3, 15, 63))
              if int(cov.get("statesync_stall_ticks", 0)) <= edge), 4),
        int(cov.get("violations", 0) > 0),
        min(int(cov.get("epoch", 0)), 2),
        bucket(int(cov.get("checkpoints", 0))),
        int(cov.get("timeouts", 0) > 0),
        # recovery-latency bucket: 0 <=5s, 1 <=30s, 2 <=90s, 3 <=240s,
        # 4 beyond (the near-wedge tail the search should dwell in)
        next((i for i, edge in enumerate((5, 30, 90, 240))
              if int(cov.get("probe_s", 0)) <= edge), 4),
        # speculative plane (ISSUE 15): did anything speculate, and did
        # a ROLLBACK fire — the ramp the search climbs toward
        # rollback-during-reconfig-during-view-change interleavings
        int(cov.get("spec_executed", 0) > 0),
        bucket(int(cov.get("spec_rolled_back", 0))),
        # traffic plane (ISSUE 17): load-shape search climbs per-class
        # shed/latency gradients, not just protocol-state novelty. All
        # keys absent on closed-loop runs (cov.get -> 0: legacy corpus
        # signatures extend with zeros, they don't change meaning).
        int(cov.get("offered", 0) > 0),
        # total shed percent ramp (ingress + replica-plane)
        next((i for i, edge in enumerate((0, 5, 20, 60))
              if int(cov.get("shed_pct", 0)) <= edge), 4),
        # worst honest-class p99 ramp (ms): the latency-tail gradient
        next((i for i, edge in enumerate((50, 250, 1000, 4000))
              if int(cov.get("worst_p99_ms", 0)) <= edge), 4),
        # fairness spread: worst honest accept-ratio percent vs best —
        # the starvation GRADIENT (the planted shed-bias defect lives
        # at the far end)
        next((i for i, edge in enumerate((5, 20, 50, 80))
              if int(cov.get("fair_gap_pct", 0)) <= edge), 4),
        bucket(int(cov.get("requeued", 0)) // 8),
        int(cov.get("floods_sent", 0) > 0),
    )


# ---------------------------------------------------------------------------
# the scenario driver
# ---------------------------------------------------------------------------


def _heal_everything(com) -> None:
    """Close every network fault so the drain phase judges the
    PROTOCOL's recovery, not a still-degraded network. Byzantine
    wrappers deliberately persist — a byzantine replica does not heal,
    and the committee must survive it regardless."""
    for r in com.replicas:
        shaped = find_shaped(r.transport)
        if shaped is not None:
            shaped.heal()
            shaped.clear_shaping()
    com.net.faults.partitions.clear()
    com.net.faults.drop_rate = 0.0
    com.net.faults.delay_range = (0.0, 0.0)


async def _pump(client, sc: Scenario, idx: int, stats: Dict[str, int]) -> None:
    """Paced client load: requests spread across the horizon so fault
    windows land on in-flight traffic. Mid-fault timeouts are expected
    (liveness is judged by the post-heal probe, not by the storm)."""
    gap = sc.horizon / max(1, sc.requests)
    retries = client.retries_for_patience(min(sc.horizon, 8.0))
    for i in range(sc.requests):
        try:
            await client.submit(f"put k{idx}_{i} v{i}", retries=retries)
            stats["accepted"] += 1
        except asyncio.TimeoutError:
            stats["timeouts"] += 1
        except Exception:
            stats["errors"] += 1
        await clock_mod.sleep(gap)


async def _drive(sc: Scenario, trace: SimTrace) -> SimResult:
    from .committee import LocalCommittee
    from .consensus import replica as replica_mod
    from .consensus import speculation as speculation_mod
    from .consensus import statesync as statesync_mod
    from . import spans as spans_mod
    from . import trace as trace_plane

    t0_wall = time.monotonic()
    loop = asyncio.get_running_loop()
    wspec = sc.workload_spec()
    build_extra: Dict[str, Any] = {}
    if wspec is not None and wspec.shed_watermark:
        # scale the replica shed plane to sim scale — the production
        # default watermark is sized for real deployments and a
        # sim-sized committee would never reach it, leaving the
        # overload/fairness seams unexercised
        build_extra["shed_watermark"] = wspec.shed_watermark
    com = LocalCommittee.build(
        n=sc.n,
        # the traffic plane multiplexes every virtual client over a
        # BOUNDED pool of real endpoints; closed-loop scenarios keep
        # their per-client pumps
        clients=wspec.pool if wspec is not None else sc.clients,
        qc_mode=sc.qc_mode,
        verify_signatures=sc.verify_signatures,
        view_timeout=sc.view_timeout,
        checkpoint_interval=sc.checkpoint_interval,
        watermark_window=sc.watermark_window,
        speculative=sc.speculative,
        **build_extra,
    )

    def _tap(src: str, dst: str, kind: str, nbytes: int, verdict: str) -> None:
        trace.note("net", s=src, d=dst, k=kind, n=nbytes, v=verdict)

    com.net.trace = _tap
    if sc.trace_dir:
        # cross-replica trace plane (ISSUE 20): stamp hot consensus wire
        # frames and route the process-wide span recorder (phase spans +
        # cross-node edge docs + per-cert quorum docs) into one joined
        # ledger. Enabled BEFORE any traffic flows; restored in finally
        # so back-to-back runs in one process stay independent (the
        # configure() calls also reset the per-sender span counters that
        # make two identical seeded runs byte-identical).
        trace_plane.configure(True)
        spans_mod.configure("sim", f"{sc.trace_dir}/sim.spans.jsonl")
    auditors: Dict[str, Any] = {}
    if sc.verify_signatures:
        # the audit plane taps the signature-VERIFIED stream; unsigned
        # committees have no proof-grade stream to observe
        auditors = com.attach_auditors(log_dir=sc.audit_dir)
    prev_defects = set(statesync_mod.DEFECTS)
    statesync_mod.DEFECTS |= set(sc.defects)
    # planted-defect registries are per-module; the scenario's defect
    # list feeds them all (unknown names are simply inert in each)
    prev_spec_defects = set(speculation_mod.DEFECTS)
    speculation_mod.DEFECTS |= set(sc.defects)
    prev_replica_defects = set(replica_mod.DEFECTS)
    replica_mod.DEFECTS |= set(sc.defects)
    schedule = sc.resolved_schedule()
    injector = FaultInjector(committee=com, schedule=schedule)
    failure: Optional[str] = None
    pump_stats: Dict[str, int] = {"accepted": 0, "timeouts": 0, "errors": 0}
    plane = None
    flight_recorders: List[Any] = []
    if wspec is not None:
        from .workload import TrafficPlane

        plane = TrafficPlane(
            com, wspec, schedule.workload, sc.seed, sc.horizon,
            # per-window load notes ride the trace, so the run
            # fingerprint covers the traffic timeline too
            note=lambda **kv: trace.note("load", **kv),
        )
        com.traffic_stats = plane.stats
    controller = None
    registry = None
    knob_baseline: Dict[str, Any] = {}
    final_knobs: Dict[str, Any] = {}
    if sc.knobs or sc.controller is not None:
        # perf plane (ISSUE 19): fixed knob cells go through the same
        # bounds-enforcing registry the online controller uses — an
        # off-ladder campaign cell fails loudly here, not silently
        registry = com.attach_knobs()
        # some knob targets are process-global (the QC verify lane is a
        # singleton) — snapshot before touching so this run's tuning
        # can't leak into the next run_scenario in the same process
        knob_baseline = registry.values()
        for kname in sorted(sc.knobs):
            registry.set(kname, sc.knobs[kname])
    try:
        com.start()
        if sc.controller is not None:
            from .controller import KnobController

            cdoc = dict(sc.controller)
            ledger_path = cdoc.pop("ledger", None)
            if ledger_path is None and sc.flight_dir:
                ledger_path = (
                    f"{sc.flight_dir}/{sc.name or 'sim'}.knobs.jsonl"
                )
            # the controller watches the PRIMARY's snapshot: traffic/
            # qc/knob blocks are committee-wide and the primary owns the
            # backlog the admission rules react to
            tel = com.node_telemetry(com.replicas[0].id)
            controller = KnobController(
                registry, tel.snapshot, ledger_path,
                interval=float(cdoc.pop("interval", 0.5)),
                profile=str(cdoc.pop("profile", "default")),
                cooldown_ticks=int(cdoc.pop("cooldown_ticks", 2)),
                effect_ticks=int(cdoc.pop("effect_ticks", 2)),
                osc_window_ticks=int(cdoc.pop("osc_window_ticks", 6)),
                freeze_ticks=int(cdoc.pop("freeze_ticks", 8)),
            )
            controller.start()
        for c in com.clients:
            c.request_timeout = sc.request_timeout
        if sc.flight_dir:
            from .telemetry import FlightRecorder

            for r in com.replicas:
                fr = FlightRecorder(
                    com.node_telemetry(r.id),
                    f"{sc.flight_dir}/flight_{r.id}.jsonl",
                )
                fr.start()
                flight_recorders.append(fr)
        inj_task = loop.create_task(
            injector.run(stop_at=clock_mod.now() + sc.horizon)
        )
        if plane is not None:
            pumps = [loop.create_task(plane.run())]
        else:
            pumps = [
                loop.create_task(_pump(c, sc, i, pump_stats))
                for i, c in enumerate(com.clients)
            ]
        await clock_mod.sleep(sc.horizon)
        injector.stop()
        await asyncio.gather(inj_task, return_exceptions=True)
        _heal_everything(com)
        trace.note("healed")
        # bounded drain: let in-flight pumps finish or give up
        done, pending = await asyncio.wait(pumps, timeout=sc.drain)
        for p in pending:
            p.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        if plane is not None:
            # settle the plane's in-flight submissions (whatever
            # outlives the budget is counted abandoned, never lost)
            await plane.drain(sc.drain)
            pump_stats["accepted"] += sum(plane.stats.accepted.values())
            pump_stats["timeouts"] += sum(plane.stats.timeouts.values())
            pump_stats["errors"] += sum(plane.stats.errors.values())
        # liveness probes: with every network fault healed, a SEQUENCE
        # of fresh requests must commit within the (virtual) probe
        # patience each. A sequence, not one: several wedge shapes (a
        # replica stuck below the stable watermark, a committee one
        # quorum member short of advancing h) stay live for a few more
        # slots and only hit the wall at the watermark window's edge.
        probe = com.clients[0]
        probes_ok = 0
        t_probe0 = clock_mod.now()
        for k in range(sc.probes):
            try:
                await asyncio.wait_for(
                    probe.submit(
                        f"put __probe{k}__ ok",
                        retries=probe.retries_for_patience(sc.probe_patience),
                    ),
                    sc.probe_patience,
                )
                probes_ok += 1
            except asyncio.TimeoutError:
                failure = f"liveness:probe-timeout@{k}"
                break
        trace.note("probes", ok=probes_ok, want=sc.probes)
        pump_stats["probes_ok"] = probes_ok
        pump_stats["probe_s"] = int(clock_mod.now() - t_probe0)
        for fr in flight_recorders:
            await fr.stop()
        flight_recorders = []
        if controller is not None:
            await controller.stop()  # seals the decision ledger
        if sc.trace_dir:
            # seal the quorum ledger: certs still open at shutdown (a
            # straggler vote that never arrived) finalize with what was
            # seen, while the span sink is still attached
            for r in com.replicas:
                r.qstats.flush_all()
        await com.stop()
    finally:
        statesync_mod.DEFECTS.clear()
        statesync_mod.DEFECTS |= prev_defects
        speculation_mod.DEFECTS.clear()
        speculation_mod.DEFECTS |= prev_spec_defects
        replica_mod.DEFECTS.clear()
        replica_mod.DEFECTS |= prev_replica_defects
        for fr in flight_recorders:  # failure path: stop what's left
            try:
                await fr.stop()
            except Exception:
                pass
        if controller is not None and controller._task is not None:
            try:  # failure path: the happy path already stopped it
                await controller.stop()
            except Exception:
                pass
        for a in auditors.values():
            a.close()
        if sc.trace_dir:
            # detach the process-wide surfaces as we found them so the
            # next run_scenario in this process starts untraced
            trace_plane.configure(False)
            spans_mod.configure("", None)
        if registry is not None:
            # read the tuned values for details, then put process-global
            # knob targets (qc lane singleton) back as we found them so
            # back-to-back runs in one process stay seed-deterministic
            final_knobs = registry.values()
            for kname, kval in sorted(knob_baseline.items()):
                try:
                    registry.set(kname, kval)
                except Exception:
                    pass

    # ---- oracles + coverage over the final state ----------------------
    byz = sorted({w.node_id for w in injector.byzantine})
    honest = [r for r in com.replicas if r.id not in byz]
    # safety: per-slot committed-digest agreement across honest replicas
    agreed: Dict[int, str] = {}
    divergent_seq: Optional[int] = None
    for r in honest:
        for seq, digest in r.committed_log.items():
            if seq in agreed and agreed[seq] != digest:
                divergent_seq = seq
            agreed.setdefault(seq, digest)
    if divergent_seq is not None:
        failure = f"safety:commit-divergence@seq{divergent_seq}"
    # speculative-leak oracle (ISSUE 15): checkpoint digests are a
    # deterministic function of COMMITTED history, identical on every
    # honest replica at the same seq — replicas speculate on different
    # timings, so any leak of speculative state into a checkpoint
    # snapshot diverges the digests instantly. This is the
    # machine-checkable form of "speculative state never leaks into a
    # checkpoint digest".
    cp_by_seq: Dict[int, str] = {}
    cp_divergent: Optional[int] = None
    for r in honest:
        for seq, dg in r.checkpoint_digests.items():
            if seq in cp_by_seq and cp_by_seq[seq] != dg:
                cp_divergent = seq
            cp_by_seq.setdefault(seq, dg)
    if cp_divergent is not None and failure is None:
        failure = f"safety:checkpoint-divergence@seq{cp_divergent}"
    # ...and never into a committed reply: the replicated reply cache is
    # checkpoint state, so a speculative mark inside it would both leak
    # and replay a possibly-rolled-back result to retrying clients
    if failure is None and any(
        getattr(rep, "spec", 0)
        for r in honest
        for per in r.recent_replies.values()
        for rep in per.values()
    ):
        failure = "safety:spec-reply-in-committed-cache"
    violations = sum(
        getattr(auditors.get(r.id), "violations", 0) for r in honest
    )
    if violations and not byz and failure is None:
        failure = "safety:unexpected-evidence"
    # an HONEST replica accused by honest auditors is a safety bug
    # regardless of injected byzantine company: the injectors sign their
    # own lies, so evidence naming anyone else means a replica's
    # replicated state genuinely diverged (the ISSUE 15 leak shape:
    # speculative state reaching a checkpoint digest shows up exactly
    # here, as checkpoint-divergence evidence among honest nodes)
    accused_union: set = set()
    for r in honest:
        accused_union |= set(
            getattr(auditors.get(r.id), "accused_ever", ()) or ()
        )
    honest_accused = sorted(accused_union - set(byz))
    if honest_accused and failure is None:
        failure = f"safety:honest-accused:{','.join(honest_accused)}"
    # SLO oracles (ISSUE 17): judged AFTER safety/liveness so a genuine
    # protocol failure keeps its (more actionable) failure class;
    # verdicts ride details.slo either way
    slo_verdicts: Dict[str, Any] = {}
    if plane is not None and wspec is not None:
        from .workload import judge_slo

        slo_verdicts, slo_failure = judge_slo(
            plane.stats, wspec, sc.slo or None
        )
        if slo_failure is not None and failure is None:
            failure = slo_failure
    app_digests = {}
    for r in honest:
        snap = r.app.snapshot()
        app_digests[r.id] = hashlib.sha256(
            repr(sorted(snap.items()) if isinstance(snap, dict) else snap)
            .encode()
        ).hexdigest()[:16]

    cov: Dict[str, int] = {
        "commits": max((r.executed_seq for r in honest), default=0),
        "max_view": max((r.view for r in com.replicas), default=0),
        "views_installed": sum(
            r.metrics.get("views_installed", 0) for r in com.replicas
        ),
        "vc_started": sum(
            r.metrics.get("view_changes_started", 0) for r in com.replicas
        ),
        "statesync": sum(
            r.metrics.get("statesync_transfers", 0) for r in com.replicas
        ),
        "statesync_restarts": sum(
            r.metrics.get("statesync_restarts", 0) for r in com.replicas
        ),
        "statesync_abandoned": sum(
            r.metrics.get("statesync_abandoned", 0) for r in com.replicas
        ),
        # worst consecutive no-progress stretch any transfer saw: the
        # GRADIENT toward starvation interleavings (abandon needs 64
        # ticks; without this ramp the search only sees the cliff)
        "statesync_stall_ticks": max(
            (r.metrics.get("statesync_stall_ticks_max", 0)
             for r in com.replicas), default=0,
        ),
        "checkpoints": max(
            (r.stable_seq for r in com.replicas), default=0
        ) // max(1, sc.checkpoint_interval),
        "violations": violations,
        "epoch": max((r.cfg.epoch for r in com.replicas), default=0),
        "timeouts": pump_stats["timeouts"],
        "accepted": pump_stats["accepted"],
        # post-heal recovery latency (virtual): how long the liveness
        # probes took end to end — the ladder-tail signal (slow failover
        # is COVERAGE to steer toward, not an oracle failure)
        "probe_s": pump_stats.get("probe_s", 0),
        "crashes": injector.crashes_applied,
        "faults_applied": injector.applied_count,
        # speculative plane (ISSUE 15): slots executed at PREPARED and
        # slots walked back — the rollback count is the novelty signal
        # the schedule search steers toward (rollback-during-reconfig-
        # during-view-change interleavings live behind it)
        "spec_executed": sum(
            r.metrics.get("spec_executed", 0) for r in com.replicas
        ),
        "spec_rolled_back": sum(
            r.metrics.get("spec_rolled_back", 0) for r in com.replicas
        ),
    }
    if plane is not None:
        # traffic-plane coverage (ISSUE 17): the per-class shed/latency
        # gradients load-shape search climbs. Closed-loop runs carry
        # none of these keys (coverage_key reads them via cov.get).
        stats = plane.stats
        t = stats.totals()
        replica_shed = sum(
            r.metrics.get("messages_shed", 0) for r in com.replicas
        )
        honest_ratios = [
            stats.accept_ratio(n) for n in stats.class_names
            if n not in stats.byz_names and stats.offered[n] >= 50
        ]
        cov.update({
            "offered": t["offered"],
            "ingress_shed": t["shed"],
            "replica_shed": replica_shed,
            "shed_pct": int(
                100 * (t["shed"] + replica_shed) / max(1, t["offered"])
            ),
            "worst_p99_ms": int(stats.worst_honest_p99_ms()),
            "worst_e2e_p99_ms": int(stats.worst_honest_e2e_p99_ms()),
            "fair_gap_pct": int(
                100 * (max(honest_ratios) - min(honest_ratios))
            ) if honest_ratios else 0,
            "requeued": t["requeued"],
            "floods_sent": t["floods_sent"],
            "clients_touched": t["clients"],
        })
    # fold the consensus outcome into the trace so the fingerprint
    # covers protocol RESULTS, not just wire traffic
    for r in sorted(honest, key=lambda x: x.id):
        trace.note(
            "final", id=r.id, exec=r.executed_seq, view=r.view,
            stable=r.stable_seq, app=app_digests[r.id],
        )

    details: Dict[str, Any] = {
        "pump": dict(pump_stats), "trace_lines": len(trace.lines),
    }
    if plane is not None:
        details["traffic"] = plane.stats.snapshot_block()
        # flat block for bench ledger lines (workload.bench_record /
        # tools/traffic_smoke.py — the run itself stays ledger-agnostic)
        details["traffic_bench"] = plane.stats.bench_traffic_block(
            sc.horizon
        )
        details["slo"] = slo_verdicts
    if registry is not None:
        # perf plane (ISSUE 19): final knob values, controller activity,
        # and the PBL006 invariant (zero post-warm compiles while the
        # controller moved batch-shape knobs) — knob_campaign reads this
        pwc = 0
        for r in com.replicas:
            snap_fn = getattr(getattr(r, "verifier", None), "snapshot", None)
            if callable(snap_fn):
                try:
                    shapes = snap_fn().get("device_shapes") or {}
                    pwc += int(shapes.get("post_warm_compiles", 0) or 0)
                except Exception:
                    pass
        ctl: Dict[str, Any] = {
            "knobs": final_knobs,
            "post_warm_compiles": pwc,
        }
        if controller is not None:
            ctl.update(controller.coverage())
            ctl["ledger"] = (
                controller.ledger.path if controller.ledger else ""
            )
            cov["ctl_actions"] = controller.actions
            cov["ctl_oscillations"] = controller.oscillations
        details["controller"] = ctl
    return SimResult(
        ok=failure is None,
        failure=failure,
        coverage=cov,
        fingerprint=trace.fingerprint(),
        committed=cov["commits"],
        wall_s=round(time.monotonic() - t0_wall, 3),
        vtime_s=round(loop.time() - SIM_START, 3),
        schedule=schedule.summary(),
        byzantine=byz,
        app_digests=app_digests,
        details=details,
    )


def run_scenario(sc: Scenario, *, wall_timeout: float = 120.0) -> SimResult:
    """Run one scenario under the virtual clock; never raises for
    in-scenario failures — the oracle verdict rides SimResult.failure
    (SimStall becomes ``liveness:sim-stall``)."""
    loop_holder: List[SimTrace] = []

    async def main() -> SimResult:
        trace = SimTrace(asyncio.get_running_loop())
        loop_holder.append(trace)
        return await _drive(sc, trace)

    try:
        return sim_run(main(), wall_timeout=wall_timeout)
    except SimStall as e:
        trace = loop_holder[0] if loop_holder else None
        return SimResult(
            ok=False,
            failure="liveness:sim-stall",
            coverage={},
            fingerprint=trace.fingerprint() if trace else "",
            committed=0,
            wall_s=0.0,
            vtime_s=0.0,
            schedule=sc.resolved_schedule().summary(),
            byzantine=[],
            app_digests={},
            details={"stall": str(e)},
        )


# ---------------------------------------------------------------------------
# schedule minimization (delta debugging)
# ---------------------------------------------------------------------------


def minimize(
    sc: Scenario,
    *,
    max_runs: int = 160,
    progress: Optional[Callable[[str], None]] = None,
) -> Tuple[Scenario, SimResult, int]:
    """ddmin over the failing scenario's event list — fault AND workload
    events as one tagged pool (since schema v3 a repro's load shape is
    part of the replay tuple, and a flash crowd can be as load-bearing
    as a crash): find a (locally) minimal subset that still produces the
    SAME failure class, each probe being one full deterministic re-run.
    Returns the minimized scenario (explicit schedule), its result, and
    how many runs the search spent."""
    base_sched = sc.resolved_schedule()
    baseline = run_scenario(replace(sc, schedule=base_sched))
    if baseline.failure is None:
        raise ValueError("minimize() wants a FAILING scenario")
    target = baseline.failure_class
    runs = 1

    def _sched(items: List[Tuple[str, Any]]) -> FaultSchedule:
        return FaultSchedule(
            seed=base_sched.seed,
            horizon=base_sched.horizon,
            events=tuple(e for tag, e in items if tag == "f"),
            workload=tuple(e for tag, e in items if tag == "w"),
        )

    def fails(items: List[Tuple[str, Any]]) -> bool:
        nonlocal runs
        if runs >= max_runs:
            return False
        runs += 1
        res = run_scenario(replace(sc, schedule=_sched(items)))
        return res.failure_class == target

    items: List[Tuple[str, Any]] = (
        [("f", e) for e in base_sched.events]
        + [("w", e) for e in base_sched.workload]
    )
    granularity = 2
    while len(items) >= 2 and runs < max_runs:
        chunk = max(1, len(items) // granularity)
        shrunk = False
        i = 0
        while i < len(items):
            cand = items[:i] + items[i + chunk:]
            if cand and fails(cand):
                items = cand
                granularity = max(2, granularity - 1)
                shrunk = True
                if progress:
                    progress(f"shrunk to {len(items)} events ({runs} runs)")
            else:
                i += chunk
        if not shrunk:
            if granularity >= len(items):
                break
            granularity = min(len(items), granularity * 2)
    # final greedy pass: drop single events
    i = 0
    while i < len(items) and len(items) > 1 and runs < max_runs:
        cand = items[:i] + items[i + 1:]
        if fails(cand):
            items = cand
        else:
            i += 1
    final = replace(sc, schedule=_sched(items))
    return final, run_scenario(final), runs


# ---------------------------------------------------------------------------
# repro artifacts
# ---------------------------------------------------------------------------

ARTIFACT_SCHEMA = "sim-repro-v1"


def artifact_doc(sc: Scenario, result: SimResult) -> Dict[str, Any]:
    return {
        "schema": ARTIFACT_SCHEMA,
        "scenario": sc.to_doc(),
        "failure": result.failure,
        "coverage": result.coverage,
        "fingerprint": result.fingerprint,
        "vtime_s": result.vtime_s,
        "byzantine": result.byzantine,
    }


def scenario_from_artifact(doc: Dict[str, Any]) -> Scenario:
    if doc.get("schema") != ARTIFACT_SCHEMA:
        raise ValueError(
            f"not a {ARTIFACT_SCHEMA} artifact: schema={doc.get('schema')!r}"
        )
    return Scenario.from_doc(doc["scenario"])
