"""Deployment descriptor: committees as data, processes as peers.

Parity target: the reference's entire deployment story is a hard-coded
4-entry NodeTable (node.go:60-65: localhost:1111-1114) plus run.bat. Here
a deployment is a JSON document shared by every node and client:

    {
      "options": {"checkpoint_interval": 64, "view_timeout": 2.0, ...},
      "replicas": {"r0": {"host": "127.0.0.1", "port": 7000,
                           "pubkey": "<hex>",
                           "kx_pubkey": "<hex>"}, ...},
      "clients":  {"c0": {"host": "127.0.0.1", "port": 7500,
                           "pubkey": "<hex>",
                           "kx_pubkey": "<hex>"}, ...}
    }

``kx_pubkey`` (X25519, optional) enables MAC-authenticated replies
between that node and its peers (crypto/mac.py); entries lacking it
fall back to Ed25519-signed replies.

Private key seeds live in separate per-node files (`<id>.seed`, 32 raw
bytes) so the shared document carries no secrets.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Tuple

from .config import CommitteeConfig, KeyPair

_OPTION_FIELDS = (
    "checkpoint_interval",
    "watermark_window",
    "max_batch",
    "view_timeout",
    "verify_signatures",
    # identities whose signed __reconfig__ operations are authorized
    # (JSON list in the document; empty/absent = reconfiguration
    # disabled). Named explicitly per deployment — unlike
    # make_test_committee, a real deployment trusts no client by default.
    "admin_ids",
)


def _cfg_options(options: Dict) -> Dict:
    out = {k: v for k, v in options.items() if k in _OPTION_FIELDS}
    if "admin_ids" in out:
        ids = out["admin_ids"]
        if isinstance(ids, str):
            # a bare "c0" would otherwise iterate into ('c', '0') —
            # silently authorizing nobody and denying the intended admin
            ids = (ids,)
        out["admin_ids"] = tuple(str(i) for i in ids)
    return out


@dataclass
class Deployment:
    cfg: CommitteeConfig
    addresses: Dict[str, Tuple[str, int]]  # every node and client

    def addr(self, node_id: str) -> Tuple[str, int]:
        return self.addresses[node_id]

    def peers_for(self, node_id: str) -> Dict[str, Tuple[str, int]]:
        return {k: v for k, v in self.addresses.items() if k != node_id}


def generate(
    out_dir: str,
    n: int = 4,
    clients: int = 1,
    host: str = "127.0.0.1",
    base_port: int = 7000,
    **options,
) -> Deployment:
    """Create a fresh deployment: committee.json + per-node seed files."""
    os.makedirs(out_dir, exist_ok=True)
    doc: Dict = {"options": options, "replicas": {}, "clients": {}}
    addresses: Dict[str, Tuple[str, int]] = {}
    pubkeys: Dict[str, bytes] = {}
    names = [(f"r{i}", "replicas", base_port + i) for i in range(n)] + [
        (f"c{i}", "clients", base_port + 500 + i) for i in range(clients)
    ]
    from .crypto import mac as mac_mod

    kx_pubkeys: Dict[str, bytes] = {}
    for name, kind, port in names:
        seed = os.urandom(32)
        kp = KeyPair.generate(seed)
        kx = mac_mod.kx_pubkey(seed)
        with open(os.path.join(out_dir, f"{name}.seed"), "wb") as fh:
            fh.write(seed)
        doc[kind][name] = {
            "host": host,
            "port": port,
            "pubkey": kp.pub.hex(),
        }
        if kx is not None:
            # X25519 key-exchange pubkey: enables MAC'd replies (the
            # point-to-point fast path, crypto/mac.py); derived from the
            # same seed so the per-node secret material stays one file.
            # Omitted when no X25519 backend exists — replies then fall
            # back to Ed25519 signatures (mac.kx_available).
            doc[kind][name]["kx_pubkey"] = kx.hex()
            kx_pubkeys[name] = kx
        addresses[name] = (host, port)
        pubkeys[name] = kp.pub
    with open(os.path.join(out_dir, "committee.json"), "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    cfg = CommitteeConfig(
        replica_ids=tuple(sorted(doc["replicas"])),
        pubkeys=pubkeys,
        kx_pubkeys=kx_pubkeys,
        # boot address book rides the config (and thus every checkpoint
        # snapshot): joiners and reconfigurations inherit reachability,
        # not just membership (transport.base.update_peer_book)
        addrs=dict(addresses),
        **_cfg_options(options),
    )
    return Deployment(cfg=cfg, addresses=addresses)


def load(path: str) -> Deployment:
    """Load committee.json (raises ValueError on malformed documents)."""
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError("deployment must be a JSON object")
    replicas = doc.get("replicas")
    clients = doc.get("clients", {})
    options = doc.get("options", {})
    if not isinstance(replicas, dict) or not replicas:
        raise ValueError("deployment needs a non-empty 'replicas' map")
    addresses: Dict[str, Tuple[str, int]] = {}
    pubkeys: Dict[str, bytes] = {}
    kx_pubkeys: Dict[str, bytes] = {}
    for kind in (replicas, clients):
        for name, ent in kind.items():
            if not isinstance(ent, dict):
                raise ValueError(f"bad node entry: {name}")
            try:
                addresses[name] = (str(ent["host"]), int(ent["port"]))
                pubkeys[name] = bytes.fromhex(ent["pubkey"])
                # optional (older documents lack it): its absence just
                # falls the affected pairs back to Ed25519-signed replies
                if "kx_pubkey" in ent:
                    kx_pubkeys[name] = bytes.fromhex(ent["kx_pubkey"])
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(f"bad node entry {name}: {e}") from None
    cfg = CommitteeConfig(
        replica_ids=tuple(sorted(replicas)),
        pubkeys=pubkeys,
        kx_pubkeys=kx_pubkeys,
        addrs=dict(addresses),
        **_cfg_options(options),
    )
    return Deployment(cfg=cfg, addresses=addresses)


def read_seed(deploy_dir: str, node_id: str) -> bytes:
    with open(os.path.join(deploy_dir, f"{node_id}.seed"), "rb") as fh:
        seed = fh.read()
    if len(seed) != 32:
        raise ValueError(f"seed file for {node_id} must be 32 bytes")
    return seed
