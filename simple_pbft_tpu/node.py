"""Replica node binary: ``python -m simple_pbft_tpu.node``.

Parity target: the reference's pbftNode.go (flags -id/-log, one process
per replica, blocking serve). Here: deployment document instead of a
hard-coded table, pluggable verifier backend, structured logging, clean
shutdown.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from . import deploy
from .consensus.replica import Replica
from .crypto.verifier import CpuVerifier, InsecureVerifier, best_cpu_verifier
from .transport.tcp import TcpTransport


def make_transport(name: str, node_id: str, dep: "deploy.Deployment"):
    """tcp (default, intra-host) or grpc (the DCN path, SURVEY.md §2.3)."""
    cls = TcpTransport
    if name == "grpc":
        from .transport.grpc import GrpcTransport

        cls = GrpcTransport
    elif name != "tcp":
        raise SystemExit(f"unknown transport: {name}")
    return cls(
        node_id=node_id,
        listen_addr=dep.addr(node_id),
        peers=dep.peers_for(node_id),
    )


def make_verifier(
    name: str,
    dep=None,
    verify_max_pending: int = 65536,
    verify_deadline: float = 60.0,
):
    if name == "tpu":
        from .crypto.coalesce import VerifyService
        from .crypto.tpu_verifier import TpuVerifier

        # overload knobs (docs/RESILIENCE.md): bounded admission rejects
        # with Overloaded past max_pending; the dispatch-deadline
        # watchdog fails a stalled device sweep over to the CPU verifier
        # and quarantines the device path (deadline <= 0 disables it)
        svc_kw = dict(
            max_pending=verify_max_pending,
            dispatch_deadline=verify_deadline if verify_deadline > 0 else None,
        )
        if dep is None:
            return VerifyService(TpuVerifier(), **svc_kw)
        # Size the key bank to the deployment's published key population
        # and pre-pay the device compiles before serving traffic: the
        # jit signature includes the table shape, so a bank growing
        # under live traffic means minutes-long compiles mid-consensus
        # (the round-4 consensus-on-chip zero-commit bug). The warm runs
        # THROUGH the service (shape-stable coalescing, ISSUE 3): a
        # coalesced take can reach the service's max_batch even when one
        # replica's drain sweep is smaller, so warming only the sweep
        # bound left the top buckets cold — the r5 qc256 8127-item pile
        # compiled mid-run. The VerifyService wrapper gives the node
        # async non-blocking dispatch and a CPU path for tiny sweeps
        # (one process = one replica here, so coalescing is across
        # consecutive sweeps rather than replicas).
        pubkeys = list(dep.cfg.pubkeys.values())
        svc = VerifyService(
            TpuVerifier(initial_keys=len(pubkeys) + 32), **svc_kw
        )
        svc.warm_for_population(pubkeys, max_sweep=4096)
        return svc
    if name == "cpu":
        return best_cpu_verifier()
    if name == "cpu-pure":
        return CpuVerifier()
    if name == "insecure":
        return InsecureVerifier()
    raise SystemExit(f"unknown verifier backend: {name}")


def _dump_final(node_id: str, replica, transport, watchdog=None) -> None:
    """Shutdown dump: counters + sweep/verify/commit histograms as one
    JSON line each — the observability the perf work steers by (VERDICT
    weak #8). Called from run_node's ``finally`` so a FATAL EXCEPTION
    leaves the same post-mortem a clean SIGTERM would have (pre-ISSUE-2,
    a crash lost everything). With a progress watchdog attached, the
    same path writes a FULL forensic autopsy (task/thread stacks,
    in-flight instances, recent spans) — so SIGTERM/SIGINT leaves the
    deep dump too, not just flight-interval snapshots (ISSUE 4)."""
    logging.info("%s: stats %s", node_id, replica.stats.dump(replica.metrics))
    logging.info(
        "%s: transport %s", node_id, dict(getattr(transport, "metrics", {}))
    )
    svc = replica.verifier
    if hasattr(svc, "snapshot"):
        # overload-resilience counters (crypto/coalesce.py): was this run
        # ever shedding, did the device watchdog fire, how deep did the
        # pending pile get — the post-mortem for any degraded window
        logging.info("%s: verify service %s", node_id, svc.snapshot())
    auditor = getattr(replica, "auditor", None)
    if auditor is not None:
        # the accountability summary: did this node witness any safety
        # violation, and where its evidence ledger lives (docs/AUDIT.md)
        logging.info("%s: audit %s", node_id, auditor.snapshot())
    from . import sanitize

    viols = sanitize.take_violations()
    if viols:
        # an armed sanitizer's findings must reach the operator, not
        # die with the process (violations never raise into consensus)
        logging.warning(
            "%s: %s", node_id, sanitize.format_violations(viols)
        )
    if watchdog is not None:
        try:
            # a DISTINCT file: the shutdown snapshot must never overwrite
            # a mid-run stall autopsy at the watchdog's own path — that
            # wedged-state forensic is the artifact this subsystem exists
            # to preserve
            final_path = (
                watchdog.path.replace(".autopsy.json", ".final.autopsy.json")
                if watchdog.path else None
            )
            path = watchdog.dump(
                "final dump (signal or fatal exit)", path=final_path
            )
            if path:
                logging.info("%s: final autopsy at %s", node_id, path)
        except Exception:
            logging.exception("%s: final autopsy failed", node_id)


async def run_node(args) -> None:
    from . import spans
    from .telemetry import (
        FlightRecorder,
        LoopLagGauge,
        NodeTelemetry,
        ProgressWatchdog,
        RequestTracer,
        StatusServer,
        resolve_sample_mod,
        write_status_file,
    )

    dep = deploy.load(os.path.join(args.deploy_dir, "committee.json"))
    seed = deploy.read_seed(args.deploy_dir, args.id)
    transport = make_transport(args.transport, args.id, dep)
    await transport.start()
    if getattr(args, "wan_profile", ""):
        # WAN rehearsal (ISSUE 7): impose the named profile's per-link
        # latency/jitter/loss on this node's OUTBOUND links. Every node
        # of the committee should run the same profile so both directions
        # of each pair are shaped (docs/SCENARIOS.md).
        from .faults import ShapedTransport

        transport = ShapedTransport.wrap_profile(
            transport, args.wan_profile, list(dep.cfg.replica_ids)
        )
    # verifier construction includes warm_for_population — minutes of
    # XLA compiles on a cold cache. Run it off-loop: the transport is
    # already started, and blocking the loop here stalls its accept /
    # reconnect machinery (and every heartbeat) for the whole warm.
    # Found by the PBFT_SANITIZE=loop sanitizer (ISSUE 8): the static
    # checker cannot resolve the call (warm_for_population is not a
    # unique method name) — exactly the dynamic-backstop case.
    verifier = await asyncio.to_thread(
        make_verifier,
        args.verifier,
        dep,
        verify_max_pending=args.verify_max_pending,
        verify_deadline=args.verify_deadline,
    )
    replica = Replica(
        node_id=args.id,
        cfg=dep.cfg,
        seed=seed,
        transport=transport,
        verifier=verifier,
        max_drain=args.max_drain,
        shed_watermark=args.shed_watermark,
    )
    log_dir = getattr(args, "resolved_log_dir", None)
    # per-stage latency attribution (ISSUE 4): spans always accumulate
    # in-memory histograms; with a log_dir they also land as JSONL for
    # tools/critical_path.py's cross-node decomposition
    spans.configure(
        args.id,
        os.path.join(log_dir, f"{args.id}.spans.jsonl") if log_dir else None,
    )
    # cross-replica trace plane (ISSUE 20): wire-envelope stamping is
    # per-process global and off by default; edge/quorum docs share the
    # span ledger, so a sink (log_dir) is required for them to persist
    if getattr(args, "trace", 0) and log_dir:
        from . import trace as trace_plane

        trace_plane.configure(True)
    # device-plane observatory (ISSUE 14): reset the per-dispatch device
    # ledger HERE — after the verifier warm, so warmup compiles never
    # pollute the serving window's occupancy/rate aggregates, and in
    # lockstep with spans so tools/verify_observatory.py can reconcile
    # the two surfaces over the same window
    from . import devledger

    devledger.configure(args.id)
    if getattr(args, "device_profile", 0) > 0 and log_dir:
        # optional deep capture: ONE bounded jax.profiler trace window,
        # armed off-loop on a sidecar thread (never in consensus paths);
        # artifacts land under <log-dir>/device_profile for offline
        # analysis next to the flight timeline
        devledger.arm_profile(
            os.path.join(log_dir, "device_profile"), args.device_profile
        )
    tracer = None
    sample_mod = resolve_sample_mod(args.trace_sample)
    if sample_mod > 0 and log_dir:
        tracer = RequestTracer(
            args.id,
            sample_mod=sample_mod,
            path=os.path.join(log_dir, f"{args.id}.trace.jsonl"),
        )
        replica.tracer = tracer
    auditor = None
    if args.audit and log_dir:
        # consensus audit plane (ISSUE 5): online safety-invariant
        # monitor over the verified message stream; violations become
        # tamper-evident records in <log-dir>/<id>.evidence.jsonl and
        # per-slot observations in <id>.audit.jsonl for the cross-node
        # divergence join (tools/ledger_audit.py, docs/AUDIT.md)
        from .audit import SafetyAuditor

        auditor = SafetyAuditor(args.id, dep.cfg, log_dir=log_dir)
        replica.auditor = auditor
    lag = LoopLagGauge()
    telemetry = NodeTelemetry(
        args.id, replica=replica, transport=transport, tracer=tracer,
        loop_lag=lag,
    )
    status = None
    recorder = None
    watchdog = None
    try:
        replica.start()
        lag.start()
        if args.status_port >= 0:
            # live telemetry plane: /metrics.json /healthz /trace.json
            status = StatusServer(telemetry, port=args.status_port)
            await status.start()
            if log_dir:
                write_status_file(log_dir, args.id, status.bound_port)
            logging.info(
                "%s status endpoint on http://127.0.0.1:%d/metrics.json",
                args.id, status.bound_port,
            )
        if log_dir and args.flight_interval > 0:
            # flight recorder: a wedged or SIGKILLed node still leaves a
            # snapshot timeline on disk (the r5 qc256 lesson)
            recorder = FlightRecorder(
                telemetry,
                os.path.join(log_dir, f"{args.id}.flight.jsonl"),
                interval=args.flight_interval,
            )
            recorder.start()
        if args.stall_deadline > 0:
            # wedge autopsy (ISSUE 4): no commit for --stall-deadline
            # seconds while client work is outstanding dumps a forensic
            # snapshot — the r5 qc256 25-minute silence, replaced by a
            # diagnosis file
            watchdog = ProgressWatchdog(
                telemetry,
                path=(
                    os.path.join(log_dir, f"{args.id}.autopsy.json")
                    if log_dir else None
                ),
                deadline=args.stall_deadline,
                flight=recorder,
            )
            watchdog.start()
            if auditor is not None:
                # a safety violation triggers the same forensic dump
                # path as a stall (docs/AUDIT.md)
                auditor.attach_watchdog(watchdog)
        logging.info(
            "%s listening on %s (verifier=%s, n=%d, f=%d)",
            args.id, dep.addr(args.id), args.verifier, dep.cfg.n, dep.cfg.f,
        )

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await replica.stop()
        await transport.stop()
    finally:
        # fires on clean shutdown AND on a fatal exception out of the run
        # loop: the stats/transport/overload dumps (plus the recorder's
        # final frame) must not depend on an orderly exit — and no
        # telemetry teardown failure may swallow them either
        try:
            if watchdog is not None:
                await watchdog.stop()
            await lag.stop()
            if recorder is not None:
                await recorder.stop()
            if status is not None:
                await status.stop()
            if tracer is not None:
                tracer.close()
            if auditor is not None:
                auditor.close()
        except Exception:
            logging.exception("%s: telemetry teardown failed", args.id)
        _dump_final(args.id, replica, transport, watchdog=watchdog)
        spans.recorder().close()


def main() -> None:
    ap = argparse.ArgumentParser(description="simple_pbft_tpu replica node")
    ap.add_argument("--id", required=True, help="replica id (e.g. r0)")
    ap.add_argument(
        "--deploy-dir",
        required=True,
        help="directory holding committee.json and <id>.seed",
    )
    ap.add_argument(
        "--verifier",
        default="cpu",
        choices=["cpu", "cpu-pure", "tpu", "insecure"],
        help="signature verification backend",
    )
    ap.add_argument(
        "--transport",
        default="tcp",
        choices=["tcp", "grpc"],
        help="wire transport (grpc = HTTP/2 streams, the DCN path)",
    )
    ap.add_argument(
        "--wan-profile", default="",
        help="wrap the wire transport in a deterministic link shaper "
        "(faults.ShapedTransport) with the named WAN profile — wan3dc "
        "(three datacenters, ~12 ms inter-DC), lossy (5%% iid loss) — "
        "for degraded-network rehearsals (docs/SCENARIOS.md)",
    )
    ap.add_argument(
        "--max-drain", type=int, default=4096,
        help="max messages drained per sweep (inbound batch bound)",
    )
    ap.add_argument(
        "--shed-watermark", type=int, default=0,
        help="decoded-sweep size beyond which deferrable message classes "
        "(client requests, fetch/probe asks) are shed in favor of "
        "quorum-critical traffic; 0 = 3/4 of --max-drain "
        "(docs/RESILIENCE.md)",
    )
    ap.add_argument(
        "--verify-max-pending", type=int, default=65536,
        help="tpu verifier: pending-item cap before submits are "
        "admission-rejected with Overloaded (bounded queue depth)",
    )
    ap.add_argument(
        "--verify-deadline", type=float, default=60.0,
        help="tpu verifier: seconds a device dispatch may run before the "
        "watchdog fails the sweep over to the CPU verifier and "
        "quarantines the device path (0 disables)",
    )
    ap.add_argument(
        "--status-port", type=int, default=0,
        help="live telemetry endpoint (/metrics.json, /healthz, "
        "/trace.json) on 127.0.0.1; 0 = ephemeral port (written to "
        "<log-dir>/<id>.status.json for pbft_top discovery), "
        "negative = disabled (docs/OBSERVABILITY.md)",
    )
    ap.add_argument(
        "--flight-interval", type=float, default=1.0,
        help="flight recorder: seconds between telemetry snapshots "
        "appended to <log-dir>/<id>.flight.jsonl (crash-surviving "
        "timeline); 0 disables",
    )
    ap.add_argument(
        "--trace-sample", type=float, default=128,
        help="phase-level request tracing: N > 1 keeps ~1/N of requests "
        "(deterministic by hash of (client, timestamp), so every node "
        "samples the SAME requests); a fraction in (0, 1] keeps that "
        "share — '--trace-sample 1.0' is the explicit full-fidelity "
        "debug mode; 0 = off. Sampling loss is counted in the "
        "snapshot's tracer.trace_dropped. Events go to "
        "<log-dir>/<id>.trace.jsonl",
    )
    ap.add_argument(
        "--trace", type=int, default=0,
        help="cross-replica trace plane (needs a log dir): stamp "
        "unsigned trace envelopes on outbound consensus wires and "
        "recv-stamp inbound ones into <log-dir>/<id>.spans.jsonl edge "
        "docs, plus per-certificate quorum arrival-order records; join "
        "all nodes' ledgers with tools/slot_trace.py (clock skew is "
        "solved offline from the edges themselves); 0 disables "
        "(docs/OBSERVABILITY.md)",
    )
    ap.add_argument(
        "--audit", type=int, default=1,
        help="online safety auditor (needs a log dir): checks "
        "equivocation / checkpoint-consistency / commit-uniqueness / "
        "certificate-honesty invariants over the verified message "
        "stream, appends tamper-evident evidence to "
        "<log-dir>/<id>.evidence.jsonl and per-slot observations to "
        "<id>.audit.jsonl (joined across nodes by "
        "tools/ledger_audit.py); 0 disables (docs/AUDIT.md)",
    )
    ap.add_argument(
        "--device-profile", type=float, default=0,
        help="device-plane deep capture: arm ONE bounded jax.profiler "
        "trace of this many seconds right after boot (off-loop, never "
        "in consensus paths); artifacts land under "
        "<log-dir>/device_profile. 0 = off. The always-on per-dispatch "
        "device ledger (docs/OBSERVABILITY.md §device observatory) "
        "does not need this — the flag is for kernel-level forensics",
    )
    ap.add_argument(
        "--stall-deadline", type=float, default=30.0,
        help="wedge autopsy: seconds without a committed block (while "
        "client work is outstanding) before a forensic dump — task/"
        "thread stacks, verify/QC lane depths, in-flight instances, "
        "recent spans — is written to <log-dir>/<id>.autopsy.json "
        "(0 disables; docs/OBSERVABILITY.md)",
    )
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument(
        "--log-dir",
        default=None,
        help="per-node rotating log file directory (default: "
        "<deploy-dir>/log, matching the reference's zap/lumberjack "
        "layout; empty string disables the file sink)",
    )
    args = ap.parse_args()
    from .logutil import setup_node_logging

    log_dir = args.log_dir
    if log_dir is None:
        log_dir = os.path.join(args.deploy_dir, "log")
    setup_node_logging(args.id, log_dir or None, level=args.log_level)
    # the telemetry plane (flight recorder, trace sink, status-file
    # discovery) writes next to the rotating log
    args.resolved_log_dir = log_dir or None
    # arm the opt-in loop sanitizer BEFORE the loop exists: install()
    # wraps the policy's new_event_loop, so asyncio.run's loop is
    # watched on a real node exactly as under pytest (no-op unless
    # PBFT_SANITIZE=loop is set)
    from . import sanitize

    sanitize.install()
    asyncio.run(run_node(args))


if __name__ == "__main__":
    main()
