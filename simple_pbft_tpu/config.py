"""Committee configuration.

Replaces the reference's hard-coded everything: the 4-entry NodeTable
(node.go:60-65), f=1 duplicated in two files (node.go:45, pbft_impl.go:37),
the fixed primary "MainNode" (node.go:68), and the magic view id
(node.go:55). Here the committee is data: an ordered replica list, f derived
from it, per-replica Ed25519 public keys, rotating primary, and the
batching / checkpoint / watermark knobs the reference lacks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .crypto import ed25519_cpu


@dataclass(frozen=True)
class CommitteeConfig:
    """Static description of a PBFT committee."""

    replica_ids: Tuple[str, ...]
    pubkeys: Dict[str, bytes]  # replica/client id -> 32-byte Ed25519 pubkey
    checkpoint_interval: int = 64
    watermark_window: int = 256  # H = h + watermark_window
    max_batch: int = 256  # max client requests per block
    view_timeout: float = 2.0  # seconds before a replica suspects the primary
    verify_signatures: bool = True
    # BLS quorum-certificate mode (BASELINE config 4): votes carry BLS
    # shares and go only to the primary, which aggregates 2f+1 into a
    # QuorumCert verified with ONE pairing check — O(n) messages per phase
    # instead of O(n^2), and certificates that fit in a QC instead of
    # 2f+1 embedded votes.
    qc_mode: bool = False
    bls_pubkeys: Dict[str, bytes] = field(default_factory=dict)  # 192-byte G2
    # X25519 key-exchange pubkeys (replicas AND clients) for the MAC'd
    # reply fast path (crypto/mac.py); pairs lacking either key fall
    # back to Ed25519-signed replies
    kx_pubkeys: Dict[str, bytes] = field(default_factory=dict)

    @property
    def n(self) -> int:
        return len(self.replica_ids)

    @property
    def f(self) -> int:
        """Max Byzantine replicas: n >= 3f + 1."""
        return (self.n - 1) // 3

    @property
    def quorum(self) -> int:
        """2f+1 — prepare/commit certificate size (distinct senders,
        counting the replica's own vote; Castro-Liskov quorums, vs. the
        reference's 2f-others formulation at pbft_impl.go:212,227)."""
        return 2 * self.f + 1

    @property
    def weak_quorum(self) -> int:
        """f+1 — at least one honest replica (client reply matching)."""
        return self.f + 1

    @property
    def repliers(self) -> int:
        """Designated-replier set size: f+1 matching replies is what the
        client NEEDS, but transmitting exactly f+1 leaves zero slack — a
        single dropped reply (or one slow designee) then costs a full
        client timeout (measured: 2% message loss at n=64 pushed reply
        p50 to the whole 30 s retry period). A few spares make the
        common case loss-tolerant while still saving the n-f-1 wasted
        signs/sends the rotation exists to avoid."""
        return min(self.n, self.weak_quorum + max(1, self.f // 4))

    def primary(self, view: int) -> str:
        """Round-robin primary rotation (the reference sketched this in its
        dead view.go:13-31 but never wired it)."""
        return self.replica_ids[view % self.n]

    def pubkey(self, node_id: str) -> Optional[bytes]:
        return self.pubkeys.get(node_id)

    def bls_pubkey(self, node_id: str) -> Optional[bytes]:
        return self.bls_pubkeys.get(node_id)


@dataclass
class KeyPair:
    seed: bytes
    pub: bytes

    @staticmethod
    def generate(seed: bytes) -> "KeyPair":
        return KeyPair(seed=seed, pub=ed25519_cpu.public_key(seed))


def make_test_committee(
    n: int = 4, clients: int = 1, **overrides
) -> Tuple[CommitteeConfig, Dict[str, KeyPair]]:
    """Deterministic committee for tests/benchmarks: replicas r0..r{n-1},
    clients c0..c{clients-1}, keys derived from ids."""
    ids = tuple(f"r{i}" for i in range(n))
    keys: Dict[str, KeyPair] = {}
    for name in list(ids) + [f"c{i}" for i in range(clients)]:
        seed = (name.encode() * 32)[:32]
        keys[name] = KeyPair.generate(seed)
    bls_pubkeys: Dict[str, bytes] = {}
    if overrides.get("qc_mode"):
        from .crypto import bls

        for rid in ids:
            _, bls_pubkeys[rid] = bls.keygen(keys[rid].seed)
    from .crypto import mac as mac_mod

    cfg = CommitteeConfig(
        replica_ids=ids,
        pubkeys={k: v.pub for k, v in keys.items()},
        bls_pubkeys=overrides.pop("bls_pubkeys", bls_pubkeys),
        kx_pubkeys=overrides.pop(
            "kx_pubkeys",
            # empty when no X25519 backend: everyone signs replies instead
            {
                k: kx
                for k, v in keys.items()
                if (kx := mac_mod.kx_pubkey(v.seed)) is not None
            },
        ),
        **overrides,
    )
    return cfg, keys
