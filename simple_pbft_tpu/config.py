"""Committee configuration.

Replaces the reference's hard-coded everything: the 4-entry NodeTable
(node.go:60-65), f=1 duplicated in two files (node.go:45, pbft_impl.go:37),
the fixed primary "MainNode" (node.go:68), and the magic view id
(node.go:55). Here the committee is data: an ordered replica list, f derived
from it, per-replica Ed25519 public keys, rotating primary, and the
batching / checkpoint / watermark knobs the reference lacks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Optional, Tuple

from .crypto import ed25519_cpu


@dataclass(frozen=True)
class CommitteeConfig:
    """Static description of a PBFT committee."""

    replica_ids: Tuple[str, ...]
    pubkeys: Dict[str, bytes]  # replica/client id -> 32-byte Ed25519 pubkey
    checkpoint_interval: int = 64
    watermark_window: int = 256  # H = h + watermark_window
    max_batch: int = 256  # max client requests per block
    view_timeout: float = 2.0  # seconds before a replica suspects the primary
    verify_signatures: bool = True
    # BLS quorum-certificate mode (BASELINE config 4): votes carry BLS
    # shares and go only to the primary, which aggregates 2f+1 into a
    # QuorumCert verified with ONE pairing check — O(n) messages per phase
    # instead of O(n^2), and certificates that fit in a QC instead of
    # 2f+1 embedded votes.
    qc_mode: bool = False
    bls_pubkeys: Dict[str, bytes] = field(default_factory=dict)  # 192-byte G2
    # Speculative pipelined execution (ISSUE 15): execute blocks at
    # PREPARED against a forkable app state and reply early with a
    # signed speculative mark; roll back any speculated suffix whose
    # digest loses on view change (consensus/speculation.py). Commit
    # latency is pipeline depth, not crypto (ROADMAP: ~400 ms p50 at
    # n=16/depth=512 vs a 69 ms n=4 line), and speculation collapses
    # the client-visible half of it — on by default, disable to A/B.
    speculative: bool = True
    # X25519 key-exchange pubkeys (replicas AND clients) for the MAC'd
    # reply fast path (crypto/mac.py); pairs lacking either key fall
    # back to Ed25519-signed replies
    kx_pubkeys: Dict[str, bytes] = field(default_factory=dict)
    # Live membership reconfiguration (ISSUE 7): the configuration
    # epoch, bumped each time a committed Reconfig op activates at a
    # checkpoint boundary. Epoch 0 is the boot committee. ``admin_ids``
    # names the identities whose signed __reconfig__ operations are
    # honored — empty means reconfiguration is disabled (every reconfig
    # op executes as a denied no-op), the safe default.
    epoch: int = 0
    admin_ids: Tuple[str, ...] = ()
    # Network address book (id -> (host, port)) for socket transports.
    # Rides config_doc so a reconfiguration-added member is REACHABLE,
    # not just named: epoch activation and client adoption push these
    # into the transport peer maps (transport.base.update_peer_book).
    # Empty for id-routed (local) committees, where reachability is not
    # address-based. Deterministic: boot entries come from the shared
    # deployment document, later ones from committed reconfig content.
    addrs: Dict[str, Tuple[str, int]] = field(default_factory=dict)

    @property
    def n(self) -> int:
        return len(self.replica_ids)

    @property
    def f(self) -> int:
        """Max Byzantine replicas: n >= 3f + 1."""
        return (self.n - 1) // 3

    @property
    def quorum(self) -> int:
        """2f+1 — prepare/commit certificate size (distinct senders,
        counting the replica's own vote; Castro-Liskov quorums, vs. the
        reference's 2f-others formulation at pbft_impl.go:212,227)."""
        return 2 * self.f + 1

    @property
    def weak_quorum(self) -> int:
        """f+1 — at least one honest replica (client reply matching)."""
        return self.f + 1

    @property
    def repliers(self) -> int:
        """Designated-replier set size: f+1 matching replies is what the
        client NEEDS, but transmitting exactly f+1 leaves zero slack — a
        single dropped reply (or one slow designee) then costs a full
        client timeout (measured: 2% message loss at n=64 pushed reply
        p50 to the whole 30 s retry period). A few spares make the
        common case loss-tolerant while still saving the n-f-1 wasted
        signs/sends the rotation exists to avoid."""
        return min(self.n, self.weak_quorum + max(1, self.f // 4))

    @property
    def spec_repliers(self) -> int:
        """Designated SPECULATIVE-replier set size. A speculative answer
        needs 2f+1 matching marks (not f+1 — the quorum-intersection
        argument that makes a spec answer final-safe needs 2f+1
        preparers on record), so the rotation window is quorum plus the
        same loss-tolerance spares the final-reply rotation carries."""
        return min(self.n, self.quorum + max(1, self.f // 4))

    def primary(self, view: int) -> str:
        """Round-robin primary rotation (the reference sketched this in its
        dead view.go:13-31 but never wired it)."""
        return self.replica_ids[view % self.n]

    def pubkey(self, node_id: str) -> Optional[bytes]:
        return self.pubkeys.get(node_id)

    def bls_pubkey(self, node_id: str) -> Optional[bytes]:
        return self.bls_pubkeys.get(node_id)


def config_doc(cfg: CommitteeConfig) -> Dict[str, object]:
    """Deterministic JSON-ready description of the MEMBERSHIP state (the
    part a reconfiguration changes): epoch, ordered replica ids, and the
    key tables, hex-encoded with sorted ids. This block rides inside
    every checkpoint snapshot (replica._checkpoint_snapshot) so a
    state-transferred joiner restores the exact committee its peers run
    — and it is what ConfigReply ships to stale clients."""
    return {
        "epoch": cfg.epoch,
        "replica_ids": list(cfg.replica_ids),
        "admin_ids": list(cfg.admin_ids),
        "pubkeys": {k: v.hex() for k, v in sorted(cfg.pubkeys.items())},
        "bls_pubkeys": {
            k: v.hex() for k, v in sorted(cfg.bls_pubkeys.items())
        },
        "kx_pubkeys": {
            k: v.hex() for k, v in sorted(cfg.kx_pubkeys.items())
        },
        "addrs": {
            k: [v[0], v[1]] for k, v in sorted(cfg.addrs.items())
        },
    }


def config_from_doc(base: CommitteeConfig, doc: Dict[str, Any]) -> CommitteeConfig:
    """Rebuild a CommitteeConfig from a config_doc, inheriting every
    non-membership knob (timeouts, batching, qc_mode, ...) from
    ``base``. Raises ValueError on a malformed doc — snapshot installs
    must reject garbage atomically."""
    import dataclasses

    try:
        ids = tuple(str(i) for i in doc["replica_ids"])
        if not ids:
            raise ValueError("empty replica_ids")
        return dataclasses.replace(
            base,
            replica_ids=ids,
            admin_ids=tuple(str(i) for i in doc.get("admin_ids", [])),
            pubkeys={
                str(k): bytes.fromhex(v)
                for k, v in dict(doc["pubkeys"]).items()
            },
            bls_pubkeys={
                str(k): bytes.fromhex(v)
                for k, v in dict(doc.get("bls_pubkeys", {})).items()
            },
            kx_pubkeys={
                str(k): bytes.fromhex(v)
                for k, v in dict(doc.get("kx_pubkeys", {})).items()
            },
            addrs={
                str(k): (str(v[0]), int(v[1]))
                for k, v in dict(doc.get("addrs", {})).items()
            },
            epoch=int(doc["epoch"]),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad config doc: {e}") from None


def apply_reconfig(
    cfg: CommitteeConfig,
    add: Dict[str, Dict[str, str]],
    remove: Iterable[str],
) -> CommitteeConfig:
    """The committed membership change: remove ids, append new replicas
    (sorted, after the survivors — rotation order must be identical on
    every replica), bump the epoch. ``add`` maps new id -> {"pub": hex,
    optional "bls": hex, optional "kx": hex, optional "addr":
    "host:port" — required in practice for socket-transport committees,
    or the new member is named but unreachable}. Raises ValueError when the
    result would be degenerate (fewer than 4 replicas — f would hit 0
    and the committee could no longer survive ANY fault) or malformed."""
    import dataclasses

    removes = set(remove)
    unknown = removes - set(cfg.replica_ids)
    if unknown:
        raise ValueError(f"cannot remove non-members {sorted(unknown)}")
    dup = set(add) & (set(cfg.replica_ids) - removes)
    if dup:
        raise ValueError(f"cannot add existing members {sorted(dup)}")
    survivors = set(cfg.replica_ids) - removes
    # subtract SURVIVORS, not current members: remove+add of the same id
    # (key rotation) must re-add it, not silently drop the member
    new_ids = tuple(i for i in cfg.replica_ids if i not in removes) + tuple(
        sorted(set(add) - survivors)
    )
    if len(new_ids) < 4:
        raise ValueError("resulting committee below n=4")
    pubkeys = dict(cfg.pubkeys)
    bls = dict(cfg.bls_pubkeys)
    kx = dict(cfg.kx_pubkeys)
    # removed members keep their address entry: retirees keep serving
    # state-transfer chunks and config lookups until shut down
    addrs = dict(cfg.addrs)
    for rid, keys in add.items():
        pubkeys[rid] = bytes.fromhex(keys["pub"])
        if keys.get("bls"):
            bls[rid] = bytes.fromhex(keys["bls"])
        if keys.get("kx"):
            kx[rid] = bytes.fromhex(keys["kx"])
        if keys.get("addr"):
            host, _, port = str(keys["addr"]).rpartition(":")
            if not host:
                raise ValueError(f"bad addr for {rid} (want host:port)")
            addrs[rid] = (host, int(port))
    if cfg.qc_mode and any(r not in bls for r in new_ids):
        raise ValueError("qc_mode committee needs a bls key per member")
    return dataclasses.replace(
        cfg,
        replica_ids=new_ids,
        pubkeys=pubkeys,
        bls_pubkeys=bls,
        kx_pubkeys=kx,
        addrs=addrs,
        epoch=cfg.epoch + 1,
    )


@dataclass
class KeyPair:
    seed: bytes
    pub: bytes

    @staticmethod
    def generate(seed: bytes) -> "KeyPair":
        return KeyPair(seed=seed, pub=ed25519_cpu.public_key(seed))


def make_test_committee(
    n: int = 4, clients: int = 1, **overrides: Any
) -> Tuple[CommitteeConfig, Dict[str, KeyPair]]:
    """Deterministic committee for tests/benchmarks: replicas r0..r{n-1},
    clients c0..c{clients-1}, keys derived from ids."""
    ids = tuple(f"r{i}" for i in range(n))
    keys: Dict[str, KeyPair] = {}
    for name in list(ids) + [f"c{i}" for i in range(clients)]:
        seed = (name.encode() * 32)[:32]
        keys[name] = KeyPair.generate(seed)
    bls_pubkeys: Dict[str, bytes] = {}
    if overrides.get("qc_mode"):
        from .crypto import bls

        for rid in ids:
            _, bls_pubkeys[rid] = bls.keygen(keys[rid].seed)
    from .crypto import mac as mac_mod

    cfg = CommitteeConfig(
        replica_ids=ids,
        pubkeys={k: v.pub for k, v in keys.items()},
        # test committees trust their generated clients as reconfig
        # admins (production deployments name admin_ids explicitly)
        admin_ids=overrides.pop(
            "admin_ids", tuple(f"c{i}" for i in range(clients))
        ),
        bls_pubkeys=overrides.pop("bls_pubkeys", bls_pubkeys),
        kx_pubkeys=overrides.pop(
            "kx_pubkeys",
            # empty when no X25519 backend: everyone signs replies instead
            {
                k: kx
                for k, v in keys.items()
                if (kx := mac_mod.kx_pubkey(v.seed)) is not None
            },
        ),
        **overrides,
    )
    return cfg, keys
