"""Comb-table Ed25519 verification engine — the fast TPU path.

The generic ladder (ops/edwards.py) spends its time on 256 doublings, 256
unified adds, and two on-device square-root chains (point decompression of
A and R). PBFT gives us structure the TPU can exploit:

- **Pubkeys are a small committee set**, reused across every vote. So the
  host decompresses each pubkey once (exact bigint math) and uploads a
  per-key *comb table*: T_A[i][w] = (w * 16^i) A for i in 0..63, w in
  0..15, in Niels form (y+x, y−x, 2dxy). [k]A is then 64 table lookups +
  64 mixed adds — **zero doublings**.
- **The base point is fixed**, so [S]B uses a constant comb table the same
  way.
- **R never needs decompressing**: instead of comparing points in
  extended coordinates ([S]B − [k]A == R), compute P = [S]B + [k](−A),
  normalize to affine with ONE inversion amortized over the whole batch
  (tree-structured Montgomery batch inversion — log2(B) levels of batched
  multiplies, a single scalar invert chain at the root), and compare P's
  canonical encoding (y limbs + x parity) against R's wire bytes. A
  non-canonical or off-curve R simply never matches.

Per-signature device cost: 128 mixed adds (7 field muls each) + ~3 muls of
batch inversion ≈ 900 field muls, vs ≈ 4300 + two 250-square chains for
the ladder — and the table lookups are two bulk gathers, not where-chains.

Everything stays constant-shape: 64 nibble positions whatever the scalar,
identity entries for zero nibbles, verdicts masked by host prechecks.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import field25519 as fe
from ..crypto import ed25519_cpu as ref

NPOS = 64  # 4-bit comb positions covering 256-bit scalars
WINDOW = 16
FWINDOW = WINDOW * WINDOW  # fused (s_nibble, k_nibble) window: 256 entries

# ---------------------------------------------------------------------------
# Host-side table construction (exact Python bigints -> limb arrays)
# ---------------------------------------------------------------------------


def _niels_np(p: ref.Point) -> np.ndarray:
    """Affine Niels form (y+x, y−x, 2dxy) as (3, 17) int32 limbs."""
    x, y = ref.point_to_affine(p)
    return np.stack(
        [
            fe._int_to_limbs_np((y + x) % ref.P),
            fe._int_to_limbs_np((y - x) % ref.P),
            fe._int_to_limbs_np(2 * ref.D * x * y % ref.P),
        ]
    )


def comb_table_np(point: ref.Point) -> np.ndarray:
    """(NPOS, WINDOW, 3, 17) int32: T[i][w] = (w * 16^i) * point, Niels."""
    out = np.zeros((NPOS, WINDOW, 3, 17), dtype=np.int32)
    base = point
    for i in range(NPOS):
        acc = ref.IDENTITY
        for w in range(WINDOW):
            out[i, w] = _niels_np(acc)
            acc = ref.point_add(acc, base)
        for _ in range(4):  # base <- 16 * base
            base = ref.point_double(base)
    return out


def _batch_affine_niels_np(points) -> np.ndarray:
    """Extended bigint points -> (n, 3, 17) int32 Niels limbs, with ONE
    modular inversion for the whole list (host Montgomery batch trick) and
    vectorized int->limb conversion. comb_table-scale builds do tens of
    thousands of entries per key; per-entry Fermat inversions would cost
    seconds per key."""
    n = len(points)
    zs = [p[2] for p in points]
    prefix = [1] * (n + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * z % ref.P
    inv_all = pow(prefix[n], ref.P - 2, ref.P)
    zinv = [0] * n
    for i in range(n - 1, -1, -1):
        zinv[i] = prefix[i] * inv_all % ref.P
        inv_all = inv_all * zs[i] % ref.P
    vals = np.zeros((n, 3, 32), dtype=np.uint8)
    for i, (p, zi) in enumerate(zip(points, zinv)):
        x = p[0] * zi % ref.P
        y = p[1] * zi % ref.P
        vals[i, 0] = np.frombuffer(((y + x) % ref.P).to_bytes(32, "little"), np.uint8)
        vals[i, 1] = np.frombuffer(((y - x) % ref.P).to_bytes(32, "little"), np.uint8)
        vals[i, 2] = np.frombuffer(
            (2 * ref.D * x * y % ref.P).to_bytes(32, "little"), np.uint8
        )
    return fe.bytes32_to_limbs_np(vals.reshape(n * 3, 32)).reshape(n, 3, 17)


def _point_neg(p: ref.Point) -> ref.Point:
    x, y, z, t = p
    return ((-x) % ref.P, y, z, (-t) % ref.P)


def fused_table_np(point: ref.Point) -> np.ndarray:
    """(NPOS, FWINDOW, 3, 17) int32 Niels:
    T[i][ws*16 + wk] = (ws * 16^i) B + (wk * 16^i) (−A).

    One gather + ONE mixed add per nibble position evaluates
    [S]B + [k](−A) — half the madds of the separate-table comb (the
    device cost per signature drops from 128 to 64 mixed adds). The
    16x-larger table trades HBM capacity (3.3 MB/key) for compute; keys
    are few (a committee) and endlessly reused, so the build amortizes.
    """
    pts = []
    base_b = ref.B
    base_a = _point_neg(point)
    for i in range(NPOS):
        row_b = ref.IDENTITY
        for ws in range(WINDOW):
            acc = row_b
            for wk in range(WINDOW):
                pts.append(acc)
                acc = ref.point_add(acc, base_a)
            row_b = ref.point_add(row_b, base_b)
        for _ in range(4):  # bases <- 16 * bases
            base_b = ref.point_double(base_b)
            base_a = ref.point_double(base_a)
    return _batch_affine_niels_np(pts).reshape(NPOS, FWINDOW, 3, 17)


_BASE_TABLE: Optional[np.ndarray] = None
_BASE_TABLE_DEV = None


def base_table() -> np.ndarray:
    """Constant comb table of the Ed25519 base point (built once)."""
    global _BASE_TABLE
    if _BASE_TABLE is None:
        _BASE_TABLE = comb_table_np(ref.B)
    return _BASE_TABLE


def base_table_device() -> jnp.ndarray:
    """Device-resident copy of base_table() (uploaded once — the verify
    hot path must not re-transfer 200 KB per batch)."""
    global _BASE_TABLE_DEV
    if _BASE_TABLE_DEV is None:
        _BASE_TABLE_DEV = jnp.asarray(base_table())
    return _BASE_TABLE_DEV


def negate_niels(t: jnp.ndarray) -> jnp.ndarray:
    """Niels negation: swap (y+x, y−x), negate 2dxy. Shape (..., 3, 17)."""
    return jnp.stack(
        [t[..., 1, :], t[..., 0, :], fe.neg(t[..., 2, :])], axis=-2
    )


def nibbles_np(le_bytes: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 little-endian scalar -> (n, 64) int32 nibbles, least
    significant first (position i carries weight 16^i — matching
    comb_table_np, order-free since the comb has no doublings)."""
    lo = le_bytes & 0x0F
    hi = le_bytes >> 4
    return np.stack([lo, hi], axis=-1).reshape(le_bytes.shape[0], 64).astype(np.int32)


# ---------------------------------------------------------------------------
# Device kernel pieces
# ---------------------------------------------------------------------------


def madd(p: jnp.ndarray, q_niels: jnp.ndarray) -> jnp.ndarray:
    """Mixed add: extended (..., 4, 17) + affine Niels (..., 3, 17).

    ref10-style ge_madd — 7 field muls. Same group law as
    edwards.point_add with Z2 = 1 and the Niels components precomputed.
    """
    x1, y1, z1, t1 = (p[..., i, :] for i in range(4))
    ypx, ymx, xy2d = (q_niels[..., i, :] for i in range(3))
    a = fe.mul(fe.add(y1, x1), ypx)
    b = fe.mul(fe.sub(y1, x1), ymx)
    c = fe.mul(xy2d, t1)
    d = fe.mul_small(z1, 2)
    e = fe.sub(a, b)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(a, b)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def comb_accumulate(
    s_nibbles: jnp.ndarray,
    k_nibbles: jnp.ndarray,
    a_row_base: jnp.ndarray,
    a_flat: jnp.ndarray,
    b_flat: jnp.ndarray,
) -> jnp.ndarray:
    """[S]B + [k](−A) via comb tables: one fori_loop over the 64 nibble
    positions, gathering each position's Niels entries on the fly (keeps
    device memory O(B), not O(B * NPOS)) and applying two mixed adds.

    s_nibbles, k_nibbles: (B, NPOS) int32. a_row_base: (B,) int32 =
    key_index * NPOS * WINDOW. a_flat: (n_keys*NPOS*WINDOW, 3, 17).
    b_flat: (NPOS*WINDOW, 3, 17).
    """
    batch = s_nibbles.shape[0]
    ident = jnp.broadcast_to(jnp.asarray(ref_identity_limbs()), (batch, 4, 17))
    # inherit varying manual axes from the data under shard_map
    ident = ident + (s_nibbles[:, :1, None] * 0)

    def body(i, acc):
        sel_b = jnp.take(b_flat, i * WINDOW + s_nibbles[:, i], axis=0)
        sel_a = jnp.take(
            a_flat, a_row_base + i * WINDOW + k_nibbles[:, i], axis=0
        )
        acc = madd(acc, sel_b)
        return madd(acc, negate_niels(sel_a))

    return lax.fori_loop(0, NPOS, body, ident)


_IDENT_LIMBS: Optional[np.ndarray] = None


def ref_identity_limbs() -> np.ndarray:
    global _IDENT_LIMBS
    if _IDENT_LIMBS is None:
        _IDENT_LIMBS = np.stack(
            [fe._int_to_limbs_np(c % ref.P) for c in ref.IDENTITY]
        )
    return _IDENT_LIMBS


def _interleave(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(m, 17), (m, 17) -> (2m, 17) alternating a0 b0 a1 b1 ..."""
    return jnp.stack([a, b], axis=1).reshape(-1, a.shape[-1])


def batch_invert(z: jnp.ndarray) -> jnp.ndarray:
    """Tree-structured Montgomery batch inversion: (B, 17) -> (B, 17).

    Pairwise products up the tree (log2 B batched muls totalling ≈ B
    multiplies), ONE scalar invert chain at the root, then unfold back
    down (≈ 2B multiplies). Requires B a power of two and all inputs
    nonzero — guaranteed for Z coordinates of complete Edwards formulas.
    """
    n = z.shape[0]
    assert n & (n - 1) == 0, "batch_invert requires a power-of-two batch"
    levels = []
    cur = z
    while cur.shape[0] > 1:
        levels.append(cur)
        cur = fe.mul(cur[0::2], cur[1::2])
    inv = fe.invert(cur)  # (1, 17) — the only exponentiation chain
    for lev in reversed(levels):
        left, right = lev[0::2], lev[1::2]
        inv = _interleave(fe.mul(inv, right), fe.mul(inv, left))
    return inv


def fused_accumulate(
    s_nibbles: jnp.ndarray,
    k_nibbles: jnp.ndarray,
    row_base: jnp.ndarray,
    f_flat: jnp.ndarray,
) -> jnp.ndarray:
    """[S]B + [k](−A) via the fused dual-scalar table: one gather + one
    mixed add per nibble position (64 total).

    s_nibbles, k_nibbles: (B, NPOS) int32. row_base: (B,) int32 =
    key_index * NPOS * FWINDOW. f_flat: (n_keys*NPOS*FWINDOW, 3, 17).
    """
    batch = s_nibbles.shape[0]
    ident = jnp.broadcast_to(jnp.asarray(ref_identity_limbs()), (batch, 4, 17))
    # inherit varying manual axes from the data under shard_map
    ident = ident + (s_nibbles[:, :1, None] * 0)

    def body(i, acc):
        idx = row_base + i * FWINDOW + s_nibbles[:, i] * WINDOW + k_nibbles[:, i]
        return madd(acc, jnp.take(f_flat, idx, axis=0))

    return lax.fori_loop(0, NPOS, body, ident)


def fused_verify_kernel(
    s_nibbles: jnp.ndarray,  # (B, 64) int32 — S scalar nibbles
    k_nibbles: jnp.ndarray,  # (B, 64) int32 — challenge scalar nibbles
    a_index: jnp.ndarray,  # (B,) int32 — row into the fused table bank
    f_tables: jnp.ndarray,  # (n_keys, NPOS, FWINDOW, 3, 17) int32 Niels
    r_y: jnp.ndarray,  # (B, 17) int32 — R's canonical y limbs
    r_sign: jnp.ndarray,  # (B,) int32 — R's x sign bit
    precheck: jnp.ndarray,  # (B,) bool — host-side validity mask
) -> jnp.ndarray:
    """Batched verify via the fused comb: 64 gathers + 64 madds per row."""
    nk = f_tables.shape[0]
    f_flat = f_tables.reshape(nk * NPOS * FWINDOW, 3, 17)
    p = fused_accumulate(
        s_nibbles, k_nibbles, a_index * (NPOS * FWINDOW), f_flat
    )
    zinv = batch_invert(p[..., 2, :])
    x_aff = fe.mul(p[..., 0, :], zinv)
    y_aff = fe.mul(p[..., 1, :], zinv)
    ok = fe.eq(y_aff, r_y) & (fe.parity(x_aff) == r_sign)
    return ok & precheck


def comb_verify_kernel(
    s_nibbles: jnp.ndarray,  # (B, 64) int32 — S scalar nibbles
    k_nibbles: jnp.ndarray,  # (B, 64) int32 — challenge scalar nibbles
    a_index: jnp.ndarray,  # (B,) int32 — row into the pubkey table bank
    a_tables: jnp.ndarray,  # (n_keys, NPOS, WINDOW, 3, 17) int32 Niels
    b_table: jnp.ndarray,  # (NPOS, WINDOW, 3, 17) int32 Niels (base point)
    r_y: jnp.ndarray,  # (B, 17) int32 — R's canonical y limbs
    r_sign: jnp.ndarray,  # (B,) int32 — R's x sign bit
    precheck: jnp.ndarray,  # (B,) bool — host-side validity mask
) -> jnp.ndarray:
    """Batched verify via combs: [S]B + [k](−A) must encode to R's bytes."""
    b_flat = b_table.reshape(NPOS * WINDOW, 3, 17)
    nk = a_tables.shape[0]
    a_flat = a_tables.reshape(nk * NPOS * WINDOW, 3, 17)
    p = comb_accumulate(
        s_nibbles, k_nibbles, a_index * (NPOS * WINDOW), a_flat, b_flat
    )
    zinv = batch_invert(p[..., 2, :])
    x_aff = fe.mul(p[..., 0, :], zinv)
    y_aff = fe.mul(p[..., 1, :], zinv)
    ok = fe.eq(y_aff, r_y) & (fe.parity(x_aff) == r_sign)
    return ok & precheck
