"""Comb-table Ed25519 verification engine — the fast TPU path.

The generic ladder (ops/edwards.py) spends its time on 256 doublings, 256
unified adds, and two on-device square-root chains (point decompression of
A and R). PBFT gives us structure the TPU can exploit:

- **Pubkeys are a small committee set**, reused across every vote. So the
  host decompresses each pubkey once (exact bigint math) and uploads a
  per-key *comb table*: T_A[i][w] = (w * 16^i) A for i in 0..63, w in
  0..15, in Niels form (y+x, y−x, 2dxy). [k]A is then 64 table lookups +
  64 mixed adds — **zero doublings**.
- **The base point is fixed**, so [S]B uses a constant comb table the same
  way.
- **R never needs decompressing**: instead of comparing points in
  extended coordinates ([S]B − [k]A == R), compute P = [S]B + [k](−A),
  normalize to affine with ONE inversion amortized over the whole batch
  (tree-structured Montgomery batch inversion — log2(B) levels of batched
  multiplies, a single scalar invert chain at the root), and compare P's
  canonical encoding (y limbs + x parity) against R's wire bytes. A
  non-canonical or off-curve R simply never matches.

TPU-native data layout (what makes this fast, not just op-lean):

- Tables live in HBM as PACKED ROWS: one (64,) int32 row per Niels entry
  = [y+x limbs | y−x limbs | 2dxy limbs | pad] — so fetching an entry is
  one dense 256-byte row read. All 64 positions' rows for the whole batch
  are fetched in ONE flat `jnp.take` (measured ~230M rows/s on a v5e,
  vs ~11M rows/s for 64 per-position gathers in a loop).
- Compute arrays are limb-major / batch-minor ((17, B), see
  ops/field25519.py): the batch fills the 128-wide vector lanes, making
  the 64-iteration madd loop VPU-dense.

Per-signature device cost (fused mode): 64 mixed adds (7 field muls each)
+ ~3 muls of batch inversion ≈ 450 field muls, vs ≈ 4300 + two 250-square
chains for the ladder.

Everything stays constant-shape: 64 nibble positions whatever the scalar,
identity entries for zero nibbles, verdicts masked by host prechecks.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import field25519 as fe
from ..crypto import ed25519_cpu as ref

NPOS = 64  # 4-bit comb positions covering 256-bit scalars
WINDOW = 16
FWINDOW = WINDOW * WINDOW  # fused (s_nibble, k_nibble) window: 256 entries
ROW_DENSE = 64  # Niels row: 3*17 int32 limbs + 13 pad to a 256B row
ROW_PACKED = 32  # two 15-bit limbs per int32: 3*9 words + 5 pad, 128B
ROW = ROW_DENSE  # active row width — module global, see use_row_packing
PACKED = False


def use_row_packing(on: bool) -> None:
    """Select the table-row layout BEFORE any table is built or kernel
    jitted (jit traces and KeyBank allocations capture ROW). Packed rows
    halve the madd loop's gather bandwidth — the kernel's dominant HBM
    stream — for two extra shift/mask ops per element at unpack; the
    A/B lives in the chip ledger as verify_w5_pack. Layouts cannot mix:
    tables built in one mode are garbage to a kernel traced in the
    other, which is why this is a process-wide switch and not a
    per-call flag."""
    global ROW, PACKED
    PACKED = bool(on)
    ROW = ROW_PACKED if on else ROW_DENSE


def npos_for(wbits: int) -> int:
    """Positions covering a 256-bit scalar with wbits-bit windows."""
    return -(-256 // wbits)

# ---------------------------------------------------------------------------
# Host-side table construction (exact Python bigints -> packed limb rows)
# ---------------------------------------------------------------------------


def _pack_rows_np(vals: np.ndarray) -> np.ndarray:
    """(n, 3, 17) int32 Niels limbs -> (n, ROW) packed rows.

    Dense mode (ROW=64): one int32 per limb, 13 pad words — a 256-byte
    row of which only 204 bytes are payload. Packed mode (ROW=32, see
    `use_row_packing`): limbs are 15-bit nonnegative values, so pairs
    share an int32 (lo | hi << 15) — 9 words per element (the 17th limb
    rides alone), 27 + 5 pad = a 128-byte row. The madd loop's gather is
    the kernel's dominant HBM stream (r4 profile: staging copies +
    gather ~45% of the pass with the madds), so halving row bytes buys
    bandwidth at the cost of two shift/mask ops per element at unpack."""
    n = vals.shape[0]
    out = np.zeros((n, ROW), dtype=np.int32)
    if PACKED:
        v = vals.reshape(n, 3, fe.NLIMB)
        packed = np.zeros((n, 3, 9), dtype=np.int32)
        packed[:, :, :8] = v[:, :, 0:16:2] | (v[:, :, 1:16:2] << 15)
        packed[:, :, 8] = v[:, :, 16]
        out[:, : 3 * 9] = packed.reshape(n, 27)
    else:
        out[:, : 3 * fe.NLIMB] = vals.reshape(n, 3 * fe.NLIMB)
    return out


def _batch_affine_niels_np(points) -> np.ndarray:
    """Extended bigint points -> (n, ROW) packed Niels rows, with ONE
    modular inversion for the whole list (host Montgomery batch trick) and
    vectorized int->limb conversion. comb_table-scale builds do tens of
    thousands of entries per key; per-entry Fermat inversions would cost
    seconds per key."""
    n = len(points)
    zs = [p[2] for p in points]
    prefix = [1] * (n + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * z % ref.P
    inv_all = pow(prefix[n], ref.P - 2, ref.P)
    zinv = [0] * n
    for i in range(n - 1, -1, -1):
        zinv[i] = prefix[i] * inv_all % ref.P
        inv_all = inv_all * zs[i] % ref.P
    vals = np.zeros((n, 3, 32), dtype=np.uint8)
    for i, (p, zi) in enumerate(zip(points, zinv)):
        x = p[0] * zi % ref.P
        y = p[1] * zi % ref.P
        vals[i, 0] = np.frombuffer(((y + x) % ref.P).to_bytes(32, "little"), np.uint8)
        vals[i, 1] = np.frombuffer(((y - x) % ref.P).to_bytes(32, "little"), np.uint8)
        vals[i, 2] = np.frombuffer(
            (2 * ref.D * x * y % ref.P).to_bytes(32, "little"), np.uint8
        )
    limbs = fe.bytes32_to_limbs_np(vals.reshape(n * 3, 32)).reshape(n, 3, fe.NLIMB)
    return _pack_rows_np(limbs)


def comb_table_np(point: ref.Point) -> np.ndarray:
    """(NPOS * WINDOW, ROW) packed rows: row[i*W + w] = (w * 16^i) * point."""
    pts = []
    base = point
    for i in range(NPOS):
        acc = ref.IDENTITY
        for w in range(WINDOW):
            pts.append(acc)
            acc = ref.point_add(acc, base)
        for _ in range(4):  # base <- 16 * base
            base = ref.point_double(base)
    return _batch_affine_niels_np(pts)


def _point_neg(p: ref.Point) -> ref.Point:
    x, y, z, t = p
    return ((-x) % ref.P, y, z, (-t) % ref.P)


def fused_table_np(point: ref.Point, wbits: int = 4) -> np.ndarray:
    """(npos * 4^wbits, ROW) packed rows for wbits-bit windows:
    row[i*FW + ws*2^w + wk] = (ws * 2^(w*i)) B + (wk * 2^(w*i)) (−A),
    FW = 4^wbits, npos = ceil(256/wbits).

    One row fetch + ONE mixed add per window position evaluates
    [S]B + [k](−A) — half the madds of the separate-table comb. Wider
    windows cut positions (and device madds) at the cost of a bigger
    per-key table: w=4 -> 64 positions / ~4.2 MB per key, w=5 -> 52 /
    ~13.6 MB, w=6 -> 43 / ~45 MB. Keys are few (a committee) and
    endlessly reused, so the build amortizes; KeyBank caps total memory.
    """
    # Native fast path (native/ed25519.cpp): the same build in C++ group
    # arithmetic, ~80x the Python bigint loop — the difference between a
    # sub-second and a half-minute cold KeyBank at n=64 (and w=6 tables
    # are 10x bigger still). Output is affine-Niels field-element BYTES;
    # the vectorized bytes->limb conversion below is shared with the
    # Python path, so both produce bit-identical packed rows.
    from .. import native

    x, y = ref.point_to_affine(point)
    a_xy = np.frombuffer(
        x.to_bytes(32, "little") + y.to_bytes(32, "little"), dtype=np.uint8
    )
    nb = native.ed25519_fused_table(a_xy, wbits)
    if nb is not None:
        n = nb.shape[0]
        limbs = fe.bytes32_to_limbs_np(
            nb.reshape(n * 3, 32)
        ).reshape(n, 3, fe.NLIMB)
        return _pack_rows_np(limbs)

    window = 1 << wbits
    pts = []
    base_b = ref.B
    base_a = _point_neg(point)
    for i in range(npos_for(wbits)):
        row_b = ref.IDENTITY
        for ws in range(window):
            acc = row_b
            for wk in range(window):
                pts.append(acc)
                acc = ref.point_add(acc, base_a)
            row_b = ref.point_add(row_b, base_b)
        for _ in range(wbits):  # bases <- 2^wbits * bases
            base_b = ref.point_double(base_b)
            base_a = ref.point_double(base_a)
    return _batch_affine_niels_np(pts)


_BASE_TABLE: Optional[np.ndarray] = None
_BASE_TABLE_DEV = None


def base_table() -> np.ndarray:
    """Constant comb table of the Ed25519 base point (built once)."""
    global _BASE_TABLE
    if _BASE_TABLE is None:
        _BASE_TABLE = comb_table_np(ref.B)
    return _BASE_TABLE


def base_table_device() -> jnp.ndarray:
    """Device-resident copy of base_table() (uploaded once — the verify
    hot path must not re-transfer 256 KB per batch)."""
    global _BASE_TABLE_DEV
    if _BASE_TABLE_DEV is None:
        _BASE_TABLE_DEV = jnp.asarray(base_table())
    return _BASE_TABLE_DEV


def nibbles_major_np(le_bytes: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 little-endian scalar -> (NPOS, n) int32 nibbles,
    least significant first (position i carries weight 16^i — matching
    comb_table_np, order-free since the comb has no doublings).
    POSITION-MAJOR — the device layout, written directly (interleaved row
    assignment) so the hot prep path never transposes."""
    cols = le_bytes.T  # (32, n) strided view
    out = np.empty((NPOS, le_bytes.shape[0]), dtype=np.int32)
    out[0::2] = cols & 0x0F
    out[1::2] = cols >> 4
    return out


def windows_major_np(le_bytes: np.ndarray, wbits: int) -> np.ndarray:
    """(n, 32) uint8 little-endian scalar -> (npos, n) int32 wbits-bit
    windows, least significant first, position-major (the shared
    fe.extract_windows_np decoder; w=4 keeps the cheaper nibble
    interleave). The top position's window is naturally truncated to the
    scalar's top bits."""
    if wbits == 4:
        return nibbles_major_np(le_bytes)
    return fe.extract_windows_np(le_bytes, wbits, npos_for(wbits))


# ---------------------------------------------------------------------------
# Device kernel pieces (limb-major, batch-minor)
# ---------------------------------------------------------------------------


def _unpack_element(words: jnp.ndarray) -> jnp.ndarray:
    """(9, ...) packed words -> (17, ...) limbs: lo | hi << 15 pairs for
    limbs 0..15, the 17th limb rides alone in word 8."""
    lo = words[:8] & 0x7FFF
    hi = (words[:8] >> 15) & 0x7FFF
    pairs = jnp.stack([lo, hi], axis=1).reshape((16,) + words.shape[1:])
    return jnp.concatenate([pairs, words[8:9]], axis=0)


def _row_niels(rows: jnp.ndarray):
    """Table rows (ROW, ...) -> (ypx, ymx, xy2d) limb arrays (17, ...).
    Layout (dense int32-per-limb vs 15-bit pair-packed) is captured at
    trace time from the module switch (use_row_packing)."""
    if PACKED:
        return (
            _unpack_element(rows[0:9]),
            _unpack_element(rows[9:18]),
            _unpack_element(rows[18:27]),
        )
    n = fe.NLIMB
    return rows[:n], rows[n : 2 * n], rows[2 * n : 3 * n]


def negate_rows(rows: jnp.ndarray) -> jnp.ndarray:
    """Niels negation on packed rows: swap (y+x, y−x), negate 2dxy.
    Dense layout only — the separate-table comb path that needs it never
    runs packed (use_row_packing gates the fused path's tables)."""
    if PACKED:
        # unconditional (NOT an assert): under `python -O` a packed
        # table silently negated with dense-layout arithmetic would
        # produce wrong group elements — and wrong verify verdicts —
        # instead of failing loudly (ADVICE r5)
        raise RuntimeError(
            "negate_rows is a dense-layout (comb-mode) helper; "
            "packed rows (use_row_packing) only feed the fused path"
        )
    ypx, ymx, xy2d = _row_niels(rows)
    return jnp.concatenate(
        [ymx, ypx, fe.neg(xy2d), rows[3 * fe.NLIMB :]], axis=0
    )


def _madd_tuple(x1, y1, z1, t1, rows):
    """Mixed add on coordinate tuples: extended (17, ...) x4 + packed
    Niels rows (ROW, ...). ref10-style ge_madd — 7 field muls. Same group
    law as edwards.point_add with Z2 = 1 and the Niels components
    precomputed. Tuple form so the Pallas loop carries register-resident
    coordinates without stack/unstack churn."""
    ypx, ymx, xy2d = _row_niels(rows)
    a = fe.mul(fe.add(y1, x1), ypx)
    b = fe.mul(fe.sub(y1, x1), ymx)
    c = fe.mul(xy2d, t1)
    d = fe.mul_small(z1, 2)
    e = fe.sub(a, b)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(a, b)
    return fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)


def madd(p: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """Mixed add: extended (4, 17, ...) + packed Niels rows (ROW, ...)."""
    x, y, z, t = _madd_tuple(p[0], p[1], p[2], p[3], rows)
    return jnp.stack([x, y, z, t], axis=0)


_IDENT_LIMBS: Optional[np.ndarray] = None


def ref_identity_limbs() -> np.ndarray:
    global _IDENT_LIMBS
    if _IDENT_LIMBS is None:
        _IDENT_LIMBS = np.stack(
            [fe._int_to_limbs_np(c % ref.P) for c in ref.IDENTITY]
        )
    return _IDENT_LIMBS


def _ident_like(batch_ref: jnp.ndarray) -> jnp.ndarray:
    """(4, 17, B) identity accumulator. Derived from a batch-varying array
    (not a broadcast constant) so the loop carry inherits the data's
    varying manual axes under shard_map."""
    ident = jnp.asarray(ref_identity_limbs())[:, :, None]  # (4, 17, 1)
    return ident + (batch_ref * 0)[None, None]


def _gather_rows(flat_table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """One flat fetch of every position's packed row, staged position-major.

    flat_table: (M, ROW). idx: (NPOS, B) row indices. -> (NPOS, ROW, B).
    A single big `take` keeps the gather dense (the per-position-in-loop
    form is ~20x slower on TPU); the transpose to batch-minor happens once
    here, not per position.
    """
    npos, b = idx.shape
    rows = jnp.take(flat_table, idx.reshape(-1), axis=0)  # (NPOS*B, ROW)
    return rows.reshape(npos, b, ROW).transpose(0, 2, 1)


def comb_accumulate(
    s_nibbles: jnp.ndarray,
    k_nibbles: jnp.ndarray,
    a_row_base: jnp.ndarray,
    a_flat: jnp.ndarray,
    b_flat: jnp.ndarray,
) -> jnp.ndarray:
    """[S]B + [k](−A) via separate comb tables: two row fetches + two
    mixed adds per nibble position (128 madds total).

    s_nibbles, k_nibbles: (NPOS, B) int32. a_row_base: (B,) int32 =
    key_index * NPOS * WINDOW. a_flat: (n_keys*NPOS*WINDOW, ROW).
    b_flat: (NPOS*WINDOW, ROW).
    """
    pos = jnp.arange(NPOS, dtype=jnp.int32)[:, None]
    b_rows = _gather_rows(b_flat, pos * WINDOW + s_nibbles)
    a_rows = _gather_rows(a_flat, a_row_base[None, :] + pos * WINDOW + k_nibbles)
    acc0 = _ident_like(s_nibbles[0])

    def body(i, acc):
        acc = madd(acc, b_rows[i])
        return madd(acc, negate_rows(a_rows[i]))

    return lax.fori_loop(0, NPOS, body, acc0)


def fused_accumulate(
    s_windows: jnp.ndarray,
    k_windows: jnp.ndarray,
    row_base: jnp.ndarray,
    f_flat: jnp.ndarray,
    window: int = WINDOW,
    accum: Optional[str] = None,
) -> jnp.ndarray:
    """[S]B + [k](−A) via the fused dual-scalar table: one row fetch + one
    mixed add per window position (npos total; 64 for 4-bit windows).

    s_windows, k_windows: (npos, B) int32. row_base: (B,) int32 =
    key_index * npos * window^2. f_flat: (n_keys*npos*window^2, ROW).
    `window` = 2^wbits is static (captured at trace time).

    The madd loop runs either as plain XLA (fori_loop) or as a Pallas
    kernel that keeps the accumulator and every field-mul intermediate in
    VMEM across all positions (`use_accum_impl`). `accum` overrides the
    global choice — the GSPMD-sharded mesh path must force "xla" (a
    Mosaic custom call has no partitioning rule inside a sharded jit).
    """
    npos = s_windows.shape[0]
    pos = jnp.arange(npos, dtype=jnp.int32)[:, None]
    idx = row_base[None, :] + pos * (window * window) + s_windows * window + k_windows
    rows_all = _gather_rows(f_flat, idx)  # (npos, ROW, B)
    if (accum or _resolve_accum_impl()) == "pallas":
        return _madd_loop_pallas(rows_all)
    acc0 = _ident_like(s_windows[0])

    def body(i, acc):
        return madd(acc, rows_all[i])

    return lax.fori_loop(0, npos, body, acc0)


# ---------------------------------------------------------------------------
# Pallas madd-loop: the whole 64-position accumulation as ONE kernel.
#
# The XLA fori_loop materializes the (4, 17, B) accumulator in HBM every
# iteration and streams each field-mul intermediate through HBM when the
# fusion boundary falls badly. The Pallas kernel tiles the batch, holds the
# four coordinates in VMEM/vector registers across all 64 madds, and only
# the gathered table rows stream in — per-item HBM traffic drops to the
# 64 x 256-byte rows it can't avoid.
# ---------------------------------------------------------------------------

ACCUM_IMPL = "auto"
PALLAS_TILE = 256  # batch lanes per kernel program (rows block = 4 MiB)


def use_accum_impl(name: str) -> None:
    """Select the fused-accumulate implementation ('auto', 'xla' or
    'pallas') BEFORE any kernel is jitted — jit traces capture the
    choice. 'auto' resolves at trace time: the Pallas kernel on real TPU
    (measured ~28% faster at batch 8k: 662k vs 516k verifies/s on a v5e),
    the XLA fori_loop elsewhere (interpret-mode Pallas is far too slow
    for CPU tests)."""
    global ACCUM_IMPL
    if name not in ("auto", "xla", "pallas"):
        raise ValueError(f"accum impl must be auto|xla|pallas, got {name!r}")
    ACCUM_IMPL = name


def _resolve_accum_impl() -> str:
    if ACCUM_IMPL != "auto":
        return ACCUM_IMPL
    import jax

    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _madd_loop_kernel(rows_ref, out_ref):
    """Pallas body: rows_ref (npos, ROW, T) VMEM block -> out_ref
    (4*NLIMB, T) — the accumulated [S]B + [k](−A) in extended coords."""
    n = fe.NLIMB
    tile = out_ref.shape[-1]
    # identity point (0, 1, 1, 0): built from scalars via iota so the
    # kernel captures no array constants (a Pallas requirement)
    limb0 = lax.broadcasted_iota(jnp.int32, (n, tile), 0) == 0
    zero = jnp.zeros((n, tile), jnp.int32)
    one = jnp.where(limb0, 1, 0)

    def body(i, acc):
        return _madd_tuple(*acc, rows_ref[i])

    x, y, z, t = lax.fori_loop(0, rows_ref.shape[0], body, (zero, one, one, zero))
    out_ref[0 * n : 1 * n] = x
    out_ref[1 * n : 2 * n] = y
    out_ref[2 * n : 3 * n] = z
    out_ref[3 * n : 4 * n] = t


def _madd_loop_pallas(rows_all: jnp.ndarray) -> jnp.ndarray:
    """(npos, ROW, B) gathered rows -> (4, 17, B) accumulator."""
    import jax
    from jax.experimental import pallas as pl

    npos, b = rows_all.shape[0], rows_all.shape[-1]
    tile = min(PALLAS_TILE, b)
    assert b % tile == 0, (b, tile)
    out = pl.pallas_call(
        _madd_loop_kernel,
        out_shape=jax.ShapeDtypeStruct((4 * fe.NLIMB, b), jnp.int32),
        grid=(b // tile,),
        in_specs=[
            pl.BlockSpec((npos, ROW, tile), lambda i: (0, 0, i)),
        ],
        out_specs=pl.BlockSpec((4 * fe.NLIMB, tile), lambda i: (0, i)),
        interpret=jax.default_backend() != "tpu",
    )(rows_all)
    return out.reshape(4, fe.NLIMB, b)


def _interleave(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(17, m), (17, m) -> (17, 2m) alternating a0 b0 a1 b1 ..."""
    return jnp.stack([a, b], axis=2).reshape(a.shape[0], -1)


CHAIN_WIDTH = 128  # one full VREG of lanes: the Fermat chain is as cheap
# on (17, 128) as on (17, 1), so the tree stops here — the levels below
# ran 64..1-wide on 128-wide vector lanes, pure sequential-dependency
# waste (the r4 chip profile charged ~23% of the verify pass to this
# tail for ~1.3% of its field muls).


def batch_invert(z: jnp.ndarray) -> jnp.ndarray:
    """Tree-structured Montgomery batch inversion: (17, B) -> (17, B).

    Pairwise products up the tree (log2(B/CHAIN_WIDTH) batched muls
    totalling ≈ B multiplies), ONE lane-parallel Fermat chain across the
    whole CHAIN_WIDTH-wide root level, then unfold back down (≈ 2B
    multiplies). Requires B a power of two and all inputs nonzero —
    guaranteed for Z coordinates of complete Edwards formulas.
    """
    n = z.shape[1]
    assert n & (n - 1) == 0, "batch_invert requires a power-of-two batch"
    levels = []
    cur = z
    while cur.shape[1] > CHAIN_WIDTH:
        levels.append(cur)
        cur = fe.mul(cur[:, 0::2], cur[:, 1::2])
    inv = fe.invert(cur)  # the only exponentiation chain, all lanes busy
    for lev in reversed(levels):
        left, right = lev[:, 0::2], lev[:, 1::2]
        inv = _interleave(fe.mul(inv, right), fe.mul(inv, left))
    return inv


def _encode_and_compare(
    p: jnp.ndarray, r_y: jnp.ndarray, r_sign: jnp.ndarray, precheck: jnp.ndarray
) -> jnp.ndarray:
    """Affine-normalize the accumulator (batch inversion) and compare its
    canonical encoding against R's wire bytes."""
    zinv = batch_invert(p[2])
    x_aff = fe.mul(p[0], zinv)
    y_aff = fe.mul(p[1], zinv)
    ok = fe.eq(y_aff, r_y) & (fe.parity(x_aff) == r_sign)
    return ok & precheck


def fused_verify_kernel(
    s_windows: jnp.ndarray,  # (npos, B) int32 — S scalar windows
    k_windows: jnp.ndarray,  # (npos, B) int32 — challenge scalar windows
    a_index: jnp.ndarray,  # (B,) int32 — key row into the fused table bank
    f_table: jnp.ndarray,  # (n_keys*npos*window^2, ROW) packed Niels rows
    r_y: jnp.ndarray,  # (17, B) int32 — R's canonical y limbs
    r_sign: jnp.ndarray,  # (B,) int32 — R's x sign bit
    precheck: jnp.ndarray,  # (B,) bool — host-side validity mask
    window: int = WINDOW,  # static: 2^wbits entries per scalar per position
    accum: Optional[str] = None,  # static accumulate-impl override
) -> jnp.ndarray:
    """Batched verify via the fused comb: one row fetch + one madd per
    window position (64 at w=4, 52 at w=5, 43 at w=6)."""
    npos = s_windows.shape[0]
    p = fused_accumulate(
        s_windows,
        k_windows,
        a_index * (npos * window * window),
        f_table,
        window=window,
        accum=accum,
    )
    return _encode_and_compare(p, r_y, r_sign, precheck)


def fused_verify_wire_kernel(
    wire: jnp.ndarray,  # (B, 96) uint8 — S (32) ‖ k (32) ‖ R (32) raw bytes
    a_index: jnp.ndarray,  # (B,) int32 — key row into the fused table bank
    f_table: jnp.ndarray,  # (n_keys*npos*window^2, ROW) packed Niels rows
    precheck: jnp.ndarray,  # (B,) bool — host-side validity mask
    window: int = WINDOW,
    accum: Optional[str] = None,
) -> jnp.ndarray:
    """fused_verify_kernel taking RAW wire bytes, one packed (B, 96)
    uint8 array per batch: scalar-window extraction, R limb decomposition
    and the sign bit all happen on device (fe.extract_windows_dev).

    This is the transfer-lean staging path: ~100 bytes/item cross the
    host->device link instead of ~290 (int32 windows + limbs), and the
    host sheds the unpack work. XLA fuses the byte shuffling into the
    kernel prologue — measured device rate is unchanged; e2e rate is
    what improves (it is transfer/host-bound, especially over a
    tunneled device)."""
    wbits = window.bit_length() - 1
    npos = npos_for(wbits)
    s_w = fe.extract_windows_dev(wire[:, 0:32], wbits, npos)
    k_w = fe.extract_windows_dev(wire[:, 32:64], wbits, npos)
    r_y = fe.extract_windows_dev(wire[:, 64:96], fe.RADIX, fe.NLIMB)
    r_sign = wire[:, 95].astype(jnp.int32) >> 7
    return fused_verify_kernel(
        s_w, k_w, a_index, f_table, r_y, r_sign, precheck,
        window=window, accum=accum,
    )


def comb_verify_kernel(
    s_nibbles: jnp.ndarray,  # (NPOS, B) int32 — S scalar nibbles
    k_nibbles: jnp.ndarray,  # (NPOS, B) int32 — challenge scalar nibbles
    a_index: jnp.ndarray,  # (B,) int32 — key row into the pubkey table bank
    a_table: jnp.ndarray,  # (n_keys*NPOS*WINDOW, ROW) packed Niels rows
    b_table: jnp.ndarray,  # (NPOS*WINDOW, ROW) packed rows (base point)
    r_y: jnp.ndarray,  # (17, B) int32 — R's canonical y limbs
    r_sign: jnp.ndarray,  # (B,) int32 — R's x sign bit
    precheck: jnp.ndarray,  # (B,) bool — host-side validity mask
) -> jnp.ndarray:
    """Batched verify via combs: [S]B + [k](−A) must encode to R's bytes."""
    p = comb_accumulate(
        s_nibbles, k_nibbles, a_index * (NPOS * WINDOW), a_table, b_table
    )
    return _encode_and_compare(p, r_y, r_sign, precheck)
