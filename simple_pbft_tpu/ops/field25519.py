"""GF(2^255 - 19) arithmetic in int32 limbs — the TPU field kernel.

TPUs have no 64-bit integer multiply, so field elements are represented as
17 limbs of 15 bits each (17 * 15 = 255 exactly) held in int32. The radix
is chosen so that:

- a limb product fits int32: (2^15 + eps)^2 < 2^31;
- the schoolbook convolution never overflows: each 30-bit product is split
  into (lo = p & 0x7fff, hi = p >> 15) before accumulation, so a column
  sums at most 17 lo-terms (< 2^15) + 17 hi-terms (< 2^16) < 2^21;
- the reduction fold is a clean multiply-by-19: limb position 17 has
  weight 2^255 ≡ 19 (mod p), so high columns fold back as `col * 19`.

Layout: a field element is an int32 array `(17, ...)` — the LIMB axis
leads and batch axes trail. This is the TPU-native choice: XLA maps the
minor-most axis to the 128-wide vector lanes, so with batch minor a
(17, B) element wastes nothing (B is a lane multiple), while the previous
batch-major (B, 17) form padded 17 -> 128 lanes and made every hot-path
intermediate ~7.5x larger in HBM. Measured on a v5e chip this layout is
~2.8x faster for the madd chain that dominates verification.

All functions are shape-polymorphic over TRAILING batch dimensions and
pure jnp — jittable, vmappable, shardable. Carry ripples are expressed as
tiny unrolled loops over the 17 limbs (static Python loops; the batch
dimension fills the VPU lanes, so per-limb sequential carries vectorize
across the batch).

Normal form ("weak"): limbs 1..16 in [0, 2^15); limb 0 in [0, 2^15 + 19].
`to_canonical` produces the unique representative < p for comparisons and
encoding.

This fills the crypto hot path that the reference lacks entirely (no
signatures anywhere in /root/reference — SURVEY.md §2.1); it is new,
TPU-first code, not a port.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np
from jax import lax

NLIMB = 17
RADIX = 15
MASK = (1 << RADIX) - 1  # 0x7fff
P_INT = 2**255 - 19

DTYPE = jnp.int32


def _int_to_limbs_np(v: int) -> np.ndarray:
    """Host-side: Python int -> (17,) int32 limb array."""
    out = np.zeros(NLIMB, dtype=np.int32)
    for i in range(NLIMB):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0, "value exceeds 255 bits"
    return out


def _limbs_to_int_np(limbs: np.ndarray) -> int:
    """Host-side inverse (for tests/debug); limb axis leading."""
    v = 0
    for i in reversed(range(NLIMB)):
        # .item(): exact for scalars AND size-1 batch dims (a bare int()
        # on an ndim>0 array is a numpy DeprecationWarning on its way to
        # a TypeError), and loudly fails on a real batch instead of
        # silently folding it
        v = (v << RADIX) | int(np.asarray(limbs[i, ...]).item())
    return v


def bcast(c: np.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Reshape a (17,) limb constant so it broadcasts against x's
    trailing batch axes: (17,) -> (17, 1, ..., 1)."""
    return jnp.asarray(c).reshape((NLIMB,) + (1,) * (x.ndim - 1))


def const(v: int) -> jnp.ndarray:
    """Embed a Python int < 2^255 as a constant limb array (17,)."""
    return jnp.asarray(_int_to_limbs_np(v % P_INT))


ZERO = _int_to_limbs_np(0)
ONE = _int_to_limbs_np(1)
P_LIMBS = _int_to_limbs_np(P_INT)


def zeros_like(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.zeros_like(x)


# ---------------------------------------------------------------------------
# Carry propagation / normalization
# ---------------------------------------------------------------------------


def _ripple(x: jnp.ndarray) -> jnp.ndarray:
    """One sequential carry pass: limbs -> [0, 2^15), carry-out folded in
    as *19 on limb 0 (2^255 ≡ 19 mod p). Exact but latency-bound (17
    dependent steps) — used only by `normalize_strict` / `to_canonical`,
    never on the hot path."""
    outs: List[jnp.ndarray] = []
    c = jnp.zeros_like(x[0])
    for i in range(NLIMB):
        t = x[i] + c
        outs.append(t & MASK)
        c = t >> RADIX
    outs[0] = outs[0] + 19 * c
    return jnp.stack(outs, axis=0)


def normalize_strict(x: jnp.ndarray) -> jnp.ndarray:
    """Two sequential carry passes -> strict weak form (limbs 1..16 in
    [0, 2^15), limb0 < 2^15 + 19). Needed before to_canonical's
    borrow-ripple subtraction, which assumes in-range limbs."""
    return _ripple(_ripple(x))


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One PARALLEL carry pass over the whole limb axis (5 vectorized VPU
    ops, no sequential dependency across limbs): every limb sheds its
    carry to its neighbor simultaneously; the top carry folds into limb 0
    as *19."""
    c = x >> RADIX
    shifted = jnp.concatenate([19 * c[-1:], c[:-1]], axis=0)
    return (x & MASK) + shifted


def normalize(x: jnp.ndarray) -> jnp.ndarray:
    """Two parallel carry passes -> relaxed weak form. Hot-path invariant
    (inputs nonnegative, limbs < 2^26 — the mul-fold bound):

    - pass 1: carries < 2^11, so limbs < 2^15 + 2^11 (limb 0 gets 19*c
      < 2^16.3, still < 2^17);
    - pass 2: carries <= 2 (limb 1 gets <= 2^2), so limbs land in
      [0, 2^15 + 2^11) with limb 0 < 2^15 + 19*2.

    Relaxed-weak inputs keep the next mul exact in int32:
    (2^15 + 2^11)^2 < 1.14 * 2^30 < 2^31, and the lo/hi column sums stay
    17*(2^15 + 1.14*2^16) < 2^21. `to_canonical` re-normalizes strictly,
    so comparisons are unaffected.
    """
    return _carry_pass(_carry_pass(x))


def to_canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Weak form -> unique representative in [0, p)."""
    x = normalize_strict(x)
    # weak value < 2^255 + 18 < 2p, so at most one subtraction of p needed —
    # but limb0 may hold up to 2^15+18 (value can slightly exceed 2^255-1),
    # subtract with borrow and select.
    p_limbs = jnp.asarray(P_LIMBS)
    for _ in range(2):
        diff = []
        b = jnp.zeros_like(x[0])
        for i in range(NLIMB):
            t = x[i] - p_limbs[i] - b
            b = (t >> 31) & 1  # 1 if negative
            diff.append(t + (b << RADIX))
        diff_arr = jnp.stack(diff, axis=0)
        ge_p = (b == 0)[None]
        x = jnp.where(ge_p, diff_arr, x)
    return x


# ---------------------------------------------------------------------------
# Ring ops
# ---------------------------------------------------------------------------


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Sum < 2^16 + 2^12 per limb, so ONE parallel carry pass suffices
    (carries <= 2) to return to relaxed weak form."""
    return _carry_pass(a + b)


def _two_p(x: jnp.ndarray) -> jnp.ndarray:
    """2p as limbs, built from scalars via iota/where: only limb 0
    differs from 2*MASK. Constructed (not embedded as a concrete array)
    so Pallas kernels using sub/neg don't capture array constants."""
    i = lax.broadcasted_iota(jnp.int32, (NLIMB,) + (1,) * (x.ndim - 1), 0)
    return jnp.where(i == 0, 2 * (2**RADIX - 19), 2 * MASK)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b, computed as a + 2p - b to stay nonnegative (< 2^17 per
    limb, one carry pass)."""
    return _carry_pass(a + _two_p(a) - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return _carry_pass(_two_p(a) - a)


def mul_padacc(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply via 17 shifted broadcast rows (pad-accumulate).

    Each of the 17 partial rows is a broadcast multiply a_i * b ->
    (17, ...), split into lo/hi, and padded into its column offset of a
    (35, ...) accumulator. With the limb axis MAJOR the pads are extent
    changes on the slowest-varying axis — no lane relayout — and all
    elementwise ops fuse in XLA; the batch stays resident in the vector
    lanes. This is the production hot-path multiply (~3 ns/item/mul for
    the madd chain on a v5e at batch 8192, ~2.8x the batch-major form).
    """
    nb = a.ndim - 1
    acc = jnp.zeros((2 * NLIMB + 1,) + a.shape[1:], dtype=a.dtype)
    for i in range(NLIMB):
        p = a[i : i + 1] * b  # (17, ...)
        lo = p & MASK
        hi = p >> RADIX
        acc = acc + jnp.pad(lo, [(i, NLIMB - i + 1)] + [(0, 0)] * nb)
        acc = acc + jnp.pad(hi, [(i + 1, NLIMB - i)] + [(0, 0)] * nb)
    # fold: column 17+t has weight 2^255 * 2^(15t) ≡ 19 * 2^(15t);
    # column 34 (top hi) is always zero since hi of a_16*b_16 lands at 33
    out = acc[:NLIMB] + 19 * acc[NLIMB : 2 * NLIMB]
    return normalize(out)


def mul_skew(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply via the materialized outer product + skew reduction.

    Materializes a (17, 17, ...) product tensor; the antidiagonal sums use
    the skew trick (pad rows to 35 and reshape, so element (i, j) lands in
    column i + j). Compact in HLO (~25 ops/mul vs ~135 for padacc) so the
    ~300-multiply exponentiation chains always use it to keep compile
    times bounded; kept selectable for the hot path via `use_mul_impl`
    for A/B benchmarking.
    """
    prod = a[:, None] * b[None, :]  # (17, 17, ...)
    nb = prod.ndim - 2

    def anti(m):
        padded = jnp.pad(m, [(0, 0), (0, NLIMB + 1)] + [(0, 0)] * nb)
        flat = padded.reshape((NLIMB * (2 * NLIMB + 1),) + m.shape[2:])
        skewed = flat[: NLIMB * 2 * NLIMB].reshape(
            (NLIMB, 2 * NLIMB) + m.shape[2:]
        )
        return skewed.sum(axis=0)  # (34, ...)

    lo_cols = anti(prod & MASK)
    hi_cols = anti(prod >> RADIX)
    cols = lo_cols + jnp.pad(hi_cols[:-1], [(1, 0)] + [(0, 0)] * nb)
    out = cols[:NLIMB] + 19 * cols[NLIMB:]
    return normalize(out)


# The production field multiply (see mul_padacc docstring). `use_mul_impl`
# selects the skew form for A/B benchmarking on real hardware.
mul = mul_padacc

# The exponentiation chains unroll ~300 sequential multiplies on tiny
# (often (17, 1)) operands — runtime-negligible but compile-dominating.
# They always use the compact skew form (~25 HLO ops/mul vs ~135) so the
# hot-path mul choice doesn't balloon compile times 5-10x.
_chain_mul = mul_skew


def use_mul_impl(name: str) -> None:
    """Select the hot-path field-multiply formulation ('padacc' or 'skew')
    BEFORE any kernel is jitted — jit traces capture whatever `mul` is
    bound to at trace time."""
    global mul
    mul = {"padacc": mul_padacc, "skew": mul_skew}[name]


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small positive scalar (k < 2^15)."""
    return normalize(a * k)


# ---------------------------------------------------------------------------
# Exponentiation chains (ref10-style addition chains — 254 squarings,
# ~12 multiplies; vs ~510 multiplies for binary square-and-multiply)
# ---------------------------------------------------------------------------


def _sqn(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via n squarings (fori_loop keeps the XLA graph small)."""
    if n <= 4:
        for _ in range(n):
            x = _chain_mul(x, x)
        return x
    return lax.fori_loop(0, n, lambda _, v: _chain_mul(v, v), x)


def _chain_250(x: jnp.ndarray):
    """Shared prefix: returns (x^(2^250 - 1), x^11, x^2)."""
    z2 = _chain_mul(x, x)
    z8 = _sqn(z2, 2)
    z9 = _chain_mul(x, z8)
    z11 = _chain_mul(z2, z9)
    z22 = _chain_mul(z11, z11)
    z_5_0 = _chain_mul(z9, z22)  # x^(2^5 - 1)
    z_10_5 = _sqn(z_5_0, 5)
    z_10_0 = _chain_mul(z_10_5, z_5_0)  # x^(2^10 - 1)
    z_20_10 = _sqn(z_10_0, 10)
    z_20_0 = _chain_mul(z_20_10, z_10_0)
    z_40_20 = _sqn(z_20_0, 20)
    z_40_0 = _chain_mul(z_40_20, z_20_0)
    z_50_10 = _sqn(z_40_0, 10)
    z_50_0 = _chain_mul(z_50_10, z_10_0)
    z_100_50 = _sqn(z_50_0, 50)
    z_100_0 = _chain_mul(z_100_50, z_50_0)
    z_200_100 = _sqn(z_100_0, 100)
    z_200_0 = _chain_mul(z_200_100, z_100_0)
    z_250_50 = _sqn(z_200_0, 50)
    z_250_0 = _chain_mul(z_250_50, z_50_0)  # x^(2^250 - 1)
    return z_250_0, z11, z2


def invert(x: jnp.ndarray) -> jnp.ndarray:
    """x^(p-2) = x^(2^255 - 21): multiplicative inverse (0 -> 0)."""
    z_250_0, z11, _ = _chain_250(x)
    return _chain_mul(_sqn(z_250_0, 5), z11)


def pow22523(x: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3) — the square-root helper exponent."""
    z_250_0, _, _ = _chain_250(x)
    return _chain_mul(_sqn(z_250_0, 2), x)


# ---------------------------------------------------------------------------
# Predicates / conversion helpers
# ---------------------------------------------------------------------------


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Canonical equality -> bool (...,)."""
    return jnp.all(to_canonical(a) == to_canonical(b), axis=0)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(to_canonical(a) == 0, axis=0)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical representative (the Edwards sign bit)."""
    return to_canonical(a)[0] & 1


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, broadcasting cond (...,) over the leading limb axis."""
    return jnp.where(cond[None], a, b)


# ---------------------------------------------------------------------------
# Host-side byte <-> limb conversion (vectorized numpy; used by the
# verifier's batch-preparation path). Host arrays are batch-major (n, 17)
# — natural for row-wise wire decoding — and transposed to the device's
# limb-major layout at staging time (see tpu_verifier.prepare_*).
# ---------------------------------------------------------------------------


def extract_windows_np(data: np.ndarray, wbits: int, count: int) -> np.ndarray:
    """(n, 32) uint8 little-endian -> (count, n) int32: window i holds
    bits [i*wbits, (i+1)*wbits) of the 256-bit value, position-major (the
    device layout, produced directly so hot prep paths never transpose).

    View the bytes as four little-endian uint64 words and extract each
    window with two shifts — `count` vectorized ops total vs an
    unpackbits expansion to 256 int32 lanes per item (~10x faster at
    batch 8k). Windows extending past bit 255 are naturally truncated.
    Shared by the field-limb (wbits=15) and comb-window (wbits=4/5/6)
    decoders so the word-straddle logic lives in exactly one place."""
    words = np.ascontiguousarray(data).view("<u8")  # (n, 4)
    mask = np.uint64((1 << wbits) - 1)
    out = np.empty((count, data.shape[0]), dtype=np.int32)
    for i in range(count):
        bitpos = i * wbits
        w, s = bitpos >> 6, bitpos & 63
        v = words[:, w] >> np.uint64(s)
        if s > 64 - wbits and w + 1 < 4:  # window straddles a word boundary
            v = v | (words[:, w + 1] << np.uint64(64 - s))
        out[i] = (v & mask).astype(np.int32)
    return out


def bytes32_to_limbs_major_np(data: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 little-endian -> (17, n) int32 limbs of the low 255
    bits (bit 255 — the sign bit — is excluded), limb-major."""
    return extract_windows_np(data, RADIX, NLIMB)


def extract_windows_dev(data: jnp.ndarray, wbits: int, count: int) -> jnp.ndarray:
    """Device-side twin of extract_windows_np: (n, 32) uint8 wire bytes ->
    (count, n) int32 windows, inside jit.

    Exists so the verify kernel can take RAW wire bytes: the host then
    transfers 32 bytes per scalar instead of `count` int32 windows (3.3x
    fewer bytes over the host->device link — which is the e2e bound when
    the device sits behind a network tunnel, and still saves HBM traffic
    when it doesn't). TPUs have no 64-bit lanes, so instead of the numpy
    version's uint64 word trick each window gathers its (at most) three
    covering bytes and shifts in int32 — all static indexing, fused by
    XLA into the kernel prologue."""
    b = data.astype(jnp.int32)  # (n, 32)
    bitpos = np.arange(count) * wbits
    lo = bitpos >> 3
    sh = jnp.asarray(bitpos & 7, dtype=jnp.int32)
    parts = []
    for k in range(3):  # wbits<=15 and sh<=7 => a window spans <=3 bytes
        idx = np.minimum(lo + k, 31)
        byte = b[:, idx]  # (n, count) static gather
        byte = jnp.where(jnp.asarray(lo + k <= 31), byte, 0)
        left = jnp.maximum(8 * k - sh, 0)  # k=0 only ever shifts right
        right = jnp.maximum(sh - 8 * k, 0)
        parts.append((byte << left) >> right)
    v = parts[0] | parts[1] | parts[2]
    return (v & ((1 << wbits) - 1)).T.astype(jnp.int32)


def bytes32_to_limbs_np(data: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 little-endian -> (n, 17) int32 limbs (batch-major
    form for host-side table building; see bytes32_to_limbs_major_np)."""
    return bytes32_to_limbs_major_np(data).T


def sign_bits_np(data: np.ndarray) -> np.ndarray:
    """(n, 32) uint8 -> (n,) int32 top bit (Edwards x sign)."""
    return (data[..., 31] >> 7).astype(np.int32)
