"""TPU-native numeric kernels: GF(2^255-19) limb arithmetic and Edwards
curve point operations, written in pure jnp (int32) so they jit/vmap/shard
onto TPU. The Pallas variants (ops/pallas_field.py) slot in behind the same
API for the hot multiply."""
