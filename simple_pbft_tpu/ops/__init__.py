"""TPU-native numeric kernels: GF(2^255-19) limb arithmetic
(``field25519``), Edwards curve point operations (``edwards``), and the
comb-table double-scalar multiplication kernel (``comb``) — written in
pure jnp (int32) so they jit/vmap/shard onto TPU."""
