"""Edwards25519 point arithmetic on TPU (extended coordinates, a = -1).

Points are int32 arrays of shape (4, 17, ...): stacked (X, Y, Z, T) limb
vectors with x = X/Z, y = Y/Z, T = XY/Z. Like the field layer
(ops/field25519.py), the limb axis leads and batch axes trail so the batch
fills the 128-wide vector lanes — the layout that makes the fixed ladder
VPU-dense instead of HBM-bound. The stacked layout keeps constant-shape
table selection (jnp.where over a (k, 4, 17, ...) table) trivial — the
design constraint is XLA: no data-dependent control flow, every verify is
the same fixed ladder.

Formulas: unified add-2008-hwcd-3 and dbl-2008-hwcd (same formulas the CPU
oracle in crypto/ed25519_cpu.py uses, so both planes agree bit-for-bit).

The double-scalar ladder computes [s]B + [k]Q in one 256-iteration
interleaved (Straus) pass: shared doublings, one table add per bit pair.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from . import field25519 as fe
from ..crypto import ed25519_cpu as ref

# -- constants (limb form, derived from the CPU module's verified ints) ----

D2_INT = (2 * ref.D) % ref.P
SQRT_M1 = fe._int_to_limbs_np(ref.SQRT_M1)
D_LIMBS = fe._int_to_limbs_np(ref.D)
D2_LIMBS = fe._int_to_limbs_np(D2_INT)


def _point_const(p: Tuple[int, int, int, int]) -> np.ndarray:
    return np.stack([fe._int_to_limbs_np(c % ref.P) for c in p])


IDENTITY = _point_const(ref.IDENTITY)  # (4, 17)
BASE = _point_const(ref.B)


def _pconst(c: np.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """(4, 17) point constant -> broadcastable against (4, 17, ...)."""
    return jnp.asarray(c).reshape((4, fe.NLIMB) + (1,) * (like.ndim - 2))


# -- coordinate accessors ---------------------------------------------------


def _unpack(p: jnp.ndarray):
    return p[0], p[1], p[2], p[3]


def _pack(x, y, z, t) -> jnp.ndarray:
    return jnp.stack([x, y, z, t], axis=0)


# -- group law --------------------------------------------------------------


def point_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Unified addition (add-2008-hwcd-3); mirrors ed25519_cpu.point_add."""
    x1, y1, z1, t1 = _unpack(p)
    x2, y2, z2, t2 = _unpack(q)
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, fe.bcast(D2_LIMBS, t1)), t2)
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return _pack(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_double(p: jnp.ndarray) -> jnp.ndarray:
    """Doubling (dbl-2008-hwcd); mirrors ed25519_cpu.point_double."""
    x1, y1, z1, _ = _unpack(p)
    a = fe.sq(x1)
    b = fe.sq(y1)
    c = fe.mul_small(fe.sq(z1), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.sq(fe.add(x1, y1)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return _pack(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_neg(p: jnp.ndarray) -> jnp.ndarray:
    """-(x, y) = (-x, y); T = xy negates too."""
    x, y, z, t = _unpack(p)
    return _pack(fe.neg(x), y, z, fe.neg(t))


def point_select(idx: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """table[idx] with constant shape: table (k, 4, 17, ...), idx (...,).
    A where-chain (not gather) so XLA vectorizes it across the batch."""
    k = table.shape[0]
    out = table[0]
    for i in range(1, k):
        out = jnp.where((idx == i)[None, None], table[i], out)
    return out


# -- scalar multiplication --------------------------------------------------


def double_scalar_mul_base(
    s_bits: jnp.ndarray, k_bits: jnp.ndarray, q: jnp.ndarray
) -> jnp.ndarray:
    """[s]B + [k]Q via interleaved Straus ladder.

    s_bits, k_bits: (256, ...) int32 bits, MSB first. q: (4, 17, ...).
    One shared doubling per bit; the per-bit addend is selected from the
    4-entry table {identity, B, Q, B+Q} by the bit pair. 256 uniform
    iterations — constant shape, no data-dependent control flow.
    """
    base = jnp.broadcast_to(_pconst(BASE, q), q.shape)
    # derive from q (not broadcast a constant) so the loop carry inherits
    # q's varying manual axes under shard_map
    ident = q * 0 + _pconst(IDENTITY, q)
    table = jnp.stack([ident, base, q, point_add(base, q)], axis=0)

    def body(i, acc):
        acc = point_double(acc)
        idx = s_bits[i] + 2 * k_bits[i]
        addend = point_select(idx, table)
        return point_add(acc, addend)

    return lax.fori_loop(0, 256, body, ident)


# -- compression / decompression -------------------------------------------


def compress(p: jnp.ndarray):
    """-> (y_limbs canonical (17, ...), x_parity (...,)) — the wire form is
    y with the sign bit of x in bit 255 (RFC 8032 §5.1.2)."""
    x, y, z, _ = _unpack(p)
    zinv = fe.invert(z)
    xa = fe.mul(x, zinv)
    ya = fe.mul(y, zinv)
    return fe.to_canonical(ya), fe.parity(xa)


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Recover (4, 17, ...) extended point from canonical y and sign bit.

    RFC 8032 §5.1.3: x^2 = (y^2-1)/(d y^2+1); the square root and the
    inversion share one exponentiation: x = u v^3 (u v^7)^((p-5)/8).
    Returns (point, ok) with ok False when x^2 is a non-residue or when
    x = 0 with sign = 1. Mirrors ed25519_cpu._recover_x (callers must
    ensure y < p — host-side canonicality check).
    """
    one = fe.bcast(fe.ONE, y_limbs)
    yy = fe.sq(y_limbs)
    u = fe.sub(yy, one)  # y^2 - 1
    v = fe.add(fe.mul(yy, fe.bcast(D_LIMBS, yy)), one)
    v3 = fe.mul(fe.sq(v), v)
    v7 = fe.mul(fe.sq(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sq(x))
    ok_direct = fe.eq(vxx, u)
    ok_twist = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok_twist, fe.mul(x, fe.bcast(SQRT_M1, x)), x)
    ok = ok_direct | ok_twist
    x = fe.to_canonical(x)
    x_is_zero = fe.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # match the requested sign
    flip = (x[0] & 1) != sign
    x = fe.select(flip, fe.neg(x), x)
    t = fe.mul(x, y_limbs)
    z = jnp.broadcast_to(one, y_limbs.shape)
    return _pack(x, y_limbs, z, t), ok
