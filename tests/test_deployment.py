"""Deployment plane: TCP framing, deploy documents, process launcher.

These are the 582 LoC that landed untested in round 1 (VERDICT weak #4):
hostile/oversized frames, reconnect, outbox overflow, deploy round-trip,
and one real multi-process launch over localhost TCP.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from simple_pbft_tpu import deploy
from simple_pbft_tpu.transport.tcp import (
    MAX_FRAME,
    OUTBOX_DEPTH,
    TcpTransport,
    encode_frame,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _pair():
    """Two connected endpoints on ephemeral localhost ports."""
    a = TcpTransport("a", ("127.0.0.1", 0), peers={})
    b = TcpTransport("b", ("127.0.0.1", 0), peers={})
    await a.start()
    await b.start()
    a.peers["b"] = ("127.0.0.1", b.bound_port)
    b.peers["a"] = ("127.0.0.1", a.bound_port)
    return a, b


async def _stop_all(*ts):
    for t in ts:
        await t.stop()


class TestTcpFraming:
    def test_roundtrip_and_self_send(self):
        async def scenario():
            a, b = await _pair()
            try:
                payloads = [b"x", b"y" * 1000, b"z" * 100_000]
                for p in payloads:
                    await a.send("b", p)
                got = [await asyncio.wait_for(b.recv(), 10) for _ in payloads]
                assert got == payloads
                # self-send loops back without touching the network
                await a.send("a", b"self")
                assert await a.recv() == b"self"
                # unknown destination: fire-and-forget no-op
                await a.send("nobody", b"lost")
            finally:
                await _stop_all(a, b)

        run(scenario())

    def test_hostile_frames_close_connection_but_not_server(self):
        async def scenario():
            a, b = await _pair()
            try:
                for hostile in [
                    (0).to_bytes(4, "big"),  # zero-length frame
                    (MAX_FRAME + 1).to_bytes(4, "big") + b"x",  # oversized
                    b"\xff\xff",  # truncated header then close
                ]:
                    r, w = await asyncio.open_connection(
                        "127.0.0.1", b.bound_port
                    )
                    w.write(hostile)
                    await w.drain()
                    w.close()
                    await w.wait_closed()
                # the server must still accept well-formed traffic
                await a.send("b", b"still alive")
                assert await asyncio.wait_for(b.recv(), 10) == b"still alive"
            finally:
                await _stop_all(a, b)

        run(scenario())

    def test_raw_frame_bytes_layout(self):
        f = encode_frame(b"abc")
        assert f == b"\x00\x00\x00\x03abc"

    def test_reconnect_after_peer_restart(self):
        async def scenario():
            a, b = await _pair()
            b_port = b.bound_port
            try:
                await a.send("b", b"one")
                assert await asyncio.wait_for(b.recv(), 10) == b"one"
                # peer goes down; frames sent meanwhile are fire-and-forget
                await b.stop()
                await a.send("b", b"into the void")
                await asyncio.sleep(0.2)
                # peer comes back on the SAME port
                b2 = TcpTransport("b", ("127.0.0.1", b_port), peers={})
                await b2.start()
                for attempt in range(50):
                    await a.send("b", b"hello again %d" % attempt)
                    got = b2.recv_nowait()
                    if got is not None:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        f"no frame after restart (reconnects="
                        f"{a.metrics['reconnects']})"
                    )
                await b2.stop()
            finally:
                await a.stop()

        run(scenario())

    def test_outbox_overflow_drops_not_blocks(self):
        async def scenario():
            # peer address that never answers: connect() fails fast on a
            # closed port, sender loop backs off, outbox fills
            a = TcpTransport("a", ("127.0.0.1", 0), peers={"ghost": ("127.0.0.1", 1)})
            await a.start()
            try:
                for i in range(OUTBOX_DEPTH + 100):
                    await a.send("ghost", b"frame %d" % i)
                assert a.metrics["dropped_outbox"] >= 100
            finally:
                await a.stop()

        run(scenario())

    def test_recv_queue_bound_drops(self):
        async def scenario():
            b = TcpTransport("b", ("127.0.0.1", 0), peers={}, recv_depth=2)
            await b.start()
            try:
                r, w = await asyncio.open_connection("127.0.0.1", b.bound_port)
                for i in range(10):
                    w.write(encode_frame(b"m%d" % i))
                await w.drain()
                await asyncio.sleep(0.3)
                assert b.metrics["recv"] == 10
                assert b.metrics["dropped_recv"] >= 8
                w.close()
            finally:
                await b.stop()

        run(scenario())


class TestDeployDocs:
    def test_generate_load_roundtrip(self, tmp_path):
        dep = deploy.generate(
            str(tmp_path), n=4, clients=2, base_port=7400,
            checkpoint_interval=16, view_timeout=5.0,
        )
        loaded = deploy.load(str(tmp_path / "committee.json"))
        assert loaded.cfg.replica_ids == dep.cfg.replica_ids == (
            "r0", "r1", "r2", "r3",
        )
        assert loaded.cfg.checkpoint_interval == 16
        assert loaded.cfg.view_timeout == 5.0
        assert loaded.addresses == dep.addresses
        assert loaded.cfg.pubkeys == dep.cfg.pubkeys
        assert loaded.peers_for("r0") == {
            k: v for k, v in loaded.addresses.items() if k != "r0"
        }
        for node in ["r0", "r1", "r2", "r3", "c0", "c1"]:
            seed = deploy.read_seed(str(tmp_path), node)
            assert len(seed) == 32

    def test_node_tpu_verifier_sized_and_warmed_from_deploy(self, tmp_path):
        """node.py's tpu backend must size the key bank to the deploy
        doc's key population and pre-register those keys (the jit table
        shape must never move under live traffic — round-4
        consensus-on-chip fix)."""
        from unittest import mock

        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier
        from simple_pbft_tpu.node import make_verifier

        deploy.generate(str(tmp_path), n=4, clients=2, base_port=7410)
        dep = deploy.load(str(tmp_path / "committee.json"))
        # warm only the smallest bucket here: the full (8..512) boot
        # warm compiles 4 kernels (~minutes cold), covered by the chip
        # path; this test pins the sizing/registration contract
        real_warm = TpuVerifier.warm
        with mock.patch.object(
            TpuVerifier,
            "warm",
            lambda self, pubkeys=(), buckets=(8,): real_warm(
                self, pubkeys, (8,)
            ),
        ):
            svc = make_verifier("tpu", dep)
        # node.py wraps the device verifier in the coalescing service;
        # the sizing/registration contract lives on the device verifier
        v = svc.device
        n_keys = len(dep.cfg.pubkeys)
        assert len(v._bank._index) == n_keys  # all published keys cached
        cap = v._bank._cap
        assert cap >= n_keys + 32  # headroom for walk-in client keys
        # live traffic — including a WALK-IN key the deploy doc never
        # published — must not grow the table (growth = a fresh kernel
        # compile under the device lock mid-consensus)
        from simple_pbft_tpu.crypto import ed25519_cpu as ref
        from simple_pbft_tpu.crypto.verifier import BatchItem

        seed = b"\x77" * 32
        walkin = BatchItem(
            ref.public_key(seed), b"walk-in", ref.sign(seed, b"walk-in")
        )
        assert v.verify_batch([walkin]) == [True]
        assert len(v._bank._index) == n_keys + 1  # registered in place
        assert v._bank._cap == cap  # capacity (jit shape) unmoved

    def test_seed_files_hold_no_shared_secrets(self, tmp_path):
        deploy.generate(str(tmp_path), n=4, clients=1)
        doc = json.load(open(tmp_path / "committee.json"))
        blob = json.dumps(doc)
        for node in ["r0", "r1", "r2", "r3", "c0"]:
            seed = deploy.read_seed(str(tmp_path), node)
            assert seed.hex() not in blob  # document carries only pubkeys

    @pytest.mark.parametrize(
        "doc",
        [
            [],  # not an object
            {},  # no replicas
            {"replicas": {}},  # empty replicas
            {"replicas": {"r0": "nope"}},  # entry not an object
            {"replicas": {"r0": {"host": "x", "port": "NaN", "pubkey": ""}}},
            {"replicas": {"r0": {"host": "x", "port": 1, "pubkey": "zz"}}},
            {"replicas": {"r0": {"host": "x", "port": 1}}},  # missing pubkey
        ],
    )
    def test_malformed_documents_raise(self, tmp_path, doc):
        path = tmp_path / "committee.json"
        path.write_text(json.dumps(doc))
        with pytest.raises(ValueError):
            deploy.load(str(path))

    def test_short_seed_rejected(self, tmp_path):
        (tmp_path / "r0.seed").write_bytes(b"short")
        with pytest.raises(ValueError):
            deploy.read_seed(str(tmp_path), "r0")


class TestLaunchIntegration:
    def test_four_node_launch_commits_load(self, tmp_path):
        """The run.bat analog, for real: 4 replica processes + 1 client
        process over localhost TCP, 8 requests, f+1 reply matching."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # children must never touch the chip
        base_port = 7900 + (os.getpid() % 500)  # dodge stale-orphan ports
        out = subprocess.run(
            [
                sys.executable, "-m", "simple_pbft_tpu.launch",
                "-n", "4", "--load", "8",
                "--base-port", str(base_port),
                "--deploy-dir", str(tmp_path),
                "--keep",
            ],
            capture_output=True,
            text=True,
            timeout=240,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, (out.stdout[-800:], out.stderr[-800:])
        assert '"ops": 8' in out.stdout, out.stdout[-800:]
