"""Overload resilience (ISSUE 1 tentpole): bounded admission, the
device-stall watchdog with CPU failover + quarantine, per-pile latency
isolation, replica priority shedding, and client backoff/recovery.

The r5 evidence these pin: qc256 committed ZERO requests with
svc_rtt_ms_ema ~15,000 ms (unbounded pile growth) and one 25-minute
wedge (a silent device call nothing ever timed out). Every test here is
the counterfactual: the pile stays bounded, the wedge becomes a CPU
failover, and shed work RECOVERS through client retries instead of
becoming an unexplained timeout.
"""

import asyncio
import threading
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.crypto.coalesce import Overloaded, VerifyService
from simple_pbft_tpu.crypto.verifier import BatchItem, best_cpu_verifier


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class FakeDevice:
    """Device double (sig == msg predicate) with a completion gate."""

    def __init__(self, gate: bool = False):
        self.batches = []
        self.device_calls = 0
        self.device_items = 0
        self.device_seconds = 0.0
        self._gate = threading.Event()
        if not gate:
            self._gate.set()

    def release(self):
        self._gate.set()

    def dispatch_batch(self, items):
        items = list(items)
        self.batches.append(len(items))
        self.device_calls += 1
        self.device_items += len(items)

        def finish():
            self._gate.wait(60)
            return [it.sig == it.msg for it in items]

        return finish


class FakeCpu:
    def __init__(self, delay_per_item: float = 0.0):
        self.batches = []
        self.delay_per_item = delay_per_item

    def verify_batch(self, items):
        self.batches.append(len(items))
        if self.delay_per_item:
            time.sleep(self.delay_per_item * len(items))
        return [it.sig == it.msg for it in items]


def _items(n, tag=b"x", good=True):
    return [
        BatchItem(
            b"pk",
            tag + bytes([i % 251]),
            tag + bytes([i % 251]) if good else b"bad",
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# bounded admission
# ---------------------------------------------------------------------------


def test_submit_past_cap_rejected_with_overloaded():
    """Submit rate > drain rate must bound queue depth and reject with
    Overloaded — never grow the pile (acceptance criterion 4)."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(dev, cpu=FakeCpu(), cpu_cutoff=0, max_pending=500)
    # two piles > MIN_SECOND_DISPATCH occupy both device slots (submitted
    # sequentially — back-to-back submits would coalesce into ONE pass)
    inflight = [svc.submit(_items(300, tag=b"a"))]
    for _ in range(200):
        if len(dev.batches) == 1:
            break
        time.sleep(0.005)
    inflight.append(svc.submit(_items(300, tag=b"b")))
    for _ in range(200):
        if len(dev.batches) == 2:
            break
        time.sleep(0.005)
    assert len(dev.batches) == 2
    queued = svc.submit(_items(400, tag=b"c"))  # fits the 500 cap
    rejected = svc.submit(_items(200, tag=b"d"))  # 600 > 500: rejected
    with pytest.raises(Overloaded):
        rejected.result(5)
    assert svc.overload_rejections == 1
    assert svc.overload_rejected_items == 200
    assert svc.max_pending_seen <= 500
    # drain: everything admitted still resolves, and NEW work is accepted
    dev.release()
    for f in inflight:
        assert f.result(10) == [True] * 300
    assert queued.result(10) == [True] * 400
    assert svc.submit(_items(50, tag=b"e")).result(10) == [True] * 50
    svc.close()


def test_single_oversized_submission_still_admitted_when_idle():
    """One batch larger than max_pending with an EMPTY queue must be
    admitted (and chunked downstream), or it could never run at all."""
    svc = VerifyService(
        FakeDevice(), cpu=FakeCpu(), cpu_cutoff=0, max_pending=100
    )
    assert svc.submit(_items(250)).result(10) == [True] * 250
    assert svc.overload_rejections == 0
    svc.close()


# ---------------------------------------------------------------------------
# dispatch-deadline watchdog + quarantine
# ---------------------------------------------------------------------------


def test_watchdog_fails_stalled_sweep_over_to_cpu():
    """A device call past the deadline resolves via the CPU verifier
    within ~the deadline — the committee's quorum sweep is never held
    hostage by a silent device (acceptance criterion 3)."""
    dev = FakeDevice(gate=True)  # never released: permanent stall
    cpu = FakeCpu()
    svc = VerifyService(
        dev, cpu=cpu, cpu_cutoff=0, dispatch_deadline=0.2,
        quarantine_base=0.5,
    )
    t0 = time.perf_counter()
    out = svc.submit(_items(300)).result(10)
    took = time.perf_counter() - t0
    assert out == [True] * 300
    assert took < 5.0  # deadline + CPU pass, not the 60 s gate wait
    assert svc.watchdog_failovers == 1
    assert svc.cpu_reroute_passes >= 1
    assert svc.quarantined and svc.degraded
    # quarantined: big piles route to the CPU, the device is left alone
    assert svc.submit(_items(300, tag=b"q")).result(10) == [True] * 300
    assert len(dev.batches) == 1
    svc.close()


def test_late_device_completion_lifts_quarantine():
    """The abandoned finisher eventually landing is evidence of device
    health: quarantine lifts early instead of waiting out the backoff."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(
        dev, cpu=FakeCpu(), cpu_cutoff=0, dispatch_deadline=0.2,
        quarantine_base=30.0,  # would bench the device for 30 s
    )
    assert svc.submit(_items(300)).result(10) == [True] * 300
    assert svc.quarantined
    dev.release()  # the stalled call lands late
    for _ in range(200):
        if svc.late_device_completions and not svc.quarantined:
            break
        time.sleep(0.01)
    assert svc.late_device_completions == 1
    assert not svc.quarantined
    svc.close()


def test_reprobe_backoff_doubles_on_repeat_failure():
    """Re-probing a still-dead device must back off exponentially, not
    hammer it at the base interval."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(
        dev, cpu=FakeCpu(), cpu_cutoff=0, dispatch_deadline=0.1,
        quarantine_base=0.2, quarantine_cap=5.0,
    )
    assert svc.submit(_items(300)).result(10) == [True] * 300
    assert svc._quarantine_backoff == pytest.approx(0.4)
    time.sleep(0.3)  # first quarantine window expires
    assert not svc.quarantined
    # next big pile is the re-probe; the device is still dead
    assert svc.submit(_items(300, tag=b"p")).result(10) == [True] * 300
    assert svc.watchdog_failovers == 2
    assert svc.quarantine_probes >= 1
    assert svc._quarantine_backoff == pytest.approx(0.8)
    svc.close()


def test_small_sweeps_not_serialized_behind_big_cpu_reroute():
    """Per-pile latency isolation: a multi-thousand-item CPU reroute runs
    on its own thread, so a 10-item quorum sweep submitted right behind
    it clears in milliseconds, not after the big pile."""
    dev = FakeDevice(gate=True)
    cpu = FakeCpu(delay_per_item=0.001)  # 2000 items => ~2 s
    svc = VerifyService(
        dev, cpu=cpu, cpu_cutoff=64, dispatch_deadline=0.1,
        quarantine_base=10.0,
    )
    # trip the watchdog to quarantine the device
    assert svc.submit(_items(100)).result(10) == [True] * 100
    assert svc.quarantined
    big = svc.submit(_items(2000, tag=b"B"))
    time.sleep(0.05)  # let the dispatcher take the big pile first
    t0 = time.perf_counter()
    small = svc.submit(_items(10, tag=b"s"))
    assert small.result(10) == [True] * 10
    small_latency = time.perf_counter() - t0
    assert not big.done()  # the big reroute is still grinding
    assert small_latency < 1.0
    assert big.result(15) == [True] * 2000
    svc.close()


def test_quarantine_lifecycle_observable_in_snapshot():
    """ISSUE 2 satellite: enter-quarantine -> probe -> recover is
    observable as counter/state transitions through the unified
    snapshot, not just internal fields."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(
        dev, cpu=FakeCpu(), cpu_cutoff=0, dispatch_deadline=0.1,
        quarantine_base=0.2, quarantine_cap=5.0,
    )
    s0 = svc.snapshot()
    assert s0["quarantine_entries"] == 0
    assert s0["quarantine_recoveries"] == 0
    assert not s0["quarantined"] and not s0["degraded"]
    assert s0["pending_items"] == 0

    # ENTER: a stalled device pass trips the watchdog
    assert svc.submit(_items(300)).result(10) == [True] * 300
    s1 = svc.snapshot()
    assert s1["quarantine_entries"] == 1
    assert s1["watchdog_failovers"] == 1
    assert s1["quarantined"] and s1["degraded"]
    assert s1["quarantine_recoveries"] == 0

    dev.release()  # device healthy again
    time.sleep(0.45)  # quarantine window (0.2 s, late-lift aside) expires
    # PROBE: the next big pile touches the device again...
    assert svc.submit(_items(300, tag=b"p")).result(10) == [True] * 300
    # ...and RECOVER: the in-deadline completion resets the ladder
    for _ in range(200):
        s2 = svc.snapshot()
        if s2["quarantine_recoveries"]:
            break
        time.sleep(0.01)
    assert s2["quarantine_probes"] >= 1
    assert s2["quarantine_recoveries"] == 1
    assert not s2["quarantined"]
    assert s2["quarantine_entries"] == 1  # no new entry on the way out
    svc.close()


# ---------------------------------------------------------------------------
# replica priority shedding
# ---------------------------------------------------------------------------


def test_priority_shedding_keeps_quorum_traffic_first():
    """Past the shed watermark: every quorum-critical message survives,
    deferrable ones fill the remaining budget in arrival order, the rest
    drop, and degraded_mode flags (then clears on a calm sweep)."""

    async def scenario():
        from simple_pbft_tpu.crypto.signer import Signer
        from simple_pbft_tpu.messages import Prepare, Request

        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        r0 = com.replica("r0")
        r0.shed_watermark = 4
        reqs = []
        signer = Signer("c0", com.keys["c0"].seed)
        for i in range(5):
            rq = Request(client_id="c0", timestamp=1000 + i, operation="noop")
            signer.sign_msg(rq)
            reqs.append(rq)
        preps = []
        s1 = Signer("r1", com.keys["r1"].seed)
        for i in range(3):
            pp = Prepare(view=0, seq=i + 1, digest="a" * 64)
            s1.sign_msg(pp)
            preps.append(pp)
        # arrival order: req, req, prep, req, prep, req, prep, req
        order = [reqs[0], reqs[1], preps[0], reqs[2], preps[1], reqs[3],
                 preps[2], reqs[4]]
        decoded, _spans, _task = r0._start_sweep([m.to_wire() for m in order])
        # all 3 prepares kept + budget (4-3=1) -> first request only
        kinds = [type(m).__name__ for m in decoded]
        assert kinds == ["Request", "Prepare", "Prepare", "Prepare"]
        assert decoded[0].timestamp == 1000  # arrival order preserved
        assert r0.metrics["messages_shed"] == 4
        assert r0.metrics["degraded_mode"] == 1
        # a calm sweep (<= watermark/2) clears the degraded flag
        r0._start_sweep([reqs[0].to_wire()])
        assert r0.metrics["degraded_mode"] == 0

    run(scenario())


def test_no_shedding_below_watermark():
    async def scenario():
        from simple_pbft_tpu.crypto.signer import Signer
        from simple_pbft_tpu.messages import Request

        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        r0 = com.replica("r0")
        signer = Signer("c0", com.keys["c0"].seed)
        wires = []
        for i in range(10):
            rq = Request(client_id="c0", timestamp=2000 + i, operation="noop")
            signer.sign_msg(rq)
            wires.append(rq.to_wire())
        decoded, _s, _t = r0._start_sweep(wires)
        assert len(decoded) == 10
        assert r0.metrics["messages_shed"] == 0
        assert r0.metrics.get("degraded_mode", 0) == 0

    run(scenario())


# ---------------------------------------------------------------------------
# client backoff + idempotent retry
# ---------------------------------------------------------------------------


def test_backoff_schedule_grows_capped_and_deterministic():
    from simple_pbft_tpu.client import Client
    from simple_pbft_tpu.config import make_test_committee
    from simple_pbft_tpu.transport.local import LocalNetwork

    cfg, keys = make_test_committee(n=4, clients=1)
    net = LocalNetwork()

    def mk():
        return Client(
            client_id="c0", cfg=cfg, seed=keys["c0"].seed,
            transport=net.endpoint("c0"), request_timeout=1.0,
            backoff_factor=2.0, jitter=0.1,
        )

    c1, c2 = mk(), mk()
    sched1 = [c1._attempt_timeout(k) for k in range(8)]
    sched2 = [c2._attempt_timeout(k) for k in range(8)]
    assert sched1 == sched2  # same seed -> same jitter stream
    # grows ~2x within jitter until the 8x cap
    assert sched1[0] == pytest.approx(1.0, rel=0.11)
    assert sched1[2] == pytest.approx(4.0, rel=0.11)
    assert all(t <= 8.0 * 1.1 + 1e-9 for t in sched1)
    assert sched1[6] == pytest.approx(8.0, rel=0.11)  # capped
    # factor 1.0 restores the fixed-interval legacy behavior (no growth)
    c3 = mk()
    c3.backoff_factor, c3.jitter = 1.0, 0.0
    assert [c3._attempt_timeout(k) for k in range(4)] == [1.0] * 4


def test_client_retry_recovers_after_partition_exactly_once():
    """A request lost to a partition recovers via backoff retransmission
    and executes EXACTLY once (idempotent dedup server-side)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        for rid in com.cfg.replica_ids:
            com.net.faults.cut("c0", rid)
        com.start()
        client = com.clients[0]
        client.request_timeout = 0.3

        async def heal():
            await asyncio.sleep(0.8)
            com.net.faults.heal()

        heal_task = asyncio.create_task(heal())
        try:
            assert await client.submit("put k recovered", retries=10) == "ok"
            assert client.metrics["retransmissions"] >= 1
            assert client.metrics["recovered_after_retry"] == 1
            # submit may resolve on the 2f+1 SPECULATIVE quorum (ISSUE
            # 15) before the commit certificates land: settle, then pin
            # exactly-once execution
            for _ in range(100):
                if all(
                    r.metrics.get("committed_requests") for r in com.replicas
                ):
                    break
                await asyncio.sleep(0.05)
            for r in com.replicas:
                assert r.metrics["committed_requests"] == 1
        finally:
            await heal_task
            await com.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# end to end: overload shed -> client retry recovery;
#             seeded stalled device -> watchdog -> commits continue
# ---------------------------------------------------------------------------


class GatedCpuDevice:
    """Real-verdict device double: verifies with the CPU backend inside
    its finisher (so committee signatures get genuine outcomes), with a
    gate to hold passes in flight."""

    def __init__(self, gate: bool = False):
        self._cpu = best_cpu_verifier()
        self.batches = []
        self.device_calls = 0
        self.device_items = 0
        self.device_seconds = 0.0
        self._gate = threading.Event()
        if not gate:
            self._gate.set()

    def release(self):
        self._gate.set()

    def dispatch_batch(self, items):
        items = list(items)
        self.batches.append(len(items))
        self.device_calls += 1
        self.device_items += len(items)

        def finish():
            self._gate.wait(60)
            return self._cpu.verify_batch(items)

        return finish


def test_overloaded_sweeps_shed_and_client_retries_recover():
    """Acceptance criterion 4, end to end: with the verify pile pinned at
    its admission cap, replica sweeps are rejected (shed, counted) — and
    once the pile drains, the client's retries recover the request
    instead of it becoming a timeout."""

    async def scenario():
        dev = GatedCpuDevice(gate=True)
        svc = VerifyService(
            dev, cpu=best_cpu_verifier(), cpu_cutoff=0, max_pending=40
        )
        # occupy both device slots, then pin the queue at the cap
        svc.submit(_items(300, tag=b"a"))
        svc.submit(_items(300, tag=b"b"))
        for _ in range(200):
            if len(dev.batches) == 2:
                break
            await asyncio.sleep(0.005)
        filler = svc.submit(_items(40, tag=b"c"))
        com = LocalCommittee.build(n=4, clients=1, verifier_factory=lambda: svc)
        com.start()
        client = com.clients[0]
        client.request_timeout = 0.3
        task = asyncio.create_task(client.submit("put k v", retries=30))
        try:
            # every sweep is admission-rejected while the pile is pinned
            for _ in range(300):
                if sum(
                    r.metrics.get("sweeps_shed_overload", 0)
                    for r in com.replicas
                ) >= 1:
                    break
                await asyncio.sleep(0.01)
            shed = sum(
                r.metrics.get("sweeps_shed_overload", 0) for r in com.replicas
            )
            assert shed >= 1
            assert any(
                r.metrics.get("degraded_mode", 0) for r in com.replicas
            )
            assert svc.overload_rejections >= 1
            dev.release()  # drain: the committee recovers
            assert await asyncio.wait_for(task, 30) == "ok"
            # the pinned filler drained too (fake items: all invalid —
            # what matters is the future RESOLVED, not wedged)
            assert filler.result(10) == [False] * 40
        finally:
            if not task.done():
                task.cancel()
            await com.stop()
            svc.close()

    run(scenario(), timeout=120)


def test_seeded_stalled_device_schedule_does_not_wedge():
    """Acceptance criterion 3: under a SEEDED stall_device schedule the
    watchdog fails verification over to the CPU within the deadline and
    the committee keeps committing — nonzero commits despite the device
    being silent for most of the window."""

    async def scenario():
        from simple_pbft_tpu.faults import (
            FaultInjector,
            FaultSchedule,
            StallableDevice,
        )

        dev = StallableDevice(GatedCpuDevice())
        svc = VerifyService(
            dev, cpu=best_cpu_verifier(), cpu_cutoff=0,
            dispatch_deadline=0.3, quarantine_base=0.5,
        )
        schedule = FaultSchedule.generate(
            seed=99, horizon=3.0, device_stalls=1, stall_s=10.0
        )
        assert schedule.events[0].kind == "stall_device"
        com = LocalCommittee.build(
            n=4, clients=1, verifier_factory=lambda: svc
        )
        com.start()
        client = com.clients[0]
        client.request_timeout = 1.0
        injector = FaultInjector(
            committee=com, schedule=schedule, service=svc
        )
        inj_task = asyncio.create_task(injector.run(time.perf_counter() + 8.0))
        commits = 0
        try:
            t_end = time.perf_counter() + 5.0
            i = 0
            while time.perf_counter() < t_end:
                assert await client.submit(f"put k{i} {i}", retries=20) == "ok"
                commits += 1
                i += 1
            assert commits > 0  # the committee kept committing
            # the stall actually happened and the watchdog caught it
            # (stall lasts 10 s, the load window is 5 s: commits after
            # the event fired can only have gone through the failover)
            assert dev.stalls_injected == 1
            assert svc.watchdog_failovers >= 1
            assert svc.cpu_reroute_passes >= 1
        finally:
            injector.stop()
            dev.release()
            await asyncio.gather(inj_task, return_exceptions=True)
            await com.stop()
            svc.close()

    run(scenario(), timeout=120)
