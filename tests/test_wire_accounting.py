"""Wire accounting (ISSUE 12 tentpole): kind classification without a
parse, per-link per-kind conservation under shaped loss and asymmetric
partitions, schema alignment across transports, and the derived
per-commit costs every bench record now carries."""

from __future__ import annotations

import asyncio
import json

import pytest

from simple_pbft_tpu import messages
from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.faults import LinkShape, ShapedTransport, find_shaped
from simple_pbft_tpu.telemetry import (
    WIRE_PHASE_OF_KIND,
    transport_snapshot,
    wire_aggregate,
    wire_delta,
    wire_per_commit,
)
from simple_pbft_tpu.transport.base import (
    COUNTER_SCHEMA,
    UNKNOWN_KIND,
    WireAccounting,
    base_metrics,
    wire_kind,
    wire_of,
)


def _run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class TestWireKind:
    def test_every_registered_kind_classifies_from_default_instance(self):
        for kind, cls in messages._REGISTRY.items():
            assert wire_kind(cls().to_wire()) == kind

    def test_embedded_request_kind_does_not_fool_the_classifier(self):
        # a pre-prepare's block field sorts BEFORE its top-level kind in
        # canonical JSON, and the block embeds full requests — the exact
        # shape a first-substring scan would misclassify as "request"
        req = messages.Request(
            client_id="c0", timestamp=7, operation="put a b",
            sender="c0", sig="ab" * 32,
        )
        pp = messages.PrePrepare(
            view=0, seq=3, digest="d" * 64, sender="r0", sig="ab" * 32,
            block={"reqs": [req.to_dict()], "kind_decoy": '"kind":"qc"'},
        )
        assert wire_kind(pp.to_wire()) == "preprepare"

    def test_escaped_quotes_and_braces_in_payload(self):
        req = messages.Request(
            client_id="c0", timestamp=1, sender="c0", sig="cd" * 32,
            operation='put k {"quoted\\" }{[ brace bomb, \\"kind\\":\\"qc\\"',
        )
        assert wire_kind(req.to_wire()) == "request"

    def test_malformed_frames_return_unknown_and_never_raise(self):
        cases = [
            b"", b"[1,2]", b"garbage", b'{"a":}', b'{"zeta":1}',
            b'{"kind":12}', b'{"block"', b'{"a":"unterminated',
            b'{"kind":"x"',  # classifiable prefix, torn tail is fine
        ]
        for raw in cases[:-1]:
            assert wire_kind(raw) == UNKNOWN_KIND
        # truncation fuzz over a real message: any cut must classify or
        # return unknown, never raise
        raw = messages.Prepare(
            view=1, seq=2, digest="e" * 64, sender="r1", sig="ef" * 32
        ).to_wire()
        for cut in range(0, len(raw), 7):
            out = wire_kind(raw[:cut])
            assert isinstance(out, str)

    def test_phase_table_covers_every_registered_kind(self):
        # a new message kind must get a phase assignment (or this drifts
        # silently into "other" and per-phase rollups undercount)
        assert set(WIRE_PHASE_OF_KIND) == set(messages.ALL_KINDS)


class TestSchemaAlignment:
    def test_local_endpoint_metrics_carry_the_full_shared_schema(self):
        async def go():
            from simple_pbft_tpu.transport.local import LocalNetwork

            net = LocalNetwork()
            ep = net.endpoint("r0")
            assert set(ep.metrics) == set(COUNTER_SCHEMA)
            assert all(v == 0 for v in ep.metrics.values())
            assert isinstance(ep.wire, WireAccounting)
            # a re-handle for the same id shares the accounting ledger
            assert net.endpoint("r0").wire is ep.wire

        _run(go())

    def test_base_metrics_is_fresh_per_call(self):
        a, b = base_metrics(), base_metrics()
        a["sent"] = 9
        assert b["sent"] == 0

    def test_tcp_and_grpc_metrics_share_the_schema(self):
        from simple_pbft_tpu.transport.grpc import GrpcTransport
        from simple_pbft_tpu.transport.tcp import TcpTransport

        t = TcpTransport("r0", ("127.0.0.1", 0), peers={})
        g = GrpcTransport("r0", ("127.0.0.1", 0), peers={})
        assert set(t.metrics) == set(COUNTER_SCHEMA)
        assert set(g.metrics) == set(COUNTER_SCHEMA)
        assert isinstance(t.wire, WireAccounting)
        assert isinstance(g.wire, WireAccounting)


def _sum_sent(wires):
    out = {}
    for w in wires:
        for kinds in w.sent.values():
            for k, (m, b) in kinds.items():
                cell = out.setdefault(k, [0, 0])
                cell[0] += m
                cell[1] += b
    return out


def _sum_recv(wires):
    out = {}
    for w in wires:
        for k, (m, b) in w.recv.items():
            cell = out.setdefault(k, [0, 0])
            cell[0] += m
            cell[1] += b
    return out


def _sum_lost(wires, bucket):
    out = {}
    for w in wires:
        for k, (m, b) in w.lost.get(bucket, {}).items():
            cell = out.setdefault(k, [0, 0])
            cell[0] += m
            cell[1] += b
    return out


class TestConservation:
    def test_bytes_conserve_under_shaped_loss_and_asymmetric_partition(self):
        """The acceptance invariant: per-kind bytes summed over senders'
        links equal receivers' observed totals; shaped/partition losses
        land in named buckets, never vanish."""

        async def go():
            com = LocalCommittee.build(n=4, clients=1, view_timeout=60.0)
            ids = list(com.cfg.replica_ids)
            for r in com.replicas:
                # lossy links replica->replica; client links unshaped
                r.transport = ShapedTransport(
                    r.transport,
                    shapes={d: LinkShape(loss=0.05) for d in ids if d != r.id},
                    seed=7,
                )
            com.clients[0].request_timeout = 5.0
            com.start()
            try:
                for i in range(4):
                    assert await com.clients[0].submit(
                        f"put a{i} {i}", retries=8) == "ok"
                # asymmetric partition: r0 stops reaching r3 (r3 still
                # talks to r0) — quorum 3/4 keeps committing
                find_shaped(com.replica("r0").transport).partition(["r3"])
                for i in range(4):
                    assert await com.clients[0].submit(
                        f"put b{i} {i}", retries=8) == "ok"
                find_shaped(com.replica("r0").transport).heal()
                for i in range(2):
                    assert await com.clients[0].submit(
                        f"put c{i} {i}", retries=8) == "ok"
            finally:
                await com.stop()

            wires = [wire_of(r.transport) for r in com.replicas] + [
                wire_of(c.transport) for c in com.clients
            ]
            assert all(w is not None for w in wires)
            sent, recv = _sum_sent(wires), _sum_recv(wires)
            assert sent == recv, (sent, recv)
            assert sent, "nothing was accounted"
            assert UNKNOWN_KIND not in sent
            shaped = _sum_lost(wires, "shaped_lost")
            cut = _sum_lost(wires, "partition_dropped")
            assert sum(b for _, b in shaped.values()) > 0, \
                "5% loss over a whole run lost nothing?"
            assert sum(b for _, b in cut.values()) > 0, \
                "an open partition dropped nothing?"
            # the shaped wrapper reports through the SAME ledger the
            # telemetry plane reads: counters reconcile exactly
            w0 = wire_of(com.replica("r0").transport)
            snap = w0.snapshot()
            assert snap["lost"].get("partition_dropped", [0, 0])[0] == sum(
                m for m, _ in w0.lost.get("partition_dropped", {}).values()
            )

        _run(go())

    def test_local_faultplan_drops_land_in_net_dropped(self):
        async def go():
            from simple_pbft_tpu.transport.local import (
                FaultPlan,
                LocalNetwork,
            )

            net = LocalNetwork(FaultPlan(drop_rate=1.0, seed=1))
            a, b = net.endpoint("a"), net.endpoint("b")
            raw = messages.Prepare(
                view=0, seq=1, digest="d" * 64, sender="a", sig="ab" * 32
            ).to_wire()
            await a.send("b", raw)
            assert a.wire.sent == {}
            assert a.wire.lost["net_dropped"]["prepare"] == [1, len(raw)]
            assert b.wire.recv == {}
            # unknown destination: accounted, not silent
            await a.send("nobody", raw)
            assert a.wire.lost["no_route"]["prepare"][0] == 1

        _run(go())

    def test_tcp_self_send_and_overflow_buckets(self):
        async def go():
            from simple_pbft_tpu.transport.tcp import TcpTransport

            t = TcpTransport("r0", ("127.0.0.1", 0), peers={})
            raw = messages.Commit(
                view=0, seq=1, digest="d" * 64, sender="r0", sig="ab" * 32
            ).to_wire()
            await t.send("r0", raw)
            assert t.wire.sent["r0"]["commit"] == [1, len(raw)]
            assert t.wire.recv["commit"] == [1, len(raw)]
            await t.send("ghost", raw)
            assert t.wire.lost["no_route"]["commit"][0] == 1

        _run(go())


class TestDerived:
    def test_per_commit_costs_and_phase_amplification(self):
        per_kind = {
            "prepare": {"sent_msgs": 24, "sent_bytes": 4800,
                        "recv_msgs": 24, "recv_bytes": 4800,
                        "lost_msgs": 0, "lost_bytes": 0},
            "commit": {"sent_msgs": 24, "sent_bytes": 4800,
                       "recv_msgs": 24, "recv_bytes": 4800,
                       "lost_msgs": 2, "lost_bytes": 400},
            "preprepare": {"sent_msgs": 6, "sent_bytes": 6000,
                           "recv_msgs": 6, "recv_bytes": 6000,
                           "lost_msgs": 0, "lost_bytes": 0},
        }
        pc = wire_per_commit(per_kind, slots=2, requests=8)
        assert pc["per_kind"]["prepare"] == {
            "phase": "prepare", "msgs_per_slot": 12.0,
            "bytes_per_slot": 2400.0, "msgs_per_req": 3.0,
            "bytes_per_req": 600.0,
        }
        # a phase's msgs_per_slot IS its broadcast amplification: the
        # all-to-all vote phase reads n(n-1) here
        assert pc["per_phase"]["prepare"]["msgs_per_slot"] == 12.0
        assert pc["per_phase"]["commit"]["lost_bytes"] == 400
        assert pc["per_phase"]["preprepare"]["bytes_per_slot"] == 3000.0
        assert pc["total_msgs_per_slot"] == 27.0
        assert pc["total_msgs_per_req"] == pytest.approx(54 / 8)

    def test_aggregate_and_delta(self):
        a = {"prepare": {"sent_msgs": 2, "sent_bytes": 100}}
        b = {"prepare": {"sent_msgs": 5, "sent_bytes": 300},
             "commit": {"sent_msgs": 1, "sent_bytes": 50}}
        agg = wire_aggregate([a, b])
        assert agg["prepare"]["sent_msgs"] == 7
        d = wire_delta(a, b)
        assert d["prepare"]["sent_msgs"] == 3
        assert d["commit"]["sent_msgs"] == 1
        # a restarted node's counter going backwards clamps, no nonsense
        assert wire_delta(b, a) == {}

    def test_snapshot_shape_and_telemetry_block(self):
        w = WireAccounting("r0")
        raw = messages.Reply(sender="r0", sig="ab" * 32).to_wire()
        w.account_send("c0", raw)
        w.account_recv(raw)
        w.account_lost("shaped_lost", raw)
        snap = w.snapshot()
        assert snap["sent_msgs"] == 1 and snap["recv_msgs"] == 1
        assert snap["links"]["c0"] == [1, len(raw)]
        assert snap["lost"]["shaped_lost"] == [1, len(raw)]
        assert snap["per_kind"]["reply"]["lost_bytes"] == len(raw)

        class FakeT:
            node_id = "r0"
            metrics = {"sent": 1}
            wire = w

        blk = transport_snapshot(FakeT())
        assert blk["wire"]["sent_bytes"] == len(raw)

    def test_accounting_never_raises_on_hostile_input(self):
        w = WireAccounting("r0")
        w.account_send("d", b"")
        w.account_recv(b"\xff\xfe")
        w.account_lost("b", None)  # type: ignore[arg-type]
        assert w.snapshot()["sent_msgs"] == 1


class TestNetioCell:
    def test_rate_and_totals_rendering(self):
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "pbft_top", os.path.join(root, "tools", "pbft_top.py")
        )
        top = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(top)
        snap = {"transport": {"wire": {
            "sent_msgs": 300, "recv_msgs": 100,
            "sent_bytes": 200 * 1024, "recv_bytes": 56 * 1024,
        }}}
        prev = {"transport": {"wire": {
            "sent_msgs": 100, "recv_msgs": 100,
            "sent_bytes": 100 * 1024, "recv_bytes": 28 * 1024,
        }}}
        live = top.netio_cell(snap, prev, dt=2.0)
        assert live == "100/s 64K/s", live
        post = top.netio_cell(snap, None, dt=0.0)
        assert post == "400 256K", post
        assert top.netio_cell({"transport": {}}, None, 0.0) == ""
        assert "NETIO" in top.COLUMNS
