"""VerifyService: the process-wide coalescing verify front.

Round-4 chip evidence showed n replicas each paying a full device round
trip per sweep, serialized (bench_results/chip_r04.jsonl: n=16 TPU at
6.4 req/s vs CPU 422). The service folds every pending sweep into one
async device pass; these tests pin the coalescing, routing, ordering,
failure, and end-to-end consensus behavior with controllable fakes (the
real TpuVerifier path is covered by the committee test at the bottom).
"""

import asyncio
import threading
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.crypto.coalesce import VerifyService
from simple_pbft_tpu.crypto.verifier import BatchItem


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class FakeDevice:
    """Device verifier double: correct verdicts via a trivial predicate
    (sig == msg), with a gate so tests control when a pass completes."""

    def __init__(self, gate: bool = False):
        self.batches = []  # item counts per dispatch, in dispatch order
        self.device_calls = 0
        self.device_items = 0
        self.device_seconds = 0.0
        self._gate = threading.Event()
        if not gate:
            self._gate.set()

    def release(self):
        self._gate.set()

    def dispatch_batch(self, items):
        items = list(items)
        self.batches.append(len(items))
        self.device_calls += 1
        self.device_items += len(items)

        def finish():
            assert self._gate.wait(30), "test gate never released"
            return [it.sig == it.msg for it in items]

        return finish


class FakeCpu:
    def __init__(self):
        self.batches = []

    def verify_batch(self, items):
        self.batches.append(len(items))
        return [it.sig == it.msg for it in items]


def _items(n, tag=b"x", good=True):
    return [
        BatchItem(b"pk", tag + bytes([i % 251]), tag + bytes([i % 251]) if good else b"bad")
        for i in range(n)
    ]


def test_small_batch_takes_cpu_path():
    dev, cpu = FakeDevice(), FakeCpu()
    svc = VerifyService(dev, cpu=cpu, cpu_cutoff=64)
    out = svc.verify_batch(_items(10))
    assert out == [True] * 10
    assert cpu.batches == [10]
    assert dev.batches == []
    svc.close()


def test_large_batch_takes_device_path():
    dev, cpu = FakeDevice(), FakeCpu()
    svc = VerifyService(dev, cpu=cpu, cpu_cutoff=64)
    out = svc.verify_batch(_items(500))
    assert out == [True] * 500
    assert dev.batches == [500]
    assert cpu.batches == []
    svc.close()


def test_concurrent_submissions_coalesce_and_map_back():
    """While pass 1 is gated in flight, every later submission piles up
    and rides ONE second pass; each submitter gets exactly its own
    verdict slice (including its invalid rows)."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(dev, cpu=FakeCpu(), cpu_cutoff=0)
    first = svc.submit(_items(100, tag=b"a"))
    # wait until the first dispatch is actually in flight
    for _ in range(200):
        if dev.batches:
            break
        time.sleep(0.005)
    assert dev.batches == [100]
    futs = [
        svc.submit(_items(40, tag=bytes([65 + k]), good=(k % 2 == 0)))
        for k in range(6)
    ]
    time.sleep(0.05)  # submissions must pile up behind the gated pass
    dev.release()
    assert first.result(10) == [True] * 100
    for k, f in enumerate(futs):
        expect = [k % 2 == 0] * 40
        assert f.result(10) == expect
    # everything after the gate landed in at most MAX_DEPTH passes
    assert len(dev.batches) <= 1 + VerifyService.MAX_DEPTH
    assert sum(dev.batches) == 100 + 6 * 40
    assert svc.max_coalesced >= 2 * 40
    svc.close()


def test_oversized_submission_split_by_max_batch():
    dev = FakeDevice()
    svc = VerifyService(dev, cpu=FakeCpu(), cpu_cutoff=0, max_batch=128)
    out = svc.verify_batch(_items(300))
    assert out == [True] * 300
    # one submission > max_batch is taken alone (dispatch_batch chunks
    # internally in the real verifier; the fake sees it whole)
    assert sum(dev.batches) == 300
    svc.close()


def test_device_failure_propagates_not_hangs():
    class BoomDevice(FakeDevice):
        def dispatch_batch(self, items):
            raise RuntimeError("device gone")

    svc = VerifyService(BoomDevice(), cpu=FakeCpu(), cpu_cutoff=0)
    with pytest.raises(RuntimeError, match="device gone"):
        svc.verify_batch(_items(10))
    svc.close()


def test_close_never_abandons_inflight_futures():
    """close() while a device pass is gated in flight: the completion
    thread must still resolve every dispatched future (the shutdown
    sentinel rides the FIFO behind all real finishers)."""
    dev = FakeDevice(gate=True)
    svc = VerifyService(dev, cpu=FakeCpu(), cpu_cutoff=0)
    fut = svc.submit(_items(80))
    for _ in range(200):
        if dev.batches:
            break
        time.sleep(0.005)
    late = svc.submit(_items(30))  # queued behind the gated pass
    svc.close()
    dev.release()
    assert fut.result(10) == [True] * 80
    assert late.result(10) == [True] * 30


def test_submit_after_close_answers_on_cpu():
    dev, cpu = FakeDevice(), FakeCpu()
    svc = VerifyService(dev, cpu=cpu, cpu_cutoff=0)
    svc.close()
    assert svc.submit(_items(5)).result(5) == [True] * 5
    assert cpu.batches == [5]


def test_committee_commits_through_coalescing_service():
    """End to end: an n=4 committee whose every replica fronts the SAME
    VerifyService (real Ed25519 on the CPU path — the routing, futures
    and async replica path are the production code under test)."""

    async def scenario():
        from simple_pbft_tpu.crypto.verifier import best_cpu_verifier

        svc = VerifyService(FakeDevice(), cpu=best_cpu_verifier())
        com = LocalCommittee.build(n=4, clients=1, verifier_factory=lambda: svc)
        com.start()
        try:
            results = await asyncio.gather(
                *(com.clients[0].submit(f"put k{i} v{i}") for i in range(12))
            )
            assert results == ["ok"] * 12
        finally:
            await com.stop()
            svc.close()
        digests = {r.app.state_digest() for r in com.replicas}
        assert len(digests) == 1
        # the replicas actually used the submit path (not _timed_verify)
        assert svc.cpu_passes + svc.device_passes > 0
        assert svc.coalesced_submissions > 0

    run(scenario())


def test_committee_commits_through_real_device_route():
    """The on-chip shape, end to end on the CPU backend: every replica
    fronts one service over a REAL TpuVerifier with the CPU path
    disabled, so every sweep rides an actual jitted device pass (tiny
    buckets keep XLA-CPU pass time sub-second). Pins the full chain the
    chip experiments run: replica -> submit -> coalesce -> dispatch ->
    finisher -> future -> quorum -> execute."""

    async def scenario():
        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier

        dev = TpuVerifier(initial_keys=16)
        svc = VerifyService(dev, cpu_cutoff=0, max_batch=32)
        com = LocalCommittee.build(
            n=4, clients=1, verifier_factory=lambda: svc, max_batch=8
        )
        dev.warm_for_population(
            [kp.pub for kp in com.keys.values()], max_sweep=32
        )
        com.start()
        try:
            res = await asyncio.gather(
                *(com.clients[0].submit(f"put k{i} v{i}") for i in range(6))
            )
            assert res == ["ok"] * 6
        finally:
            await com.stop()
            svc.close()
        assert svc.device_passes > 0 and svc.cpu_passes == 0
        assert len({r.app.state_digest() for r in com.replicas}) == 1

    run(scenario(), timeout=300)


def test_failover_through_coalescing_service():
    """The storm-on-chip shape: the primary crashes while every replica
    fronts the SAME service over a real device route. View change —
    whose certificate verifies also ride the service — must elect a new
    primary and keep committing."""

    async def scenario():
        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier

        dev = TpuVerifier(initial_keys=16)
        svc = VerifyService(dev, cpu_cutoff=0, max_batch=32)
        com = LocalCommittee.build(
            n=4,
            clients=1,
            verifier_factory=lambda: svc,
            max_batch=8,
            view_timeout=1.5,  # headroom: XLA-CPU device passes are slow
        )
        dev.warm_for_population(
            [kp.pub for kp in com.keys.values()], max_sweep=32
        )
        com.start()
        client = com.clients[0]
        client.request_timeout = 1.0
        try:
            assert await client.submit("put a 1") == "ok"
            com.replica("r0").kill()
            assert await client.submit("put b 2", retries=120) == "ok"
            survivors = [r for r in com.replicas if r.id != "r0"]
            assert all(r.view >= 1 for r in survivors)
            assert await client.submit("get a", retries=120) == "1"
        finally:
            await com.stop()
            svc.close()
        assert svc.device_passes > 0

    run(scenario(), timeout=300)


def test_bad_signature_still_rejected_through_service():
    """Byzantine semantics survive the coalescing front: a forged vote
    is dropped while the quorum still forms from valid ones."""

    async def scenario():
        from simple_pbft_tpu.crypto.verifier import best_cpu_verifier

        svc = VerifyService(FakeDevice(), cpu=best_cpu_verifier())
        com = LocalCommittee.build(n=4, clients=1, verifier_factory=lambda: svc)
        com.start()
        try:
            from simple_pbft_tpu.crypto.signer import Signer
            from simple_pbft_tpu.messages import Commit

            r0 = com.replica("r0")
            # forged commit vote: r2's key but claiming r1, on a
            # not-yet-quorate slot (votes for committed seqs drop
            # pre-verification as redundant)
            forged = Commit(view=0, seq=200, digest="f" * 64)
            Signer("r1", com.keys["r2"].seed).sign_msg(forged)
            forged.sender = "r1"
            await com.net.endpoint("r2").send("r0", forged.to_wire())
            assert await com.clients[0].submit("put k v") == "ok"
            for _ in range(100):  # poll: the verify may still be in flight
                if r0.metrics.get("bad_sig", 0) >= 1:
                    break
                await asyncio.sleep(0.1)
            assert r0.metrics.get("bad_sig", 0) >= 1
        finally:
            await com.stop()
            svc.close()

    run(scenario())


class SlowCpu:
    """CPU double whose pass time scales with batch size — makes the
    serialize-behind-a-big-pass failure observable in wall clock."""

    def __init__(self, per_item_s=0.0005):
        self.batches = []
        self.per_item_s = per_item_s

    def verify_batch(self, items):
        self.batches.append(len(items))
        time.sleep(len(items) * self.per_item_s)
        return [it.sig == it.msg for it in items]


def test_big_cpu_reroute_does_not_serialize_small_sweeps():
    """ADVICE r5 (ISSUE 3 satellite): a big pile forced onto the CPU
    (quarantine or depth-full) runs on its own thread, so a small
    quorum sweep submitted while the big pass churns answers in
    milliseconds instead of waiting out the whole pass."""
    dev = FakeDevice()
    svc = VerifyService(dev, cpu=SlowCpu(), cpu_cutoff=64)
    svc._quarantined_until = time.monotonic() + 60  # device benched
    big = svc.submit(_items(3000, tag=b"B"))  # ~1.5 s of CPU
    for _ in range(400):  # wait until the reroute thread owns the pile
        if svc.cpu_reroute_passes:
            break
        time.sleep(0.005)
    assert svc.cpu_reroute_passes == 1
    t0 = time.perf_counter()
    small = svc.submit(_items(10, tag=b"s"))
    assert small.result(10) == [True] * 10
    small_latency = time.perf_counter() - t0
    # the small sweep cleared while the big pass was still in flight
    assert not big.done()
    assert small_latency < 0.5
    assert big.result(30) == [True] * 3000
    svc.close()


def test_cpu_reroute_resolves_submissions_progressively():
    """Chunked reroute: submissions coalesced into one rerouted take
    resolve in order as their chunk completes — the first submitter
    never waits for the last one's items."""
    from concurrent.futures import Future

    svc = VerifyService(FakeDevice(), cpu=SlowCpu(per_item_s=0.0001))
    svc.REROUTE_CHUNK = 64  # instance override: 4 chunks below
    order = []
    subs = []
    for k in range(4):
        fut = Future()
        fut.add_done_callback(lambda _f, k=k: order.append(k))
        subs.append((_items(64, tag=bytes([65 + k])), fut))
    svc._run_cpu_chunked(subs)
    assert order == [0, 1, 2, 3]
    assert svc.cpu_reroute_chunks == 4
    for _items_k, fut in subs:
        assert fut.result(0) == [True] * 64
    svc.close()
