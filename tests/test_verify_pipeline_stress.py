"""Concurrency stress for the verify pipeline's KeyBank (VERDICT r3
next-round #9, SURVEY §5 sanitizers row).

The replica runtime overlaps consecutive sweeps' signature verifies in
separate executor threads, so KeyBank.lookup/lookup_many/device_tables
race: an unlocked check-then-append once could map one pubkey onto
another's table row — every later signature from that key failing (or,
adversarially, verifying against the wrong key). These tests hammer the
locked paths from multiple threads with an adversarial fresh-key spray
through the max_keys/UNCACHED boundary and then audit the bank:

- every cached pubkey maps to a UNIQUE row, and the row's table content
  bit-exactly matches a freshly built table for that key;
- keys beyond the cap consistently report UNCACHED (CPU fallback), never
  a stolen row;
- invalid keys stay -1 and the negative cache stays bounded;
- a two-thread TpuVerifier pipeline returns the same verdict bitmap as
  the CPU oracle under the race.
"""

import threading

import numpy as np
import pytest

from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.crypto.verifier import BatchItem


def _keys(n, tag=0):
    out = []
    for i in range(n):
        seed = bytes([tag, i % 256, (i >> 8) % 256]) + b"\x5a" * 29
        out.append((seed, ref.public_key(seed)))
    return out


def test_keybank_races_never_alias_rows():
    from simple_pbft_tpu.ops import comb
    from simple_pbft_tpu.crypto.tpu_verifier import KeyBank

    bank = KeyBank(initial_capacity=4, max_keys=24, mode="fused", window=4)
    committee = _keys(16, tag=1)
    spray = _keys(40, tag=2)  # 8 more fit under the cap; the rest UNCACHED
    bad = [bytes([i]) * 32 for i in range(8)]  # mostly non-points
    # committee keys are registered at deployment time (replica startup
    # warms the bank); the adversarial spray then fights over the
    # REMAINING capacity — cached rows must never move or alias
    baseline = {pk: bank.lookup(pk) for _, pk in committee}
    assert all(0 <= i < 24 for i in baseline.values())
    errors = []
    results: dict = dict(baseline)
    res_lock = threading.Lock()

    def worker(wid):
        try:
            for i in range(250):  # 4 workers x 250 = 1k iterations
                seed_pk = committee[(wid + i) % len(committee)]
                idx = bank.lookup(seed_pk[1])
                if not (0 <= idx < 24):
                    errors.append(f"committee key got {idx}")
                with res_lock:
                    prev = results.setdefault(seed_pk[1], idx)
                    if prev != idx:
                        errors.append(f"row moved {prev} -> {idx}")
                if i % 5 == 0:
                    s = spray[(wid * 13 + i) % len(spray)]
                    j = bank.lookup(s[1])
                    if j == -1:
                        errors.append("valid spray key reported invalid")
                if i % 7 == 0:
                    b = bank.lookup(bad[(wid + i) % len(bad)])
                    # a random 32-byte string is a point ~50% of the time;
                    # it must never be both cached and invalid
                    if b == -1 and bad[(wid + i) % len(bad)] in bank._index:
                        errors.append("key both cached and invalid")
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]

    # audit: unique rows, and each cached row's content matches a fresh
    # single-threaded build of that key's table (catches silent aliasing)
    idxs = list(bank._index.values())
    assert len(idxs) == len(set(idxs)), "row collision"
    assert len(bank._index) <= 24
    for pk, idx in list(bank._index.items())[:8]:
        pt = ref.point_decompress(pk)
        fresh = comb.fused_table_np(pt, 4)
        assert np.array_equal(bank._np[idx], fresh), "aliased table row"
    # spray keys beyond the cap must be UNCACHED, consistently
    over = [pk for _, pk in spray if pk not in bank._index]
    assert over, "cap never reached — spray too small"
    for pk in over[:4]:
        assert bank.lookup(pk) == KeyBank.UNCACHED


def test_two_thread_verify_pipeline_matches_oracle():
    """Two threads interleave verify_batch on one TpuVerifier (the
    replica pipeline's exact shape) with fresh keys appearing mid-run;
    verdicts must match the CPU oracle bit-for-bit."""
    jax = pytest.importorskip("jax")
    from simple_pbft_tpu import force_cpu

    force_cpu()
    from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier

    v = TpuVerifier()
    keys = _keys(12, tag=3)
    batches = []
    for b in range(8):
        items, want = [], []
        for i in range(8):
            seed, pk = keys[(b * 5 + i) % len(keys)]
            msg = b"stress %d %d" % (b, i)
            sig = ref.sign(seed, msg)
            if (b + i) % 3 == 0:  # corrupt a third of them
                sig = sig[:-1] + bytes([sig[-1] ^ 1])
                want.append(False)
            else:
                want.append(True)
            items.append(BatchItem(pk, msg, sig))
        batches.append((items, want))

    failures = []

    def run(wid):
        for k, (items, want) in enumerate(batches):
            if k % 2 != wid:
                continue
            got = v.verify_batch(items)
            if [bool(x) for x in got] != want:
                failures.append((wid, k, got, want))

    ts = [threading.Thread(target=run, args=(w,)) for w in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not failures, failures[:2]
