"""Batched BLS aggregate verification (QC-plane fast path, ISSUE 3).

Pins the random-linear-combination multi-pairing against the single-cert
oracle: valid batches, invalid batches, mixed batches (the halving
fallback must isolate exactly the bad certs), adversarial shares inside
an aggregate, signer-set grouping, structural rejects, and native/Python
path agreement. Pure-Python pairings cost ~0.8 s each — the Python-path
cases keep batch sizes tiny.
"""

import pytest

from simple_pbft_tpu.crypto import bls

MSGS = [b"qc payload %d" % i for i in range(8)]


@pytest.fixture(scope="module")
def keys():
    return [bls.keygen(bytes([i + 1]) * 32) for i in range(4)]


def _cert(keys, msg, signers=None, forge=None):
    """(pubkeys, msg, agg_sig) over `msg` by `signers` (index list).
    `forge` replaces that signer's share with one over b"forged"."""
    signers = signers if signers is not None else range(len(keys))
    sigs = []
    pks = []
    for i in signers:
        sk, pk = keys[i]
        sigs.append(bls.sign(sk, b"forged" if i == forge else msg))
        pks.append(pk)
    return pks, msg, bls.aggregate_signatures(sigs)


class _NoNative:
    """Native library stub: every entry point reports unavailable, so
    the module exercises its pure-Python fallback."""

    @staticmethod
    def bls_verify_one(*a, **k):
        return None

    @staticmethod
    def bls_verify_aggregate(*a, **k):
        return None

    @staticmethod
    def bls_verify_batch_rlc(*a, **k):
        return None


def test_valid_batch_matches_singles(keys):
    entries = [_cert(keys, m) for m in MSGS[:6]]
    out = bls.verify_aggregates_batch(entries)
    assert out == [True] * 6
    singles = [bls.verify_aggregate(*e) for e in entries]
    assert out == singles
    assert bls.verify_aggregates_all(entries) is True


def test_mixed_batch_isolates_bad_certs(keys):
    entries = [_cert(keys, m) for m in MSGS[:6]]
    # cert 2: one adversarial share poisoned the aggregate (valid curve
    # point, valid structure — only the pairing can catch it)
    entries[2] = _cert(keys, MSGS[2], forge=1)
    # cert 4: aggregate over the wrong message entirely
    entries[4] = (entries[4][0], MSGS[4], _cert(keys, b"other")[2])
    out = bls.verify_aggregates_batch(entries)
    assert out == [True, True, False, True, False, True]
    assert out == [bls.verify_aggregate(*e) for e in entries]
    assert bls.verify_aggregates_all(entries) is False


def test_structural_rejects_do_not_poison_siblings(keys):
    good = _cert(keys, MSGS[0])
    entries = [
        good,
        (good[0], MSGS[1], b"\x00" * bls.G1_BYTES),  # infinity encoding
        (good[0], MSGS[2], b"junk"),  # wrong length
        ([], MSGS[3], good[2]),  # empty signer set
        _cert(keys, MSGS[3]),
    ]
    out = bls.verify_aggregates_batch(entries)
    assert out == [True, False, False, False, True]


def test_distinct_signer_sets_group_separately(keys):
    e_full = _cert(keys, MSGS[0])
    e_sub1 = _cert(keys, MSGS[1], signers=[0, 1, 2])
    e_sub2 = _cert(keys, MSGS[2], signers=[0, 1, 2])
    e_bad = _cert(keys, MSGS[3], signers=[0, 1, 2], forge=1)
    out = bls.verify_aggregates_batch([e_full, e_sub1, e_sub2, e_bad])
    assert out == [True, True, True, False]
    # signer-set mismatch: right aggregate, wrong claimed set
    wrong_set = (e_sub1[0], MSGS[0], e_full[2])
    assert bls.verify_aggregates_batch([wrong_set]) == [False]


def test_python_fallback_agrees_with_native(keys, monkeypatch):
    """Differential: the pure-Python RLC path must return the same
    verdicts as the native multi-pairing on valid and mixed batches
    (kept at k=2: python pairings are ~0.8 s each)."""
    from simple_pbft_tpu import native

    if not native.bls_available():
        pytest.skip("no native toolchain")
    entries = [_cert(keys, MSGS[0]), _cert(keys, MSGS[1], forge=2)]
    native_out = bls.verify_aggregates_batch(entries)
    monkeypatch.setattr(bls, "_native", lambda: _NoNative)
    python_out = bls.verify_aggregates_batch(entries)
    assert native_out == python_out == [True, False]


def test_all_or_nothing_rejects_without_bisection(keys, monkeypatch):
    """verify_aggregates_all on a poisoned batch must reject after ONE
    group check — counted via the group-check hook — preserving the
    Byzantine-certificate DoS bound of the old sequential path."""
    calls = {"n": 0}
    orig = bls._rlc_check

    def counting(pk_set, ents):
        calls["n"] += 1
        return orig(pk_set, ents)

    monkeypatch.setattr(bls, "_rlc_check", counting)
    entries = [_cert(keys, m) for m in MSGS[:4]]
    entries[1] = _cert(keys, MSGS[1], forge=0)
    assert bls.verify_aggregates_all(entries) is False
    assert calls["n"] == 1


def test_halving_cost_bounded(keys, monkeypatch):
    """One bad cert in k=8 must cost O(log k) group checks, not k."""
    calls = {"n": 0}
    orig = bls._rlc_check

    def counting(pk_set, ents):
        calls["n"] += 1
        return orig(pk_set, ents)

    monkeypatch.setattr(bls, "_rlc_check", counting)
    entries = [_cert(keys, m) for m in MSGS]
    entries[5] = _cert(keys, MSGS[5], forge=3)
    out = bls.verify_aggregates_batch(entries)
    assert out == [i != 5 for i in range(8)]
    # full batch + halving path: well under one check per cert, and the
    # single-cert bottom is verify_aggregate (not counted here)
    assert calls["n"] <= 6
