"""Logging + instrumentation: rotating per-node files (zapConfig parity)
and histogram correctness."""

import json
import logging
import os

from simple_pbft_tpu.logutil import (
    ROTATE_BACKUPS,
    Histogram,
    ReplicaStats,
    setup_node_logging,
)


def test_histogram_summary():
    h = Histogram(bounds=[1, 2, 4, 8])
    for v in [0.5, 1.5, 3, 3, 7, 100]:
        h.record(v)
    s = h.summary()
    assert s["count"] == 6
    assert s["min"] == 0.5 and s["max"] == 100
    assert 0 < s["p50"] <= 8
    assert s["p99"] >= s["p50"]
    # empty histograms emit the FULL zeroed schema (ISSUE 2 satellite):
    # snapshot consumers index p50/p99 unconditionally on idle nodes
    empty = Histogram().summary()
    assert empty == {
        "count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
        "p50": 0.0, "p90": 0.0, "p99": 0.0,
    }


def test_replica_stats_dump_is_json():
    st = ReplicaStats()
    st.sweep_size.record(3)
    st.verify_ms.record(1.5)
    st.verify_items += 10
    st.verify_seconds += 0.01
    doc = json.loads(st.dump({"committed_blocks": 2}))
    assert doc["metrics"]["committed_blocks"] == 2
    assert doc["verify_per_s"] == 1000.0
    assert doc["sweep_size"]["count"] == 1


def test_per_node_rotating_file(tmp_path):
    root = setup_node_logging("rX", str(tmp_path), level="INFO", console=False)
    logging.getLogger("pbft.test").info("hello %s", "world")
    for h in root.handlers:
        h.flush()
    path = tmp_path / "rX.log"
    assert path.exists()
    line = path.read_text().strip()
    # caller annotation + tab-separated structure (zap parity)
    assert "hello world" in line and "test_logutil.py" in line
    handler = root.handlers[0]
    assert handler.backupCount == ROTATE_BACKUPS
    # idempotent: re-setup must not duplicate handlers
    root2 = setup_node_logging("rX", str(tmp_path), console=False)
    assert len(root2.handlers) == 1
    for h in root2.handlers:
        root2.removeHandler(h)  # leave global state clean for other tests