"""WAN survival plane (ISSUE 7): the three pillars and their injectors.

(a) an n=7 TCP committee under `wan3dc` link shaping commits through an
    asymmetric partition that opens and HEALS MID-VIEW-CHANGE;
(b) a killed replica rejoins via chunked checkpoint state-transfer with
    the transferred volume bounded (asserted) by snapshot size + one
    watermark window of log suffix, and commits after rejoin;
(c) a replica is added then removed through the committed config slot,
    with the audit plane clean across both epoch boundaries and the
    verify seam's jit shapes untouched by the key registration.

Plus the new byzantine surfaces (ForgedSnapshotServer, StaleEpochVoter),
the tcp frames_dropped/requeue accounting, the client's stale-address-
book re-resolution, the faults kind-registry doc sync, and pbft_top's
NET column.
"""

import asyncio
import json
import os
import sys

import pytest

from simple_pbft_tpu.app import KVStore
from simple_pbft_tpu import clock as pbft_clock
from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.client import Client
from simple_pbft_tpu.config import KeyPair, make_test_committee
from simple_pbft_tpu.consensus.replica import Replica
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.faults import (
    KIND_REGISTRY,
    FaultEvent,
    FaultInjector,
    FaultSchedule,
    ForgedSnapshotServer,
    LinkShape,
    ShapedTransport,
    StaleEpochVoter,
    find_shaped,
    kind_table,
)
from simple_pbft_tpu.transport.tcp import TcpTransport

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import ledger_audit  # noqa: E402  (tools/ is not a package)
import pbft_top  # noqa: E402


def run(coro, timeout=180):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _joiner_keys(rid: str) -> KeyPair:
    # same derivation as make_test_committee: keys are a function of the id
    return KeyPair.generate((rid.encode() * 32)[:32])


async def _drain_stop(replicas, clients, transports=()):
    await asyncio.gather(
        *(r.stop() for r in replicas), return_exceptions=True
    )
    await asyncio.gather(
        *(c.stop() for c in clients), return_exceptions=True
    )
    for t in transports:
        try:
            await t.stop()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# pillar (a): wan3dc-shaped TCP committee, partition heals mid-view-change
# ---------------------------------------------------------------------------


class TestWanPartitionHeal:
    def test_n7_tcp_wan3dc_partition_opens_and_heals_mid_view_change(self):
        async def scenario():
            n = 7
            cfg, keys = make_test_committee(
                n=n, clients=1, view_timeout=0.8, checkpoint_interval=8
            )
            inner = {}
            for nid in list(cfg.replica_ids) + ["c0"]:
                t = TcpTransport(nid, ("127.0.0.1", 0), peers={})
                await t.start()
                inner[nid] = t
            addrs = {
                nid: ("127.0.0.1", t.bound_port) for nid, t in inner.items()
            }
            for nid, t in inner.items():
                t.peers.update(
                    {k: v for k, v in addrs.items() if k != nid}
                )
            replicas = []
            for rid in cfg.replica_ids:
                shaped = ShapedTransport.wrap_profile(
                    inner[rid], "wan3dc", list(cfg.replica_ids)
                )
                replicas.append(
                    Replica(
                        node_id=rid, cfg=cfg, seed=keys[rid].seed,
                        transport=shaped, app=KVStore(),
                    )
                )
            client = Client(
                "c0", cfg, keys["c0"].seed, inner["c0"], request_timeout=1.5
            )
            try:
                for r in replicas:
                    r.start()
                client.start()
                for i in range(8):
                    assert await client.submit(f"put a{i} {i}", retries=8) == "ok"

                # open an ASYMMETRIC partition around the live primary:
                # its outbound links die (proposals vanish), inbound stays
                # — the shape only a per-link direction cut can produce
                view0 = max(r.view for r in replicas)
                primary = cfg.primary(view0)
                prim = next(r for r in replicas if r.id == primary)
                find_shaped(prim.transport).partition(
                    [r for r in cfg.replica_ids if r != primary]
                )

                # load pump in the background keeps failover timers armed
                pump_ok = 0

                async def pump():
                    nonlocal pump_ok
                    for i in range(24):
                        try:
                            res = await client.submit(
                                f"put b{i} {i}", retries=12
                            )
                            if res == "ok":
                                pump_ok += 1
                        except Exception:
                            pass

                pump_task = asyncio.create_task(pump())

                # heal EXACTLY mid-view-change: wait for any survivor to
                # enter the view change the dead primary caused, then
                # reopen the links while the change is still in flight
                healed_mid_vc = False
                for _ in range(400):
                    if any(
                        r.vc.in_view_change
                        for r in replicas if r.id != primary
                    ):
                        find_shaped(prim.transport).heal()
                        healed_mid_vc = True
                        break
                    await asyncio.sleep(0.05)
                assert healed_mid_vc, "no view change within the window"

                await pump_task
                # the committee moved views AND kept committing through it
                assert pump_ok == 24, f"only {pump_ok}/24 committed"
                assert max(r.view for r in replicas) > view0
                # post-heal quiesce: every replica converges (the healed
                # ex-primary catches up too, via probes or state transfer)
                for _ in range(200):
                    execs = {r.executed_seq for r in replicas}
                    if len(execs) == 1:
                        break
                    await asyncio.sleep(0.05)
                shaped0 = find_shaped(replicas[0].transport)
                snap = shaped0.shaping_snapshot()
                assert snap["profile"] == "wan3dc"
                assert snap["shaped_links"] == n - 1
            finally:
                await _drain_stop(replicas, [client], inner.values())

        run(scenario())


# ---------------------------------------------------------------------------
# pillar (b): rejoin via chunked state transfer, bounded volume
# ---------------------------------------------------------------------------


class TestStatesyncRejoin:
    def test_killed_replica_rejoins_chunked_with_bounded_volume(self):
        async def scenario():
            com = LocalCommittee.build(
                n=4, clients=1, checkpoint_interval=4, view_timeout=1.0
            )
            com.start()
            c = com.clients[0]
            victim = com.replica("r3")
            try:
                for i in range(6):
                    await c.submit(f"put k{i} {i}", retries=5)
                victim.kill()
                # the committee moves on past several checkpoints; the
                # victim's unexecuted suffix is GC'd under the watermark
                for i in range(14):
                    await c.submit(f"put m{i} {i}", retries=5)
                frontier = max(r.executed_seq for r in com.replicas)

                fresh = Replica(
                    node_id="r3", cfg=com.cfg, seed=com.keys["r3"].seed,
                    transport=com.net.endpoint("r3"), app=KVStore(),
                )
                com.replicas[com.replicas.index(victim)] = fresh
                fresh.start()
                # background traffic produces the checkpoint broadcasts
                # the cold-started replica learns the gap from
                for i in range(10):
                    await c.submit(f"put s{i} {i}", retries=5)
                for _ in range(300):
                    if fresh.executed_seq >= frontier:
                        break
                    await asyncio.sleep(0.05)
                assert fresh.executed_seq >= frontier, (
                    fresh.executed_seq, frontier, dict(fresh.metrics),
                )

                # it caught up by TRANSFER, not replay
                m = fresh.metrics
                assert m["state_syncs"] >= 1
                assert m["statesync_chunks"] >= 1
                sync_seq = m["stable_checkpoint"]
                assert sync_seq > 0

                # volume bound (asserted, not eyeballed): chunk payload
                # received == the installed snapshots' bytes (no forgery
                # -> no re-fetch), and the replayed log suffix above the
                # snapshot is within one watermark window by construction
                snap_bytes = sum(
                    len(s) for s in fresh.snapshots.values()
                )
                assert 0 < m["statesync_bytes"] <= max(
                    snap_bytes,
                    m["statesync_transfers"] * snap_bytes,
                ), (m["statesync_bytes"], snap_bytes)
                assert (
                    fresh.executed_seq - sync_seq
                    <= com.cfg.watermark_window
                )

                # commits WITHIN one checkpoint interval of rejoin: the
                # first post-install execution lands at sync_seq + 1 and
                # the replica participates in the next interval's blocks
                r = await c.submit("put after-rejoin 1", retries=5)
                assert r == "ok"
                assert fresh.app.data.get("k0") == "0"  # transferred state
                assert fresh.app.data.get("s0") == "0"  # suffix state
            finally:
                await com.stop()

        run(scenario())

    def test_forged_snapshot_server_detected_and_survived(self):
        async def scenario():
            com = LocalCommittee.build(
                n=4, clients=1, checkpoint_interval=4, view_timeout=1.0
            )
            com.start()
            c = com.clients[0]
            victim = com.replica("r3")
            try:
                for i in range(6):
                    await c.submit(f"put k{i} {i}", retries=5)
                victim.kill()
                for i in range(10):
                    await c.submit(f"put m{i} {i}", retries=5)

                # EVERY serving peer forges its chunks: the joiner's only
                # defense is the certified digest
                wrapped = []
                for rid in ("r0", "r1", "r2"):
                    r = com.replica(rid)
                    w = ForgedSnapshotServer(
                        r.transport, Signer(rid, com.keys[rid].seed)
                    )
                    r.transport = w
                    wrapped.append((r, w))

                fresh = Replica(
                    node_id="r3", cfg=com.cfg, seed=com.keys["r3"].seed,
                    transport=com.net.endpoint("r3"), app=KVStore(),
                )
                com.replicas[com.replicas.index(victim)] = fresh
                fresh.start()
                for i in range(6):
                    await c.submit(f"put s{i} {i}", retries=5)
                # the forged transfer MUST be detected (digest mismatch)
                for _ in range(200):
                    if fresh.metrics["statesync_forged"] >= 1:
                        break
                    await asyncio.sleep(0.05)
                assert fresh.metrics["statesync_forged"] >= 1
                assert fresh.metrics["statesync_restarts"] >= 1
                assert sum(w.injections for _, w in wrapped) >= 1

                # heal the liars; the joiner re-fetches and installs the
                # REAL state (the restart path, not a wedge)
                for r, w in wrapped:
                    r.transport = w._inner
                for i in range(8):
                    await c.submit(f"put t{i} {i}", retries=5)
                frontier = max(
                    r.executed_seq for r in com.replicas if r is not fresh
                )
                for _ in range(300):
                    if fresh.executed_seq >= frontier:
                        break
                    await asyncio.sleep(0.05)
                assert fresh.executed_seq >= frontier, dict(fresh.metrics)
                assert fresh.app.data.get("k0") == "0"
            finally:
                await com.stop()

        run(scenario())


# ---------------------------------------------------------------------------
# pillar (c): live membership reconfiguration through the committed slot
# ---------------------------------------------------------------------------


class TestReconfiguration:
    def test_add_then_remove_epoch_cycle_with_clean_audit(self, tmp_path):
        async def scenario():
            com = LocalCommittee.build(
                n=4, clients=1, checkpoint_interval=4, view_timeout=1.0
            )
            auditors = com.attach_auditors(log_dir=str(tmp_path))
            com.start()
            c = com.clients[0]
            joiner = None
            try:
                for i in range(6):
                    await c.submit(f"put k{i} {i}", retries=5)

                # ADD r4 through the committed config slot
                kp = _joiner_keys("r4")
                res = await c.submit(
                    "__reconfig__ "
                    + json.dumps({"add": {"r4": {"pub": kp.pub.hex()}}}),
                    retries=5,
                )
                assert res.startswith("reconfig-staged:epoch=1"), res
                # activation at the next checkpoint boundary
                for i in range(8):
                    await c.submit(f"put m{i} {i}", retries=5)
                assert all(r.cfg.epoch == 1 for r in com.replicas)
                assert all(
                    "r4" in r.cfg.replica_ids for r in com.replicas
                )
                # the client re-resolved the committee from reply epochs
                for _ in range(100):
                    if c.epoch == 1:
                        break
                    await asyncio.sleep(0.05)
                assert c.epoch == 1
                assert c.metrics["config_refreshes"] >= 1
                assert "r4" in c.cfg.replica_ids

                # the joiner cold-starts with the new config and
                # bootstraps via chunked state transfer
                from simple_pbft_tpu.audit import SafetyAuditor

                new_cfg = com.replicas[0].cfg
                joiner = Replica(
                    node_id="r4", cfg=new_cfg, seed=kp.seed,
                    transport=com.net.endpoint("r4"), app=KVStore(),
                )
                joiner.auditor = SafetyAuditor(
                    "r4", new_cfg, log_dir=str(tmp_path)
                )
                auditors["r4"] = joiner.auditor
                com.replicas.append(joiner)
                joiner.start()
                for i in range(12):
                    await c.submit(f"put j{i} {i}", retries=5)
                frontier = max(
                    r.executed_seq for r in com.replicas if r is not joiner
                )
                for _ in range(300):
                    if joiner.executed_seq >= frontier:
                        break
                    await asyncio.sleep(0.05)
                assert joiner.executed_seq >= frontier
                assert joiner.metrics["state_syncs"] >= 1

                # REMOVE r4 again; it retires, the committee shrinks
                res = await c.submit(
                    "__reconfig__ " + json.dumps({"remove": ["r4"]}),
                    retries=5,
                )
                assert res.startswith("reconfig-staged:epoch=2"), res
                for i in range(10):
                    await c.submit(f"put z{i} {i}", retries=5)
                assert all(r.cfg.epoch == 2 for r in com.replicas)
                assert joiner.retired
                assert all(
                    "r4" not in r.cfg.replica_ids
                    for r in com.replicas if r is not joiner
                )

                # non-admin reconfig is DENIED deterministically
                evil_cfg = com.replicas[0].cfg
                assert "c9" not in evil_cfg.admin_ids
            finally:
                await com.stop()
                for a in auditors.values():
                    a.close()

            # the audit plane held I1-I4 across BOTH epoch boundaries:
            # zero violations, cross-node ledgers agree, clean bill
            assert all(a.violations == 0 for a in auditors.values())
            report, code = ledger_audit.run_audit(
                [str(tmp_path)], cfg=com.replicas[0].cfg
            )
            assert code == 0, report
            assert not report["accused"]

        run(scenario())

    def test_reconfig_denied_for_non_admin_and_bad_spec(self):
        async def scenario():
            com = LocalCommittee.build(
                n=4, clients=2, checkpoint_interval=4,
                admin_ids=("c0",),  # c1 is NOT an admin
            )
            com.start()
            c0, c1 = com.clients
            try:
                res = await c1.submit(
                    "__reconfig__ " + json.dumps({"remove": ["r3"]}),
                    retries=5,
                )
                assert res == "reconfig-denied:not-admin"
                # structurally bad change from a real admin: denied, not
                # staged (removing below n=4 would make f = 0)
                res = await c0.submit(
                    "__reconfig__ " + json.dumps({"remove": ["r3"]}),
                    retries=5,
                )
                assert res.startswith("reconfig-denied:"), res
                assert all(r.cfg.epoch == 0 for r in com.replicas)
            finally:
                await com.stop()

        run(scenario())

    def test_stale_epoch_voter_is_role_gated_not_believed(self, tmp_path):
        async def scenario():
            com = LocalCommittee.build(
                n=5, clients=1, checkpoint_interval=4, view_timeout=1.0
            )
            auditors = com.attach_auditors(log_dir=str(tmp_path))
            com.start()
            c = com.clients[0]
            try:
                for i in range(6):
                    await c.submit(f"put k{i} {i}", retries=5)
                res = await c.submit(
                    "__reconfig__ " + json.dumps({"remove": ["r4"]}),
                    retries=5,
                )
                assert res.startswith("reconfig-staged:"), res
                for i in range(6):
                    await c.submit(f"put m{i} {i}", retries=5)
                removed = com.replica("r4")
                assert removed.retired

                # r4 turns byzantine: refuses retirement, keeps voting
                # into the new epoch with its still-published key
                w = StaleEpochVoter(
                    removed.transport, Signer("r4", com.keys["r4"].seed)
                )
                w.mark_stale()
                removed.transport = w
                removed.retired = False  # the byzantine un-retire
                before = {
                    r.id: r.metrics["dropped_precheck"]
                    for r in com.replicas if r.id != "r4"
                }
                # the refusenik actively votes into the new epoch:
                # validly signed prepares/commits for live slots, sent
                # straight at the new committee's members
                from simple_pbft_tpu.messages import Commit, Prepare

                signer = Signer("r4", com.keys["r4"].seed)
                live_view = max(r.view for r in com.replicas if r.id != "r4")
                frontier = max(
                    r.executed_seq for r in com.replicas if r.id != "r4"
                )
                for cls in (Prepare, Commit):
                    vote = cls(
                        view=live_view, seq=frontier + 1, digest="ab" * 32
                    )
                    signer.sign_msg(vote)
                    for r in com.replicas:
                        if r.id != "r4":
                            await w.send(r.id, vote.to_wire())
                for i in range(10):
                    await c.submit(f"put z{i} {i}", retries=5)
                await asyncio.sleep(0.3)
                # the committee kept committing; honest replicas dropped
                # the stale votes at the role gate (no signature spent,
                # no quorum influence) and nobody got accused
                assert w.injections >= 1
                gated = sum(
                    r.metrics["dropped_precheck"] - before[r.id]
                    for r in com.replicas if r.id != "r4"
                )
                assert gated >= 1, "stale votes never hit the role gate"
                assert all(a.violations == 0 for a in auditors.values())
            finally:
                await com.stop()
                for a in auditors.values():
                    a.close()

        run(scenario())

    def test_epoch_key_registration_keeps_jit_shapes_closed(self):
        """PR 3's warm_for_population contract across an epoch boundary:
        registering a NEW member's key fills a reserved bank row — the
        jit signature (mode, window, batch, table cap) is unchanged, so
        post_warm_compiles stays 0 and the new key's signatures verify
        on the warmed device path."""
        from simple_pbft_tpu.crypto import ed25519_cpu
        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier
        from simple_pbft_tpu.crypto.verifier import BatchItem

        cfg, keys = make_test_committee(n=4, clients=1)
        pop = list(cfg.pubkeys.values())
        v = TpuVerifier(initial_keys=len(pop) + 32)
        v.warm_for_population(pop, max_sweep=8)
        assert v.shape_snapshot()["post_warm_compiles"] == 0

        # the epoch boundary registers the joiner's key, shapes closed
        kp = _joiner_keys("r4")
        v.warm(pubkeys=[kp.pub], buckets=[])
        payload = b"post-epoch message"
        sig = ed25519_cpu.sign(kp.seed, payload)
        out = v.verify_batch(
            [BatchItem(pubkey=kp.pub, msg=payload, sig=sig)] * 8
        )
        assert all(out)
        snap = v.shape_snapshot()
        assert snap["post_warm_compiles"] == 0, snap


# ---------------------------------------------------------------------------
# satellites: tcp frame accounting, kind-registry sync, NET column
# ---------------------------------------------------------------------------


class TestTcpFrameAccounting:
    def test_mid_write_failure_counted_and_quorum_frames_requeued(self):
        async def scenario():
            # a peer that accepts every connection and slams it shut:
            # every frame that reaches the writer dies mid-write
            conns = 0

            async def slam(reader, writer):
                nonlocal conns
                conns += 1
                writer.close()

            server = await asyncio.start_server(slam, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            a = TcpTransport(
                "a", ("127.0.0.1", 0), peers={"b": ("127.0.0.1", port)}
            )
            await a.start()
            try:
                critical = b'{"kind":"commit","seq":1}'
                deferrable = b'{"kind":"request","op":"x"}'
                # phase 1: only quorum-critical frames — a mid-write
                # failure must requeue, never silently drop
                for _ in range(400):
                    await a.send("b", critical)
                    if a.metrics["frames_requeued"] >= 1:
                        break
                    await asyncio.sleep(0.02)
                # phase 2: only deferrable frames — a mid-write failure
                # is a COUNTED drop (the sender retries on its own timer)
                for _ in range(400):
                    await a.send("b", deferrable)
                    if a.metrics["frames_dropped"] >= 1:
                        break
                    await asyncio.sleep(0.02)
                # quorum-critical frames got their one requeue; the
                # second failure (and every deferrable failure) is a
                # counted drop — never a silent loss
                assert a.metrics["frames_requeued"] >= 1, dict(a.metrics)
                assert a.metrics["frames_dropped"] >= 1, dict(a.metrics)
            finally:
                await a.stop()
                server.close()
                await server.wait_closed()

        run(scenario(), timeout=60)


class TestKindRegistrySync:
    def test_docstrings_and_parse_errors_name_every_kind(self):
        import simple_pbft_tpu.faults as faults_mod

        table = kind_table()
        for kind in KIND_REGISTRY:
            assert kind in table
            # regenerated into both docstrings at import: no drift
            assert kind in (faults_mod.__doc__ or "")
            assert kind in (FaultSchedule.__doc__ or "")
        with pytest.raises(ValueError) as ei:
            FaultSchedule.parse("bogus_key=1", horizon=10.0)
        msg = str(ei.value)
        for kind in KIND_REGISTRY:
            assert kind in msg, f"parse error does not name {kind!r}"

    def test_new_kind_parse_and_determinism(self):
        spec = (
            "seed=7,partition=1.0:r0|r1<>r2|r3:0.5;3.0:*>r0,"
            "heal=4.0,shape=wan3dc,stale=1,forgesync=1"
        )
        ids = ["r0", "r1", "r2", "r3"]
        s1 = FaultSchedule.parse(spec, horizon=10.0, replica_ids=ids)
        s2 = FaultSchedule.parse(spec, horizon=10.0, replica_ids=ids)
        assert s1.events == s2.events
        kinds = {e.kind for e in s1.events}
        assert {
            "partition", "heal", "shape", "stale_epoch", "forge_statesync"
        } <= kinds
        with pytest.raises(ValueError):
            FaultSchedule.parse("shape=nosuchprofile", horizon=10.0)
        with pytest.raises(ValueError):
            FaultSchedule.parse("partition=oops", horizon=10.0)
        # 'shape=lossy:5' is malformed (T:NAME[:DUR]), not 'lossy forever'
        with pytest.raises(ValueError):
            FaultSchedule.parse("shape=lossy:5", horizon=10.0)

    def test_reconfig_key_rotation_keeps_the_member(self):
        # remove+add of the SAME id in one op is key rotation: the member
        # must survive with the new key, not be silently dropped
        from simple_pbft_tpu.config import apply_reconfig, make_test_committee

        cfg, _ = make_test_committee(n=5, clients=1)
        kp = _joiner_keys("r2x")
        new_cfg = apply_reconfig(
            cfg, {"r2": {"pub": kp.pub.hex()}}, ["r2"]
        )
        assert "r2" in new_cfg.replica_ids
        assert new_cfg.n == 5
        assert new_cfg.pubkeys["r2"] == kp.pub
        # rotation re-enters at the END of the order (it is a re-add)
        assert new_cfg.replica_ids[-1] == "r2"


class TestNetColumn:
    def test_net_cell_renders_shaping_partition_and_sync_state(self):
        snap = {
            "replica": {"statesync_active": True, "retired": False},
            "transport": {
                "shaping": {
                    "profile": "wan3dc",
                    "cut_to": ["r1", "r2"],
                    "shaped_links": 6,
                    "shaped_lost": 3,
                    "partition_dropped": 4,
                },
            },
        }
        cell = pbft_top.net_cell(snap)
        assert "wan3dc" in cell and "!2cut" in cell
        assert "~7" in cell and "sync" in cell
        assert pbft_top.net_cell({"replica": {}, "transport": {}}) == ""
        row = pbft_top.row_from_snapshot(snap, "http", None, 1.0)
        assert cell in row
        assert len(row) == len(pbft_top.COLUMNS)


# ---------------------------------------------------------------------------
# statesync SOLO mode: forgery attribution without honest-peer collateral
# ---------------------------------------------------------------------------


class _StubReplica:
    """Minimal replica surface for driving StateSync deterministically."""

    def __init__(self):
        from collections import defaultdict
        from types import SimpleNamespace

        self.id = "rx"
        self.cfg = SimpleNamespace(
            replica_ids=["rx", "r0", "r1", "r2"]
        )
        self.metrics = defaultdict(int)
        self.signer = SimpleNamespace(sign_msg=lambda m: None)
        self.sent = []
        self.transport = SimpleNamespace(send=self._send)
        self.installed = []
        self.snapshots = {}

    async def _send(self, dest, raw):
        self.sent.append((dest, raw))

    async def install_snapshot(self, seq, digest, snap):
        self.installed.append((seq, digest, snap))
        return True

    async def send_slot_probe(self):
        pass


def _chunk_reply(sender, seq, index, total, data):
    from simple_pbft_tpu.messages import StateChunkReply

    msg = StateChunkReply(seq=seq, index=index, total=total, data=data)
    msg.sender = sender
    return msg


class TestStatesyncSoloMode:
    def test_forgery_attribution_convicts_only_the_liar(self):
        """The full recovery ladder: a multi-source mismatch convicts
        NOBODY (attribution impossible) and drops to SOLO mode; a solo
        mismatch convicts its sole source definitively; the next honest
        solo peer completes the install. Before this, a mismatch
        excluded EVERY serving peer — one persistent forger livelocked
        the transfer (honest peers excluded, nobody left to serve)."""
        from simple_pbft_tpu.app import snapshot_digest
        from simple_pbft_tpu.consensus.statesync import StateSync

        async def scenario():
            r = _StubReplica()
            ss = StateSync(r)
            snap = "A" * 40 + "B" * 40
            digest = snapshot_digest(snap)
            await ss.begin(8, digest, certifiers=["r0", "r1", "r2"])
            a = ss.active

            # round 1: striped assembly, r0's chunk forged — mismatch
            # with two sources convicts nobody, enters solo mode
            await ss.on_chunk_reply(_chunk_reply("r0", 8, 0, 2, "X" * 40))
            await ss.on_chunk_reply(_chunk_reply("r1", 8, 1, 2, snap[40:]))
            assert ss.active is a  # still transferring
            assert r.metrics["statesync_forged"] == 1
            assert a["bad_peers"] == set()
            assert a["solo"] is not None
            assert not a["chunks"] and a["total"] is None

            # round 2: the solo peer serves the WHOLE (forged) snapshot
            # — every byte came from it, so conviction is definitive
            liar = a["solo"]
            await ss.on_chunk_reply(_chunk_reply(liar, 8, 0, 1, "Z" * 80))
            assert r.metrics["statesync_forged"] == 2
            assert a["bad_peers"] == {liar}
            assert a["solo"] is not None and a["solo"] != liar

            # replies from the convicted liar (and stale multi-source
            # peers) are ignored in solo mode
            await ss.on_chunk_reply(_chunk_reply(liar, 8, 0, 1, snap))
            others = [
                p for p in ("r0", "r1", "r2")
                if p != a["solo"] and p != liar
            ]
            await ss.on_chunk_reply(_chunk_reply(others[0], 8, 0, 1, snap))
            assert not a["chunks"]

            # round 3: the honest solo peer completes the transfer
            await ss.on_chunk_reply(_chunk_reply(a["solo"], 8, 0, 1, snap))
            assert ss.active is None
            assert r.installed == [(8, digest, snap)]
            assert r.metrics["statesync_restarts"] == 2

        run(scenario(), timeout=30)

    def test_conflicting_totals_convict_only_on_clean_attribution(self):
        from simple_pbft_tpu.app import snapshot_digest
        from simple_pbft_tpu.consensus.statesync import StateSync

        async def scenario():
            r = _StubReplica()
            ss = StateSync(r)
            snap = "C" * 64
            await ss.begin(4, snapshot_digest(snap), certifiers=["r0", "r1"])
            a = ss.active
            # two DISTINCT claimants disagree on the count: either could
            # be lying — nobody convicted, transfer isolates to solo
            await ss.on_chunk_reply(_chunk_reply("r0", 4, 0, 2, "C" * 32))
            await ss.on_chunk_reply(_chunk_reply("r1", 4, 0, 3, "C" * 16))
            assert a["bad_peers"] == set()
            assert a["solo"] is not None
            assert a["total"] is None

            # the SAME peer contradicting its own earlier claim is
            # definitive: convict it
            solo = a["solo"]
            await ss.on_chunk_reply(_chunk_reply(solo, 4, 0, 2, "C" * 32))
            await ss.on_chunk_reply(_chunk_reply(solo, 4, 1, 5, "C" * 16))
            assert solo in a["bad_peers"]
            assert a["solo"] != solo

        run(scenario(), timeout=30)

    def test_serve_bucket_admits_pipelined_burst_then_throttles(self):
        """The requester's WINDOW round-robin lands back-to-back requests
        on the same peer; a fixed per-request cooldown dropped them
        (capping transfers at ~1 chunk/peer/tick) — the token bucket
        serves the whole burst and still bounds a hostile spammer."""
        from simple_pbft_tpu.consensus.statesync import (
            SERVE_BURST, StateSync,
        )
        from simple_pbft_tpu.messages import StateChunkRequest

        async def scenario():
            r = _StubReplica()
            r.snapshots[4] = "D" * 64
            ss = StateSync(r)
            req = StateChunkRequest(seq=4, index=0)
            req.sender = "joiner"
            for _ in range(SERVE_BURST):
                await ss.on_chunk_request(req)
            assert r.metrics["statesync_chunks_served"] == SERVE_BURST
            assert r.metrics["statesync_throttled"] == 0
            await ss.on_chunk_request(req)  # burst exhausted
            assert r.metrics["statesync_throttled"] == 1
            assert len(r.sent) == SERVE_BURST

        run(scenario(), timeout=30)

    def test_persistent_forgers_cannot_livelock_rejoin(self):
        """Integration regression for the livelock: TWO of three serving
        peers forge every chunk and NEVER heal; the snapshot spans
        multiple chunks so the striped first assembly must touch a
        forger. Solo mode convicts the forgers individually and the
        honest peer completes the transfer — previously the first
        mismatch excluded all three peers and the joiner never caught
        up while a forger stayed active."""

        async def scenario():
            from simple_pbft_tpu.consensus.statesync import CHUNK_BYTES

            com = LocalCommittee.build(
                n=4, clients=1, checkpoint_interval=4, view_timeout=1.0
            )
            com.start()
            c = com.clients[0]
            victim = com.replica("r3")
            big = "x" * 20000
            try:
                for i in range(6):
                    await c.submit(f"put k{i} {big}", retries=5)
                victim.kill()
                for i in range(10):
                    await c.submit(f"put m{i} {big}", retries=5)
                # the live snapshot now spans >= 2 chunks. Settle: the
                # speculative fast path (ISSUE 15) answers submits
                # before the commit wave executes, so the checkpoint
                # that cuts the big snapshot may still be in flight
                donor = com.replica("r0")
                for _ in range(200):
                    if any(
                        len(s) > CHUNK_BYTES
                        for s in donor.snapshots.values()
                    ):
                        break
                    await asyncio.sleep(0.05)
                assert any(
                    len(s) > CHUNK_BYTES for s in donor.snapshots.values()
                )
                wrapped = []
                for rid in ("r0", "r1"):
                    rep = com.replica(rid)
                    w = ForgedSnapshotServer(
                        rep.transport, Signer(rid, com.keys[rid].seed)
                    )
                    rep.transport = w
                    wrapped.append(w)

                fresh = Replica(
                    node_id="r3", cfg=com.cfg, seed=com.keys["r3"].seed,
                    transport=com.net.endpoint("r3"), app=KVStore(),
                )
                com.replicas[com.replicas.index(victim)] = fresh
                fresh.start()
                for i in range(6):
                    await c.submit(f"put s{i} {i}", retries=5)
                frontier = max(
                    r.executed_seq for r in com.replicas if r is not fresh
                )
                # catch-up WHILE the forgers stay active — no heal
                for _ in range(500):
                    if fresh.executed_seq >= frontier:
                        break
                    await asyncio.sleep(0.05)
                assert fresh.executed_seq >= frontier, (
                    fresh.executed_seq, frontier, dict(fresh.metrics),
                )
                assert sum(w.injections for w in wrapped) >= 1
                assert fresh.metrics["statesync_forged"] >= 1
                assert fresh.app.data.get("k0") == big
            finally:
                await com.stop()

        run(scenario())

    def test_oversized_chunk_convicts_before_storing(self):
        """An honest server never exceeds CHUNK_BYTES per chunk, so an
        oversized reply is an individually attributable lie — it must be
        convicted BEFORE a byte is stored, or a forged stream of
        transport-cap-sized chunks balloons the joiner's memory long
        before the assembly digest check could notice."""
        from simple_pbft_tpu.app import snapshot_digest
        from simple_pbft_tpu.consensus.statesync import CHUNK_BYTES, StateSync

        async def scenario():
            r = _StubReplica()
            ss = StateSync(r)
            snap = "E" * 64
            await ss.begin(4, snapshot_digest(snap), certifiers=["r0", "r1"])
            a = ss.active
            await ss.on_chunk_reply(
                _chunk_reply("r0", 4, 0, 2, "F" * (CHUNK_BYTES + 1))
            )
            assert "r0" in a["bad_peers"]
            assert not a["chunks"]
            assert r.metrics["statesync_bytes"] == 0
            assert r.metrics["statesync_forged"] == 1
            # the honest peer still completes the transfer in solo mode
            while a["solo"] == "r0":
                ss._rotate_solo(a)
            await ss.on_chunk_reply(_chunk_reply(a["solo"], 4, 0, 1, snap))
            assert r.installed == [(4, snapshot_digest(snap), snap)]

        run(scenario(), timeout=30)


# ---------------------------------------------------------------------------
# review hardening: link FIFO, schedule-driven stale voter, address plane
# ---------------------------------------------------------------------------


class _RecordingInner:
    def __init__(self, node_id="rA"):
        self.node_id = node_id
        self.delivered = []

    async def send(self, dest, raw):
        self.delivered.append(raw)

    async def broadcast(self, raw, dests):
        for d in dests:
            if d != self.node_id:
                await self.send(d, raw)


class TestShapedLinkFifo:
    def test_jitter_never_reorders_a_link(self):
        """A TCP byte stream cannot deliver frame B before an earlier
        frame A. Independent per-frame jitter draws used to violate that
        on every shaped link (both shipped profiles set jitter but no
        bandwidth, so nothing serialized deliveries) — shaped-over-TCP
        rehearsals were strictly MORE adversarial than the WAN they
        claim to model. Deliveries are now clamped behind the link's
        previous one."""

        async def scenario():
            inner = _RecordingInner()
            shaped = ShapedTransport(
                inner,
                shapes={"rB": LinkShape(delay_s=0.0005, jitter_s=0.02)},
                seed=3,
            )
            frames = [f"frame-{i}".encode() for i in range(30)]
            for f in frames:
                await shaped.send("rB", f)
            deadline = asyncio.get_event_loop().time() + 5.0
            while (
                len(inner.delivered) < len(frames)
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.01)
            assert inner.delivered == frames

        run(scenario(), timeout=30)


class TestScheduleDrivenStaleVoter:
    def test_armed_voter_actually_votes_after_removal(self):
        """The honest retiree self-gags at _send_vote, so a StaleEpochVoter
        armed purely on `retired` never saw a vote frame: injections
        stayed 0 and the schedule recorded a byzantine fault that never
        happened. The injector now makes the target REFUSE retirement —
        its stale-epoch votes actually leave the process and die at the
        honest peers' role gate."""
        import time as time_mod

        async def scenario():
            com = LocalCommittee.build(
                n=5, clients=1, checkpoint_interval=4, view_timeout=2.0
            )
            com.start()
            c = com.clients[0]
            schedule = FaultSchedule(
                seed=0, horizon=0.2,
                events=(FaultEvent(t=0.0, kind="stale_epoch", target="r4"),),
            )
            injector = FaultInjector(committee=com, schedule=schedule)
            try:
                await injector.run(pbft_clock.now() + 0.5)
                removed = com.replica("r4")
                assert removed.refuse_retirement
                assert isinstance(removed.transport, StaleEpochVoter)
                for i in range(4):
                    await c.submit(f"put k{i} {i}", retries=5)
                res = await c.submit(
                    "__reconfig__ " + json.dumps({"remove": ["r4"]}),
                    retries=5,
                )
                assert res.startswith("reconfig-staged:"), res
                before = {
                    r.id: r.metrics["dropped_precheck"]
                    for r in com.replicas if r.id != "r4"
                }
                for i in range(10):
                    await c.submit(f"put m{i} {i}", retries=5)
                await asyncio.sleep(0.3)
                # the refusenik crossed the boundary WITHOUT gagging
                assert removed.cfg.epoch >= 1
                assert "r4" not in removed.cfg.replica_ids
                assert not removed.retired
                # its stale votes really left the process this time...
                assert injector.byzantine_injections >= 1
                # ...and died at the honest role gate, not in a quorum
                gated = sum(
                    r.metrics["dropped_precheck"] - before[r.id]
                    for r in com.replicas if r.id != "r4"
                )
                assert gated >= 1
                assert all(
                    r.executed_seq >= 14
                    for r in com.replicas if r.id != "r4"
                )
            finally:
                await com.stop()

        run(scenario())


class TestAddressPlane:
    def test_reconfig_addr_rides_config_and_updates_peer_books(self):
        """Socket transports route by peer book: a reconfiguration-added
        member used to be named by the committed config but unreachable
        (tcp/grpc send drops unknown dests silently). The add spec now
        carries `addr`, the book rides config_doc (so snapshots and
        ConfigReply ship it), and epoch activation / client adoption
        push it into every peer map in the transport wrapper chain."""
        import dataclasses

        from simple_pbft_tpu.config import (
            apply_reconfig, config_doc, config_from_doc,
        )
        from simple_pbft_tpu.transport.base import update_peer_book

        cfg, _ = make_test_committee(n=4, clients=1)
        cfg = dataclasses.replace(
            cfg,
            addrs={f"r{i}": ("127.0.0.1", 7000 + i) for i in range(4)},
        )
        kp = _joiner_keys("r9")
        new_cfg = apply_reconfig(
            cfg,
            {"r9": {"pub": kp.pub.hex(), "addr": "10.0.0.9:7009"}},
            [],
        )
        assert new_cfg.addrs["r9"] == ("10.0.0.9", 7009)
        # survivors keep their entries; the doc round-trip (checkpoint
        # snapshot / ConfigReply) preserves the whole book
        assert new_cfg.addrs["r0"] == ("127.0.0.1", 7000)
        rt = config_from_doc(cfg, config_doc(new_cfg))
        assert rt.addrs == new_cfg.addrs
        # a malformed addr denies the whole reconfig deterministically
        with pytest.raises(ValueError):
            apply_reconfig(
                cfg, {"r9": {"pub": kp.pub.hex(), "addr": "nocolon"}}, []
            )

        class _Sock:
            node_id = "r0"

            def __init__(self):
                self.peers = {"r1": ("127.0.0.1", 7001)}

        sock = _Sock()
        shaped = ShapedTransport(sock)
        assert update_peer_book(shaped, new_cfg.addrs) >= 1
        assert sock.peers["r9"] == ("10.0.0.9", 7009)
        assert sock.peers["r3"] == ("127.0.0.1", 7003)
        assert "r0" not in sock.peers  # a book never routes to itself

    def test_deployment_boot_config_carries_the_book(self, tmp_path):
        from simple_pbft_tpu import deploy

        dep = deploy.generate(str(tmp_path), n=4, clients=1)
        assert dep.cfg.addrs == dep.addresses
        loaded = deploy.load(str(tmp_path / "committee.json"))
        assert loaded.cfg.addrs == dep.addresses


class TestEpochBoundarySafety:
    """A slot past a staged membership boundary belongs to the NEXT
    epoch: the old committee's (smaller) quorum must never decide it.
    Stop-sequence gates hold such slots while the change is staged, and
    activation refits any straddler that slipped through the
    staging-knowledge race (proposals pipelined ahead of the execution
    frontier)."""

    def _staged_replica(self):
        com = LocalCommittee.build(
            n=4, clients=1, checkpoint_interval=4, view_timeout=5.0
        )
        r0 = com.replica("r0")
        kp = _joiner_keys("r4")
        from simple_pbft_tpu.config import apply_reconfig

        grown = apply_reconfig(
            r0.cfg,
            {"r4": {"pub": kp.pub.hex()},
             "r5": {"pub": _joiner_keys("r5").pub.hex()},
             "r6": {"pub": _joiner_keys("r6").pub.hex()}},
            [],
        )
        assert grown.quorum > r0.cfg.quorum  # 3 -> 5: the unsafe delta
        r0.pending_reconfig = (8, grown)
        return com, r0, grown

    def test_stop_sequence_gates_proposals_and_admission(self):
        from simple_pbft_tpu.messages import PrePrepare, Request

        async def scenario():
            com, r0, grown = self._staged_replica()
            # primary side: next_seq past the staged boundary stalls
            r0.next_seq = 9
            req = Request(client_id="c0", timestamp=1, operation="put a 1")
            r0.pending_requests = [req]
            await r0._propose_if_ready()
            assert r0.metrics["reconfig_boundary_stall"] == 1
            assert r0.metrics["proposed_blocks"] == 0
            assert (0, 9) not in r0.instances
            # backup side: a proposal past the boundary is refused
            pp = PrePrepare(
                view=0, seq=9, digest=PrePrepare.block_digest([]), block=[]
            )
            pp.sender = "r0"
            await r0._on_phase(pp)
            assert r0.metrics["preprepare_beyond_boundary"] == 1
            assert (0, 9) not in r0.instances
            # at/below the boundary is untouched by the gate
            pp8 = PrePrepare(
                view=0, seq=8, digest=PrePrepare.block_digest([]), block=[]
            )
            pp8.sender = "r0"
            await r0._on_phase(pp8)
            assert r0.metrics["preprepare_beyond_boundary"] == 1

        run(scenario(), timeout=30)

    def test_activation_refits_straddler_instances(self):
        from simple_pbft_tpu.consensus.state import (
            ExecuteBlock, Stage,
        )
        from simple_pbft_tpu.messages import Commit, Prepare

        async def scenario():
            com, r0, grown = self._staged_replica()
            old_quorum = r0.cfg.quorum
            # a straddler: slot 9 fully committed under the OLD quorum
            # (its pre-prepare outran r0's execution of the staging op),
            # with one vote from a sender the new epoch removes
            inst = r0._instance(0, 9)
            assert inst.quorum == old_quorum
            inst.digest = "ab" * 32
            inst.block = []
            from simple_pbft_tpu.messages import PrePrepare

            ppin = PrePrepare(view=0, seq=9, digest=inst.digest, block=[])
            ppin.sender = r0.cfg.primary(0)
            inst.pre_prepare = ppin
            for sender in ("r0", "r1", "r2"):
                p = Prepare(view=0, seq=9, digest=inst.digest)
                p.sender = sender
                inst.on_prepare(p)
                c = Commit(view=0, seq=9, digest=inst.digest)
                c.sender = sender
                inst.on_commit(c)
            inst.stage = Stage.COMMITTED
            inst.executed = True
            r0.ready[9] = ExecuteBlock(0, 9, inst.digest, [])
            # also an UNPINNED buffer instance: primary must repoint
            buf = r0._instance(1, 10)

            low = r0._instance(0, 8)
            r0.executed_seq = 8
            r0._activate_epoch(grown)
            assert inst.quorum == grown.quorum
            # 3 surviving old-epoch votes < new quorum 5: the commit is
            # walked back (digest stays pinned — no re-vote two ways)
            assert inst.stage == Stage.PRE_PREPARED
            assert inst.digest == "ab" * 32
            assert not inst.executed
            assert 9 not in r0.ready
            assert r0.metrics["epoch_slots_downgraded"] >= 1
            assert buf.quorum == grown.quorum
            assert buf.primary == grown.primary(1)
            # slots at/below the boundary keep their old-epoch threshold
            assert low.quorum == old_quorum

        run(scenario(), timeout=30)

    def test_generated_partition_durations_respect_short_horizons(self):
        # uniform(0.5, 0.15*h) inverts its bounds below h~3.3s and dealt
        # durations past the cap (into the bench drain window)
        s = FaultSchedule.generate(
            seed=3, horizon=2.0, partition_windows=8,
            replica_ids=["r0", "r1", "r2", "r3"],
        )
        durs = [e.duration for e in s.events if e.kind == "partition"]
        assert durs and all(d <= 0.15 * 2.0 + 1e-9 for d in durs)


class TestConfigVoteSpam:
    def test_hostile_replica_cannot_starve_honest_config_adoption(self):
        """Per-sender claim slots: a hostile KNOWN replica signing any
        number of distinct configs only overwrites its own slot, so the
        honest f+1 still accumulates and the client adopts. (The old
        bounded-table eviction could be pre-filled and then starved the
        honest entry on the fewest-votes tie-break.)"""
        from simple_pbft_tpu.config import (
            apply_reconfig, config_doc, make_test_committee,
        )
        from simple_pbft_tpu.messages import ConfigReply

        async def scenario():
            cfg, keys = make_test_committee(
                n=4, clients=1, verify_signatures=False
            )
            client = Client(
                "c0", cfg, keys["c0"].seed, _RecordingInner("c0")
            )
            grown = apply_reconfig(
                cfg, {"r4": {"pub": _joiner_keys("r4").pub.hex()}}, []
            )
            good = json.dumps(config_doc(grown))
            # r3 floods distinct forged configs for epochs far ahead
            for i in range(200):
                spam = ConfigReply(
                    epoch=1 + i, config=json.dumps({"junk": i})
                )
                spam.sender = "r3"
                client._on_config_reply(spam)
            assert len(client._config_votes) == 1  # only r3's own slot
            # two honest members (f+1) report the real epoch-1 config
            for sender in ("r0", "r1"):
                msg = ConfigReply(epoch=1, config=good)
                msg.sender = sender
                client._on_config_reply(msg)
            assert client.epoch == 1
            assert "r4" in client.cfg.replica_ids

        run(scenario(), timeout=30)
