"""Directed regressions for the round-4 targeted-repair mechanisms
(docs/PROTOCOL.md "Targeted repair under message loss").

Each test pins one of the liveness holes found in the qc-n64 chaos
tail post-mortem (a unanimous live committee, idle primary, starving
clients) with a DETERMINISTIC small-scale reproduction — the seeded
chaos A/Bs in bench_results/ prove the composite; these prove each
mechanism in isolation so a regression names its culprit.

The reference has no failure handling at all (stage gates wait forever,
`需要改进的地方.md:26-29`; dead view change, view.go) — this entire
surface is rebuild-only.
"""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.messages import Commit, Message, Prepare
from simple_pbft_tpu.transport.local import FaultPlan


def run(coro):
    asyncio.run(coro)


def _drop_first_votes(replica, kinds, count):
    """Wrap `replica`'s outbound send AND broadcast: silently eat the
    first `count` emissions of the given kinds (the vote frames a lossy
    link would lose), then pass everything — including RESENDS of the
    same votes. QC mode votes are unicast (shares to the primary);
    normal mode votes are broadcast."""
    real_send = replica.transport.send
    real_broadcast = replica.transport.broadcast
    state = {"left": count, "eaten": 0}

    def _eats(wire) -> bool:
        if state["left"] <= 0:
            return False
        try:
            msg = Message.from_wire(wire)
        except ValueError:
            return False
        if isinstance(msg, kinds):
            state["left"] -= 1
            state["eaten"] += 1
            return True
        return False

    async def send(target, wire):
        if not _eats(wire):
            await real_send(target, wire)

    async def broadcast(wire, dests):
        if not _eats(wire):
            await real_broadcast(wire, dests)

    replica.transport.send = send
    replica.transport.broadcast = broadcast
    return state


def test_lost_commit_shares_repaired_without_view_change():
    """QC mode: eat the FIRST commit share from two backups (quorum now
    unreachable from first sends alone). The frontier stalls, the probe
    chain notices zero progress between ticks, the senders re-emit their
    shares, the primary aggregates — all in view 0. Before round 4 this
    slot stalled until the failover ladder outran client patience."""

    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, qc_mode=True,
            # failover timer far beyond the test: recovery must come
            # from vote retransmission, not a view change
            view_timeout=60.0,
            # speculation off: every replica PREPARES this slot, so the
            # speculative fast path would answer the client before the
            # stalled commit quorum even matters — this test pins the
            # resend REPAIR path, which needs the client blocked on the
            # final commit (tests/test_speculation.py covers the fast
            # answer itself)
            speculative=False,
        )
        com.start()
        c = com.clients[0]
        # the client must never get to retry: success before the first
        # client timeout proves the PROBE-cadence resend did the repair
        # (admitted pre-prepares arm the chain; ~2 ticks at <=3 s each)
        c.request_timeout = 30.0
        eaten = [
            _drop_first_votes(com.replica(r), (Commit,), 1)
            for r in ("r1", "r2")
        ]
        t0 = time.perf_counter()
        assert await c.submit("put k 1") == "ok"
        assert time.perf_counter() - t0 < 25.0, "repair waited on client patience"
        assert all(s["eaten"] == 1 for s in eaten), eaten
        assert all(r.view == 0 for r in com.replicas)
        resent = sum(
            r.metrics.get("frontier_votes_resent", 0) for r in com.replicas
        )
        assert resent > 0, "repair must be the resend path, not luck"
        await com.stop()

    run(scenario())


def test_lost_prepare_votes_repaired_without_view_change():
    """Normal (broadcast-vote) mode: eat the first prepare AND commit
    from two backups toward everyone — with n=4 the 2f+1=3 quorums then
    need the resend path at every replica."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, view_timeout=60.0)
        com.start()
        c = com.clients[0]
        c.request_timeout = 30.0
        # eat each backup's first prepare broadcast and first commit
        # broadcast: 2f+1=3 quorums then need the resend path
        eaten = [
            _drop_first_votes(com.replica(r), (Prepare, Commit), 2)
            for r in ("r1", "r2")
        ]
        t0 = time.perf_counter()
        assert await c.submit("put k 2") == "ok"
        assert time.perf_counter() - t0 < 25.0, "repair waited on client patience"
        assert all(s["eaten"] == 2 for s in eaten), eaten
        assert all(r.view == 0 for r in com.replicas)
        await com.stop()

    run(scenario())


def test_stranded_request_rescued_across_failover():
    """Client work must survive a failover that kills the only primary
    that ever saw it as primary: the request is queued at r0 (isolated
    before proposing), the committee moves to view 1, and the backups'
    install-time re-relay plus the new primary's requeue path must get
    it committed — the O-set cannot carry it (it was never prepared)."""

    async def scenario():
        plan = FaultPlan(seed=3)
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, view_timeout=1.5,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        # cut r0 off from the committee BUT not from the client: the
        # request reaches r0 (it queues it as primary) and reaches the
        # backups only as the client's retry broadcasts
        for other in ("r1", "r2", "r3"):
            plan.cut("r0", other)
        assert await c.submit("put stranded 7", retries=30) == "ok"
        survivors = [r for r in com.replicas if r.id != "r0"]
        assert all(r.view >= 1 for r in survivors)
        # submit resolves at f+1 matching replies; the slowest survivor
        # may still be executing — settle before the all-survivors check
        for _ in range(80):
            if all(r.app.data.get("stranded") == "7" for r in survivors):
                break
            await asyncio.sleep(0.25)
        assert all(r.app.data.get("stranded") == "7" for r in survivors)
        await com.stop()

    run(scenario())


def test_new_primary_requeues_retry_for_dead_slot():
    """The dedup-eats-retries hole: work assigned to a slot that died
    with an old view must be re-queued when the client's retry reaches
    the new primary, not swallowed by seen_requests."""

    async def scenario():
        plan = FaultPlan(seed=5)
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, view_timeout=1.5,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        assert await c.submit("put warm 0") == "ok"  # healthy baseline
        # isolate r0 (view 0's primary) completely mid-reign; the next
        # request strands wherever it was first seen until failover
        for other in ("r1", "r2", "r3", c.id):
            plan.cut("r0", other)
        assert await c.submit("put rescued 9", retries=30) == "ok"
        survivors = [r for r in com.replicas if r.id != "r0"]
        assert all(r.view >= 1 for r in survivors)
        for _ in range(80):
            if all(r.app.data.get("rescued") == "9" for r in survivors):
                break
            await asyncio.sleep(0.25)
        assert all(r.app.data.get("rescued") == "9" for r in survivors)
        await com.stop()

    run(scenario())


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
