"""Traffic observatory (ISSUE 17): open-loop arrival determinism, SLO
oracle true-positive/true-negative behavior, bounded memory at 10^5+
virtual clients, flight-frame stitching, and the schedule schema that
carries load shapes.

Everything scenario-shaped runs in VIRTUAL time (sim.run_scenario): the
arrival stream, the shed decisions, and the latency windows are a pure
function of the seed."""

import json
import tracemalloc
from dataclasses import replace

import pytest

from simple_pbft_tpu.faults import FaultSchedule
from simple_pbft_tpu.sim import Scenario, run_scenario
from simple_pbft_tpu.workload import (
    DEFAULT_SLO,
    PRESETS,
    TrafficStats,
    WorkloadEvent,
    arrival_digest,
    judge_slo,
    preset,
    spec_from_doc,
)


# ---------------------------------------------------------------------------
# deterministic arrivals
# ---------------------------------------------------------------------------


def test_arrival_stream_is_seed_deterministic():
    """Same (spec, events, seed) => byte-identical planned arrival
    stream, including client identities, flood counts, and the ingress
    shed accounting; a different seed diverges."""
    spec = preset("overload")
    events = (
        WorkloadEvent(t=2.0, kind="burst", duration=1.5, magnitude=4.0),
        WorkloadEvent(t=5.0, kind="retry_storm", duration=2.0,
                      magnitude=3.0),
        WorkloadEvent(t=3.0, kind="remix", duration=2.0, magnitude=0.5,
                      spec="interactive>bulk"),
    )
    d1 = arrival_digest(spec, events, seed=11, horizon=8.0)
    d2 = arrival_digest(spec, events, seed=11, horizon=8.0)
    d3 = arrival_digest(spec, events, seed=12, horizon=8.0)
    assert d1 == d2
    assert d1 != d3


def test_workload_run_fingerprint_deterministic():
    """The full sim (plane + committee + oracles) replays byte for
    byte: same seed => same trace fingerprint and same traffic totals."""
    sc = Scenario(seed=7, horizon=4.0, workload={"preset": "steady"})
    r1, r2 = run_scenario(sc), run_scenario(sc)
    assert r1.fingerprint == r2.fingerprint
    assert r1.details["traffic"]["offered"] == r2.details["traffic"]["offered"]
    assert r1.details["traffic"]["accepted"] == r2.details["traffic"]["accepted"]
    assert r1.coverage["clients_touched"] > 0


# ---------------------------------------------------------------------------
# SLO oracles: true negatives (healthy committees pass under any shape)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_slo_clean_overload_passes():
    """3x overcommit with fair shedding: the starvation oracle must NOT
    fire — fair arrival-order shedding equalizes per-window accept
    ratios, which is exactly what it checks. (Tier-1 runs the same TN
    through tools/traffic_smoke.py's smoke gate; this stays slow-tier.)"""
    res = run_scenario(Scenario(seed=3, workload={"preset": "overload"}))
    assert res.ok, res.failure
    sv = res.details["slo"]["starvation"]
    assert sv["ok"] and not sv["starved_windows"]
    # and the run genuinely overloaded (this is not a trivially idle TN)
    assert res.details["traffic"]["shed"] > 0


def test_slo_oracles_judged_on_steady():
    res = run_scenario(Scenario(seed=3, workload={"preset": "steady"}))
    assert res.ok, res.failure
    slo = res.details["slo"]
    assert set(slo) >= {"p99", "starvation", "shed_before_collapse"}
    for n in ("interactive", "bulk"):
        assert slo["p99"][n]["ok"]


# ---------------------------------------------------------------------------
# SLO oracles: true positives (each family can actually fire)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_slo_starvation_fires_on_planted_defect():
    """overload + shed_bulk_bias: size-biased shedding starves the
    interactive class in every loaded window. (Tier-1 runs the same TP
    through tools/traffic_smoke.py's canary gate; this stays slow-tier.)"""
    res = run_scenario(Scenario(
        seed=3, workload={"preset": "overload"},
        defects=("shed_bulk_bias",),
    ))
    assert res.failure == "slo:starved-class:interactive"
    sv = res.details["slo"]["starvation"]
    assert sv["starved_windows"]["interactive"] >= DEFAULT_SLO["starve_windows"]


def _synthetic_stats(spec):
    stats = TrafficStats(spec)

    class _FakePlan:
        def __init__(self, index, offered, shed):
            self.index = index
            self.t0 = index * spec.window
            self.offered = offered
            self.shed_ingress = shed

    return stats, _FakePlan


def test_slo_p99_fires_on_slow_accepts():
    spec = preset("steady")
    stats, _ = _synthetic_stats(spec)
    bound_s = (2.0 * spec.patience + 10.0)  # the derived default, in s
    for _ in range(30):
        stats.complete("interactive", "accepted", latency=bound_s * 2)
    verdicts, failure = judge_slo(stats, spec)
    assert failure == "slo:p99:interactive"
    assert not verdicts["p99"]["interactive"]["ok"]


def test_slo_collapse_fires_on_silent_queueing():
    """Windows that push wire traffic but neither complete nor shed are
    the silent-queuing shape: past collapse_windows consecutive ones
    the run fails even though no safety oracle tripped."""
    spec = preset("steady")
    stats, FakePlan = _synthetic_stats(spec)
    blind = int(DEFAULT_SLO["collapse_windows"]) + 1
    for w in range(blind):
        stats.close_window(
            FakePlan(w, {"interactive": 40, "bulk": 10}, {}),
            {"interactive": 30, "bulk": 8},
        )
    verdicts, failure = judge_slo(stats, spec)
    assert failure == "slo:collapse"
    assert verdicts["shed_before_collapse"]["longest_blind_run"] >= blind


def test_slo_starvation_synthetic_needs_persistence():
    """One starved window is attribution noise; starve_windows of them
    is a verdict — the persistence threshold is what makes the oracle
    sound under retry-landing skew."""
    spec = preset("steady")
    need = int(DEFAULT_SLO["starve_windows"])

    def run_windows(n_starved):
        stats, FakePlan = _synthetic_stats(spec)
        for w in range(n_starved):
            stats._win_acc = {"interactive": 1, "bulk": 30, "byzantine": 0}
            stats.close_window(
                FakePlan(w, {"interactive": 60, "bulk": 60}, {}),
                {"interactive": 60, "bulk": 60},
            )
        return judge_slo(stats, spec)[1]

    assert run_windows(need - 1) is None
    assert run_windows(need) == "slo:starved-class:interactive"


# ---------------------------------------------------------------------------
# scale: 10^5 clients, bounded memory
# ---------------------------------------------------------------------------


def test_1e5_clients_bounded_memory():
    """Planning 10^5+ virtual clients' arrivals must stay O(classes +
    wire budget): identity is a rotating pointer, never a per-client
    object. 60 windows of smoke1e5 touch the full 110k population in a
    few MB."""
    spec = preset("smoke1e5")
    assert spec.population() >= 100_000
    from simple_pbft_tpu.workload import ArrivalGen

    tracemalloc.start()
    gen = ArrivalGen(spec, (), seed=5)
    for w in range(60):  # 30 s of 0.5 s windows
        gen.plan(w)
    touched = sum(gen.clients_touched().values())
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert touched >= 100_000
    assert peak < 8 * 1024 * 1024, f"peak {peak} bytes for 1e5 clients"


@pytest.mark.slow
def test_million_clients_open_loop_acceptance():
    """ISSUE 17 acceptance: one sim run drives >= 10^6 distinct virtual
    clients open-loop within the tier-2 wall budget, with per-class SLO
    verdicts on the result."""
    sc = Scenario(
        seed=3, horizon=360.0, workload={"preset": "million"},
        name="million_acceptance",
    )
    res = run_scenario(sc, wall_timeout=900.0)
    assert res.ok, res.failure
    assert res.coverage["clients_touched"] >= 1_000_000
    slo = res.details["slo"]
    for n in ("interactive", "bulk"):
        assert n in slo["p99"]
    assert slo["starvation"]["ok"]


# ---------------------------------------------------------------------------
# flight frames -> traffic_report
# ---------------------------------------------------------------------------


def test_traffic_report_stitches_flight_frames(tmp_path):
    sc = Scenario(seed=3, horizon=6.0, workload={"preset": "steady"},
                  flight_dir=str(tmp_path))
    res = run_scenario(sc)
    assert res.ok, res.failure
    from tools import traffic_report

    paths = sorted(str(p) for p in tmp_path.glob("flight_*.jsonl"))
    assert paths
    frames = traffic_report.load_frames(paths)
    windows = traffic_report.stitch_windows(frames)
    # the union across overlapping tails reconstructs EVERY window
    assert [w["w"] for w in windows] == list(range(len(windows)))
    assert len(windows) >= 10
    classes = traffic_report.totals_by_class(windows, frames)
    assert classes["interactive"]["acc"] > 0
    # rendering is exercised too (no live terminal needed)
    out = traffic_report.render(
        windows, traffic_report.commit_series(frames), classes
    )
    assert "totals:" in out and "interactive" in out


# ---------------------------------------------------------------------------
# schedule schema v3 (workload events ride FaultSchedule summaries)
# ---------------------------------------------------------------------------


def test_fault_schedule_v3_roundtrip_with_workload():
    sched = FaultSchedule.generate(
        seed=9, horizon=20.0, replica_ids=("r0", "r1", "r2", "r3"),
        crashes=1, bursts=2, retry_storms=1, byz_floods=1, remixes=1,
        class_names=("interactive", "bulk"),
    )
    assert sched.workload  # the draws actually happened
    d = sched.summary()
    assert d["schema"] == "fault-schedule-v3"
    assert d["workload_counts"]["burst"] == 2
    r = FaultSchedule.from_summary(d)
    assert r.summary() == d  # fixed point (the repo's replay contract)


def test_fault_schedule_v2_docs_still_parse():
    """A pre-ISSUE-17 summary (no workload keys) must load with an
    empty workload tuple and no crc warning."""
    sched = FaultSchedule.generate(
        seed=9, horizon=20.0, replica_ids=("r0", "r1"), crashes=1,
    )
    d = sched.summary()
    assert "workload" not in d  # fault-only summaries stay v2-shaped
    v2 = dict(d)
    v2["schema"] = "fault-schedule-v2"
    r = FaultSchedule.from_summary(v2)
    assert r.workload == ()
    # summary() rounds event times; compare at its precision
    assert tuple(e.t for e in r.events) == tuple(
        round(e.t, 3) for e in sched.events)


def test_zero_workload_draws_leave_fault_stream_identical():
    """Workload draws happen AFTER every fault draw, so arming the
    kwargs with zero counts is byte-invisible to the fault stream —
    pre-ISSUE-17 seeds replay unchanged."""
    kw = dict(seed=4, horizon=15.0, replica_ids=("r0", "r1", "r2"),
              crashes=1, partition_windows=2)
    a = FaultSchedule.generate(**kw)
    b = FaultSchedule.generate(
        **kw, bursts=0, retry_storms=0, byz_floods=0, remixes=0,
        class_names=("interactive", "bulk"),
    )
    assert a.events == b.events
    assert b.workload == ()


# ---------------------------------------------------------------------------
# presets / spec docs
# ---------------------------------------------------------------------------


def test_preset_doc_roundtrip_with_overrides():
    spec = spec_from_doc({"preset": "overload", "shed_watermark": 12})
    assert spec.shed_watermark == 12
    base = preset("overload")
    assert [c.name for c in spec.classes] == [c.name for c in base.classes]
    # every preset materializes and carries at least one honest class
    for name in PRESETS:
        p = preset(name)
        assert p.honest(), name
        assert p.population() > 0


def test_workload_scenario_doc_roundtrip():
    """Scenario.workload rides artifact docs verbatim (the repro path:
    scenario_from_artifact must rebuild the same plane)."""
    from simple_pbft_tpu.sim import artifact_doc, scenario_from_artifact

    sc = Scenario(seed=5, horizon=4.0,
                  workload={"preset": "steady", "pool": 2})
    res = run_scenario(sc)
    doc = artifact_doc(sc, res)
    sc2 = scenario_from_artifact(doc)
    assert sc2.workload == sc.workload
    assert run_scenario(sc2).fingerprint == res.fingerprint
