"""Deterministic simulation runtime (ISSUE 13): virtual clock, trace
determinism, virtual-vs-wall equivalence, oracles, the schedule
minimizer, and the checked-in search-found repro artifacts.

Everything here runs in VIRTUAL time (sim_run): minutes of scenario
burn milliseconds of wall clock, and a saturated CI host cannot shift
any timer — the interleavings are a pure function of the seeds."""

import asyncio
import json
import os
import time
from dataclasses import replace

import pytest

from simple_pbft_tpu import clock
from simple_pbft_tpu.faults import FaultEvent, FaultSchedule
from simple_pbft_tpu.sim import (
    SIM_START,
    Scenario,
    SimLoop,
    SimStall,
    minimize,
    run_scenario,
    scenario_from_artifact,
    sim_run,
)

REPROS = os.path.join(os.path.dirname(__file__), "sim_repros")


def load_repro(name):
    with open(os.path.join(REPROS, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the virtual clock itself
# ---------------------------------------------------------------------------


def test_virtual_time_jumps_instead_of_sleeping():
    async def main():
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.sleep(300)  # five virtual minutes
        await clock.sleep(45)
        return loop.time() - t0

    w0 = time.monotonic()
    elapsed = sim_run(main())
    wall = time.monotonic() - w0
    assert elapsed == pytest.approx(345.0, abs=1e-6)
    assert wall < 5.0  # 345 virtual seconds for ~free


def test_clock_seam_modes():
    # wall mode: now() is a plain monotonic read
    assert not clock.simulated()
    assert abs(clock.now() - time.monotonic()) < 1.0

    async def main():
        assert clock.simulated()
        loop = asyncio.get_running_loop()
        assert clock.now() == loop.time()
        # off_thread runs INLINE under simulation (no thread race
        # against virtual time) — observable via thread identity
        import threading

        tid = await clock.off_thread(threading.get_ident)
        assert tid == threading.get_ident()
        # timestamps derive from virtual time against a fixed epoch
        ts1 = clock.timestamp_us()
        await clock.sleep(1.0)
        ts2 = clock.timestamp_us()
        assert ts2 - ts1 == pytest.approx(1_000_000, abs=2)

    sim_run(main())
    assert not clock.simulated()  # restored after the run


def test_timer_ordering_is_preserved():
    fired = []

    async def main():
        loop = asyncio.get_running_loop()
        loop.call_later(2.0, fired.append, "b")
        loop.call_later(1.0, fired.append, "a")
        loop.call_later(3.0, fired.append, "c")
        await asyncio.sleep(5.0)

    sim_run(main())
    assert fired == ["a", "b", "c"]


def test_sim_stall_guard():
    async def wedge():
        await asyncio.get_running_loop().create_future()  # never set

    with pytest.raises(SimStall):
        sim_run(wedge())


def test_wall_timeout_guard():
    async def runaway():
        while True:  # infinite virtual events: no virtual bound trips
            await asyncio.sleep(0.01)

    with pytest.raises(SimStall, match="wall timeout"):
        sim_run(runaway(), wall_timeout=1.0)


# ---------------------------------------------------------------------------
# trace determinism (acceptance: same seed => byte-identical trace)
# ---------------------------------------------------------------------------

STORM = dict(
    n=4, requests=8, horizon=10.0, probes=2,
    gen=dict(crashes=1, partition_windows=1, drop_windows=1),
)


def test_same_seed_byte_identical_trace():
    sc = Scenario(seed=11, **STORM)
    a = run_scenario(sc)
    b = run_scenario(sc)
    assert a.fingerprint == b.fingerprint
    assert a.coverage == b.coverage
    assert a.schedule == b.schedule


def test_different_seed_different_trace():
    a = run_scenario(Scenario(seed=11, **STORM))
    b = run_scenario(Scenario(seed=12, **STORM))
    assert a.fingerprint != b.fingerprint


def test_faulty_scenario_oracles_hold():
    res = run_scenario(Scenario(seed=11, **STORM))
    assert res.ok, res.failure
    assert res.coverage["crashes"] == 1
    assert res.committed > 0


def test_equivocating_primary_convicted_under_sim():
    """The audit plane works inside the simulation: a byzantine
    injector's forks are observed, safety holds, and the violations
    land on the INJECTED target only."""
    res = run_scenario(Scenario(
        seed=5, n=4, requests=8, horizon=10.0, probes=1,
        gen=dict(equivocators=1), verify_signatures=True,
    ))
    assert res.ok, res.failure  # divergence would be a safety failure
    assert res.byzantine  # the injector armed
    assert res.coverage["violations"] > 0  # ...and was caught


# ---------------------------------------------------------------------------
# virtual-vs-wall equivalence (acceptance)
# ---------------------------------------------------------------------------


def test_virtual_vs_wall_equivalence():
    """The same fault-free scenario under the virtual clock and under
    the real clock commits the same operation sequence to the same
    application state — simulation changes TIME, not the protocol."""
    from simple_pbft_tpu.sim import SimTrace, _drive

    sc = Scenario(seed=3, n=4, requests=5, horizon=3.0, probes=1,
                  drain=20.0, probe_patience=20.0)

    def wall_run():
        async def main():
            loop = asyncio.get_running_loop()
            return await _drive(sc, SimTrace(loop, base=loop.time()))

        return asyncio.run(main())

    async def sim_main():
        loop = asyncio.get_running_loop()
        return await _drive(sc, SimTrace(loop, base=SIM_START))

    wall = wall_run()
    sim = sim_run(sim_main())
    assert wall.ok and sim.ok, (wall.failure, sim.failure)
    # same per-replica application outcome (digests computed over the
    # final KV state) and the same commit count
    assert wall.app_digests == sim.app_digests
    assert wall.committed == sim.committed
    # both runs' honest replicas agreed internally (the safety oracle
    # passed in both worlds)
    assert wall.coverage["violations"] == sim.coverage["violations"] == 0


# ---------------------------------------------------------------------------
# wall-clock compression (acceptance: wan3dc minutes -> seconds)
# ---------------------------------------------------------------------------


def test_wan3dc_compression():
    """An n=7 wan3dc committee with a partition healing mid-run — the
    scenario class that costs minutes of WALL time in the wan-smoke CI
    job — finishes in seconds of wall clock under the virtual clock,
    having simulated the full virtual horizon."""
    sc = Scenario(
        seed=9, n=7, requests=10, horizon=45.0, probes=2,
        gen=dict(wan="wan3dc", partition_windows=1, crashes=1),
    )
    w0 = time.monotonic()
    res = run_scenario(sc)
    wall = time.monotonic() - w0
    assert res.ok, res.failure
    assert res.vtime_s >= 45.0  # the whole horizon was simulated
    assert wall < 30.0  # seconds of wall for minutes of virtual time
    assert res.committed > 0


# ---------------------------------------------------------------------------
# replay tuple (satellite: summary <-> from_summary)
# ---------------------------------------------------------------------------


def test_schedule_summary_replay_tuple():
    s = FaultSchedule.parse(
        "seed=9,crashes=2,partition=2.0:r0|r1<>r2|r3:1.5,shape=lossy",
        horizon=20.0, replica_ids=["r0", "r1", "r2", "r3"],
    )
    doc = s.summary()
    # the complete replay tuple rides every ledger line
    assert doc["schema"] == FaultSchedule.SUMMARY_SCHEMA
    assert doc["seed"] == 9 and doc["horizon_s"] == 20.0
    assert isinstance(doc["kinds_crc"], int)
    assert len(doc["events"]) == len(s.events)
    # reconstruction is a fixed point of the wire form
    r = FaultSchedule.from_summary(doc)
    assert r.summary() == doc
    assert [e.kind for e in r.events] == [e.kind for e in s.events]
    # and a drifted kind registry fails loudly instead of lying
    bad = dict(doc, events=[{"t": 1.0, "kind": "not_a_kind"}])
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSchedule.from_summary(bad)


# ---------------------------------------------------------------------------
# minimizer (acceptance: known-bad schedule shrinks to <= a fixed count)
# ---------------------------------------------------------------------------


def test_minimizer_shrinks_known_bad_schedule(monkeypatch):
    """Start from the checked-in slow-failover repro (2 essential
    events at a tightened patience) buried under noise events; ddmin
    must strip the noise back down while preserving the failure.

    The ISSUE 14 dead-target fast-path FIXED the tail this repro
    records (see test_slow_failover_tail_repro_fast_failover), so to
    keep a known-bad schedule for the minimizer to converge on, the
    fast-path is disabled here — this test exercises ddmin, not the
    failover ladder."""
    from simple_pbft_tpu.consensus.viewchange import ViewChanger

    monkeypatch.setattr(
        ViewChanger, "primary_evidence_dead", lambda self, view: False
    )
    doc = load_repro("slow_failover_tail.json")
    base = scenario_from_artifact(doc)
    # tighten the oracle so the KNOWN tail counts as the failure under
    # minimization (the production oracle hunts wedges; this test hunts
    # the minimizer's convergence)
    base = replace(base, probe_patience=90.0, probes=1, drain=30.0)
    noisy = list(base.schedule.events) + [
        FaultEvent(t=5.0, kind="drop_window", duration=2.0, magnitude=0.01),
        FaultEvent(t=20.0, kind="delay_window", duration=2.0,
                   magnitude=0.01),
        FaultEvent(t=55.0, kind="heal"),
    ]
    sc = replace(base, schedule=FaultSchedule(
        seed=base.schedule.seed, horizon=base.schedule.horizon,
        events=tuple(sorted(noisy, key=lambda e: (e.t, e.kind))),
    ))
    assert not run_scenario(sc).ok  # still failing with the noise
    min_sc, min_res, runs = minimize(sc, max_runs=60)
    assert not min_res.ok
    assert len(min_sc.schedule.events) <= 4  # the fixed-count bound
    assert runs <= 60


# ---------------------------------------------------------------------------
# checked-in search-found repros (acceptance: found by search, minimized,
# regression-tested)
# ---------------------------------------------------------------------------


def test_slow_failover_tail_repro_fast_failover():
    """The coverage-guided search found (and ddmin minimized) a
    crash+partition interleaving that parked every live replica on a
    crashed primary's target view for MINUTES of virtual time (the
    backoff ladder retransmitted-then-escalated at 60 s rungs; probe_s
    was 300+ when the repro was checked in). The ISSUE 14 dead-target
    fast-path fixes it: heartbeat silence marks the crashed primary
    evidence-dead, escalation skips its views, and the same schedule
    now converges promptly. This replay is the regression gate — the
    ladder reappearing flips probe_s back over the bound. Triage:
    docs/SCENARIOS.md."""
    sc = scenario_from_artifact(load_repro("slow_failover_tail.json"))
    # the artifact records the patience the search ran at (300 s, once
    # inside the tail); judge at the calibrated wedge bound
    res = run_scenario(replace(sc, probe_patience=600.0))
    assert res.ok, res.failure  # converges within the wedge oracle
    # the fixed ladder recovers fast: probe_s bounded well under the
    # pre-fix 300+ s tail (measured 0 s with the fast-path; 90 is the
    # old test's "pathologically slow" threshold, now the ceiling)
    assert res.coverage["probe_s"] <= 90, res.coverage


def test_planted_defect_wedge_repro():
    """End-to-end proof the search loop finds real bugs: the
    sync_abandon_leak defect (a once-real PR 7 wedge, re-armable via
    statesync.DEFECTS) was found by coverage-guided search — NOT by a
    hand-written scenario — minimized, and checked in. With the defect
    armed the minimized schedule wedges the committee (statesync
    abandons, pending_sync leaks, the dedup guard swallows every
    re-trigger); on the FIXED code the same schedule passes."""
    doc = load_repro("sync_abandon_wedge.json")
    sc = scenario_from_artifact(doc)
    assert "sync_abandon_leak" in sc.defects  # recorded as found
    wedged = run_scenario(sc)
    assert not wedged.ok
    assert wedged.failure_class == "liveness"
    # the same schedule on the fixed code: no wedge
    fixed = run_scenario(replace(sc, defects=()))
    assert fixed.ok, fixed.failure


def test_spec_rollback_viewchange_repro():
    """ISSUE 15: rollback-under-view-change, both ways. The schedule
    (ddmin-minimized: wan3dc shaping + a spec_divergence primary + the
    victim's outbound cut) makes a replica speculate a PREPARED block
    whose slot the NEW-VIEW then no-op-fills — a real rollback fires on
    the fixed code and the run is clean (zero safety-oracle failures,
    zero honest-node audit evidence). With the ``spec_leak`` planted
    defect armed (rollback leaves checkpoint snapshots reading the
    speculative fork), the SAME schedule fails the safety oracle:
    honest checkpoint digests diverge and the audit plane's I2
    invariant accuses honest nodes. Triage: docs/SCENARIOS.md."""
    doc = load_repro("spec_rollback_viewchange.json")
    sc = scenario_from_artifact(doc)
    assert "spec_leak" in sc.defects  # recorded as found
    leaky = run_scenario(sc)
    assert not leaky.ok
    assert leaky.failure_class == "safety", leaky.failure
    # the same schedule on the fixed code: clean, with the rollback
    # genuinely exercised (this is a ROLLBACK repro, not just a leak
    # repro — spec slots were discarded on the NEW-VIEW install)
    fixed = run_scenario(replace(sc, defects=()))
    assert fixed.ok, fixed.failure
    assert fixed.coverage.get("spec_rolled_back", 0) > 0
    assert fixed.coverage.get("spec_executed", 0) > 0


# ---------------------------------------------------------------------------
# explorer plumbing
# ---------------------------------------------------------------------------


def test_explorer_sweep_smoke(tmp_path):
    """A tiny in-process sweep: deterministic selfcheck passes, runs
    complete, coverage keys accumulate."""
    import argparse

    from tools import sim_explore

    args = argparse.Namespace(
        mode="sweep", runs=4, seed_base=77, search_seed=1, n=4,
        clients=1, requests=6, horizon=6.0, probes=1, view_timeout=1.0,
        checkpoint_interval=8, watermark_window=32, signed=False,
        qc=False, defect=None, selfcheck=2, audit_every=0,
        max_failures=1, minimize_budget=10, out=str(tmp_path),
        progress=False,
    )
    stats = sim_explore.mode_sweep(args)
    assert stats["runs"] == 6  # 4 + 2 selfcheck re-runs
    assert stats["selfcheck_ok"] is True
    assert stats["failures"] == []
    assert len(stats["coverage_keys"]) >= 1


def test_explorer_mutations_stay_in_registry():
    """Every mutated schedule round-trips through the replay tuple —
    mutation can never invent an event the kind registry (and so a
    ledger replay) does not understand."""
    import random

    from tools import sim_explore

    rng = random.Random(3)
    ids = ("r0", "r1", "r2", "r3")
    sched = FaultSchedule.generate(seed=1, horizon=30.0, crashes=1,
                                   partition_windows=1, replica_ids=ids)
    for _ in range(60):
        sched = sim_explore.mutate(rng, sched, ids)
        FaultSchedule.from_summary(sched.summary())  # must not raise
    assert all(0 <= e.t <= 0.9 * 30.0 for e in sched.events)


def test_explorer_workload_mutations_stay_in_registry():
    """ISSUE 17: the load-shape operators (w_burst/w_flood/w_storm/
    w_remix/w_shift/w_scale/w_drop) obey the same closure — every
    mutant's summary replays as fault-schedule-v3, workload kinds stay
    inside the WorkloadEvent registry, and times stay in-horizon."""
    import random

    from simple_pbft_tpu.workload import WORKLOAD_KINDS
    from tools import sim_explore

    rng = random.Random(5)
    ids = ("r0", "r1", "r2", "r3")
    sched = FaultSchedule.generate(
        seed=2, horizon=30.0, crashes=1, replica_ids=ids,
        bursts=1, class_names=("interactive", "bulk"),
    )
    saw_workload = False
    for _ in range(80):
        sched = sim_explore.mutate(rng, sched, ids, workload=True,
                                   wclasses=("interactive", "bulk"))
        rt = FaultSchedule.from_summary(sched.summary())
        assert rt.summary() == sched.summary()  # fixed point
        saw_workload = saw_workload or bool(sched.workload)
    assert saw_workload  # the operators actually fired
    assert all(e.kind in WORKLOAD_KINDS for e in sched.workload)
    assert all(0 <= e.t <= 0.9 * 30.0 for e in sched.workload)


@pytest.mark.slow
def test_overload_starvation_repro():
    """ISSUE 17, both ways: the load-shape search (sim_explore --mode
    search --workload overload) found the planted shed_bulk_bias
    defect's fairness hole — size-biased overload shedding starves the
    interactive class — and ddmin minimized the shape to a single
    demand burst with zero fault events. Armed, the starvation SLO
    oracle fails the run; on fixed code the same shape passes clean."""
    doc = load_repro("overload_starvation.json")
    sc = scenario_from_artifact(doc)
    assert "shed_bulk_bias" in sc.defects  # recorded as found
    assert sc.workload  # the repro carries its load shape
    starved = run_scenario(sc)
    assert not starved.ok
    assert starved.failure.startswith("slo:starved-class"), starved.failure
    fixed = run_scenario(replace(sc, defects=()))
    assert fixed.ok, fixed.failure
    # and the clean run still genuinely overloads (non-trivial TN)
    assert fixed.details["traffic"]["shed"] > 0
