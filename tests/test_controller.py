"""Self-driving perf plane (ISSUE 19): knob registry bounds, decision
rule true-positive/true-negative behavior per verdict family, the
oscillation guard, hash-chained decision-ledger determinism + replay,
the speculation depth gate, and the bench_gate ``controller.*`` rows
with their pathological-knob canary.

Rule tests drive ``KnobController.tick(snap)`` synchronously with
synthetic telemetry snapshots — no loop, no committee. Ledger
determinism runs the full sim twice on the virtual clock and compares
file bytes."""

import asyncio
import json

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.controller import (
    CALM_TICKS,
    GENESIS,
    Knob,
    KnobController,
    KnobRegistry,
    RULES,
    RULES_BY_NAME,
    WIN_P99_FAST_MS,
    WIN_P99_STORM_MS,
    chain_hash,
    parse_decision_ledger,
    registry_for_committee,
    replay_ledger,
)
from simple_pbft_tpu.sim import Scenario, run_scenario
from simple_pbft_tpu.telemetry import BENCH_SCHEMA_VERSION, SCHEMA_VERSION


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# synthetic harness: toy registry + snapshot builder
# ---------------------------------------------------------------------------


def _toy_registry():
    state = {
        "replica.shed_watermark": 64,
        "qc.close_window_ms": 2.0,
        "verify.max_batch": 512,
        "verify.cpu_cutoff": -1,
        "spec.max_depth": 64,
    }
    ladders = {
        "replica.shed_watermark": (8, 16, 32, 64, 96, 128, 192, 256),
        "qc.close_window_ms": (0.5, 1.0, 2.0, 4.0, 8.0),
        "verify.max_batch": (64, 128, 256, 512),
        "verify.cpu_cutoff": (16, 64, 256, 1024, -1),
        "spec.max_depth": (4, 16, 64),
    }
    reg = KnobRegistry()
    for name, choices in ladders.items():
        reg.register(Knob(
            name=name, doc="toy", choices=choices,
            get=(lambda n=name: state[n]),
            set=(lambda v, n=name: state.__setitem__(n, v)),
        ))
    return reg, state


def _snap(*, offered=80.0, accepted=80.0, win_p99=50.0, shed=0,
          pending=0, rollbacks=0, verify=None, qc=None):
    return {
        "traffic": {
            "offered_req_s": offered,
            "accepted_req_s": accepted,
            "worst_p99_ms": win_p99,
            "classes": {
                "interactive": {"byzantine": False, "p99_ms": win_p99},
                "byz": {"byzantine": True, "p99_ms": 9999.0},
            },
            "windows_tail": [{"classes": {
                "interactive": {"p99_ms": win_p99},
                "byz": {"p99_ms": 9999.0},
            }}],
        },
        "replica": {
            "pending_requests": pending, "relay_buffer": 0,
            "metrics": {"messages_shed": shed,
                        "spec_rollbacks": rollbacks},
        },
        "verify": verify or {},
        "qc_lane": qc or {},
    }


def _controller(tmp_path=None, **kw):
    reg, state = _toy_registry()
    kw.setdefault("cooldown_ticks", 0)
    path = str(tmp_path / "t.knobs.jsonl") if tmp_path else None
    ctl = KnobController(reg, dict, ledger_path=path, **kw)
    return ctl, state


# ---------------------------------------------------------------------------
# traffic family: storm cut / served-inflow relax
# ---------------------------------------------------------------------------


def test_storm_cut_fires_on_shed_wave():
    """TP: a shed wave far above the watermark scale reads as storm
    and steps the watermark DOWN, even with the window p99 fast (the
    fail-fast brownout direction)."""
    ctl, state = _controller()
    ctl.tick(_snap(shed=0))  # baseline for the cumulative counters
    ctl.tick(_snap(shed=1000, offered=600.0, accepted=100.0))
    assert state["replica.shed_watermark"] == 32
    assert ctl._last_info["rule"] == "storm_backlog"


def test_storm_cut_fires_on_window_p99():
    """TP: queue buildup shows as the last closed window's honest p99
    inflating — cut even when nothing is shed yet. The byzantine
    class's p99 must NOT count (it is 9999 in every snapshot here)."""
    ctl, state = _controller()
    ctl.tick(_snap())
    ctl.tick(_snap(win_p99=WIN_P99_STORM_MS + 50))
    assert state["replica.shed_watermark"] == 32
    assert ctl._last_info["rule"] == "storm_backlog"


def test_no_cut_in_dead_band():
    """TN: a window p99 between FAST and STORM with no shed wave moves
    nothing — the dead band is the hysteresis."""
    ctl, state = _controller()
    ctl.tick(_snap(win_p99=WIN_P99_FAST_MS + 20))
    ctl.tick(_snap(win_p99=WIN_P99_STORM_MS - 20))
    assert state["replica.shed_watermark"] == 64
    assert ctl.actions == 0


def test_relax_requires_served_inflow():
    """The served-ratio interlock: sheds with fresh inflow NOT served
    (a strangled backlog) must never relax the watermark; the same
    shed trickle with inflow fully served relaxes it."""
    ctl, state = _controller()
    ctl.tick(_snap(shed=0))
    # TN: shedding while only a quarter of fresh inflow is served
    ctl.tick(_snap(shed=40, offered=80.0, accepted=20.0))
    assert state["replica.shed_watermark"] == 64
    # TP: shedding while inflow is served => the watermark is trimming
    # benign traffic; step UP
    ctl.tick(_snap(shed=80, offered=80.0, accepted=80.0))
    assert state["replica.shed_watermark"] == 96
    assert ctl._last_info["rule"] == "drain_relax"


# ---------------------------------------------------------------------------
# devledger / costmodel / qc / spec families
# ---------------------------------------------------------------------------


def test_pad_waste_shrinks_batch():
    ctl, state = _controller()
    ctl.tick(_snap())
    ctl.tick(_snap(verify={
        "pending_items": 0, "max_pending": 4096,
        "device": {"occupancy": 0.1, "pad_waste_pct": 70.0},
    }))
    assert state["verify.max_batch"] == 256
    assert ctl._last_info["rule"] == "pad_waste"


def test_queue_pressure_grows_batch():
    ctl, state = _controller()
    ctl.tick(_snap())
    ctl.tick(_snap(verify={
        "pending_items": 3500, "max_pending": 4096,
        "device": {"occupancy": 0.9, "pad_waste_pct": 5.0},
    }))
    assert state["verify.max_batch"] == 512  # already at the ceiling
    assert ctl.actions == 0  # no-op step is skipped, not ledgered
    state["verify.max_batch"] = 256
    ctl.tick(_snap(verify={
        "pending_items": 3500, "max_pending": 4096,
        "device": {"occupancy": 0.9, "pad_waste_pct": 5.0},
    }))
    assert state["verify.max_batch"] == 512
    assert ctl._last_info["rule"] == "queue_wait"


def test_host_cpu_path_lowers_cutoff():
    """TP: most verify items landing on the CPU pass with a device
    present reads as a mis-set cutoff — step it DOWN (toward forcing
    the device path)."""
    ctl, state = _controller()
    ctl.tick(_snap())
    ctl.tick(_snap(verify={
        "pending_items": 10, "max_pending": 4096,
        "cpu_pass_items": 900, "device_pass_items": 100,
        "device": {"occupancy": 0.9},
    }))
    assert state["verify.cpu_cutoff"] == 1024
    assert ctl._last_info["rule"] == "host_cpu_path"


def test_qc_idle_needs_calm_ticks():
    """Hysteresis: an empty QC lane only narrows the close window
    after CALM_TICKS quiet ticks — one idle snapshot is not calm."""
    ctl, state = _controller()
    ctl.tick(_snap(qc={"pending": 0, "max_pending": 4096}))
    assert state["qc.close_window_ms"] == 2.0
    for _ in range(CALM_TICKS):
        ctl.tick(_snap(qc={"pending": 0, "max_pending": 4096}))
    assert state["qc.close_window_ms"] == 1.0
    assert ctl._last_info["rule"] == "qc_idle"


def test_spec_churn_shrinks_depth():
    ctl, state = _controller()
    ctl.tick(_snap(rollbacks=0))
    ctl.tick(_snap(rollbacks=3))
    assert state["spec.max_depth"] == 16
    assert ctl._last_info["rule"] == "spec_churn"
    # TN: no NEW rollbacks -> the cumulative counter no longer moves
    # the knob
    ctl.tick(_snap(rollbacks=3))
    assert state["spec.max_depth"] == 16


# ---------------------------------------------------------------------------
# oscillation guard
# ---------------------------------------------------------------------------


def test_oscillation_guard_freezes_reversal(tmp_path):
    """A direction reversal on the same knob within the oscillation
    window freezes the knob (NOT applied), counts an oscillation, and
    writes a ``guard`` ledger record."""
    ctl, state = _controller(tmp_path, osc_window_ticks=10,
                             freeze_ticks=5)
    ctl.ledger.append("open", knobs=ctl.registry.values())
    ctl.tick(_snap(shed=0))
    ctl.tick(_snap(shed=1000, offered=600.0, accepted=100.0))  # cut
    assert state["replica.shed_watermark"] == 32
    ctl.tick(_snap(shed=1040, offered=80.0, accepted=80.0))  # reversal
    assert state["replica.shed_watermark"] == 32  # frozen, not applied
    assert ctl.oscillations == 1
    ctl.tick(_snap(shed=1080, offered=80.0, accepted=80.0))
    assert state["replica.shed_watermark"] == 32  # still frozen
    run(ctl.stop())
    recs, err = parse_decision_ledger(str(tmp_path / "t.knobs.jsonl"))
    assert err == ""
    kinds = [r["kind"] for r in recs]
    assert "guard" in kinds
    guard = next(r for r in recs if r["kind"] == "guard")
    assert guard["knob"] == "replica.shed_watermark"


# ---------------------------------------------------------------------------
# knob registry bounds
# ---------------------------------------------------------------------------


def test_registry_rejects_off_ladder_values():
    reg, state = _toy_registry()
    with pytest.raises(ValueError):
        reg.set("replica.shed_watermark", 77)
    with pytest.raises(KeyError):
        reg.set("no.such.knob", 1)
    reg.set("replica.shed_watermark", 128)
    assert state["replica.shed_watermark"] == 128


def test_registry_steps_clamp_at_ladder_edges():
    reg, state = _toy_registry()
    reg.set("verify.max_batch", 512)
    assert reg.peek_step("verify.max_batch", +1) == (512, 512)
    reg.set("verify.max_batch", 64)
    assert reg.peek_step("verify.max_batch", -1) == (64, 64)


def test_committee_registry_caps_batch_at_warmed_ceiling():
    """PBL006 by construction: the batch-shape ladders top out at the
    constructor value — the controller can never request a shape that
    was not warmed, so zero post-warm compiles is structural."""
    com = LocalCommittee.build(n=4)
    reg = registry_for_committee(com)
    assert "replica.shed_watermark" in reg
    wm0 = com.replicas[0].shed_watermark
    assert max(reg.knob("replica.shed_watermark").choices) == wm0 * 4
    if "verify.max_batch" in reg:
        k = reg.knob("verify.max_batch")
        assert max(k.choices) == com.replicas[0].verifier._max_batch
    # setters fan out to every replica
    lo = min(reg.knob("replica.shed_watermark").choices)
    reg.set("replica.shed_watermark", lo)
    assert all(r.shed_watermark == lo for r in com.replicas)
    snap = reg.snapshot_block()
    assert snap["knobs"]["replica.shed_watermark"]["value"] == lo


# ---------------------------------------------------------------------------
# decision ledger: chain, tamper, sim determinism, replay
# ---------------------------------------------------------------------------


def test_ledger_chain_verifies_and_detects_tamper(tmp_path):
    ctl, state = _controller(tmp_path)
    ctl.ledger.append("open", knobs=ctl.registry.values())
    ctl.tick(_snap(shed=0))
    ctl.tick(_snap(shed=1000, offered=600.0, accepted=100.0))
    run(ctl.stop())
    path = tmp_path / "t.knobs.jsonl"
    recs, err = parse_decision_ledger(str(path))
    assert err == "" and len(recs) >= 3
    assert recs[0]["prev"] == GENESIS
    for r in recs:
        assert chain_hash(r) == r["h"]
    ok, rerr = replay_ledger(recs)
    assert ok, rerr
    # flip one recorded trigger signal: the chain must break
    lines = path.read_text().splitlines()
    doc = json.loads(lines[1])
    doc["trigger"]["shed_delta"] = 0
    lines[1] = json.dumps(doc, sort_keys=True)
    path.write_text("\n".join(lines) + "\n")
    _, err2 = parse_decision_ledger(str(path))
    assert "chain break" in err2 or "hash" in err2


def test_replay_rejects_unrefireable_action(tmp_path):
    """Replay re-evaluates every action's rule over its recorded
    trigger: an action whose trigger does not fire its rule is a
    forged ledger, even with a valid hash chain."""
    ctl, state = _controller(tmp_path)
    led = ctl.ledger
    led.append("open", knobs=ctl.registry.values())
    led.append(
        "action", tick=1, rule="storm_backlog", family="traffic",
        knob="replica.shed_watermark", direction=-1, old=64, new=48,
        trigger={"shed_delta": 0, "win_p99_ms": 10.0, "backlog": 0,
                 "shed_watermark": 64},
    )
    led.close()
    recs, err = parse_decision_ledger(str(tmp_path / "t.knobs.jsonl"))
    assert err == ""
    ok, rerr = replay_ledger(recs)
    assert not ok and "re-fire" in rerr


def test_sim_decision_ledger_is_seed_deterministic(tmp_path):
    """Same seed, same scenario => byte-identical decision ledger (the
    controller runs on the virtual clock; every signal it reads is a
    pure function of the seed), and the ledger replays."""
    def go(name):
        sc = Scenario(
            n=4, seed=5, horizon=4.0, workload={"preset": "steady"},
            controller={"interval": 0.5},
            flight_dir=str(tmp_path / name), name=name,
        )
        res = run_scenario(sc)
        assert res.ok, res.failure
        path = tmp_path / name / f"{name}.knobs.jsonl"
        return path.read_bytes()

    b1, b2 = go("a"), go("b")
    assert b1 == b2
    recs, err = parse_decision_ledger(
        str(tmp_path / "a" / "a.knobs.jsonl"))
    assert err == ""
    ok, rerr = replay_ledger(recs)
    assert ok, rerr
    assert recs[0]["kind"] == "open" and recs[-1]["kind"] == "close"


# ---------------------------------------------------------------------------
# telemetry: knobs block is additive
# ---------------------------------------------------------------------------


def test_knobs_block_rides_snapshot_without_schema_bump():
    com = LocalCommittee.build(n=4)
    reg = com.attach_knobs()
    snap = com.node_telemetry(com.replicas[0].id).snapshot()
    assert snap["schema_version"] == SCHEMA_VERSION == 1
    kb = snap["knobs"]
    assert kb["schema"] == 1
    assert "replica.shed_watermark" in kb["knobs"]
    k = kb["knobs"]["replica.shed_watermark"]
    assert k["lo"] <= k["value"] <= k["hi"]
    # no registry attached -> no knobs key at all (additive surface)
    com2 = LocalCommittee.build(n=4)
    snap2 = com2.node_telemetry(com2.replicas[0].id).snapshot()
    assert "knobs" not in snap2
    assert reg.values()  # silence unused warning


# ---------------------------------------------------------------------------
# speculation depth gate
# ---------------------------------------------------------------------------


def test_spec_depth_gate_skips_when_full():
    com = LocalCommittee.build(n=4)
    r = com.replicas[0]
    eng = r.spec
    assert eng.max_depth == 64  # constructor default
    eng.max_depth = 2
    eng.slots[101] = object()
    eng.slots[102] = object()

    class _Inst:
        seq = 103
        block = [{"op": "x"}]
        digest = "d"

    before = r.metrics["spec_skipped_depth"]
    assert eng.on_prepared(_Inst()) is None
    assert r.metrics["spec_skipped_depth"] == before + 1
    assert 103 not in eng.slots
    assert eng.snapshot()["max_depth"] == 2


# ---------------------------------------------------------------------------
# bench_gate controller.* rows + pathological canary
# ---------------------------------------------------------------------------


def _ctl_bench_line(**over):
    base = {
        "swing_e2e_p99_ms": 124, "swing_p99_ms": 124.8,
        "accepted": 1048, "offered": 4680, "actions": 5,
        "oscillations": 0, "post_warm_compiles": 0,
        "swing_p99_vs_best_fixed": 0.043,
        "accepted_vs_best_fixed": 1.79,
    }
    base.update(over)
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "cell": "knob_campaign_ctl",
        "controller": base,
    }


def _ctl_reference():
    ref = _ctl_bench_line()
    ref["gate"] = {
        "max": {"controller.swing_p99_vs_best_fixed": 1.0,
                "controller.oscillations": 4,
                "controller.post_warm_compiles": 0},
        "min": {"controller.accepted_vs_best_fixed": 1.0,
                "controller.actions": 2},
    }
    ref["gate_mode"] = "floors"
    return ref


def test_bench_gate_passes_healthy_controller_cell():
    from tools.bench_gate import run_gate

    rep = run_gate([_ctl_bench_line()], [_ctl_reference()])
    assert rep["ok"], rep


def test_bench_gate_canary_catches_pathological_knobs():
    """Negative test: a controller run that lost to the fixed sweep,
    oscillated, or recompiled post-warm MUST flag — a gate that cannot
    fail is not a gate."""
    from tools.bench_gate import run_gate

    for bad, metric in (
        ({"swing_p99_vs_best_fixed": 1.6},
         "controller.swing_p99_vs_best_fixed"),
        ({"accepted_vs_best_fixed": 0.4},
         "controller.accepted_vs_best_fixed"),
        ({"oscillations": 9}, "controller.oscillations"),
        ({"post_warm_compiles": 2}, "controller.post_warm_compiles"),
        ({"actions": 0}, "controller.actions"),
    ):
        rep = run_gate([_ctl_bench_line(**bad)], [_ctl_reference()])
        assert not rep["ok"]
        assert any(r["metric"] == metric for r in rep["regressions"]), rep


def test_rules_catalog_is_replay_complete():
    """Every rule the controller can act on is resolvable by name for
    replay, and its trigger keys are exactly its ``needs`` — the
    ledger alone must reconstruct any decision."""
    assert set(RULES_BY_NAME) == {r.name for r in RULES}
    for r in RULES:
        view = {k: 1.0 for k in r.needs}
        trig = r.trigger(view)
        assert set(trig) == set(r.needs)
