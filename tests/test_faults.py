"""Deterministic fault injection (simple_pbft_tpu/faults.py): schedule
determinism, CLI-spec parsing, the verifier-seam wrappers, and injector
semantics (quorum floor, window restore)."""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.faults import (
    FaultEvent,
    FaultInjector,
    FaultSchedule,
    SlowVerifier,
    StallableDevice,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# schedule determinism (the acceptance-criteria replay property)
# ---------------------------------------------------------------------------


def test_same_seed_replays_identically():
    """The core reproducibility contract: generate() is a pure function
    of its arguments — same seed, same schedule, byte for byte."""
    kw = dict(
        horizon=30.0, crashes=3, drop_windows=2, delay_windows=1,
        slow_verifier_windows=1, device_stalls=2,
        equivocators=1, checkpoint_forkers=1,
        replica_ids=[f"r{i}" for i in range(16)],
    )
    a = FaultSchedule.generate(seed=42, **kw)
    b = FaultSchedule.generate(seed=42, **kw)
    assert a == b
    assert a.events == b.events
    assert [e.to_dict() for e in a.events] == [e.to_dict() for e in b.events]
    # and a different seed actually differs
    c = FaultSchedule.generate(seed=43, **kw)
    assert c.events != a.events


def test_schedule_shape_and_bounds():
    s = FaultSchedule.generate(
        seed=7, horizon=20.0, crashes=2, drop_windows=1, device_stalls=1,
        replica_ids=["r0", "r1", "r2", "r3"],
    )
    assert len(s.events) == 4
    kinds = sorted(e.kind for e in s.events)
    assert kinds == ["crash", "crash", "drop_window", "stall_device"]
    for e in s.events:
        assert 0.1 * 20.0 <= e.t <= 0.9 * 20.0  # clean setup/drain edges
    assert list(s.events) == sorted(s.events, key=lambda e: (e.t, e.kind, e.target))
    # summary round-trips the regeneration arguments
    summ = s.summary()
    assert summ["seed"] == 7
    assert summ["counts"] == {"crash": 2, "drop_window": 1, "stall_device": 1}


def test_parse_cli_spec_and_reject_typos():
    s = FaultSchedule.parse("seed=9,crashes=2,stalls=1", horizon=10.0)
    assert s.seed == 9
    assert sum(1 for e in s.events if e.kind == "crash") == 2
    assert sum(1 for e in s.events if e.kind == "stall_device") == 1
    # same spec -> same schedule (the CLI path keeps the replay contract)
    assert s == FaultSchedule.parse("seed=9,crashes=2,stalls=1", horizon=10.0)
    with pytest.raises(ValueError, match="crashs"):
        FaultSchedule.parse("crashs=2", horizon=10.0)


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------


class _Inner:
    name = "inner"
    device_calls = 0
    device_items = 0
    device_seconds = 0.0

    def __init__(self):
        self.seen = []

    def verify_batch(self, items):
        self.seen.append(len(items))
        return [True] * len(items)

    def dispatch_batch(self, items):
        items = list(items)
        return lambda: self.verify_batch(items)


def test_slow_verifier_arms_and_disarms():
    inner = _Inner()
    sv = SlowVerifier(inner)
    t0 = time.perf_counter()
    assert sv.verify_batch([1, 2]) == [True, True]
    assert time.perf_counter() - t0 < 0.05  # disarmed: no delay
    sv.arm(0.1)
    t0 = time.perf_counter()
    assert sv.verify_batch([1]) == [True]
    assert time.perf_counter() - t0 >= 0.1
    sv.disarm()
    t0 = time.perf_counter()
    sv.verify_batch([1])
    assert time.perf_counter() - t0 < 0.05
    assert sv.name == "inner"  # passthrough


def test_stallable_device_blocks_then_releases():
    inner = _Inner()
    dev = StallableDevice(inner)
    # healthy: instant
    assert dev.verify_batch([1, 2, 3]) == [True] * 3
    dev.stall(duration=0.2)
    assert dev.stalled
    t0 = time.perf_counter()
    out = dev.dispatch_batch([1])()  # blocks until the auto-release
    assert time.perf_counter() - t0 >= 0.15
    assert out == [True]
    assert dev.stalls_injected == 1 and dev.finishers_stalled == 1
    # manual release path
    dev.stall()
    assert dev.stalled
    dev.release()
    assert not dev.stalled
    assert dev.verify_batch([1]) == [True]


def test_stallable_device_counter_passthrough_survives_writes():
    """VerifyService (and the bench) write device_calls/items/seconds
    through the wrapper; the write must reach the INNER counters, not
    shadow them on the wrapper."""
    inner = _Inner()
    dev = StallableDevice(inner)
    dev.device_calls = 7
    inner.device_calls += 1
    assert dev.device_calls == 8  # reads keep tracking the inner value
    dev.device_seconds = 1.5
    assert inner.device_seconds == 1.5


# ---------------------------------------------------------------------------
# injector semantics
# ---------------------------------------------------------------------------


def test_injector_crash_respects_quorum_floor():
    """n=4 (quorum 3): a 3-crash schedule may only apply ONE crash —
    never below 2f+1 live replicas (a resilience run must stay a
    liveness-possible configuration)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        com.start()
        schedule = FaultSchedule(
            seed=0, horizon=1.0,
            events=tuple(
                FaultEvent(t=0.01 * (i + 1), kind="crash") for i in range(3)
            ),
        )
        injector = FaultInjector(committee=com, schedule=schedule)
        try:
            await injector.run(time.perf_counter() + 2.0)
            assert injector.crashes_applied == 1
            assert injector.skipped == 2
            live = sum(1 for r in com.replicas if r._running)
            assert live == 3 == com.cfg.quorum
        finally:
            await com.stop()

    run(scenario())


def test_injector_windows_apply_and_restore():
    """drop/delay windows raise the network knobs for their duration and
    restore the previous values afterwards — and stop() restores early
    (no degraded settings may leak into the drain phase)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        schedule = FaultSchedule(
            seed=0, horizon=2.0,
            events=(
                FaultEvent(t=0.0, kind="drop_window", duration=0.2,
                           magnitude=0.5),
                FaultEvent(t=0.0, kind="delay_window", duration=30.0,
                           magnitude=0.04),
            ),
        )
        injector = FaultInjector(committee=com, schedule=schedule)
        task = asyncio.create_task(injector.run(time.perf_counter() + 1.0))
        await asyncio.sleep(0.1)
        assert com.net.faults.drop_rate == pytest.approx(0.5)
        assert com.net.faults.delay_range == (0.0, 0.04)
        await asyncio.sleep(0.25)  # the 0.2 s drop window expires
        assert com.net.faults.drop_rate == 0.0
        assert com.net.faults.delay_range == (0.0, 0.04)  # still open
        injector.stop()  # cancels the 30 s window -> restores NOW
        await asyncio.gather(task, return_exceptions=True)
        assert com.net.faults.delay_range == (0.0, 0.0)

    run(scenario())


def test_injector_skips_seamless_faults():
    """stall_device without a service / slow_verifier without a wrapper
    are counted skipped, never raised — a CPU-only run just has no
    device to stall."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        schedule = FaultSchedule(
            seed=0, horizon=1.0,
            events=(
                FaultEvent(t=0.0, kind="stall_device", duration=1.0),
                FaultEvent(t=0.0, kind="slow_verifier", duration=1.0,
                           magnitude=0.1),
            ),
        )
        injector = FaultInjector(committee=com, schedule=schedule)
        await injector.run(time.perf_counter() + 1.0)
        assert injector.skipped == 2
        assert all(not rec["applied"] for rec in injector.applied)

    run(scenario())


def test_injector_slow_verifier_window():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, verify_signatures=False)
        slow = SlowVerifier(_Inner())
        schedule = FaultSchedule(
            seed=0, horizon=1.0,
            events=(
                FaultEvent(t=0.0, kind="slow_verifier", duration=0.15,
                           magnitude=0.07),
            ),
        )
        injector = FaultInjector(committee=com, schedule=schedule, slow=slow)
        task = asyncio.create_task(injector.run(time.perf_counter() + 1.0))
        await asyncio.sleep(0.05)
        assert slow._delay == pytest.approx(0.07)
        await asyncio.gather(task, return_exceptions=True)
        assert slow._delay == 0.0  # window expired -> disarmed

    run(scenario())
