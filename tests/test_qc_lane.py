"""Off-loop QC verify lane (ISSUE 3 tentpole).

Unit-drives QcVerifyLane's batch-close, dedup, memo and bounded-admission
mechanics deterministically (worker internals driven by hand), then runs
the acceptance scenario the r5 qc256 wedge would have failed: a qc-mode
committee fronting a real coalescing VerifyService must commit requests
within a bounded wall clock with ZERO verify-service wedges and ZERO
post-warmup XLA compiles, with the QC-lane counters visible in the
unified telemetry snapshot.
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.consensus import qc as qc_mod
from simple_pbft_tpu.crypto import bls


def run(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(scope="module")
def keys():
    return [bls.keygen(bytes([i + 17]) * 32) for i in range(4)]


class _Cfg:
    def __init__(self, keys):
        self.bls = {f"r{i}": pk for i, (_, pk) in enumerate(keys)}
        self.quorum = 3
        self.replica_ids = tuple(sorted(self.bls))

    def bls_pubkey(self, nid):
        return self.bls.get(nid)


def _qc(cfg, keys, seq, phase="prepare", digest="d" * 64, corrupt=False):
    shares = {
        f"r{i}": qc_mod.sign_share(
            sk, phase, 1000 if corrupt else 0, seq, digest
        )
        for i, (sk, _) in enumerate(keys[:3])
    }
    cert = qc_mod.build_qc(phase, 0, seq, digest, shares, cfg.quorum)
    assert cert is not None
    return cert


def _drain(lane):
    """Deterministic stand-in for one worker iteration."""
    with lane._cond:
        take = lane._take_locked()
    if take:
        lane._run_batch(take)


def test_lane_batches_dedups_and_memoizes(keys):
    cfg = _Cfg(keys)
    lane = qc_mod.QcVerifyLane()
    lane._started = True  # drive the worker by hand: deterministic
    certs = [_qc(cfg, keys, seq=100 + i) for i in range(3)]
    bad = _qc(cfg, keys, seq=103, corrupt=True)
    futs = [lane.submit(cfg, c) for c in certs + [bad]]
    dup = lane.submit(cfg, certs[0])  # concurrent duplicate: joins entry
    assert lane.dedup_joins == 1
    _drain(lane)
    assert [f.result(5) for f in futs] == [True, True, True, False]
    assert dup.result(5) is True
    assert lane.batches == 1 and lane.batch_items == 4
    assert lane.rlc_batches == 1 and lane.batch_fallbacks == 1
    assert lane.verified_true == 3 and lane.verified_false == 1
    # memo: resubmits answer inline from the process-wide cache
    hit = lane.submit(cfg, certs[1])
    assert hit.done() and hit.result() is True
    miss_bad = lane.submit(cfg, bad)
    assert miss_bad.done() and miss_bad.result() is False
    assert lane.cache_hits == 2
    snap = lane.snapshot()
    assert snap["pending"] == 0 and snap["max_batch_seen"] == 4
    assert snap["pairing_ms_ema"] > 0


def test_lane_bounded_admission(keys):
    cfg = _Cfg(keys)
    lane = qc_mod.QcVerifyLane(max_pending=2)
    lane._started = True
    f1 = lane.submit(cfg, _qc(cfg, keys, seq=200))
    f2 = lane.submit(cfg, _qc(cfg, keys, seq=201))
    f3 = lane.submit(cfg, _qc(cfg, keys, seq=202))
    with pytest.raises(qc_mod.QcLaneOverloaded):
        f3.result(1)
    assert lane.overload_rejections == 1
    _drain(lane)
    assert f1.result(5) is True and f2.result(5) is True


def test_lane_structural_reject_inline(keys):
    cfg = _Cfg(keys)
    lane = qc_mod.QcVerifyLane()
    lane._started = True
    from simple_pbft_tpu.messages import QuorumCert

    bogus = QuorumCert(
        phase="bogus", view=0, seq=1, digest="d" * 64,
        signers=["r0", "r1", "r2"], agg_sig="00",
    )
    f = lane.submit(cfg, bogus)
    assert f.done() and f.result() is False  # no pairing spent
    assert lane.structural_rejects == 1


def test_verify_qcs_all_batches_and_memoizes(keys):
    cfg = _Cfg(keys)
    good = [_qc(cfg, keys, seq=300 + i, phase="checkpoint") for i in range(3)]
    assert qc_mod.verify_qcs_all(cfg, good) is True
    # memoized now: a second pass costs zero pairings (cache answers)
    assert all(qc_mod.cached_verdict(c) is True for c in good)
    poisoned = good + [_qc(cfg, keys, seq=304, corrupt=True)]
    assert qc_mod.verify_qcs_all(cfg, poisoned) is False
    # the unattributable batch failure memoized nothing for the bad cert
    assert qc_mod.cached_verdict(poisoned[-1]) is None


def test_qc_committee_fast_path_bounded_no_wedge(monkeypatch):
    """The qc256-wedge regression (ISSUE 3 acceptance): a qc-mode
    committee whose every replica fronts ONE coalescing VerifyService
    over a real (XLA-CPU) device verifier, with the QC lane verifying
    certificates off-loop, must commit requests within the test's
    bounded wall clock, with zero verify-service wedges (no overload
    rejections, no quarantine) and ZERO post-warmup compiles."""
    from simple_pbft_tpu.crypto import tpu_verifier as tv
    from simple_pbft_tpu.crypto.coalesce import VerifyService

    # two tiny buckets keep the XLA-CPU compile bill in CI seconds while
    # still exercising the padded-bucket shape discipline
    monkeypatch.setattr(tv, "BUCKETS", (8, 32))

    async def scenario():
        dev = tv.TpuVerifier(initial_keys=16)
        svc = VerifyService(dev, cpu_cutoff=0, max_batch=32)
        com = LocalCommittee.build(
            n=4, clients=1, qc_mode=True,
            verifier_factory=lambda: svc,
            view_timeout=60.0, max_batch=8,
        )
        com.clients[0].request_timeout = 60.0
        # service-level warm: covers every bucket a coalesced take can
        # hit (max_batch), closing the shape set before traffic. Off
        # the loop — seconds of table building + XLA compiles; the loop
        # sanitizer (PBFT_SANITIZE=loop) fails this test otherwise
        await asyncio.to_thread(
            svc.warm_for_population,
            [kp.pub for kp in com.keys.values()],
            max_sweep=8,
        )
        com.start()
        try:
            res = await asyncio.gather(
                *(com.clients[0].submit(f"put k{i} {i}") for i in range(6))
            )
            assert res == ["ok"] * 6
        finally:
            await com.stop()
            svc.close()
        snap = svc.snapshot()
        # zero verify-service wedges
        assert snap["overload_rejections"] == 0
        assert snap["quarantined"] is False and snap["watchdog_failovers"] == 0
        # shape-stable coalescing: the warmup closed the shape set
        assert snap["device_shapes"]["warmed"] is True
        assert snap["device_shapes"]["post_warm_compiles"] == 0
        assert svc.device_passes > 0
        # the QC lane actually carried the certificate checks
        lane = qc_mod.lane_snapshot()
        assert lane is not None
        assert lane["submitted"] > 0 and lane["batches"] > 0
        assert lane["pending"] == 0 and lane["overload_rejections"] == 0
        # and its counters ride the unified telemetry snapshot
        tel = com.node_telemetry("r0").snapshot()
        assert tel["qc_lane"]["submitted"] >= lane["submitted"] - 1
        assert "pairing_ms_ema" in tel["qc_lane"]

    run(scenario(), timeout=240)
