"""Message schema: canonical serialization, digests, signing payloads."""

import json
import random

from simple_pbft_tpu import messages as m
from simple_pbft_tpu.crypto import ed25519_cpu as ed


def test_roundtrip_all_kinds():
    samples = [
        m.Request(sender="c1", client_id="c1", timestamp=7, operation="put x 1"),
        m.Reply(sender="r0", view=1, seq=2, client_id="c1", timestamp=7, result="ok"),
        m.PrePrepare(sender="r0", view=0, seq=1, digest="ab", block=[{"op": 1}]),
        m.Prepare(sender="r1", view=0, seq=1, digest="ab"),
        m.Commit(sender="r2", view=0, seq=1, digest="ab"),
        m.Checkpoint(sender="r1", seq=100, state_digest="cd"),
        m.ViewChange(sender="r3", new_view=2, stable_seq=100),
        m.NewView(sender="r2", new_view=2),
    ]
    for msg in samples:
        wire = msg.to_wire()
        back = m.Message.from_wire(wire)
        assert back == msg
        assert type(back) is type(msg)


def test_canonical_encoding_deterministic():
    a = m.Prepare(sender="r1", view=3, seq=9, digest="dd")
    b = m.Prepare(digest="dd", seq=9, view=3, sender="r1")
    assert a.to_wire() == b.to_wire()
    assert a.payload_digest() == b.payload_digest()


def test_signing_payload_excludes_sig():
    msg = m.Prepare(sender="r1", view=1, seq=1, digest="d")
    unsigned_payload = msg.signing_payload()
    msg.sig = "aa" * 64
    assert msg.signing_payload() == unsigned_payload
    assert msg.payload_digest() == m.Message.from_wire(msg.to_wire()).payload_digest()


def test_sign_and_verify_message():
    seed = b"\x05" * 32
    pub = ed.public_key(seed)
    msg = m.Commit(sender="r2", view=1, seq=4, digest="beef")
    msg.sig = ed.sign(seed, msg.signing_payload()).hex()
    assert ed.verify(pub, msg.signing_payload(), bytes.fromhex(msg.sig))
    # Mutating any field invalidates
    msg.seq = 5
    assert not ed.verify(pub, msg.signing_payload(), bytes.fromhex(msg.sig))


def test_block_digest_matches_content():
    block = [{"client_id": "c", "timestamp": 1, "operation": "x"}]
    d1 = m.PrePrepare.block_digest(block)
    d2 = m.PrePrepare.block_digest(list(block))
    assert d1 == d2
    assert d1 != m.PrePrepare.block_digest([])


def test_from_wire_malformed_always_valueerror():
    import pytest

    bad = [
        b"not json",
        b"123",
        b"[1,2]",
        b'{"kind":"nope"}',
        b'{"no_kind":1}',
        b'{"kind":"prepare","sender":{"x":1}}',
        b'{"kind":"prepare","view":"high"}',
        b'{"kind":"prepare","view":true}',
        b'{"kind":"preprepare","block":"notalist"}',
        b"\xff\xfe",
    ]
    for raw in bad:
        with pytest.raises(ValueError):
            m.Message.from_wire(raw)


def test_from_wire_hostile_nesting_and_size():
    import pytest

    deep = b"[" * 200000 + b"]" * 200000
    with pytest.raises(ValueError):
        m.Message.from_wire(b'{"kind":"preprepare","block":' + deep + b"}")
    nested = {"kind": "preprepare", "block": [{"a": 1}]}
    cur = nested["block"][0]
    for _ in range(100):
        cur["a"] = [{"a": 1}]
        cur = cur["a"][0]
    import json

    with pytest.raises(ValueError):
        m.Message.from_dict(nested)
    with pytest.raises(ValueError):
        m.Message.from_wire(b" " * (m.Message.MAX_WIRE_BYTES + 1))


def test_list_fields_require_dict_elements():
    import pytest

    with pytest.raises(ValueError):
        m.Message.from_wire(
            b'{"kind":"preprepare","view":0,"seq":1,"digest":"d","block":[1,"x"]}'
        )


def test_fuzz_mutated_wires_never_crash():
    """Systematic hostile-input sweep (SURVEY.md §5 sanitizer hygiene):
    thousands of deterministic random mutations of valid wire bytes must
    either decode to a Message or raise ValueError — never any other
    exception. This is the invariant every transport relies on."""
    rng = random.Random(1234)
    samples = [
        m.Request(sender="c1", client_id="c1", timestamp=7, operation="x"),
        m.PrePrepare(sender="r0", view=0, seq=1, digest="ab", block=[{"o": 1}]),
        m.Prepare(sender="r1", view=0, seq=1, digest="ab"),
        m.ViewChange(sender="r3", new_view=2, stable_seq=100),
        m.NewView(sender="r2", new_view=2),
    ]
    wires = [s.to_wire() for s in samples]
    for _ in range(4000):
        raw = bytearray(rng.choice(wires))
        for _ in range(rng.randint(1, 8)):
            op = rng.randrange(3)
            pos = rng.randrange(len(raw)) if raw else 0
            if op == 0 and raw:
                raw[pos] ^= 1 << rng.randrange(8)
            elif op == 1 and raw:
                del raw[pos]
            else:
                raw.insert(pos, rng.randrange(256))
        try:
            m.Message.from_wire(bytes(raw))
        except ValueError:
            pass  # the one allowed failure mode


def test_fuzz_random_json_structures_never_crash():
    """Random well-formed JSON (nested arrays/objects/scalars in schema
    and out) through from_wire: decode or ValueError, nothing else."""
    rng = random.Random(99)

    def gen(depth):
        k = rng.randrange(7 if depth < 4 else 5)
        if k == 0:
            return rng.randrange(-(2**40), 2**40)
        if k == 1:
            return rng.choice(["", "r0", "prepare", "x" * rng.randrange(40)])
        if k == 2:
            return rng.choice([True, False, None])
        if k == 3:
            return rng.random()
        if k == 4:
            kind = rng.choice(
                ["request", "preprepare", "prepare", "commit", "reply",
                 "checkpoint", "viewchange", "newview", "zzz"]
            )
            return {"kind": kind, "view": gen(depth + 1), "seq": gen(depth + 1)}
        if k == 5:
            return [gen(depth + 1) for _ in range(rng.randrange(4))]
        return {
            rng.choice(["kind", "view", "block", "sig", "sender", "q"]):
                gen(depth + 1)
            for _ in range(rng.randrange(4))
        }

    for _ in range(2000):
        raw = json.dumps(gen(0)).encode()
        try:
            m.Message.from_wire(raw)
        except ValueError:
            pass
