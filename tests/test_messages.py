"""Message schema: canonical serialization, digests, signing payloads."""

from simple_pbft_tpu import messages as m
from simple_pbft_tpu.crypto import ed25519_cpu as ed


def test_roundtrip_all_kinds():
    samples = [
        m.Request(sender="c1", client_id="c1", timestamp=7, operation="put x 1"),
        m.Reply(sender="r0", view=1, seq=2, client_id="c1", timestamp=7, result="ok"),
        m.PrePrepare(sender="r0", view=0, seq=1, digest="ab", block=[{"op": 1}]),
        m.Prepare(sender="r1", view=0, seq=1, digest="ab"),
        m.Commit(sender="r2", view=0, seq=1, digest="ab"),
        m.Checkpoint(sender="r1", seq=100, state_digest="cd"),
        m.ViewChange(sender="r3", new_view=2, stable_seq=100),
        m.NewView(sender="r2", new_view=2),
    ]
    for msg in samples:
        wire = msg.to_wire()
        back = m.Message.from_wire(wire)
        assert back == msg
        assert type(back) is type(msg)


def test_canonical_encoding_deterministic():
    a = m.Prepare(sender="r1", view=3, seq=9, digest="dd")
    b = m.Prepare(digest="dd", seq=9, view=3, sender="r1")
    assert a.to_wire() == b.to_wire()
    assert a.payload_digest() == b.payload_digest()


def test_signing_payload_excludes_sig():
    msg = m.Prepare(sender="r1", view=1, seq=1, digest="d")
    unsigned_payload = msg.signing_payload()
    msg.sig = "aa" * 64
    assert msg.signing_payload() == unsigned_payload
    assert msg.payload_digest() == m.Message.from_wire(msg.to_wire()).payload_digest()


def test_sign_and_verify_message():
    seed = b"\x05" * 32
    pub = ed.public_key(seed)
    msg = m.Commit(sender="r2", view=1, seq=4, digest="beef")
    msg.sig = ed.sign(seed, msg.signing_payload()).hex()
    assert ed.verify(pub, msg.signing_payload(), bytes.fromhex(msg.sig))
    # Mutating any field invalidates
    msg.seq = 5
    assert not ed.verify(pub, msg.signing_payload(), bytes.fromhex(msg.sig))


def test_block_digest_matches_content():
    block = [{"client_id": "c", "timestamp": 1, "operation": "x"}]
    d1 = m.PrePrepare.block_digest(block)
    d2 = m.PrePrepare.block_digest(list(block))
    assert d1 == d2
    assert d1 != m.PrePrepare.block_digest([])


def test_from_wire_malformed_always_valueerror():
    import pytest

    bad = [
        b"not json",
        b"123",
        b"[1,2]",
        b'{"kind":"nope"}',
        b'{"no_kind":1}',
        b'{"kind":"prepare","sender":{"x":1}}',
        b'{"kind":"prepare","view":"high"}',
        b'{"kind":"prepare","view":true}',
        b'{"kind":"preprepare","block":"notalist"}',
        b"\xff\xfe",
    ]
    for raw in bad:
        with pytest.raises(ValueError):
            m.Message.from_wire(raw)


def test_from_wire_hostile_nesting_and_size():
    import pytest

    deep = b"[" * 200000 + b"]" * 200000
    with pytest.raises(ValueError):
        m.Message.from_wire(b'{"kind":"preprepare","block":' + deep + b"}")
    nested = {"kind": "preprepare", "block": [{"a": 1}]}
    cur = nested["block"][0]
    for _ in range(100):
        cur["a"] = [{"a": 1}]
        cur = cur["a"][0]
    import json

    with pytest.raises(ValueError):
        m.Message.from_dict(nested)
    with pytest.raises(ValueError):
        m.Message.from_wire(b" " * (m.Message.MAX_WIRE_BYTES + 1))


def test_list_fields_require_dict_elements():
    import pytest

    with pytest.raises(ValueError):
        m.Message.from_wire(
            b'{"kind":"preprepare","view":0,"seq":1,"digest":"d","block":[1,"x"]}'
        )
