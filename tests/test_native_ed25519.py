"""Dedicated oracle-parity suite for the native batch Ed25519 verifier
(native/ed25519.cpp via crypto.verifier.NativeEdVerifier) — the default
CPU backend on hosts with a toolchain, so it gets the same adversarial
coverage as the TPU backend (tests/test_tpu_verifier.py), not just
implicit exercise through best_cpu_verifier().

Semantics note: the native backend mirrors the TPU kernel (ops/comb.py):
P = [S]B + [k](-A) must byte-compare to the wire R. For every signature
an honest signer can produce — and every corruption of one — this agrees
with the RFC 8032 oracle; the tests below pin that agreement.
"""

import random

import pytest

from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.crypto.verifier import BatchItem, CpuVerifier

try:
    from simple_pbft_tpu.crypto.verifier import NativeEdVerifier

    _native = NativeEdVerifier()
except ImportError:  # pragma: no cover - toolchain-less host
    _native = None

pytestmark = pytest.mark.skipif(
    _native is None, reason="native ed25519 library unavailable"
)


def _sig_items(n=16, distinct_keys=4, seed=1234):
    rng = random.Random(seed)
    items = []
    for i in range(n):
        sd = bytes([i % distinct_keys + 1]) * 32
        msg = bytes(rng.randbytes(rng.randrange(0, 150)))
        items.append(BatchItem(ref.public_key(sd), msg, ref.sign(sd, msg)))
    return items


def test_valid_batch_all_true():
    items = _sig_items(32)
    assert _native.verify_batch(items) == [True] * 32


def test_corruption_classes_match_oracle():
    rng = random.Random(9)
    base = _sig_items(8)
    items = list(base)
    for it in base:
        bad_sig = bytearray(it.sig)
        bad_sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
        items.append(BatchItem(it.pubkey, it.msg, bytes(bad_sig)))
        items.append(BatchItem(it.pubkey, it.msg + b"!", it.sig))
        items.append(BatchItem(ref.public_key(b"\x77" * 32), it.msg, it.sig))
        items.append(BatchItem(it.pubkey[:-1], it.msg, it.sig))  # short key
        items.append(BatchItem(it.pubkey, it.msg, it.sig[:-1]))  # short sig
        items.append(BatchItem(b"\xff" * 32, it.msg, it.sig))  # off-curve
        # malleable S' = S + L: the oracle and the native path both reject
        s_int = int.from_bytes(it.sig[32:], "little") + ref.L
        items.append(
            BatchItem(it.pubkey, it.msg, it.sig[:32] + s_int.to_bytes(32, "little"))
        )
    got = _native.verify_batch(items)
    oracle = CpuVerifier().verify_batch(items)
    assert got == oracle
    assert got[: len(base)] == [True] * len(base)
    assert not any(got[len(base) :])


def test_boundary_scalars_and_wnaf_carry_edges():
    """Signatures whose S/k hit w-NAF carry chains: long runs of 1-bits
    arise from messages hashed to extreme challenge scalars — approximate
    by verifying many random messages per key so the 251+ bit patterns
    vary; parity with the oracle is the invariant."""
    rng = random.Random(31337)
    items = []
    for i in range(96):
        sd = bytes([i % 3 + 9]) * 32
        msg = bytes(rng.randbytes(64))
        items.append(BatchItem(ref.public_key(sd), msg, ref.sign(sd, msg)))
    assert _native.verify_batch(items) == [True] * 96


def test_mixed_validity_bitmap_positions():
    items = _sig_items(12)
    bad = bytearray(items[5].sig)
    bad[3] ^= 0x10
    items[5] = BatchItem(items[5].pubkey, items[5].msg, bytes(bad))
    items[9] = BatchItem(items[9].pubkey, b"swapped", items[9].sig)
    got = _native.verify_batch(items)
    assert got == [i not in (5, 9) for i in range(12)]


def test_empty_and_single():
    assert _native.verify_batch([]) == []
    it = _sig_items(1)[0]
    assert _native.verify_batch([it]) == [True]


@pytest.mark.parametrize("wbits", [4, 5, 6])
def test_native_fused_table_bit_exact(wbits):
    """The C++ fused-table build must produce byte-identical packed rows
    to the exact-bigint Python path for every window width — the KeyBank
    swaps between them transparently."""
    import numpy as np

    from simple_pbft_tpu import native
    from simple_pbft_tpu.ops import comb

    pt = ref.point_decompress(ref.public_key(bytes([40 + wbits]) * 32))
    nat = comb.fused_table_np(pt, wbits)
    orig = native.ed25519_fused_table
    native.ed25519_fused_table = lambda *a: None  # force the Python path
    try:
        py = comb.fused_table_np(pt, wbits)
    finally:
        native.ed25519_fused_table = orig
    assert np.array_equal(nat, py)


def test_key_cache_remap_across_calls():
    """Key bank grows across calls; later batches referencing a subset of
    cached keys must remap indices correctly."""
    a = _sig_items(8, distinct_keys=8, seed=5)
    assert _native.verify_batch(a) == [True] * 8
    # a batch touching only keys 6,7 (bank indices high) + one new key
    sub = [a[6], a[7]]
    sd = bytes([42]) * 32
    sub.append(BatchItem(ref.public_key(sd), b"new", ref.sign(sd, b"new")))
    assert _native.verify_batch(sub) == [True, True, True]
