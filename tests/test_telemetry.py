"""Telemetry plane (ISSUE 2 tentpole): unified snapshot schema, live
/metrics.json exposure mid-run, crash-surviving flight recorder, and
sampled phase-level request tracing that joins client and replica events
by request id."""

import asyncio
import json

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.telemetry import (
    SCHEMA_VERSION,
    FlightRecorder,
    NodeTelemetry,
    RequestTracer,
    StatusServer,
    trace_sampled,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _http_get(port: int, path: str):
    """Raw HTTP/1.0 GET against the status server; returns (status, body)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.0\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, body


# ---------------------------------------------------------------------------
# unified snapshot
# ---------------------------------------------------------------------------


def test_snapshot_schema_on_idle_node():
    """An IDLE node's snapshot carries the full stable schema — zeroed
    histograms included (the logutil satellite) — so consumers never
    key-error before traffic arrives."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        snap = com.node_telemetry("r0").snapshot()
        assert snap["schema"] == SCHEMA_VERSION
        assert snap["node"] == "r0"
        rep = snap["replica"]
        assert rep["view"] == 0 and rep["executed_seq"] == 0
        assert rep["is_primary"] is True  # r0 is view-0 primary
        # idle histograms: full zeroed schema, no KeyError
        for h in ("sweep_ms", "verify_ms", "commit_ms", "sweep_size"):
            assert rep["stats"][h]["p99"] == 0.0
            assert rep["stats"][h]["count"] == 0
        # idle transport: the FULL shared counter schema, all zero
        # (ISSUE 12 satellite: local aligned with tcp/grpc), plus an
        # empty wire-accounting block
        from simple_pbft_tpu.transport.base import COUNTER_SCHEMA

        assert snap["transport"]["metrics"] == {k: 0 for k in COUNTER_SCHEMA}
        assert snap["transport"]["wire"]["sent_msgs"] == 0
        # plain CPU verifier: name only (nothing to overload)
        assert "name" in snap["verify"]
        # the whole snapshot is JSON-serializable (flight recorder / HTTP)
        json.dumps(snap)

    run(scenario())


def test_snapshot_absorbs_all_four_surfaces_after_traffic():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            # the submit may resolve on the speculative fast path
            # (ISSUE 15): settle until r0's commit lands
            r0 = com.replica("r0")
            for _ in range(100):
                if r0.metrics.get("committed_requests"):
                    break
                await asyncio.sleep(0.05)
            snap = com.node_telemetry("r0").snapshot()
            rep = snap["replica"]
            assert rep["metrics"]["committed_requests"] == 1
            assert rep["executed_seq"] == 1
            assert rep["stats"]["commit_ms"]["count"] >= 1
            assert snap["transport"]["metrics"]["recv"] > 0
            cli = com.node_telemetry("c0").snapshot()
            assert cli["client"]["id"] == "c0"
            assert cli["client"]["inflight"] == 0
        finally:
            await com.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# live HTTP exposure
# ---------------------------------------------------------------------------


def test_status_server_serves_metrics_mid_run():
    """Acceptance criterion: scraping a node's /metrics.json MID-RUN
    returns the unified snapshot — no shutdown required."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        srv = StatusServer(com.node_telemetry("r0"), port=0)
        await srv.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            status, body = await _http_get(srv.bound_port, "/metrics.json")
            assert status == 200
            snap = json.loads(body)
            assert snap["schema"] == SCHEMA_VERSION
            assert snap["replica"]["metrics"]["committed_requests"] >= 1
            status, body = await _http_get(srv.bound_port, "/healthz")
            assert status == 200
            hz = json.loads(body)
            assert hz["ok"] is True and hz["node"] == "r0"
            status, _ = await _http_get(srv.bound_port, "/nope")
            assert status == 404
        finally:
            await srv.stop()
            await com.stop()

    run(scenario())


def test_healthz_reports_degraded_and_stopped():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        r0 = com.replica("r0")
        srv = StatusServer(com.node_telemetry("r0"), port=0)
        await srv.start()
        try:
            r0.metrics["degraded_mode"] = 1
            _, body = await _http_get(srv.bound_port, "/healthz")
            assert json.loads(body)["degraded"] is True
            r0.kill()  # crash-stop: /healthz flips to 503, still serving
            status, body = await _http_get(srv.bound_port, "/healthz")
            assert status == 503
            assert json.loads(body)["ok"] is False
        finally:
            await srv.stop()
            await com.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_timeline_survives_crash_stop(tmp_path):
    """The r5 lesson: a node that never shuts down cleanly must still
    leave a telemetry timeline. Lines are flushed per snapshot, so after
    kill() (crash-stop, no stop()/close()) the JSONL already on disk
    reconstructs the run."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        path = str(tmp_path / "r0.flight.jsonl")
        rec = FlightRecorder(
            com.node_telemetry("r0"), path, interval=0.05
        )
        rec.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            await asyncio.sleep(0.25)
            com.replica("r0").kill()  # SIGKILL stand-in: no clean shutdown
            await asyncio.sleep(0.1)
            # read WITHOUT stopping the recorder: what's on disk now is
            # exactly what a post-mortem of a dead process would find
            lines = [
                json.loads(ln)
                for ln in open(path).read().splitlines()
                if ln.strip()
            ]
            assert len(lines) >= 3
            assert all(ln["schema"] == SCHEMA_VERSION for ln in lines)
            assert all(ln["node"] == "r0" for ln in lines)
            # the timeline shows progress, then the crash-stop
            assert lines[-1]["replica"]["metrics"].get(
                "committed_requests", 0
            ) >= 1
            assert lines[-1]["replica"]["running"] is False
            # monotonic timestamps make deltas meaningful
            monos = [ln["t_mono"] for ln in lines]
            assert monos == sorted(monos)
        finally:
            await rec.stop()
            await com.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# sampled phase-level request tracing
# ---------------------------------------------------------------------------


def test_trace_sampling_is_deterministic_and_proportional():
    assert trace_sampled("c0", 123, 1) is True
    assert trace_sampled("c0", 123, 0) is False
    # same decision everywhere, every time
    assert trace_sampled("c0", 999, 16) == trace_sampled("c0", 999, 16)
    hits = sum(1 for ts in range(4096) if trace_sampled("cX", ts, 16))
    assert 150 < hits < 370  # ~256 expected at 1/16


def test_trace_joins_client_and_replica_phases():
    """Acceptance criterion: a committed request's sampled trace yields
    the full per-phase lifecycle, joining client and replica events by
    request id, with monotonic per-phase timestamps."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        tracers = com.attach_tracers(sample_mod=1)  # trace everything
        com.start()
        try:
            assert await com.clients[0].submit("put traced v") == "ok"
        finally:
            await com.stop()

        client_evs = tracers["c0"].recent()
        assert {e["phase"] for e in client_evs} >= {"submit", "accepted"}
        rids = {e["rid"] for e in client_evs}
        assert len(rids) == 1
        rid = rids.pop()
        assert rid.startswith("c0:")

        # primary (r0, view 0) stamps the whole replica-side lifecycle
        r0_evs = [e for e in tracers["r0"].recent() if e["rid"] == rid]
        phases = [e["phase"] for e in r0_evs]
        for ph in ("request", "pre_prepare", "prepare", "commit", "execute"):
            assert ph in phases, f"missing {ph} in {phases}"
        # per-phase latency decomposition: first stamp of each phase is
        # monotonic along the lifecycle
        order = ["request", "pre_prepare", "prepare", "commit", "execute"]
        t = [
            next(e["t_mono"] for e in r0_evs if e["phase"] == ph)
            for ph in order
        ]
        assert t == sorted(t)
        # slot ids ride along from pre_prepare on
        pp = next(e for e in r0_evs if e["phase"] == "pre_prepare")
        assert pp["view"] == 0 and pp["seq"] == 1
        assert len(pp["digest"]) == 64
        # a designated replier stamped the reply leg
        assert any(
            e["phase"] == "reply" and e["rid"] == rid
            for tr in tracers.values()
            for e in tr.recent()
        )
        # every node agreed on the sampling decision (same rid seen on
        # all replicas that executed the block)
        for node in ("r1", "r2", "r3"):
            assert any(
                e["rid"] == rid and e["phase"] == "execute"
                for e in tracers[node].recent()
            )

    run(scenario())


def test_trace_jsonl_sink_and_trace_endpoint(tmp_path):
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        tracers = com.attach_tracers(sample_mod=1, trace_dir=str(tmp_path))
        com.start()
        srv = StatusServer(com.node_telemetry("r0"), port=0)
        await srv.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            status, body = await _http_get(srv.bound_port, "/trace.json")
            assert status == 200
            doc = json.loads(body)
            assert doc["node"] == "r0"
            assert any(e["phase"] == "execute" for e in doc["events"])
        finally:
            await srv.stop()
            await com.stop()
            for t in tracers.values():
                t.close()
        # file sink: line-flushed JSONL, one file per node, joinable
        r0_lines = [
            json.loads(ln)
            for ln in (tmp_path / "r0.trace.jsonl").read_text().splitlines()
        ]
        c0_lines = [
            json.loads(ln)
            for ln in (tmp_path / "c0.trace.jsonl").read_text().splitlines()
        ]
        assert {e["rid"] for e in r0_lines} & {e["rid"] for e in c0_lines}

    run(scenario())


def test_unsampled_requests_emit_nothing():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        tracers = com.attach_tracers(sample_mod=0)  # sample nothing
        com.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
        finally:
            await com.stop()
        assert all(not t.recent() for t in tracers.values())
        assert all(t.events_emitted == 0 for t in tracers.values())

    run(scenario())


# ---------------------------------------------------------------------------
# bench integration: start/end snapshots ride the record
# ---------------------------------------------------------------------------


def test_bench_committee_telemetry_aggregate():
    async def scenario():
        import bench_consensus

        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            # settle past the speculative fast answer (ISSUE 15): the
            # aggregate must see every replica's commit applied
            for _ in range(100):
                if all(r.executed_seq >= 1 for r in com.replicas):
                    break
                await asyncio.sleep(0.05)
            agg = bench_consensus._committee_telemetry(com)
            assert agg["schema"] == SCHEMA_VERSION
            assert agg["replicas_running"] == 4
            assert agg["exec_seq_min"] == agg["exec_seq_max"] == 1
            assert agg["replica_metrics"]["committed_requests"] == 4
            assert agg["transport"]["sent"] > 0
            json.dumps(agg)
        finally:
            await com.stop()

    run(scenario())
