"""Directed tests for the round-2 advisor findings (ADVICE.md):

1. block_pending must key waiters per (view, seq) — a Byzantine primary
   can get the SAME block prepared at two sequence numbers, and one
   BlockReply must release both detached pre-prepares.
2. BlockFetch targets must rotate: a fixed first-f+1 pick can be f
   honest-but-lagging non-signers plus one silent Byzantine signer.
3. A request folded under the checkpoint watermark with no cached reply
   must get an explicit SUPERSEDED reply (exec path and retry path),
   not a silent permanent drop that hangs the client.
4. The gRPC self-delivery path must honor RECV_BUFFER_BYTES like the
   inbound-stream path, so local frames can't starve peer frames.
"""

import asyncio

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.messages import BlockReply, PrePrepare, Request


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class CapturingTransport:
    """Records (dest, raw) of every send; drops broadcasts silently."""

    def __init__(self, node_id):
        self.node_id = node_id
        self.sent = []

    async def send(self, dest, raw):
        self.sent.append((dest, raw))

    async def broadcast(self, raw, dests):
        pass


def test_block_reply_releases_every_waiting_slot():
    """One digest pending at two (view, seq) slots -> one BlockReply
    replays BOTH detached pre-prepares (the old digest-keyed buffer
    silently overwrote the first waiter)."""

    async def scenario():
        com = LocalCommittee.build(n=4)
        backup = com.replica("r1")
        primary_signer = com.replica("r0").signer
        block = [{"op": "noop"}]
        digest = PrePrepare.block_digest(block)
        for seq in (1, 2):
            pp = PrePrepare(view=0, seq=seq, digest=digest, block=None)
            primary_signer.sign_msg(pp)
            backup.buffer_for_block(pp)
        assert len(backup.block_pending[digest]) == 2

        reply = BlockReply(blocks=[{"digest": digest, "block": block}])
        com.replica("r2").signer.sign_msg(reply)
        await backup._on_block_reply(reply)
        # both waiters released and counted; nothing left pending
        assert backup.metrics["blocks_fetched"] == 2
        assert digest not in backup.block_pending

    run(scenario())


def test_block_fetch_targets_rotate():
    async def scenario():
        com = LocalCommittee.build(n=7)  # f=2: fetch targets f+1=3 peers
        rep = com.replica("r0")
        cap = CapturingTransport("r0")
        rep.transport = cap
        await rep.request_blocks(["d1"])
        first = {d for d, _ in cap.sent}
        cap.sent.clear()
        await rep.request_blocks(["d1"])
        second = {d for d, _ in cap.sent}
        assert len(first) == len(second) == rep.cfg.weak_quorum
        # rotation: consecutive retries must not re-ask the same set
        assert first != second
        # and over enough retries every peer gets asked
        seen = first | second
        for _ in range(4):
            cap.sent.clear()
            await rep.request_blocks(["d1"])
            seen |= {d for d, _ in cap.sent}
        assert seen == {r for r in rep.cfg.replica_ids if r != "r0"}

    run(scenario())


def test_superseded_reply_instead_of_silent_drop():
    """Retry of a timestamp at/below the folded watermark with no cached
    reply -> an explicit SUPERSEDED reply, deterministic across replicas."""

    async def scenario():
        com = LocalCommittee.build(n=4)
        rep = com.replica("r1")
        cap = CapturingTransport("r1")
        rep.transport = cap
        client = com.clients[0]
        # simulate the post-fold state: watermark advanced, reply folded
        rep.client_watermark["c0"] = 100
        req = Request(client_id="c0", timestamp=50, operation="put k v")
        client.signer.sign_msg(req)
        await rep._on_request(req)
        assert len(cap.sent) == 1
        from simple_pbft_tpu.messages import Message, Reply

        dest, raw = cap.sent[0]
        reply = Message.from_wire(raw)
        assert dest == "c0"
        assert isinstance(reply, Reply)
        assert reply.superseded == 1
        assert reply.timestamp == 50

    run(scenario())


def test_superseded_reply_on_exec_of_folded_timestamp():
    """A below-watermark request that slips into a committed block is NOT
    re-applied but the client hears about it (exec path)."""

    async def scenario():
        com = LocalCommittee.build(n=4)
        com.start()
        try:
            assert await com.clients[0].submit("put k v1") == "ok"
            rep0 = com.replica("r0")
            # submit() returns on f+1 replies — r0 may lag; wait for it
            t0 = asyncio.get_running_loop().time()
            while (
                rep0.metrics["committed_requests"] == 0
                and asyncio.get_running_loop().time() - t0 < 10
            ):
                await asyncio.sleep(0.02)
            for rep in com.replicas:
                rep.client_watermark["c0"] = 10**9
                rep.recent_replies.get("c0", {}).clear()
            applied_before = rep0.metrics["committed_requests"]
            # a fresh submit uses a now-stale timestamp? No — force one:
            # craft a signed request below the watermark and inject it
            # into the primary's pending queue directly (as if an old
            # request had been stuck in a failover replay).
            req = Request(client_id="c0", timestamp=5, operation="put k v2")
            com.clients[0].signer.sign_msg(req)
            rep0.pending_requests.append(req)
            await rep0._propose_if_ready()
            t0 = asyncio.get_running_loop().time()
            while (
                rep0.metrics["exec_replay_skipped"] == 0
                and asyncio.get_running_loop().time() - t0 < 10
            ):
                await asyncio.sleep(0.02)
            assert rep0.metrics["exec_replay_skipped"] >= 1
            # not applied: the KV value is unchanged
            assert rep0.metrics["committed_requests"] == applied_before
            assert rep0.app.apply("get k") == "v1"
        finally:
            await com.stop()

    run(scenario())


def test_stale_relay_buffer_folds_with_watermark():
    """Backup relay_buffer entries at/below the client watermark must be
    GC'd: a stale entry would shadow the SUPERSEDED retry answer (the dup
    branch sees it 'in flight') and keep arming spurious failovers."""

    async def scenario():
        com = LocalCommittee.build(n=4)
        backup = com.replica("r1")
        req = Request(client_id="c0", timestamp=50, operation="put k v")
        com.clients[0].signer.sign_msg(req)
        backup.relay_buffer[("c0", 50)] = req
        backup.seen_requests[("c0", 50)] = 0
        backup.client_watermark["c0"] = 100
        backup._advance_stable(backup.stable_seq + 1)
        assert ("c0", 50) not in backup.relay_buffer
        assert ("c0", 50) not in backup.seen_requests
        # and the retry now gets the definitive answer
        cap = CapturingTransport("r1")
        backup.transport = cap
        await backup._on_request(req)
        from simple_pbft_tpu.messages import Message, Reply

        assert len(cap.sent) == 1
        reply = Message.from_wire(cap.sent[0][1])
        assert isinstance(reply, Reply) and reply.superseded == 1

    run(scenario())


def test_client_submit_raises_superseded():
    """End-to-end: f+1 SUPERSEDED replies surface as SupersededError, not
    as a fake result string handed to the application."""
    import itertools

    import pytest

    from simple_pbft_tpu.client import SupersededError

    async def scenario():
        com = LocalCommittee.build(n=4)
        com.start()
        try:
            for rep in com.replicas:
                rep.client_watermark["c0"] = 10**18
            com.clients[0]._ts = itertools.count(1000)  # below the floor
            with pytest.raises(SupersededError):
                await com.clients[0].submit("put k v")
        finally:
            await com.stop()

    run(scenario())


def test_grpc_self_send_respects_recv_buffer_cap():
    from simple_pbft_tpu.transport.grpc import GrpcTransport
    from simple_pbft_tpu.transport.tcp import RECV_BUFFER_BYTES

    async def scenario():
        t = GrpcTransport("n0", ("127.0.0.1", 0), peers={})
        t._recv_bytes = RECV_BUFFER_BYTES - 10
        await t.send("n0", b"x" * 100)  # would blow past the cap
        assert t.metrics["dropped_recv"] == 1
        assert t._recv_q.qsize() == 0
        await t.send("n0", b"x" * 5)  # still fits
        assert t._recv_q.qsize() == 1

    run(scenario())
