# Fixture corpus for tests/test_pbftlint.py: each checker has a minimal
# positive case (*_pos), a negative twin (*_neg), and where relevant a
# suppression case. These files are PARSED by pbftlint, never imported.
