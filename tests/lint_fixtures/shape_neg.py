# pbftlint: shape-tracked-module
"""PBL006 negative twin: dispatch routed through shape recording, and
jit construction inside an opted-in (engine) module."""

import jax


class Verifier:
    def _build(self):
        return jax.jit(lambda x: x * 2)  # construction allowed here

    def dispatch(self, batch):
        self._record_shape("verify", len(batch))
        return self._fn(batch)
