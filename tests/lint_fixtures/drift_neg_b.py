"""PBL003 negative twin: an ALIAS single-sources the table (not a
display, never flags), and a small numeric tuple is below the
coincidence threshold."""

from tests.lint_fixtures import drift_neg_a

SHED_KINDS = drift_neg_a.WIRE_KINDS  # alias, not a mirrored literal

RETRY_SCHEDULE = (0, 1, 2)
