# pbftlint: consensus-module
"""PBL004 negative twin: audited entry point, or an explicit guard."""


def on_commit(tracer, seq):
    try:
        tracer.flush_all(seq)  # guarded: telemetry failure stays contained
    except Exception:
        pass


def on_execute(tracer, rid):
    tracer.emit(rid, "execute")  # audited no-raise entry point


def on_reply(tracer, rid):
    try:
        tracer.flush_all(rid)
    except (ValueError, Exception):  # tuple containing Exception = broad
        pass
