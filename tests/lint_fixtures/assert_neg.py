"""PBL005 negative twin: validation raises."""


def admit(batch):
    if not batch:
        raise ValueError("empty batch")
    return batch
