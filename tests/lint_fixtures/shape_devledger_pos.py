# pbftlint: shape-tracked-module
"""PBL006 positive (ISSUE 14 seam): a device-ledger record in the same
body must NOT launder the missing _record_shape — the ledger counts the
dispatch's cost, the shape recorder keeps post_warm_compiles honest,
and only the latter satisfies the check."""

from simple_pbft_tpu import devledger


class Verifier:
    def dispatch(self, batch):
        out = self._fn(batch)  # no _record_shape: must flag
        devledger.record("ed25519", "fused", 4, len(batch), len(batch))
        return out
