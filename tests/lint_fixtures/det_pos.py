# pbftlint: deterministic-module
"""PBL002 positive: every nondeterminism class in a replay module."""

import random
import time


def salt(node_id):
    return hash(node_id)  # PYTHONHASHSEED-salted (the ShapedTransport bug)


def jitter():
    return random.random()  # shared unseeded global RNG


def stamp():
    return time.time()  # wall clock in protocol content


def walk():
    for item in {"a", "b", "c"}:  # hash-order iteration
        print(item)
