# pbftlint: consensus-module
"""PBL004 positive: unguarded, unaudited telemetry call in a consensus
path."""


def on_commit(tracer, seq):
    tracer.flush_all(seq)  # not in AUDITED_NO_RAISE, no guard
