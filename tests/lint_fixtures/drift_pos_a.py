"""PBL003 positive, origin half: a literal kind table."""

WIRE_KINDS = ("request", "prepare", "commit")
