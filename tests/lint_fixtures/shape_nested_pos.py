# pbftlint: shape-tracked-module
"""PBL006 positive (nested-def boundary): a _record_shape inside a
nested callback must NOT satisfy the enclosing function's dispatch —
and the dispatch must be reported exactly once."""


class Verifier:
    def outer(self, batch):
        out = self._fn(batch)  # dispatch in OUTER body

        def cb(result):
            self._record_shape("verify", result)  # nested: doesn't count

        return out, cb
