# pbftlint: clock-injectable
"""PBL007 negative twin: the seam-compliant forms."""

from simple_pbft_tpu import clock


def cooldown_stamp():
    return clock.now()  # virtual under simulation, monotonic otherwise


async def retry_tick():
    await clock.sleep(0.4)  # ownership explicit at the seam


def schedule_delivery(loop, fn):
    # pbftlint: disable=PBL007 -- feeds call_at on the SAME loop: the virtualized timebase itself
    target = loop.time() + 0.5
    loop.call_at(target, fn)
