"""PBL003 positive, mirror half: the same table hand-copied (the
_DEFERRABLE_KINDS vs SHED_DEFERRABLE precedent)."""

SHED_KINDS = ("request", "prepare", "commit")
