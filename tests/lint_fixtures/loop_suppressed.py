"""PBL001 suppression case: justified disable is honored, bare is not."""

import time


async def documented_exception():
    time.sleep(0.1)  # pbftlint: disable=PBL001 -- fixture: capped, documented


async def bare_disable():
    time.sleep(0.1)  # pbftlint: disable=PBL001
