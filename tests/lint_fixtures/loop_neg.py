"""PBL001 negative twin: the same work, correctly off-loaded."""

import asyncio
import json
import time


def blocking_work():
    time.sleep(0.1)  # runs on a worker thread: caller off-loads it


async def handler(frames):
    await asyncio.to_thread(blocking_work)
    if frames:
        json.loads(frames[0])  # ONE decode per frame is the wire protocol


def sync_entry():
    time.sleep(0.1)  # never reachable from the loop: no caller is async
