"""PBL001 positive: blocking work reachable on the event loop."""

import json
import time


async def handler(frames):
    time.sleep(0.1)  # direct block in a coroutine
    for f in frames:
        json.loads(f)  # per-item decode in a loop statement


def helper():
    time.sleep(1)  # blocked, and transitively loop-resident via caller()


async def caller():
    helper()
