# pbftlint: deterministic-module
"""PBL002 negative twin: the sanctioned deterministic forms."""

import random
import time
import zlib


def salt(node_id):
    return zlib.crc32(node_id.encode())  # seed-independent


def jitter(rng: random.Random):
    return rng.random()  # private seeded RNG instance


def stamp():
    return time.monotonic()  # intervals, not protocol content


def walk():
    for item in sorted({"a", "b", "c"}):  # order fixed before iterating
        print(item)
