"""PBL003 negative twin, origin half."""

WIRE_KINDS = ("request", "prepare", "commit")

# small pure-numeric tuples recur legitimately and must not pair up
# with drift_neg_b's copy
RETRY_SCHEDULE = (0, 1, 2)
