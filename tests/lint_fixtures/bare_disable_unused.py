"""PBL000 positive: a bare disable that matches NO finding (dead
policy) must still flag — an unjustified marker is never a free pass."""

import time  # pbftlint: disable=PBL001


def not_even_loop_resident():
    return time.monotonic()
