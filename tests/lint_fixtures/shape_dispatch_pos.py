# pbftlint: shape-tracked-module
"""PBL006 positive (unrecorded dispatch): calling a jitted handle with
no _record_shape in the same body escapes post_warm_compiles."""


class Verifier:
    def dispatch(self, batch):
        return self._fn(batch)  # no shape recording
