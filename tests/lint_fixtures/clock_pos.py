# pbftlint: clock-injectable
"""PBL007 positive: every raw-clock class that bypasses the seam."""

import asyncio
import time


def cooldown_stamp():
    return time.monotonic()  # deadline math invisible to virtual time


def latency_anchor():
    return time.perf_counter()  # same class, different spelling


def wall_stamp():
    return time.time()  # wall read (also a PBL002 concern elsewhere)


async def retry_tick():
    await asyncio.sleep(0.4)  # must be clock.sleep at the seam


def loop_read(loop):
    return loop.time()  # raw loop-time read outside the call_at idiom
