"""PBL005 positive: assert in production control flow."""


def admit(batch):
    assert len(batch) > 0, "empty batch"  # vanishes under python -O
    return batch
