# pbftlint: shape-tracked-module
"""PBL006 negative twin of shape_devledger_pos: the full ISSUE 14
dispatch-recording seam — shape recording AND the device-ledger event
in the same body — is exactly what crypto/tpu_verifier.py does."""

from simple_pbft_tpu import devledger


class Verifier:
    def dispatch(self, batch):
        self._record_shape(len(batch))
        out = self._fn(batch)
        devledger.record("ed25519", "fused", 4, len(batch), len(batch))
        return out
