"""PBL006 positive (stray construction): jax.jit outside the registered
engine modules is a new unwarmed dispatch surface by definition."""

import jax


def make_kernel():
    return jax.jit(lambda x: x * 2)
