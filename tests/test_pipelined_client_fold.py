"""Directed tests for the signed completion floor (Request.ack).

A pipelined client (many concurrent submits over one identity) races the
checkpoint fold: the fold's horizon is measured in SEQS, so at high block
rates it passes in milliseconds and a dropped-then-retried lower
timestamp would come back SUPERSEDED instead of executing (the round-4
'terminal stall under fading load' failure mode). The fix: each Request
carries the client's signed completion floor — every own timestamp
<= ack is fully answered — and the fold never crosses it (replica.py
_emit_checkpoint), with a cap fallback bounding memory against clients
that never declare. The reference has no analog: its client sends one
request and exits without ever reading a reply (client.go:27-34), and
its request pool keeps exactly one request per client (requestPool.go).
"""

import asyncio

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.consensus.replica import RECENT_REPLIES_CAP
from simple_pbft_tpu.messages import Reply


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _mk_reply(client: str, ts: int, seq: int) -> Reply:
    return Reply(client_id=client, timestamp=ts, seq=seq, result="ok")


def test_fold_never_crosses_declared_floor():
    """Entries above the client's floor survive folds while fresh (their
    executing seq within STALE_FOLD_INTERVALS), no matter that the
    one-interval horizon has long passed them."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        # client answered through ts=105; ts>=106 may still be in flight
        r.client_ack["c0"] = 105
        r.recent_replies["c0"] = {
            ts: _mk_reply("c0", ts, seq=390) for ts in (104, 105, 106, 107)
        }
        # horizon (396) is past seq=390, stale bound (336) is not
        await r._emit_checkpoint(400)
        # at/below floor folded down to the top (105) which stays cached;
        # above-floor entries untouched
        assert set(r.recent_replies["c0"]) == {105, 106, 107}
        assert r.client_watermark["c0"] == 105
        # same-age fold again: still protected (stale bound 400-64=336)
        await r._emit_checkpoint(420)
        assert set(r.recent_replies["c0"]) == {105, 106, 107}
        assert r.client_watermark["c0"] == 105

    run(scenario())


def test_departed_client_window_ages_out():
    """A departed client's final in-flight window (floor never raised)
    folds once STALE_FOLD_INTERVALS checkpoint intervals pass — it must
    not ride every future snapshot forever."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        r.client_ack["c0"] = 105
        r.recent_replies["c0"] = {
            ts: _mk_reply("c0", ts, seq=390) for ts in (105, 106, 107)
        }
        # stale bound = 460 - 16*4 = 396 >= 390: everything ages out
        await r._emit_checkpoint(460)
        assert set(r.recent_replies["c0"]) == {107}  # top stays cached
        assert r.client_watermark["c0"] == 107

    run(scenario())


def test_fold_cap_fallback_bounds_undeclared_client():
    """A client that never declares a floor (ack=0) still folds by the
    seq horizon once its reply cache exceeds the cap — replay-state
    memory must not depend on client cooperation."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        n = RECENT_REPLIES_CAP + 10
        r.recent_replies["c0"] = {
            ts: _mk_reply("c0", ts, seq=ts) for ts in range(1, n + 1)
        }
        # below the cap nothing FRESH folds without a declaration
        # (seq chosen past the horizon-minus-stale window)
        r.recent_replies["c1"] = {
            ts: _mk_reply("c1", ts, seq=n + 90) for ts in (1, 2, 3)
        }
        await r._emit_checkpoint(n + 100)
        assert len(r.recent_replies["c0"]) == 1  # horizon fold, top kept
        assert r.client_watermark["c0"] == n
        assert set(r.recent_replies["c1"]) == {1, 2, 3}
        assert "c1" not in r.client_watermark

    run(scenario())


def test_active_client_siblings_never_age_out():
    """One fresh execution keeps the whole window alive: an ACTIVE
    pipelined client's above-floor siblings must survive the stale
    age-out no matter how old they are (sustained third-party load must
    not reintroduce the fold race via the staleness rule)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        r.client_ack["c0"] = 105
        r.recent_replies["c0"] = {
            105: _mk_reply("c0", 105, seq=10),   # ancient, at floor
            107: _mk_reply("c0", 107, seq=10),   # ancient, above floor
            109: _mk_reply("c0", 109, seq=458),  # fresh: client is alive
        }
        # stale bound = 460-64 = 396: 107 is way past it, but the fresh
        # 109 (seq 458 > 396) vetoes the age-out for the whole window
        await r._emit_checkpoint(460)
        assert set(r.recent_replies["c0"]) == {105, 107, 109}
        assert r.client_watermark["c0"] == 105

    run(scenario())


def test_quiesced_ack_entries_pruned():
    """A floor at/below the watermark gates nothing and is dropped at
    the next fold: departed clients leave only their watermark entry."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        r.client_ack["gone"] = 50
        r.client_watermark["gone"] = 50
        r.client_ack["live"] = 200
        r.client_watermark["live"] = 150
        await r._emit_checkpoint(400)
        assert "gone" not in r.client_ack
        assert r.client_ack["live"] == 200
        assert r.client_watermark["gone"] == 50  # replay floor persists

    run(scenario())


def test_cap_counts_only_above_floor_entries():
    """A declaring client whose recent below-floor executions exceed the
    cap must NOT lose floor protection: the fallback counts only
    above-floor (genuinely unfoldable) entries, since below-floor ones
    fold within one interval by the horizon rule anyway."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        r = com.replicas[0]
        r.client_ack["c0"] = 600
        recent = {
            ts: _mk_reply("c0", ts, seq=390)
            for ts in range(1, RECENT_REPLIES_CAP + 9)  # below floor
        }
        recent[700] = _mk_reply("c0", 700, seq=390)  # in flight (above)
        recent[701] = _mk_reply("c0", 701, seq=390)
        r.recent_replies["c0"] = recent
        await r._emit_checkpoint(400)
        top = RECENT_REPLIES_CAP + 8
        assert set(r.recent_replies["c0"]) == {top, 700, 701}
        assert r.client_watermark["c0"] == top  # floor never crossed

    run(scenario())


def test_ack_floor_rides_executed_blocks():
    """End to end: sequential submits carry a rising floor, replicas pick
    it up from executed blocks, and folds converge identically (the floor
    is checkpoint state — divergence would split checkpoint digests)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        com.start()
        try:
            cl = com.clients[0]
            for i in range(10):
                assert await cl.submit(f"put k{i} v{i}") == "ok"
            # submit() returns at f+1 matching replies — let the laggard
            # replicas finish executing the last block before reading
            await asyncio.sleep(0.3)
            floors = {r.client_ack.get("c0", 0) for r in com.replicas}
            assert len(floors) == 1
            # floor = oldest-outstanding-1: after 10 serial submits it
            # trails the last used timestamp (probe - 1) by exactly one
            probe_ts = next(cl._ts)
            assert floors.pop() == probe_ts - 2
        finally:
            await com.stop()

    run(scenario())
