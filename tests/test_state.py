"""Unit tests for the pure PBFT instance state machine."""

from simple_pbft_tpu.consensus.state import (
    ExecuteBlock,
    Instance,
    SendCommit,
    SendPrepare,
    Stage,
)
from simple_pbft_tpu.messages import Commit, PrePrepare, Prepare


QUORUM = 3  # n=4, f=1 -> 2f+1 = 3


def make_preprepare(view=0, seq=1, sender="r0"):
    block = [{"client_id": "c0", "timestamp": 1, "operation": "x"}]
    return PrePrepare(
        sender=sender,
        view=view,
        seq=seq,
        digest=PrePrepare.block_digest(block),
        block=block,
    )


def test_happy_path_full_round():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")

    acts = inst.on_pre_prepare(pp)
    assert [type(a) for a in acts] == [SendPrepare]
    assert inst.stage == Stage.PRE_PREPARED

    # 3 prepare votes (incl. own) -> prepared, send commit
    acts = []
    for r in ["r0", "r1", "r2"]:
        acts += inst.on_prepare(
            Prepare(sender=r, view=0, seq=1, digest=pp.digest)
        )
    assert [type(a) for a in acts] == [SendCommit]
    assert inst.stage == Stage.PREPARED

    acts = []
    for r in ["r1", "r2", "r3"]:
        acts += inst.on_commit(
            Commit(sender=r, view=0, seq=1, digest=pp.digest)
        )
    assert [type(a) for a in acts] == [ExecuteBlock]
    assert inst.stage == Stage.COMMITTED
    assert acts[0].block == pp.block


def test_votes_before_preprepare_buffered_then_fire():
    """Prepare votes arriving before the pre-prepare (network reordering —
    the hazard the reference's pools absorb, SURVEY.md §3.3) must count
    once the proposal lands."""
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    for r in ["r1", "r2", "r3"]:
        assert inst.on_prepare(
            Prepare(sender=r, view=0, seq=1, digest=pp.digest)
        ) == []
    acts = inst.on_pre_prepare(pp)
    # pre-prepare triggers own prepare AND the already-satisfied quorum
    assert [type(a) for a in acts] == [SendPrepare, SendCommit]
    assert inst.stage == Stage.PREPARED


def test_duplicate_votes_dont_count():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    inst.on_pre_prepare(pp)
    for _ in range(5):
        inst.on_prepare(Prepare(sender="r1", view=0, seq=1, digest=pp.digest))
    assert not inst.prepared()


def test_wrong_digest_votes_dont_count():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    inst.on_pre_prepare(pp)
    for r in ["r1", "r2", "r3"]:
        inst.on_prepare(Prepare(sender=r, view=0, seq=1, digest="evil"))
    assert not inst.prepared()


def test_wrong_view_or_seq_ignored():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    inst.on_pre_prepare(pp)
    assert inst.on_prepare(Prepare(sender="r1", view=1, seq=1, digest=pp.digest)) == []
    assert inst.on_prepare(Prepare(sender="r1", view=0, seq=2, digest=pp.digest)) == []
    assert inst.prepares == {}


def test_preprepare_digest_mismatch_rejected():
    pp = make_preprepare()
    pp.digest = "not-the-block-digest"
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    assert inst.on_pre_prepare(pp) == []
    assert inst.stage == Stage.IDLE


def test_conflicting_preprepare_first_wins():
    pp1 = make_preprepare()
    block2 = [{"client_id": "c0", "timestamp": 2, "operation": "y"}]
    pp2 = PrePrepare(
        sender="r0", view=0, seq=1,
        digest=PrePrepare.block_digest(block2), block=block2,
    )
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    inst.on_pre_prepare(pp1)
    assert inst.on_pre_prepare(pp2) == []
    assert inst.digest == pp1.digest


def test_execute_fires_exactly_once():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    inst.on_pre_prepare(pp)
    for r in ["r0", "r1", "r2"]:
        inst.on_prepare(Prepare(sender=r, view=0, seq=1, digest=pp.digest))
    execs = []
    for r in ["r0", "r1", "r2", "r3"]:
        for a in inst.on_commit(Commit(sender=r, view=0, seq=1, digest=pp.digest)):
            if isinstance(a, ExecuteBlock):
                execs.append(a)
    assert len(execs) == 1


def test_prepared_proof_certificate():
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    assert inst.prepared_proof() is None
    inst.on_pre_prepare(pp)
    for r in ["r0", "r1", "r2"]:
        inst.on_prepare(Prepare(sender=r, view=0, seq=1, digest=pp.digest))
    proof = inst.prepared_proof()
    assert proof is not None
    assert proof["pre_prepare"]["digest"] == pp.digest
    assert len(proof["prepares"]) == QUORUM


def test_larger_committee_quorum():
    # n=7, f=2, quorum=5
    pp = make_preprepare()
    inst = Instance(view=0, seq=1, quorum=5, primary="r0")
    inst.on_pre_prepare(pp)
    for r in ["r0", "r1", "r2", "r3"]:
        inst.on_prepare(Prepare(sender=r, view=0, seq=1, digest=pp.digest))
    assert not inst.prepared()
    inst.on_prepare(Prepare(sender="r4", view=0, seq=1, digest=pp.digest))
    assert inst.prepared()


def test_preprepare_from_non_primary_rejected():
    """A Byzantine backup must not steal a slot with its own pre-prepare."""
    pp = make_preprepare(sender="r3")
    inst = Instance(view=0, seq=1, quorum=QUORUM, primary="r0")
    assert inst.on_pre_prepare(pp) == []
    assert inst.stage == Stage.IDLE
    # the real primary's proposal still lands
    assert [type(a) for a in inst.on_pre_prepare(make_preprepare(sender="r0"))] == [
        SendPrepare
    ]


class TestQcModeInstance:
    """State-machine-level QC-mode safety (the two hard cases from
    review: QC-before-pre-prepare orderings)."""

    def _qc(self, phase, digest):
        from simple_pbft_tpu.messages import QuorumCert

        return QuorumCert(
            phase=phase, view=0, seq=1, digest=digest,
            signers=["r0", "r1", "r2"], agg_sig="ab",
        )

    def test_equivocation_after_commit_qc_rejected(self):
        """A commit QC fixes the slot's digest; an equivocating primary's
        later pre-prepare for a DIFFERENT block must not execute."""
        from simple_pbft_tpu.messages import PrePrepare
        from simple_pbft_tpu.consensus.state import Instance

        inst = Instance(view=0, seq=1, quorum=3, primary="r0", qc_mode=True)
        committed_digest = "d" * 64
        assert inst.on_commit_qc(self._qc("commit", committed_digest)) == []
        evil_block = [{"kind": "request", "sender": "cX", "client_id": "cX",
                       "timestamp": 1, "operation": "evil", "sig": "00"}]
        pp = PrePrepare(view=0, seq=1,
                        digest=PrePrepare.block_digest(evil_block),
                        block=evil_block)
        pp.sender = "r0"
        assert inst.on_pre_prepare(pp) == []
        assert inst.block is None and not inst.executed

    def test_commit_share_waits_for_preprepare(self):
        """A prepare QC alone must NOT emit the commit share — the replica
        could not prove the slot in a view change (quorum intersection).
        The share goes out once the pre-prepare lands."""
        from simple_pbft_tpu.messages import PrePrepare
        from simple_pbft_tpu.consensus.state import Instance, SendCommit

        inst = Instance(view=0, seq=1, quorum=3, primary="r0", qc_mode=True)
        block = []
        digest = PrePrepare.block_digest(block)
        acts = inst.on_prepare_qc(self._qc("prepare", digest))
        assert not any(isinstance(a, SendCommit) for a in acts)
        pp = PrePrepare(view=0, seq=1, digest=digest, block=block)
        pp.sender = "r0"
        acts = inst.on_pre_prepare(pp)
        assert any(isinstance(a, SendCommit) for a in acts)
        assert inst.prepared_proof() is not None
