"""QC-mode committees: BLS aggregate certificates driving consensus.

BASELINE config 4: instead of O(n^2) vote broadcasts, votes carry BLS
shares to the primary, which aggregates 2f+1 into a QuorumCert verified
with ONE pairing check. Covers: the happy path, a Byzantine share
corrupting the aggregate (bisection), primary-crash failover with
QC-based prepared certificates, and a large committee committing with
one aggregate check per QC.
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee


def run(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_qc_committee_commits():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, qc_mode=True, view_timeout=30.0)
        com.clients[0].request_timeout = 30.0
        com.start()
        try:
            assert await com.clients[0].submit("put k 1") == "ok"
            rs = await asyncio.gather(
                *(com.clients[0].submit(f"put q{i} {i}") for i in range(4))
            )
            assert rs == ["ok"] * 4
            assert await com.clients[0].submit("get k") == "1"
            await asyncio.sleep(0.5)
        finally:
            await com.stop()
        for r in com.replicas:
            assert r.metrics["committed_requests"] >= 6
        primary = com.replica("r0")
        assert primary.metrics["qcs_formed"] >= 4  # 2 phases x >= 2 blocks
        # backups never reach vote quorums locally — QCs drove them
        for r in com.replicas[1:]:
            assert r.metrics["qcs_formed"] == 0

    run(scenario())


def test_qc_byzantine_share_bisected():
    """A replica that ships garbage BLS shares must not stall the
    committee: the primary's aggregate self-check fails, bisection drops
    the bad share, and the quorum forms from the honest 2f+1."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, qc_mode=True, view_timeout=60.0)
        com.clients[0].request_timeout = 60.0
        # r3 signs shares for the WRONG payload (valid curve point, valid
        # ed25519 envelope — only the pairing can catch it)
        evil = com.replica("r3")
        from simple_pbft_tpu.consensus import qc as qc_mod

        orig = qc_mod.sign_share
        calls = {"n": 0}

        def corrupt(sk, phase, view, seq, digest):
            if sk == evil.bls_sk:
                calls["n"] += 1
                return orig(sk, phase, view + 1000, seq, digest)
            return orig(sk, phase, view, seq, digest)

        qc_mod.sign_share = corrupt
        com.start()
        try:
            assert await com.clients[0].submit("put z 9") == "ok"
            await asyncio.sleep(0.5)
        finally:
            qc_mod.sign_share = orig
            await com.stop()
        primary = com.replica("r0")
        assert calls["n"] >= 1  # the corrupt path actually ran
        # either the bad share landed in an aggregate (bisected) or the
        # primary formed the quorum from the honest 3 before r3's share
        assert (
            primary.metrics.get("qc_bad_shares", 0) >= 1
            or primary.metrics["qcs_formed"] >= 2
        )

    run(scenario())


def test_qc_failover_preserves_state():
    """Kill the primary mid-run: the committee view-changes using
    QC-based prepared certificates and the new view serves old state."""

    async def scenario():
        # timers must dominate the ~1 s/pairing pure-Python QC latency on
        # a busy single-core host or the failover retries before it lands
        com = LocalCommittee.build(n=4, clients=1, qc_mode=True, view_timeout=4.0)
        com.clients[0].request_timeout = 8.0
        com.start()
        try:
            assert await com.clients[0].submit("put a 1") == "ok"
            com.replica("r0").kill()
            assert await com.clients[0].submit("put b 2", retries=60) == "ok"
            assert await com.clients[0].submit("get a", retries=60) == "1"
            views = {x.id: x.view for x in com.replicas if x._running}
            assert all(v >= 1 for v in views.values()), views
        finally:
            await com.stop()

    run(scenario())


@pytest.mark.slow
def test_qc_large_committee_single_aggregate_check():
    """BASELINE config 4 shape: a large committee commits a block where
    the whole prepare/commit quorum is certified by ONE aggregate each.
    n=32 keeps CI wall-clock sane (the BLS key generation is ~40 ms/key
    and the in-process simulation serializes all replicas on one core);
    bench_consensus --qc runs the full n=256."""

    async def scenario():
        n = 32
        com = LocalCommittee.build(
            n=n, clients=1, qc_mode=True, view_timeout=120.0
        )
        com.clients[0].request_timeout = 120.0
        com.start()
        try:
            assert await com.clients[0].submit("put big 1") == "ok"
            await asyncio.sleep(1.0)
        finally:
            await com.stop()
        primary = com.replica("r0")
        assert primary.metrics["qcs_formed"] == 2  # one per phase
        committed = sum(
            1 for r in com.replicas if r.metrics["committed_requests"] >= 1
        )
        assert committed >= com.cfg.quorum

    run(scenario(), timeout=600)


def test_qc_checkpoint_aggregate_in_viewchange():
    """QC-mode failover after a stable checkpoint: the VIEW-CHANGE must
    prove h with ONE CheckpointQC aggregate instead of 2f+1 signed
    checkpoint messages, and peers must accept it (failover completes,
    state survives)."""

    async def _eventually(pred, timeout=10.0):
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            if pred():
                return True
            await asyncio.sleep(0.05)
        return False

    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, qc_mode=True, view_timeout=4.0,
            checkpoint_interval=2,
        )
        com.clients[0].request_timeout = 8.0
        com.start()
        try:
            for i in range(4):  # past two checkpoint intervals
                assert await com.clients[0].submit(f"put k{i} {i}") == "ok"
            # submit returns on f+1 replies; poll for committee-wide state
            assert await _eventually(
                lambda: all(r.stable_seq > 0 for r in com.replicas)
            )
            com.replica("r0").kill()
            survivors = [r for r in com.replicas if r.id != "r0"]
            submit = asyncio.create_task(
                com.clients[0].submit("put after 1", retries=60)
            )
            # capture the aggregate WHILE the failover holds it: the
            # CheckpointQC at h is built for the VIEW-CHANGE and GC'd
            # once the new view's commits advance the stable watermark
            # past it (faster now that the speculative fast path answers
            # clients before the commit wave lands — ISSUE 15)
            got_qc = []

            def _snap_qcs():
                for r in survivors:
                    for c in r.checkpoint_qcs.values():
                        got_qc.append(c)
                return bool(got_qc)

            assert await _eventually(_snap_qcs, timeout=30.0)
            assert await submit == "ok"
            assert all(r.view >= 1 for r in survivors)
            assert await _eventually(
                lambda: all(r.app.data.get("after") == "1" for r in survivors)
            )
            qc = got_qc[0]
            assert qc.phase == "checkpoint" and len(qc.signers) >= com.cfg.quorum
        finally:
            await com.stop()

    run(scenario())
