"""bench_gate + wan_campaign analysis units (ISSUE 12): the noise-aware
regression gate flags a seeded 30% throughput regression, passes an
unmodified repeat, widens with measured reference noise (MAD), enforces
hardware-portable absolute floors (the CI canary path), and refuses
cross-schema comparisons; the campaign's epoch-boundary spike
measurement is exercised on synthetic slot timelines."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_gate = _load_tool("bench_gate")
wan_campaign = _load_tool("wan_campaign")
campaign_report = _load_tool("campaign_report")


def mkline(cell, *, req_s=100.0, p50=40.0, p99=120.0, msgs_slot=41.0,
           bytes_slot=11000.0, schema=1, **extra):
    doc = {
        "schema_version": schema,
        "bench": "wan_campaign",
        "cell": cell,
        "n": 4,
        "profile": "none",
        "transport": "tcp",
        "committed_req_s": req_s,
        "p50_ms": p50,
        "p99_ms": p99,
        "client_timeouts": 0,
        "wire": {"per_commit": {
            "total_msgs_per_slot": msgs_slot,
            "total_bytes_per_slot": bytes_slot,
            "total_msgs_per_req": msgs_slot / 3,
            "total_bytes_per_req": bytes_slot / 3,
        }},
    }
    doc.update(extra)
    return doc


def repeats(cell, base=100.0, jitter=(1.0, 0.97, 1.03, 0.99, 1.01), **kw):
    return [mkline(cell, req_s=base * j, **kw) for j in jitter]


class TestGate:
    def test_unmodified_repeat_passes(self):
        ref = repeats("c1")
        fresh = repeats("c1", jitter=(0.98, 1.02, 1.0))
        rep = bench_gate.run_gate(fresh, ref)
        assert rep["ok"], rep
        assert rep["cells_compared"] == ["c1"]

    def test_seeded_30pct_throughput_regression_flags(self):
        ref = repeats("c1")
        fresh = repeats("c1", base=70.0, jitter=(1.0, 0.99, 1.01))
        rep = bench_gate.run_gate(fresh, ref)
        assert not rep["ok"]
        metrics = {r["metric"] for r in rep["regressions"]}
        assert "committed_req_s" in metrics, rep

    def test_latency_regression_flags_and_improvement_does_not(self):
        ref = repeats("c1")
        worse = [mkline("c1", p99=300.0)]
        rep = bench_gate.run_gate(worse, ref)
        assert {r["metric"] for r in rep["regressions"]} == {"p99_ms"}
        better = [mkline("c1", req_s=200.0, p50=10.0, p99=30.0)]
        assert bench_gate.run_gate(better, ref)["ok"]

    def test_wire_cost_regression_is_tighter_than_throughput(self):
        ref = repeats("c1")
        # +20% msgs/slot: the wire metrics are deterministic, so the
        # floor is 15% and this flags even though 20% of throughput
        # would pass
        fresh = [mkline("c1", msgs_slot=49.3)]
        rep = bench_gate.run_gate(fresh, ref)
        assert {r["metric"] for r in rep["regressions"]} == {
            "wire.per_commit.total_msgs_per_slot"
        }

    def test_measured_noise_widens_the_tolerance(self):
        # the reference itself wobbles ±40%: MAD scaling must not flag a
        # fresh median well inside that spread
        ref = repeats("c1", jitter=(1.0, 1.4, 0.6, 1.3, 0.7))
        fresh = repeats("c1", base=65.0, jitter=(1.0, 1.01, 0.99))
        rep = bench_gate.run_gate(fresh, ref)
        assert rep["ok"], rep

    def test_missing_cell_and_schema_mismatch_are_structural_errors(self):
        ref = repeats("c1") + repeats("c2")
        rep = bench_gate.run_gate(repeats("c1"), ref)
        assert not rep["ok"] and any("c2" in e for e in rep["errors"])
        rep2 = bench_gate.run_gate(
            [mkline("c1", schema=99)], repeats("c1"))
        assert any("schema_version" in e for e in rep2["errors"])

    def test_floors_mode_is_absolute_and_skips_relative(self):
        ref = [mkline("ci", req_s=1000.0, gate_mode="floors",
                      gate={"min": {"committed_req_s": 5.0},
                            "max": {"client_timeouts": 0}})]
        # 95% below the (other-hardware) reference median: floors-only
        # mode must still pass — it clears the absolute floor
        assert bench_gate.run_gate([mkline("ci", req_s=50.0)], ref)["ok"]
        # below the floor: flagged
        rep = bench_gate.run_gate([mkline("ci", req_s=2.0)], ref)
        assert not rep["ok"] and rep["regressions"][0]["bound"] == "min=5.0"
        # ceiling: timeouts above max flag
        rep2 = bench_gate.run_gate(
            [mkline("ci", client_timeouts=3)], ref)
        assert any(r["metric"] == "client_timeouts"
                   for r in rep2["regressions"])

    def test_canary_floor_raised_10x_fails(self):
        # the CI canary shape: copy the reference, raise the throughput
        # floor to 10x the measured fresh value — the gate MUST fail
        fresh = [mkline("ci", req_s=50.0)]
        canary = [mkline("ci", req_s=1000.0, gate_mode="floors",
                         gate={"min": {"committed_req_s": 500.0}})]
        rep = bench_gate.run_gate(fresh, canary)
        assert not rep["ok"]

    def test_device_floors_and_relative_directions(self):
        """ISSUE 14: device-ledger aggregates gate both ways — absolute
        floors (the device-smoke CI shape, including the checked-in
        reference file) and relative directions (occupancy/items-per-
        dispatch only regress DOWN, pad waste only UP)."""
        dev = {"dispatches": 20, "occupancy": 0.9,
               "items_per_dispatch": 12.0, "pad_waste_pct": 20.0,
               "verifies_per_s_effective": 5000.0}
        # the committed CI reference accepts a healthy device cell
        ref_path = os.path.join(
            ROOT, "bench_results", "device_ci_reference.jsonl")
        ref = [json.loads(l) for l in open(ref_path)]
        fresh = [mkline("device-smoke-cpu", device=dev)]
        assert bench_gate.run_gate(fresh, ref)["ok"]
        # an impossible occupancy floor flags (the CI canary shape)
        canary = json.loads(json.dumps(ref))
        canary[0]["gate"]["min"]["device.occupancy"] = 2.0
        rep = bench_gate.run_gate(fresh, canary)
        assert not rep["ok"]
        assert any(r["metric"] == "device.occupancy"
                   for r in rep["regressions"])
        # relative mode: coalescing regression (items/dispatch halved)
        # and pad-waste blowup flag; an occupancy IMPROVEMENT does not
        ref_rel = [mkline("dev", device=dev)]
        worse = dict(dev, items_per_dispatch=4.0, pad_waste_pct=60.0,
                     occupancy=0.99)
        rep2 = bench_gate.run_gate([mkline("dev", device=worse)], ref_rel)
        flagged = {r["metric"] for r in rep2["regressions"]}
        assert "device.items_per_dispatch" in flagged
        assert "device.pad_waste_pct" in flagged
        assert "device.occupancy" not in flagged

    def test_cli_exit_codes_and_json(self, tmp_path):
        ref_p, fresh_p = tmp_path / "ref.jsonl", tmp_path / "fresh.jsonl"
        ref_p.write_text(
            "\n".join(json.dumps(d) for d in repeats("c1")) + "\n")
        fresh_p.write_text(json.dumps(mkline("c1")) + "\n")
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "bench_gate.py"),
             "--fresh", str(fresh_p), "--reference", str(ref_p), "--json"],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout)["ok"] is True
        fresh_p.write_text(json.dumps(mkline("c1", req_s=50.0)) + "\n")
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "bench_gate.py"),
             "--fresh", str(fresh_p), "--reference", str(ref_p), "--json"],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 1
        assert json.loads(out.stdout)["regressions"]
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "bench_gate.py"),
             "--fresh", str(fresh_p), "--reference",
             str(tmp_path / "empty.jsonl"), "--json"],
            capture_output=True, text=True, cwd=ROOT)
        assert out.returncode == 2


class TestSpikeMeasurement:
    def test_flat_series_has_zero_width(self):
        slots = [(float(i), 50.0 + (i % 3)) for i in range(40)]
        spike = wan_campaign.measure_commit_spike(slots)
        assert spike["width_s"] == 0.0 and spike["spike_slots"] == 0
        assert spike["baseline_ms"] == pytest.approx(51.0, abs=1.0)

    def test_epoch_boundary_excursion_width(self):
        # 0.2 s per slot baseline 50 ms; slots 20-22 spike to 400/900/
        # 400 ms — the stop-sequencing stall shape
        slots = []
        for i in range(40):
            t = i * 0.2
            e2e = 50.0
            if i in (20, 21, 22):
                e2e = {20: 400.0, 21: 900.0, 22: 400.0}[i]
            slots.append((t + e2e / 1e3, e2e))
        spike = wan_campaign.measure_commit_spike(slots)
        assert spike["spike_slots"] == 3
        assert spike["peak_ms"] == 900.0
        # width: first affected slot start (t=4.0) to last end (~4.8)
        assert 0.5 < spike["width_s"] < 1.5, spike
        assert spike["baseline_ms"] == 50.0

    def test_empty_series(self):
        spike = wan_campaign.measure_commit_spike([])
        assert spike == {"slots": 0, "baseline_ms": 0.0,
                         "threshold_ms": 0.0, "spike_slots": 0,
                         "peak_ms": 0.0, "width_s": 0.0}

    def test_slot_series_joins_phase_spans(self):
        spans = []
        for seq in (1, 2):
            for stage, dur in (("phase.prepare", 10.0),
                               ("phase.commit", 20.0),
                               ("phase.execute", 1.0)):
                spans.append({"evt": "span", "stage": stage, "node": "r0",
                              "view": 0, "seq": seq, "dur_ms": dur,
                              "t_mono": 100.0 + seq})
        # incomplete slot (no execute) and foreign node are excluded
        spans.append({"evt": "span", "stage": "phase.prepare", "node": "r0",
                      "view": 0, "seq": 3, "dur_ms": 5.0, "t_mono": 104.0})
        spans.append({"evt": "span", "stage": "phase.execute", "node": "r9",
                      "view": 0, "seq": 4, "dur_ms": 5.0, "t_mono": 105.0})
        series = wan_campaign.slot_series(spans, "r0")
        assert series == [(101.0, 31.0), (102.0, 31.0)]


class TestCampaignReport:
    def test_render_curves_and_reconfig_section(self):
        cells = [
            mkline("wan-tcp-n4-none-o16", n=4, profile="none",
                   critical_path={"decomposition": [
                       {"pct": 99.0, "shares": {"phase.prepare": 0.7,
                                                "phase.commit": 0.3}}]}),
            mkline("wan-tcp-n4-lossy-o16", n=4, profile="lossy",
                   req_s=60.0, p99=400.0),
            mkline("wan-tcp-n16-none-o16", n=16, profile="none",
                   req_s=40.0, msgs_slot=530.0),
        ]
        reconf = mkline("wan-tcp-n5-none-o16-reconfig", n=5)
        reconf["reconfig"] = {
            "result": "reconfig-staged:epoch=1:activate_at=48",
            "removed": "r4", "activated": True, "spike_width_s": 0.35,
            "spike": {"width_s": 0.35, "peak_ms": 348.0,
                      "baseline_ms": 108.0, "slots": 83,
                      "spike_slots": 1, "threshold_ms": 325.0},
        }
        md = campaign_report.render(cells + [reconf])
        assert "## Committed req/s — n × profile" in md
        assert "| 16 |" in md
        assert "prepare 70%" in md
        assert "spike width: 0.35 s" in md
        assert "lossy" in md
