"""Speculative pipelined execution + fault-tolerant rollback (ISSUE 15).

Covers the tentpole's correctness surface: honest runs converge
(speculative digests == final digests, every spec slot confirmed),
forced divergence rolls back cleanly with a clean audit bill,
out-of-order slots execute only over committed disjoint gaps, and
speculative state never reaches a checkpoint snapshot.
"""

import asyncio
import json

import pytest

from simple_pbft_tpu.app import ForkableApp, KVStore
from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.consensus import speculation as spec_mod
from simple_pbft_tpu.consensus.replica import RECONFIG_PREFIX
from simple_pbft_tpu.consensus.state import ExecuteBlock, Instance
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import (
    EMPTY_BLOCK_DIGEST,
    NewView,
    PrePrepare,
    Prepare,
    Request,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _signed_request(keys, ts, op, client="c0"):
    req = Request(client_id=client, timestamp=ts, operation=op)
    Signer(client, keys[client].seed).sign_msg(req)
    return req


def _signed_pp(keys, sender, view, seq, reqs):
    block = [r.to_dict() for r in reqs]
    pp = PrePrepare(
        view=view, seq=seq, digest=PrePrepare.block_digest(block),
        block=block,
    )
    Signer(sender, keys[sender].seed).sign_msg(pp)
    return pp


async def _prepare_slot(com, replica, seq, reqs, view=0):
    """Drive one replica to PREPARED at (view, seq) for a block of
    ``reqs``: the primary's pre-prepare plus the other replicas'
    prepare votes (the replica's own vote self-counts)."""
    primary = com.cfg.primary(view)
    pp = _signed_pp(com.keys, primary, view, seq, reqs)
    await replica.on_phase_msg(pp)
    for rid in com.cfg.replica_ids:
        if rid in (replica.id, primary):
            continue
        vote = Prepare(view=view, seq=seq, digest=pp.digest)
        Signer(rid, com.keys[rid].seed).sign_msg(vote)
        await replica.on_phase_msg(vote)
    return pp


# ---------------------------------------------------------------------------
# honest runs: spec == final, everything confirms
# ---------------------------------------------------------------------------


def test_honest_run_spec_equals_final():
    """End to end: with speculation on, every slot executes at PREPARED,
    every speculation confirms at commit, nothing rolls back, and the
    speculative fork's digest converges to the committed digest on every
    replica (spec == final)."""

    async def main():
        com = LocalCommittee.build(n=4, clients=2)
        com.start()
        for i in range(6):
            assert await com.clients[i % 2].submit(f"put k{i} v{i}") == "ok"
        # drain: let the last commits confirm everywhere
        for _ in range(100):
            if all(
                r.metrics.get("spec_confirmed", 0)
                >= r.metrics.get("spec_executed", 0) > 0
                and not r.spec.slots
                for r in com.replicas
            ):
                break
            await asyncio.sleep(0.05)
        for r in com.replicas:
            assert r.spec is not None and r.spec.enabled
            assert r.metrics.get("spec_executed", 0) > 0
            assert r.metrics.get("spec_rolled_back", 0) == 0
            assert (
                r.metrics["spec_confirmed"] == r.metrics["spec_executed"]
            ), r.metrics
            # spec == final: the fork (if still open) matches committed
            fork_digest = r.spec.app.spec_digest()
            if fork_digest is not None:
                assert fork_digest == r.app.state_digest()
        # the client used the fast path and got final confirmation
        total_spec = sum(
            c.metrics.get("spec_accepted", 0) for c in com.clients
        )
        total_confirm = sum(
            c.metrics.get("final_confirms", 0) for c in com.clients
        )
        assert total_spec > 0 and total_confirm > 0
        assert not any(
            c.metrics.get("spec_final_mismatch", 0) for c in com.clients
        )
        await com.stop()

    run(main())


# ---------------------------------------------------------------------------
# forced divergence: rollback at NEW-VIEW install, clean audit bill
# ---------------------------------------------------------------------------


def test_forced_divergence_rolls_back_cleanly(tmp_path):
    """A backup speculates a PREPARED block, then a NEW-VIEW installs
    whose O-set no-op-fills the slot (the block was prepared by too few
    replicas to survive the view change). The speculated suffix must
    walk back to the committed anchor, the no-op and the re-proposed
    work must execute cleanly, and the audit plane must have nothing to
    say (speculation is local — rollback is not a safety event):
    tools/ledger_audit.py exits 0 over the run's ledgers."""

    async def main():
        from tools import ledger_audit

        com = LocalCommittee.build(
            n=4, clients=1, verify_signatures=False, view_timeout=0,
        )
        auditors = com.attach_auditors(log_dir=str(tmp_path))
        r1 = com.replica("r1")
        req = _signed_request(com.keys, ts=7, op="put a 1")
        await _prepare_slot(com, r1, seq=1, reqs=[req])
        assert 1 in r1.spec.slots  # speculated at PREPARED
        assert r1.metrics["spec_executed"] == 1
        assert json.loads(r1.app.snapshot()) == {}  # committed untouched
        assert r1.spec.app.spec_digest() != r1.app.state_digest()

        # view change: the NEW-VIEW's O-set no-op-fills seq 1 (nobody
        # else prepared it, and our VC was not in the certificate)
        noop = PrePrepare(
            view=1, seq=1, digest=EMPTY_BLOCK_DIGEST, block=[],
        )
        Signer("r1", com.keys["r1"].seed).sign_msg(noop)  # view 1 primary
        nv = NewView(new_view=1, pre_prepares=[noop.to_dict()])
        await r1.vc.install(1, nv)
        assert r1.metrics.get("spec_rolled_back", 0) == 1
        assert not r1.spec.slots and not r1.spec.app.spec_open()

        # the no-op commits in view 1; the request re-proposes behind it
        for rid in ("r0", "r2", "r3"):
            from simple_pbft_tpu.messages import Commit, Prepare as Prep

            for cls in (Prep, Commit):
                vote = cls(view=1, seq=1, digest=EMPTY_BLOCK_DIGEST)
                Signer(rid, com.keys[rid].seed).sign_msg(vote)
                await r1.on_phase_msg(vote)
        assert r1.executed_seq == 1
        assert json.loads(r1.app.snapshot()) == {}  # the no-op won

        req2 = _signed_request(com.keys, ts=9, op="put a 2")
        await _prepare_slot(com, r1, seq=2, reqs=[req2], view=1)
        assert 2 in r1.spec.slots  # re-speculation after the rollback
        from simple_pbft_tpu.messages import Commit

        for rid in ("r0", "r2", "r3"):
            vote = Commit(
                view=1, seq=2,
                digest=r1.instances[(1, 2)].digest,
            )
            Signer(rid, com.keys[rid].seed).sign_msg(vote)
            await r1.on_phase_msg(vote)
        assert r1.executed_seq == 2
        assert r1.app.data == {"a": "2"}
        # two confirmations: the re-prepared no-op at seq 1 speculates
        # too (trivially), then the re-proposed block at seq 2
        assert r1.metrics.get("spec_confirmed", 0) == 2
        # fork back in lockstep after confirm
        fork = r1.spec.app.spec_digest()
        assert fork is None or fork == r1.app.state_digest()

        for a in auditors.values():
            a.close()
        report, code = ledger_audit.run_audit(
            [str(tmp_path)], cfg=com.cfg
        )
        assert code == 0, report  # clean bill: rollback is not evidence

    run(main())


# ---------------------------------------------------------------------------
# out-of-order speculation over committed disjoint gaps
# ---------------------------------------------------------------------------


def _inst(com, view, seq, reqs):
    block = [r.to_dict() for r in reqs]
    inst = Instance(
        view=view, seq=seq, quorum=com.cfg.quorum,
        primary=com.cfg.primary(view),
    )
    inst.digest = PrePrepare.block_digest(block)
    inst.block = block
    return inst


def test_out_of_order_disjoint_executes_conflicting_does_not():
    """A slot PREPARED above an execution hole speculates iff every gap
    slot is COMMITTED with a known block (parked in replica.ready) and
    the candidate's read/write sets are disjoint from the gap's —
    commitment fixes the gap blocks, so the disjointness proof cannot be
    invalidated by a later view."""

    async def main():
        com = LocalCommittee.build(
            n=4, clients=1, verify_signatures=False, view_timeout=0,
        )
        r1 = com.replica("r1")
        # slot 1: PREPARED and speculated in order
        await _prepare_slot(
            com, r1, seq=1, reqs=[_signed_request(com.keys, 1, "put a 1")]
        )
        assert 1 in r1.spec.slots
        # slot 2: committed-but-parked (simulated hole repair shape):
        # the block is fixed forever — park it in ready directly
        gap_reqs = [_signed_request(com.keys, 2, "put b 2")]
        gap_block = [r.to_dict() for r in gap_reqs]
        r1.ready[2] = ExecuteBlock(
            view=0, seq=2,
            digest=PrePrepare.block_digest(gap_block), block=gap_block,
        )
        # slot 3 DISJOINT from the gap (writes c, gap writes b): spec ok
        inst3 = _inst(
            com, 0, 3, [_signed_request(com.keys, 3, "put c 3")]
        )
        replies = r1.spec.on_prepared(inst3)
        assert 3 in r1.spec.slots and r1.spec.slots[3].ooo
        assert replies and all(rep.spec == 1 for rep in replies)
        assert r1.metrics["spec_ooo"] == 1
        # slot 4 CONFLICTS with the gap (writes b): refused
        inst4 = _inst(
            com, 0, 4, [_signed_request(com.keys, 4, "put b 9")]
        )
        assert r1.spec.on_prepared(inst4) is None
        assert 4 not in r1.spec.slots
        assert r1.metrics["spec_skipped_conflict"] == 1
        # slot 6 above an UNKNOWN gap (5 is neither specced nor ready):
        # refused — no disjointness proof against an unknown block
        inst6 = _inst(
            com, 0, 6, [_signed_request(com.keys, 6, "put z 1")]
        )
        assert r1.spec.on_prepared(inst6) is None
        assert r1.metrics["spec_skipped_gap"] == 1
        await com.stop()

    run(main())


# ---------------------------------------------------------------------------
# the safety invariant: speculative state never reaches a checkpoint
# ---------------------------------------------------------------------------


def test_spec_state_excluded_from_checkpoint():
    """With a block speculated but uncommitted, the checkpoint snapshot
    must be cut from the COMMITTED state only: a speculating replica and
    a never-speculating one produce byte-identical snapshots."""

    async def main():
        com = LocalCommittee.build(
            n=4, clients=1, verify_signatures=False, view_timeout=0,
        )
        r1, r2 = com.replica("r1"), com.replica("r2")
        await _prepare_slot(
            com, r1, seq=1,
            reqs=[_signed_request(com.keys, 5, "put leak v")],
        )
        assert 1 in r1.spec.slots  # r1 speculated; r2 never saw the slot
        assert r1.spec.app.spec_open()
        snap1, snap2 = r1._checkpoint_snapshot(), r2._checkpoint_snapshot()
        assert snap1 == snap2
        assert json.loads(snap1)["app"] == "{}"  # no speculative write
        # ...and the planted spec_leak defect violates exactly this
        # (the sim repro's oracle target): fork-tainted snapshot
        spec_mod.DEFECTS.add("spec_leak")
        try:
            r1.spec.rolled_back_once = True
            leaked = r1._checkpoint_snapshot()
            assert "leak" in json.loads(leaked)["app"]
            assert leaked != snap2
        finally:
            spec_mod.DEFECTS.discard("spec_leak")
            r1.spec.rolled_back_once = False
        await com.stop()

    run(main())


def test_spec_replies_never_enter_committed_cache():
    """Speculative replies are transmitted but never cached in
    recent_replies (checkpoint state): a rolled-back result must not be
    replayable to a retrying client from the replicated cache."""

    async def main():
        com = LocalCommittee.build(
            n=4, clients=1, verify_signatures=False, view_timeout=0,
        )
        r1 = com.replica("r1")
        await _prepare_slot(
            com, r1, seq=1, reqs=[_signed_request(com.keys, 5, "put x 1")]
        )
        assert r1.metrics["spec_replies_sent"] >= 1
        assert r1.recent_replies.get("c0", {}) == {}
        await com.stop()

    run(main())


# ---------------------------------------------------------------------------
# plumbing pins
# ---------------------------------------------------------------------------


def test_reconfig_prefix_pinned_against_drift():
    assert spec_mod.RECONFIG_PREFIX_ == RECONFIG_PREFIX


def test_forkable_app_surface():
    """ForkableApp: the committed protocol surface is fork-blind; the
    fork clones lazily, diverges under apply_spec, and rolls back O(1)."""
    app = ForkableApp(KVStore())
    assert app.forkable()
    base = app.state_digest()
    assert app.spec_digest() is None  # no fork yet
    assert app.apply_spec("put k v") == "ok"
    assert app.spec_open()
    assert app.state_digest() == base  # committed untouched
    assert app.spec_digest() != base
    app.rollback()
    assert not app.spec_open()
    # restore drops the fork too (state transfer)
    app.apply_spec("put k v")
    app.restore("{}")
    assert not app.spec_open()
    # committed applies pass through
    assert app.apply("put a 1") == "ok"
    assert app.data == {"a": "1"}  # attribute delegation


def test_kvstore_rw_sets():
    kv = KVStore()
    assert kv.rw_sets("put k v") == (frozenset(), frozenset(["k"]))
    assert kv.rw_sets("get k") == (frozenset(["k"]), frozenset())
    assert kv.rw_sets("noop") == (frozenset(), frozenset())
    assert kv.rw_sets("weird op") is None


def test_speculation_skips_reconfig_blocks():
    """Membership changes have side effects outside the app (staging,
    epoch activation): a block carrying one must never speculate."""

    async def main():
        com = LocalCommittee.build(
            n=4, clients=1, verify_signatures=False, view_timeout=0,
        )
        r1 = com.replica("r1")
        op = RECONFIG_PREFIX + json.dumps({"add": {}, "remove": []})
        await _prepare_slot(
            com, r1, seq=1, reqs=[_signed_request(com.keys, 3, op)]
        )
        assert 1 not in r1.spec.slots
        assert r1.metrics["spec_skipped_reconfig"] == 1
        await com.stop()

    run(main())


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
