"""Client library: f+1 reply matching under forged and late replies.

The client skips signature checks for replies no waiter needs (a
throughput optimization) — these tests pin that verification still
gates every reply that CAN affect a result.
"""

import asyncio

from simple_pbft_tpu.client import Client
from simple_pbft_tpu.config import make_test_committee
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import Reply


class FakeTransport:
    """Message sink + injectable inbox (no network)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self.q: asyncio.Queue = asyncio.Queue()
        self.sent = []

    async def send(self, dest, raw):
        self.sent.append((dest, raw))

    async def broadcast(self, raw, dests):
        self.sent.append(("*", raw))

    async def recv(self):
        return await self.q.get()

    def recv_nowait(self):
        try:
            return self.q.get_nowait()
        except asyncio.QueueEmpty:
            return None


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _reply(rid, result, ts=1, view=0, spec=0):
    return Reply(sender=rid, view=view, seq=1, client_id="c0", timestamp=ts,
                 result=result, spec=spec)


def test_forged_replies_never_match_and_valid_ones_do():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op x", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()  # the live wall-clock timestamp
        # forged: signed by a key that is not the claimed sender's
        forger = Signer("evil", b"\xee" * 32)
        for rid in ("r0", "r1", "r2"):
            msg = _reply(rid, "EVIL", ts=ts)
            forger.sign_msg(msg)
            msg.sender = rid
            await t.q.put(msg.to_wire())
        # non-replica sender with a valid-for-itself signature
        msg = _reply("nobody", "EVIL", ts=ts)
        forger.sign_msg(msg)
        await t.q.put(msg.to_wire())
        await asyncio.sleep(0.2)
        assert not task.done(), "forged replies must never reach f+1"
        # two honest matching replies (f+1 for n=4) resolve it
        for rid in ("r0", "r1"):
            msg = _reply(rid, "ok", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        assert await task == "ok"
        await client.stop()

    run(scenario())


class CountingVerifier:
    """Real CPU verification plus a call counter — observes whether the
    client pays signature work for a reply."""

    def __init__(self):
        from simple_pbft_tpu.crypto.verifier import best_cpu_verifier

        self.inner = best_cpu_verifier()
        self.calls = 0

    def verify_batch(self, items):
        self.calls += len(items)
        return self.inner.verify_batch(items)


def test_late_replies_after_match_skip_signature_work():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        counter = CountingVerifier()
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0,
                        verifier=counter)
        client.start()
        task = asyncio.create_task(client.submit("op y", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        for rid in ("r0", "r1"):
            msg = _reply(rid, "done", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        assert await task == "done"
        verified_during_match = counter.calls
        assert verified_during_match == 2  # both active replies verified
        # late replies for the resolved timestamp: the recv loop must
        # drop them BEFORE verification (the throughput optimization
        # this suite pins) — the counter must not move
        for rid in ("r2", "r3"):
            msg = _reply(rid, "divergent", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        await asyncio.sleep(0.1)
        assert counter.calls == verified_during_match
        await client.stop()

    run(scenario())


def test_spec_reply_upgrade_never_double_counts():
    """ISSUE 15 reply accounting: a replica that upgrades its
    speculative reply to final is ONE voice — per-(replica, request)
    dedupe with the stricter (final) mark winning. n=4: the speculative
    fast path needs 2f+1 = 3 DISTINCT replicas; a double-counted
    upgrade would fake the third."""

    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op s", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()

        async def put(rid, spec):
            msg = _reply(rid, "ok", ts=ts, spec=spec)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())

        # two speculative replies, then the SAME replica upgrades to
        # final: still only two distinct replicas — no quorum of any
        # kind may form (2 < f+1 finals is false... 1 final < 2; and
        # 2 distinct marks < 3 spec quorum)
        await put("r0", spec=1)
        await put("r1", spec=1)
        await put("r0", spec=0)  # upgrade, not a third voice
        # ...and a late speculative copy must not downgrade the final
        await put("r0", spec=1)
        await asyncio.sleep(0.2)
        assert not task.done(), "double-counted replica reached a quorum"
        # final won, recorded at its slot identity
        assert client._replies[ts]["r0"] == ("ok", False, False, 1, 0)
        # a third DISTINCT replica completes the 2f+1 speculative quorum
        await put("r2", spec=1)
        assert await task == "ok"
        assert client.metrics.get("spec_accepted") == 1
        # final-commit confirmation retained: f+1 final replies upgrade
        # the fast answer (r0 final already counted; r1's arrives now)
        await put("r1", spec=0)
        await asyncio.sleep(0.2)
        assert client.metrics.get("final_confirms") == 1
        assert not client._confirming
        await client.stop()

    run(scenario())


def test_spec_marks_across_slots_never_pool_into_a_quorum():
    """The speculative quorum is PER-SLOT: 2f+1 speculators of one slot
    are 2f+1 preparers of that slot (the quorum-intersection safety
    argument). Marks for the same request speculated at DIFFERENT seqs
    across failover re-proposals — each slot with <= f preparers — must
    never pool into a fake 2f+1."""

    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op x", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()

        async def put(rid, seq, spec=1):
            msg = Reply(sender=rid, view=0, seq=seq, client_id="c0",
                        timestamp=ts, result="ok", spec=spec)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())

        # three distinct replicas, same result — but three DIFFERENT
        # slots: no 2f+1 quorum exists for any one slot
        await put("r0", seq=1)
        await put("r1", seq=2)
        await put("r2", seq=3)
        await asyncio.sleep(0.2)
        assert not task.done(), "cross-slot marks pooled into a quorum"
        # a third mark for slot 2 completes a real per-slot quorum
        await put("r0", seq=2)
        await put("r3", seq=2)
        assert await task == "ok"
        await client.stop()

    run(scenario())


def test_final_quorum_still_resolves_without_speculation():
    """Plain f+1 final matching is untouched: two final replies resolve
    at n=4 with no speculative reply in sight."""

    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op f", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        for rid in ("r0", "r1"):
            msg = _reply(rid, "done", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        assert await task == "done"
        assert client.metrics.get("spec_accepted", 0) == 0
        await client.stop()

    run(scenario())


def test_conflicting_results_wait_for_true_quorum():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op z", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        # two replicas disagree (one Byzantine): no f+1 match yet
        for rid, res in (("r0", "A"), ("r1", "B")):
            msg = _reply(rid, res, ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        await asyncio.sleep(0.2)
        assert not task.done()
        # a third replica agreeing with A completes f+1 on A
        msg = _reply("r2", "A", ts=ts)
        Signer("r2", keys["r2"].seed).sign_msg(msg)
        await t.q.put(msg.to_wire())
        assert await task == "A"
        await client.stop()

    run(scenario())
