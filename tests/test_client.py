"""Client library: f+1 reply matching under forged and late replies.

The client skips signature checks for replies no waiter needs (a
throughput optimization) — these tests pin that verification still
gates every reply that CAN affect a result.
"""

import asyncio

from simple_pbft_tpu.client import Client
from simple_pbft_tpu.config import make_test_committee
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.messages import Reply


class FakeTransport:
    """Message sink + injectable inbox (no network)."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self.q: asyncio.Queue = asyncio.Queue()
        self.sent = []

    async def send(self, dest, raw):
        self.sent.append((dest, raw))

    async def broadcast(self, raw, dests):
        self.sent.append(("*", raw))

    async def recv(self):
        return await self.q.get()

    def recv_nowait(self):
        try:
            return self.q.get_nowait()
        except asyncio.QueueEmpty:
            return None


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _reply(rid, result, ts=1, view=0):
    return Reply(sender=rid, view=view, seq=1, client_id="c0", timestamp=ts,
                 result=result)


def test_forged_replies_never_match_and_valid_ones_do():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op x", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()  # the live wall-clock timestamp
        # forged: signed by a key that is not the claimed sender's
        forger = Signer("evil", b"\xee" * 32)
        for rid in ("r0", "r1", "r2"):
            msg = _reply(rid, "EVIL", ts=ts)
            forger.sign_msg(msg)
            msg.sender = rid
            await t.q.put(msg.to_wire())
        # non-replica sender with a valid-for-itself signature
        msg = _reply("nobody", "EVIL", ts=ts)
        forger.sign_msg(msg)
        await t.q.put(msg.to_wire())
        await asyncio.sleep(0.2)
        assert not task.done(), "forged replies must never reach f+1"
        # two honest matching replies (f+1 for n=4) resolve it
        for rid in ("r0", "r1"):
            msg = _reply(rid, "ok", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        assert await task == "ok"
        await client.stop()

    run(scenario())


class CountingVerifier:
    """Real CPU verification plus a call counter — observes whether the
    client pays signature work for a reply."""

    def __init__(self):
        from simple_pbft_tpu.crypto.verifier import best_cpu_verifier

        self.inner = best_cpu_verifier()
        self.calls = 0

    def verify_batch(self, items):
        self.calls += len(items)
        return self.inner.verify_batch(items)


def test_late_replies_after_match_skip_signature_work():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        counter = CountingVerifier()
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0,
                        verifier=counter)
        client.start()
        task = asyncio.create_task(client.submit("op y", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        for rid in ("r0", "r1"):
            msg = _reply(rid, "done", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        assert await task == "done"
        verified_during_match = counter.calls
        assert verified_during_match == 2  # both active replies verified
        # late replies for the resolved timestamp: the recv loop must
        # drop them BEFORE verification (the throughput optimization
        # this suite pins) — the counter must not move
        for rid in ("r2", "r3"):
            msg = _reply(rid, "divergent", ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        await asyncio.sleep(0.1)
        assert counter.calls == verified_during_match
        await client.stop()

    run(scenario())


def test_conflicting_results_wait_for_true_quorum():
    async def scenario():
        cfg, keys = make_test_committee(n=4, clients=1)
        t = FakeTransport("c0")
        client = Client(client_id="c0", cfg=cfg, seed=keys["c0"].seed,
                        transport=t, request_timeout=2.0)
        client.start()
        task = asyncio.create_task(client.submit("op z", retries=0))
        await asyncio.sleep(0.05)
        (ts,) = client._waiters.keys()
        # two replicas disagree (one Byzantine): no f+1 match yet
        for rid, res in (("r0", "A"), ("r1", "B")):
            msg = _reply(rid, res, ts=ts)
            Signer(rid, keys[rid].seed).sign_msg(msg)
            await t.q.put(msg.to_wire())
        await asyncio.sleep(0.2)
        assert not task.done()
        # a third replica agreeing with A completes f+1 on A
        msg = _reply("r2", "A", ts=ts)
        Signer("r2", keys["r2"].seed).sign_msg(msg)
        await t.q.put(msg.to_wire())
        assert await task == "A"
        await client.stop()

    run(scenario())
