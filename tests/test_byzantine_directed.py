"""Directed Byzantine tests for the PROTOCOL.md threat-model claims
(VERDICT round-2 item 8): each attack is exercised against the real
guard AND against a deliberately broken variant of the guard, proving
the test would catch a regression (the guard is load-bearing, not
decorative).

1. Lying checkpoint digest at the 2f+1 boundary: f Byzantine replicas
   vote a fake state digest; stabilization must count per-digest, not
   per-seq.
2. View-change certificate replay across views: a NEW-VIEW for view w
   embedding (individually valid, properly signed) VIEW-CHANGEs for
   view v != w must be rejected — the certificate is view-bound.
3. Valid-but-reordered O-set: a Byzantine new primary re-issues the
   prepared digests at permuted sequence numbers (every pre-prepare
   properly signed by it); receivers must recompute O deterministically
   and reject the permutation (it would re-execute committed blocks at
   different positions).
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.consensus import viewchange as vc_mod
from simple_pbft_tpu.messages import Checkpoint, Message, NewView, PrePrepare


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# 1. Lying checkpoint digest
# ---------------------------------------------------------------------------


def test_lying_checkpoint_digest_cannot_stabilize():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=2)
        r0 = com.replica("r0")
        liar = com.replica("r1")
        # the lie arrives FIRST, before any honest checkpoint vote: a
        # first-seen-digest stabilizer would adopt it at the 2f+1 edge
        fake = Checkpoint(seq=2, state_digest="f" * 64)
        liar.signer.sign_msg(fake)
        com.start()
        try:
            await r0.on_checkpoint_msg(Message.from_wire(fake.to_wire()))
            for i in range(2):
                assert await com.clients[0].submit(f"put c{i} {i}") == "ok"
            t0 = asyncio.get_running_loop().time()
            while (
                r0.stable_seq < 2
                and asyncio.get_running_loop().time() - t0 < 20
            ):
                await asyncio.sleep(0.05)
        finally:
            await com.stop()
        assert r0.stable_seq == 2
        # stabilized on the honest digest, never the lie; and the replica
        # never tried to state-sync toward the fake digest
        assert r0.checkpoint_digests[2] != "f" * 64
        assert r0.pending_sync is None
        assert r0.metrics["state_sync_requests"] == 0

    run(scenario())


def test_lying_checkpoint_digest_at_quorum_edge_lagging_replica():
    """The dangerous victim is a LAGGING replica (it state-syncs toward
    whatever digest 'stabilizes'): with the real per-digest guard, a
    first-arriving lie + 2f honest votes is one honest vote short of any
    certificate, so the replica must NOT chase either digest yet; the
    2f+1th honest vote then stabilizes the honest digest only."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=2)
        r3 = com.replica("r3")  # fresh: executed_seq == 0 (lagging)
        fake = Checkpoint(seq=2, state_digest="f" * 64)
        com.replica("r1").signer.sign_msg(fake)
        honest = []
        for rid in ("r0", "r2", "r1"):
            cp = Checkpoint(seq=2, state_digest="a" * 64)
            # r1 equivocates: lie first, honest-looking vote later — the
            # per-sender map keeps ONE vote per sender (latest wins)
            com.replica(rid).signer.sign_msg(cp)
            honest.append(cp)
        await r3.on_checkpoint_msg(Message.from_wire(fake.to_wire()))
        await r3.on_checkpoint_msg(Message.from_wire(honest[0].to_wire()))
        await r3.on_checkpoint_msg(Message.from_wire(honest[1].to_wire()))
        # 1 lie + 2 honest votes at seq 2: per-digest max is 2 < 2f+1
        assert r3.pending_sync is None
        assert r3.metrics["state_sync_requests"] == 0
        # the 3rd matching honest vote completes the honest certificate
        await r3.on_checkpoint_msg(Message.from_wire(honest[2].to_wire()))
        assert r3.pending_sync == (2, "a" * 64)

    run(scenario())


def test_lying_checkpoint_digest_breaks_a_naive_stabilizer():
    """Sensitivity check: replace the per-digest quorum count with a
    naive per-seq count (any 2f+1 votes at seq, first-seen digest wins).
    The same attack then poisons a lagging replica into state-syncing
    toward the fake digest — proving the real guard is load-bearing."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=2)
        r3 = com.replica("r3")

        async def naive_on_checkpoint(msg):
            if msg.seq <= r3.stable_seq:
                return
            r3.checkpoints[msg.seq][msg.sender] = msg
            votes = r3.checkpoints[msg.seq]
            if len(votes) >= r3.cfg.quorum:  # BROKEN: ignores digests
                first = next(iter(votes.values()))
                await r3._stabilize(msg.seq, first.state_digest)

        r3._on_checkpoint = naive_on_checkpoint
        fake = Checkpoint(seq=2, state_digest="f" * 64)
        com.replica("r1").signer.sign_msg(fake)
        await r3.on_checkpoint_msg(Message.from_wire(fake.to_wire()))
        for rid in ("r0", "r2"):
            cp = Checkpoint(seq=2, state_digest="a" * 64)
            com.replica(rid).signer.sign_msg(cp)
            await r3.on_checkpoint_msg(Message.from_wire(cp.to_wire()))
        # the naive stabilizer chased the first-seen (fake) digest
        assert r3.pending_sync is not None and r3.pending_sync[1] == "f" * 64

    run(scenario())


# ---------------------------------------------------------------------------
# 2 + 3. View-change certificate replay / reordered O-set
# ---------------------------------------------------------------------------


async def _committee_with_prepared_seqs():
    """n=4 with three committed (still-windowed) seqs of distinct
    digests, plus each replica's signed VIEW-CHANGE for view 1."""
    com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=1 << 30)
    com.start()
    for i in range(3):
        assert await com.clients[0].submit(f"put k{i} {i}") == "ok"
    # build a valid 2f+1 view-change certificate for view 1
    vcs = {}
    for rid in ("r0", "r1", "r2"):
        r = com.replica(rid)
        vc = r.vc.build_view_change(1)
        r.signer.sign_msg(vc)
        vcs[rid] = vc
    return com, vcs


def _make_new_view(com, vcs, new_view, pre_prepares):
    sender = com.replica(com.cfg.primary(new_view))
    nv = NewView(
        new_view=new_view,
        viewchange_proof=[vc.to_dict() for vc in vcs.values()],
        pre_prepares=pre_prepares,
    )
    sender.signer.sign_msg(nv)
    return nv


def _signed_reissues(com, new_view, o_set):
    sender = com.replica(com.cfg.primary(new_view))
    out = []
    for seq, digest in o_set:
        pp = PrePrepare(view=new_view, seq=seq, digest=digest, block=[])
        sender.signer.sign_msg(pp)
        out.append(pp.to_dict())
    return out


def test_newview_embedding_other_views_certificates_rejected():
    async def scenario():
        com, vcs = await _committee_with_prepared_seqs()
        try:
            cfg = com.cfg
            h, o_set = vc_mod.compute_o_set(cfg, vcs, 1)
            # sanity: the honest NEW-VIEW(1) validates
            good = _make_new_view(com, vcs, 1, _signed_reissues(com, 1, o_set))
            assert vc_mod.validate_new_view(cfg, good) is not None

            # replay attack: NEW-VIEW(2) built from the view-1 VCs
            # (each individually valid and properly signed) — the
            # certificate must be view-bound
            evil = _make_new_view(com, vcs, 2, _signed_reissues(com, 2, o_set))
            assert vc_mod.validate_new_view(cfg, evil) is None

            # and the replica runtime rejects it end-to-end
            r3 = com.replica("r3")
            before = r3.view
            await r3._on_view_message(Message.from_wire(evil.to_wire()))
            assert r3.view == before
            assert r3.metrics["bad_newview"] >= 1
        finally:
            await com.stop()

    run(scenario())


def test_newview_with_reordered_o_set_rejected():
    async def scenario():
        com, vcs = await _committee_with_prepared_seqs()
        try:
            cfg = com.cfg
            h, o_set = vc_mod.compute_o_set(cfg, vcs, 1)
            assert len(o_set) >= 2
            digests = [d for _, d in o_set]
            assert len(set(digests)) >= 2  # distinct blocks to permute
            # swap the first two digests: every re-issue stays properly
            # signed by the legitimate new primary, but committed block 1
            # would re-execute at seq 2 and vice versa
            swapped = list(o_set)
            (s0, d0), (s1, d1) = swapped[0], swapped[1]
            swapped[0], swapped[1] = (s0, d1), (s1, d0)
            evil = _make_new_view(com, vcs, 1, _signed_reissues(com, 1, swapped))
            assert vc_mod.validate_new_view(cfg, evil) is None

            r3 = com.replica("r3")
            before = r3.view
            await r3._on_view_message(Message.from_wire(evil.to_wire()))
            assert r3.view == before
            assert r3.metrics["bad_newview"] >= 1
        finally:
            await com.stop()

    run(scenario())


def test_reordered_o_set_breaks_a_guardless_validator():
    """Sensitivity check: a validator that trusts the primary's O-set
    (skipping the deterministic recompute-and-compare) accepts the
    permuted re-issues — the cross-check is what stops the attack."""

    async def scenario():
        com, vcs = await _committee_with_prepared_seqs()
        try:
            cfg = com.cfg
            h, o_set = vc_mod.compute_o_set(cfg, vcs, 1)
            swapped = list(o_set)
            (s0, d0), (s1, d1) = swapped[0], swapped[1]
            swapped[0], swapped[1] = (s0, d1), (s1, d0)
            evil = _make_new_view(com, vcs, 1, _signed_reissues(com, 1, swapped))

            orig = vc_mod.compute_o_set

            def trusting(cfg_, vcs_, view_):
                # BROKEN guard: echo whatever the NEW-VIEW carries
                return h, swapped

            vc_mod.compute_o_set = trusting
            try:
                res = vc_mod.validate_new_view(cfg, evil)
            finally:
                vc_mod.compute_o_set = orig
            # without the deterministic cross-check the forgery validates
            assert res is not None
        finally:
            await com.stop()

    run(scenario())
