"""Critical-path span profiler + wedge autopsy (ISSUE 4 tentpole).

Acceptance pins: (1) a traced LocalCommittee run yields a
tools/critical_path.py decomposition whose per-slot stage sums reconcile
with the measured end-to-end commit latency within 15%; (2) an injected
device stall (faults.StallableDevice) produces an autopsy dump naming
the stalled stage. Satellites pinned here: event-loop lag gauge,
--trace-sample fraction mode + trace_dropped, SIGTERM-path final
autopsy through node._dump_final.
"""

import asyncio
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import critical_path  # noqa: E402  (tools/ is not a package)

from simple_pbft_tpu import spans  # noqa: E402
from simple_pbft_tpu.committee import LocalCommittee  # noqa: E402
from simple_pbft_tpu.crypto.coalesce import VerifyService  # noqa: E402
from simple_pbft_tpu.faults import StallableDevice  # noqa: E402
from simple_pbft_tpu.telemetry import (  # noqa: E402
    LoopLagGauge,
    ProgressWatchdog,
    RequestTracer,
    diagnose_stall,
    resolve_sample_mod,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class EchoDevice:
    """Device double: verdict is sig == msg (the FakeDevice predicate)."""

    def __init__(self):
        self.device_calls = 0
        self.device_items = 0
        self.device_seconds = 0.0

    def dispatch_batch(self, items):
        items = list(items)
        self.device_calls += 1
        self.device_items += len(items)
        return lambda: [it.sig == it.msg for it in items]


class EchoCpu:
    def verify_batch(self, items):
        return [it.sig == it.msg for it in items]


class CpuDevice:
    """Real-crypto device double (the test_overload GatedCpuDevice shape
    minus the gate): StallableDevice supplies the stall, this supplies
    verdicts a real committee's signed traffic passes."""

    def __init__(self):
        from simple_pbft_tpu.crypto.verifier import best_cpu_verifier

        self._cpu = best_cpu_verifier()
        self.device_calls = 0
        self.device_items = 0
        self.device_seconds = 0.0

    def dispatch_batch(self, items):
        items = list(items)
        self.device_calls += 1
        self.device_items += len(items)
        return lambda: self._cpu.verify_batch(items)


# ---------------------------------------------------------------------------
# span recorder
# ---------------------------------------------------------------------------


def test_span_recorder_histograms_ring_and_sink(tmp_path):
    rec = spans.SpanRecorder(ring=4)
    rec.configure("t0", str(tmp_path / "t.spans.jsonl"))
    for i in range(6):
        rec.record("phase.prepare", 0.010, node="t0", view=0, seq=i + 1)
    rec.record("transport.queue", 0.002, n=17, persist=False)
    snap = rec.snapshot()
    assert snap["recorded"] == 7
    assert snap["stages"]["phase.prepare"]["count"] == 6
    assert snap["stages"]["transport.queue"]["count"] == 1  # hist: yes
    assert 8.0 < snap["stages"]["phase.prepare"]["p50"] < 16.0  # ms buckets
    # ring is bounded and excludes per-message persist=False stages —
    # an autopsy's recent window keeps the diagnostic pipeline spans
    recent = rec.recent()
    assert len(recent) == 4
    assert all(r["stage"] == "phase.prepare" for r in recent)
    assert recent[-1]["seq"] == 6
    rec.close()
    # sink got ONLY the persist=True spans, as parseable JSONL
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "t.spans.jsonl").read_text().splitlines()
    ]
    assert len(lines) == 6
    assert all(ln["evt"] == "span" and ln["node"] == "t0" for ln in lines)
    assert lines[0]["dur_ms"] == pytest.approx(10.0, rel=0.01)


def test_verify_service_spans_cover_queue_and_device_paths():
    """The coalescing service's critical path attributes itself:
    admission-queue wait and device RTT for big piles, verify.cpu for
    size-routed small ones."""
    from simple_pbft_tpu.crypto.verifier import BatchItem

    base = spans.recorder().snapshot()["stages"]

    def count(stage):
        cur = spans.recorder().snapshot()["stages"].get(stage, {})
        return cur.get("count", 0) - (base.get(stage, {}).get("count", 0))

    svc = VerifyService(EchoDevice(), cpu=EchoCpu(), cpu_cutoff=8)
    items = [BatchItem(b"pk", bytes([i]), bytes([i])) for i in range(64)]
    assert svc.submit(items).result(10) == [True] * 64  # device (64 > 8)
    assert svc.submit(items[:4]).result(10) == [True] * 4  # cpu (4 <= 8)
    svc.close()
    assert count(spans.VERIFY_QUEUE) >= 2
    assert count(spans.VERIFY_DEVICE) >= 1
    assert count(spans.VERIFY_CPU) >= 1


# ---------------------------------------------------------------------------
# acceptance: per-stage decomposition reconciles with commit latency
# ---------------------------------------------------------------------------


def test_slot_spans_reconcile_with_commit_latency(tmp_path):
    """The three phase.* spans tile pre-prepare -> execution, so the
    critical_path slot decomposition must agree with the replicas' own
    commit_ms histogram within 15% — the acceptance reconciliation."""

    async def scenario():
        spans.configure("recon", str(tmp_path / "recon.spans.jsonl"))
        com = LocalCommittee.build(n=4, clients=2)
        com.attach_tracers(sample_mod=1)
        com.start()
        try:
            for i in range(8):
                assert await com.clients[i % 2].submit(f"put k{i} {i}") == "ok"
            commit_means = [
                r.stats.commit_ms.summary()["mean"]
                for r in com.replicas
                if r.stats.commit_ms.count
            ]
            return sum(commit_means) / len(commit_means)
        finally:
            await com.stop()
            spans.recorder().close()

    commit_mean_ms = run(scenario())
    assert commit_mean_ms > 0
    loaded = critical_path.load_spans([str(tmp_path / "recon.spans.jsonl")])
    an = critical_path.analyze(loaded)
    assert an["slots_complete"] >= 8  # 8 blocks x 4 replicas, minus races
    # nonempty decomposition at every percentile, shares summing to ~1
    assert an["decomposition"]
    for d in an["decomposition"]:
        assert 0.99 < sum(d["shares"].values()) <= 1.01
    # the reconciliation: mean slot e2e vs mean measured commit latency
    assert an["slot_e2e_ms"]["mean"] == pytest.approx(
        commit_mean_ms, rel=0.15
    )


def test_critical_path_tool_renders_and_json(tmp_path):
    path = tmp_path / "x.spans.jsonl"
    with open(path, "w") as fh:
        for seq in range(1, 11):
            for stage, dur in (
                ("phase.prepare", 6.0), ("phase.commit", 3.0),
                ("phase.execute", 1.0),
            ):
                fh.write(json.dumps({
                    "evt": "span", "stage": stage, "node": "r0",
                    "view": 0, "seq": seq, "dur_ms": dur * seq,
                    "t_mono": float(seq),
                }) + "\n")
        fh.write("{torn line\n")  # must be skipped, not fatal
    loaded = critical_path.load_spans([str(path)])
    assert len(loaded) == 30
    an = critical_path.analyze(loaded, pcts=[50.0, 99.0])
    assert an["slots_complete"] == 10
    d99 = an["decomposition"][-1]
    assert d99["shares"]["phase.prepare"] == pytest.approx(0.6, abs=0.01)
    text = critical_path.render(an)
    assert "commit-path decomposition" in text
    assert "phase.prepare" in text
    json.dumps(an)  # --json output is serializable


# ---------------------------------------------------------------------------
# acceptance: injected device stall -> autopsy naming the stalled stage
# ---------------------------------------------------------------------------


def test_device_stall_produces_autopsy_naming_stage(tmp_path):
    """A 10 s-class silent device (faults.StallableDevice, the r5 qc256
    shape) must produce an autopsy file whose suspect names the device
    stage — with the service's own watchdog disabled, exactly the
    configuration that used to wedge in silence."""

    async def scenario():
        dev = StallableDevice(CpuDevice())
        # dispatch_deadline=None: the ISSUE-1 failover is OFF, so the
        # stall persists and the PROGRESS watchdog is the only alarm
        svc = VerifyService(dev, cpu_cutoff=0, dispatch_deadline=None)
        com = LocalCommittee.build(
            n=4, clients=1, verifier_factory=lambda: svc, view_timeout=120.0
        )
        com.clients[0].request_timeout = 120.0
        com.start()
        wd = ProgressWatchdog(
            com.node_telemetry("r0"),
            path=str(tmp_path / "r0.autopsy.json"),
            deadline=1.5,
            interval=0.2,
        )
        wd.start()
        try:
            dev.stall()  # device accepts work and goes silent
            pump = asyncio.create_task(com.clients[0].submit("put k v"))
            for _ in range(200):  # until the watchdog fires
                if wd.dumps:
                    break
                await asyncio.sleep(0.1)
            assert wd.dumps == 1, "stall must dump exactly once"
            dev.release()
            assert await pump == "ok"  # the run RECOVERS after release
        finally:
            await wd.stop()
            await com.stop()
            svc.close()

    run(scenario(), timeout=90)
    doc = json.loads((tmp_path / "r0.autopsy.json").read_text())
    assert doc["evt"] == "autopsy"
    assert doc["node"] == "r0"
    # the verdict names the stalled stage: a dispatched-but-unanswered
    # device pass, aged past any healthy RTT
    assert doc["suspect"]["stage"] == "verify.device"
    assert "in flight" in doc["suspect"]["detail"]
    snap = doc["snapshot"]
    assert snap["verify"]["inflight_oldest_age_s"] >= 1.0
    assert snap["verify"]["inflight_passes"] >= 1
    # forensics ride along: stacks, instance table, recent spans
    assert doc["threads"]  # thread stacks (verify-dispatch et al.)
    assert any(t["stack"] for t in doc["tasks"])
    assert isinstance(doc["instances_inflight"], list)
    assert isinstance(doc["spans_recent"], list)


def test_watchdog_stays_quiet_when_idle_or_progressing(tmp_path):
    """No outstanding work = no stall (an idle committee must not dump);
    steady progress re-arms but never fires."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        wd = ProgressWatchdog(
            com.node_telemetry("r0"),
            path=str(tmp_path / "idle.autopsy.json"),
            deadline=0.3,
            interval=0.1,
        )
        wd.start()
        try:
            await asyncio.sleep(0.8)  # idle past the deadline: quiet
            assert wd.dumps == 0
            for i in range(3):  # progressing: quiet
                assert await com.clients[0].submit(f"put p{i} {i}") == "ok"
                await asyncio.sleep(0.2)
            assert wd.dumps == 0
        finally:
            await wd.stop()
            await com.stop()

    run(scenario())
    assert not (tmp_path / "idle.autopsy.json").exists()


def test_watchdog_rearms_after_stall_clears_without_commit(tmp_path):
    """A stall that ends by SHEDDING (no commit ever lands) must re-arm
    the watchdog: the next, distinct wedge still gets its autopsy —
    zero-diagnostic-output is the failure mode this subsystem exists to
    kill, including the second time."""

    class StubReplica:
        executed_seq = 0
        instances = {}
        verifier = object()  # no _pending_items/_inflight attrs
        busy = True

        def has_outstanding_work(self):
            return self.busy

    class StubTelemetry:
        node_id = "stub"
        replica = StubReplica()

        def snapshot(self):
            return {}

    tel = StubTelemetry()
    wd = ProgressWatchdog(
        tel, path=str(tmp_path / "stub.autopsy.json"), deadline=0.05
    )
    wd._check()  # baseline: registers executed_seq, starts the clock
    time.sleep(0.06)
    wd._check()  # stall 1 fires
    assert wd.dumps == 1
    time.sleep(0.06)
    wd._check()  # same stall: one dump per stall, no spam
    assert wd.dumps == 1
    tel.replica.busy = False
    wd._check()  # work cleared WITHOUT a commit: must re-arm
    tel.replica.busy = True
    time.sleep(0.06)
    wd._check()  # distinct stall 2 fires again
    assert wd.dumps == 2


def test_persisted_counter_stops_when_sink_degrades(tmp_path):
    """ENOSPC-style sink death must not keep inflating the on-disk span
    count, and the degradation is surfaced in the snapshot."""
    rec = spans.SpanRecorder()
    rec.configure("deg", str(tmp_path / "deg.spans.jsonl"))
    rec.record("phase.prepare", 0.001)
    assert rec.persisted == 1
    rec._sink._fh.close()  # next write raises -> sink degrades
    rec.record("phase.prepare", 0.001)
    snap = rec.snapshot()
    assert snap["recorded"] == 2  # in-memory surfaces keep going
    assert snap["persisted"] == 1  # only what actually landed on disk
    assert snap["sink_write_errors"] == 1
    rec.close()


def test_final_dump_path_writes_autopsy(tmp_path):
    """The SIGTERM/SIGINT (and fatal-exception) path: node._dump_final
    with a watchdog attached writes the full forensic dump, not just
    counter log lines — to a DISTINCT file, so a mid-run stall autopsy
    at the watchdog's own path survives the shutdown (ISSUE 4
    satellite)."""

    async def scenario():
        from simple_pbft_tpu.node import _dump_final

        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        r0 = com.replica("r0")
        try:
            assert await com.clients[0].submit("put k v") == "ok"
            # settle past the speculative fast answer (ISSUE 15): the
            # final dump below must snapshot a COMMITTED request
            for _ in range(100):
                if r0.metrics.get("committed_requests"):
                    break
                await asyncio.sleep(0.05)
            wd = ProgressWatchdog(
                com.node_telemetry("r0"),
                path=str(tmp_path / "r0.autopsy.json"),
                deadline=9999.0,  # never fires on its own
            )
            wd.dump("simulated mid-run stall")  # the evidence to preserve
            _dump_final("r0", r0, r0.transport, watchdog=wd)
        finally:
            await com.stop()

    run(scenario())
    final = json.loads((tmp_path / "r0.final.autopsy.json").read_text())
    assert final["reason"].startswith("final dump")
    assert final["snapshot"]["replica"]["metrics"]["committed_requests"] >= 1
    # the stall autopsy was NOT overwritten by the shutdown snapshot
    stall = json.loads((tmp_path / "r0.autopsy.json").read_text())
    assert stall["reason"] == "simulated mid-run stall"


# ---------------------------------------------------------------------------
# satellites: loop-lag gauge, trace-sample fraction mode, trace_dropped
# ---------------------------------------------------------------------------


# the stall is the test subject: the loop sanitizer would (correctly)
# attribute it to this test — declared, not suppressed
@pytest.mark.sanitize_allow("loop")
def test_loop_lag_gauge_sees_a_blocked_loop():
    async def scenario():
        g = LoopLagGauge(interval=0.05)
        g.start()
        await asyncio.sleep(0.15)  # healthy baseline samples
        time.sleep(0.3)  # block the loop (the starved-core shape)
        await asyncio.sleep(0.1)  # let the gauge take the late sample
        snap = g.snapshot()
        await g.stop()
        assert snap["samples"] >= 2
        assert snap["max_ms"] >= 200.0  # the block is visible
        return snap

    run(scenario())


def test_loop_lag_in_snapshot_and_diagnose():
    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        g = com.attach_loop_lag(interval=0.05)
        await asyncio.sleep(0.2)
        snap = com.node_telemetry("r0").snapshot()
        assert "loop_lag" in snap
        assert snap["loop_lag"]["samples"] >= 1
        await com.stop()
        assert com.lag_gauge is None  # stop() tears the gauge down
        assert g.snapshot()["samples"] >= 1

    run(scenario())
    # diagnose: a starved loop with no queued crypto blames event_loop
    verdict = diagnose_stall({
        "loop_lag": {"ema_ms": 500.0, "max_ms": 900.0},
        "replica": {"instances": 3},
    })
    assert verdict["stage"] == "event_loop"


def test_diagnose_stall_orders_causes():
    dev = {
        "verify": {"inflight_passes": 1, "inflight_oldest_age_s": 12.0,
                   "pending_items": 900},
        "qc_lane": {"pending": 5},
    }
    assert diagnose_stall(dev)["stage"] == "verify.device"
    assert diagnose_stall({"qc_lane": {"pending": 5}})["stage"] == "qc.pairing"
    assert diagnose_stall(
        {"replica": {"ready_holes": 2, "executed_seq": 7}}
    )["stage"] == "phase.execute"
    assert diagnose_stall({})["stage"] == "unknown"


def test_trace_sample_fraction_and_modulus():
    assert resolve_sample_mod(0) == 0  # off
    assert resolve_sample_mod(-1) == 0
    assert resolve_sample_mod(1.0) == 1  # full-fidelity debug mode
    assert resolve_sample_mod(0.25) == 4  # fraction -> modulus
    assert resolve_sample_mod(128) == 128  # historical modulus spelling
    assert resolve_sample_mod(64.0) == 64


def test_trace_dropped_counts_sampling_loss():
    t = RequestTracer("n0", sample_mod=2)
    kept = sum(
        1 for ts in range(64) if t.rid_if_sampled("c0", ts) is not None
    )
    assert kept + t.trace_dropped == 64
    assert t.trace_dropped > 0  # mod 2 drops roughly half
    full = RequestTracer("n1", sample_mod=1)
    for ts in range(16):
        assert full.rid_if_sampled("c0", ts)
    assert full.trace_dropped == 0  # full fidelity: zero loss, provably
