"""Failover across REAL processes and sockets.

Every storm/failover test runs in-process over the simulated transport;
this one spawns 4 replica OS processes (TCP and gRPC), SIGKILLs the
view-0 primary's process mid-run, and drives a client through the
view change — the whole deployment plane (deploy docs, node binary,
wire transports, view-change protocol) failing over for real.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_node(rid, deploy_dir, transport, env):
    return subprocess.Popen(
        [
            sys.executable, "-m", "simple_pbft_tpu.node",
            "--id", rid,
            "--deploy-dir", deploy_dir,
            "--transport", transport,
            "--log-dir", "",
        ],
        env=env,
        cwd=REPO,
    )


def _client(deploy_dir, transport, load, timeout, retries, env):
    return subprocess.run(
        [
            sys.executable, "-m", "simple_pbft_tpu.client_cli",
            "--id", "c0",
            "--deploy-dir", deploy_dir,
            "--transport", transport,
            "--load", str(load),
            "--concurrency", "4",
            "--timeout", str(timeout),
            "--retries", str(retries),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=150,
    )


@pytest.mark.slow
@pytest.mark.parametrize("transport", ["tcp", "grpc"])
def test_primary_process_sigkill_failover(tmp_path, transport):
    sys.path.insert(0, REPO)
    from simple_pbft_tpu import deploy

    base_port = 9100 + (os.getpid() % 400) + (0 if transport == "tcp" else 450)
    deploy.generate(
        str(tmp_path), n=4, clients=1, base_port=base_port, view_timeout=1.0
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # children must never touch the chip
    procs = {}
    try:
        for i in range(4):
            procs[f"r{i}"] = _spawn_node(f"r{i}", str(tmp_path), transport, env)
        time.sleep(1.5)  # listeners up
        # a first wave commits under the view-0 primary
        out = _client(str(tmp_path), transport, 4, 1.0, 10, env)
        assert out.returncode == 0, (out.stdout[-500:], out.stderr[-500:])
        assert '"ops": 4' in out.stdout, out.stdout[-500:]
        # crash-stop the primary's PROCESS (no drain, no goodbye)
        procs["r0"].send_signal(signal.SIGKILL)
        procs["r0"].wait(timeout=10)
        # the survivors must view-change and keep serving the client
        out = _client(str(tmp_path), transport, 6, 2.0, 30, env)
        assert out.returncode == 0, (out.stdout[-500:], out.stderr[-500:])
        assert '"ops": 6' in out.stdout, out.stdout[-500:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
