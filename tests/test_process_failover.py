"""Failover across REAL processes and sockets.

Every storm/failover test runs in-process over the simulated transport;
this one spawns 4 replica OS processes (TCP and gRPC), SIGKILLs the
view-0 primary's process mid-run, and drives a client through the
view change — the whole deployment plane (deploy docs, node binary,
wire transports, view-change protocol) failing over for real.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_node(rid, deploy_dir, transport, env, log_dir=""):
    """log_dir="" disables the file sink; pass None for the default
    per-node log file (the rejoin test reads it for shutdown stats)."""
    argv = [
        sys.executable, "-m", "simple_pbft_tpu.node",
        "--id", rid,
        "--deploy-dir", deploy_dir,
        "--transport", transport,
    ]
    if log_dir is not None:
        argv += ["--log-dir", log_dir]
    return subprocess.Popen(argv, env=env, cwd=REPO)


def _client(deploy_dir, transport, load, timeout, retries, env):
    return subprocess.run(
        [
            sys.executable, "-m", "simple_pbft_tpu.client_cli",
            "--id", "c0",
            "--deploy-dir", deploy_dir,
            "--transport", transport,
            "--load", str(load),
            "--concurrency", "4",
            "--timeout", str(timeout),
            "--retries", str(retries),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=150,
    )


@pytest.mark.slow
@pytest.mark.parametrize("transport", ["tcp", "grpc"])
def test_primary_process_sigkill_failover(tmp_path, transport):
    sys.path.insert(0, REPO)
    from simple_pbft_tpu import deploy

    base_port = 9100 + (os.getpid() % 400) + (0 if transport == "tcp" else 450)
    deploy.generate(
        str(tmp_path), n=4, clients=1, base_port=base_port, view_timeout=1.0
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # children must never touch the chip
    procs = {}
    try:
        for i in range(4):
            procs[f"r{i}"] = _spawn_node(f"r{i}", str(tmp_path), transport, env)
        time.sleep(1.5)  # listeners up
        # a first wave commits under the view-0 primary
        out = _client(str(tmp_path), transport, 4, 1.0, 10, env)
        assert out.returncode == 0, (out.stdout[-500:], out.stderr[-500:])
        assert '"ops": 4' in out.stdout, out.stdout[-500:]
        # crash-stop the primary's PROCESS (no drain, no goodbye)
        procs["r0"].send_signal(signal.SIGKILL)
        procs["r0"].wait(timeout=10)
        # the survivors must view-change and keep serving the client
        out = _client(str(tmp_path), transport, 6, 2.0, 30, env)
        assert out.returncode == 0, (out.stdout[-500:], out.stderr[-500:])
        assert '"ops": 6' in out.stdout, out.stdout[-500:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_killed_replica_process_rejoins(tmp_path):
    """Crash recovery across real processes: a SIGKILLed replica restarts
    from scratch (no disk state), learns the committee moved on via the
    f+1 view-change join rule + checkpoint certificates, state-transfers,
    and participates again — verified by its own shutdown stats."""
    import re

    sys.path.insert(0, REPO)
    from simple_pbft_tpu import deploy

    # distinct range from the sigkill tests' 9100-9950 spread so a child
    # outliving its SIGTERM grace can never squat this test's ports
    base_port = 10100 + (os.getpid() % 400)
    deploy.generate(
        str(tmp_path), n=4, clients=1, base_port=base_port,
        view_timeout=1.0, checkpoint_interval=4,
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = {}
    try:
        for i in range(4):
            procs[f"r{i}"] = _spawn_node(f"r{i}", str(tmp_path), "tcp", env)
        time.sleep(1.5)
        out = _client(str(tmp_path), "tcp", 8, 2.0, 10, env)
        assert out.returncode == 0, (out.stdout[-400:], out.stderr[-400:])
        procs["r0"].send_signal(signal.SIGKILL)
        procs["r0"].wait(timeout=10)
        out = _client(str(tmp_path), "tcp", 8, 2.0, 20, env)
        assert out.returncode == 0, (out.stdout[-400:], out.stderr[-400:])
        # r0 rejoins with no state and must catch up (log_dir=None: the
        # default per-node log file carries the shutdown stats we assert)
        procs["r0"] = _spawn_node("r0", str(tmp_path), "tcp", env,
                                  log_dir=None)
        time.sleep(2)
        out = _client(str(tmp_path), "tcp", 8, 2.0, 20, env)
        assert out.returncode == 0, (out.stdout[-400:], out.stderr[-400:])
        time.sleep(5)  # let r0 finish catching up
        procs["r0"].send_signal(signal.SIGTERM)
        procs["r0"].wait(timeout=10)
        log = open(os.path.join(str(tmp_path), "log", "r0.log")).read()
        stats = [ln for ln in log.splitlines() if "stats" in ln]
        assert stats, "r0 must dump stats on shutdown"
        committed = re.search(r'"committed_requests": (\d+)', stats[-1])
        synced = re.search(r'"state_syncs": (\d+)', stats[-1])
        views = re.search(r'"views_installed": (\d+)', stats[-1])
        # r0 must have PARTICIPATED again: either it executed part of the
        # third wave, or (if state transfer snapshot-jumped past it) it
        # applied a sync — history behind the snapshot never increments
        # the execution counter
        participated = (committed and int(committed.group(1)) >= 1) or (
            synced and int(synced.group(1)) >= 1
        )
        assert participated, stats[-1][-300:]
        assert views and int(views.group(1)) >= 1, stats[-1][-300:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
