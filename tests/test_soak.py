"""Fault-injection soak: safety and liveness under sustained chaos.

The SURVEY §5 race/sanitizer-hygiene analog for an asyncio design:
drive a committee for a sustained window under message drops, delays,
and duplicates (dozens of view changes fire), then assert the safety
invariant that matters — every checkpoint seq certified by multiple
replicas has ONE digest (prefix agreement) — and that client work kept
committing. A 300 s variant of this soak caught a real bug: the reply
cache embedded the execution view in checkpoint digests, so identical
states produced diverging digests around failovers and stabilization
stalled (fixed in replica._checkpoint_snapshot).
"""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.transport.local import FaultPlan


@pytest.mark.slow
def test_soak_faulty_network_prefix_agreement():
    async def main():
        plan = FaultPlan(drop_rate=0.02, delay_range=(0.0, 0.02),
                        duplicate_rate=0.01, seed=7)
        c = LocalCommittee.build(n=7, clients=3, view_timeout=1.5,
                                 checkpoint_interval=16, fault_plan=plan)
        for cl in c.clients:
            cl.request_timeout = 1.0
        c.start()
        t0 = time.perf_counter()
        ok = 0

        async def pump(cl, tag):
            nonlocal ok
            i = 0
            while time.perf_counter() - t0 < 45:
                try:
                    r = await cl.submit(f"put {tag}{i} v{i}", retries=10)
                    ok += 1 if r == "ok" else 0
                except (asyncio.TimeoutError, TimeoutError):
                    pass  # individual give-ups are chaos, not failure
                i += 1

        await asyncio.gather(*(pump(cl, f"c{j}_")
                               for j, cl in enumerate(c.clients)))
        plan.heal()
        plan.drop_rate = 0.0
        plan.duplicate_rate = 0.0
        await asyncio.sleep(2)
        # SAFETY: any checkpoint seq certified by 2+ replicas agrees
        seqs = set()
        for r in c.replicas:
            seqs.update(r.checkpoint_digests)
        for s in sorted(seqs):
            digests = {
                r.checkpoint_digests[s]
                for r in c.replicas
                if s in r.checkpoint_digests
            }
            assert len(digests) == 1, (s, digests)
        # LIVENESS: meaningful progress through the chaos
        assert ok >= 50, ok
        await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


@pytest.mark.slow
def test_fading_load_drain_tail_completes():
    """Every request in flight when load STOPS must still commit and be
    answered, with chaos still active.

    Directed at the round-4 'terminal stall under fading load' wart
    (bench_results/consensus_cpu_r04.jsonl line 1: the 128 requests in
    flight at window end all timed out in the drain tail of a qc-n64
    chaos run). The hazard is specific to fading load: most repair and
    progress machinery — drain sweeps, slot probes, failover timers — is
    (re)armed by arriving traffic, so the last requests' loss-repair must
    be driven by the client-retry path alone. The reference has no
    analog (its client never waits for replies at all, client.go:27-34).
    """
    async def main():
        plan = FaultPlan(drop_rate=0.03, delay_range=(0.0, 0.02),
                         duplicate_rate=0.01, seed=11)
        c = LocalCommittee.build(n=7, clients=4, view_timeout=1.5,
                                 checkpoint_interval=16, fault_plan=plan,
                                 qc_mode=True)
        for cl in c.clients:
            cl.request_timeout = 1.5
            cl.hedge = 2
        c.start()
        stop_at = time.perf_counter() + 8.0
        tally = {"ok": 0, "gaveup": 0}

        async def pump(cl, tag):
            i = 0
            while time.perf_counter() < stop_at:
                try:
                    # 20 retries x 1.5 s = 30 s patience: far beyond any
                    # single failover, so a give-up here means the
                    # committee truly stopped serving the drain tail
                    await cl.submit(f"put {tag}{i} v{i}", retries=20)
                    tally["ok"] += 1
                except (asyncio.TimeoutError, TimeoutError):
                    tally["gaveup"] += 1
                i += 1

        # 4 pumps per client: ~16 requests in flight when the load fades
        await asyncio.gather(*(pump(cl, f"c{j}p{k}_")
                               for j, cl in enumerate(c.clients)
                               for k in range(4)))
        assert tally["gaveup"] == 0, tally
        assert tally["ok"] >= 32, tally
        await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
