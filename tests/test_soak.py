"""Fault-injection soak: safety and liveness under sustained chaos.

The SURVEY §5 race/sanitizer-hygiene analog for an asyncio design:
drive a committee for a sustained window under message drops, delays,
and duplicates (dozens of view changes fire), then assert the safety
invariant that matters — every checkpoint seq certified by multiple
replicas has ONE digest (prefix agreement) — and that client work kept
committing. A 300 s variant of this soak caught a real bug: the reply
cache embedded the execution view in checkpoint digests, so identical
states produced diverging digests around failovers and stabilization
stalled (fixed in replica._checkpoint_snapshot).
"""

import asyncio
import time

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.transport.local import FaultPlan


@pytest.mark.slow
def test_soak_faulty_network_prefix_agreement():
    async def main():
        plan = FaultPlan(drop_rate=0.02, delay_range=(0.0, 0.02),
                        duplicate_rate=0.01, seed=7)
        c = LocalCommittee.build(n=7, clients=3, view_timeout=1.5,
                                 checkpoint_interval=16, fault_plan=plan)
        for cl in c.clients:
            cl.request_timeout = 1.0
        c.start()
        t0 = time.perf_counter()
        ok = 0

        async def pump(cl, tag):
            nonlocal ok
            i = 0
            while time.perf_counter() - t0 < 45:
                try:
                    r = await cl.submit(f"put {tag}{i} v{i}", retries=10)
                    ok += 1 if r == "ok" else 0
                except (asyncio.TimeoutError, TimeoutError):
                    pass  # individual give-ups are chaos, not failure
                i += 1

        await asyncio.gather(*(pump(cl, f"c{j}_")
                               for j, cl in enumerate(c.clients)))
        plan.heal()
        plan.drop_rate = 0.0
        plan.duplicate_rate = 0.0
        await asyncio.sleep(2)
        # SAFETY: any checkpoint seq certified by 2+ replicas agrees
        seqs = set()
        for r in c.replicas:
            seqs.update(r.checkpoint_digests)
        for s in sorted(seqs):
            digests = {
                r.checkpoint_digests[s]
                for r in c.replicas
                if s in r.checkpoint_digests
            }
            assert len(digests) == 1, (s, digests)
        # LIVENESS: meaningful progress through the chaos
        assert ok >= 50, ok
        await c.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
