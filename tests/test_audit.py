"""Consensus audit plane (ISSUE 5): online safety-invariant monitor,
tamper-evident evidence ledger, byzantine injectors, and the cross-node
divergence auditor (tools/ledger_audit.py).

The acceptance criteria under test:
- an honest committee soak produces ZERO evidence records (the
  false-positive guard) and a clean-bill divergence report;
- an injected equivocation produces evidence naming exactly the faulty
  replica, whose signatures re-verify;
- a corrupted evidence line is rejected by ledger_audit with a nonzero
  exit.
"""

import asyncio
import json
import os
import sys

import pytest

from simple_pbft_tpu.audit import (
    GENESIS,
    SafetyAuditor,
    chain_hash,
    parse_evidence,
    reverify_record,
    substantiate_record,
)
from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.config import make_test_committee
from simple_pbft_tpu.crypto.signer import Signer
from simple_pbft_tpu.faults import (
    EquivocatingPrimary,
    FaultEvent,
    FaultInjector,
    FaultSchedule,
    ForkingCheckpointer,
)
from simple_pbft_tpu.messages import (
    Checkpoint,
    Commit,
    NewView,
    PrePrepare,
    Prepare,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import ledger_audit  # noqa: E402  (tools/ is not a package)
import pbft_top  # noqa: E402


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _committee_cfg(n=4):
    cfg, keys = make_test_committee(n=n)
    return cfg, keys


def _signed(keys, rid, cls, **fields):
    msg = cls(**fields)
    Signer(rid, keys[rid].seed).sign_msg(msg)
    return msg


# ---------------------------------------------------------------------------
# unit: invariant checks + evidence chain
# ---------------------------------------------------------------------------


def test_equivocating_votes_detected_and_resends_ignored():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("obs", cfg)
    a = _signed(keys, "r1", Prepare, view=0, seq=3, digest="aa" * 32)
    b = _signed(keys, "r1", Prepare, view=0, seq=3, digest="bb" * 32)
    aud.observe_message(a)
    aud.observe_message(a)  # byte-identical resend: not evidence
    assert aud.violations == 0
    aud.observe_message(b)
    assert aud.violations == 1
    assert aud.by_kind == {"equivocation": 1}
    assert aud.last_accused == ["r1"]
    aud.observe_message(b)  # the conflicting pair again: deduped
    assert aud.violations == 1
    rec = aud.recent()[0]
    # the two conflicting signed messages ride the record VERBATIM and
    # re-verify against the committee's published keys
    assert [m["digest"] for m in rec["msgs"]] == ["aa" * 32, "bb" * 32]
    assert reverify_record(cfg, rec)
    # a vote from a different sender with a different digest is not
    # equivocation (false-positive guard)
    c = _signed(keys, "r2", Prepare, view=0, seq=3, digest="cc" * 32)
    aud.observe_message(c)
    assert aud.violations == 1


def test_preprepare_equivocation_names_primary_and_reverifies():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("obs", cfg)
    blk_a = [{"kind": "request", "client_id": "c0", "sender": "c0",
              "timestamp": 1, "operation": "put a 1", "sig": "", "ack": 0}]
    pa = _signed(keys, "r0", PrePrepare, view=0, seq=1,
                 digest=PrePrepare.block_digest(blk_a), block=blk_a)
    pb = _signed(keys, "r0", PrePrepare, view=0, seq=1,
                 digest=PrePrepare.block_digest([]), block=[])
    aud.observe_message(pa)
    aud.observe_message(pb)
    assert aud.by_kind == {"equivocation": 1}
    rec = aud.recent()[0]
    assert rec["accused"] == ["r0"] and rec["attribution"] == "proof"
    # evidence pre-prepares are block-DETACHED and still re-verify (the
    # signature covers the detached payload)
    assert all(m["block"] == [] for m in rec["msgs"])
    assert reverify_record(cfg, rec)


def test_checkpoint_divergence_and_equivocation():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("r0", cfg)
    own = _signed(keys, "r0", Checkpoint, seq=4, state_digest="11" * 32)
    peer_ok = _signed(keys, "r1", Checkpoint, seq=4, state_digest="11" * 32)
    peer_bad = _signed(keys, "r2", Checkpoint, seq=4, state_digest="22" * 32)
    aud.observe_message(peer_ok)  # peer first, before our own executes
    aud.observe_message(own)
    assert aud.violations == 0  # matching digests: clean
    aud.observe_message(peer_bad)
    assert aud.by_kind == {"checkpoint_divergence": 1}
    rec = aud.recent()[0]
    assert rec["accused"] == ["r2"] and rec["attribution"] == "divergence"
    assert reverify_record(cfg, rec)
    # same sender, same seq, second digest: proof-grade equivocation
    peer_flip = _signed(keys, "r1", Checkpoint, seq=4,
                        state_digest="33" * 32)
    aud.observe_message(peer_flip)
    assert aud.by_kind["checkpoint_equivocation"] == 1


def test_commit_fork_detected():
    cfg, _ = _committee_cfg()
    aud = SafetyAuditor("r0", cfg)
    aud.observe_commit(0, 7, "aa" * 32)
    aud.observe_commit(0, 8, "ab" * 32)  # next seq: fine
    assert aud.violations == 0
    aud._on_committed(1, 7, "bb" * 32, None)  # conflicting certificate
    assert aud.by_kind == {"commit_fork": 1}


def test_rejected_new_view_needs_valid_envelope():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("obs", cfg)
    # primary(1) = r1 for the 4-replica test committee
    nv = _signed(keys, "r1", NewView, new_view=1, viewchange_proof=[])
    forged = NewView(new_view=1, viewchange_proof=[])
    forged.sender, forged.sig = "r1", "00" * 64  # forged envelope
    aud.observe_rejected_new_view(forged)
    assert aud.violations == 0  # a forgery must not frame r1
    aud.observe_rejected_new_view(nv)
    assert aud.by_kind == {"newview_invalid": 1}
    assert aud.recent()[0]["accused"] == ["r1"]
    assert reverify_record(cfg, aud.recent()[0])


def test_evidence_chain_is_tamper_evident(tmp_path):
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("r9", cfg, log_dir=str(tmp_path))
    for seq in (3, 4):
        aud.observe_message(
            _signed(keys, "r1", Prepare, view=0, seq=seq, digest="aa" * 32))
        aud.observe_message(
            _signed(keys, "r1", Prepare, view=0, seq=seq, digest="bb" * 32))
    aud.close()
    path = tmp_path / "r9.evidence.jsonl"
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    recs, err = parse_evidence(lines)
    assert err is None and len(recs) == 2
    assert recs[0]["prev"] == GENESIS
    assert recs[1]["prev"] == recs[0]["h"] == chain_hash(recs[0])
    # tamper with record 1's content: its own hash breaks
    bad = json.loads(lines[0])
    bad["detail"] = "history rewritten"
    _, err = parse_evidence([json.dumps(bad, sort_keys=True), lines[1]])
    assert err is not None and "tamper" in err
    # drop record 1: record 2's prev link breaks
    _, err = parse_evidence([lines[1]])
    assert err is not None and "chain" in err
    # undecodable line
    _, err = parse_evidence(["{not json", lines[1]])
    assert err is not None and "undecodable" in err


def test_violation_triggers_autopsy_dump(tmp_path):
    from simple_pbft_tpu.telemetry import NodeTelemetry, ProgressWatchdog

    cfg, keys = _committee_cfg()
    wd = ProgressWatchdog(
        NodeTelemetry("r0"), path=str(tmp_path / "r0.autopsy.json"))
    aud = SafetyAuditor("r0", cfg, watchdog=wd)
    aud.observe_message(
        _signed(keys, "r1", Prepare, view=0, seq=1, digest="aa" * 32))
    aud.observe_message(
        _signed(keys, "r1", Prepare, view=0, seq=1, digest="bb" * 32))
    assert wd.dumps == 1
    doc = json.loads((tmp_path / "r0.autopsy.json").read_text())
    assert "safety violation: equivocation" in doc["reason"]
    # one autopsy per auditor: a second violation doesn't re-dump
    aud.observe_message(
        _signed(keys, "r1", Prepare, view=0, seq=2, digest="aa" * 32))
    aud.observe_message(
        _signed(keys, "r1", Prepare, view=0, seq=2, digest="bb" * 32))
    assert aud.violations == 2 and wd.dumps == 1


def test_gc_folds_stores_at_watermark():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("r0", cfg)
    for seq in (1, 5):
        aud.observe_message(
            _signed(keys, "r1", Prepare, view=0, seq=seq, digest="aa" * 32))
        aud.observe_commit(0, seq, "cc" * 32)
    aud.observe_message(
        _signed(keys, "r0", Checkpoint, seq=4, state_digest="dd" * 32))
    aud.gc(4)
    assert list(aud._votes) == [("r1", 0, 5, "prepare")]
    assert list(aud._commits) == [5]
    assert list(aud._ckpts) == [4]  # the stable checkpoint itself stays


# ---------------------------------------------------------------------------
# snapshot surfaces + pbft_top
# ---------------------------------------------------------------------------


def test_snapshot_audit_block_and_schema_version():
    from simple_pbft_tpu.telemetry import SCHEMA_VERSION

    async def main():
        com = LocalCommittee.build(n=4, clients=1)
        auds = com.attach_auditors()
        com.start()
        try:
            assert await com.clients[0].submit("put s 1") == "ok"
            snap = com.node_telemetry("r0").snapshot()
            assert snap["schema_version"] == SCHEMA_VERSION
            assert snap["schema"] == SCHEMA_VERSION  # back-compat spelling
            aud = snap["audit"]
            assert aud["violations"] == 0
            assert aud["observations"] >= 1
            assert aud["chain_head"] == GENESIS
        finally:
            await com.stop()
            for a in auds.values():
                a.close()

    run(main())


def test_pbft_top_aud_column_and_evidence_fallback(tmp_path):
    snap = {"node": "r0", "replica": {"metrics": {}},
            "audit": {"violations": 2, "last_accused": "r0"}}
    row = pbft_top.row_from_snapshot(snap, "http", None, 1.0)
    assert row[pbft_top.COLUMNS.index("AUD")] == "2:r0"
    clean = {"node": "r0", "replica": {"metrics": {}},
             "audit": {"violations": 0}}
    row = pbft_top.row_from_snapshot(clean, "http", None, 1.0)
    assert row[pbft_top.COLUMNS.index("AUD")] == "0"
    # post-mortem fallback: synthesize the audit block from the ledger
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("r7", cfg, log_dir=str(tmp_path))
    aud.observe_message(
        _signed(keys, "r2", Prepare, view=0, seq=1, digest="aa" * 32))
    aud.observe_message(
        _signed(keys, "r2", Prepare, view=0, seq=1, digest="bb" * 32))
    aud.close()
    summ = pbft_top.evidence_summary(str(tmp_path / "r7.evidence.jsonl"))
    assert summ == {"violations": 1, "last_kind": "equivocation",
                    "last_accused": "r2"}
    _, _, evidence = pbft_top.discover(str(tmp_path))
    assert evidence == {"r7": str(tmp_path / "r7.evidence.jsonl")}


# ---------------------------------------------------------------------------
# byzantine injectors (faults.py satellites)
# ---------------------------------------------------------------------------


def test_fault_schedule_parses_byzantine_kinds_deterministically():
    ids = [f"r{i}" for i in range(4)]
    s = FaultSchedule.parse("seed=9,equiv=1,forkckpt=2", horizon=10.0,
                            replica_ids=ids)
    kinds = sorted(e.kind for e in s.events)
    assert kinds == ["equivocate", "fork_checkpoint", "fork_checkpoint"]
    assert s == FaultSchedule.parse("seed=9,equiv=1,forkckpt=2",
                                    horizon=10.0, replica_ids=ids)
    assert s.summary()["counts"] == {"equivocate": 1, "fork_checkpoint": 2}
    with pytest.raises(ValueError, match="equivv"):
        FaultSchedule.parse("equivv=1", horizon=10.0)


def test_injector_arms_byzantine_wrappers_idempotently():
    async def main():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        inj = FaultInjector(
            committee=com,
            schedule=FaultSchedule.generate(seed=1, horizon=1.0),
        )
        try:
            inj._apply(FaultEvent(t=0, kind="equivocate"))
            assert inj.applied[-1]["applied"] is True
            assert isinstance(com.replica("r0").transport,
                              EquivocatingPrimary)
            inj._apply(FaultEvent(t=0, kind="fork_checkpoint", target="r2"))
            assert isinstance(com.replica("r2").transport,
                              ForkingCheckpointer)
            # re-arming the same wrapper kind is a no-op, not a stack
            inj._apply(FaultEvent(t=0, kind="equivocate"))
            assert inj.applied[-1]["applied"] is False
            assert len(inj.byzantine) == 2
            assert inj.byzantine_injections == 0  # nothing forged yet
        finally:
            await com.stop()

    run(main())


# ---------------------------------------------------------------------------
# end to end: soak / equivocation / checkpoint fork / corrupted ledger
# ---------------------------------------------------------------------------


def test_honest_soak_zero_evidence_and_clean_bill(tmp_path):
    """The false-positive guard: an honest committee crossing several
    checkpoint folds yields zero evidence records, no evidence FILES at
    all (the sink is lazy), and a clean-bill report with exit 0."""

    async def main():
        com = LocalCommittee.build(n=4, clients=2, checkpoint_interval=4)
        auds = com.attach_auditors(log_dir=str(tmp_path))
        com.start()
        try:
            for i in range(8):
                for j, cl in enumerate(com.clients):
                    assert await cl.submit(f"put h{j}_{i} {i}") == "ok"
            await asyncio.sleep(0.3)  # let trailing checkpoints settle
        finally:
            await com.stop()
            for a in auds.values():
                a.close()
        for rid, a in auds.items():
            assert a.violations == 0, (rid, a.snapshot())
            assert a.observations > 0, rid
        assert not list(tmp_path.glob("*.evidence.jsonl"))
        cfg, _ = _committee_cfg()
        report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
        assert code == 0, report
        assert report["clean"] is True
        assert report["commit_matrix"]["agree"] is True
        assert report["commit_matrix"]["seqs"] >= 8
        assert report["checkpoint_matrix"]["agree"] is True
        assert report["accused"] == []

    run(main())


def test_equivocating_primary_accused_with_reverified_signatures(tmp_path):
    """The acceptance scenario: r0 forks its pre-prepares to disjoint
    halves; the cross-node ledger join (and any online sighting via the
    repair path) must accuse exactly r0, signatures re-verified."""

    async def main():
        com = LocalCommittee.build(n=4, clients=1, view_timeout=1.0,
                                   checkpoint_interval=8)
        auds = com.attach_auditors(log_dir=str(tmp_path))
        evil = com.replica("r0")
        evil.transport = EquivocatingPrimary(
            evil.transport, Signer("r0", com.keys["r0"].seed))
        com.clients[0].request_timeout = 2.0
        com.start()
        ok = 0
        try:
            for i in range(10):
                try:
                    r = await com.clients[0].submit(f"put e{i} {i}",
                                                    retries=8)
                    ok += 1 if r == "ok" else 0
                except Exception:
                    pass
        finally:
            await com.stop()
            for a in auds.values():
                a.close()
        assert evil.transport.injections >= 1
        assert ok >= 4, ok  # liveness: the honest quorum keeps committing
        cfg, _ = _committee_cfg()
        report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
        assert code == 1, report
        assert report["accused"] == ["r0"], report["accused"]
        # SAFETY: honest nodes never committed diverging digests
        assert report["commit_matrix"]["agree"] is True
        # the accusation rests on re-verified signatures: either a
        # proposal-join fork or proof-grade evidence, never hearsay
        assert report["proposal_forks"] or any(
            a["verified"] for a in report["accusations"])
        for f in report["proposal_forks"]:
            assert f["verified"] is True and f["accused"] == ["r0"]
        assert report["evidence"]["signature_failures"] == 0

    run(main())


def test_forking_checkpointer_accused_by_every_honest_node(tmp_path):
    async def main():
        com = LocalCommittee.build(n=4, clients=1, checkpoint_interval=4)
        auds = com.attach_auditors(log_dir=str(tmp_path))
        evil = com.replica("r3")
        evil.transport = ForkingCheckpointer(
            evil.transport, Signer("r3", com.keys["r3"].seed))
        com.start()
        try:
            for i in range(12):
                assert await com.clients[0].submit(f"put f{i} {i}") == "ok"
            await asyncio.sleep(0.3)
        finally:
            await com.stop()
            for a in auds.values():
                a.close()
        assert evil.transport.injections >= 1
        # every honest node independently produced divergence evidence
        for rid in ("r0", "r1", "r2"):
            assert auds[rid].by_kind.get("checkpoint_divergence"), rid
            assert auds[rid].accused_ever == {"r3"}
        assert auds["r3"].violations == 0  # its own state is honest
        cfg, _ = _committee_cfg()
        report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
        assert code == 1
        assert report["accused"] == ["r3"]
        assert report["evidence"]["signature_failures"] == 0

    run(main())


def test_framing_evidence_not_substantiated(tmp_path):
    """A byzantine node's SELF-AUTHORED ledger must not frame honest
    replicas: records whose (validly signed) messages do not constitute
    the claimed violation accuse nobody and flag the ledger."""
    cfg, keys = _committee_cfg()
    # valid signatures, but the same digest twice: NOT equivocation
    same = [
        _signed(keys, "r0", Prepare, view=0, seq=1, digest="aa" * 32)
        .to_dict()
        for _ in range(2)
    ]
    framed = {"kind": "equivocation", "accused": ["r0"],
              "attribution": "proof", "msgs": same}
    assert not substantiate_record(cfg, framed)
    # empty msgs under a proof kind: also unsubstantiated
    assert not substantiate_record(
        cfg, {"kind": "equivocation", "accused": ["r0"], "msgs": []})
    # cross-phase pair (a prepare for X plus a commit for Y): not a slot
    mixed = [
        _signed(keys, "r0", Prepare, view=0, seq=1,
                digest="aa" * 32).to_dict(),
        _signed(keys, "r0", Commit, view=0, seq=1,
                digest="bb" * 32).to_dict(),
    ]
    assert not substantiate_record(
        cfg, {"kind": "equivocation", "accused": ["r0"], "msgs": mixed})
    # a genuine pair substantiates
    real = [
        _signed(keys, "r0", Prepare, view=0, seq=1,
                digest="aa" * 32).to_dict(),
        _signed(keys, "r0", Prepare, view=0, seq=1,
                digest="bb" * 32).to_dict(),
    ]
    assert substantiate_record(
        cfg, {"kind": "equivocation", "accused": ["r0"], "msgs": real})
    # end to end: a hand-forged (but correctly hash-chained) framing
    # ledger yields unsubstantiated + exit 2, and r0 is NOT accused
    rec = {"evt": "violation", "schema_version": 1, "node": "evil",
           "t_wall": 0.0, "kind": "equivocation", "accused": ["r0"],
           "attribution": "proof", "detail": "framed", "msgs": same,
           "prev": GENESIS}
    rec["h"] = chain_hash(rec)
    (tmp_path / "evil.evidence.jsonl").write_text(
        json.dumps(rec, sort_keys=True) + "\n")
    report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
    assert code == 2, report
    assert report["accused"] == []
    assert report["evidence"]["unsubstantiated"] == 1


def test_framing_proposal_observation_not_a_fork(tmp_path):
    """A fabricated proposal observation (a REAL signed message filed
    under the wrong slot/digest) must not produce a fork accusation."""
    cfg, keys = _committee_cfg()
    blk = []
    real = _signed(keys, "r0", PrePrepare, view=0, seq=1,
                   digest=PrePrepare.block_digest(blk), block=blk)
    # honest ledger: the real proposal, filed truthfully
    honest = {"evt": "proposal", "sender": "r0", "view": 0, "seq": 1,
              "digest": real.digest, "msg": real.to_dict()}
    # byzantine ledger: the SAME real signed message filed under a
    # different digest — signature-valid, content-unbound
    lie = {"evt": "proposal", "sender": "r0", "view": 0, "seq": 1,
           "digest": "ff" * 32, "msg": real.to_dict()}
    (tmp_path / "good.audit.jsonl").write_text(json.dumps(honest) + "\n")
    (tmp_path / "evil.audit.jsonl").write_text(json.dumps(lie) + "\n")
    report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
    assert report["accused"] == [], report
    assert report["proposal_forks"] == []
    assert report["evidence"]["unverified_forks"] == 1
    assert code == 2  # a lying ledger is a corrupt ledger


def test_non_primary_new_view_evidence_substantiates(tmp_path):
    """A BACKUP signing a NEW-VIEW is misbehavior too: the online record
    against it must survive offline substantiation (regression: the
    offline check once required sender == primary, misclassifying the
    honest reporter's ledger as a framing attempt)."""
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("obs", cfg, log_dir=str(tmp_path))
    # r3 is NOT primary of view 1 (that's r1): validate_new_view rejects
    nv = _signed(keys, "r3", NewView, new_view=1, viewchange_proof=[])
    aud.observe_rejected_new_view(nv)
    aud.close()
    assert aud.by_kind == {"newview_invalid": 1}
    assert aud.last_accused == ["r3"]
    assert substantiate_record(cfg, aud.recent()[0])
    report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
    assert code == 1, report
    assert report["accused"] == ["r3"]
    assert report["evidence"]["unsubstantiated"] == 0


def test_rejected_new_view_envelope_checks_bounded():
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("obs", cfg)
    forged = NewView(new_view=1, viewchange_proof=[])
    forged.sender, forged.sig = "r1", "00" * 64
    for _ in range(SafetyAuditor.MAX_ENVELOPE_CHECKS + 10):
        aud.observe_rejected_new_view(forged)
    assert aud._envelope_checks == SafetyAuditor.MAX_ENVELOPE_CHECKS
    assert aud.violations == 0


def test_corrupted_evidence_rejected_nonzero_exit(tmp_path):
    cfg, keys = _committee_cfg()
    aud = SafetyAuditor("r1", cfg, log_dir=str(tmp_path))
    for seq in (1, 2):
        aud.observe_message(
            _signed(keys, "r2", Prepare, view=0, seq=seq, digest="aa" * 32))
        aud.observe_message(
            _signed(keys, "r2", Prepare, view=0, seq=seq, digest="bb" * 32))
    aud.close()
    path = tmp_path / "r1.evidence.jsonl"
    report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
    assert code == 1 and report["evidence"]["chains_ok"]
    # flip one field inside the FIRST record: self-hash breaks
    lines = path.read_text().splitlines()
    rec = json.loads(lines[0])
    rec["accused"] = ["r0"]  # frame someone else
    lines[0] = json.dumps(rec, sort_keys=True)
    path.write_text("\n".join(lines) + "\n")
    report, code = ledger_audit.run_audit([str(tmp_path)], cfg=cfg)
    assert code == 2, report
    assert not report["evidence"]["chains_ok"]
    assert report["evidence"]["corrupt"][0]["node"] == "r1"
