"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Benchmarks (bench.py) run on the real TPU chip; tests exercise the same
jitted code paths on CPU, with 8 virtual devices so the shard_map /
multi-chip sharding paths are genuinely executed (see SURVEY.md §7 and the
driver's dryrun_multichip contract).

The ambient environment pre-imports jax and registers an 'axon' backend
(the tunnel to the one real TPU chip) via sitecustomize, overriding
JAX_PLATFORMS — setting env vars here is too late. Unit tests must never
run over the tunnel (each jit would remote-compile, and a killed test run
wedges the device for every other process), so we override the platform
in-process: XLA_FLAGS must be in the env before the CPU backend
initializes, and jax.config wins over the sitecustomize registration as
long as no backend has been used yet (none has at conftest import).
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the crypto kernels take ~40-60 s each to
# compile on a small CPU host; caching them across test runs turns every
# rerun's compile into a disk load. Safe to share — entries key on the
# full HLO + flags.
import simple_pbft_tpu  # noqa: E402

simple_pbft_tpu.enable_jit_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running scenario (large committees, storms)"
    )
    config.addinivalue_line(
        "markers",
        "sanitize_allow(kind, ...): violations of these sanitizer kinds "
        "(loop/locks) are EXPECTED by this test (it deliberately stalls "
        "a loop or crosses a lock) and do not fail it",
    )


# ---------------------------------------------------------------------------
# runtime sanitizers (ISSUE 8): PBFT_SANITIZE=loop,locks arms them; every
# violation recorded during a test FAILS that test with the attributed
# stack. Zero overhead when the env is unset (the fixture yields through).
# ---------------------------------------------------------------------------

import pytest  # noqa: E402

from simple_pbft_tpu import sanitize  # noqa: E402

sanitize.install()  # no-op unless PBFT_SANITIZE asks for the loop watcher


@pytest.fixture(autouse=True)
def _pbft_sanitizer_gate(request):
    if not (sanitize.enabled("loop") or sanitize.enabled("locks")):
        yield
        return
    sanitize.take_violations()  # drop anything from a previous test
    sanitize.reset_owners()  # fresh objects get fresh owner bindings
    yield
    viols = sanitize.take_violations()
    marker = request.node.get_closest_marker("sanitize_allow")
    if marker is not None:
        allowed = set(marker.args)
        viols = [v for v in viols if v["kind"] not in allowed]
    if viols:
        pytest.fail(sanitize.format_violations(viols), pytrace=False)
