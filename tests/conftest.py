"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Benchmarks (bench.py) run on the real TPU chip; tests exercise the same
jitted code paths on CPU, with 8 virtual devices so the shard_map /
multi-chip sharding paths are genuinely executed (see SURVEY.md §7 and the
driver's dryrun_multichip contract).

Must run before jax is imported anywhere — hence env vars set at module
import time in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
