"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Benchmarks (bench.py) run on the real TPU chip; tests exercise the same
jitted code paths on CPU, with 8 virtual devices so the shard_map /
multi-chip sharding paths are genuinely executed (see SURVEY.md §7 and the
driver's dryrun_multichip contract).

Must run before jax is imported anywhere — hence env vars set at module
import time in conftest.
"""

import os

# FORCE cpu (not setdefault): the ambient environment pins
# JAX_PLATFORMS to the single real TPU chip's tunnel, which must never be
# used for unit tests (each jit would remote-compile over the tunnel, and
# a killed test run wedges the device for every other process).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
