"""Known-answer + property tests for the pure-Python Ed25519 backend.

RFC 8032 §7.1 test vector 1 plus cross-validation against the independent
`cryptography` (OpenSSL) implementation.
"""

import os

import pytest

from simple_pbft_tpu.crypto import ed25519_cpu as ed


# RFC 8032 §7.1 TEST 1 (empty message)
RFC_SEED = bytes.fromhex(
    "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
)
RFC_PUB = bytes.fromhex(
    "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
)
RFC_SIG = bytes.fromhex(
    "e5564300c360ac729086e2cc806e828a"
    "84877f1eb8e5d974d873e06522490155"
    "5fb8821590a33bacc61e39701cf9b46b"
    "d25bf5f0595bbe24655141438e7a100b"
)


def test_rfc8032_vector1_pubkey():
    assert ed.public_key(RFC_SEED) == RFC_PUB


def test_rfc8032_vector1_sign():
    assert ed.sign(RFC_SEED, b"") == RFC_SIG


def test_rfc8032_vector1_verify():
    assert ed.verify(RFC_PUB, b"", RFC_SIG)


def test_tampered_message_rejected():
    assert not ed.verify(RFC_PUB, b"x", RFC_SIG)


def test_tampered_sig_rejected():
    bad = bytearray(RFC_SIG)
    bad[0] ^= 1
    assert not ed.verify(RFC_PUB, b"", bytes(bad))


def test_wrong_key_rejected():
    other_pub = ed.public_key(b"\x01" * 32)
    assert not ed.verify(other_pub, b"", RFC_SIG)


def test_noncanonical_s_rejected():
    s = int.from_bytes(RFC_SIG[32:], "little") + ed.L
    bad = RFC_SIG[:32] + int.to_bytes(s, 32, "little")
    assert not ed.verify(RFC_PUB, b"", bad)


def test_sign_verify_roundtrip_many():
    for i in range(8):
        seed = bytes([i]) * 32
        pub = ed.public_key(seed)
        msg = b"message-%d" % i
        sig = ed.sign(seed, msg)
        assert ed.verify(pub, msg, sig)
        assert not ed.verify(pub, msg + b"!", sig)


def test_cross_check_against_openssl():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    for i in range(4):
        seed = os.urandom(32)
        msg = os.urandom(100)
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        from cryptography.hazmat.primitives import serialization

        their_pub = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        their_sig = sk.sign(msg)
        # Our pubkey matches theirs; our signature matches theirs
        # (Ed25519 signing is deterministic); our verify accepts theirs.
        assert ed.public_key(seed) == their_pub
        assert ed.sign(seed, msg) == their_sig
        assert ed.verify(their_pub, msg, their_sig)


def test_batch_verify_bitmap():
    seeds = [bytes([i]) * 32 for i in range(4)]
    pubs = [ed.public_key(s) for s in seeds]
    msgs = [b"m%d" % i for i in range(4)]
    sigs = [ed.sign(s, m) for s, m in zip(seeds, msgs)]
    sigs[2] = sigs[2][:-1] + bytes([sigs[2][-1] ^ 1])
    assert ed.batch_verify_cpu(pubs, msgs, sigs) == [True, True, False, True]


def test_point_roundtrip():
    p = ed.point_mul(12345, ed.B)
    enc = ed.point_compress(p)
    q = ed.point_decompress(enc)
    assert q is not None
    assert ed.point_equal(p, q)


def test_decompress_invalid():
    # A y-coordinate >= p with no valid x (all-0xff is non-canonical/invalid)
    assert ed.point_decompress(b"\xff" * 32) is None


def test_openssl_verifier_key_cache_is_bounded():
    """The OpenSSL backend's parsed-key cache must not grow without
    bound under an adversarial fresh-key spray (it serves as the
    TpuVerifier's over-bank-cap fallback, which sees exactly that
    traffic shape); verdicts stay correct across the reset."""
    pytest.importorskip("cryptography")
    from simple_pbft_tpu.crypto.verifier import BatchItem, OpenSSLVerifier

    v = OpenSSLVerifier()
    v.MAX_KEYS = 8  # shrink the bound for the test
    items = []
    for i in range(20):
        seed = bytes([i]) * 32
        msg = b"spray %d" % i
        items.append(BatchItem(ed.public_key(seed), msg, ed.sign(seed, msg)))
    bad = BatchItem(items[0].pubkey, b"other", items[0].sig)
    out = v.verify_batch(items + [bad])
    assert out == [True] * 20 + [False]
    assert len(v._cache) <= 8
    # a key evicted by a reset and untouched since (key 1: loaded before
    # the first clear, never re-seen) must still verify on re-sight —
    # the reload-after-clear path, not a cache hit
    assert items[1].pubkey not in v._cache
    assert v.verify_batch([items[1]]) == [True]
