"""TPU (JAX) batched verifier vs the pure-Python RFC 8032 oracle.

Covers SURVEY.md §4's crypto-plane test strategy: RFC 8032 known-answer
vectors, adversarial inputs (corrupted bits, non-canonical encodings,
wrong lengths), per-position verdict bitmaps under batching, and the
shard_map quorum step on the virtual 8-device mesh.
"""

import numpy as np
import pytest

from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.crypto.verifier import BatchItem
from simple_pbft_tpu.crypto.tpu_verifier import (
    TpuVerifier,
    prepare_batch,
    verify_kernel,
)

# RFC 8032 §7.1 test vectors 1-3 (seed, pubkey, msg, sig)
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.fixture(scope="module")
def verifier():
    return TpuVerifier()


def _signed(i: int, msg: bytes):
    seed = bytes([i]) * 32
    return BatchItem(ref.public_key(seed), msg, ref.sign(seed, msg))


def test_rfc8032_vectors(verifier):
    items = [
        BatchItem(bytes.fromhex(pk), bytes.fromhex(msg), bytes.fromhex(sig))
        for _, pk, msg, sig in RFC8032_VECTORS
    ]
    assert verifier.verify_batch(items) == [True] * len(items)


def test_bitmap_positions_and_adversarial(verifier):
    """One mixed batch: verdict positions must map 1:1 to items, agreeing
    with the CPU oracle on every adversarial case."""
    good = [_signed(i, b"vote %d" % i) for i in range(4)]
    bad_sig = bytearray(good[0].sig)
    bad_sig[1] ^= 0x40
    noncanon_s = good[2].sig[:32] + (
        (int.from_bytes(good[2].sig[32:], "little") + ref.L).to_bytes(32, "little")
    )
    items = [
        good[0],
        BatchItem(good[0].pubkey, good[0].msg, bytes(bad_sig)),  # flipped bit
        good[1],
        BatchItem(good[1].pubkey, b"forged", good[1].sig),  # wrong msg
        BatchItem(good[2].pubkey, good[2].msg, noncanon_s),  # S >= L
        BatchItem(good[3].pubkey[:16], good[3].msg, good[3].sig),  # bad len
        BatchItem(b"\xff" * 32, good[3].msg, good[3].sig),  # y >= p
        good[3],
    ]
    got = verifier.verify_batch(items)
    oracle = [ref.verify(i.pubkey, i.msg, i.sig) for i in items]
    assert got == oracle == [True, False, True, False, False, False, False, True]


def test_swapped_keys_rejected(verifier):
    a, b = _signed(1, b"m1"), _signed(2, b"m2")
    items = [BatchItem(b.pubkey, a.msg, a.sig), BatchItem(a.pubkey, b.msg, b.sig)]
    assert verifier.verify_batch(items) == [False, False]


def test_bucket_padding_indifferent(verifier):
    """Verdicts must not depend on padding rows (batch of 3 -> bucket 8)."""
    items = [_signed(i, b"pad %d" % i) for i in range(3)]
    assert verifier.verify_batch(items) == [True, True, True]


def test_empty_batch(verifier):
    assert verifier.verify_batch([]) == []


def test_windows_major_extraction():
    """wbits-bit window extraction must reassemble to the scalar for
    every supported width (the w>4 comb geometries depend on it)."""
    from simple_pbft_tpu.ops import comb

    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (16, 32), dtype=np.uint8)
    data[0, :] = 0xFF
    for w in (4, 5, 6):
        out = comb.windows_major_np(data, w)
        assert out.shape == (comb.npos_for(w), 16)
        assert (out < (1 << w)).all() and (out >= 0).all()
        for j in range(16):
            v = sum(int(out[i, j]) << (w * i) for i in range(out.shape[0]))
            assert v == int.from_bytes(bytes(data[j]), "little")


def test_fused_window5_matches_oracle():
    """The wide-window comb (fewer positions, bigger tables) must stay
    bit-exact: w=5 TpuVerifier vs the RFC 8032 oracle on a mixed batch."""
    v5 = TpuVerifier(mode="fused", window=5)
    good = [_signed(i, b"w5 %d" % i) for i in range(3)]
    tampered = BatchItem(good[0].pubkey, b"tampered", good[0].sig)
    items = good + [tampered]
    oracle = [ref.verify(i.pubkey, i.msg, i.sig) for i in items]
    assert v5.verify_batch(items) == oracle == [True, True, True, False]


def test_wire_kernel_matches_host_prep():
    """The wire kernel (raw (B, 96) bytes, on-device unpack) must be
    bit-identical to the host-prepped fused kernel for every window
    width — same verdicts on valid, tampered and padding rows."""
    import jax

    from simple_pbft_tpu.crypto.tpu_verifier import (
        KeyBank,
        prepare_comb_batch,
        prepare_wire_batch,
    )
    from simple_pbft_tpu.ops import comb

    good = [_signed(i, b"wire %d" % i) for i in range(5)]
    bad = BatchItem(good[0].pubkey, b"altered", good[0].sig)
    items = good + [bad]
    for w in (4, 5, 6):
        bank = KeyBank(mode="fused", window=w)
        hp, _ = prepare_comb_batch(items, bank)
        hp = hp.padded(8)
        s_nib, k_nib, a_idx, r_y, r_sign, pre = hp.arrays()
        tables = bank.device_tables()
        want = np.asarray(
            jax.jit(comb.fused_verify_kernel, static_argnames=("window",))(
                s_nib, k_nib, a_idx, tables, r_y, r_sign, pre, window=1 << w
            )
        )
        wp, _ = prepare_wire_batch(items, bank)
        wire, wa_idx, wpre = wp.padded(8).arrays()
        got = np.asarray(
            jax.jit(
                comb.fused_verify_wire_kernel, static_argnames=("window",)
            )(wire, wa_idx, tables, wpre, window=1 << w)
        )
        assert (got == want).all(), (w, got, want)
        assert got[: len(items)].tolist() == [True] * 5 + [False]


def test_initial_keys_pins_table_shape_and_warm_is_inert():
    """TpuVerifier(initial_keys=...) must fix the bank capacity so live
    traffic never grows it (a growth means a fresh kernel compile under
    the device lock — the bug that zeroed every consensus-on-chip run),
    and warm() must not register its dummy row into the bank."""
    v = TpuVerifier(initial_keys=20)
    assert v._bank._cap == 32  # next power of two
    v.warm(buckets=[8])
    assert len(v._bank._index) == 0  # dummy never registered
    items = [_signed(i, b"pin %d" % i) for i in range(6)]
    assert v.verify_batch(items) == [True] * 6
    assert v._bank._cap == 32  # capacity untouched by traffic


def test_jit_cache_dir_is_host_namespaced(tmp_path):
    """enable_jit_cache must partition by CPU fingerprint (cross-machine
    XLA:CPU AOT entries wedge at execution) and must not initialize a
    backend to do it."""
    import jax

    from simple_pbft_tpu import _cache_fingerprint, enable_jit_cache

    before = jax.config.jax_compilation_cache_dir
    try:
        enable_jit_cache(str(tmp_path))
        got = jax.config.jax_compilation_cache_dir
        assert got == str(tmp_path / f"host-{_cache_fingerprint()}")
        assert _cache_fingerprint() == _cache_fingerprint()  # stable
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_keybank_cap_falls_back_to_cpu():
    """Keys beyond the bank cap must still verify correctly (CPU path),
    and the bank must not grow past max_keys."""
    from simple_pbft_tpu.crypto.tpu_verifier import KeyBank

    v = TpuVerifier()
    v._bank = KeyBank(initial_capacity=2, max_keys=2, mode=v._mode)
    items = [_signed(i, b"cap %d" % i) for i in range(4)]  # 4 distinct keys
    bad = bytearray(items[3].sig)
    bad[2] ^= 4
    items.append(BatchItem(items[3].pubkey, items[3].msg, bytes(bad)))
    assert v.verify_batch(items) == [True, True, True, True, False]
    assert len(v._bank._index) == 2


def test_overbank_fallback_agrees_with_kernel():
    """The over-bank-cap fallback must be KERNEL-EQUIVALENT (ADVICE r5):
    the same batch split between kernel rows and fallback rows shares
    one verdict bitmap, so the two paths must agree on every known edge
    vector — non-canonical S (>= L), y >= p key encodings, wrong
    lengths, tampered bits — or a crafted signature could verify on one
    replica's split and not another's. Pins both the agreement and the
    fallback CLASS (native/oracle, never OpenSSL)."""
    from simple_pbft_tpu.crypto.tpu_verifier import KeyBank
    from simple_pbft_tpu.crypto.verifier import (
        CpuVerifier,
        NativeEdVerifier,
        kernel_equivalent_cpu_verifier,
    )

    good = [_signed(50 + i, b"edge %d" % i) for i in range(3)]
    flipped = bytearray(good[0].sig)
    flipped[1] ^= 0x40
    noncanon_s = good[1].sig[:32] + (
        (int.from_bytes(good[1].sig[32:], "little") + ref.L).to_bytes(
            32, "little"
        )
    )
    edge_items = [
        good[0],
        BatchItem(good[0].pubkey, good[0].msg, bytes(flipped)),
        good[1],
        BatchItem(good[1].pubkey, b"forged", good[1].sig),
        BatchItem(good[1].pubkey, good[1].msg, noncanon_s),  # S >= L
        BatchItem(good[2].pubkey[:16], good[2].msg, good[2].sig),  # bad len
        BatchItem(b"\xff" * 32, good[2].msg, good[2].sig),  # y >= p
        good[2],
    ]
    oracle = [ref.verify(i.pubkey, i.msg, i.sig) for i in edge_items]
    # kernel verdicts: roomy bank, every key resident
    kernel = TpuVerifier().verify_batch(edge_items)
    assert kernel == oracle
    # fallback verdicts: bank capacity 1, pre-occupied by an unrelated
    # key, so EVERY edge item routes to the over-cap fallback path
    v = TpuVerifier()
    v._bank = KeyBank(initial_capacity=1, max_keys=1, mode=v._mode)
    occupier = _signed(99, b"occupier")
    assert v.verify_batch([occupier]) == [True]
    assert len(v._bank._index) == 1
    got = v.verify_batch(edge_items)
    assert got == kernel == oracle
    assert len(v._bank._index) == 1  # nothing evicted/registered
    # the fallback actually ran and is a kernel-equivalent class
    assert v._cpu_fb is not None
    assert isinstance(v._cpu_fb, (NativeEdVerifier, CpuVerifier))
    assert type(kernel_equivalent_cpu_verifier()) is type(v._cpu_fb)


@pytest.mark.parametrize("packed", [False, True], ids=["dense", "packed"])
def test_meshed_tpu_verifier_fused(packed):
    """TpuVerifier(mesh=...) fused mode: the GSPMD-sharded jit path (with
    its forced XLA accumulator — a Pallas call has no partitioning rule)
    must agree with the oracle over the 8-device mesh, in both table-row
    layouts (the table is replicated whatever its row width — this
    pre-validates the default flip if the on-chip A/B favors packing)."""
    import jax
    from jax.sharding import Mesh

    from simple_pbft_tpu.ops import comb

    comb.use_row_packing(packed)
    try:
        mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
        v = TpuVerifier(mesh=mesh, mode="fused")
        items = [_signed(i % 4, b"meshed %d" % i) for i in range(12)]
        forged = BatchItem(items[0].pubkey, b"not the msg", items[0].sig)
        items.append(forged)
        oracle = [ref.verify(i.pubkey, i.msg, i.sig) for i in items]
        assert v.verify_batch(items) == oracle == [True] * 12 + [False]
    finally:
        comb.use_row_packing(False)


def test_sharded_comb_quorum_step():
    """Comb-engine shard_map verify + psum tally over the 8-device mesh."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from simple_pbft_tpu.ops import comb
    from simple_pbft_tpu.crypto.tpu_verifier import KeyBank, prepare_comb_batch
    from simple_pbft_tpu.parallel import make_comb_quorum_step

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
    n_inst = 2
    items = [_signed(i % 8, b"inst vote %d" % i) for i in range(16)]
    broken = bytearray(items[0].sig)
    broken[3] ^= 1
    items[0] = BatchItem(items[0].pubkey, items[0].msg, bytes(broken))

    bank = KeyBank()
    prep, _fallback = prepare_comb_batch(items, bank)
    inst = np.arange(16, dtype=np.int32) % n_inst
    onehot = np.eye(n_inst, dtype=np.int32)[inst]
    vec = NamedSharding(mesh, P("dp"))  # (B,)
    mat = NamedSharding(mesh, P(None, "dp"))  # batch axis trailing
    repl = NamedSharding(mesh, P())
    s_nib, k_nib, a_idx, r_y, r_sign, precheck = prep.arrays()
    args = [
        jax.device_put(s_nib, mat),
        jax.device_put(k_nib, mat),
        jax.device_put(a_idx, vec),
        jax.device_put(np.asarray(bank.device_tables()), repl),
        jax.device_put(comb.base_table(), repl),
        jax.device_put(r_y, mat),
        jax.device_put(r_sign, vec),
        jax.device_put(precheck, vec),
        jax.device_put(onehot, NamedSharding(mesh, P("dp", None))),
    ]
    verdict, counts = make_comb_quorum_step(mesh)(*args)
    verdict, counts = np.asarray(verdict), np.asarray(counts)
    assert not verdict[0] and verdict[1:].all()
    assert counts.tolist() == [7, 8]


def test_sharded_quorum_step():
    """Ladder-engine shard_map verify + psum tally (fallback path)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from simple_pbft_tpu.parallel import make_quorum_step

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
    n_inst = 2
    items = [_signed(i % 8, b"inst vote %d" % i) for i in range(16)]
    # corrupt one vote of instance 0
    broken = bytearray(items[0].sig)
    broken[3] ^= 1
    items[0] = BatchItem(items[0].pubkey, items[0].msg, bytes(broken))

    prep = prepare_batch(items)
    inst = np.arange(16, dtype=np.int32) % n_inst
    onehot = np.eye(n_inst, dtype=np.int32)[inst]
    vec = NamedSharding(mesh, P("dp"))
    mat = NamedSharding(mesh, P(None, "dp"))  # batch axis trailing
    # arg order: a_y, a_sign, r_y, r_sign, s_bits, k_bits, precheck
    specs = [mat, vec, mat, vec, mat, mat, vec]
    args = [jax.device_put(a, s) for a, s in zip(prep.arrays(), specs)]
    args.append(jax.device_put(onehot, NamedSharding(mesh, P("dp", None))))

    verdict, counts = make_quorum_step(mesh)(*args)
    verdict, counts = np.asarray(verdict), np.asarray(counts)
    assert not verdict[0] and verdict[1:].all()
    assert counts.tolist() == [7, 8]  # one invalid vote lost from instance 0


def test_pallas_accumulate_matches_xla():
    """The Pallas madd-loop kernel (interpret mode on CPU) must agree
    bit-for-bit with the XLA fori_loop path on the same batch."""
    import jax.numpy as jnp

    from simple_pbft_tpu.ops import comb
    from simple_pbft_tpu.crypto.tpu_verifier import KeyBank, prepare_comb_batch

    items = [_signed(i % 3, b"pallas %d" % i) for i in range(8)]
    broken = bytearray(items[5].sig)
    broken[9] ^= 2
    items[5] = BatchItem(items[5].pubkey, items[5].msg, bytes(broken))

    bank = KeyBank(mode="fused")
    prep, _ = prepare_comb_batch(items, bank)
    s_nib, k_nib, a_idx, r_y, r_sign, precheck = prep.arrays()
    tables = bank.device_tables()
    args = (jnp.asarray(s_nib), jnp.asarray(k_nib), jnp.asarray(a_idx),
            tables, jnp.asarray(r_y), jnp.asarray(r_sign), jnp.asarray(precheck))
    try:
        comb.use_accum_impl("xla")
        want = np.asarray(comb.fused_verify_kernel(*args))
        comb.use_accum_impl("pallas")
        got = np.asarray(comb.fused_verify_kernel(*args))
    finally:
        comb.use_accum_impl("auto")  # restore the shipped default
    assert want.tolist() == [True] * 5 + [False] + [True] * 2
    assert got.tolist() == want.tolist()


def test_row_packing_matches_oracle_and_dense():
    """Packed table rows (two 15-bit limbs per int32, 128-byte rows —
    the gather-bandwidth A/B, ops/comb.use_row_packing) must be
    bit-exact against both the RFC 8032 oracle and the dense layout,
    including invalid rows; kernels and banks built after the switch
    capture the packed shapes."""
    from simple_pbft_tpu.ops import comb

    good = [_signed(40 + i, b"pack %d" % i) for i in range(5)]
    bad_sig = bytearray(good[1].sig)
    bad_sig[7] ^= 1
    items = good + [
        BatchItem(good[0].pubkey, b"wrong msg", good[0].sig),
        BatchItem(good[1].pubkey, good[1].msg, bytes(bad_sig)),
    ]
    oracle = [ref.verify(i.pubkey, i.msg, i.sig) for i in items]
    assert oracle == [True] * 5 + [False, False]
    dense = TpuVerifier(mode="fused", window=5).verify_batch(items)
    comb.use_row_packing(True)
    try:
        assert comb.ROW == comb.ROW_PACKED
        packed = TpuVerifier(mode="fused", window=5).verify_batch(items)
        # the unpack must also hold INSIDE the Pallas accumulate kernel
        # (interpret mode here; the on-chip A/B runs it under Mosaic) —
        # exercised directly at a small packed batch
        comb.use_accum_impl("pallas")
        try:
            pal = TpuVerifier(mode="fused", window=4).verify_batch(items)
        finally:
            comb.use_accum_impl("auto")
    finally:
        comb.use_row_packing(False)
    assert packed == dense == oracle
    assert pal == oracle


def test_shape_stability_hook_post_warm(monkeypatch):
    """Shape-stable coalescing (ISSUE 3): warm_for_population closes the
    jit-signature set — after warmup, NO dispatch may hit a fresh shape
    (post_warm_compiles stays 0 across every reachable batch size), and
    a verifier warmed short of a reachable bucket is caught by the hook."""
    from simple_pbft_tpu.crypto import tpu_verifier as tv

    monkeypatch.setattr(tv, "BUCKETS", (8, 32))
    pubs = [ref.public_key(bytes([40 + i]) * 32) for i in range(4)]
    items = [_signed(40 + (i % 4), b"shape probe %d" % i) for i in range(40)]

    v = tv.TpuVerifier(initial_keys=8)
    v.warm_for_population(pubs, max_sweep=32)
    snap = v.shape_snapshot()
    assert snap["warmed"] is True and snap["post_warm_compiles"] == 0
    base = v.shape_compiles
    for n in (1, 5, 8, 20, 32, 40):  # 40 chunks to 32+8: no new shape
        assert v.verify_batch(items[:n]) == [True] * n
    assert v.shape_compiles == base
    assert v.post_warm_compiles == 0
    hits = v.shape_snapshot()["bucket_hits"]
    assert set(hits) == {"8", "32"}

    # under-warmed verifier: the 32 bucket was never compiled pre-warm,
    # so the first big sweep is a mid-run compile — counted and visible
    v2 = tv.TpuVerifier(initial_keys=8)
    v2.warm_for_population(pubs, max_sweep=8)
    assert v2.post_warm_compiles == 0
    assert v2.verify_batch(items[:20]) == [True] * 20  # pads to 32
    assert v2.post_warm_compiles == 1
