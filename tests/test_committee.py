"""Integration tests: full committees on the in-process network.

Behavioral-parity checkpoint vs the reference's only demonstrated scenario
(SURVEY.md §3.2: 4 nodes, one client, request -> 3-phase commit -> reply),
then everything the reference could not do: concurrent requests, larger
committees, faulty replicas, duplicate/dropped messages.
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.transport.local import FaultPlan


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_four_node_single_request():
    """The reference's run.bat scenario: commit one request, reply to
    client — but signed, event-driven, and with f+1 reply matching."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            result = await com.clients[0].submit("put k hello")
            assert result == "ok"
            result = await com.clients[0].submit("get k")
            assert result == "hello"
        finally:
            await com.stop()
        # all replicas executed both blocks and agree on state
        digests = {r.app.state_digest() for r in com.replicas}
        assert len(digests) == 1
        assert all(r.executed_seq == 2 for r in com.replicas)

    run(scenario())


def test_concurrent_requests_pipeline():
    """Many in-flight requests (the reference serialized rounds via its
    scalar CurrentState; here seqs pipeline)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            results = await asyncio.gather(
                *(com.clients[0].submit(f"put k{i} v{i}") for i in range(20))
            )
            assert results == ["ok"] * 20
        finally:
            await com.stop()
        primary = com.replica("r0")
        assert primary.metrics["committed_requests"] == 20
        # batching: fewer blocks than requests (drain sweeps coalesce)
        assert primary.metrics["committed_blocks"] <= 20
        digests = {r.app.state_digest() for r in com.replicas}
        assert len(digests) == 1

    run(scenario())


def test_seven_node_committee():
    """n=7, f=2: quorums of 5."""

    async def scenario():
        com = LocalCommittee.build(n=7, clients=1)
        com.start()
        try:
            assert await com.clients[0].submit("put a 1") == "ok"
        finally:
            await com.stop()
        assert sum(r.executed_seq == 1 for r in com.replicas) == 7

    run(scenario())


def test_commits_with_f_crashed_backups():
    """f crashed backups must not block progress (quorum 2f+1 of n)."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        # crash r3 by never starting it
        for r in com.replicas:
            if r.id != "r3":
                r.start()
        for c in com.clients:
            c.start()
        try:
            assert await com.clients[0].submit("put a 1") == "ok"
        finally:
            await com.stop()

    run(scenario())


def test_progress_under_message_duplication():
    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=FaultPlan(duplicate_rate=0.5, seed=7)
        )
        com.start()
        try:
            for i in range(5):
                assert await com.clients[0].submit(f"put x{i} {i}") == "ok"
        finally:
            await com.stop()
        digests = {r.app.state_digest() for r in com.replicas}
        assert len(digests) == 1

    run(scenario())


def test_progress_under_light_message_loss():
    """Client retransmission + quorum redundancy ride out 5% drop."""

    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=FaultPlan(drop_rate=0.05, seed=3)
        )
        com.start()
        try:
            for i in range(5):
                # generous retries: a dropped-vote pattern can force a
                # multi-view failover (~7 s with 2 s view timers) and the
                # client must outlast it, not win a race with it
                assert (
                    await com.clients[0].submit(f"put y{i} {i}", retries=12)
                    == "ok"
                )
        finally:
            await com.stop()

    run(scenario())


def test_duplicate_request_reexecutes_nothing():
    """At-most-once execution: a retransmitted request must not re-apply."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            await com.clients[0].submit("put k 1")
            # forge a retransmission of the EXECUTED timestamp (clients
            # use wall-clock timestamps) straight to the primary
            from simple_pbft_tpu.messages import Request

            primary = com.replica("r0")
            for _ in range(100):  # submit returns on f+1; primary may lag
                if primary.recent_replies.get("c0"):
                    break
                await asyncio.sleep(0.02)
            (ts,) = primary.recent_replies["c0"].keys()
            req = Request(client_id="c0", timestamp=ts, operation="put k 1")
            com.clients[0].signer.sign_msg(req)
            await com.clients[0].transport.send("r0", req.to_wire())
            await asyncio.sleep(0.2)
        finally:
            await com.stop()
        primary = com.replica("r0")
        assert primary.metrics["committed_requests"] == 1

    run(scenario())


def test_unsigned_traffic_rejected():
    """Messages with missing/garbage signatures never reach consensus."""

    async def scenario():
        com = LocalCommittee.build(n=4, clients=1)
        com.start()
        try:
            from simple_pbft_tpu.messages import PrePrepare, Request

            # unsigned request straight at the primary
            req = Request(
                sender="c0", client_id="c0", timestamp=99, operation="put z 9"
            )
            ep = com.net.endpoint("intruder")
            await ep.send("r0", req.to_wire())
            # bogus pre-prepare from a non-member
            pp = PrePrepare(
                sender="intruder", view=0, seq=1, digest="d", block=[]
            )
            await ep.send("r1", pp.to_wire())
            await asyncio.sleep(0.2)
        finally:
            await com.stop()
        assert all(r.metrics["committed_requests"] == 0 for r in com.replicas)
        # unsigned request = no signature items collected -> precheck drop
        assert com.replica("r0").metrics["dropped_precheck"] >= 1
        assert com.replica("r1").metrics["dropped_precheck"] >= 1

    run(scenario())


def test_checkpoint_advances_watermark_and_gcs():
    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, checkpoint_interval=2, watermark_window=64
        )
        com.start()
        try:
            for i in range(6):
                await com.clients[0].submit(f"put c{i} {i}")
            await asyncio.sleep(0.3)  # let checkpoint gossip settle
        finally:
            await com.stop()
        for r in com.replicas:
            assert r.stable_seq >= 2, (r.id, r.stable_seq)
            # GC dropped instances at/below the watermark
            assert all(seq > r.stable_seq for (_, seq) in r.instances)

    run(scenario())


def test_client_keys_cannot_join_quorums():
    """A Byzantine primary signing votes as clients must not reach quorum
    (clients' keys are known committee-wide but carry no consensus role)."""

    async def scenario():
        from simple_pbft_tpu.crypto.signer import Signer
        from simple_pbft_tpu.messages import Commit, PrePrepare, Prepare

        com = LocalCommittee.build(n=4, clients=2)
        # only r0 (Byzantine primary) + r1 honest; r2/r3 "crashed"
        com.replica("r0").start()
        com.replica("r1").start()
        for c in com.clients:
            c.start()
        try:
            # r0 proposes an empty block legitimately, then forges
            # prepare/commit votes as c0 and c1 toward r1
            block = []
            pp = PrePrepare(
                view=0, seq=1, digest=PrePrepare.block_digest(block), block=block
            )
            r0 = com.replica("r0")
            r0.signer.sign_msg(pp)
            await r0.transport.send("r1", pp.to_wire())
            for fake in ["c0", "c1"]:
                signer = Signer(fake, com.keys[fake].seed)
                for cls in (Prepare, Commit):
                    vote = cls(view=0, seq=1, digest=pp.digest)
                    signer.sign_msg(vote)
                    await r0.transport.send("r1", vote.to_wire())
            await asyncio.sleep(0.3)
        finally:
            await com.stop()
        r1 = com.replica("r1")
        assert r1.metrics["committed_blocks"] == 0
        # client-keyed votes are a ROLE violation: rejected before any
        # signature items are collected (bad_sig stays a pure forged-
        # signature alarm)
        assert r1.metrics["dropped_precheck"] >= 4

    run(scenario())


def test_client_impersonation_rejected():
    """c1 signing a request that claims client_id=c0 must be dropped."""

    async def scenario():
        from simple_pbft_tpu.messages import Request

        com = LocalCommittee.build(n=4, clients=2)
        com.start()
        try:
            req = Request(client_id="c0", timestamp=5, operation="put k evil")
            com.clients[1].signer.sign_msg(req)  # signs as c1
            await com.clients[1].transport.send("r0", req.to_wire())
            await asyncio.sleep(0.2)
        finally:
            await com.stop()
        assert all(r.metrics["committed_requests"] == 0 for r in com.replicas)

    run(scenario())


def test_lagging_replica_state_transfer():
    """A replica partitioned through several checkpoints must catch up via
    verified snapshot transfer when the partition heals."""

    async def scenario():
        plan = FaultPlan()
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, checkpoint_interval=2
        )
        # partition r3 from everyone
        for other in ["r0", "r1", "r2", "c0"]:
            plan.cut("r3", other)
        com.start()
        try:
            for i in range(6):
                assert await com.clients[0].submit(f"put s{i} {i}") == "ok"
            r3 = com.replica("r3")
            assert r3.executed_seq == 0  # fully partitioned
            plan.heal()
            # next round of traffic brings checkpoint gossip + state sync
            for i in range(6, 10):
                assert await com.clients[0].submit(f"put s{i} {i}") == "ok"
            await asyncio.sleep(0.5)
        finally:
            await com.stop()
        r3 = com.replica("r3")
        assert r3.metrics["state_syncs"] >= 1
        assert r3.executed_seq >= 6
        # r3's data matches the quorum's
        assert r3.app.data == com.replica("r0").app.data

    run(scenario())


def test_committee_over_meshed_tpu_verifier():
    """Consensus traffic through the dp-SHARDED verifier: one TpuVerifier
    over an 8-device mesh (shard_map wire kernel, batch rows split
    across devices, tables replicated) shared by every replica — the
    multi-chip §2.2 data plane under a live committee, not a standalone
    batch call."""

    async def scenario():
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))
        shared = TpuVerifier(mesh=mesh, mode="fused", initial_keys=16)
        com = LocalCommittee.build(
            n=4,
            clients=1,
            verifier_factory=lambda: shared,
            # 8 virtual devices time-share ONE core here: a sharded
            # dispatch costs ~1 s, a 3-phase round tens of seconds —
            # timers sized for the hardware shape, like a tunneled chip
            view_timeout=180.0,
        )
        shared.warm(
            pubkeys=[kp.pub for kp in com.keys.values()], buckets=[8, 32]
        )
        baseline = shared.device_calls  # warm() already dispatched
        com.clients[0].request_timeout = 150.0
        com.start()
        try:
            assert await com.clients[0].submit("put m1 1") == "ok"
            assert await com.clients[0].submit("get m1") == "1"
            # consensus traffic itself must hit the mesh, beyond warmup
            assert shared.device_calls > baseline
        finally:
            await com.stop()

    run(scenario(), timeout=360)


def test_committee_over_tpu_verifier():
    """The full replica<->device seam under real traffic: every replica
    runs the TpuVerifier (fused comb engine, CPU-jax here, same code path
    as TPU) while clients drive concurrent requests, including one forged
    vote injected mid-stream. VERDICT round-1 weak #5."""

    async def scenario():
        from simple_pbft_tpu.crypto.ed25519_cpu import public_key, sign
        from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier
        from simple_pbft_tpu.crypto.verifier import BatchItem

        # Pre-warm the shared jit cache for the bucket sizes this traffic
        # hits (8 and 32): first-compile is ~40-60 s on a small CPU host,
        # far beyond a client's retry patience, and belongs to no replica.
        warm_seed = b"\xaa" * 32
        warm = [
            BatchItem(public_key(warm_seed), b"warm %d" % i, sign(warm_seed, b"warm %d" % i))
            for i in range(9)
        ]
        warmer = TpuVerifier()
        await asyncio.to_thread(warmer.verify_batch, warm[:1])  # bucket 8
        await asyncio.to_thread(warmer.verify_batch, warm)  # bucket 32

        # CPU-jax device calls are ~100-150 ms each (vs ~2 ms on the real
        # chip), so a 3-phase round takes seconds here: give the client and
        # the failover timers TPU-test-scale patience.
        com = LocalCommittee.build(
            n=4,
            clients=1,
            verifier_factory=lambda: TpuVerifier(),
            view_timeout=60.0,
        )
        com.clients[0].request_timeout = 30.0
        com.start()
        try:
            results = await asyncio.gather(
                *(com.clients[0].submit(f"put t{i} {i}") for i in range(8))
            )
            assert results == ["ok"] * 8
            # forged commit vote: signed with r2's key but claiming r1
            from simple_pbft_tpu.crypto.signer import Signer
            from simple_pbft_tpu.messages import Commit

            r0 = com.replica("r0")
            # target a not-yet-quorate slot: votes for already-committed
            # seqs are dropped pre-verification as redundant (and thus
            # never reach the forged-signature alarm)
            forged = Commit(view=0, seq=200, digest="f" * 64)
            Signer("r1", com.keys["r2"].seed).sign_msg(forged)
            forged.sender = "r1"
            await com.net.endpoint("r2").send("r0", forged.to_wire())
            for _ in range(100):  # poll: the verify may still be in flight
                if r0.metrics["bad_sig"] >= 1:
                    break
                await asyncio.sleep(0.1)
            assert r0.metrics["bad_sig"] >= 1
            assert await com.clients[0].submit("get t3") == "3"
            await asyncio.sleep(0.5)  # let laggards finish the last block
        finally:
            await com.stop()
        for r in com.replicas:
            # concurrent submits batch into few blocks; count requests
            assert r.metrics["committed_requests"] >= 9
            assert r.metrics["sweep_errors"] == 0

    run(scenario(), timeout=240)
