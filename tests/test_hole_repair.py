"""Steady-state hole repair and failover deferral (round-4 fixes for the
qc-n64 chaos near-stall, VERDICT round-3 weak #3 / next-round #6).

Execution is sequential per replica, so one lost frame (a commit QC, a
pre-prepare, a NEW-VIEW) left a replica stalled forever while the
committee progressed; its unilateral view change was never joined,
freezing it into a deaf zombie. These tests pin the repair machinery:

1. A fully-partitioned replica catches up after healing via slot probes
   (blocks adopted against commit QCs) WITHOUT any view change.
2. The failover timer defers while the committee demonstrably commits
   (max_committed_seen advances) and the stall is local.
3. A replica that misses the NEW-VIEW broadcast re-fetches it from a
   peer (NewViewFetch) and rejoins the new view.
4. A dead primary with no committee progress still fails over (the
   deferral must not break classic liveness).
"""

import asyncio

import pytest

from simple_pbft_tpu.committee import LocalCommittee
from simple_pbft_tpu.sim import sim_run
from simple_pbft_tpu.transport.local import FaultPlan


def run(coro, timeout=120):
    # Virtual clock (ISSUE 13 satellite): these tests are TIMER-SHAPED —
    # deferral windows, probe cadences, failover ladders — and were the
    # suite's flake source under full-suite CPU saturation (view
    # timeouts repeatedly lengthened: 0.6 -> 1.5 -> 2.5 s, see the
    # in-test comments' history). Under the simulation runtime the
    # timers are VIRTUAL: a saturated host cannot stall the loop past a
    # deadline because deadlines only advance when the loop is idle —
    # and the sleeps compress, so the tests are faster too. ``timeout``
    # is now a virtual bound (generous; it no longer needs host slack).
    return sim_run(asyncio.wait_for(coro, timeout))


def _cut_all(plan: FaultPlan, com: LocalCommittee, rid: str) -> None:
    """Symmetric partition of one replica from every other endpoint."""
    for other in list(com.cfg.replica_ids) + [c.id for c in com.clients]:
        if other != rid:
            plan.partitions.add((other, rid))
            plan.partitions.add((rid, other))


async def _pump_n(client, n, prefix="x"):
    for i in range(n):
        await client.submit(f"put {prefix}{i} v{i}")


def test_partitioned_replica_catches_up_without_view_change():
    """QC mode: cut r3 off mid-load; after healing, slot probes must
    repair its holes (commit QCs + adopted blocks) with zero view
    changes committee-wide."""

    async def scenario():
        plan = FaultPlan(seed=7)
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, qc_mode=True,
            # 2.5 s: the assertion is BEHAVIORAL (repair happens in-view,
            # zero failovers) — at 1.0 s a saturated full-suite host can
            # stall the event loop past the timer and fail it spuriously
            view_timeout=2.5, checkpoint_interval=512,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        await _pump_n(c, 3, "pre")
        victim = com.replica("r3")
        base_exec = victim.executed_seq
        _cut_all(plan, com, "r3")
        await _pump_n(c, 6, "cut")
        assert victim.executed_seq == base_exec  # truly isolated
        plan.heal()
        # post-heal traffic gives the victim the signal something is
        # missing (new pre-prepares/QCs beyond its frontier arm the
        # probe chain); a totally quiet committee has nothing to repair
        # toward until the next checkpoint broadcast
        await _pump_n(c, 2, "post")
        # probes fire at view_timeout/2 (jittered); give generous rounds —
        # under batch-run CPU contention a round trip can take seconds
        deadline = asyncio.get_event_loop().time() + 45.0
        target = max(r.executed_seq for r in com.replicas)
        while (
            victim.executed_seq < target
            and asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.25)
        assert victim.executed_seq == target, (
            victim.executed_seq, target, victim.metrics)
        # repair happened in-view: no failover anywhere
        assert all(r.view == 0 for r in com.replicas)
        assert sum(r.metrics.get("views_installed", 0) for r in com.replicas) == 0
        assert victim.metrics.get("slot_probes_sent", 0) > 0
        await com.stop()

    run(scenario())


def test_failover_defers_while_committee_commits():
    """The victim's timer must defer (metrics: failover_deferred) rather
    than start a view change while observed commits advance."""

    async def scenario():
        plan = FaultPlan(seed=11)
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, qc_mode=True,
            # 1.5 s: like the catch-up test above, the assertion is
            # BEHAVIORAL (no failover while commits advance) — at 0.6 s a
            # saturated full-suite host stalls the loop past the timer
            # with no observable progress and fires it spuriously
            view_timeout=1.5, checkpoint_interval=512,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        await _pump_n(c, 2, "pre")
        victim = com.replica("r3")
        _cut_all(plan, com, "r3")
        await _pump_n(c, 4, "cut")
        plan.heal()
        # park client work on the victim so its timer arms: relay a
        # request through it by healing first (normal traffic resumes)
        await _pump_n(c, 8, "post")
        # long enough for an (incorrectly) undeferred timer to fire
        await asyncio.sleep(2.5)
        assert sum(
            r.metrics.get("view_changes_started", 0) for r in com.replicas
        ) == 0
        await com.stop()

    run(scenario())


def test_newview_refetch_after_missed_broadcast():
    """Crash the primary of view 0 and cut ONLY the new primary's link
    TO r3 (one-directional): r3's VIEW-CHANGE still reaches r1, the
    failover completes, but r3 never receives the NEW-VIEW broadcast.
    Seeing view-1 traffic from r2, r3 must fetch the certificate from
    the rotating peer (NewViewFetch) and install view 1."""

    async def scenario():
        plan = FaultPlan(seed=13)
        com = LocalCommittee.build(
            n=4, clients=1, fault_plan=plan, qc_mode=False,
            view_timeout=0.8, checkpoint_interval=512,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        c.hedge = 2
        await _pump_n(c, 2, "pre")
        victim = com.replica("r3")
        plan.partitions.add(("r1", "r3"))  # new primary -> victim only
        com.replica("r0").kill()
        # keep load flowing so view-1 traffic exists for the hint
        pump = asyncio.get_event_loop().create_task(_pump_n(c, 30, "post"))
        deadline = asyncio.get_event_loop().time() + 25.0
        while (
            victim.view < 1 and asyncio.get_event_loop().time() < deadline
        ):
            await asyncio.sleep(0.2)
        pump.cancel()
        try:
            await pump
        except (asyncio.CancelledError, asyncio.TimeoutError, TimeoutError):
            pass
        assert victim.view >= 1, (victim.view, victim.metrics)
        assert victim.metrics.get("newview_fetches_sent", 0) > 0
        assert any(
            r.metrics.get("newview_fetches_served", 0) > 0
            for r in com.replicas
        )
        await com.stop()

    run(scenario())


def test_dead_primary_still_fails_over():
    """No committee progress + outstanding work => the classic view
    change fires despite the deferral logic."""

    async def scenario():
        com = LocalCommittee.build(
            n=4, clients=1, qc_mode=False,
            view_timeout=0.6, checkpoint_interval=512,
        )
        com.start()
        c = com.clients[0]
        c.request_timeout = 2.0
        c.hedge = 2
        await _pump_n(c, 2, "pre")
        com.replica("r0").kill()
        # next request must commit under the successor primary
        await asyncio.wait_for(c.submit("put after crash"), 20.0)
        assert all(
            r.view >= 1 for r in com.replicas if r._running
        ), [r.view for r in com.replicas]
        await com.stop()

    run(scenario())
