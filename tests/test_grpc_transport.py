"""gRPC transport: the DCN path (SURVEY.md §2.3, VERDICT missing #5).

Mirrors the TCP transport's contract tests (tests/test_deployment.py):
interchangeable behavior is the whole point — the replica runtime must
not be able to tell the deployments apart. Plus one real 4-process
launch over localhost gRPC.
"""

import asyncio
import os
import subprocess
import sys

import pytest

grpc = pytest.importorskip("grpc")

from simple_pbft_tpu.transport.grpc import GrpcTransport  # noqa: E402
from simple_pbft_tpu.transport.tcp import MAX_FRAME, OUTBOX_DEPTH  # noqa: E402


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _pair():
    """Two connected endpoints on ephemeral localhost ports."""
    a = GrpcTransport("a", ("127.0.0.1", 0), peers={})
    b = GrpcTransport("b", ("127.0.0.1", 0), peers={})
    await a.start()
    await b.start()
    a.peers["b"] = ("127.0.0.1", b.bound_port)
    b.peers["a"] = ("127.0.0.1", a.bound_port)
    return a, b


async def _stop_all(*ts):
    for t in ts:
        await t.stop()


class TestGrpcTransport:
    def test_roundtrip_and_self_send(self):
        async def scenario():
            a, b = await _pair()
            try:
                payloads = [b"x", b"y" * 1000, b"z" * 100_000]
                for p in payloads:
                    await a.send("b", p)
                got = [await asyncio.wait_for(b.recv(), 20) for _ in payloads]
                assert got == payloads
                # streams are per-direction: b can answer over its own
                await b.send("a", b"reply")
                assert await asyncio.wait_for(a.recv(), 20) == b"reply"
                # self-send loops back without touching the network
                await a.send("a", b"self")
                assert a.recv_nowait() == b"self"
                # unknown destination: fire-and-forget no-op
                await a.send("nobody", b"lost")
            finally:
                await _stop_all(a, b)

        run(scenario())

    def test_oversized_frame_dropped_at_send(self):
        async def scenario():
            a, b = await _pair()
            try:
                await a.send("b", b"x" * (MAX_FRAME + 1))
                assert a.metrics["dropped_outbox"] == 1
                # transport stays usable
                await a.send("b", b"fits")
                assert await asyncio.wait_for(b.recv(), 20) == b"fits"
            finally:
                await _stop_all(a, b)

        run(scenario())

    def test_reconnect_after_peer_restart(self):
        async def scenario():
            a, b = await _pair()
            b_port = b.bound_port
            try:
                await a.send("b", b"one")
                assert await asyncio.wait_for(b.recv(), 20) == b"one"
                # peer goes down; frames sent meanwhile are fire-and-forget
                await b.stop()
                await a.send("b", b"into the void")
                await asyncio.sleep(0.2)
                # peer comes back on the SAME port; gRPC reconnects the
                # channel and the sender loop reopens the stream
                b2 = GrpcTransport("b", ("127.0.0.1", b_port), peers={})
                await b2.start()
                for attempt in range(100):
                    await a.send("b", b"hello again %d" % attempt)
                    got = b2.recv_nowait()
                    if got is not None:
                        break
                    await asyncio.sleep(0.1)
                else:
                    raise AssertionError(
                        f"no frame after restart (reconnects="
                        f"{a.metrics['reconnects']})"
                    )
                await b2.stop()
            finally:
                await a.stop()

        run(scenario())

    def test_reconnect_under_churn_no_corruption_bounded_backoff(self):
        """ISSUE 7 satellite: kill and restart a peer MID-STREAM while
        the sender keeps writing. Every frame that arrives — before,
        during, or after the churn — must be byte-identical to one that
        was sent (a write torn by the kill must vanish, never surface
        corrupt), and the sender must recover within the BOUNDED backoff
        ladder (cap 2 s), not a compounding one."""

        async def scenario():
            frames = [
                b"frame-%06d|" % i + bytes([65 + i % 26]) * (i % 500)
                for i in range(400)
            ]
            sent_set = set(frames)
            it = iter(frames)
            a, b = await _pair()
            b_port = b.bound_port
            got = []
            b2 = None
            try:
                # phase 1: a healthy stream
                for _ in range(100):
                    await a.send("b", next(it))
                while True:
                    try:
                        got.append(await asyncio.wait_for(b.recv(), 0.5))
                    except asyncio.TimeoutError:
                        break
                assert len(got) >= 90
                # kill the peer MID-STREAM and keep writing into the blip
                await b.stop()
                for _ in range(100):
                    await a.send("b", next(it))
                    await asyncio.sleep(0.002)
                # restart on the SAME port: the stream must reopen within
                # the bounded ladder and deliver intact frames
                b2 = GrpcTransport("b", ("127.0.0.1", b_port), peers={})
                await b2.start()
                t0 = asyncio.get_running_loop().time()
                recovered = False
                for _ in range(200):
                    await a.send("b", next(it))
                    raw = b2.recv_nowait()
                    if raw is not None:
                        got.append(raw)
                        recovered = True
                        break
                    await asyncio.sleep(0.05)
                assert recovered, (
                    f"stream never recovered (reconnects="
                    f"{a.metrics['reconnects']})"
                )
                # bounded backoff: 2 s cap + stream-reopen slack, never
                # the compounding worst case
                assert asyncio.get_running_loop().time() - t0 < 8.0
                assert a.metrics["reconnects"] >= 1
                while True:
                    raw = b2.recv_nowait()
                    if raw is None:
                        break
                    got.append(raw)
                # NO frame corruption across the churn: every received
                # frame is exactly one that was sent
                assert got
                assert all(g in sent_set for g in got), [
                    g[:40] for g in got if g not in sent_set
                ]
            finally:
                if b2 is not None:
                    await b2.stop()
                await a.stop()

        run(scenario(), timeout=90)

    def test_outbox_overflow_drops_not_blocks(self):
        async def scenario():
            # a peer that is never up: wait_for_ready parks the stream, the
            # outbox fills, further sends drop without blocking the loop
            a = GrpcTransport(
                "a", ("127.0.0.1", 0), peers={"ghost": ("127.0.0.1", 1)}
            )
            await a.start()
            try:
                for i in range(OUTBOX_DEPTH + 100):
                    await a.send("ghost", b"frame %d" % i)
                assert a.metrics["dropped_outbox"] >= 90
            finally:
                await a.stop()

        run(scenario())

    def test_recv_queue_bound_drops(self):
        async def scenario():
            a = GrpcTransport("a", ("127.0.0.1", 0), peers={})
            b = GrpcTransport("b", ("127.0.0.1", 0), peers={}, recv_depth=2)
            await a.start()
            await b.start()
            a.peers["b"] = ("127.0.0.1", b.bound_port)
            try:
                for i in range(10):
                    await a.send("b", b"m%d" % i)
                for _ in range(200):
                    if b.metrics["recv"] + b.metrics["dropped_recv"] >= 10:
                        break
                    await asyncio.sleep(0.05)
                assert b.metrics["dropped_recv"] >= 8, dict(b.metrics)
            finally:
                await _stop_all(a, b)

        run(scenario())


class TestGrpcLaunchIntegration:
    def test_four_node_launch_commits_load_over_grpc(self, tmp_path):
        """4 replica processes + 1 client over localhost gRPC streams."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # children must never touch the chip
        base_port = 8400 + (os.getpid() % 500)  # dodge stale-orphan ports
        out = subprocess.run(
            [
                sys.executable, "-m", "simple_pbft_tpu.launch",
                "-n", "4", "--load", "8",
                "--transport", "grpc",
                "--base-port", str(base_port),
                "--deploy-dir", str(tmp_path),
                "--keep",
            ],
            capture_output=True,
            text=True,
            timeout=240,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, (out.stdout[-800:], out.stderr[-800:])
        assert '"ops": 8' in out.stdout, out.stdout[-800:]
