"""Chip-daemon protocol tests — offline (no device, no subprocesses).

The daemon (tools/chip_daemon.py) is how the driver's bench.py gets a
live chip number without ever attaching to the single-tenant tunnel
itself (VERDICT r4 next #3). These tests pin the socket protocol, the
busy/priority semantics around the device lock, and bench.py's
daemon-first client path, with the worker mocked out.
"""

import json
import os
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench
import chip_daemon


class FakeWorker:
    def __init__(self, value=777_000.0):
        self.value = value
        self.info = {"platform": "axon", "window": 5, "batch": 8192}

    def alive(self):
        return True

    def request(self, obj, timeout):
        if obj["cmd"] == "ping":
            return {"ok": True}
        if obj["cmd"] == "measure":
            return {
                "ok": True,
                "value": self.value,
                "batch": 8192,
                "window": 5,
                "mode": "fused",
                "platform": "axon",
                "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            }
        return {"ok": False}


@pytest.fixture()
def daemon(monkeypatch, tmp_path):
    monkeypatch.setattr(chip_daemon, "OUT", str(tmp_path / "chip_test.jsonl"))
    d = chip_daemon.Daemon()

    def fake_ensure():
        d.worker = FakeWorker()
        return {"ok": True}

    monkeypatch.setattr(d, "_ensure_worker", fake_ensure)
    t = threading.Thread(target=d.serve, args=(0,), daemon=True)
    t.start()
    for _ in range(200):
        if hasattr(d, "port"):
            break
        time.sleep(0.01)
    return d


def _ask(port, req, timeout=10.0):
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as s:
        s.settimeout(timeout)
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode())


def test_status_and_live_measure(daemon):
    st = _ask(daemon.port, {"cmd": "status"})
    assert st["ok"] and st["round"] == chip_daemon.ROUND
    rec = _ask(daemon.port, {"cmd": "measure", "min_s": 0.1})
    assert rec["ok"] and rec["value"] == 777_000.0
    assert rec["live"] is True and rec["platform"] == "axon"
    # the measurement was ledgered for the prior-evidence fallback
    with open(chip_daemon.OUT) as f:
        lines = [json.loads(s) for s in f if s.strip()]
    assert lines and lines[-1]["exp"] == "daemon_measure" and lines[-1]["ok"]
    # and status now carries it
    st = _ask(daemon.port, {"cmd": "status"})
    assert st["last_measure"]["value"] == 777_000.0


def test_measure_while_experiment_holds_device_reports_busy(daemon):
    daemon.current_exp = "verify_w6"
    assert daemon.device_lock.acquire(timeout=1)
    try:
        rec = _ask(daemon.port, {"cmd": "measure", "wait_s": 0.2})
        assert rec["busy"] and rec["current_exp"] == "verify_w6"
    finally:
        daemon.device_lock.release()
    # device freed: measurement goes through
    rec = _ask(daemon.port, {"cmd": "measure", "wait_s": 5})
    assert rec["ok"] and rec["value"] > 0


def test_bench_daemon_first_path(daemon, monkeypatch, capsys):
    """bench.py's orchestrator takes the daemon's live number and emits
    the driver JSON line without ever probing the tunnel."""
    monkeypatch.setattr(bench, "DAEMON_PORT", daemon.port)
    monkeypatch.setattr(
        bench, "_probe", lambda *a, **k: pytest.fail("must not probe")
    )
    rec = bench._try_daemon(deadline=time.time() + 300)
    assert rec is not None
    assert rec["value"] == 777_000.0 and rec["source"] == "chip_daemon"


def test_bench_falls_back_when_no_daemon(monkeypatch):
    monkeypatch.setattr(bench, "DAEMON_PORT", 1)  # nothing listens there
    assert bench._try_daemon(deadline=time.time() + 300) is None


def test_bench_rejects_cpu_platform_daemon(daemon, monkeypatch):
    """A daemon whose worker attached to a CPU-only backend must not be
    reported as a chip measurement."""
    monkeypatch.setattr(bench, "DAEMON_PORT", daemon.port)

    def cpu_ensure():
        w = FakeWorker()

        def req(obj, timeout):
            r = FakeWorker.request(w, obj, timeout)
            if "platform" in r:
                r["platform"] = "cpu"
            return r

        w.request = req
        w.info = {"platform": "cpu"}
        daemon.worker = w
        return {"ok": True}

    daemon._ensure_worker = cpu_ensure
    rec = bench._try_daemon(deadline=time.time() + 130)
    assert rec is None


def test_queue_next_experiment_order(tmp_path, monkeypatch):
    """The round-5 queue leads with the thesis experiment (n=16
    consensus on chip), then the w6 A/B; attempts are bounded."""
    monkeypatch.setattr(chip_daemon, "OUT", str(tmp_path / "q.jsonl"))
    # isolate from the repo's live operator override file — this test
    # pins the STATIC queue order
    monkeypatch.setattr(
        chip_daemon, "QUEUE_OVERRIDE", str(tmp_path / "no_override.json")
    )
    results = []
    exp = chip_daemon.next_experiment(results)
    assert exp["exp"] == "consensus_n16"
    results.append({"exp": "consensus_n16", "ok": True, "rec": {"value": 1.0}})
    assert chip_daemon.next_experiment(results)["exp"] == "verify_w6"
    # failed attempts retry up to MAX_ATTEMPTS, then fall through
    for _ in range(chip_daemon.MAX_ATTEMPTS):
        results.append({"exp": "verify_w6", "ok": False})
    assert chip_daemon.next_experiment(results)["exp"] == "verify_w5"


def test_queue_override_file(tmp_path, monkeypatch):
    """Operator-queued experiments (chip_queue_<round>.json) run before
    the static queue, in file order, with attempt bounds; malformed
    specs are skipped without killing the queue; JSON-number env values
    are coerced to strings for subprocess.run."""
    ovr = tmp_path / "override.json"
    monkeypatch.setattr(chip_daemon, "QUEUE_OVERRIDE", str(ovr))
    monkeypatch.setattr(chip_daemon, "OUT", str(tmp_path / "q.jsonl"))
    ovr.write_text(json.dumps([
        {"exp": "ab_one", "kind": "bench",
         "env": {"BENCH_WINDOW": 5, "BENCH_BATCH": 16384}, "timeout": 60},
        {"exp": "bad_spec", "kind": "consensus", "args": "--configs 2"},
        {"exp": "ab_two", "kind": "consensus",
         "args": ["--configs", "2", "--seconds", 20]},
    ]))
    results = []
    exp = chip_daemon.next_experiment(results)
    assert exp["exp"] == "ab_one"
    # env coercion: every value a string (subprocess.run requirement)
    assert exp["env"]["BENCH_WINDOW"] == "5"
    assert exp["env"]["BENCH_BATCH"] == "16384"
    results.append({"exp": "ab_one", "ok": True, "rec": {"value": 1.0}})
    # the malformed string-args spec is skipped, not exploded char-wise
    exp = chip_daemon.next_experiment(results)
    assert exp["exp"] == "ab_two"
    assert exp["cmd"][-2:] == ["--seconds", "20"]
    # attempt bound applies to override experiments too
    for _ in range(chip_daemon.MAX_ATTEMPTS):
        results.append({"exp": "ab_two", "ok": False})
    assert chip_daemon.next_experiment(results)["exp"] == "consensus_n16"
    # a corrupt file is ignored, falling through to the static queue
    ovr.write_text("{not json")
    assert chip_daemon.next_experiment([])["exp"] == "consensus_n16"
