"""Device-plane observatory (ISSUE 14): per-dispatch ledger schema and
aggregates, the zero-overhead-when-disabled A/B, the BLS and shard
lanes sharing the schema, the static cost model's r05 anchor points,
verify_observatory's decomposition/reconciliation/limiter logic, the
pbft_top DEV cell, and the dead-target view-change evidence rule."""

from __future__ import annotations

import importlib.util
import json
import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from simple_pbft_tpu import clock, devledger
from simple_pbft_tpu.crypto import costmodel
from simple_pbft_tpu.crypto import ed25519_cpu as ref
from simple_pbft_tpu.crypto.coalesce import VerifyService
from simple_pbft_tpu.crypto.tpu_verifier import TpuVerifier
from simple_pbft_tpu.crypto.verifier import BatchItem
from simple_pbft_tpu.devledger import DeviceLedger

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


observatory = _load_tool("verify_observatory")
pbft_top = _load_tool("pbft_top")


@pytest.fixture()
def fresh_ledger():
    devledger.configure("t")
    yield devledger.ledger()
    devledger.configure("")


@pytest.fixture(scope="module")
def signed_items():
    sk = b"\x07" * 32
    pub = ref.public_key(sk)
    return pub, [
        BatchItem(pubkey=pub, msg=b"dl%d" % i, sig=ref.sign(sk, b"dl%d" % i))
        for i in range(8)
    ]


@pytest.fixture(scope="module")
def warm_verifier(signed_items):
    pub, _ = signed_items
    v = TpuVerifier(initial_keys=4)
    v.warm(pubkeys=[pub], buckets=[8])
    v._warm_done = True
    return v


# ---------------------------------------------------------------------------
# the ledger itself
# ---------------------------------------------------------------------------


def test_dispatch_event_schema(fresh_ledger, warm_verifier, signed_items):
    """A real jit dispatch records the full per-dispatch tuple: shape,
    pad waste, host prep, RTT, compile-vs-cache, bytes both ways."""
    _, items = signed_items
    assert warm_verifier.verify_batch(items[:5]) == [True] * 5
    evs = devledger.recent()
    assert len(evs) == 1
    ev = evs[0]
    assert ev["lane"] == "ed25519"
    assert ev["mode"] == "fused" and ev["window"] == 4
    assert ev["bucket"] == 8 and ev["n"] == 5 and ev["pad"] == 3
    assert ev["rtt_s"] > 0 and ev["host_prep_s"] > 0
    assert ev["compile"] is False  # warmed shape: cached
    assert ev["bytes_up"] > 0 and ev["bytes_down"] == 8
    snap = devledger.snapshot()
    assert snap["dispatches"] == 1 and snap["items"] == 5
    assert snap["pad_waste_pct"] == pytest.approx(100 * 3 / 8, abs=0.1)
    # lane-qualified shape key: an ed25519 and a shard lane sharing a
    # (mode, window, bucket) must never overwrite each other
    assert "ed25519:fused/w4/b8" in snap["shapes"]
    assert 0 < snap["occupancy"] <= 1.0


def test_service_route_records_queue_wait(fresh_ledger, warm_verifier,
                                          signed_items):
    """Through the coalescing service the dispatch events carry the
    admission-queue wait and submission count (the thread-local
    annotation seam), and the service snapshot exposes the aggregate
    ``device`` block."""
    _, items = signed_items
    svc = VerifyService(warm_verifier, cpu_cutoff=0, max_batch=8)
    f1 = svc.submit(items[:3])
    f2 = svc.submit(items[3:6])
    assert f1.result(30) == [True] * 3
    assert f2.result(30) == [True] * 3
    snap = svc.snapshot()
    svc.close()
    dev = snap["device"]
    lane = dev["lanes"]["ed25519"]
    assert lane["items"] == 6
    assert 1 <= lane["dispatches"] <= 2
    assert lane["submissions"] == 2
    assert lane["queue_wait_s"] >= 0.0
    assert lane["busy_s"] > 0
    # the top-level mirror pbft_top / bench_gate floors read
    assert dev["dispatches"] == lane["dispatches"]
    assert dev["verifies_per_s_effective"] > 0


def test_disabled_ledger_is_free_ab(signed_items):
    """The acceptance A/B: a disabled ledger records NOTHING and its
    per-call cost is one attribute read — orders of magnitude under the
    enabled path, and far under any measurable per-dispatch budget."""
    led = DeviceLedger()
    n = 20000
    led.configure("ab", enabled=False)
    t0 = time.perf_counter()
    for _ in range(n):
        led.record("ed25519", "fused", 4, 8, 5, rtt_s=0.001)
    dt_off = time.perf_counter() - t0
    assert led.recorded == 0 and not led._ring  # structurally inert
    assert led.snapshot()["dispatches"] == 0
    led.configure("ab", enabled=True)
    t0 = time.perf_counter()
    for _ in range(n):
        led.record("ed25519", "fused", 4, 8, 5, rtt_s=0.001)
    dt_on = time.perf_counter() - t0
    assert led.recorded == n
    assert dt_off < dt_on  # disabled strictly cheaper than enabled
    assert dt_off / n < 5e-6  # one attribute read, generous CI margin


def test_record_never_raises(fresh_ledger):
    """PBL004 discipline: hostile/malformed fields drop the event (and
    count it dropped), never raise into the verify pipeline."""
    devledger.record("x", "fused", "not-an-int", None, "nope")
    assert devledger.ledger().dropped == 1
    assert devledger.snapshot()["dispatches"] == 0


def test_annotation_is_consumed_once(fresh_ledger):
    devledger.annotate(0.25, 3)
    devledger.record("ed25519", "fused", 4, 8, 8)
    ev = devledger.recent()[-1]
    assert ev["queue_wait_s"] == pytest.approx(0.25)
    devledger.record("ed25519", "fused", 4, 8, 8)
    assert devledger.recent()[-1]["queue_wait_s"] == 0.0  # not sticky
    lane = devledger.snapshot()["lanes"]["ed25519"]
    assert lane["submissions"] == 3 + 1


def test_bls_lane_shares_schema(fresh_ledger):
    """One RLC pairing batch in the QC lane = one ledger event on the
    ``bls`` lane, same schema as the jit dispatches."""
    from simple_pbft_tpu.consensus import qc as qc_mod
    from simple_pbft_tpu.crypto import bls

    keys = [bls.keygen(bytes([i + 31]) * 32) for i in range(4)]
    cfg = SimpleNamespace(
        quorum=3,
        replica_ids=tuple(f"r{i}" for i in range(4)),
        bls={f"r{i}": pk for i, (_, pk) in enumerate(keys)},
    )
    cfg.bls_pubkey = cfg.bls.get
    shares = {
        f"r{i}": qc_mod.sign_share(sk, "prepare", 0, 7, "d" * 64)
        for i, (sk, _) in enumerate(keys[:3])
    }
    cert = qc_mod.build_qc("prepare", 0, 7, "d" * 64, shares, cfg.quorum)
    lane = qc_mod.QcVerifyLane()
    lane._started = True  # drive the worker by hand: deterministic
    fut = lane.submit(cfg, cert)
    with lane._cond:
        take = lane._take_locked()
    lane._run_batch(take)
    assert fut.result(5) is True
    evs = [e for e in devledger.recent() if e["lane"] == "bls"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["mode"] == "pairing" and ev["bucket"] == 1 and ev["n"] == 1
    assert ev["rtt_s"] > 0 and ev["bytes_up"] > 0
    assert devledger.snapshot()["lanes"]["bls"]["dispatches"] == 1


def test_shard_lane_per_device_events(fresh_ledger):
    """instrument_step fans one SPMD pass into per-device events (the
    8-mesh shard-out's schema, exercised without a mesh compile)."""
    from simple_pbft_tpu.parallel.sharded_verify import instrument_step

    mesh = SimpleNamespace(devices=np.zeros(2))  # 2-"device" stand-in
    calls = []

    def step(*args):
        calls.append(args)
        return np.ones(8, dtype=bool)

    run = instrument_step(step, mesh, mode="comb", window=4)
    out = run(np.zeros((17, 8), np.int32), np.zeros(8, np.int32),
              n_valid=6)
    assert out.shape == (8,) and len(calls) == 1
    evs = [e for e in devledger.recent() if e["lane"] == "shard"]
    assert len(evs) == 2
    assert {e["device"] for e in evs} == {"d0", "d1"}
    assert all(e["bucket"] == 4 for e in evs)
    assert sum(e["n"] for e in evs) == 6  # pre-pad items split across
    lane = devledger.snapshot()["lanes"]["shard"]
    assert lane["devices"] == 2 and lane["dispatches"] == 2
    # one SPMD trace = ONE compile, stamped on one device row only
    assert sum(1 for e in evs if e["compile"]) == 1
    assert lane["compiles"] == 1
    # occupancy normalizes by device count: one pass != 2x busy window
    assert lane["occupancy"] <= 1.0


# ---------------------------------------------------------------------------
# cost model (the r05 anchors)
# ---------------------------------------------------------------------------


def test_costmodel_r05_anchor_points():
    # fused w=5: 52 joint-window gathers x 256 B dense rows — the
    # 13,312 B/item stream the r05 memo priced the 8192-pass at
    c5 = costmodel.shape_cost("fused", 5, 8192)
    assert c5["gathers_per_item"] == 52
    assert c5["gather_bytes_per_item"] == 13312
    assert c5["gather_bytes_per_pass"] == 13312 * 8192
    assert c5["madds_per_item"] == 52
    # w=6 cuts madds 52 -> 43 (the A/B that pinned bandwidth-bound)
    assert costmodel.shape_cost("fused", 6, 8192)["madds_per_item"] == 43
    # split comb gathers two rows per position; ladder gathers nothing
    assert costmodel.shape_cost("comb", 4, 8)["gathers_per_item"] == 128
    assert costmodel.shape_cost("ladder", 4, 8)["gather_bytes_per_item"] == 0
    # wire staging ships ~101 B/item on the fused path
    assert c5["wire_bytes_per_item"] == 101
    # unknown lane modes sum as zero instead of raising
    assert costmodel.shape_cost("pairing", 0, 4)["gather_bytes_per_item"] == 0


def test_costmodel_shapes_rollup():
    shapes = {
        "ed25519:fused/w4/b8": {"dispatches": 2, "items": 10,
                                "pad_items": 6},
        "bls:pairing/w0/b4": {"dispatches": 1, "items": 4, "pad_items": 0},
        "garbage-key": {"dispatches": 9},
    }
    per_item = costmodel.shape_cost("fused", 4, 8)["gather_bytes_per_item"]
    assert costmodel.gather_bytes_for_shapes(shapes) == per_item * 8 * 2
    # both the lane-qualified and bare spellings parse
    assert costmodel.parse_shape_key("ed25519:fused/w4/b8")["lane"] == \
        "ed25519"
    assert costmodel.parse_shape_key("fused/w4/b8")["mode"] == "fused"
    assert costmodel.parse_shape_key("nonsense") is None


# ---------------------------------------------------------------------------
# observatory analysis
# ---------------------------------------------------------------------------


def _dev_block(busy=1.0, prep=0.01, queue=0.005, occ=0.9, disp=10):
    return {
        "window_s": 2.0,
        "dispatches": disp,
        "items": 100,
        "busy_s": busy,
        "host_prep_s": prep,
        "queue_wait_s": queue,
        "occupancy": occ,
        "shapes": {"ed25519:fused/w4/b32": {"dispatches": disp,
                                            "items": 100,
                                            "pad_items": 20}},
    }


def test_analyze_shares_sum_and_reconciliation():
    dev = _dev_block()
    stages = {"verify.device": {"total_ms": 1005.0, "count": 10},
              "verify.queue": {"total_ms": 5.0, "count": 10}}
    v = observatory.analyze(dev, stages)
    shares = v["decomposition"]["shares"]
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    rec = v["reconciliation"]
    assert rec["ledger_device_ms"] == pytest.approx(1010.0)
    assert rec["ok"] and rec["delta_pct"] <= 15.0
    assert v["limiter"] == "bandwidth"
    assert v["roofline"]["per_shape"][0]["shape"] == "ed25519:fused/w4/b32"
    assert v["roofline"]["gather_bytes"] > 0


def test_analyze_reconciliation_flags_disagreement():
    dev = _dev_block(busy=1.0)
    stages = {"verify.device": {"total_ms": 2000.0, "count": 10}}
    rec = observatory.analyze(dev, stages)["reconciliation"]
    assert not rec["ok"] and rec["delta_pct"] > 15.0


def test_limiter_decision_tree():
    # device-dominated + saturated = bandwidth (table engines)
    assert observatory.dominant_limiter(
        {"device_busy": 0.9, "host_prep": 0.05, "queue_wait": 0.05,
         "cpu_path": 0.0}, {"dispatches": 5, "occupancy": 0.9}, 1000
    ) == "bandwidth"
    # device-dominated + idle device = the pipeline starves it
    assert observatory.dominant_limiter(
        {"device_busy": 0.9, "host_prep": 0.05, "queue_wait": 0.05,
         "cpu_path": 0.0}, {"dispatches": 5, "occupancy": 0.2}, 1000
    ) == "queue_starvation"
    # gather-free kernels are compute-bound, not bandwidth-bound
    assert observatory.dominant_limiter(
        {"device_busy": 0.9, "host_prep": 0.05, "queue_wait": 0.05,
         "cpu_path": 0.0}, {"dispatches": 5, "occupancy": 0.9}, 0
    ) == "device_compute"
    # queue-dominated + idle device = dispatch gap
    assert observatory.dominant_limiter(
        {"device_busy": 0.2, "host_prep": 0.1, "queue_wait": 0.7,
         "cpu_path": 0.0}, {"dispatches": 5, "occupancy": 0.3}, 1000
    ) == "dispatch_gap"
    assert observatory.dominant_limiter(
        {"device_busy": 0.2, "host_prep": 0.7, "queue_wait": 0.1,
         "cpu_path": 0.0}, {"dispatches": 5, "occupancy": 0.9}, 1000
    ) == "host_prep"
    assert observatory.dominant_limiter(
        {}, {"dispatches": 0}, 0
    ) == "no_device_dispatches"


def test_merge_device_blocks_sums_processes_and_dedups():
    a = {"node": "r0", "window_s": 2.0, "lanes": {"ed25519": {
        "dispatches": 2, "items": 10, "pad_items": 2, "submissions": 3,
        "busy_s": 0.8, "host_prep_s": 0.01, "queue_wait_s": 0.0,
        "bytes_up": 100, "bytes_down": 10, "compiles": 1, "devices": 1,
    }}, "shapes": {"ed25519:fused/w4/b8": {"dispatches": 2, "items": 10,
                                           "pad_items": 2}}}
    b = json.loads(json.dumps(a))  # second PROCESS, same posture
    b["node"] = "r1"
    merged = observatory.merge_device_blocks([a, b])
    assert merged["dispatches"] == 4 and merged["items"] == 20
    assert merged["shapes"]["ed25519:fused/w4/b8"]["dispatches"] == 4
    assert merged["window_s"] == 2.0  # max, not sum
    assert merged["processes"] == 2
    lane = merged["lanes"]["ed25519"]
    assert lane["compiles"] == 2
    # device counts SUM across per-process blocks (distinct hardware):
    # two nodes each 40% busy on their own device merge to 40% fleet
    # occupancy, not a saturated single device
    assert lane["devices"] == 2
    assert lane["occupancy"] == pytest.approx(1.6 / (2.0 * 2), abs=1e-6)
    # the SAME process-wide ledger seen through n per-replica flight
    # files (an in-process committee) dedups to one block — the n-fold
    # over-count would inflate every rate and trip reconciliation
    same = [json.loads(json.dumps(a)) for _ in range(4)]
    m1 = observatory.merge_device_blocks(same)
    assert m1["dispatches"] == 2 and m1["processes"] == 1
    assert m1["lanes"]["ed25519"]["devices"] == 1


# ---------------------------------------------------------------------------
# pbft_top DEV cell
# ---------------------------------------------------------------------------


def test_dev_cell_renders_and_blanks():
    snap = {"verify": {"device": {
        "dispatches": 42, "dispatches_per_s": 8.8, "occupancy": 0.95,
        "verifies_per_s_effective": 4123.0, "pad_waste_pct": 12.4,
    }}}
    cell = pbft_top.dev_cell(snap)
    assert cell == "8.8/s 95% 4.1kv/s 12%"
    assert pbft_top.dev_cell({"verify": {"device": {"dispatches": 0}}}) == ""
    assert pbft_top.dev_cell({}) == ""
    # the column is wired into the row renderer
    assert "DEV" in pbft_top.COLUMNS


# ---------------------------------------------------------------------------
# dead-target view-change fast-path (ISSUE 14 satellite; e2e regression
# gate is tests/test_sim.py::test_slow_failover_tail_repro_fast_failover)
# ---------------------------------------------------------------------------


def _stub_viewchanger(view_timeout=1.0):
    from collections import defaultdict

    from simple_pbft_tpu.consensus.viewchange import ViewChanger

    cfg = SimpleNamespace(
        view_timeout=view_timeout, n=4, weak_quorum=2,
        replica_ids=("r0", "r1", "r2", "r3"),
        primary=lambda v: f"r{v % 4}",
    )
    rep = SimpleNamespace(
        id="r0", cfg=cfg, view=0, executed_seq=0, max_committed_seen=0,
        peer_seen={}, _boot_mono=clock.now(), metrics=defaultdict(int),
    )
    return ViewChanger(rep), rep


def test_dead_target_evidence_rule():
    vc, rep = _stub_viewchanger()
    now = clock.now()
    # r1 silent past the window, r2+r3 loud: evidence-dead
    rep.peer_seen = {"r2": now, "r3": now, "r1": now - 100.0}
    assert vc.primary_evidence_dead(1)  # primary(1) = r1
    assert not vc.primary_evidence_dead(2)  # r2 is loud
    assert not vc.primary_evidence_dead(4)  # ourselves: never
    # idle committee: nobody loud -> nobody dead
    rep.peer_seen = {}
    assert not vc.primary_evidence_dead(1)
    # we are the partitioned ones: everyone silent -> no verdicts
    rep.peer_seen = {p: now - 100.0 for p in ("r1", "r2", "r3")}
    assert not vc.primary_evidence_dead(1)


def test_next_live_target_skips_dead_and_is_bounded():
    vc, rep = _stub_viewchanger()
    now = clock.now()
    # r1 and r2 crashed (silent), r3 loud: escalation from view 1 must
    # land on view 3 (primary r3), two skips counted
    rep.peer_seen = {"r3": now, "r1": now - 100.0, "r2": now - 100.0}
    assert vc.next_live_target(1) == 3
    assert rep.metrics["deadview_skipped"] == 2
    # a live-primaried start view is never skipped
    assert vc.next_live_target(3) == 3
    # skip budget is one rotation: even a pathological evidence table
    # cannot stall escalation (monkey-verdict everything dead)
    vc.primary_evidence_dead = lambda view: True
    assert vc.next_live_target(1) == 1 + (rep.cfg.n - 1)
