"""Runtime sanitizer tests (ISSUE 8): the loop-blocking watcher and the
lock-discipline checker each prove a true positive AND a true negative,
and the documented lock-order table stays bound to the code."""

import asyncio
import os
import re
import threading
import time

import pytest

from simple_pbft_tpu import sanitize


@pytest.fixture(autouse=True)
def _drain():
    sanitize.take_violations()
    sanitize.reset_owners()
    yield
    sanitize.take_violations()
    sanitize.reset_owners()


# ---------------------------------------------------------------------------
# loop-blocking watcher
# ---------------------------------------------------------------------------


def _wait_violations(kind, timeout=2.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = sanitize.violations(kind)
        if v:
            return v
        time.sleep(0.02)
    return sanitize.violations(kind)


def _fresh_loop():
    """Construct the loop DIRECTLY (not via the policy): when these
    tests themselves run under PBFT_SANITIZE=loop, install() has wrapped
    policy.new_event_loop and would auto-watch the loop at the default
    threshold before the test can attach its own fast watcher."""
    return asyncio.SelectorEventLoop()


def test_loop_watcher_true_positive():
    """A coroutine blocking the loop in time.sleep is caught and the
    violation attributes the offending frame."""
    loop = _fresh_loop()
    try:
        watch = sanitize.watch_loop(loop, threshold_s=0.05)
        assert watch is not None  # explicit opt-in works regardless of env

        async def blocker():
            time.sleep(0.4)  # the bug under test: sync sleep on the loop

        loop.run_until_complete(blocker())
    finally:
        loop.close()
    viols = _wait_violations("loop")
    assert viols, "stalled loop was not detected"
    v = viols[0]
    assert v["stall_ms"] >= 50
    # attribution: the sampled stack bottoms out in our blocker frame
    assert any("blocker" in fr for fr in v["stack"]), v["stack"]
    assert "time.sleep" in v["stack"][-1]


def test_loop_watcher_true_negative():
    """A loop that only awaits never violates: parked-in-selector frames
    are idle, not blocked — even past the threshold."""
    loop = _fresh_loop()
    try:
        sanitize.watch_loop(loop, threshold_s=0.05)

        async def healthy():
            for _ in range(4):
                await asyncio.sleep(0.05)

        loop.run_until_complete(healthy())
        time.sleep(0.15)  # give the watcher time to (not) fire
    finally:
        loop.close()
    assert sanitize.violations("loop") == []


def test_loop_watcher_idempotent_per_loop():
    loop = _fresh_loop()
    try:
        first = sanitize.watch_loop(loop, threshold_s=0.5)
        second = sanitize.watch_loop(loop, threshold_s=0.5)
        assert first is not None and second is None
    finally:
        loop.close()


def test_loop_watcher_releases_id_after_close():
    """The dedup set must not pin a closed loop's id() forever: a later
    loop allocated at the recycled address would silently go unwatched
    — a false negative in the exact tool built to prevent them."""
    loop = _fresh_loop()
    sanitize.watch_loop(loop, threshold_s=0.5)
    loop.close()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        with sanitize._watch_lock:
            if id(loop) not in sanitize._watched:
                return
        time.sleep(0.02)
    raise AssertionError("closed loop's id never left the watch set")


def test_one_violation_per_stall_episode():
    loop = _fresh_loop()
    try:
        sanitize.watch_loop(loop, threshold_s=0.05)

        async def long_block():
            time.sleep(0.5)  # many watcher periods, ONE episode

        loop.run_until_complete(long_block())
    finally:
        loop.close()
    viols = _wait_violations("loop")
    assert len(viols) == 1


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------


def _mk(name):
    return sanitize.wrap_lock(threading.Lock(), name, force=True)


def test_lock_rank_violation():
    lo = _mk("verify_service.cond")  # rank 20
    hi = _mk("qc.lane.cond")  # rank 30
    with hi:
        with lo:  # descending rank: the deadlock-prone order
            pass
    viols = sanitize.take_violations()
    assert any("lock order violation" in v["message"] for v in viols)


def test_lock_rank_clean_in_order():
    lo = _mk("verify_service.cond")
    hi = _mk("qc.lane.cond")
    with lo:
        with hi:
            pass
    assert sanitize.take_violations() == []


def test_leaf_lock_must_not_nest_outward():
    leaf = _mk("qc.cache")  # leaf: nothing may be acquired under it
    other = _mk("qc.lane_registry")  # rank 10 < 90, but leaf rule first
    with leaf:
        with other:
            pass
    viols = sanitize.take_violations()
    assert any("LEAF" in v["message"] for v in viols)


def test_group_exclusion_both_orders():
    ring = _mk("spans.recorder")
    sink = _mk("spans.sink")
    with ring:
        with sink:  # ascending rank but same group: still forbidden
            pass
    viols = sanitize.take_violations()
    assert any("group" in v["message"] for v in viols)


def test_nonblocking_acquire_exempt():
    """Trylocks can't deadlock; Condition's ownership probe relies on
    this exemption."""
    hi = _mk("qc.lane.cond")
    lo = _mk("verify_service.cond")
    with hi:
        got = lo.acquire(blocking=False)
        assert got
        lo.release()
    assert sanitize.take_violations() == []


def test_condition_integration():
    """A _RankedLock drops into threading.Condition unchanged — the
    product seams construct Condition(wrap_lock(...))."""
    cond = threading.Condition(_mk("qc.lane.cond"))
    with cond:
        cond.notify_all()
    assert sanitize.take_violations() == []


def test_unknown_lock_name_raises_at_construction():
    with pytest.raises(KeyError):
        sanitize.wrap_lock(threading.Lock(), "not.in.the.table", force=True)


def test_wrap_lock_is_passthrough_when_disabled(monkeypatch):
    monkeypatch.delenv("PBFT_SANITIZE", raising=False)
    raw = threading.Lock()
    assert sanitize.wrap_lock(raw, "qc.cache") is raw


# ---------------------------------------------------------------------------
# owning-thread annotations
# ---------------------------------------------------------------------------


def test_owner_violation_cross_thread(monkeypatch):
    monkeypatch.setenv("PBFT_SANITIZE", "locks")
    sanitize.check_owner(("fixture", 1), "fixture.surface")  # binds here

    t = threading.Thread(
        target=sanitize.check_owner, args=(("fixture", 1), "fixture.surface")
    )
    t.start()
    t.join()
    viols = sanitize.take_violations()
    assert any("owning-thread violation" in v["message"] for v in viols)


def test_owner_clean_same_thread(monkeypatch):
    monkeypatch.setenv("PBFT_SANITIZE", "locks")
    sanitize.bind_owner(("fixture", 2), "fixture.worker")
    sanitize.check_owner(("fixture", 2), "fixture.worker")
    assert sanitize.take_violations() == []


def test_owner_rebind_violation(monkeypatch):
    monkeypatch.setenv("PBFT_SANITIZE", "locks")
    sanitize.bind_owner(("fixture", 3), "fixture.worker")
    t = threading.Thread(
        target=sanitize.bind_owner, args=(("fixture", 3), "fixture.worker")
    )
    t.start()
    t.join()
    viols = sanitize.take_violations()
    assert any("owner rebind" in v["message"] for v in viols)


def test_release_owner_allows_fresh_bind(monkeypatch):
    """Teardown releases the binding so a later object at a recycled
    id() binds fresh from any thread — no spurious rebind violation."""
    monkeypatch.setenv("PBFT_SANITIZE", "locks")
    key = ("fixture", 5)
    sanitize.bind_owner(key, "fixture.worker")
    sanitize.release_owner(key)
    t = threading.Thread(
        target=sanitize.bind_owner, args=(key, "fixture.worker")
    )
    t.start()
    t.join()
    assert sanitize.take_violations() == []


def test_qc_lane_worker_releases_owner_on_close(monkeypatch):
    """The product seam end-to-end: closing a QcVerifyLane releases its
    worker binding, so a successor lane at the same id binds clean."""
    monkeypatch.setenv("PBFT_SANITIZE", "locks")
    from simple_pbft_tpu.consensus.qc import QcVerifyLane

    lane = QcVerifyLane()
    # the worker spawns lazily on first submit; start it the same way
    lane._started = True
    threading.Thread(
        target=lane._worker, name="qc-verify-lane", daemon=True
    ).start()
    key = ("qc.lane.worker", id(lane))
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        with sanitize._owner_lock:
            if key in sanitize._owners:
                break
        time.sleep(0.01)
    lane.close()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        with sanitize._owner_lock:
            if key not in sanitize._owners:
                break
        time.sleep(0.01)
    with sanitize._owner_lock:
        assert key not in sanitize._owners
    assert sanitize.take_violations() == []


def test_install_arms_policy_created_loops(monkeypatch):
    """PBFT_SANITIZE=loop must work OUTSIDE pytest too: install() (run
    by node.main before asyncio.run) wraps the policy so every new loop
    is watched."""
    monkeypatch.setenv("PBFT_SANITIZE", "loop")
    pol = asyncio.get_event_loop_policy()
    orig = pol.new_event_loop
    monkeypatch.setattr(sanitize, "_installed", False)
    try:
        sanitize.install()
        loop = asyncio.new_event_loop()
        try:
            with sanitize._watch_lock:
                assert id(loop) in sanitize._watched
        finally:
            loop.close()
    finally:
        pol.new_event_loop = orig


def test_owner_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("PBFT_SANITIZE", raising=False)
    sanitize.bind_owner(("fixture", 4), "fixture.worker")
    t = threading.Thread(
        target=sanitize.check_owner, args=(("fixture", 4), "fixture.worker")
    )
    t.start()
    t.join()
    assert sanitize.take_violations() == []


# ---------------------------------------------------------------------------
# documentation binding + report format
# ---------------------------------------------------------------------------


def test_lock_table_matches_docs():
    """docs/STATIC_ANALYSIS.md's lock-order table and LOCK_RANKS are the
    same table — drift in either direction fails here."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "docs", "STATIC_ANALYSIS.md")) as fh:
        text = fh.read()
    rows = re.findall(
        r"\|\s*`([\w.]+)`\s*\|\s*(\d+)\s*\|\s*(yes|—)\s*\|\s*([\w-]+|—)\s*\|",
        text,
    )
    documented = {
        name: (int(rank), leaf == "yes", None if group == "—" else group)
        for name, rank, leaf, group in rows
    }
    coded = {
        name: (
            spec["rank"],
            bool(spec.get("leaf")),
            spec.get("group"),
        )
        for name, spec in sanitize.LOCK_RANKS.items()
    }
    assert documented == coded


def test_format_violations_carries_stack():
    sanitize._record(
        "locks", message="fixture violation", stack=["a.py:1 in f: x()"]
    )
    out = sanitize.format_violations(sanitize.take_violations())
    assert "fixture violation" in out
    assert "a.py:1 in f" in out
